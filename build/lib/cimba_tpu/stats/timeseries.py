"""Time-weighted series recording (piecewise-constant signals).

Reference parity: ``cmb_timeseries`` (`src/cmb_timeseries.c:106-188`) —
a dataset plus parallel time/duration arrays where each recorded value is
assumed to hold until the next record; ``finalize(t)`` closes the last
interval and ``summarize`` produces a weighted summary.  Used by every
L5 component for utilization / queue-length statistics.

Two TPU renditions:

* :class:`StepAccum` — the hot-loop form.  Streams segments directly into
  a weighted :class:`~cimba_tpu.stats.summary.Summary` (O(1) state).  This
  is what resources/queues carry inside the jitted event loop.
* :class:`Timeseries` — the full recorder with fixed-capacity (time, value)
  arrays for post-analysis (histograms, inspection), mirroring the
  reference's array-of-everything layout.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from cimba_tpu import config
from cimba_tpu.stats import summary as _sm

_R = config.REAL


class StepAccum(NamedTuple):
    """Streaming time-weighted accumulator for a piecewise-constant signal."""

    summary: _sm.Summary
    last_t: jnp.ndarray
    last_v: jnp.ndarray
    started: jnp.ndarray  # bool: has any record happened


def step_create(t0=0.0, v0=0.0) -> StepAccum:
    return StepAccum(
        summary=_sm.empty(),
        last_t=jnp.asarray(t0, _R),
        last_v=jnp.asarray(v0, _R),
        started=jnp.asarray(False),
    )


def step_record(acc: StepAccum, t, v) -> StepAccum:
    """Record signal value ``v`` effective at time ``t``; the previous value
    is credited with weight (t - last_t)."""
    t = jnp.asarray(t, _R)
    dur = jnp.maximum(t - acc.last_t, 0.0)
    new_sum = _sm.add(acc.summary, acc.last_v, dur)
    # zero-duration segments contribute nothing but must not corrupt moments
    summary = _sm.Summary(*[
        jnp.where(dur > 0.0, a, b) for a, b in zip(new_sum, acc.summary)
    ])
    return StepAccum(
        summary=summary,
        last_t=t,
        last_v=jnp.asarray(v, _R),
        started=jnp.asarray(True),
    )


def step_finalize(acc: StepAccum, t_end) -> _sm.Summary:
    """Close the last interval at ``t_end`` and return the weighted summary."""
    closed = _sm.add(acc.summary, acc.last_v, jnp.maximum(jnp.asarray(t_end, _R) - acc.last_t, 0.0))
    return closed


class Timeseries(NamedTuple):
    times: jnp.ndarray    # [CAP]
    values: jnp.ndarray   # [CAP]
    n: jnp.ndarray        # i32
    dropped: jnp.ndarray  # i32


def create(capacity: int, t0=0.0) -> Timeseries:
    return Timeseries(
        times=jnp.full((capacity,), jnp.asarray(t0, _R)),
        values=jnp.zeros((capacity,), _R),
        n=jnp.zeros((), jnp.int32),
        dropped=jnp.zeros((), jnp.int32),
    )


def add(ts: Timeseries, t, v) -> Timeseries:
    cap = ts.times.shape[0]
    ok = ts.n < cap
    idx = jnp.minimum(ts.n, cap - 1)
    return Timeseries(
        times=ts.times.at[idx].set(jnp.where(ok, jnp.asarray(t, _R), ts.times[idx])),
        values=ts.values.at[idx].set(jnp.where(ok, jnp.asarray(v, _R), ts.values[idx])),
        n=ts.n + jnp.where(ok, 1, 0).astype(jnp.int32),
        dropped=ts.dropped + jnp.where(ok, 0, 1).astype(jnp.int32),
    )


def durations(ts: Timeseries, t_end):
    """Piecewise-constant durations: value i holds from times[i] to
    times[i+1] (last until t_end).  Parity: `src/cmb_timeseries.c:106-157`."""
    cap = ts.times.shape[0]
    idx = jnp.arange(cap)
    nxt = jnp.where(
        idx + 1 < ts.n,
        jnp.roll(ts.times, -1),
        jnp.asarray(t_end, _R),
    )
    dur = jnp.where(idx < ts.n, nxt - ts.times, 0.0)
    return jnp.maximum(dur, 0.0)


def summarize(ts: Timeseries, t_end) -> _sm.Summary:
    """Weighted summary of the recorded signal over [times[0], t_end]."""
    dur = durations(ts, t_end)
    mask = dur > 0.0
    w = jnp.sum(dur)
    safe_w = jnp.maximum(w, 1e-300)
    mu = jnp.sum(ts.values * dur) / safe_w
    c = jnp.where(mask, ts.values - mu, 0.0)
    return _sm.Summary(
        n=ts.n.astype(_R),
        w=w,
        mn=jnp.min(jnp.where(mask, ts.values, jnp.inf)),
        mx=jnp.max(jnp.where(mask, ts.values, -jnp.inf)),
        m1=mu,
        m2=jnp.sum(dur * c * c),
        m3=jnp.sum(dur * c**3),
        m4=jnp.sum(dur * c**4),
    )
