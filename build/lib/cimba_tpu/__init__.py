"""cimba-tpu: a TPU-native discrete-event simulation framework.

A brand-new implementation of the capabilities of the reference library
(ambonvik/cimba — C17 + assembly coroutines + pthreads): simulated processes
with hold/interrupt/preempt semantics, resources and queues, a full random
distribution catalogue, streaming statistics, and an experiment runner for
hundreds of thousands of parallel replications.

Architecture (see SURVEY.md for the full design translation):

* The reference fans *trials* over pthreads; here replications are the
  leading batch axis of every state array, ``vmap``-ed across lanes and
  ``shard_map``-ed across a TPU mesh.
* The reference multiplexes *processes* with assembly context switches;
  here processes are state machines (numbered blocks) stepped by a
  jit-compiled ``lax.while_loop`` event dispatcher.
* The reference draws randomness from thread-local sfc64; here each
  replication owns a counter-based Threefry-2x32 stream.
* Cross-replication statistics merge with the same associative (Pébay)
  update the reference uses across pthreads — but via ``psum`` over ICI.
"""

from cimba_tpu import config as config  # noqa: F401  (side effect: x64 setup)

__version__ = "0.1.0"

# convenience re-exports (import is cheap; submodules lazy-load jax anyway)
from cimba_tpu.core import api, cmd  # noqa: E402, F401
from cimba_tpu.core.loop import Sim, init_sim, make_run, make_step  # noqa: E402, F401
from cimba_tpu.core.model import Model  # noqa: E402, F401
