"""Job-shop network: a two-stage flow line with buffers, a shared crew
pool, and a condition-gated maintenance process.

Reference parity: the "job-shop network: buffers + condition-vars"
benchmark config (BASELINE.json configs[3], tut_4_2 pattern).  Structure:

    source --[stage A: crew + machine time]--> WIP buffer
           --[stage B: crew + machine time]--> done counter

* ``wip``: a cmb_buffer-style fungible store between the stages.
* ``crew``: a cmb_resourcepool shared by both stages (contention).
* maintenance waits on a condition "WIP backlog >= threshold" and then
  briefly slows stage B (acquiring extra crew) — exercising cond_wait/
  cond_signal against moving state.

Statistics: per-stage counts, WIP level time-average, sojourn through the
line.
"""

from __future__ import annotations

import jax.numpy as jnp

import cimba_tpu.random as cr
from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import api, cmd
from cimba_tpu.core.model import Model
from cimba_tpu.stats import summary as sm

_R = config.REAL
_I = INDEX_DTYPE


def build(
    wip_cap: float = 20.0,
    crew_size: float = 3.0,
    backlog: float = 8.0,
    b_slow: float = 5.0,
):
    """``b_slow`` scales stage B's work relative to stage A, making B the
    bottleneck so WIP genuinely accumulates (the tut_4_2 dynamic)."""
    m = Model("jobshop", n_ilocals=1, event_cap=16, guard_cap=8)
    wip = m.buffer("wip", capacity=wip_cap, initial=0.0)
    crew = m.resourcepool("crew", capacity=crew_size)
    cv = m.condition(
        "backlog", lambda sim, p: sim.buffers.level[wip.id] >= backlog
    )

    @m.user_state
    def user_init(params):
        arr_mean, work_mean, n_jobs = params
        return {
            "arr_mean": jnp.asarray(arr_mean, _R),
            "work_mean": jnp.asarray(work_mean, _R),
            "n_jobs": jnp.asarray(n_jobs, _I),
            "done": sm.empty(),          # completion-time summary
            "maintenance_runs": jnp.zeros((), _I),
        }

    # --- stage A: make one WIP unit per job -------------------------------
    def _next_arrival(sim, p):
        """(sim, command) for the arrival cycle — shared by the entry
        block and a_sig's inlined tail so the logic has one copy."""
        made = api.local_i(sim, p, 0)
        finished = made >= sim.user["n_jobs"]
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        return sim, cmd.select(
            finished, cmd.exit_(), cmd.hold(t, next_pc=a_crew.pc)
        )

    @m.block
    def a_arrive(sim, p, sig):
        return _next_arrival(sim, p)

    @m.block
    def a_crew(sim, p, sig):
        return sim, cmd.pool_acquire(crew.id, 1.0, next_pc=a_work.pc)

    @m.block
    def a_work(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["work_mean"])
        return sim, cmd.hold(t, next_pc=a_store.pc)

    @m.block
    def a_store(sim, p, sig):
        sim = api.add_local_i(sim, p, 0, 1)
        return sim, cmd.pool_release(crew.id, 1.0, next_pc=a_put.pc)

    @m.block
    def a_put(sim, p, sig):
        return sim, cmd.buffer_put(wip.id, 1.0, next_pc=a_sig.pc)

    @m.block
    def a_sig(sim, p, sig):
        # the unit is now IN the store — signal the backlog condition after
        # the state change (signal-before-change would evaluate the
        # predicate one unit short and never fire).  The next-arrival
        # logic is inlined rather than cmd.jump(a_arrive): same draw
        # order (the chain ran a_arrive immediately anyway), one fewer
        # chain iteration of the whole masked kernel body per job
        sim = api.cond_signal(sim, _spec(), cv)
        return _next_arrival(sim, p)

    # --- stage B: consume WIP ---------------------------------------------
    @m.block
    def b_take(sim, p, sig):
        return sim, cmd.buffer_get(wip.id, 1.0, next_pc=b_crew.pc)

    @m.block
    def b_crew(sim, p, sig):
        return sim, cmd.pool_acquire(crew.id, 1.0, next_pc=b_work.pc)

    @m.block
    def b_work(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["work_mean"] * b_slow)
        return sim, cmd.hold(t, next_pc=b_done.pc)

    @m.block
    def b_done(sim, p, sig):
        done = sm.add(sim.user["done"], api.clock(sim))
        sim = api.set_user(sim, {**sim.user, "done": done})
        sim = api.stop(sim, done.n >= sim.user["n_jobs"].astype(_R))
        # continue straight at b_take (no jump-tail block: each chain
        # iteration re-executes the whole masked body in the kernel)
        return sim, cmd.pool_release(crew.id, 1.0, next_pc=b_take.pc)

    # --- maintenance: condition-gated -------------------------------------
    @m.block
    def mt_wait(sim, p, sig):
        return sim, cmd.cond_wait(cv.id, next_pc=mt_act.pc)

    @m.block
    def mt_act(sim, p, sig):
        sim = api.set_user(
            sim,
            {
                **sim.user,
                "maintenance_runs": sim.user["maintenance_runs"] + 1,
            },
        )
        # grab a crew member for a while (slows the shop down)
        return sim, cmd.pool_acquire(crew.id, 1.0, next_pc=mt_hold.pc)

    @m.block
    def mt_hold(sim, p, sig):
        return sim, cmd.hold(2.0, next_pc=mt_rel.pc)

    @m.block
    def mt_rel(sim, p, sig):
        return sim, cmd.pool_release(crew.id, 1.0, next_pc=mt_wait.pc)

    m.process("stageA", entry=a_arrive)
    m.process("stageB", entry=b_take, count=2)
    m.process("maintenance", entry=mt_wait)

    spec_box = {}

    def _spec():
        return spec_box["spec"]

    spec = m.build()
    spec_box["spec"] = spec
    return spec, {"wip": wip, "crew": crew, "cond": cv}


def params(n_jobs: int, arr_mean: float = 1.0, work_mean: float = 0.4):
    return (arr_mean, work_mean, n_jobs)