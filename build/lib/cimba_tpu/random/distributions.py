"""Random variate generation: the full cimba distribution catalogue.

Reference parity: ``include/cmb_random.h`` / ``src/cmb_random.c`` expose ~30
distributions built on a thread-local sfc64 generator.  This module provides
the same catalogue on top of the counter-based Threefry streams in
:mod:`cimba_tpu.random.bits`.

Design (TPU-first, intentionally different from the reference):

* Every sampler is **scalar-style, stateful and functional**:
  ``fn(state, *params) -> (state, sample)``.  Vectorize with ``jax.vmap``
  over the replication axis; the framework's event loop does exactly that.
* Continuous samplers default to **inversion / transform methods**, not the
  reference's ziggurat: the VPU evaluates ``log``/``erfinv`` in a handful of
  cycles with no divergence, whereas a vectorized ziggurat pays the rare
  overhang path on *every* batched draw (with R lanes the probability some
  lane rejects is ~1).  The ziggurat tables and samplers still exist in
  :mod:`cimba_tpu.random.ziggurat` for parity and for the Pallas kernel.
* Rejection samplers (gamma, Poisson PTRS) use ``lax.while_loop`` per draw;
  the RNG counter travels in the carry, so each replication's draw sequence
  stays deterministic regardless of how many rounds its neighbours needed.

Draws consume one 64-bit counter tick each unless noted.  All samples are
float64 (see config.py rationale).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from cimba_tpu import config
from cimba_tpu.random.bits import RandomState, next_bits64, to_u64

_R = config.REAL
_INV_2_53 = 1.0 / 9007199254740992.0  # 2**-53


def uniform01(st: RandomState):
    """Standard uniform on [0, 1) with 32-bit resolution (1 draw).

    Parity: ``cmb_random()`` (`include/cmb_random.h:150`), which assembles a
    53-bit significand from a u64.  Here the significand is deliberately
    32-bit: ``b1 * 2**-32`` uses only a u32->f64 conversion and a power-of-2
    scale, both of which are exactly computed on every backend — whereas the
    TPU's software-emulated float64 *addition* is not always correctly
    rounded (observed: low 2 bits lost for some operand patterns), so any
    multi-word mantissa assembly would break cross-backend bit-identity of
    the stream.  The 2**-32 granularity biases means by ~2**-33, far below
    Monte-Carlo error at any realistic replication count; the second word
    ``b0`` is reserved for samplers that need extra bits.
    """
    st, _, b1 = next_bits64(st)
    if _R.dtype.itemsize == 4:
        # f32 profile (Pallas kernel path): a full-width u32->f32 convert
        # rounds values near 2**32 up to exactly 1.0 (fatal for -log1p(-u));
        # 24 bits is the widest exact significand, same one-draw contract.
        # u32->i32 first: the value fits in 24 bits, and Mosaic's
        # u32->f32 convert rule recurses forever (i32->f32 is native)
        u = (b1 >> jnp.uint32(8)).astype(jnp.int32).astype(_R) * _R(2.0**-24)
    else:
        u = b1.astype(_R) * _R(2.0**-32)
    return st, u


def uniform01_53(st: RandomState):
    """Standard uniform on [0, 1) with full 53-bit resolution (1 draw).

    Used by continuous transform samplers (exponential, normal) whose tail
    extent depends on uniform granularity: 53 bits puts the inversion tail
    cap at ~36.7 for the exponential and ~8.2 sigma for the normal, matching
    the reference ziggurat's practical support.  The final addition is not
    bit-exact across backends (TPU f64 add rounding, see uniform01) — which
    is already true of the downstream ``log``/``erf_inv``, so these samplers
    carry a tolerance contract, not a bit-identity one.
    """
    st, b0, b1 = next_bits64(st)
    if _R.dtype.itemsize == 4:
        # f32 profile: 24 bits IS full resolution; tail cap ~16.6 for the
        # exponential / ~5.7 sigma for the normal (documented envelope).
        # Consumes the same one counter tick as the f64 path so draw
        # streams stay aligned across profiles.
        return st, (b1 >> jnp.uint32(8)).astype(jnp.int32).astype(_R) * _R(
            2.0**-24
        )
    hi = b1.astype(_R) * _R(2.0**-32)
    lo = (b0 >> jnp.uint32(11)).astype(_R) * _R(_INV_2_53)
    return st, hi + lo


def uniform(st, lo, hi):
    """Uniform on [lo, hi). Parity: ``cmb_random_uniform``."""
    st, u = uniform01(st)
    return st, lo + (hi - lo) * u


def triangular(st, lo, mode, hi):
    """Triangular on [lo, hi] with the given mode (inversion)."""
    st, u = uniform01(st)
    fc = (mode - lo) / (hi - lo)
    left = lo + jnp.sqrt(u * (hi - lo) * (mode - lo))
    right = hi - jnp.sqrt((1.0 - u) * (hi - lo) * (hi - mode))
    return st, jnp.where(u < fc, left, right)


def std_exponential(st):
    """Unit-mean exponential via inversion (1 draw, 1 log).

    The reference's hot path is a ziggurat (`include/cmb_random.h:324-347`);
    on TPU the branch-free inversion wins (see module docstring).
    """
    st, u = uniform01_53(st)
    return st, -jnp.log1p(-u)


def exponential(st, mean):
    st, x = std_exponential(st)
    return st, mean * x


def std_normal(st):
    """Standard normal via inverse-CDF: sqrt(2) * erfinv(2u - 1) (1 draw,
    53-bit uniform so the practical tail support reaches ~8.2 sigma)."""
    st, u = uniform01_53(st)
    # map u in [0,1) to (-1, 1); u==0 gives -1 -> erfinv(-1) = -inf, so
    # nudge by one representable step of the active profile's dtype (a
    # fixed 1e-16 would round to exactly -1 in f32 and leak -inf samples)
    tiny = float(jnp.finfo(_R.dtype).eps) / 2.0
    x = 2.0 * u - 1.0
    x = jnp.clip(x, -1.0 + tiny, 1.0 - tiny)
    return st, jnp.sqrt(_R(2.0)) * lax.erf_inv(x)


def normal(st, mu, sigma):
    st, z = std_normal(st)
    return st, mu + sigma * z


def lognormal(st, m, s):
    """exp(N(m, s)): mean exp(m + s^2/2), median exp(m)."""
    st, z = normal(st, m, s)
    return st, jnp.exp(z)


def logistic(st, m, s):
    st, u = uniform01(st)
    u = jnp.clip(u, 1e-300, 1.0 - 1e-16)
    return st, m + s * jnp.log(u / (1.0 - u))


def cauchy(st, mode, scale):
    st, u = uniform01(st)
    return st, mode + scale * jnp.tan(jnp.pi * (u - 0.5))


def erlang(st, k, mean):
    """Sum of k exponentials, each of mean ``mean`` (k draws).

    ``k`` may be a traced integer; the loop is a ``lax.while_loop``.
    """
    k = jnp.asarray(k, jnp.int32)

    def body(carry):
        st, i, acc = carry
        st, x = std_exponential(st)
        return st, i + 1, acc + x

    st, _, total = lax.while_loop(lambda c: c[1] < k, body, (st, jnp.int32(0), _R(0.0)))
    return st, mean * total


def hypoexponential(st, means):
    """Series of exponential stages with per-stage means (len(means) draws).

    ``means`` is a fixed-size array (the reference takes n + double[]).
    """
    means = jnp.asarray(means, _R)

    def body(i, carry):
        st, acc = carry
        st, x = std_exponential(st)
        return st, acc + means[i] * x

    from cimba_tpu.core import dyn

    st, total = dyn.kfori(0, means.shape[0], body, (st, _R(0.0)))
    return st, total


def hyperexponential(st, probs, means):
    """Mixture of exponentials: pick stage by probs, then exp(means[i])."""
    probs = jnp.asarray(probs, _R)
    means = jnp.asarray(means, _R)
    st, i = discrete_nonuniform(st, probs)
    st, x = std_exponential(st)
    return st, means[i] * x


def std_gamma(st, shape):
    """Gamma(shape, 1) via Marsaglia–Tsang squeeze (rejection while_loop).

    Same algorithm family as the reference (`src/cmb_random.c:465-497`),
    minus the thread-local parameter cache (stateless fits the counter
    design).  Shapes < 1 use the boosting identity
    gamma(a) = gamma(a+1) * U^(1/a).
    """
    shape = jnp.asarray(shape, _R)
    boosted = shape < 1.0
    d_shape = jnp.where(boosted, shape + 1.0, shape)
    d = d_shape - 1.0 / 3.0
    c = 1.0 / jnp.sqrt(9.0 * d)

    def cond(carry):
        _, accepted, _ = carry
        return ~accepted

    def body(carry):
        st, _, _ = carry
        st, z = std_normal(st)
        st, u = uniform01(st)
        v = (1.0 + c * z) ** 3
        ok_v = v > 0.0
        lhs = jnp.log(jnp.maximum(u, 1e-300))
        rhs = 0.5 * z * z + d - d * v + d * jnp.log(jnp.maximum(v, 1e-300))
        accepted = ok_v & (lhs < rhs)
        return st, accepted, d * v

    st, _, x = lax.while_loop(cond, body, (st, jnp.bool_(False), _R(0.0)))
    st, u = uniform01(st)
    u = jnp.maximum(u, 1e-300)
    boost = jnp.where(boosted, u ** (1.0 / jnp.maximum(shape, 1e-12)), 1.0)
    return st, x * boost


def gamma(st, shape, scale):
    st, x = std_gamma(st, shape)
    return st, scale * x


def std_beta(st, a, b):
    """Beta(a, b) from two gammas: X/(X+Y)."""
    st, x = std_gamma(st, a)
    st, y = std_gamma(st, b)
    return st, x / (x + y)


def beta(st, a, b, lo, hi):
    st, z = std_beta(st, a, b)
    return st, lo + (hi - lo) * z


def pert_mod(st, lo, mode, hi, lam):
    """Modified-PERT: scaled beta with peakiness ``lam`` (4.0 = classic)."""
    span = hi - lo
    a = 1.0 + lam * (mode - lo) / span
    b = 1.0 + lam * (hi - mode) / span
    return beta(st, a, b, lo, hi)


def pert(st, lo, mode, hi):
    """Classic PERT: mean (lo + 4 mode + hi)/6."""
    return pert_mod(st, lo, mode, hi, 4.0)


def weibull(st, shape, scale):
    st, x = std_exponential(st)
    return st, scale * x ** (1.0 / shape)


def pareto(st, shape, mode):
    """Pareto on [mode, inf): mode / U^(1/shape)."""
    st, u = uniform01(st)
    u = jnp.maximum(1.0 - u, _R(_INV_2_53))  # (0, 1]
    return st, mode / u ** (1.0 / shape)


def chisquared(st, k):
    """Chi-squared with (possibly non-integer) dof k = 2 * Gamma(k/2, 1)."""
    st, x = std_gamma(st, k * 0.5)
    return st, 2.0 * x


def f_dist(st, a, b):
    st, x = chisquared(st, a)
    st, y = chisquared(st, b)
    return st, (x / a) / (y / b)


def std_t_dist(st, v):
    st, z = std_normal(st)
    st, x = chisquared(st, v)
    return st, z / jnp.sqrt(x / v)


def t_dist(st, m, s, v):
    st, t = std_t_dist(st, v)
    return st, m + s * t


def rayleigh(st, s):
    st, x = std_exponential(st)
    return st, s * jnp.sqrt(2.0 * x)


# --- discrete ---------------------------------------------------------------


def flip(st):
    """Fair coin in {0, 1} (1 draw; the reference amortizes one draw over 64
    flips via a bit cache — stateless streams spend the whole draw)."""
    st, b0, _ = next_bits64(st)
    return st, (b0 & jnp.uint32(1)).astype(jnp.int32)


def bernoulli(st, p):
    st, u = uniform01(st)
    return st, (u < p).astype(jnp.int32)


def geometric(st, p):
    """Trials up to and including first success; support [1, inf), mean 1/p.

    Inversion: ceil(ln(1-u) / ln(1-p)) — the reference simulates the trials;
    inversion is branch-free and exact in distribution.
    """
    st, u = uniform01(st)
    ratio = jnp.log1p(-u) / jnp.log1p(-p)
    return st, jnp.maximum(jnp.ceil(ratio), 1.0).astype(jnp.int64)


def binomial(st, n, p):
    """Successes in n Bernoulli trials (simulated, n draws — like the
    reference; fine for the moderate n used in models)."""
    n = jnp.asarray(n, jnp.int64)

    def body(carry):
        st, i, acc = carry
        st, b = bernoulli(st, p)
        return st, i + 1, acc + jnp.asarray(b, jnp.int64)

    st, _, total = lax.while_loop(
        lambda c: c[1] < n, body, (st, jnp.int64(0), jnp.int64(0))
    )
    return st, total


def negative_binomial(st, m, p):
    """Failures before the m-th success; mean m(1-p)/p (m geometric draws)."""
    m = jnp.asarray(m, jnp.int64)

    def body(carry):
        st, i, acc = carry
        st, g = geometric(st, p)
        return st, i + 1, acc + g - 1  # failures = trials - 1 per success

    st, _, total = lax.while_loop(
        lambda c: c[1] < m, body, (st, jnp.int64(0), jnp.int64(0))
    )
    return st, total


def pascal(st, m, p):
    """Trials to reach the m-th success = negative_binomial + m."""
    st, nb = negative_binomial(st, m, p)
    return st, nb + jnp.asarray(m, jnp.int64)


def poisson(st, rate):
    """Poisson(rate) — Knuth product-of-uniforms for rate < 10, Hörmann's
    PTRS transformed rejection for larger rates (both loop per draw)."""
    rate = jnp.asarray(rate, _R)

    # Each branch clamps the rate to its own valid domain: under vmap with
    # per-lane rates, lax.cond lowers to "run both branches masked", so each
    # branch must terminate even for rates it will never be selected for
    # (PTRS constants go negative below ~10 and its loop would never accept;
    # Knuth needs ~rate iterations).

    # Knuth: count uniforms until product drops below exp(-rate).  The
    # loop condition is >= so that rate == 0 (limit 1.0) still runs one
    # iteration and yields k = 0, not the -1 initializer.
    def knuth(st):
        limit = jnp.exp(-jnp.minimum(rate, 10.0))

        def body(carry):
            st, prod, k = carry
            st, u = uniform01(st)
            return st, prod * u, k + 1

        st, _, k = lax.while_loop(
            lambda c: c[1] >= limit, body, (st, _R(1.0), jnp.int64(-1))
        )
        return st, k

    # PTRS (Hörmann 1993, "The transformed rejection method for generating
    # Poisson random variables").
    def ptrs(st):
        r = jnp.maximum(rate, 10.0)  # clamped local; see note above
        b = 0.931 + 2.53 * jnp.sqrt(r)
        a = -0.059 + 0.02483 * b
        inv_alpha = 1.1239 + 1.1328 / (b - 3.4)
        v_r = 0.9277 - 3.6224 / (b - 2.0)
        log_rate = jnp.log(r)

        def cond(carry):
            _, accepted, _ = carry
            return ~accepted

        def body(carry):
            st, _, _ = carry
            st, u = uniform01(st)
            u = u - 0.5
            st, v = uniform01(st)
            us = 0.5 - jnp.abs(u)
            k = jnp.floor((2.0 * a / us + b) * u + r + 0.43)
            fast_accept = (us >= 0.07) & (v <= v_r)
            bad = (k < 0.0) | ((us < 0.013) & (v > us))
            lhs = jnp.log(v * inv_alpha / (a / (us * us) + b))
            rhs = -r + k * log_rate - lax.lgamma(k + 1.0)
            slow_accept = lhs <= rhs
            accepted = fast_accept | (~bad & slow_accept)
            return st, accepted, k

        st, _, k = lax.while_loop(cond, body, (st, jnp.bool_(False), _R(0.0)))
        return st, k.astype(jnp.int64)

    # lax.cond picks the right branch for scalar rates; under vmap with
    # per-lane rates BOTH branches still run masked, which is why each
    # branch clamps the rate to its own valid domain above.
    return lax.cond(rate < 10.0, knuth, ptrs, st)


def discrete_uniform(st, n):
    """Integer in [0, n) (1 draw; 64-bit modulo, bias < 2^-32 for n < 2^32 —
    the reference uses Lemire's nearly-divisionless trick which exists to
    avoid CPU division, irrelevant here)."""
    st, b0, b1 = next_bits64(st)
    return st, (to_u64(b0, b1) % jnp.asarray(n, jnp.uint64)).astype(jnp.int64)


def dice(st, a, b):
    """Integer in [a, b] inclusive."""
    st, i = discrete_uniform(st, jnp.asarray(b - a + 1, jnp.uint64))
    return st, a + i


def discrete_nonuniform(st, probs):
    """Index i with probability probs[i]/sum(probs) (O(n) scan, 1 draw)."""
    probs = jnp.asarray(probs, _R)
    cdf = jnp.cumsum(probs)
    st, u = uniform01(st)
    target = u * cdf[-1]
    idx = jnp.sum((cdf <= target).astype(jnp.int64))
    return st, jnp.minimum(idx, probs.shape[0] - 1)


def loaded_dice(st, a, b, probs):
    """Integer in [a, b] with per-face weights; len(probs) must be b-a+1."""
    probs = jnp.asarray(probs)
    if isinstance(a, int) and isinstance(b, int):
        if probs.shape[0] != b - a + 1:
            raise ValueError(
                f"loaded_dice needs {b - a + 1} weights for [{a}, {b}], "
                f"got {probs.shape[0]}"
            )
    st, i = discrete_nonuniform(st, probs)
    return st, a + i
