"""Pallas TPU kernels for bulk variate generation.

North-star parity: "the ziggurat normal/exponential samplers in cmb_random
become Pallas kernels keyed by a per-replication Threefry counter"
(BASELINE.json).  These kernels generate [R, N] blocks of variates with the
Threefry counter advanced *in kernel* — bit generation and transform fused
in VMEM, no HBM round-trip for the uniforms.

Counter contract: sample j of replication r consumes counter base_r + j of
stream r — exactly the sequence the scalar samplers in ``distributions``
would consume drawing N times, so bulk pre-generation and sequential
event-loop draws are interchangeable (tested for exact equality).

Two transforms per distribution:

* ``*_inversion`` (default): log/erfinv on the VPU — branch-free,
  gather-free, exact.  On TPU this is the fast path; per-lane 256-entry
  table gathers (a CPU ziggurat's bread and butter) are the VPU's weakest
  operation.
* ``*_ziggurat``: K fixed rounds of the select-based ziggurat over the
  codegen tables, then an exact inversion fallback for lanes that never
  accepted.  Each accepted round yields an exact draw, the fallback is an
  exact draw, and acceptance is independent of the fallback value — so the
  mixture is exactly the target distribution despite the bounded loop.

Kernels run under ``pl.pallas_call`` with ``interpret=True`` on CPU (how
the tests exercise them) and compile natively on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

import numpy as np

from cimba_tpu import config
from cimba_tpu.random import _ziggurat_tables as _zt

_R = config.REAL

# numpy scalar, not jnp: a module-level jnp array would be captured as a
# constant by the pallas kernel closure, which pallas_call rejects
_PARITY = np.uint32(0x1BD11BDA)
_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)


def _rotl(x, r):
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _mix4(x0, x1, rots):
    for r in rots:
        x0 = x0 + x1
        x1 = _rotl(x1, r)
        x1 = x1 ^ x0
    return x0, x1


def _threefry(k0, k1, c0, c1):
    ks2 = k0 ^ k1 ^ _PARITY
    x0 = c0 + k0
    x1 = c1 + k1
    x0, x1 = _mix4(x0, x1, _ROT_A)
    x0, x1 = x0 + k1, x1 + ks2 + jnp.uint32(1)
    x0, x1 = _mix4(x0, x1, _ROT_B)
    x0, x1 = x0 + ks2, x1 + k0 + jnp.uint32(2)
    x0, x1 = _mix4(x0, x1, _ROT_A)
    x0, x1 = x0 + k0, x1 + k1 + jnp.uint32(3)
    x0, x1 = _mix4(x0, x1, _ROT_B)
    x0, x1 = x0 + k1, x1 + ks2 + jnp.uint32(4)
    x0, x1 = _mix4(x0, x1, _ROT_A)
    x0, x1 = x0 + ks2, x1 + k0 + jnp.uint32(5)
    return x0, x1


def _block_bits(key0, key1, ctr_lo, ctr_hi, n: int, offset: int = 0):
    """[R, n] pairs of u32 words: counters base+offset .. base+offset+n-1."""
    j = jnp.arange(n, dtype=jnp.uint32)[None, :] + jnp.uint32(offset)
    lo = ctr_lo[:, None] + j
    hi = ctr_hi[:, None] + jnp.where(lo < j, jnp.uint32(1), jnp.uint32(0))
    return _threefry(key0[:, None], key1[:, None], lo, hi)


def _u53(b0, b1):
    return (
        b1.astype(_R) * _R(2.0**-32)
        + (b0 >> jnp.uint32(11)).astype(_R) * _R(2.0**-53)
    )


# --- inversion kernels -------------------------------------------------------


def _exp_inv_kernel(k0_ref, k1_ref, lo_ref, hi_ref, out_ref, *, n):
    b0, b1 = _block_bits(k0_ref[...], k1_ref[...], lo_ref[...], hi_ref[...], n)
    out_ref[...] = -jnp.log1p(-_u53(b0, b1))


def _nor_inv_kernel(k0_ref, k1_ref, lo_ref, hi_ref, out_ref, *, n):
    b0, b1 = _block_bits(k0_ref[...], k1_ref[...], lo_ref[...], hi_ref[...], n)
    u = _u53(b0, b1)
    x = jnp.clip(2.0 * u - 1.0, -1.0 + 1e-16, 1.0 - 1e-16)
    out_ref[...] = jnp.sqrt(_R(2.0)) * jax.lax.erf_inv(x)


# --- ziggurat kernel (K rounds + exact inversion fallback) -------------------

_ZK = 2  # fixed ziggurat rounds; P(no accept) ~ 0.02^K per lane


def _exp_zig_kernel(k0_ref, k1_ref, lo_ref, hi_ref, xt_ref, yt_ref,
                    out_ref, *, n):
    k0, k1 = k0_ref[...], k1_ref[...]
    lo, hi = lo_ref[...], hi_ref[...]
    xt = xt_ref[...]  # ziggurat tables arrive as kernel inputs (VMEM)
    yt = yt_ref[...]
    r_const = _R(_zt.R_EXP)
    base_w = _R(_zt.V_EXP) / yt[255]

    accepted = jnp.zeros((k0.shape[0], n), dtype=jnp.bool_)
    out = jnp.zeros((k0.shape[0], n), _R)
    off = 0
    for _ in range(_ZK):
        b0, b1 = _block_bits(k0, k1, lo, hi, n, offset=off)
        off += n
        layer = (b0 & jnp.uint32(0xFF)).astype(jnp.int32)
        u1 = b1.astype(_R) * _R(2.0**-32)
        xj = xt[layer]
        width = jnp.where(layer == 0, base_w, xj)
        x = u1 * width
        hot = x < jnp.where(layer == 0, r_const, xt[layer - 1])
        # y-test for interior layers (uses the low word's spare bits)
        u2 = (b0 >> jnp.uint32(8)).astype(_R) * _R(2.0**-24)
        ylo = yt[layer]
        yhi = jnp.where(layer == 0, yt[255], yt[layer - 1])
        y = ylo + u2 * (yhi - ylo)
        ok = hot | ((layer > 0) & (y < jnp.exp(-x)))
        # layer-0 miss -> exact memoryless tail: r + Exp(1) via inversion
        b0t, b1t = _block_bits(k0, k1, lo, hi, n, offset=off)
        off += n
        tail = r_const - jnp.log1p(-_u53(b0t, b1t))
        is_tail = (layer == 0) & ~hot
        val = jnp.where(is_tail, tail, x)
        take = ~accepted & (ok | is_tail)
        out = jnp.where(take, val, out)
        accepted = accepted | ok | is_tail
    # exact fallback for never-accepted lanes
    b0f, b1f = _block_bits(k0, k1, lo, hi, n, offset=off)
    fb = -jnp.log1p(-_u53(b0f, b1f))
    out_ref[...] = jnp.where(accepted, out, fb)


def _run(kernel, states, n: int, interpret: bool, extra=()):
    k0, k1, lo, hi = states.key0, states.key1, states.ctr_lo, states.ctr_hi
    r = k0.shape[0]
    call = pl.pallas_call(
        functools.partial(kernel, n=n),
        out_shape=jax.ShapeDtypeStruct((r, n), _R),
        interpret=interpret,
    )
    return call(k0, k1, lo, hi, *extra)


def exponential_block(states, n: int, *, interpret: bool = False):
    """[R, n] unit exponentials for a batch of RandomState streams, counters
    advanced in kernel; returns (new_states, samples)."""
    out = _run(_exp_inv_kernel, states, n, interpret)
    return _advance(states, n), out


def normal_block(states, n: int, *, interpret: bool = False):
    """[R, n] standard normals (inversion)."""
    out = _run(_nor_inv_kernel, states, n, interpret)
    return _advance(states, n), out


def exponential_block_zig(states, n: int, *, interpret: bool = False):
    """[R, n] unit exponentials via in-kernel ziggurat (fixed rounds +
    exact fallback).  Consumes (2*ZK + 1) * n counters per stream."""
    tables = (
        jnp.asarray(_zt.X_EXP, _R),
        jnp.asarray(_zt.Y_EXP, _R),
    )
    out = _run(_exp_zig_kernel, states, n, interpret, extra=tables)
    return _advance(states, (2 * _ZK + 1) * n), out


def _advance(states, n: int):
    lo = states.ctr_lo + jnp.uint32(n)
    hi = states.ctr_hi + jnp.where(
        lo < states.ctr_lo, jnp.uint32(1), jnp.uint32(0)
    )
    return states._replace(ctr_lo=lo, ctr_hi=hi)