"""cimba-tpu random subsystem: counter-based streams + distribution catalogue.

See :mod:`cimba_tpu.random.bits` for the Threefry stream design and
:mod:`cimba_tpu.random.distributions` for the samplers (parity with the
reference's ``include/cmb_random.h``).
"""

from cimba_tpu.random.bits import (
    RandomState,
    fmix64,
    initialize,
    next_bits64,
    threefry2x32,
)
from cimba_tpu.random.alias import AliasTable, alias_create, alias_sample
from cimba_tpu.random.distributions import (
    bernoulli,
    beta,
    binomial,
    cauchy,
    chisquared,
    dice,
    discrete_nonuniform,
    discrete_uniform,
    erlang,
    exponential,
    f_dist,
    flip,
    gamma,
    geometric,
    hyperexponential,
    hypoexponential,
    loaded_dice,
    logistic,
    lognormal,
    negative_binomial,
    normal,
    pareto,
    pascal,
    pert,
    pert_mod,
    poisson,
    rayleigh,
    std_beta,
    std_exponential,
    std_gamma,
    std_normal,
    std_t_dist,
    t_dist,
    triangular,
    uniform,
    uniform01,
    uniform01_53,
    weibull,
)

__all__ = [name for name in dir() if not name.startswith("_")]
