"""Ziggurat samplers for the standard exponential and normal.

Reference parity: the reference's hot-path samplers
(`include/cmb_random.h:207-216,325-335`, cold path `src/cmb_random.c:216-451`)
are McFarland-variant ziggurats over 256-entry codegen tables.  This module
is the TPU rendition over the tables from
:mod:`cimba_tpu.codegen.make_ziggurat`.

These are NOT the framework defaults: on TPU the branch-free inversion in
:mod:`cimba_tpu.random.distributions` wins, because a vectorized ziggurat
pays its rare-path cost on every batched draw (with R lanes, some lane
rejects almost surely).  They exist for (a) component parity, (b) statistical
cross-validation of the inversion samplers against an independent method,
and (c) the Pallas kernel path, where the table lookups live in VMEM.

Layer geometry (see make_ziggurat.py): X[j] increases with j, X[0]=0,
X[255]=r, Y[j]=f(X[j]).  Layer j>=1 is the rectangle of width X[j] spanning
y in [Y[j], Y[j-1]]; layer 0 is the base rectangle [0,r]x[0,f(r)] plus the
tail beyond r.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from cimba_tpu import config
from cimba_tpu.random import _ziggurat_tables as _t
from cimba_tpu.random.bits import RandomState, next_bits64
from cimba_tpu.random.distributions import std_exponential as _inv_exp
from cimba_tpu.random.distributions import uniform01, uniform01_53

_R = config.REAL

def _tables():
    """Trace-time table construction: the profile's dtype must be read at
    trace time, not import time, or use_profile('f32') would silently mix
    f64 tables into the computation."""
    return (
        jnp.asarray(_t.X_EXP, _R),
        jnp.asarray(_t.Y_EXP, _R),
        jnp.asarray(_t.X_NOR, _R),
        jnp.asarray(_t.Y_NOR, _R),
    )


def _zig_draw(st, xtab, ytab, r, v, f, tail_sample):
    """One ziggurat round-trip as a rejection while_loop (scalar-style).

    Batched-execution model: every round computes ALL paths — hot accept,
    y-test, and ``tail_sample`` — and selects, so each round consumes the
    draws of every path (2 bits-draws + the tail's).  That is the price of
    branch-free vectorization and exactly why the inversion samplers in
    ``distributions.py`` are the TPU defaults; this sampler exists for
    parity and cross-validation (see module docstring).
    """

    def cond(carry):
        _, accepted, _ = carry
        return ~accepted

    def body(carry):
        st, _, _ = carry
        st, b0, b1 = next_bits64(st)
        layer = (b0 & jnp.uint32(0xFF)).astype(jnp.int32)
        u1 = b1.astype(_R) * _R(2.0**-32)

        xj = xtab[layer]
        # layer 0: base rectangle [0, r] x [0, f(r)] plus tail, sampled by
        # the width trick: x uniform on [0, v/f(r)] accepts iff x < r.
        base_w = _R(v) / ytab[255]
        width = jnp.where(layer == 0, base_w, xj)
        x = u1 * width

        hot = x < jnp.where(layer == 0, _R(r), xtab[layer - 1])
        # y test for interior layers (layer>=1, x between X[j-1] and X[j])
        st, u2 = uniform01(st)
        ylo = ytab[layer]
        yhi = jnp.where(layer == 0, ytab[255], ytab[layer - 1])
        y = ylo + u2 * (yhi - ylo)
        interior_ok = (layer > 0) & (y < f(x))

        # layer 0 miss -> tail sample (always accepted)
        st, xt = tail_sample(st)
        is_tail = (layer == 0) & ~hot

        accepted = hot | interior_ok | is_tail
        out = jnp.where(is_tail, xt, x)
        return st, accepted, out

    st, _, x = lax.while_loop(cond, body, (st, jnp.bool_(False), _R(0.0)))
    return st, x


def std_exponential_zig(st: RandomState):
    """Unit-mean exponential via 256-layer ziggurat."""

    def tail(st):
        # memoryless: tail beyond r is r + Exp(1), exactly
        st, e = _inv_exp(st)
        return st, _R(_t.R_EXP) + e

    x_exp, y_exp, _, _ = _tables()
    return _zig_draw(
        st,
        x_exp,
        y_exp,
        _t.R_EXP,
        _t.V_EXP,
        lambda x: jnp.exp(-x),
        tail,
    )


def std_normal_zig(st: RandomState):
    """Standard normal via 256-layer ziggurat (half-normal + random sign)."""

    def tail(st):
        # Marsaglia's tail method: x = -ln(u1)/r, y = -ln(u2),
        # accept when 2y > x^2; result r + x.
        def cond(carry):
            _, accepted, _ = carry
            return ~accepted

        def body(carry):
            st, _, _ = carry
            st, u1 = uniform01_53(st)
            st, u2 = uniform01_53(st)
            x = -jnp.log(jnp.maximum(u1, 1e-300)) / _R(_t.R_NOR)
            y = -jnp.log(jnp.maximum(u2, 1e-300))
            return st, 2.0 * y > x * x, _R(_t.R_NOR) + x

        st, _, x = lax.while_loop(cond, body, (st, jnp.bool_(False), _R(0.0)))
        return st, x

    _, _, x_nor, y_nor = _tables()
    st, x = _zig_draw(
        st,
        x_nor,
        y_nor,
        _t.R_NOR,
        _t.V_NOR,
        lambda x: jnp.exp(-0.5 * x * x),
        tail,
    )
    st, b0, _ = next_bits64(st)
    sign = jnp.where((b0 & jnp.uint32(1)) == 0, _R(1.0), _R(-1.0))
    return st, sign * x
