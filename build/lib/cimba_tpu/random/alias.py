"""Vose alias tables for O(1) discrete sampling.

Reference parity: ``cmb_random_alias_create/sample/destroy``
(`src/cmb_random.c:733-806`).  Setup runs host-side in NumPy once per model
(the reference builds it once per trial too); sampling on device is one
64-bit draw plus two gathers — ideal for the VPU.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax.numpy as jnp

from cimba_tpu import config
from cimba_tpu.random.bits import RandomState, next_bits64


class AliasTable(NamedTuple):
    """Static sampling table (a pytree of two arrays; safe to close over
    in jitted code or carry in the model state)."""

    prob: jnp.ndarray   # [n] float64: acceptance probability of column i
    alias: jnp.ndarray  # [n] int32: fallback index of column i


def alias_create(weights) -> AliasTable:
    """Build an alias table from unnormalized weights (host-side, Vose '91)."""
    w = np.asarray(weights, dtype=np.float64)
    n = w.shape[0]
    if n == 0:
        raise ValueError("alias table needs at least one weight")
    if np.any(w < 0.0) or not np.all(np.isfinite(w)) or w.sum() <= 0.0:
        raise ValueError("weights must be finite, non-negative, not all zero")
    p = w * (n / w.sum())
    prob = np.zeros(n, dtype=np.float64)
    alias = np.zeros(n, dtype=np.int32)
    small = [i for i in range(n) if p[i] < 1.0]
    large = [i for i in range(n) if p[i] >= 1.0]
    while small and large:
        s = small.pop()
        l = large.pop()
        prob[s] = p[s]
        alias[s] = l
        p[l] = (p[l] + p[s]) - 1.0
        (small if p[l] < 1.0 else large).append(l)
    for i in large + small:  # numerical leftovers are certain columns
        prob[i] = 1.0
        alias[i] = i
    return AliasTable(jnp.asarray(prob, config.REAL), jnp.asarray(alias, jnp.int32))


def alias_sample(st: RandomState, table: AliasTable):
    """Sample an index: ONE 64-bit draw — low word picks the column
    (modulo, bias n/2^32: negligible for the n <= ~1e5 tables alias
    sampling is used for), high word is the acceptance coin."""
    n = table.prob.shape[0]
    st, b0, b1 = next_bits64(st)
    col = (b0 % jnp.uint32(n)).astype(jnp.int32)
    if config.REAL.dtype.itemsize == 4:
        # f32 profile: 24-bit coin (full-width u32->f32 rounds to 1.0 and
        # hits Mosaic's recursing u32->f32 convert; see uniform01)
        u = (b1 >> jnp.uint32(8)).astype(jnp.int32).astype(
            config.REAL
        ) * config.REAL(2.0**-24)
    else:
        u = b1.astype(config.REAL) * config.REAL(2.0**-32)
    take_alias = u >= table.prob[col]
    return st, jnp.where(take_alias, table.alias[col], col).astype(config.COUNT)
