// cimba-tpu native runtime pieces (C++17, no external deps).
//
// Two jobs, mirroring the reference's native layer re-imagined for this
// framework:
//
// 1. Hardware entropy (parity: src/port/x86-64/linux/cmi_random_hwseed.asm
//    — RDSEED with RDRAND retry fallback and clock mashup last resort),
//    here via compiler intrinsics + CPUID runtime detection instead of
//    hand assembly.
//
// 2. A scalar oracle engine: a plain-C++ discrete-event core implementing
//    the exact semantics of the JAX engine (threefry2x32 streams, 32-bit
//    uniforms, (time, prio DESC, seq) event ordering, guard pend/retry
//    protocol) so large runs of the batched XLA path can be cross-checked
//    against an independent sequential implementation at speeds the Python
//    oracle cannot reach.  This inherits the role of the reference's
//    C library as the trusted scalar ground truth.
//
// Exposed as a tiny extern "C" surface loaded via ctypes
// (cimba_tpu/native/__init__.py); no pybind11 per environment constraints.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <queue>
#include <vector>
#include <cstdlib>

#if defined(__x86_64__)
#include <cpuid.h>
#include <immintrin.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// Threefry-2x32 (Salmon et al. SC'11), bitwise-identical to random/bits.py
// ---------------------------------------------------------------------------

constexpr uint32_t kParity = 0x1BD11BDAu;
constexpr int kRotA[4] = {13, 15, 26, 6};
constexpr int kRotB[4] = {17, 29, 16, 24};

inline uint32_t rotl(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

inline void mix4(uint32_t& x0, uint32_t& x1, const int rot[4]) {
  for (int i = 0; i < 4; ++i) {
    x0 += x1;
    x1 = rotl(x1, rot[i]);
    x1 ^= x0;
  }
}

void threefry2x32(uint32_t k0, uint32_t k1, uint32_t c0, uint32_t c1,
                  uint32_t* o0, uint32_t* o1) {
  const uint32_t ks2 = k0 ^ k1 ^ kParity;
  uint32_t x0 = c0 + k0;
  uint32_t x1 = c1 + k1;
  mix4(x0, x1, kRotA); x0 += k1;  x1 += ks2 + 1;
  mix4(x0, x1, kRotB); x0 += ks2; x1 += k0 + 2;
  mix4(x0, x1, kRotA); x0 += k0;  x1 += k1 + 3;
  mix4(x0, x1, kRotB); x0 += k1;  x1 += ks2 + 4;
  mix4(x0, x1, kRotA); x0 += ks2; x1 += k0 + 5;
  *o0 = x0;
  *o1 = x1;
}

uint64_t fmix64(uint64_t h) {
  h ^= h >> 33;
  h *= 0xFF51AFD7ED558CCDull;
  h ^= h >> 33;
  h *= 0xC4CEB9FE1A85EC53ull;
  h ^= h >> 33;
  return h;
}

struct Stream {
  uint32_t k0, k1, lo, hi;

  static Stream init(uint64_t seed, uint64_t replication) {
    const uint64_t mixed = fmix64(seed + 0x9E3779B97F4A7C15ull * replication);
    return Stream{static_cast<uint32_t>(mixed & 0xFFFFFFFFull),
                  static_cast<uint32_t>(mixed >> 32), 0u, 0u};
  }

  void next(uint32_t* b0, uint32_t* b1) {
    threefry2x32(k0, k1, lo, hi, b0, b1);
    if (++lo == 0u) ++hi;
  }

  // 32-bit-resolution uniform (bitwise-identical to uniform01)
  double uniform01() {
    uint32_t b0, b1;
    next(&b0, &b1);
    return static_cast<double>(b1) * 0x1p-32;
  }

  // 53-bit uniform (uniform01_53): hi word + 21 bits of the low word
  double uniform01_53() {
    uint32_t b0, b1;
    next(&b0, &b1);
    return static_cast<double>(b1) * 0x1p-32 +
           static_cast<double>(b0 >> 11) * 0x1p-53;
  }

  double exponential(double mean) { return -std::log1p(-uniform01_53()) * mean; }
};

// ---------------------------------------------------------------------------
// Scalar M/M/1 oracle with the engine's exact event semantics
// ---------------------------------------------------------------------------

struct Ev {
  double t;
  int32_t prio;
  int32_t seq;
  int32_t target;  // 0 a_start, 1 a_cycle, 2 s_start, 3 service-done,
                   // 4 woken guard retry
  double payload;
  double payload2;  // retry events: the pre-drawn service duration the
                    // pended get_hold carries (engine: pend_f3)
};

struct EvOrder {
  bool operator()(const Ev& a, const Ev& b) const {
    if (a.t != b.t) return a.t > b.t;          // min-heap on time
    if (a.prio != b.prio) return a.prio < b.prio;  // higher prio first
    return a.seq > b.seq;                      // FIFO
  }
};

struct MM1Result {
  double clock;
  double n, mean, m2, min, max;
  uint64_t events;
  // run_mm1_fast only: its fixed 4-slot table's invariant (mm1 carries
  // <= 3 live events) was violated — the result is partial and the
  // caller must fall back to run_mm1.  A structured flag instead of
  // std::abort(): an invariant violation in a bench fast path must
  // never kill the embedding Python process.
  uint64_t overflow = 0;
};

// Scalar M/M/1 oracle mirroring the FUSED-verb flagship cycle
// (models/mm1.py round 5: cmd.put_hold / cmd.get_hold — durations
// pre-drawn one wake earlier; a pended get_hold carries its drawn
// service time through the wait, engine field pend_f3).
MM1Result run_mm1(uint64_t seed, uint64_t rep, uint64_t n_objects,
                  double arr_mean, double srv_mean) {
  Stream rng = Stream::init(seed, rep);
  std::priority_queue<Ev, std::vector<Ev>, EvOrder> heap;
  int32_t seq = 0;
  auto sched = [&](double t, int32_t target, double payload,
                   double payload2 = 0.0) {
    heap.push(Ev{t, 0, seq++, target, payload, payload2});
  };

  double clock = 0.0;
  uint64_t produced = 0, events = 0;
  std::queue<double> fifo;
  bool service_waiting = false;
  double pending_srv_t = 0.0;  // the pended get_hold's drawn duration

  // streaming summary (same Pebay singleton-merge as stats/summary.py)
  double sn = 0, smean = 0, sm2 = 0, smin = HUGE_VAL, smax = -HUGE_VAL;
  auto record = [&](double x) {
    sn += 1.0;
    const double d = x - smean;
    smean += d / sn;
    sm2 += d * (x - smean);
    if (x < smin) smin = x;
    if (x > smax) smax = x;
  };

  // get_hold apply: take an item (service-done at +t_srv) or pend
  // carrying the pre-drawn duration
  auto service_try = [&](double t_srv) {
    if (fifo.empty()) {
      service_waiting = true;
      pending_srv_t = t_srv;
      return;
    }
    const double item = fifo.front();
    fifo.pop();
    sched(clock + t_srv, 3, item);
  };

  sched(0.0, 0, 0.0);  // arrival start
  sched(0.0, 2, 0.0);  // service start

  bool done = false;
  while (!heap.empty() && !done) {
    const Ev ev = heap.top();
    heap.pop();
    clock = ev.t;
    ++events;
    switch (ev.target) {
      case 0:  // a_start: hold exp before the first put
        sched(clock + rng.exponential(arr_mean), 1, 0.0);
        break;
      case 1: {  // a_cycle: count, pre-draw, put (signal first), hold
        ++produced;
        const bool finished = produced >= n_objects;
        const double t_next = rng.exponential(arr_mean);
        fifo.push(clock);
        if (service_waiting) {  // guard-retry wake seq precedes the hold's
          service_waiting = false;
          sched(clock, 4, 0.0, pending_srv_t);
        }
        if (!finished) sched(clock + t_next, 1, 0.0);
        break;
      }
      case 2:  // s_start: pre-draw, then get_hold
        service_try(rng.exponential(srv_mean));
        break;
      case 4:  // woken retry re-applies get_hold with its kept duration
        service_try(ev.payload2);
        break;
      case 3:
        record(clock - ev.payload);
        if (static_cast<uint64_t>(sn) >= n_objects) {
          done = true;
        } else {
          service_try(rng.exponential(srv_mean));
        }
        break;
    }
  }
  return MM1Result{clock, sn, smean, sm2, smin, smax, events};
}

// ---------------------------------------------------------------------------
// Single-stream M/M/1 at engine semantics, tuned for the host core — the
// reference's MM1_single benchmark shape (one replication, one core;
// reference: benchmark/MM1_single.c, ~32M events/s on a 3970X core).
// Trajectory-identical to run_mm1: same RNG placement, same (t, seq) pop
// order (every mm1 event shares priority 0), bitwise-equal outputs
// (pinned by test_native.py).  Only the data structures change: the <=3
// live events sit in a flat 4-slot table (linear lexmin beats a binary
// heap at n<=3) and the FIFO is a power-of-two ring.
// ---------------------------------------------------------------------------

MM1Result run_mm1_fast(uint64_t seed, uint64_t rep, uint64_t n_objects,
                       double arr_mean, double srv_mean) {
  Stream rng = Stream::init(seed, rep);
  struct Slot {
    double t;
    int32_t seq, target;
    double payload, payload2;
    bool live;
  };
  Slot slots[4] = {};
  int32_t seq = 0;
  int n_live = 0;
  bool slots_overflow = false;
  auto sched = [&](double t, int32_t target, double payload,
                   double payload2 = 0.0) {
    for (auto& s : slots) {
      if (!s.live) {
        s = Slot{t, seq++, target, payload, payload2, true};
        ++n_live;
        return;
      }
    }
    // mm1 never carries more than 3 live events; a violation flags the
    // result as overflowed (the loop bails) instead of aborting the
    // process — cimba_mm1_single falls back to run_mm1
    slots_overflow = true;
  };

  std::vector<double> ring(1u << 4);  // FIFO ring; starts small so the
                                    // doubling path is routinely
                                    // exercised (growth is amortized
                                    // and the equality test covers it)
  uint32_t head = 0, count = 0;
  auto fifo_push = [&](double x) {
    if (count == ring.size()) {
      std::vector<double> bigger(ring.size() * 2);
      for (uint32_t i = 0; i < count; ++i)
        bigger[i] = ring[(head + i) & (ring.size() - 1)];
      ring.swap(bigger);
      head = 0;
    }
    ring[(head + count) & (ring.size() - 1)] = x;
    ++count;
  };

  double clock = 0.0;
  uint64_t produced = 0, events = 0;
  bool service_waiting = false;
  double pending_srv_t = 0.0;
  double sn = 0, smean = 0, sm2 = 0, smin = HUGE_VAL, smax = -HUGE_VAL;
  auto record = [&](double x) {
    sn += 1.0;
    const double d = x - smean;
    smean += d / sn;
    sm2 += d * (x - smean);
    if (x < smin) smin = x;
    if (x > smax) smax = x;
  };
  auto service_try = [&](double t_srv) {
    if (count == 0) {
      service_waiting = true;
      pending_srv_t = t_srv;
      return;
    }
    const double item = ring[head & (ring.size() - 1)];
    head = (head + 1) & (ring.size() - 1);
    --count;
    sched(clock + t_srv, 3, item);
  };

  sched(0.0, 0, 0.0);  // arrival start
  sched(0.0, 2, 0.0);  // service start

  bool done = false;
  while (n_live > 0 && !done && !slots_overflow) {
    int best = -1;
    for (int i = 0; i < 4; ++i) {
      if (!slots[i].live) continue;
      if (best < 0 || slots[i].t < slots[best].t ||
          (slots[i].t == slots[best].t && slots[i].seq < slots[best].seq))
        best = i;
    }
    const Slot ev = slots[best];
    slots[best].live = false;
    --n_live;
    clock = ev.t;
    ++events;
    switch (ev.target) {
      case 0:
        sched(clock + rng.exponential(arr_mean), 1, 0.0);
        break;
      case 1: {
        ++produced;
        const bool finished = produced >= n_objects;
        const double t_next = rng.exponential(arr_mean);
        fifo_push(clock);
        if (service_waiting) {
          service_waiting = false;
          sched(clock, 4, 0.0, pending_srv_t);
        }
        if (!finished) sched(clock + t_next, 1, 0.0);
        break;
      }
      case 2:
        service_try(rng.exponential(srv_mean));
        break;
      case 4:
        service_try(ev.payload2);
        break;
      case 3:
        record(clock - ev.payload);
        if (static_cast<uint64_t>(sn) >= n_objects) {
          done = true;
        } else {
          service_try(rng.exponential(srv_mean));
        }
        break;
    }
  }
  MM1Result r{clock, sn, smean, sm2, smin, smax, events};
  r.overflow = slots_overflow ? 1 : 0;
  return r;
}

// ---------------------------------------------------------------------------
// Scalar M/M/c oracle — c symmetric servers sharing one FIFO, with the
// engine's exact guard protocol (parity role: src/cmb_resourceguard.c FIFO
// wake order; engine rendition: core/guard.py + h_get/h_put in core/loop.py)
// ---------------------------------------------------------------------------

MM1Result run_mmc(uint64_t seed, uint64_t rep, uint64_t n_objects,
                  double arr_mean, double srv_mean, uint32_t c) {
  Stream rng = Stream::init(seed, rep);
  std::priority_queue<Ev, std::vector<Ev>, EvOrder> heap;
  int32_t seq = 0;
  // Fused-verb protocol (models/mmc.py round 5): every server's
  // get_hold pre-draws its service time; a pended get_hold carries it
  // (engine pend_f3).  targets: 0 a_start, 1 a_cycle, 2 server start,
  // 3 service done, 4 woken guard retry (payload = kept guard seq,
  // payload2 = the carried service duration)
  auto sched = [&](double t, int32_t target, double payload,
                   double payload2 = 0.0) {
    heap.push(Ev{t, 0, seq++, target, payload, payload2});
  };

  double clock = 0.0;
  uint64_t produced = 0, events = 0;
  std::queue<double> fifo;
  // waiting servers: min-heap of (guard seq, carried duration) — all
  // priorities equal, so the engine's (prio DESC, seq ASC) best-waiter
  // pick reduces to min seq
  using Waiter = std::pair<int32_t, double>;
  std::priority_queue<Waiter, std::vector<Waiter>, std::greater<Waiter>>
      guard;
  int32_t gseq = 0;

  double sn = 0, smean = 0, sm2 = 0, smin = HUGE_VAL, smax = -HUGE_VAL;
  auto record = [&](double x) {
    sn += 1.0;
    const double d = x - smean;
    smean += d / sn;
    sm2 += d * (x - smean);
    if (x < smin) smin = x;
    if (x > smax) smax = x;
  };

  auto signal_front = [&]() {
    if (!guard.empty()) {
      const Waiter woken = guard.top();
      guard.pop();
      sched(clock, 4, static_cast<double>(woken.first), woken.second);
    }
  };
  // successful get_hold: take the item, cascade-signal the next waiter
  // (engine h_queue signals unconditionally — an empty-handed wake
  // retries and re-enqueues with its kept seq), THEN schedule the fused
  // hold's own wake: signal seq precedes the done-event seq, exactly
  // the engine's _guard_signal-before-_schedule_wake order.
  auto service_take = [&](double t_srv) {
    const double item = fifo.front();
    fifo.pop();
    signal_front();
    sched(clock + t_srv, 3, item);
  };
  // fresh get_hold: no-jump-ahead fairness — with waiters ahead, queue
  // behind them even if items are available (engine's `may` predicate)
  auto service_fresh = [&](double t_srv) {
    if (fifo.empty() || !guard.empty()) {
      guard.push({gseq++, t_srv});
    } else {
      service_take(t_srv);
    }
  };
  auto service_retry = [&](int32_t kept_seq, double t_srv) {
    if (fifo.empty()) {
      guard.push({kept_seq, t_srv});  // keeps its FIFO position
    } else {
      service_take(t_srv);
    }
  };

  sched(0.0, 0, 0.0);  // arrival start
  for (uint32_t s = 0; s < c; ++s) sched(0.0, 2, 0.0);  // server starts

  bool done = false;
  while (!heap.empty() && !done) {
    const Ev ev = heap.top();
    heap.pop();
    clock = ev.t;
    ++events;
    switch (ev.target) {
      case 0:  // a_start: hold exp before the first put
        sched(clock + rng.exponential(arr_mean), 1, 0.0);
        break;
      case 1: {  // a_cycle: count, pre-draw, put (signal first), hold
        ++produced;
        const bool finished = produced >= n_objects;
        const double t_next = rng.exponential(arr_mean);
        fifo.push(clock);
        signal_front();
        if (!finished) sched(clock + t_next, 1, 0.0);
        break;
      }
      case 2:  // server start: pre-draw, then get_hold
        service_fresh(rng.exponential(srv_mean));
        break;
      case 3:
        record(clock - ev.payload);
        if (static_cast<uint64_t>(sn) >= n_objects) {
          done = true;
        } else {
          service_fresh(rng.exponential(srv_mean));
        }
        break;
      case 4:
        service_retry(static_cast<int32_t>(ev.payload), ev.payload2);
        break;
    }
  }
  return MM1Result{clock, sn, smean, sm2, smin, smax, events};
}

}  // namespace

extern "C" {

// Threefry known-answer access for binding sanity checks.
void cimba_threefry2x32(uint32_t k0, uint32_t k1, uint32_t c0, uint32_t c1,
                        uint32_t* o0, uint32_t* o1) {
  threefry2x32(k0, k1, c0, c1, o0, o1);
}

// Hardware entropy (parity: cmb_random_hwseed).
uint64_t cimba_hwseed(void) {
#if defined(__x86_64__)
  unsigned int a, b, c, d;
  // CPUID leaf 7: RDSEED bit EBX[18]; leaf 1: RDRAND bit ECX[30]
  if (__get_cpuid_count(7, 0, &a, &b, &c, &d) && (b & (1u << 18))) {
    unsigned long long v;
    for (int i = 0; i < 64; ++i) {
      if (_rdseed64_step(&v)) return v;
    }
  }
  if (__get_cpuid(1, &a, &b, &c, &d) && (c & (1u << 30))) {
    unsigned long long v;
    for (int i = 0; i < 64; ++i) {
      if (_rdrand64_step(&v)) return v;
    }
  }
#endif
  // clock mashup fallback (parity with the reference's C wrapper)
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  uint64_t v = static_cast<uint64_t>(ts.tv_sec) * 1000000000ull + ts.tv_nsec;
  struct timespec tm;
  clock_gettime(CLOCK_MONOTONIC, &tm);
  v ^= static_cast<uint64_t>(tm.tv_nsec) << 17;
  return fmix64(v);
}

// Scalar M/M/1 oracle; outputs [clock, n, mean, m2, min, max, events].
void cimba_oracle_mm1(uint64_t seed, uint64_t rep, uint64_t n_objects,
                      double arr_mean, double srv_mean, double* out7) {
  const MM1Result r = run_mm1(seed, rep, n_objects, arr_mean, srv_mean);
  out7[0] = r.clock;
  out7[1] = r.n;
  out7[2] = r.mean;
  out7[3] = r.m2;
  out7[4] = r.min;
  out7[5] = r.max;
  out7[6] = static_cast<double>(r.events);
}

// Single-stream M/M/1 at engine semantics (run_mm1_fast): the native
// host-core latency path behind bench.py --config mm1_single; same
// output layout as cimba_oracle_mm1 (+ out8[7] = fast-path overflow)
// and bitwise-equal results.  A slot-table invariant violation in the
// fast path falls back to the general run_mm1 engine and reports the
// event via out8[7] — a structured bench failure, never an abort.
void cimba_mm1_single(uint64_t seed, uint64_t rep, uint64_t n_objects,
                      double arr_mean, double srv_mean, double* out8) {
  MM1Result r = run_mm1_fast(seed, rep, n_objects, arr_mean, srv_mean);
  double fast_overflow = 0.0;
  if (r.overflow) {
    fast_overflow = 1.0;
    r = run_mm1(seed, rep, n_objects, arr_mean, srv_mean);
  }
  out8[0] = r.clock;
  out8[1] = r.n;
  out8[2] = r.mean;
  out8[3] = r.m2;
  out8[4] = r.min;
  out8[5] = r.max;
  out8[6] = static_cast<double>(r.events);
  out8[7] = fast_overflow;
}

// Scalar M/M/c oracle; same output layout as cimba_oracle_mm1.
void cimba_oracle_mmc(uint64_t seed, uint64_t rep, uint64_t n_objects,
                      double arr_mean, double srv_mean, uint32_t c,
                      double* out7) {
  const MM1Result r = run_mmc(seed, rep, n_objects, arr_mean, srv_mean, c);
  out7[0] = r.clock;
  out7[1] = r.n;
  out7[2] = r.mean;
  out7[3] = r.m2;
  out7[4] = r.min;
  out7[5] = r.max;
  out7[6] = static_cast<double>(r.events);
}

}  // extern "C"