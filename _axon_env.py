"""The one place that knows how to disable the axon accelerator plugin.

The plugin registers at interpreter startup (sitecustomize on PYTHONPATH),
gated on ``PALLAS_AXON_POOL_IPS`` being non-empty; once registered, a
wedged tunnel hangs jax backend init even under ``JAX_PLATFORMS=cpu``.
CPU-only entry points (tests, dry runs, bench fallback) therefore need a
*fresh process* whose environment clears that gate — built here, nowhere
else.  stdlib-only: importable before jax in every entry context.
"""

import os


def cpu_env(n_devices=None, base=None):
    """A copy of ``base`` (default: os.environ) with the accelerator
    plugin disabled and the CPU backend forced; ``n_devices`` adds
    ``--xla_force_host_platform_device_count`` (replacing any existing
    count flag)."""
    env = dict(os.environ if base is None else base)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return env


def plugin_enabled():
    """True when the axon plugin will have registered itself at startup."""
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
