"""Root conftest: wedge-proof pytest against the axon accelerator plugin.

Loaded as an initial conftest for every invocation style (`pytest`,
`python -m pytest`, any cwd with args under this repo).  Tests always run
on the virtual CPU mesh; when the axon plugin is armed (see ``_axon_env``)
a wedged tunnel hangs jax backend init even under in-process
``JAX_PLATFORMS=cpu``, so re-exec the whole process with the plugin
disabled in the environment.

pytest's FD-level capture already owns fds 1/2 by the time initial
conftests load; the exec'd image would report into a capture tempfile
nobody reads.  Point them back at the invoking process's stdout/stderr
first — if that parent is gone (nohup), the report is lost but the exit
code still tells the truth.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _axon_env  # noqa: E402

# CIMBA_ON_DEVICE=1 deliberately keeps the accelerator: the kernel
# equivalence battery then proves Mosaic-*executed* semantics (not just
# interpret-mode) — see tests/test_kernel_fuzz.py and tools/first_contact.py.
# Compared to "1" exactly, matching the tests' own gate, so
# CIMBA_ON_DEVICE=0 means OFF here too (not a live-TPU pytest session).
if _axon_env.plugin_enabled() and os.environ.get("CIMBA_ON_DEVICE") != "1":
    for _fd in (1, 2):
        try:
            _orig = os.open(
                f"/proc/{os.getppid()}/fd/{_fd}", os.O_WRONLY | os.O_APPEND
            )
            os.dup2(_orig, _fd)
            os.close(_orig)
        except OSError:
            pass
    os.execve(
        sys.executable,
        [sys.executable, "-m", "pytest"] + sys.argv[1:],
        _axon_env.cpu_env(),
    )
