"""Multi-tenant QoS plane for the serving layer (docs/27_qos.md).

Users arrive as *tenants*, not requests: this package carries the
per-tenant policy (:mod:`~cimba_tpu.qos.tenant`), the weighted-fair
lane-share scheduler that apportions freed refill lanes across tenants
(:mod:`~cimba_tpu.qos.fair`), and the admission-time quota/rate
limiter whose rejections are structured
:class:`~cimba_tpu.serve.sched.RetryAfter` backpressure
(:mod:`~cimba_tpu.qos.limits`).

Everything here is HOST-side admission policy: the tenant id never
joins the program/compatibility class key, the chunk program is
untouched (the ``qos`` gate in check/gates.py pins ambient inertness),
and delivered results stay bitwise their direct solo calls regardless
of the admission order QoS chooses.
"""

from cimba_tpu.qos.fair import FairScheduler
from cimba_tpu.qos.limits import AdmissionLimiter, TokenBucket
from cimba_tpu.qos.tenant import (
    DEFAULT_TENANT,
    TenantPolicy,
    TenantRegistry,
)

__all__ = [
    "DEFAULT_TENANT",
    "TenantPolicy",
    "TenantRegistry",
    "TokenBucket",
    "AdmissionLimiter",
    "FairScheduler",
]
