"""Weighted-fair lane shares: deficit round robin across tenants, EDF
within a class.

This is the policy that replaces the PR 15 priority-order prefix at
the refill admission point.  The problem with the prefix: freed lanes
go to the globally highest-priority queued requests, so one flooding
tenant's backlog occupies every freed lane and every other tenant's
p99 degrades without bound.  The fix is classic packet scheduling
transplanted to lanes:

* **Across tenants** — deficit round robin (DRR): each tenant carries
  a persistent *deficit* counter; each pass over the tenants credits
  ``weight x quantum`` and a tenant admits its head request only when
  its deficit covers the request's lane demand.  Lanes are the packet
  size, ``weight`` the link share: over time tenant lane shares
  converge to weights regardless of how unbalanced the backlogs are.
  A tenant whose backlog empties forfeits its residual deficit (the
  standard DRR anti-hoarding rule), so idleness is not bankable.
* **Within a tenant** — priority first (the existing user-visible
  contract is untouched), then **EDF**: among equal-priority requests
  the earliest ``deadline_at`` admits first (None = no deadline =
  last), then the ``fmix64(seq)`` mix as the final tie-break — the
  obs/audit.py host mixer, arbitrary but stable, so equal keys order
  reproducibly and owe nothing to arrival interleaving.

Everything is pure host arithmetic over the candidate list the
admission queue offers under its lock (``take_selected``): no clocks,
no randomness — two fresh services replaying one recorded stream make
identical selections, which is the admission-determinism contract
tests/test_qos.py pins.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, List, Optional

from cimba_tpu.qos.tenant import TenantRegistry

__all__ = ["FairScheduler", "entry_order_key", "tenant_mix"]

#: hard cap on DRR credit passes per selection — deficits grow by
#: ``weight x quantum > 0`` every pass, so any admissible head admits
#: long before this; the cap only bounds a pathological weight spread
_MAX_PASSES = 1024


def tenant_mix(name: str) -> int:
    """A stable 64-bit mix of a tenant name: blake2b (stable across
    processes, unlike ``hash``) through the audit fmix64 — the DRR
    visit order is arbitrary-but-reproducible, never alphabetic
    favoritism, never list position."""
    from cimba_tpu.obs.audit import _fmix64_host

    h = hashlib.blake2b(name.encode("utf-8"), digest_size=8).digest()
    return _fmix64_host(int.from_bytes(h, "big"))


def entry_order_key(entry: Any):
    """The within-tenant admission order: priority desc (the existing
    contract), then EDF (earliest ``deadline_at``; no deadline last),
    then ``fmix64(seq)`` — deterministic to the last tie."""
    from cimba_tpu.obs.audit import _fmix64_host

    dl = getattr(entry, "deadline_at", None)
    return (
        -entry.priority,
        float("inf") if dl is None else float(dl),
        _fmix64_host(int(entry.seq)),
    )


class FairScheduler:
    """The per-service DRR state + selection policy.

    One instance lives on the ``Service`` and is only touched from the
    dispatcher thread (inside the queue's ``take_selected`` lock), so
    it needs no lock of its own.  Deficits persist across claims —
    that is what makes shares hold over time when per-boundary lane
    budgets are lumpy."""

    def __init__(self, registry: TenantRegistry):
        self.registry = registry
        self._deficit: Dict[str, float] = {}

    def deficits(self) -> Dict[str, float]:
        """Snapshot for ``stats()`` (dispatcher-thread consistent)."""
        return dict(self._deficit)

    def select(
        self,
        candidates: List[Any],
        budget: int,
        *,
        lanes_of: Callable[[Any], int],
        tenant_of: Callable[[Any], str],
        room_of: Optional[Callable[[str], float]] = None,
    ) -> List[Any]:
        """Choose which candidates get the ``budget`` freed lanes.

        ``candidates`` is the queue's whole ready set (already
        class-filtered by the caller's closure); ``lanes_of`` the lane
        demand per entry; ``tenant_of`` the resolved tenant id;
        ``room_of`` the tenant's remaining lane-quota headroom
        (``inf`` when unlimited).  Returns the selected entries in
        admission order.  Within a tenant the order is strict
        (priority / EDF / fmix64): a blocked head blocks its tenant —
        admitting a later request over a blocked earlier one would
        reintroduce the starvation this scheduler exists to end."""
        if budget <= 0 or not candidates:
            return []
        groups: Dict[str, List[Any]] = {}
        for e in candidates:
            groups.setdefault(tenant_of(e), []).append(e)
        for q in groups.values():
            q.sort(key=entry_order_key)
        order = sorted(groups, key=lambda t: (tenant_mix(t), t))
        room = {
            t: (float("inf") if room_of is None else float(room_of(t)))
            for t in groups
        }
        heads = {t: 0 for t in groups}
        # anti-hoarding: a tenant with no backlog right now forfeits
        # its residual deficit
        for t in list(self._deficit):
            if t not in groups:
                del self._deficit[t]
        if len(groups) == 1:
            # no contention, no deficit arithmetic: the sole backlogged
            # tenant takes every lane its quota and the budget allow —
            # weights are SHARES, and a share of an uncontended link is
            # the whole link (a microscopic weight must not trickle)
            (t,) = groups
            out: List[Any] = []
            left = int(budget)
            for e in groups[t]:
                n = lanes_of(e)
                if n > left or n > room[t]:
                    break
                out.append(e)
                left -= n
                room[t] -= n
            if len(out) == len(groups[t]):
                # backlog emptied: forfeit residue, the standard rule
                self._deficit.pop(t, None)
            return out
        quantum = max(
            lanes_of(groups[t][0]) for t in groups
        )
        selected: List[Any] = []
        budget_left = int(budget)
        for _ in range(_MAX_PASSES):
            if budget_left <= 0:
                break
            progressed = False
            admissible = False
            for t in order:
                q = groups[t]
                i = heads[t]
                if i >= len(q):
                    continue
                w = self.registry.policy(t).weight
                self._deficit[t] = (
                    self._deficit.get(t, 0.0) + w * quantum
                )
                while i < len(q):
                    e = q[i]
                    n = lanes_of(e)
                    if n > budget_left or n > room[t]:
                        break
                    admissible = True
                    if n > self._deficit[t]:
                        break
                    selected.append(e)
                    self._deficit[t] -= n
                    budget_left -= n
                    room[t] -= n
                    i += 1
                    progressed = True
                heads[t] = i
                if i >= len(q):
                    # backlog emptied: forfeit the residue now, not at
                    # the next claim — within this selection too,
                    # idleness must not bank credit
                    self._deficit.pop(t, None)
                if budget_left <= 0:
                    break
            if not progressed and not admissible:
                break
        return selected
