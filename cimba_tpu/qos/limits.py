"""Admission-time quotas and rate limits with structured retry-after.

The limiter runs on the SUBMIT path, before the request touches the
admission queue: a request the tenant's policy cannot admit right now
raises :class:`~cimba_tpu.serve.sched.RetryAfter` — never bare
``QueueFull`` — naming the tenant, the reason (``"rate"`` |
``"quota"``), and a concrete ``delay_s``.  Nothing was admitted, no
lanes are held, other tenants are untouched; the client sleeps exactly
``delay_s`` and retries (``serve/client.py`` honors it in the
open-loop driver).

Determinism: the token bucket takes an injectable ``clock`` so the
replay contract — two fresh services fed one recorded stream produce
identical admission/throttle logs — holds under a logical clock in
tests, while production uses ``time.monotonic``.  The lane-quota check
is pure arithmetic over the service's own accounting and needs no
clock at all.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from cimba_tpu.qos.tenant import TenantRegistry
from cimba_tpu.serve.sched import RetryAfter

__all__ = ["TokenBucket", "AdmissionLimiter", "QUOTA_RETRY_S"]

#: the retry hint for a lane-quota rejection: quota frees when one of
#: the tenant's own requests retires, which the limiter cannot
#: schedule — a short fixed poll interval beats a fake derivation
QUOTA_RETRY_S = 0.05


class TokenBucket:
    """A deterministic token bucket: ``rate`` tokens/second refill,
    ``burst`` depth, one token per submission.  NOT thread-safe on its
    own — the owner (:class:`AdmissionLimiter`) serializes access.

    The clock is sampled lazily at the first take, so a bucket built
    at service construction does not grant a spurious head start to a
    tenant that first submits much later (the bucket starts FULL; the
    first ``burst`` submissions pass regardless)."""

    def __init__(
        self, rate: float, burst: int,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not (rate > 0):
            raise ValueError(f"rate must be positive, got {rate}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t: Optional[float] = None

    def try_take(self, n: float = 1.0) -> float:
        """Take ``n`` tokens: returns 0.0 on success, else the delay
        in seconds until ``n`` tokens will have refilled (the bucket
        is left untouched on failure — a throttled submission must not
        drain what the retry needs)."""
        now = self._clock()
        if self._t is not None:
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate
            )
        self._t = now
        if self._tokens >= n:
            self._tokens -= n
            return 0.0
        return (n - self._tokens) / self.rate

    def tokens(self) -> float:
        return self._tokens


class AdmissionLimiter:
    """Per-tenant rate + lane-quota enforcement for one service.

    Owns one :class:`TokenBucket` per rate-limited tenant (created on
    first submission).  The caller (``Service.submit``) passes the
    tenant's currently held lanes; the limiter is otherwise stateless
    about lanes — the service's own accounting is the single source of
    truth, so limiter and scheduler can never disagree about quota."""

    def __init__(
        self,
        registry: TenantRegistry,
        *,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.registry = registry
        self._clock = clock
        self._buckets: Dict[str, TokenBucket] = {}

    def check(
        self, tenant: Optional[str], lanes: int, lanes_held: int,
        label: Optional[str] = None,
    ) -> None:
        """Admit-or-raise for one submission: ``lanes`` is the
        request's lane demand, ``lanes_held`` the tenant's lanes
        currently in flight.  Raises :class:`RetryAfter`; returns
        None on admit (the rate token is then spent)."""
        policy = self.registry.policy(tenant)
        name = self.registry.resolve(tenant)
        if policy.lane_quota is not None \
                and lanes_held + lanes > policy.lane_quota:
            raise RetryAfter(
                QUOTA_RETRY_S, name, reason="quota", label=label,
            )
        if policy.rate is not None:
            bucket = self._buckets.get(name)
            if bucket is None:
                bucket = TokenBucket(
                    policy.rate, policy.burst, clock=self._clock
                )
                self._buckets[name] = bucket
            delay = bucket.try_take(1.0)
            if delay > 0.0:
                raise RetryAfter(
                    delay, name, reason="rate", label=label,
                )

    def deadline_for(self, tenant: Optional[str]) -> Optional[float]:
        """The tenant's ``deadline_class`` default (seconds), for
        requests that carry no explicit deadline."""
        return self.registry.policy(tenant).deadline_class
