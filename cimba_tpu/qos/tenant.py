"""The tenant model: who a request belongs to, and what that buys it.

A :class:`TenantPolicy` is the whole per-tenant contract in one frozen
record:

* ``weight`` — the tenant's share of freed lanes under the
  deficit-weighted round robin of :mod:`~cimba_tpu.qos.fair` (shares
  are relative: weight 3.0 next to weight 1.0 gets ~3/4 of contended
  lanes, and an uncontended tenant still gets everything);
* ``lane_quota`` — a hard cap on the tenant's *concurrently held*
  lanes (in flight + claimed), enforced both at submit (structured
  :class:`~cimba_tpu.serve.sched.RetryAfter` with ``reason="quota"``)
  and inside the fair claim (a tenant at quota is skipped, never
  starves others);
* ``rate``/``burst`` — a token bucket over *submissions*
  (requests/second with ``burst`` depth), the flood valve: a tenant
  past its rate gets ``RetryAfter(delay_s=...)`` naming exactly when a
  retry can succeed;
* ``deadline_class`` — a default deadline (seconds) stamped on the
  tenant's requests that carry none, which is what the EDF ordering
  within a compatibility class keys on.

The :class:`TenantRegistry` maps tenant names to policies.  A request
with ``tenant=None`` — or naming a tenant nobody registered — gets the
registry's **default** policy: weight 1, no quota, no rate limit, no
deadline class.  That default IS today's behavior, which is how the
whole plane stays zero-cost off: with no registry (or ``CIMBA_QOS``
unset) every request is the default tenant and admission reduces to
the PR 15 priority-order prefix byte for byte.

The tenant id is carried on ``Request(tenant=)`` beside
``trace_context`` and is **never** part of the program/compatibility
class key — two tenants' identical requests share one compiled
program, one wave, one digest.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, Iterable, List, Optional

__all__ = ["DEFAULT_TENANT", "TenantPolicy", "TenantRegistry"]

#: the tenant every request without one belongs to
DEFAULT_TENANT = "default"


@dataclass(frozen=True)
class TenantPolicy:
    """One tenant's QoS contract.  Frozen: policies are values, shared
    freely across threads and snapshots."""

    name: str
    weight: float = 1.0
    lane_quota: Optional[int] = None
    rate: Optional[float] = None
    burst: int = 8
    deadline_class: Optional[float] = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if not (self.weight > 0):
            raise ValueError(
                f"tenant {self.name!r}: weight must be positive, "
                f"got {self.weight}"
            )
        if self.lane_quota is not None and self.lane_quota <= 0:
            raise ValueError(
                f"tenant {self.name!r}: lane_quota must be positive, "
                f"got {self.lane_quota}"
            )
        if self.rate is not None and not (self.rate > 0):
            raise ValueError(
                f"tenant {self.name!r}: rate must be positive, "
                f"got {self.rate}"
            )
        if self.burst < 1:
            raise ValueError(
                f"tenant {self.name!r}: burst must be >= 1, "
                f"got {self.burst}"
            )
        if self.deadline_class is not None \
                and not (self.deadline_class > 0):
            raise ValueError(
                f"tenant {self.name!r}: deadline_class must be "
                f"positive, got {self.deadline_class}"
            )


class TenantRegistry:
    """Name -> :class:`TenantPolicy`, with a default for everyone else.

    Read-mostly and internally immutable after construction plus
    explicit :meth:`register` calls; lookups take no lock (dict reads
    are atomic, policies are frozen), which keeps :meth:`policy` safe
    on the submit path and inside the dispatcher's claim."""

    def __init__(
        self,
        policies: Iterable[TenantPolicy] = (),
        *,
        default: Optional[TenantPolicy] = None,
    ):
        self.default = (
            default if default is not None
            else TenantPolicy(DEFAULT_TENANT)
        )
        self._policies: Dict[str, TenantPolicy] = {
            self.default.name: self.default
        }
        for p in policies:
            self.register(p)

    def register(self, policy: TenantPolicy) -> None:
        if not isinstance(policy, TenantPolicy):
            raise TypeError(
                f"expected TenantPolicy, got {type(policy).__name__}"
            )
        self._policies[policy.name] = policy
        if policy.name == self.default.name:
            self.default = policy

    def policy(self, name: Optional[str]) -> TenantPolicy:
        """The effective policy for ``name``: ``None`` is the default
        tenant; an unregistered name gets the default policy's limits
        under its own name (so unknown tenants are fairly weighted
        peers, not errors — registration is opt-in shaping)."""
        if name is None:
            return self.default
        p = self._policies.get(name)
        if p is not None:
            return p
        return replace(self.default, name=name)

    def resolve(self, name: Optional[str]) -> str:
        """The canonical tenant id a request with ``tenant=name``
        belongs to (``None`` -> the default tenant's name)."""
        return self.default.name if name is None else str(name)

    def names(self) -> List[str]:
        return sorted(self._policies)

    def __contains__(self, name: str) -> bool:
        return name in self._policies

    def __len__(self) -> int:
        return len(self._policies)
