"""Packed while-loop carries: many narrow leaves -> a few wide buffers.

Measured on v5e (BENCH_NOTES round-5 floor probes): the per-iteration
fixed cost of a while loop scales super-linearly with the number of
narrow carried leaves — mm1's real 54-leaf carry costs ~135 us/step with
a TRIVIAL body, while the same bytes in a few wide f32 buffers cost
<1 us.  Packing trades ~2 slice + reshape (+bitcast) ops per leaf per
iteration — all wide-array structural ops — for that per-leaf overhead.

One plan serves both hot paths:

* the Pallas chunk kernel (``core/pallas_run.py``, lane-LAST leaves
  ``[comp..., L]`` -> ``[rows, L]`` buffers — ``CIMBA_KERNEL_PACK``);
* the XLA while-loop path (``core/loop.make_run``, per-replication
  leaves ``[comp...]`` -> ``[rows]`` buffers, vmapped after —
  ``CIMBA_XLA_PACK``; see docs/11_dispatch_cost.md).

Same-width leaves share one buffer per dtype class: f32; i32 with u32
rows riding along via same-width bitcast (bitcast is bitwise, selects
and copies do not interpret the payload); f64/i64 classes exist for the
exact-profile XLA path (the kernel path can never produce them — Mosaic
has no 64-bit types, so its plans degenerate to the historical f32/i32
pair and trace the identical jaxpr).  Bool leaves and anything else
pass through per-leaf.

Packing is a CARRY-LAYOUT change, never a semantic one: pack followed by
unpack is bitwise identity (pinned by tests/test_kernel_fuzz.py and
tests/test_xla_pack.py), so the loop body computes on exactly the leaves
it always did.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

#: dtype classes that pack, in buffer order: (name, buffer dtype,
#: member dtypes bitcast into it).  32-bit classes first so kernel-mode
#: plans (which can only contain them) keep their historical buffer
#: order bit-for-bit.
_CLASSES = (
    ("f32", jnp.float32, (jnp.float32,)),
    ("i32", jnp.int32, (jnp.int32, jnp.uint32)),
    ("f64", jnp.float64, (jnp.float64,)),
    ("i64", jnp.int64, (jnp.int64, jnp.uint64)),
)


def pack_plan(avals, lane_last: bool = True):
    """Static packing plan over carried leaves.

    ``lane_last=True`` treats the trailing axis as the lane axis L
    (kernel layout): a ``[s..., L]`` leaf becomes ``prod(s)`` rows of a
    ``[rows, L]`` buffer.  ``lane_last=False`` packs whole per-
    replication leaves: ``[s...]`` becomes ``prod(s)`` elements of a
    flat ``[rows]`` buffer (vmap then batches the buffers, not the
    leaves).

    Returns a dict: ``groups`` maps class name to the list of leaf
    indices packed in that buffer (row-major, stable order),
    ``passthrough`` lists leaf indices carried per-leaf, and
    ``meta[i] = (rows_i, packed_shape_i, dtype_i)`` for every leaf.
    """
    groups = {name: [] for name, _, _ in _CLASSES}
    passthrough = []
    meta = []
    for i, a in enumerate(avals):
        s = tuple(a.shape[:-1]) if lane_last else tuple(a.shape)
        r = 1
        for d in s:
            r *= int(d)
        meta.append((r, s, a.dtype))
        for name, _, members in _CLASSES:
            if any(a.dtype == m for m in members):
                groups[name].append(i)
                break
        else:
            passthrough.append(i)
    return {
        "groups": groups,
        "passthrough": passthrough,
        "meta": meta,
        "lane_last": lane_last,
    }


def n_buffers(plan) -> int:
    """Carried values in the packed layout (buffers + passthroughs)."""
    return sum(1 for _, idxs in plan["groups"].items() if idxs) + len(
        plan["passthrough"]
    )


def _pack_rows(x, r, s, lane_last: bool):
    """lane_last: [s..., L] -> [r, L]; else [s...] -> [r] (reshape
    touches leading dims only in the lane-last form — the Mosaic-clean
    direction)."""
    if lane_last:
        L = x.shape[-1]
        if s == ():
            return lax.reshape(x, (1, L))
        if len(s) == 1:
            return x
        return lax.reshape(x, (r, L))
    if s == ():
        return lax.reshape(x, (1,))
    if len(s) == 1:
        return x
    return lax.reshape(x, (r,))


def pack(leaves, plan):
    """leaves (original order) -> packed carry list:
    [f32 buffer?, i32 buffer?, f64?, i64?, *passthrough leaves]."""
    lane_last = plan["lane_last"]
    out = []
    for name, dt, _ in _CLASSES:
        idxs = plan["groups"][name]
        if not idxs:
            continue
        parts = []
        for i in idxs:
            r, s, dtype = plan["meta"][i]
            p = _pack_rows(leaves[i], r, s, lane_last)
            if dtype != dt:  # u32/u64 rows ride the int buffer bitwise
                p = lax.bitcast_convert_type(p, dt)
            parts.append(p)
        out.append(
            parts[0] if len(parts) == 1 else lax.concatenate(parts, 0)
        )
    for i in plan["passthrough"]:
        out.append(leaves[i])
    return out


def unpack(packed, plan, L=None):
    """Inverse of :func:`pack`: packed carry list -> leaves in original
    order (row slices + bitcast + reshape, all wide-array structural
    ops).  ``L`` is the lane width (required for lane-last plans)."""
    lane_last = plan["lane_last"]
    n = len(plan["meta"])
    leaves = [None] * n
    k = 0
    for name, dt, _ in _CLASSES:
        idxs = plan["groups"][name]
        if not idxs:
            continue
        buf = packed[k]
        k += 1
        o = 0
        for i in idxs:
            r, s, dtype = plan["meta"][i]
            if len(idxs) == 1:
                p = buf
            elif lane_last:
                p = lax.slice(buf, (o, 0), (o + r, L))
            else:
                p = lax.slice(buf, (o,), (o + r,))
            o += r
            if dtype != dt:
                p = lax.bitcast_convert_type(p, dtype)
            if lane_last:
                if s == ():
                    p = lax.reshape(p, (L,))
                elif len(s) != 1:
                    p = lax.reshape(p, s + (L,))
            else:
                if s == ():
                    p = lax.reshape(p, ())
                elif len(s) != 1:
                    p = lax.reshape(p, s)
            leaves[i] = p
    for i in plan["passthrough"]:
        leaves[i] = packed[k]
        k += 1
    return leaves
