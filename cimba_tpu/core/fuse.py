"""Cross-spec wave fusion: branch-dispatch superprograms.

A wave (docs/14_wave_packing.md) packs lanes of ONE compatibility
class — same spec, same chunk geometry.  A fleet serving many small
*different* models degenerates to all-solo waves: each spec compiles
its own program and occupies its own (mostly padded) wave, and the
refill/occupancy machinery (docs/22, docs/24) cannot help because no
two requests are ever compatible.

Fusion extends the per-lane seed/horizon-column trick (docs/14) to
per-lane *model identity*.  :func:`fuse_specs` merges N
compatible-shape member specs into one **superspec** whose block table
is the concatenation of the members' tables, each member's entry pcs
rebased by its table offset.  The chunk program built from the
superspec is the ordinary :func:`cimba_tpu.core.loop.make_chunk` —
block dispatch is already a per-lane ``lax.switch`` on ``procs.pc``,
so once a lane's pcs live in member k's slice of the merged table, the
existing dispatch IS the per-lane model switch.  Only *initialisation*
needs an explicit branch: :func:`make_fused_init` switches each lane's
``init_sim`` through its member's own process table / ``user_init`` on
a per-lane ``spec_id`` column, and :func:`make_fused_refill` does the
same for mid-wave lane splices (docs/22_refill.md).

Why lanes stay BITWISE equal to their solo runs (docs/26_wave_fusion.md):

* dispatch is value-exact: ``lax.switch`` under ``vmap`` computes every
  branch and *selects* per lane, and selection never perturbs the
  selected values — a member lane runs exactly its own block functions
  (member 0's table entries are the original function objects; other
  members' entries are thin wrappers that add the pc base to
  ``Command.next_pc`` and change nothing else);
* pc values are shifted by the member's base but pc never reaches a
  result: summaries fold user state, ``n_events`` and metrics only,
  and the machinery never compares pcs across specs;
* the merged spec's command-tag union can only *add* machinery arms,
  and every arm is tag-selected per command — a lane whose commands
  carry only its member's tags computes exactly what its solo program
  computes;
* member shape compatibility (:func:`fusion_shape_key`) pins every
  capacity and component layout, so all Sim leaves have identical
  shapes and dtypes across members — no re-layout, no padding drift.

What CANNOT fuse (and why — docs/26_wave_fusion.md#when-not-to-fuse):

* specs with spawn pools (``m.process(..., start=False)``):
  ``api.spawn`` bakes the pool's *unrebased* ``entry_pc`` into the
  traced program at build time (``loop.spawn_process``), so a spawned
  row would dispatch into the wrong member's table slice;
* specs with ``boundary_pcs``: the kernel boundary protocol keys block
  *indices*, which rebasing renumbers;
* specs whose component geometry, caps, local counts, condition
  predicates or user handlers differ: the merged program keeps ONE
  copy of the machinery, so all members must agree on it exactly
  (predicates/handlers by function identity, everything else by
  value).

The serving layer (docs/26) additionally requires members to share a
params-row signature and a Sim *structure* signature (user state /
metrics / trace leaves), so a structure mismatch is rejected at class
formation — never at trace time inside ``lax.switch``.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cimba_tpu.core import loop as _loop
from cimba_tpu.core.model import ModelSpec


class FusionError(ValueError):
    """The spec (or spec set) cannot participate in wave fusion; the
    message names the disqualifying structure.  Callers treat this as
    "serve it solo", never as a hard failure."""


def _ref_shape(r):
    # the identity-free twin of cache.spec_fingerprint's ref_key: drop
    # the display name, keep ids/capacities/guards; callables (condition
    # predicates) key by object identity — members must SHARE them,
    # because the merged spec keeps a single copy of the machinery
    out = []
    for f in dataclasses.fields(r):
        if f.name == "name":
            continue
        v = getattr(r, f.name)
        if callable(v):
            out.append((f.name, "fn", id(v)))
        elif isinstance(v, (list, tuple)):
            out.append((f.name, tuple(v)))
        else:
            out.append((f.name, v))
    return (type(r).__name__, tuple(out))


def fusion_shape_key(spec: ModelSpec) -> tuple:
    """The structural-geometry key of a spec MINUS its model identity:
    two specs with equal keys can share one fused superprogram.  Keeps
    process count, local/caps/component layout, condition predicate and
    user-handler identities; excludes the name, the block table, the
    per-process entry/prio/start data and ``user_init`` (all per-member
    — consumed only inside :func:`~cimba_tpu.core.loop.init_sim`, which
    fused waves dispatch per lane).  Raises :class:`FusionError` for
    structurally unfusable specs."""
    cached = getattr(spec, "_cimba_fusion_shape", None)
    if cached is not None:
        return cached
    if tuple(spec.boundary_pcs):
        raise FusionError(
            f"spec {spec.name!r} has boundary_pcs: the kernel boundary "
            "protocol keys block indices, which fusion renumbers"
        )
    if not all(bool(s) for s in np.asarray(spec.proc_start).tolist()):
        raise FusionError(
            f"spec {spec.name!r} declares a spawn pool (start=False): "
            "api.spawn bakes the unrebased entry_pc into the trace "
            "(loop.spawn_process), so spawned rows cannot be rebased"
        )
    key = (
        int(spec.n_procs),
        tuple(_ref_shape(q) for q in spec.queues),
        tuple(_ref_shape(r) for r in spec.resources),
        tuple(_ref_shape(p) for p in spec.pools),
        tuple(_ref_shape(b) for b in spec.buffers),
        tuple(_ref_shape(q) for q in spec.pqueues),
        tuple(_ref_shape(c) for c in spec.conditions),
        spec.n_guards, spec.guard_cap, spec.event_cap,
        spec.queue_cap_max, spec.pqueue_cap_max,
        spec.n_flocals, spec.n_ilocals, spec.max_chain,
        tuple(id(h) for h in spec.user_handlers),
    )
    try:
        object.__setattr__(spec, "_cimba_fusion_shape", key)
    except (AttributeError, TypeError):
        pass
    return key


def _rebase_block(fn, base: int):
    """Wrap one member block so every pc it yields lands back in the
    member's slice of the merged table.  ``Command.next_pc`` is the
    ONLY pc-bearing command field (core/process.py), and blocks yield
    pcs exclusively through it — ``cmd.select`` merges whole Commands,
    so a data-dependent next_pc is still a single field to shift.  The
    shift is value-preserving for results: exit commands ignore
    next_pc, and nothing downstream compares pcs across members."""

    def rebased(sim, p, sig, _fn=fn, _base=base):
        sim, c = _fn(sim, p, sig)
        return sim, c._replace(next_pc=c.next_pc + _base)

    return rebased


@dataclasses.dataclass(frozen=True)
class FusedSpec:
    """A fused superspec bundle.

    ``spec`` is a real :class:`ModelSpec` — the merged block table over
    member 0's machinery — so every downstream consumer (chunk
    programs, program caches, stores, ``obs.program_size``) handles it
    unchanged.  ``rebased[k]`` is member k's spec twin carrying the
    merged table and rebased ``proc_entry`` — the spec a lane's
    ``init_sim`` branch runs, and the ONLY place member identity
    survives (prio/start/user_init are init-time data).  ``members``
    keeps the original specs pinned (cache entries embedding function
    ids must pin the objects — serve/cache.py's entry-pinning
    invariant)."""

    spec: ModelSpec
    members: Tuple[ModelSpec, ...]
    rebased: Tuple[ModelSpec, ...]
    bases: Tuple[int, ...]

    @property
    def n_members(self) -> int:
        return len(self.members)


def fuse_specs(specs: Sequence[ModelSpec]) -> FusedSpec:
    """Merge compatible-shape member specs into one superspec.

    The merged block table is the concatenation of the members' tables
    (member 0's blocks verbatim — base 0 needs no wrapper, so a
    single-member "fusion" degenerates to the original functions).
    The merged spec keeps member 0's process arrays and machinery; a
    lane only ever reaches the merged table through its member's
    rebased ``init_sim``, so the merged spec's own entry data is never
    consulted for foreign lanes (``proc_entry``/``prio``/``start`` are
    consumed exclusively by :func:`~cimba_tpu.core.loop.init_sim`)."""
    specs = tuple(specs)
    if not specs:
        raise FusionError("fuse_specs: empty member set")
    shape0 = fusion_shape_key(specs[0])
    for s in specs[1:]:
        if fusion_shape_key(s) != shape0:
            raise FusionError(
                f"fuse_specs: {s.name!r} is not shape-compatible with "
                f"{specs[0].name!r} (component geometry, caps, locals, "
                "predicates and handlers must match exactly)"
            )
    merged: list = []
    bases: list = []
    for k, s in enumerate(specs):
        base = len(merged)
        bases.append(base)
        if base == 0:
            merged.extend(s.blocks)
        else:
            merged.extend(_rebase_block(b, base) for b in s.blocks)
    table = tuple(merged)
    name = "fused(" + "+".join(s.name for s in specs) + ")"
    spec = dataclasses.replace(
        specs[0], name=name, blocks=table, boundary_pcs=(),
    )
    rebased = tuple(
        dataclasses.replace(
            s,
            blocks=table,
            proc_entry=np.asarray(s.proc_entry) + b,
        )
        for s, b in zip(specs, bases)
    )
    return FusedSpec(
        spec=spec, members=specs, rebased=rebased, bases=tuple(bases),
    )


def _switched_init(fused: FusedSpec):
    # one lane: dispatch init_sim through the lane's member spec.  The
    # index is clipped like block dispatch (lax.switch clamps anyway;
    # the clip keeps the contract explicit) — pad lanes carry sid 0.
    branches = tuple(
        (lambda r, s, t, q, _sp=sp: _loop.init_sim(_sp, s, r, q, t_stop=t))
        for sp in fused.rebased
    )
    if len(branches) == 1:
        only = branches[0]
        return lambda r, s, t, sid, q: only(r, s, t, q)

    def init1(r, s, t, sid, q):
        return jax.lax.switch(
            jnp.clip(sid, 0, len(branches) - 1), branches, r, s, t, q,
        )

    return init1


def make_fused_init(fused: FusedSpec):
    """Build ``init(reps, seeds, t_stops, sids, params) -> Sim`` — the
    fused twin of the serving init program: per-lane ``lax.switch`` on
    the ``sids`` column routes each lane's :func:`init_sim` through its
    own member spec (rebased entry pcs, own prio/start/``user_init``).
    Under ``vmap`` the switch computes every member's init and selects
    per lane — selection is value-exact, so a member lane's born state
    is bitwise its solo wave's.  All members of a fusion class share
    one params-row signature (the class key guarantees it), so a single
    batched params tree serves every branch."""
    init1 = _switched_init(fused)

    def init(reps, seeds, t_stops, sids, params):
        return jax.vmap(init1)(reps, seeds, t_stops, sids, params)

    return init


def make_fused_refill(fused: FusedSpec):
    """Build ``refill(sims, mask, reps, seeds, t_stops, sids, params)
    -> sims`` — the fused twin of :func:`cimba_tpu.core.loop.make_refill`:
    masked lanes are re-born through :func:`make_fused_init`'s per-lane
    member dispatch and spliced in with the same per-leaf masked select
    (unmasked lanes pass through bit-identically; dead/pad rows carry
    ``t_stop=-inf`` and sid 0).  One refill program serves the whole
    fusion class — a boundary splice admits any member without
    retracing."""
    init1 = _switched_init(fused)

    def refill(sims: _loop.Sim, mask, reps, seeds, t_stops, sids, params):
        if sims.t_stop is None:
            raise ValueError(
                "make_fused_refill: the wave carries no per-lane t_stop "
                "leaf — fused refill waves always materialize the "
                "horizon column (docs/22_refill.md, docs/26)"
            )
        fresh = jax.vmap(init1)(reps, seeds, t_stops, sids, params)

        def sel(a, b):
            m = mask.reshape(mask.shape + (1,) * (a.ndim - 1))
            return jnp.where(m, a, b)

        return jax.tree.map(sel, fresh, sims)

    return refill
