"""lanelast: batch a per-lane jaxpr with the lane axis ALWAYS last.

Why not ``jax.vmap(step, in_axes=-1)``: Mosaic tiles the last two dims of
every array, so the one layout where per-lane scalars ([L], lanes minor),
component arrays ([k, L]) and masks all interact without relayout is
lane-LAST — every broadcast adds *leading* dims (free) and every per-lane
reduction contracts *leading* dims (supported).  vmap cannot produce that
program: its reshape/broadcast batching rules normalize batch dims to
axis 0 and wrap the ops in minor-axis moveaxis pairs, several of which
the Mosaic layout pass rejects ("unsupported shape cast") or check-fails
on (layout.h:320) — all bisected in round 2 (tools/mosaic_eqn_bisect.py).

This module re-implements the batching as a jaxpr interpreter with a
fixed discipline:

* a BATCHED value of per-lane shape ``s`` is carried as ``s + (L,)``;
* an UNBATCHED rank>=1 value is carried "lane-ready" as ``s + (1,)`` —
  constructed that way at its origin (iota, broadcast, const) so no
  traced reshape ever moves the minor dim; mixing it with batched
  operands is then a size-1-minor lane broadcast, which Mosaic supports;
* unbatched scalars stay scalars (splats are free);
* elementwise ops broadcast every operand to ``out_shape + (L|1,)``;
* reductions/arg-reductions keep their axes (per-lane dims coincide with
  leading dims) and never touch the lane axis;
* ``broadcast_in_dim``/``reshape``/``slice``/``squeeze`` keep the lane
  axis last and untouched;
* ``while`` recurses with a batchedness fixpoint over the carry;
  ``pjit`` bodies are inlined.

The result is a batched jaxpr whose every op keeps lanes minor — the
program vmap should have written.  Used by core/pallas_run.py; bool32
runs after it to eliminate i1 vectors.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax._src import core as jcore

_ELEMENTWISE = {
    "add", "sub", "mul", "div", "rem", "max", "min", "pow",
    "and", "or", "xor", "not", "neg", "abs", "sign", "integer_pow",
    "log", "log1p", "exp", "expm1", "sqrt", "rsqrt", "floor", "ceil",
    "round", "logistic", "tanh", "sin", "cos", "atan2", "atan", "asin",
    "acos", "erf", "erfc", "erf_inv", "square",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "eq", "ne", "lt", "le", "gt", "ge", "is_finite",
    "select_n", "convert_element_type", "clamp", "nextafter",
}
_REDUCTIONS = {
    "reduce_sum", "reduce_prod", "reduce_max", "reduce_min",
    "reduce_or", "reduce_and", "argmax", "argmin",
}


class _Val:
    __slots__ = ("x", "batched")

    def __init__(self, x, batched):
        self.x = x
        self.batched = batched


def _lane_ready(c):
    """Concrete unbatched const -> lane-ready form, converted HOST-side."""
    arr = np.asarray(c)
    if arr.ndim == 0:
        return jnp.asarray(arr)
    return jnp.asarray(arr.reshape(arr.shape + (1,)))


def _read(env, v):
    if isinstance(v, jcore.Literal):
        return _Val(_lane_ready(v.val), False)
    return env[v]


def _align(val, out_shape, L):
    """Broadcast a _Val to ``out_shape + (L,)``.  Scalars splat; batched
    values broadcast leading dims; unbatched lane-ready values ([..., 1])
    add a size-1-minor lane broadcast — all Mosaic-supported directions."""
    return jnp.broadcast_to(val.x, out_shape + (L,))


def _align_unbatched(val, out_shape):
    return jnp.broadcast_to(val.x, out_shape + (1,))


def eval_lanelast(jaxpr, consts, L, in_vals):
    """Evaluate ``jaxpr`` under the lane-last batching discipline.

    ``in_vals``: list of _Val for the jaxpr invars.  Returns list of _Val.
    """
    env = {}
    for v, c in zip(jaxpr.constvars, consts):
        env[v] = _Val(_lane_ready(c), False)
    for v, val in zip(jaxpr.invars, in_vals):
        env[v] = val

    def write(eqn, outs):
        for var, o in zip(eqn.outvars, outs):
            if type(var).__name__ != "DropVar":
                env[var] = o

    for eqn in jaxpr.eqns:
        prim = str(eqn.primitive)
        ins = [_read(env, v) for v in eqn.invars]
        batched = any(i.batched for i in ins)

        if prim in _ELEMENTWISE:
            out_shape = eqn.outvars[0].aval.shape
            if batched:
                ops = [_align(i, out_shape, L) for i in ins]
            else:
                scalar_out = len(out_shape) == 0
                if scalar_out:
                    ops = [i.x for i in ins]
                else:
                    ops = [_align_unbatched(i, out_shape) for i in ins]
            outs = eqn.primitive.bind(*ops, **eqn.params)
            outs = outs if eqn.primitive.multiple_results else [outs]
            write(eqn, [_Val(o, batched) for o in outs])
        elif prim in _REDUCTIONS:
            (i,) = ins
            outs = eqn.primitive.bind(i.x, **eqn.params)
            outs = outs if eqn.primitive.multiple_results else [outs]
            if not i.batched:
                # unbatched operands are lane-ready ([..., 1]); a per-lane
                # scalar result must collapse that trailing dim back to a
                # true rank-0 scalar or the 'unbatched scalars stay
                # scalars' invariant breaks downstream (mixed ()/(1,)
                # elementwise operands, while-cond rank check)
                outs = [
                    lax.reshape(o, ())
                    if tuple(v.aval.shape) == () and jnp.ndim(o) == 1
                    else o
                    for o, v in zip(outs, eqn.outvars)
                ]
            write(eqn, [_Val(o, i.batched) for o in outs])
        elif prim == "broadcast_in_dim":
            (i,) = ins
            shape = tuple(eqn.params["shape"])
            bdims = tuple(eqn.params["broadcast_dimensions"])
            x = i.x
            if jnp.ndim(x) == 0:
                out = lax.broadcast_in_dim(x, shape + (1,), ())
                write(eqn, [_Val(out, False)])
            else:
                # x carries a trailing lane dim (L or 1): map it to the
                # appended last output dim
                lane = x.shape[-1]
                out = lax.broadcast_in_dim(
                    x, shape + (lane,), bdims + (len(shape),)
                )
                write(eqn, [_Val(out, i.batched)])
        elif prim == "reshape":
            (i,) = ins
            new_sizes = tuple(eqn.params["new_sizes"])
            if eqn.params.get("dimensions") is not None:
                raise NotImplementedError("reshape with dimensions")
            x = i.x
            if jnp.ndim(x) == 0:
                write(eqn, [_Val(lax.reshape(x, new_sizes + (1,)), False)])
            else:
                lane = x.shape[-1]
                out = lax.reshape(x, new_sizes + (lane,))
                write(eqn, [_Val(out, i.batched)])
        elif prim == "squeeze":
            (i,) = ins
            dims = tuple(eqn.params["dimensions"])
            x = i.x
            # per-lane dims coincide with leading dims; lane stays
            out_shape = eqn.outvars[0].aval.shape
            out = lax.reshape(x, tuple(out_shape) + (x.shape[-1],))
            write(eqn, [_Val(out, i.batched)])
        elif prim == "slice":
            (i,) = ins
            x = i.x
            start = tuple(eqn.params["start_indices"]) + (0,)
            limit = tuple(eqn.params["limit_indices"]) + (x.shape[-1],)
            strides = eqn.params["strides"]
            strides = (
                tuple(strides) + (1,) if strides is not None
                else (1,) * x.ndim
            )
            out = lax.slice(x, start, limit, strides)
            write(eqn, [_Val(out, i.batched)])
        elif prim == "concatenate":
            d = eqn.params["dimension"]
            if batched:
                ops = [
                    _align(i, tuple(v.aval.shape), L)
                    for i, v in zip(ins, eqn.invars)
                ]
            else:
                ops = [
                    _align_unbatched(i, tuple(v.aval.shape))
                    for i, v in zip(ins, eqn.invars)
                ]
            out = lax.concatenate(ops, dimension=d)
            write(eqn, [_Val(out, batched)])
        elif prim == "iota":
            shape = tuple(eqn.params["shape"])
            dim = eqn.params["dimension"]
            dtype = eqn.params["dtype"]
            out = lax.broadcasted_iota(dtype, shape + (1,), dim)
            write(eqn, [_Val(out, False)])
        elif prim == "dynamic_slice":
            op, *starts = ins
            _check_unbatched_starts(prim, starts)
            sizes = tuple(eqn.params["slice_sizes"])
            lane = op.x.shape[-1]
            out = lax.dynamic_slice(
                op.x,
                tuple(s.x for s in starts) + (jnp.zeros_like(starts[0].x),),
                sizes + (lane,),
            )
            write(eqn, [_Val(out, op.batched)])
        elif prim == "dynamic_update_slice":
            op, upd, *starts = ins
            _check_unbatched_starts(prim, starts)
            if batched:
                xop = _align(op, tuple(eqn.invars[0].aval.shape), L)
                xup = _align(upd, tuple(eqn.invars[1].aval.shape), L)
            else:
                xop = op.x
                xup = upd.x
            out = lax.dynamic_update_slice(
                xop, xup,
                tuple(s.x for s in starts) + (jnp.zeros_like(starts[0].x),),
            )
            write(eqn, [_Val(out, batched)])
        elif prim == "dot_general":
            write(eqn, [_dot_general(eqn, ins, L)])
        elif prim == "while":
            write(eqn, _bind_while(eqn, ins, L))
        elif prim in ("pjit", "jit"):
            closed = eqn.params["jaxpr"]
            write(
                eqn, eval_lanelast(closed.jaxpr, closed.consts, L, ins)
            )
        elif prim == "custom_jvp_call":
            # forward-pass semantics only (no AD inside the kernel):
            # inline the primal jaxpr, e.g. jax.nn.relu / sigmoid
            closed = eqn.params["call_jaxpr"]
            write(
                eqn, eval_lanelast(closed.jaxpr, closed.consts, L, ins)
            )
        else:
            raise NotImplementedError(
                f"lanelast: no rule for primitive '{prim}' "
                f"({[str(v.aval) for v in eqn.invars]})"
            )

    return [_read(env, v) for v in jaxpr.outvars]


def _check_unbatched_starts(prim, starts):
    """Dynamic-slice starts must be UNBATCHED scalars under the lane-last
    discipline: a per-lane start is a gather/scatter in disguise, which
    Mosaic has no rule for.  The scan-over-rows table dispatch
    (core/dyn.py) keys every slice on the unbatched block counter, so a
    batched start reaching here is a programming error, not a layout to
    support."""
    if any(s.batched or jnp.ndim(s.x) for s in starts):
        raise NotImplementedError(
            f"lanelast: {prim} start indices must be unbatched scalars "
            "(a per-lane start is a gather — slice on the unbatched "
            "block counter instead; see core/dyn.py scan-over-rows)"
        )


def _dot_general(eqn, ins, L):
    """Per-lane matmul, lane-last: [m,K] @ [K,n] per lane, carried as
    [m,K,lane] x [K,n,1].  Covers the physics-hook pattern — batched
    activations against UNBATCHED weights (consts), no batch dims — by
    unrolling the contracting dim into multiply-accumulates whose only
    broadcasts are sublane 1->n and minor 1->lane, both Mosaic-supported.
    The MXU is unreachable from a lane-last VPU kernel, but K,n are small
    for in-loop scorers (e.g. models/awacs.py NN: K<=33), so the VPU
    multiply-add cost equals the matmul FLOPs."""
    lhs, rhs = ins
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    out_aval = eqn.outvars[0].aval
    pref = eqn.params.get("preferred_element_type") or out_aval.dtype
    lhs_shape = tuple(lhs.x.shape[:-1])  # per-lane (trailing dim = lane)
    rhs_shape = tuple(rhs.x.shape[:-1])
    if (
        rhs.batched
        or lb
        or rb
        or len(lhs_shape) != 2
        or len(rhs_shape) != 2
        or tuple(lc) != (1,)
        or tuple(rc) != (0,)
    ):
        raise NotImplementedError(
            "lanelast: dot_general rule covers per-lane [m,K] @ unbatched "
            f"[K,n] only (dims {eqn.params['dimension_numbers']}, "
            f"lhs {lhs_shape} batched={lhs.batched}, "
            f"rhs {rhs_shape} batched={rhs.batched})"
        )
    m, K = lhs_shape
    n = rhs_shape[1]
    lane = lhs.x.shape[-1]
    acc = jnp.zeros((m, n, lane), pref)
    for k in range(K):
        lk = lax.slice(lhs.x, (0, k, 0), (m, k + 1, lane))  # [m,1,lane]
        rk = lax.slice(rhs.x, (k, 0, 0), (k + 1, n, 1))  # [1,n,1]
        acc = acc + jnp.broadcast_to(lk.astype(pref), (m, n, lane)) * (
            jnp.broadcast_to(rk.astype(pref), (m, n, lane))
        )
    if acc.dtype != out_aval.dtype:
        acc = acc.astype(out_aval.dtype)
    return _Val(acc, lhs.batched)


def _promote(val, aval, L):
    """Unbatched -> batched (per-lane shape ``aval.shape``)."""
    if val.batched:
        return val.x
    return _align(val, tuple(aval.shape), L)


def _bind_while(eqn, ins, L):
    cond_j = eqn.params["cond_jaxpr"]
    body_j = eqn.params["body_jaxpr"]
    cn = eqn.params["cond_nconsts"]
    bn = eqn.params["body_nconsts"]
    cond_consts = ins[:cn]
    body_consts = ins[cn : cn + bn]
    carry = list(ins[cn + bn :])
    carry_avals = [v.aval for v in body_j.jaxpr.invars[bn:]]

    def _sub(flags):
        return [
            _Val(jax.ShapeDtypeStruct(
                tuple(a.shape) + ((L,) if f else ()), a.dtype
            ), f)
            for a, f in zip(carry_avals, flags)
        ]

    # batchedness fixpoint over the carry: a body pass may batch a carry
    # leaf that started unbatched; promote and re-trace until stable
    flags = [c.batched for c in carry]
    for _ in range(len(flags) + 1):
        def _flags_of(vals):
            return [v.batched for v in vals]

        out_flags = _flags_of(
            _abstract_eval(body_j, body_consts, L, _sub(flags))
        )
        new_flags = [a or b for a, b in zip(flags, out_flags)]
        if new_flags == flags:
            break
        flags = new_flags
    else:
        raise RuntimeError("lanelast: while batchedness did not converge")

    # Does the condition vary per lane?  A counter-only loop (dyn.kfori)
    # keeps an unbatched scalar cond and lowers as-is.  A DATA-DEPENDENT
    # loop (per-lane cond, e.g. the dispatcher's chain loop) lowers as
    # any-lane-live with per-lane freeze masking — the same shape as the
    # chunk driver's proven-on-Mosaic outer loop (pallas_run
    # batched_chunk): scalar `reduce_or` condition, masked carries.  Each
    # lane stops updating the moment its own cond goes false (cond is a
    # pure function of the carry, so a frozen lane's cond stays false),
    # which makes the batched loop exit after max-over-lanes iterations
    # instead of a static worst-case trip count.
    cond_batched = _abstract_eval(
        cond_j, cond_consts, L, _sub(flags)
    )[0].batched
    if cond_batched:
        # per-lane divergence freezes lanes independently, so every
        # carry leaf must be able to hold per-lane values
        flags = [True] * len(flags)

    def _eval_cond(c):
        vals = [_Val(x, f) for x, f in zip(c, flags)]
        (out,) = eval_lanelast(
            cond_j.jaxpr, cond_j.consts, L,
            list(cond_consts) + vals,
        )
        return out

    def cond_fn(c):
        out = _eval_cond(c)
        r = out.x
        if cond_batched:
            if not out.batched or jnp.ndim(r) != 1:
                raise RuntimeError(
                    "lanelast: batched while condition must be a "
                    f"per-lane scalar (got shape {jnp.shape(r)})"
                )
            return jnp.any(r)
        if out.batched or jnp.ndim(r):
            raise RuntimeError(
                "lanelast: while condition must be unbatched scalar "
                "(kernel-mode loops key on an unbatched counter)"
            )
        return r

    def body_fn(c):
        vals = [_Val(x, f) for x, f in zip(c, flags)]
        outs = eval_lanelast(
            body_j.jaxpr, body_j.consts, L,
            list(body_consts) + vals,
        )
        new = tuple(
            _promote(o, a, L) if f else o.x
            for o, a, f in zip(outs, carry_avals, flags)
        )
        if not cond_batched:
            return new
        live = _eval_cond(c).x  # [L]; broadcasts over leading dims
        return tuple(
            x if x is y else jnp.where(live, x, y)
            for x, y in zip(new, c)
        )

    init = tuple(
        _promote(c, a, L) if f else c.x
        for c, a, f in zip(carry, carry_avals, flags)
    )
    outs = lax.while_loop(cond_fn, body_fn, init)
    return [_Val(x, f) for x, f in zip(outs, flags)]


def _abstract_eval(closed, consts_vals, L, in_vals):
    """Shape-level pass to learn output batchedness without building ops:
    evaluate with ShapeDtypeStructs via jax.eval_shape."""
    out_box = []
    all_vals = list(consts_vals) + list(in_vals)

    def run(*xs):
        ins = [_Val(x, v.batched) for x, v in zip(xs, all_vals)]
        outs = eval_lanelast(closed.jaxpr, closed.consts, L, ins)
        out_box.append([o.batched for o in outs])
        return [o.x for o in outs]

    jax.eval_shape(run, *[v.x for v in all_vals])
    return [_Val(None, b) for b in out_box[-1]]
