"""bool32: a jaxpr transform that eliminates i1 (bool) vector values.

Why: the Mosaic TPU compiler's layout pass check-fails (`layout.h:320
Check failed: arr.size() >= layout_rank(implicit_dim)`) on elementwise
logic chains over i1 vectors whose operand layouts disagree — e.g. a mask
loaded from VMEM meeting a comparison-born mask, or an `or` of two `and`
results (measured in round 2 via tools/mosaic_eqn_bisect.py).  Comparisons
feeding selects are the one i1 pattern Mosaic handles everywhere.

What: re-interpret a jaxpr with every bool value carried as int32 (0/1):

* comparisons (`eq/ne/lt/...`, `is_finite`) bind natively, then widen the
  i1 result to i32 immediately — the i1 lives exactly one edge;
* `and/or/xor/not` on bools become bitwise ops on the i32 carriers;
* `select_n` with a bool pred re-derives the pred as ``carrier != 0``
  (comparison-born, full shape) and selects over carriers;
* `broadcast_in_dim/reshape/transpose/...`-style structural ops act on the
  i32 carrier, so no i1 broadcasts exist at all;
* `reduce_or/reduce_and` become max/min reductions over carriers;
* `convert_element_type` to/from bool routes through carriers;
* control-flow prims (`while/cond/scan/pjit`) recurse into their
  sub-jaxprs with the same convention — except `while`'s cond output and
  `cond`'s scalar predicate index, which jax requires as real bool/i32
  scalars (scalars live in SREGs, not vector mask registers: safe);
* everything else binds unchanged (a bool-typed operand to an unknown
  primitive falls back to materializing the i1 with ``!= 0``).

The function boundary also changes: bool inputs/outputs of the
transformed jaxpr become i32.  Callers own the cast (cheap, outside the
kernel).

Used by core/pallas_run.py to make the mega-kernel chunk Mosaic-clean; it
is generic over any jaxpr built from the primitives the engine uses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax._src import core as jcore

_I32 = jnp.int32

_LOGIC = {"and": lax.bitwise_and, "or": lax.bitwise_or, "xor": lax.bitwise_xor}
_COMPARISONS = {"eq", "ne", "lt", "le", "gt", "ge", "is_finite"}
_STRUCTURAL = {
    "broadcast_in_dim", "reshape", "transpose", "slice", "squeeze",
    "concatenate", "rev", "expand_dims",
}


def _is_bool(aval):
    return getattr(aval, "dtype", None) == jnp.bool_


def _widen(pred, dtype=_I32):
    """i1 -> 0/1 of ``dtype`` WITHOUT convert_element_type: a plain
    i1->i32 convert on a rank-1 vector is itself a layout-pass crash
    (measured, culprit #2 of the bisect); a select over constant operands
    is the pattern Mosaic lowers everywhere."""
    return lax.select_n(
        pred,
        jnp.zeros(jnp.shape(pred), dtype),
        jnp.ones(jnp.shape(pred), dtype),
    )


def _carrier_aval(aval):
    if _is_bool(aval):
        return jcore.ShapedArray(aval.shape, _I32, weak_type=False)
    return aval


def _to_carrier(x):
    """Concrete bool const -> i32 carrier, converted HOST-SIDE (numpy) so
    no bool->i32 convert eqn is traced into the kernel."""
    import numpy as np

    return jnp.asarray(np.asarray(x, np.int32))


def _read(env, v):
    if isinstance(v, jcore.Literal):
        val = v.val
        if _is_bool(v.aval):
            return _to_carrier(val)
        return val
    return env[v]


def _sub_jaxpr_fn(closed):
    """Python callable evaluating a ClosedJaxpr under the bool32
    convention; its signature takes/returns carriers."""

    def fn(*args):
        return eval_bool32(closed.jaxpr, closed.consts, *args)

    return fn


def eval_bool32(jaxpr, consts, *args):
    """Evaluate ``jaxpr`` with bool values carried as i32.

    ``args`` must already be carriers (i32 where the jaxpr's invars are
    bool).  Consts with bool dtype are converted on read.  Returns carrier
    outputs (i32 where outvars are bool).
    """
    env = {}
    for v, c in zip(jaxpr.constvars, consts):
        env[v] = _to_carrier(c) if _is_bool(v.aval) else c
    for v, a in zip(jaxpr.invars, args):
        env[v] = a

    def write(eqn, outs):
        for v, o in zip(eqn.outvars, outs):
            if type(v).__name__ != "DropVar":
                env[v] = o

    for eqn in jaxpr.eqns:
        prim = str(eqn.primitive)
        ins = [_read(env, v) for v in eqn.invars]
        in_bool = [_is_bool(v.aval) for v in eqn.invars]
        out_bool = [_is_bool(v.aval) for v in eqn.outvars]

        if prim in _LOGIC and any(in_bool):
            write(eqn, [_LOGIC[prim](*ins)])
        elif prim == "not" and in_bool[0]:
            write(eqn, [lax.bitwise_xor(ins[0], jnp.int32(1))])
        elif prim in _COMPARISONS:
            outs = eqn.primitive.bind(*ins, **eqn.params)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            write(eqn, [_widen(o) for o in outs])
        elif prim == "select_n" and in_bool[0]:
            pred = ins[0] != 0
            cases = ins[1:]
            write(eqn, [lax.select_n(pred, *cases)])
        elif prim == "convert_element_type":
            new_dtype = eqn.params["new_dtype"]
            if in_bool[0] and new_dtype == jnp.bool_:
                write(eqn, [ins[0]])  # carrier stays a carrier
            elif in_bool[0]:
                # the carrier is exactly 0/1 — a plain numeric convert
                write(eqn, [ins[0].astype(new_dtype)])
            elif new_dtype == jnp.bool_:
                write(eqn, [_widen(ins[0] != 0)])
            else:
                write(eqn, [eqn.primitive.bind(*ins, **eqn.params)])
        elif prim in ("reduce_or", "reduce_and") and in_bool[0]:
            red = lax.reduce_max if prim == "reduce_or" else lax.reduce_min
            write(eqn, [red(ins[0], axes=eqn.params["axes"])])
        elif prim == "while":
            write(eqn, _bind_while(eqn, ins))
        elif prim == "cond":
            write(eqn, _bind_cond(eqn, ins))
        elif prim == "scan":
            write(eqn, _bind_scan(eqn, ins))
        elif prim in ("pjit", "jit"):
            # inline the body (in-kernel there is nothing for pjit to do)
            closed = eqn.params["jaxpr"]
            write(eqn, eval_bool32(closed.jaxpr, closed.consts, *ins))
        elif prim in _STRUCTURAL and in_bool[0]:
            # structural ops act on the i32 carrier directly — binding on
            # a materialized i1 would re-emit the i1 broadcasts this
            # transform exists to eliminate
            outs = eqn.primitive.bind(*ins, **eqn.params)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            write(eqn, list(outs))
        elif any(in_bool) or any(out_bool):
            # unknown primitive touching bools: materialize, bind, widen
            mats = [
                (x != 0) if b else x for x, b in zip(ins, in_bool)
            ]
            outs = eqn.primitive.bind(*mats, **eqn.params)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            write(
                eqn,
                [
                    _widen(o) if b else o
                    for o, b in zip(outs, out_bool)
                ],
            )
        else:
            outs = eqn.primitive.bind(*ins, **eqn.params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
            write(eqn, list(outs))

    return [_read(env, v) for v in jaxpr.outvars]


def _bind_while(eqn, ins):
    cond_j = eqn.params["cond_jaxpr"]
    body_j = eqn.params["body_jaxpr"]
    cn = eqn.params["cond_nconsts"]
    bn = eqn.params["body_nconsts"]
    cond_consts = ins[:cn]
    body_consts = ins[cn : cn + bn]
    carry = ins[cn + bn :]

    def cond_fn(c):
        (out,) = eval_bool32(
            cond_j.jaxpr, cond_j.consts, *cond_consts, *c
        )
        # while_loop requires a scalar bool condition
        return out != 0 if out.dtype != jnp.bool_ else out

    def body_fn(c):
        return tuple(
            eval_bool32(body_j.jaxpr, body_j.consts, *body_consts, *c)
        )

    return list(lax.while_loop(cond_fn, body_fn, tuple(carry)))


def _bind_cond(eqn, ins):
    branches = eqn.params["branches"]
    idx = ins[0]
    if idx.dtype == jnp.bool_:  # shouldn't happen: carriers are i32
        idx = idx.astype(_I32)
    ops = ins[1:]
    fns = [_sub_jaxpr_fn(b) for b in branches]
    return list(lax.switch(idx, fns, *ops))


def _bind_scan(eqn, ins):
    p = eqn.params
    j = p["jaxpr"]
    nc, ncarry = p["num_consts"], p["num_carry"]
    consts = ins[:nc]
    init = ins[nc : nc + ncarry]
    xs = ins[nc + ncarry :]

    def body(carry, x):
        outs = eval_bool32(j.jaxpr, j.consts, *consts, *carry, *x)
        return tuple(outs[:ncarry]), tuple(outs[ncarry:])

    carry, ys = lax.scan(
        body, tuple(init), tuple(xs), length=p["length"],
        reverse=p["reverse"], unroll=p.get("unroll", 1),
    )
    return list(carry) + list(ys)


def transform(closed_jaxpr, example_carriers):
    """ClosedJaxpr -> ClosedJaxpr with the bool32 convention applied.

    ``example_carriers``: carrier-typed abstract values (or arrays) for the
    jaxpr's invars — bool invars as i32.
    """

    def fn(*args):
        return eval_bool32(
            closed_jaxpr.jaxpr, closed_jaxpr.consts, *args
        )

    return jax.make_jaxpr(fn)(*example_carriers)
