"""bool32: a jaxpr transform that eliminates i1 (bool) vector values.

Why: the Mosaic TPU compiler's layout pass check-fails (`layout.h:320
Check failed: arr.size() >= layout_rank(implicit_dim)`) on elementwise
logic chains over i1 vectors whose operand layouts disagree — e.g. a mask
loaded from VMEM meeting a comparison-born mask, or an `or` of two `and`
results (measured in round 2 via tools/mosaic_eqn_bisect.py).  Comparisons
feeding selects are the one i1 pattern Mosaic handles everywhere.

What: re-interpret a jaxpr with every bool value carried as int32 (0/1):

* comparisons (`eq/ne/lt/...`) bind natively and stay i1 until a
  consumer needs the carrier (lazy pair, see eval_bool32 — select preds
  consume the i1 directly, saving a widen+re-compare round trip per
  comparison); `is_finite` is rewritten to `x - x == 0` (Mosaic has no
  is_finite lowering);
* `and/or/xor/not` on bools become bitwise ops on the i32 carriers;
* `select_n` with a bool pred re-derives the pred as ``carrier != 0``
  (comparison-born, full shape) and selects over carriers;
* `broadcast_in_dim/reshape/transpose/...`-style structural ops act on the
  i32 carrier, so no i1 broadcasts exist at all;
* `reduce_or/reduce_and` become max/min reductions over carriers;
* `convert_element_type` to/from bool routes through carriers;
* control-flow prims (`while/cond/scan/pjit`) recurse into their
  sub-jaxprs with the same convention — except `while`'s cond output and
  `cond`'s scalar predicate index, which jax requires as real bool/i32
  scalars (scalars live in SREGs, not vector mask registers: safe);
* everything else binds unchanged (a bool-typed operand to an unknown
  primitive falls back to materializing the i1 with ``!= 0``).

The function boundary also changes: bool inputs/outputs of the
transformed jaxpr become i32.  Callers own the cast (cheap, outside the
kernel).

Used by core/pallas_run.py to make the mega-kernel chunk Mosaic-clean; it
is generic over any jaxpr built from the primitives the engine uses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax._src import core as jcore

_I32 = jnp.int32

_LOGIC = {"and": lax.bitwise_and, "or": lax.bitwise_or, "xor": lax.bitwise_xor}
_COMPARISONS = {"eq", "ne", "lt", "le", "gt", "ge"}
_STRUCTURAL = {
    "broadcast_in_dim", "reshape", "transpose", "slice", "squeeze",
    "concatenate", "rev", "expand_dims",
    # block slice/write-back of the scan-over-rows table dispatch
    # (core/dyn.py): starts are non-bool scalars and pass through; the
    # bool operand/update ride as i32 carriers like any other reshape
    "dynamic_slice", "dynamic_update_slice",
}


def _is_bool(aval):
    return getattr(aval, "dtype", None) == jnp.bool_


def _widen(pred, dtype=_I32):
    """i1 -> 0/1 of ``dtype`` WITHOUT convert_element_type: a plain
    i1->i32 convert on a rank-1 vector is itself a layout-pass crash
    (measured, culprit #2 of the bisect); a select over constant operands
    is the pattern Mosaic lowers everywhere."""
    return lax.select_n(
        pred,
        jnp.zeros(jnp.shape(pred), dtype),
        jnp.ones(jnp.shape(pred), dtype),
    )


def _carrier_aval(aval):
    if _is_bool(aval):
        return jcore.ShapedArray(aval.shape, _I32, weak_type=False)
    return aval


def _to_carrier(x):
    """Concrete bool const -> i32 carrier, converted HOST-SIDE (numpy) so
    no bool->i32 convert eqn is traced into the kernel."""
    import numpy as np

    return jnp.asarray(np.asarray(x, np.int32))


def _canon_literal(val):
    """64-bit scalar literals survive from an x64-on source trace; when
    x64 is off at re-bind time, pass their 32-bit counterparts instead
    (Mosaic's ir_constant switches on the literal VALUE's dtype, and it
    has no 64-bit constants).  Out-of-range values would be a real
    program difference, so they raise rather than wrap."""
    import numpy as np

    if jax.config.jax_enable_x64:
        return val
    a = np.asarray(val)
    tgt = {"int64": np.int32, "uint64": np.uint32,
           "float64": np.float32}.get(a.dtype.name)
    if tgt is None:
        return val
    out = a.astype(tgt)
    if a.dtype.kind in "iu" and out != a:
        raise OverflowError(
            f"64-bit literal {a} does not fit {np.dtype(tgt).name}")
    return out


def _read(env, v):
    if isinstance(v, jcore.Literal):
        val = v.val
        if _is_bool(v.aval):
            return _to_carrier(val)
        return _canon_literal(val)
    return env[v]


def _sub_jaxpr_fn(closed):
    """Python callable evaluating a ClosedJaxpr under the bool32
    convention; its signature takes/returns carriers."""

    def fn(*args):
        return eval_bool32(closed.jaxpr, closed.consts, *args)

    return fn


def eval_bool32(jaxpr, consts, *args):
    """Evaluate ``jaxpr`` with bool values carried as i32.

    ``args`` must already be carriers (i32 where the jaxpr's invars are
    bool).  Consts with bool dtype are converted on read.  Returns carrier
    outputs (i32 where outvars are bool).

    Internally an ex-bool value is a lazy PAIR (i1, carrier): comparisons
    store only the i1 (select preds use it directly — the one i1 pattern
    Mosaic handles), and the carrier is materialized at most once, on
    first use by a logic/structural/memory consumer.  This avoids the
    widen+re-compare round trip per comparison (~28% of all kernel eqns
    before this)."""

    class _B:
        __slots__ = ("i1", "c32")

        def __init__(self, i1=None, c32=None):
            self.i1 = i1
            self.c32 = c32

        def carrier(self):
            if self.c32 is None:
                self.c32 = _widen(self.i1)
            return self.c32

        def pred(self):
            if self.i1 is None:
                self.i1 = self.c32 != 0
            return self.i1

    def boxed(x):
        return x if isinstance(x, _B) else _B(c32=x)

    env = {}
    for v, c in zip(jaxpr.constvars, consts):
        env[v] = _B(c32=_to_carrier(c)) if _is_bool(v.aval) else c
    for v, a in zip(jaxpr.invars, args):
        env[v] = _B(c32=a) if _is_bool(v.aval) else a

    def read(v):
        x = _read(env, v)
        if _is_bool(v.aval):
            return boxed(x)
        return x

    def write(eqn, outs):
        for v, o in zip(eqn.outvars, outs):
            if type(v).__name__ != "DropVar":
                env[v] = o

    def carriers(eqn, ins):
        return [
            i.carrier() if isinstance(i, _B) else i for i in ins
        ]

    for eqn in jaxpr.eqns:
        prim = str(eqn.primitive)
        ins = [read(v) for v in eqn.invars]
        in_bool = [_is_bool(v.aval) for v in eqn.invars]
        out_bool = [_is_bool(v.aval) for v in eqn.outvars]

        if prim in _LOGIC and any(in_bool):
            a, b = carriers(eqn, ins)
            write(eqn, [_B(c32=_LOGIC[prim](a, b))])
        elif prim == "not" and in_bool[0]:
            write(
                eqn,
                [_B(c32=lax.bitwise_xor(ins[0].carrier(), jnp.int32(1)))],
            )
        elif prim in _COMPARISONS:
            outs = eqn.primitive.bind(*carriers(eqn, ins), **eqn.params)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            write(eqn, [_B(i1=o) for o in outs])
        elif prim == "is_finite":
            # Mosaic has no is_finite lowering (AWACS's eventset
            # liveness hits it); x - x == 0 is the same i1 — NaN and
            # +-Inf both subtract to NaN — built from prims it lowers
            (x,) = carriers(eqn, ins)
            d = lax.sub(x, x)
            write(eqn, [_B(i1=lax.eq(d, jnp.zeros_like(d)))])
        elif prim == "select_n" and in_bool[0]:
            pred = ins[0].pred()
            cases = carriers(eqn, ins[1:])
            out = lax.select_n(pred, *cases)
            write(eqn, [_B(c32=out) if out_bool[0] else out])
        elif prim == "convert_element_type":
            new_dtype = eqn.params["new_dtype"]
            if in_bool[0] and new_dtype == jnp.bool_:
                write(eqn, [ins[0]])  # stays lazy
            elif in_bool[0]:
                # the carrier is exactly 0/1 — a plain numeric convert
                write(eqn, [ins[0].carrier().astype(new_dtype)])
            elif new_dtype == jnp.bool_:
                write(eqn, [_B(i1=ins[0] != 0)])
            else:
                write(eqn, [eqn.primitive.bind(*ins, **eqn.params)])
        elif prim in ("reduce_or", "reduce_and") and in_bool[0]:
            # bind the reduction primitive directly: older jax has no
            # lax.reduce_max/reduce_min function wrappers.  Reduce in
            # f32: Mosaic has no integer-reduction lowering (the
            # eventset liveness any() hits it) and the carrier is
            # exactly 0/1, so the float round-trip is lossless
            red_p = (
                lax.reduce_max_p if prim == "reduce_or" else lax.reduce_min_p
            )
            red = red_p.bind(
                ins[0].carrier().astype(jnp.float32),
                axes=eqn.params["axes"],
            )
            write(eqn, [_B(i1=lax.ne(red, jnp.zeros_like(red)))])
        elif prim == "while":
            write(eqn, _bind_while(eqn, carriers(eqn, ins), out_bool))
        elif prim == "cond":
            write(eqn, _bind_cond(eqn, carriers(eqn, ins), out_bool))
        elif prim == "scan":
            write(eqn, _bind_scan(eqn, carriers(eqn, ins), out_bool))
        elif prim in ("pjit", "jit"):
            # inline the body (in-kernel there is nothing for pjit to do)
            closed = eqn.params["jaxpr"]
            outs = eval_bool32(
                closed.jaxpr, closed.consts, *carriers(eqn, ins)
            )
            write(
                eqn,
                [_B(c32=o) if b else o for o, b in zip(outs, out_bool)],
            )
        elif prim == "device_put":
            # staged by jnp.asarray/jnp.array around constants; device
            # placement is meaningless inside the kernel (Mosaic has no
            # lowering for it) — the value passes through unchanged
            write(eqn, list(ins))
        elif prim in _STRUCTURAL and in_bool[0]:
            # structural ops act on the i32 carrier directly — binding on
            # a materialized i1 would re-emit the i1 broadcasts this
            # transform exists to eliminate
            outs = eqn.primitive.bind(*carriers(eqn, ins), **eqn.params)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            write(
                eqn,
                [_B(c32=o) if b else o for o, b in zip(outs, out_bool)],
            )
        elif any(in_bool) or any(out_bool):
            # unknown primitive touching bools: scalar bools are safe
            # (SREGs, not vector mask registers) — materialize and bind.
            # NON-scalar bools here would silently reintroduce the i1
            # vectors this transform exists to eliminate, surfacing hours
            # later as a Mosaic layout-pass SIGABRT far from the cause:
            # fail fast with the primitive and shapes instead.
            nonscalar = [
                f"{('in' if k < len(eqn.invars) else 'out')}:{v.aval}"
                for k, (v, b) in enumerate(
                    list(zip(eqn.invars, in_bool))
                    + list(zip(eqn.outvars, out_bool))
                )
                if b and tuple(v.aval.shape)
            ]
            if nonscalar:
                raise NotImplementedError(
                    f"bool32: no rule for primitive '{prim}' touching "
                    f"non-scalar bool values ({', '.join(nonscalar)}); "
                    "binding it raw would materialize i1 vectors that "
                    "crash the Mosaic layout pass — add a rule here"
                )
            mats = [
                i.pred() if isinstance(i, _B) else i for i in ins
            ]
            outs = eqn.primitive.bind(*mats, **eqn.params)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            write(
                eqn,
                [
                    _B(i1=o) if b else o
                    for o, b in zip(outs, out_bool)
                ],
            )
        else:
            outs = eqn.primitive.bind(*ins, **eqn.params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
            write(eqn, list(outs))

    return [
        (boxed(_read(env, v)).carrier() if _is_bool(v.aval)
         else _read(env, v))
        for v in jaxpr.outvars
    ]


def _bind_while(eqn, ins, out_bool=None):
    cond_j = eqn.params["cond_jaxpr"]
    body_j = eqn.params["body_jaxpr"]
    cn = eqn.params["cond_nconsts"]
    bn = eqn.params["body_nconsts"]
    cond_consts = ins[:cn]
    body_consts = ins[cn : cn + bn]
    carry = ins[cn + bn :]

    def cond_fn(c):
        (out,) = eval_bool32(
            cond_j.jaxpr, cond_j.consts, *cond_consts, *c
        )
        # while_loop requires a scalar bool condition
        return out != 0 if out.dtype != jnp.bool_ else out

    def body_fn(c):
        return tuple(
            eval_bool32(body_j.jaxpr, body_j.consts, *body_consts, *c)
        )

    return list(lax.while_loop(cond_fn, body_fn, tuple(carry)))


def _bind_cond(eqn, ins, out_bool=None):
    branches = eqn.params["branches"]
    idx = ins[0]
    if idx.dtype == jnp.bool_:  # shouldn't happen: carriers are i32
        idx = idx.astype(_I32)
    ops = ins[1:]
    fns = [_sub_jaxpr_fn(b) for b in branches]
    return list(lax.switch(idx, fns, *ops))


def _bind_scan(eqn, ins, out_bool=None):
    p = eqn.params
    j = p["jaxpr"]
    nc, ncarry = p["num_consts"], p["num_carry"]
    consts = ins[:nc]
    init = ins[nc : nc + ncarry]
    xs = ins[nc + ncarry :]

    def body(carry, x):
        outs = eval_bool32(j.jaxpr, j.consts, *consts, *carry, *x)
        return tuple(outs[:ncarry]), tuple(outs[ncarry:])

    carry, ys = lax.scan(
        body, tuple(init), tuple(xs), length=p["length"],
        reverse=p["reverse"], unroll=p.get("unroll", 1),
    )
    return list(carry) + list(ys)


def transform(closed_jaxpr, example_carriers):
    """ClosedJaxpr -> ClosedJaxpr with the bool32 convention applied.

    ``example_carriers``: carrier-typed abstract values (or arrays) for the
    jaxpr's invars — bool invars as i32.
    """

    def fn(*args):
        return eval_bool32(
            closed_jaxpr.jaxpr, closed_jaxpr.consts, *args
        )

    return jax.make_jaxpr(fn)(*example_carriers)
