"""Model definition: the static structure a simulation is built from.

The reference builds models imperatively at trial start (create/initialize
processes, queues, resources — e.g. `benchmark/MM1_multi.c:91-124`).  Under
jit the structure must be static: a :class:`Model` collects process types,
blocks, queues and resources at Python time; :meth:`Model.build` freezes it
into a :class:`ModelSpec` the dispatcher closes over.  Only *state* (clock,
event slots, queue contents, locals, RNG counters) lives in the traced
pytree — one replication's state is created by ``core.loop.init_sim`` and
batched with vmap.

Block registration::

    m = Model("mm1", n_ilocals=1)
    q = m.objectqueue("buffer", capacity=1024)

    @m.block
    def a_hold(sim, p, sig):
        sim, t = api.draw(sim, random.exponential, 1.11)
        return sim, cmd.hold(t, next_pc=a_put.pc)

    @m.block
    def a_put(sim, p, sig):
        return sim, cmd.put(q.id, api.clock(sim), next_pc=a_hold.pc)

    m.process("arrival", entry=a_hold)

Forward references work because ``next_pc`` is read at trace time, after
the module is fully defined.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional

import numpy as np


@dataclasses.dataclass
class QueueRef:
    id: int
    name: str
    capacity: int
    front_guard: int  # getters wait here
    rear_guard: int   # putters wait here
    record: bool = True  # queue-length StepAccum recording


@dataclasses.dataclass
class ResourceRef:
    id: int
    name: str
    guard: int
    record: bool = True


@dataclasses.dataclass
class PoolRef:
    id: int
    name: str
    capacity: float
    guard: int
    record: bool = True


@dataclasses.dataclass
class BufferRef:
    id: int
    name: str
    capacity: float
    initial: float
    front_guard: int  # getters wait here
    rear_guard: int   # putters wait here
    record: bool = True


@dataclasses.dataclass
class PQueueRef:
    id: int
    name: str
    capacity: int
    front_guard: int
    rear_guard: int
    record: bool = True


@dataclasses.dataclass
class ConditionRef:
    id: int
    name: str
    guard: int
    predicate: Callable  # predicate(sim, pid) -> bool array
    #: guard ids this condition OBSERVES (parity: cmb_resourceguard_register,
    #: `src/cmb_resourceguard.c:313-330`): any signal on an observed guard —
    #: a release, put, rollback, drop-on-exit — forwards into cond_signal,
    #: so waiters re-evaluate without the model signalling at every site
    observes: tuple = ()


@dataclasses.dataclass
class ProcessType:
    name: str
    entry_pc: int
    prio: int
    count: int
    #: False = the rows exist but stay CREATED until api.spawn activates
    #: them (parity: runtime cmb_process_create/start — under jit the
    #: process POOL is declared, activation is dynamic)
    start: bool = True
    first_pid: int = -1  # assigned at build


@dataclasses.dataclass
class ModelSpec:
    """Frozen model structure (everything static the stepper needs)."""

    name: str
    blocks: List[Callable]
    proc_entry: np.ndarray     # [P] i32
    proc_prio: np.ndarray      # [P] i32
    #: [P] bool — False rows are spawn-pool members: they stay CREATED
    #: at init until api.spawn activates them
    proc_start: np.ndarray
    proc_names: List[str]
    queues: List[QueueRef]
    resources: List[ResourceRef]
    pools: List[PoolRef]
    buffers: List[BufferRef]
    pqueues: List[PQueueRef]
    conditions: List[ConditionRef]
    n_guards: int
    guard_cap: int
    event_cap: int
    queue_cap_max: int
    pqueue_cap_max: int
    n_flocals: int
    n_ilocals: int
    #: bound on non-yielding command chains for the Pallas-kernel stepper's
    #: masked fori (the XLA path uses a dynamic while with the large
    #: MAX_CHAIN runaway bound); raise it for models that chain many
    #: non-blocking commands between yields
    max_chain: int
    user_init: Optional[Callable[..., Any]]
    user_handlers: List[Callable]
    #: pcs of blocks dispatched OUTSIDE the Pallas kernel at chunk
    #: boundaries (see Model.boundary_block); empty for most models
    boundary_pcs: tuple = ()

    @property
    def n_procs(self) -> int:
        return len(self.proc_entry)


class Model:
    """Mutable model builder (Python-time only)."""

    def __init__(
        self,
        name: str,
        *,
        n_flocals: int = 0,
        n_ilocals: int = 0,
        event_cap: int = 16,
        guard_cap: int = 8,  # accepted for compat; dense guards cannot
        # overflow (capacity is n_procs by construction), so this no
        # longer sizes anything
        max_chain: int = 16,
    ):
        self.name = name
        self.n_flocals = n_flocals
        self.n_ilocals = n_ilocals
        self.event_cap = event_cap
        self.guard_cap = guard_cap
        self.max_chain = max_chain
        self._blocks: List[Callable] = []
        self._types: List[ProcessType] = []
        self._queues: List[QueueRef] = []
        self._resources: List[ResourceRef] = []
        self._pools: List[PoolRef] = []
        self._buffers: List[BufferRef] = []
        self._pqueues: List[PQueueRef] = []
        self._conditions: List[ConditionRef] = []
        self._n_guards = 0
        self._user_init: Optional[Callable] = None
        self._user_handlers: List[Callable] = []
        self._boundary_pcs: List[int] = []

    # --- structure -----------------------------------------------------

    def block(self, fn: Callable) -> Callable:
        """Register a block; sets ``fn.pc`` to its global index."""
        fn.pc = len(self._blocks)
        self._blocks.append(fn)
        return fn

    def boundary_block(self, fn: Callable) -> Callable:
        """Register a block whose dispatch runs OUTSIDE the Pallas kernel,
        at a chunk boundary, as plain XLA (the physics-hook analog of the
        reference launching CUDA from a coroutine, `tutorial/tut_5_3.c`).

        Use for bulk work over whole component arrays — batched matmuls,
        big reductions — that would otherwise execute masked on EVERY
        kernel event: the kernel freezes a lane whose next dispatch
        targets this block, and the chunk driver applies one ordinary
        XLA engine step (MXU and all) to the frozen lanes between
        chunks.  Semantics are identical to a normal block — same event
        order, same statistics — and the XLA path ignores the marker.

        Constraint: a boundary block must be entered by RESUMES (process
        entry, hold/wake continuations), not mid-chain via cmd.jump or a
        completed command's next_pc — the kernel flags such an entry as
        a failed replication (ERR_BOUNDARY)."""
        fn = self.block(fn)
        self._boundary_pcs.append(fn.pc)
        return fn

    def process(self, name: str, entry, *, prio: int = 0, count: int = 1,
                start: bool = True):
        """Declare ``count`` instances of a process type starting at block
        ``entry`` (a function registered with :meth:`block`).

        ``start=False`` declares a SPAWN POOL: the rows exist but stay
        CREATED until a block activates one with ``api.spawn(sim, pt)``
        — the jit answer to the reference's runtime process creation
        (`cmb_process_create`/`cmb_process_start`); finished rows are
        recycled by later spawns."""
        pt = ProcessType(name, entry.pc, prio, count, start)
        self._types.append(pt)
        return pt

    def _guard(self) -> int:
        g = self._n_guards
        self._n_guards += 1
        return g

    def objectqueue(
        self, name: str, capacity: int, record: bool = True
    ) -> QueueRef:
        """FIFO of f64 payloads (parity: cmb_objectqueue; the reference's
        void* objects become a float payload — typically a timestamp or an
        index into user state).  ``record=False`` disables queue-length
        recording at trace time (parity: the reference's optional
        recording; measurable speedup in hot models)."""
        q = QueueRef(
            id=len(self._queues),
            name=name,
            capacity=capacity,
            front_guard=self._guard(),
            rear_guard=self._guard(),
            record=record,
        )
        self._queues.append(q)
        return q

    def resource(self, name: str, record: bool = True) -> ResourceRef:
        """Single-holder resource (parity: cmb_resource)."""
        r = ResourceRef(
            id=len(self._resources), name=name, guard=self._guard(),
            record=record,
        )
        self._resources.append(r)
        return r

    def resourcepool(
        self, name: str, capacity: float, record: bool = True
    ) -> PoolRef:
        """Counting resource of ``capacity`` fungible units (parity:
        cmb_resourcepool)."""
        p = PoolRef(
            id=len(self._pools), name=name, capacity=float(capacity),
            guard=self._guard(), record=record,
        )
        self._pools.append(p)
        return p

    def buffer(
        self, name: str, capacity: float, initial: float = 0.0,
        record: bool = True,
    ) -> BufferRef:
        """Producer-consumer store of a fungible amount (parity: cmb_buffer)."""
        b = BufferRef(
            id=len(self._buffers), name=name, capacity=float(capacity),
            initial=float(initial), front_guard=self._guard(),
            rear_guard=self._guard(), record=record,
        )
        self._buffers.append(b)
        return b

    def priorityqueue(
        self, name: str, capacity: int, record: bool = True
    ) -> PQueueRef:
        """Object queue ordered by per-item priority, FIFO within equal
        priorities (parity: cmb_priorityqueue)."""
        q = PQueueRef(
            id=len(self._pqueues), name=name, capacity=capacity,
            front_guard=self._guard(), rear_guard=self._guard(),
            record=record,
        )
        self._pqueues.append(q)
        return q

    def condition(
        self, name: str, predicate: Callable, observes=()
    ) -> ConditionRef:
        """Condition variable: processes wait until ``predicate(sim, pid)``
        holds at a signal (parity: cmb_condition; the reference's C
        predicate pointer becomes a traced function registered here).

        ``observes`` — components (resources, pools, buffers, queues,
        priority queues) whose state changes can satisfy the predicate:
        any guard signal they emit (release, put, rollback, drop-on-exit)
        auto-forwards into a signal of this condition, so the model never
        has to call ``api.cond_signal`` at release sites (parity:
        ``cmb_resourceguard_register``, `src/cmb_resourceguard.c:313-330`,
        the mechanism the reference's harbor tutorial rests on,
        `tutorial/tut_4_1.c:499-501`).  Signals driven by non-component
        state (e.g. a tide process updating user state) still need the
        explicit ``api.cond_signal``.
        """
        gids = []
        for comp in observes:
            found = False
            for attr in ("guard", "front_guard", "rear_guard"):
                g = getattr(comp, attr, None)
                if g is not None:
                    gids.append(g)
                    found = True
            if not found:
                raise TypeError(
                    f"condition {name!r}: observes entry {comp!r} has no "
                    "guard — pass component refs (resource/pool/buffer/"
                    "queue/pqueue)"
                )
        c = ConditionRef(
            id=len(self._conditions), name=name, guard=self._guard(),
            predicate=predicate, observes=tuple(gids),
        )
        self._conditions.append(c)
        return c

    def user_state(self, fn: Callable) -> Callable:
        """Register ``fn(params) -> pytree`` building per-replication user
        state (the reference's trial struct, `include/cimba.h:100-118`)."""
        self._user_init = fn
        return fn

    def handler(self, fn: Callable) -> Callable:
        """Register a user event handler ``fn(sim, subj, arg) -> sim``;
        sets ``fn.kind`` for use with api.schedule (parity: arbitrary
        (action, subject, object) events, `include/cmb_event.h:75-180`)."""
        # kinds 0/1 are the framework's K_PROC/K_TIMER (core.loop)
        fn.kind = 2 + len(self._user_handlers)
        self._user_handlers.append(fn)
        return fn

    # --- freeze ----------------------------------------------------------

    def build(self) -> ModelSpec:
        if not self._types:
            raise ValueError("model has no processes")
        entries, prios, names, started = [], [], [], []
        for pt in self._types:
            pt.first_pid = len(entries)
            for k in range(pt.count):
                entries.append(pt.entry_pc)
                prios.append(pt.prio)
                started.append(pt.start)
                names.append(pt.name if pt.count == 1 else f"{pt.name}[{k}]")
        from cimba_tpu.utils import logger as _logger

        _logger.names_set(names)  # log lines render name(pid)
        return ModelSpec(
            name=self.name,
            blocks=list(self._blocks),
            proc_entry=np.asarray(entries, np.int32),
            proc_prio=np.asarray(prios, np.int32),
            proc_start=np.asarray(started, np.bool_),
            proc_names=names,
            queues=list(self._queues),
            resources=list(self._resources),
            pools=list(self._pools),
            buffers=list(self._buffers),
            pqueues=list(self._pqueues),
            conditions=list(self._conditions),
            n_guards=max(self._n_guards, 1),
            guard_cap=self.guard_cap,
            event_cap=self.event_cap,
            queue_cap_max=max([q.capacity for q in self._queues], default=1),
            pqueue_cap_max=max([q.capacity for q in self._pqueues], default=1),
            n_flocals=self.n_flocals,
            n_ilocals=self.n_ilocals,
            max_chain=self.max_chain,
            user_init=self._user_init,
            user_handlers=list(self._user_handlers),
            boundary_pcs=tuple(self._boundary_pcs),
        )