"""Block-author helpers: the model-facing surface inside blocks.

These are the TPU equivalents of the calls a reference process body makes
between yields — ``cmb_time()``, ``cmb_random_*``, reading/writing its own
state — expressed functionally over the :class:`~cimba_tpu.core.loop.Sim`
pytree.  Commands (the yield points) live in :mod:`cimba_tpu.core.process`.
"""

from __future__ import annotations

import jax.numpy as jnp

from cimba_tpu.config import INDEX_DTYPE, REAL_DTYPE
from cimba_tpu.core.loop import ERR_USER, Sim

_I = INDEX_DTYPE
_R = REAL_DTYPE


def clock(sim: Sim):
    """Current simulation time (parity: ``cmb_time``)."""
    return sim.clock


def draw(sim: Sim, dist, *params):
    """Draw from a distribution, threading the replication's RNG stream:
    ``sim, x = api.draw(sim, random.exponential, mean)``."""
    rng, x = dist(sim.rng, *params)
    return sim._replace(rng=rng), x


def got(sim: Sim, p):
    """Result register: the item produced by this process's last GET."""
    return sim.procs.got[p]


def local_f(sim: Sim, p, k: int):
    return sim.procs.locals_f[p, k]


def set_local_f(sim: Sim, p, k: int, v) -> Sim:
    return sim._replace(
        procs=sim.procs._replace(
            locals_f=sim.procs.locals_f.at[p, k].set(jnp.asarray(v, _R))
        )
    )


def local_i(sim: Sim, p, k: int):
    return sim.procs.locals_i[p, k]


def set_local_i(sim: Sim, p, k: int, v) -> Sim:
    return sim._replace(
        procs=sim.procs._replace(
            locals_i=sim.procs.locals_i.at[p, k].set(jnp.asarray(v, _I))
        )
    )


def add_local_i(sim: Sim, p, k: int, dv=1) -> Sim:
    return sim._replace(
        procs=sim.procs._replace(
            locals_i=sim.procs.locals_i.at[p, k].add(jnp.asarray(dv, _I))
        )
    )


def user(sim: Sim):
    return sim.user


def set_user(sim: Sim, new_user) -> Sim:
    return sim._replace(user=new_user)


def stop(sim: Sim, pred=True) -> Sim:
    """End the replication after the current event (the analog of the
    reference's user-scheduled end event)."""
    return sim._replace(done=sim.done | jnp.asarray(pred))


def fail(sim: Sim, pred=True) -> Sim:
    """Mark the replication failed (parity: cmb_logger_error recovery —
    the replication is abandoned and counted, §3.5)."""
    return sim._replace(
        err=jnp.where(
            (sim.err == 0) & jnp.asarray(pred), jnp.asarray(ERR_USER, _I), sim.err
        )
    )


def queue_length(sim: Sim, q):
    """Current number of items in an object queue (parity:
    ``cmb_objectqueue_length``)."""
    return sim.queues.size[q.id if hasattr(q, "id") else q]


def resource_holder(sim: Sim, r):
    """Holding pid of a resource, -1 if free."""
    return sim.resources.holder[r.id if hasattr(r, "id") else r]


def pool_level(sim: Sim, pool):
    """Available units in a resource pool (parity: cmb_resourcepool_level)."""
    return sim.pools.level[pool.id if hasattr(pool, "id") else pool]


def buffer_level(sim: Sim, b):
    """Stored amount in a buffer (parity: cmb_buffer_level)."""
    return sim.buffers.level[b.id if hasattr(b, "id") else b]


def pqueue_length(sim: Sim, q):
    """Items in a priority queue (parity: cmb_priorityqueue_length)."""
    qid = q.id if hasattr(q, "id") else q
    return jnp.sum(sim.pqueues.live[qid].astype(_I))


# --- inter-process verbs (thin wrappers over core.loop; blocks close over
#     their model's built spec, e.g. via a late-bound `spec()` accessor) ----


def interrupt(sim: Sim, spec, target, sig) -> Sim:
    """Deliver ``sig`` to a waiting process now, aborting its wait
    (parity: cmb_process_interrupt)."""
    from cimba_tpu.core import loop as _loop

    return _loop.interrupt(spec, sim, target, jnp.asarray(sig, _I))


def stop_process(sim: Sim, spec, target) -> Sim:
    """Kill a process: drop resources, cancel waits, wake waiters with
    STOPPED (parity: cmb_process_stop)."""
    from cimba_tpu.core import loop as _loop

    return _loop.stop_process(spec, sim, target)


def timer_add(sim: Sim, p, dur, sig):
    """(sim, handle): deliver ``sig`` to p after ``dur`` unless cancelled
    (parity: cmb_process_timer_add)."""
    from cimba_tpu.core import loop as _loop

    return _loop.timer_add(sim, p, dur, jnp.asarray(sig, _I))


def timer_cancel(sim: Sim, handle):
    """(sim, existed) — parity: cmb_process_timer_cancel."""
    from cimba_tpu.core import loop as _loop

    return _loop.timer_cancel(sim, handle)


def timers_clear(sim: Sim, p) -> Sim:
    """Cancel all timers aimed at p (parity: cmb_process_timers_clear)."""
    from cimba_tpu.core import loop as _loop

    return _loop.timers_clear(sim, p)


def priority_set(sim: Sim, p, new_prio) -> Sim:
    """Change process priority, reshuffling event and guard queues
    (parity: cmb_process_priority_set)."""
    from cimba_tpu.core import loop as _loop

    return _loop.priority_set(sim, p, new_prio)


def cond_signal(sim: Sim, spec, condition) -> Sim:
    """Signal a condition variable: wake every waiter whose predicate
    holds (parity: cmb_condition_signal)."""
    from cimba_tpu.core import loop as _loop

    cid = condition.id if hasattr(condition, "id") else condition
    return _loop.cond_signal(spec, sim, cid)


def proc_status(sim: Sim, p):
    """CREATED/RUNNING/FINISHED (parity: cmb_process_status)."""
    return sim.procs.status[p]


def schedule(sim: Sim, t, prio, handler, subj=0, arg=0) -> Sim:
    """Schedule a user event (parity: cmb_event_schedule with an arbitrary
    action); ``handler`` is a function registered with Model.handler."""
    from cimba_tpu.core import loop as _loop

    kind = handler.kind if hasattr(handler, "kind") else handler
    return _loop._schedule_if(
        sim, True, t, prio, kind, subj, arg
    )