"""Block-author helpers: the model-facing surface inside blocks.

These are the TPU equivalents of the calls a reference process body makes
between yields — ``cmb_time()``, ``cmb_random_*``, reading/writing its own
state — expressed functionally over the :class:`~cimba_tpu.core.loop.Sim`
pytree.  Commands (the yield points) live in :mod:`cimba_tpu.core.process`.
"""

from __future__ import annotations

import jax.numpy as jnp

from cimba_tpu import config
from cimba_tpu.core import dyn
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core.loop import ERR_USER, Sim

_I = INDEX_DTYPE
_R = config.REAL


def clock(sim: Sim):
    """Current simulation time (parity: ``cmb_time``)."""
    return sim.clock


def draw(sim: Sim, dist, *params):
    """Draw from a distribution, threading the replication's RNG stream:
    ``sim, x = api.draw(sim, random.exponential, mean)``."""
    rng, x = dist(sim.rng, *params)
    return sim._replace(rng=rng), x


def got(sim: Sim, p):
    """Result register: the item produced by this process's last GET."""
    return dyn.dget(sim.procs.got, p)


def local_f(sim: Sim, p, k: int):
    return dyn.dget(sim.procs.locals_f[:, k], p)


def set_local_f(sim: Sim, p, k: int, v) -> Sim:
    return sim._replace(
        procs=sim.procs._replace(
            locals_f=dyn.set_col(
                sim.procs.locals_f, k,
                dyn.dset(sim.procs.locals_f[:, k], p, jnp.asarray(v, _R)),
            )
        )
    )


def local_i(sim: Sim, p, k: int):
    return dyn.dget(sim.procs.locals_i[:, k], p)


def set_local_i(sim: Sim, p, k: int, v) -> Sim:
    return sim._replace(
        procs=sim.procs._replace(
            locals_i=dyn.set_col(
                sim.procs.locals_i, k,
                dyn.dset(sim.procs.locals_i[:, k], p, jnp.asarray(v, _I)),
            )
        )
    )


def add_local_i(sim: Sim, p, k: int, dv=1) -> Sim:
    return sim._replace(
        procs=sim.procs._replace(
            locals_i=dyn.set_col(
                sim.procs.locals_i, k,
                dyn.dadd(sim.procs.locals_i[:, k], p, jnp.asarray(dv, _I)),
            )
        )
    )


def user(sim: Sim):
    return sim.user


def set_user(sim: Sim, new_user) -> Sim:
    return sim._replace(user=new_user)


def stop(sim: Sim, pred=True) -> Sim:
    """End the replication after the current event (the analog of the
    reference's user-scheduled end event)."""
    return sim._replace(done=sim.done | jnp.asarray(pred))


def fail(sim: Sim, pred=True) -> Sim:
    """Mark the replication failed (parity: cmb_logger_error recovery —
    the replication is abandoned and counted, §3.5)."""
    return sim._replace(
        err=jnp.where(
            (sim.err == 0) & jnp.asarray(pred), jnp.asarray(ERR_USER, _I), sim.err
        )
    )


def queue_length(sim: Sim, q):
    """Current number of items in an object queue (parity:
    ``cmb_objectqueue_length``)."""
    return sim.queues.size[q.id if hasattr(q, "id") else q]


def queue_space(sim: Sim, q):
    """Free slots in an object queue (parity: ``cmb_objectqueue_space``,
    `include/cmb_objectqueue.h`).  Requires the QueueRef: the declared
    capacity lives there, and the shared ring width a bare id could
    read can be wider than this queue's real capacity."""
    if not hasattr(q, "capacity"):
        raise TypeError("queue_space needs the QueueRef, not a bare id")
    return (jnp.asarray(q.capacity, _I) - sim.queues.size[q.id]).astype(_I)


def queue_position(sim: Sim, q, item):
    """1-based position of the first item equal to ``item`` (nearest the
    front), 0 if absent (parity: cmb_objectqueue_position,
    `include/cmb_objectqueue.h:199`; the reference matches object pointers,
    this matches the f64 payload)."""
    qid = q.id if hasattr(q, "id") else q
    items = sim.queues.items[qid]
    cap = items.shape[0]
    # gather-free: for each physical slot c, its queue position is
    # (c - head) mod cap; a slot is occupied if that position < size.
    # (A permutation gather over the ring would not lower in Mosaic.)
    c = jnp.arange(cap)
    pos = (c - sim.queues.head[qid]) % cap
    hit = (pos < sim.queues.size[qid]) & (items == jnp.asarray(item, _R))
    best = jnp.min(jnp.where(hit, pos, cap))
    return jnp.where(jnp.any(hit), best + 1, 0).astype(_I)


def _pq_match(sim: Sim, qid, item):
    """Earliest-dequeuing live item equal to ``item``: returns
    ``(one_hot, match, p_best, s_best)`` — the single source of the
    payload-keyed tie-break rule (max priority, then min seq) shared by
    position/cancel/reprioritize."""
    live = sim.pqueues.live[qid]
    prio = sim.pqueues.prio[qid]
    seq = sim.pqueues.seq[qid]
    match = live & (sim.pqueues.items[qid] == jnp.asarray(item, _R))
    p_best = jnp.max(jnp.where(match, prio, jnp.asarray(-jnp.inf, _R)))
    m2 = match & (prio == p_best)
    s_best = jnp.min(jnp.where(m2, seq, jnp.iinfo(jnp.int32).max))
    return m2 & (seq == s_best), match, p_best, s_best


def pqueue_position(sim: Sim, q, item):
    """1-based position in dequeue order (priority desc, FIFO within equal
    priority) of the first item equal to ``item``, 0 if absent (parity:
    cmb_priorityqueue_position, `include/cmb_priorityqueue.h:140`; the
    reference locates by put-handle — here puts return no handle, so the
    payload is the lookup key and the earliest-dequeuing match wins)."""
    qid = q.id if hasattr(q, "id") else q
    _, match, p_best, s_best = _pq_match(sim, qid, item)
    live = sim.pqueues.live[qid]
    prio = sim.pqueues.prio[qid]
    seq = sim.pqueues.seq[qid]
    ahead = live & (
        (prio > p_best) | ((prio == p_best) & (seq < s_best))
    )
    pos = jnp.sum(ahead.astype(_I)) + 1
    return jnp.where(jnp.any(match), pos, 0).astype(_I)


def resource_holder(sim: Sim, r):
    """Holding pid of a resource, -1 if free."""
    return sim.resources.holder[r.id if hasattr(r, "id") else r]


def pool_level(sim: Sim, pool):
    """Available units in a resource pool (parity: cmb_resourcepool_level)."""
    return sim.pools.level[pool.id if hasattr(pool, "id") else pool]


def buffer_level(sim: Sim, b):
    """Stored amount in a buffer (parity: cmb_buffer_level)."""
    return sim.buffers.level[b.id if hasattr(b, "id") else b]


def buffer_space(sim: Sim, b):
    """Room left in a buffer (parity: ``cmb_buffer_space``,
    `include/cmb_buffer.h`).  Requires the BufferRef (capacity is
    declared there, not stored in the Sim)."""
    if not hasattr(b, "capacity"):
        raise TypeError("buffer_space needs the BufferRef, not a bare id")
    return jnp.asarray(b.capacity, _R) - sim.buffers.level[b.id]


def pool_in_use(sim: Sim, pool):
    """Units currently held out of a pool (parity:
    ``cmb_resourcepool_in_use``).  Requires the PoolRef (capacity is
    declared there, not stored in the Sim)."""
    if not hasattr(pool, "capacity"):
        raise TypeError("pool_in_use needs the PoolRef, not a bare id")
    return jnp.asarray(pool.capacity, _R) - sim.pools.level[pool.id]


def pool_held(sim: Sim, pool, p):
    """Units process ``p`` holds from a pool (parity:
    ``cmb_resourcepool_held_by_process``,
    `include/cmb_resourcepool.h:118`)."""
    k = pool.id if hasattr(pool, "id") else pool
    return dyn.dget2(sim.pools.held, k, p)


def proc_priority(sim: Sim, p):
    """Current process priority (parity: ``cmb_process_priority``;
    the setter is :func:`priority_set`)."""
    return dyn.dget(sim.procs.prio, p)


def pqueue_length(sim: Sim, q):
    """Items in a priority queue (parity: cmb_priorityqueue_length)."""
    qid = q.id if hasattr(q, "id") else q
    return jnp.sum(sim.pqueues.live[qid].astype(_I))


def pqueue_cancel(sim: Sim, q, item):
    """(sim, existed): remove the earliest-dequeuing item equal to
    ``item`` from a priority queue (parity: ``cmb_priorityqueue_cancel``,
    `include/cmb_priorityqueue.h` — the reference cancels by put-handle;
    payload-keyed here, matching pqueue_position's documented lookup).
    Requires the PQueueRef: the freed slot signals the rear guard so a
    blocked putter wakes (as the reference does), and the length
    recording appends a step when the queue records."""
    from cimba_tpu.core import loop as _loop

    if not hasattr(q, "rear_guard"):
        raise TypeError("pqueue_cancel needs the PQueueRef, not a bare id")
    qid = q.id
    m, _, _, _ = _pq_match(sim, qid, item)
    existed = jnp.any(m)
    live2 = dyn.dset(sim.pqueues.live, qid, sim.pqueues.live[qid] & ~m)
    pq2 = sim.pqueues._replace(live=live2)
    if q.record and sim.pqueues.acc is not None:
        pq2 = pq2._replace(
            acc=_loop._record_row(
                sim.pqueues.acc, qid, sim.clock,
                jnp.sum(live2[qid].astype(_I)).astype(_R), existed,
            )
        )
    sim = sim._replace(pqueues=pq2)
    # the freed slot can satisfy a pending putter
    sim = _loop._guard_signal(sim, q.rear_guard, pred=existed)
    return sim, existed


def pqueue_reprioritize(sim: Sim, q, item, new_prio):
    """(sim, existed): change the priority of the earliest-dequeuing
    item equal to ``item`` (parity: ``cmb_priorityqueue_reprioritize``;
    payload-keyed, see pqueue_cancel).  FIFO seq is preserved, so equal
    priorities keep insertion order — the same contract as
    event_reprioritize."""
    qid = q.id if hasattr(q, "id") else q
    m, _, _, _ = _pq_match(sim, qid, item)
    existed = jnp.any(m)
    prio2 = dyn.dset(
        sim.pqueues.prio, qid,
        jnp.where(m, jnp.asarray(new_prio, _R), sim.pqueues.prio[qid]),
    )
    return sim._replace(pqueues=sim.pqueues._replace(prio=prio2)), existed


# --- inter-process verbs (thin wrappers over core.loop; blocks close over
#     their model's built spec, e.g. via a late-bound `spec()` accessor) ----


def interrupt(sim: Sim, spec, target, sig) -> Sim:
    """Deliver ``sig`` to a waiting process now, aborting its wait
    (parity: cmb_process_interrupt)."""
    from cimba_tpu.core import loop as _loop

    return _loop.interrupt(spec, sim, target, jnp.asarray(sig, _I))


def stop_process(sim: Sim, spec, target) -> Sim:
    """Kill a process: drop resources, cancel waits, wake waiters with
    STOPPED (parity: cmb_process_stop)."""
    from cimba_tpu.core import loop as _loop

    return _loop.stop_process(spec, sim, target)


def spawn(sim: Sim, ptype, at=None, prio=None):
    """(sim, pid): activate one row of a spawn pool — a process type
    declared ``m.process(name, entry, count=N, start=False)``.  Picks
    the lowest-pid CREATED/FINISHED row, resets its state, and arms its
    entry wake at ``at`` (default now); pid == -1 when all N rows are
    RUNNING (parity: runtime ``cmb_process_create``/``start``,
    `include/cmb_process.h:119-180` — the pool is declared, activation
    is dynamic)."""
    from cimba_tpu.core import loop as _loop

    return _loop.spawn_process(sim, ptype, at=at, prio=prio)


def timer_add(sim: Sim, p, dur, sig):
    """(sim, handle): deliver ``sig`` to p after ``dur`` unless cancelled
    (parity: cmb_process_timer_add)."""
    from cimba_tpu.core import loop as _loop

    return _loop.timer_add(sim, p, dur, jnp.asarray(sig, _I))


def timer_cancel(sim: Sim, handle, spec=None):
    """(sim, existed) — parity: cmb_process_timer_cancel.  Pass the model
    ``spec`` so processes waiting on this handle (cmd.wait_event) wake with
    CANCELLED immediately rather than at the next dispatch."""
    from cimba_tpu.core import loop as _loop

    return _loop.timer_cancel(sim, handle, spec)


def event_cancel(sim: Sim, handle, spec=None):
    """(sim, existed): cancel any scheduled event by handle (parity:
    cmb_event_cancel); wait_event waiters wake with CANCELLED (immediately
    when ``spec`` is passed, else at the next dispatch)."""
    from cimba_tpu.core import loop as _loop

    return _loop.timer_cancel(sim, handle, spec)


def timers_clear(sim: Sim, p) -> Sim:
    """Cancel all timers aimed at p (parity: cmb_process_timers_clear)."""
    from cimba_tpu.core import loop as _loop

    return _loop.timers_clear(sim, p)


def priority_set(sim: Sim, p, new_prio) -> Sim:
    """Change process priority, reshuffling event and guard queues
    (parity: cmb_process_priority_set)."""
    from cimba_tpu.core import loop as _loop

    return _loop.priority_set(sim, p, new_prio)


def cond_signal(sim: Sim, spec, condition) -> Sim:
    """Signal a condition variable: wake every waiter whose predicate
    holds (parity: cmb_condition_signal)."""
    from cimba_tpu.core import loop as _loop

    cid = condition.id if hasattr(condition, "id") else condition
    return _loop.cond_signal(spec, sim, cid)


def release(sim: Sim, spec, resource, p) -> Sim:
    """Release a binary resource INLINE from a block — zero chain
    iterations (release never blocks or yields, so spending a command —
    a full masked kernel body pass — on it was pure cost; parity:
    cmb_resource_release as the reference's plain function call).
    ``cmd.release`` remains for block-boundary control flow."""
    from cimba_tpu.core import loop as _loop

    rid = resource.id if hasattr(resource, "id") else resource
    return _loop.release_resource(spec, sim, p, rid)


def pool_release(sim: Sim, spec, pool, p, amount) -> Sim:
    """Release pool units INLINE from a block (partial release allowed;
    parity: cmb_resourcepool_release) — see :func:`release` for why
    this costs zero chain iterations.  ``cmd.pool_release`` remains."""
    from cimba_tpu.core import loop as _loop

    k = pool.id if hasattr(pool, "id") else pool
    return _loop.release_pool(spec, sim, p, k, amount)


def proc_status(sim: Sim, p):
    """CREATED/RUNNING/FINISHED (parity: cmb_process_status)."""
    return dyn.dget(sim.procs.status, p)


def event_is_scheduled(sim: Sim, handle):
    """True while ``handle`` names a live scheduled event (parity:
    ``cmb_event_is_scheduled``, `include/cmb_event.h:196` — generation
    tags make a fired/cancelled/reused slot report False)."""
    from cimba_tpu.core import eventset as _ev

    return _ev._valid(sim.events, jnp.asarray(handle, _I))


def event_time(sim: Sim, handle):
    """Scheduled activation time of a live event, ``+inf`` for a dead
    handle (parity: ``cmb_event_time``, `include/cmb_event.h:205` — the
    reference errors on a dead handle; here the sentinel composes with
    jit, and :func:`event_is_scheduled` is the validity check)."""
    from cimba_tpu.core import eventset as _ev

    h = jnp.asarray(handle, _I)
    slot = _ev._slot_of(h)
    return jnp.where(
        _ev._valid(sim.events, h),
        dyn.dget(sim.events.time, slot),
        jnp.asarray(jnp.inf, sim.events.time.dtype),
    )


def event_priority(sim: Sim, handle):
    """Dispatch priority of a live event, 0 for a dead handle (parity:
    ``cmb_event_priority``, `include/cmb_event.h:214`)."""
    from cimba_tpu.core import eventset as _ev

    h = jnp.asarray(handle, _I)
    slot = _ev._slot_of(h)
    return jnp.where(
        _ev._valid(sim.events, h),
        dyn.dget(sim.events.prio, slot),
        jnp.zeros((), _I),
    )


def event_reschedule(sim: Sim, handle, new_t):
    """(sim, existed): move a scheduled event to ``new_t`` keeping its
    FIFO sequence — unlike cancel+schedule, which would send it to the
    back of its (time, prio) tie class (parity: ``cmb_event_reschedule``,
    `include/cmb_event.h:193-210`).  A non-finite ``new_t`` fails the
    move and returns existed=False."""
    from cimba_tpu.core import eventset as _ev

    es2, ok = _ev.reschedule(sim.events, handle, new_t)
    return sim._replace(events=es2), ok


def event_reprioritize(sim: Sim, handle, new_prio):
    """(sim, existed): change a scheduled event's dispatch priority in
    place, keeping time and FIFO sequence (parity:
    ``cmb_event_reprioritize``, `include/cmb_event.h:212-228`)."""
    from cimba_tpu.core import eventset as _ev

    es2, ok = _ev.reprioritize(sim.events, handle, new_prio)
    return sim._replace(events=es2), ok


def _pattern_kind(kind):
    from cimba_tpu.core import eventset as _ev

    if kind is None:
        return _ev.WILDCARD
    return kind.kind if hasattr(kind, "kind") else kind


def event_pattern_count(sim: Sim, kind=None, subj=None):
    """Number of scheduled events matching (kind, subj); ``None`` is a
    wildcard, ``kind`` may be a Model.handler function (parity:
    ``cmb_event_pattern_count``, `src/cmb_event.c:459-470`)."""
    from cimba_tpu.core import eventset as _ev

    return _ev.pattern_count(
        sim.events, _pattern_kind(kind),
        _ev.WILDCARD if subj is None else subj,
    )


def event_pattern_find(sim: Sim, kind=None, subj=None):
    """Handle of the soonest scheduled event matching (kind, subj), else
    NULL_HANDLE=-1 (parity: ``cmb_event_pattern_find``,
    `src/cmb_event.c:472-481`).  The handle feeds event_cancel /
    event_reschedule / cmd.wait_event."""
    from cimba_tpu.core import eventset as _ev

    return _ev.pattern_find(
        sim.events, _pattern_kind(kind),
        _ev.WILDCARD if subj is None else subj,
    )


def event_pattern_cancel(sim: Sim, kind=None, subj=None):
    """(sim, n_cancelled): cancel every scheduled event matching
    (kind, subj) (parity: ``cmb_event_pattern_cancel``,
    `src/cmb_event.c:483-493`)."""
    from cimba_tpu.core import eventset as _ev

    es2, n = _ev.pattern_cancel(
        sim.events, _pattern_kind(kind),
        _ev.WILDCARD if subj is None else subj,
    )
    return sim._replace(events=es2), n


def schedule(sim: Sim, t, prio, handler, subj=0, arg=0):
    """(sim, handle): schedule a user event (parity: cmb_event_schedule
    with an arbitrary action); ``handler`` is a function registered with
    Model.handler.  The handle supports event_cancel / cmd.wait_event
    (NULL_HANDLE = -1 if the event table was full; the replication is then
    already marked failed)."""
    from cimba_tpu.core import eventset as _ev
    from cimba_tpu.core import loop as _loop

    kind = handler.kind if hasattr(handler, "kind") else handler
    es2, handle = _ev.schedule(sim.events, t, prio, kind, subj, arg)
    sim = sim._replace(events=es2)
    sim = _loop._set_err(sim, es2.overflow, _loop.ERR_EVENT_OVERFLOW)
    return sim, handle