"""Block-author helpers: the model-facing surface inside blocks.

These are the TPU equivalents of the calls a reference process body makes
between yields — ``cmb_time()``, ``cmb_random_*``, reading/writing its own
state — expressed functionally over the :class:`~cimba_tpu.core.loop.Sim`
pytree.  Commands (the yield points) live in :mod:`cimba_tpu.core.process`.
"""

from __future__ import annotations

import jax.numpy as jnp

from cimba_tpu.config import INDEX_DTYPE, REAL_DTYPE
from cimba_tpu.core.loop import ERR_USER, Sim

_I = INDEX_DTYPE
_R = REAL_DTYPE


def clock(sim: Sim):
    """Current simulation time (parity: ``cmb_time``)."""
    return sim.clock


def draw(sim: Sim, dist, *params):
    """Draw from a distribution, threading the replication's RNG stream:
    ``sim, x = api.draw(sim, random.exponential, mean)``."""
    rng, x = dist(sim.rng, *params)
    return sim._replace(rng=rng), x


def got(sim: Sim, p):
    """Result register: the item produced by this process's last GET."""
    return sim.procs.got[p]


def local_f(sim: Sim, p, k: int):
    return sim.procs.locals_f[p, k]


def set_local_f(sim: Sim, p, k: int, v) -> Sim:
    return sim._replace(
        procs=sim.procs._replace(
            locals_f=sim.procs.locals_f.at[p, k].set(jnp.asarray(v, _R))
        )
    )


def local_i(sim: Sim, p, k: int):
    return sim.procs.locals_i[p, k]


def set_local_i(sim: Sim, p, k: int, v) -> Sim:
    return sim._replace(
        procs=sim.procs._replace(
            locals_i=sim.procs.locals_i.at[p, k].set(jnp.asarray(v, _I))
        )
    )


def add_local_i(sim: Sim, p, k: int, dv=1) -> Sim:
    return sim._replace(
        procs=sim.procs._replace(
            locals_i=sim.procs.locals_i.at[p, k].add(jnp.asarray(dv, _I))
        )
    )


def user(sim: Sim):
    return sim.user


def set_user(sim: Sim, new_user) -> Sim:
    return sim._replace(user=new_user)


def stop(sim: Sim, pred=True) -> Sim:
    """End the replication after the current event (the analog of the
    reference's user-scheduled end event)."""
    return sim._replace(done=sim.done | jnp.asarray(pred))


def fail(sim: Sim, pred=True) -> Sim:
    """Mark the replication failed (parity: cmb_logger_error recovery —
    the replication is abandoned and counted, §3.5)."""
    return sim._replace(
        err=jnp.where(
            (sim.err == 0) & jnp.asarray(pred), jnp.asarray(ERR_USER, _I), sim.err
        )
    )


def queue_length(sim: Sim, q):
    """Current number of items in an object queue (parity:
    ``cmb_objectqueue_length``)."""
    return sim.queues.size[q.id if hasattr(q, "id") else q]


def resource_holder(sim: Sim, r):
    """Holding pid of a resource, -1 if free."""
    return sim.resources.holder[r.id if hasattr(r, "id") else r]