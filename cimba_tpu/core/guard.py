"""Resource guards: the universal wait queue for blocked processes.

Reference parity: ``cmb_resourceguard`` (`src/cmb_resourceguard.c:71-251`)
— a hashheap of (process, demand-predicate, context) entries ordered by
priority, then entry time, then sequence; every L5 component (resource,
pool, buffer, queues, condition) funnels its blocking through one of these.

TPU redesign (round 4, dense): a process waits on at most one guard, and
the engine already records WHICH one in ``procs.pend_guard`` and its FIFO
sequence in ``procs.pend_seq`` — so the old ``[NG, GCAP]`` slot table
duplicated state the Sim carries anyway, and every enqueue/remove paid
slot-search ops to keep the copy in sync.  The wait queue is now *derived*:
membership is ``pend_guard == gid``, order is (live ``procs.prio`` DESC,
``pend_seq`` ASC), and the only state this module owns is the per-guard
FIFO sequence counter.  Wins, mirroring the round-3 dense wake table:

- enqueue = the pend bookkeeping the caller already does (+1 counter op);
- remove = clearing ``pend_guard`` — which every unwait path already does;
- reprioritize = nothing (ordering reads ``procs.prio`` live, which IS the
  reference's reshuffle-on-reprio semantics, `src/cmb_process.c:170-220`);
- guard capacity/overflow cease to exist (capacity is P by construction —
  matching the reference's unlimited heap more faithfully than the old
  fixed-capacity table with its overflow-as-failure trade).

The demand *predicate* does not live here: in the reference it's a C
function pointer evaluated at signal time; here the woken process
re-attempts its pending command at wake time (same fairness loop the
reference's acquire/get/put sites implement around
``cmb_resourceguard_wait``), so the predicate is the command's own
can-proceed check — one mechanism instead of two.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from cimba_tpu.core import dyn
from cimba_tpu.config import argmax32 as _argmax32

from cimba_tpu.config import INDEX_DTYPE

_I = INDEX_DTYPE

NO_PID = jnp.int32(-1)


class Guards(NamedTuple):
    """Per-replication guard state: only the FIFO sequence counters.

    The wait queue itself lives in the process rows (``pend_guard``,
    ``pend_seq``, ``prio``) — see the module docstring."""

    next_seq: jnp.ndarray  # [NG] i32


def create(n_guards: int) -> Guards:
    return Guards(next_seq=jnp.zeros((n_guards,), _I))


def alloc_seq(g: Guards, gid, seq_override=None, pred=True):
    """FIFO sequence for a process entering guard ``gid``; returns
    ``(g2, seq)``.

    ``seq_override`` >= 0 re-enters with a previously-held sequence
    number: a woken waiter whose retry failed keeps its FIFO position
    (parity with the reference, where the front waiter is never dequeued
    on an unsatisfied signal and so cannot lose its place)."""
    fresh = dyn.dget(g.next_seq, gid)
    if seq_override is None:
        seq = fresh
    else:
        so = jnp.asarray(seq_override, _I)
        seq = jnp.where(so >= 0, so, fresh)
    took_fresh = seq == fresh
    bump = took_fresh if pred is True else (took_fresh & pred)
    return g._replace(next_seq=dyn.dadd(g.next_seq, gid, 1, bump)), seq


def best_waiter(wait_gid, wait_seq, prio, gid):
    """Best waiter of guard ``gid``: highest live priority, then earliest
    entry (parity with the reference's priority -> entry-time -> seq
    ordering).  Returns ``(pid, found)``; the argbest index IS the pid.

    ``wait_gid``/``wait_seq`` are the engine's ``procs.pend_guard`` /
    ``procs.pend_seq`` rows; ``prio`` is ``procs.prio`` read live."""
    live = wait_gid == jnp.asarray(gid, _I)
    p_max = jnp.max(jnp.where(live, prio, jnp.iinfo(jnp.int32).min))
    m = live & (prio == p_max)
    s_min = jnp.min(jnp.where(m, wait_seq, jnp.iinfo(jnp.int32).max))
    m2 = m & (wait_seq == s_min)
    found = jnp.any(live)
    pid = jnp.where(found, _argmax32(m2).astype(_I), NO_PID)
    return pid, found


def is_empty(wait_gid, gid):
    return ~jnp.any(wait_gid == jnp.asarray(gid, _I))


def length(wait_gid, gid):
    return jnp.sum((wait_gid == jnp.asarray(gid, _I)).astype(_I))
