"""Resource guards: the universal wait queue for blocked processes.

Reference parity: ``cmb_resourceguard`` (`src/cmb_resourceguard.c:71-251`)
— a hashheap of (process, demand-predicate, context) entries ordered by
priority, then entry time, then sequence; every L5 component (resource,
pool, buffer, queues, condition) funnels its blocking through one of these.

TPU redesign: a guard is a fixed-capacity slot table per replication, like
the event set: entries are (pid, prio, seq), "pop best" is a two-key masked
argmin (priority DESC, seq ASC).  The demand *predicate* does not live here:
in the reference it's a C function pointer evaluated at signal time; here
the woken process re-attempts its pending command at wake time (same
fairness loop the reference's acquire/get/put sites implement around
``cmb_resourceguard_wait``), so the predicate is the command's own
can-proceed check — one mechanism instead of two.

Guards for a whole model are stored as one struct-of-arrays ``[NG, GCAP]``
so blocks can index them by integer id under jit.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from cimba_tpu.core import dyn
from cimba_tpu.config import argmax32 as _argmax32

from cimba_tpu.config import INDEX_DTYPE

_I = INDEX_DTYPE

NO_PID = jnp.int32(-1)


class Guards(NamedTuple):
    """All guards of one replication: [NG, GCAP] slot tables."""

    pid: jnp.ndarray    # [NG, GCAP] i32, -1 = free slot
    prio: jnp.ndarray   # [NG, GCAP] i32
    seq: jnp.ndarray    # [NG, GCAP] i32 entry order
    next_seq: jnp.ndarray  # [NG] i32
    overflow: jnp.ndarray  # bool


def create(n_guards: int, capacity: int) -> Guards:
    return Guards(
        pid=jnp.full((n_guards, capacity), NO_PID, _I),
        prio=jnp.zeros((n_guards, capacity), _I),
        seq=jnp.zeros((n_guards, capacity), _I),
        next_seq=jnp.zeros((n_guards,), _I),
        overflow=jnp.asarray(False),
    )


def enqueue(g: Guards, gid, pid, prio, seq_override=None):
    """Add a waiting process; returns (g, ok, seq).

    ``seq_override`` >= 0 re-enqueues with a previously-held sequence
    number: a woken waiter whose retry failed keeps its FIFO position
    (parity with the reference, where the front waiter is never dequeued
    on an unsatisfied signal and so cannot lose its place)."""
    row_pid = dyn.dget(g.pid, gid)
    free = row_pid == NO_PID
    slot = _argmax32(free).astype(_I)
    ok = jnp.any(free)
    fresh = dyn.dget(g.next_seq, gid)
    if seq_override is None:
        seq = fresh
    else:
        so = jnp.asarray(seq_override, _I)
        seq = jnp.where(so >= 0, so, fresh)

    def put(a, v):
        return dyn.dset2(a, gid, slot, v, ok)

    g2 = Guards(
        pid=put(g.pid, jnp.asarray(pid, _I)),
        prio=put(g.prio, jnp.asarray(prio, _I)),
        seq=put(g.seq, seq),
        next_seq=dyn.dadd(
            g.next_seq, gid, 1, ok & (seq == fresh)
        ),
        overflow=g.overflow | ~ok,
    )
    return g2, ok, seq


def _argbest(g: Guards, gid):
    """Best waiter: highest priority, then earliest entry (parity with the
    reference's priority -> entry-time -> seq ordering)."""
    row_pid = dyn.dget(g.pid, gid)
    row_prio = dyn.dget(g.prio, gid)
    row_seq = dyn.dget(g.seq, gid)
    live = row_pid != NO_PID
    p_max = jnp.max(jnp.where(live, row_prio, jnp.iinfo(jnp.int32).min))
    m = live & (row_prio == p_max)
    s_min = jnp.min(jnp.where(m, row_seq, jnp.iinfo(jnp.int32).max))
    m2 = m & (row_seq == s_min)
    return _argmax32(m2).astype(_I), jnp.any(live)


def pop_best(g: Guards, gid):
    """Dequeue the best waiter; returns (g, pid) with pid == NO_PID if the
    guard is empty."""
    slot, found = _argbest(g, gid)
    pid = jnp.where(found, dyn.dget2(g.pid, gid, slot), NO_PID)
    g2 = g._replace(pid=dyn.dset2(g.pid, gid, slot, NO_PID, found))
    return g2, pid


def remove(g: Guards, gid, pid):
    """Remove a specific process (parity: ``cmb_resourceguard_remove``, used
    when a waiting process is interrupted/killed); returns (g, existed)."""
    row = dyn.dget(g.pid, gid)
    m = row == jnp.asarray(pid, _I)
    existed = jnp.any(m)
    return g._replace(
        pid=dyn.dset(g.pid, gid, jnp.where(m, NO_PID, row))
    ), existed


def is_empty(g: Guards, gid):
    return ~jnp.any(dyn.dget(g.pid, gid) != NO_PID)


def length(g: Guards, gid):
    return jnp.sum((dyn.dget(g.pid, gid) != NO_PID).astype(_I))


def reprioritize(g: Guards, gid, pid, new_prio):
    """Update a waiter's priority in place (parity: the reprio hooks that
    reshuffle guard queues when a process's priority changes,
    `src/cmb_process.c:170-220`)."""
    row = dyn.dget(g.pid, gid)
    m = row == jnp.asarray(pid, _I)
    return g._replace(
        prio=dyn.dset(
            g.prio, gid,
            jnp.where(m, jnp.asarray(new_prio, _I), dyn.dget(g.prio, gid)),
        )
    )