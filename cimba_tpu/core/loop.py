"""The event loop: one replication's dispatcher, jit-compiled and vmapped.

Reference parity: ``cmb_event_queue_execute`` (`src/cmb_event.c:296-335`)
— pop next event, advance the clock, run the action, repeat — where the
action context-switches into a coroutine until it yields
(`src/cmb_process.c:329-375`).

TPU rendition (the "fiber scheduler lowered to an XLA while-loop" of the
north star): ``make_run`` builds ``lax.while_loop(cond, step, sim)`` where
``step`` pops from the flat event set, advances the batched clock, and
dispatches through ``lax.switch``:

* kind K_PROC / K_TIMER: resume the subject process — an inner bounded
  while_loop runs its current block (``lax.switch`` over the model's block
  table) and applies the returned command, chaining while commands complete
  without yielding.  This is exactly a coroutine running until it waits,
  with (pc, locals) rows instead of a C stack.
* kinds >= 2 = user handlers (parity: arbitrary (action, subject, object)
  events).

Signal delivery contract: a yielding command's continuation block receives
the wakeup signal as its ``sig`` argument — SUCCESS when the operation
completed, PREEMPTED/INTERRUPTED/STOPPED/TIMEOUT/app-defined when it was
aborted.  This is the array-world image of the reference's
``sig = cmb_resource_acquire(...)`` return value.  Blocked commands pend
on guards and are *re-attempted* on a SUCCESS wakeup (the reference's
loop-around-guard-wait fairness protocol, `src/cmb_resource.c:202-233`);
a non-SUCCESS wakeup aborts the pending operation instead (guard entry
removed), like ``cmi_process_cancel_awaiteds``.

Failure containment (parity: §3.5 error recovery, `src/cimba.c:185-209`):
any structural failure — event/guard overflow, non-finite time, a block
chain that never yields, releasing an unheld resource — sets ``sim.err``
and freezes the replication; the experiment runner counts and masks it,
and the other replications in the batch are unaffected.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.config import argmax32 as _argmax32
from cimba_tpu.core import dyn
from cimba_tpu.core import eventset as ev
from cimba_tpu.core import guard as gd
from cimba_tpu.core import process as pr
from cimba_tpu.core.model import ModelSpec
from cimba_tpu.obs import metrics as obs_metrics
from cimba_tpu.obs import trace as obs_trace
from cimba_tpu.random import bits as rb
from cimba_tpu.stats import timeseries as ts

_I = INDEX_DTYPE
_R = config.REAL
_T = config.TIME

K_PROC = 0   # resume process `subj` with signal `arg`
K_TIMER = 1  # same dispatch; separate kind so timers_clear can pattern-cancel
N_KINDS = 2  # user handler kinds start here

# chain-safety bound: a process may not execute more than this many blocks
# without yielding (a JUMP cycle would otherwise hang the whole batch)
MAX_CHAIN = 1024

# error codes (sim.err)
ERR_NONE = 0
ERR_EVENT_OVERFLOW = 1
ERR_GUARD_OVERFLOW = 2
ERR_CHAIN_RUNAWAY = 3
ERR_USER = 4
ERR_BAD_RELEASE = 5
ERR_BOUNDARY = 6   # boundary block entered mid-chain inside the kernel


class Queues(NamedTuple):
    items: jnp.ndarray  # [NQ, QCAP] f64 ring buffers
    head: jnp.ndarray   # [NQ] i32
    size: jnp.ndarray   # [NQ] i32
    acc: ts.StepAccum   # leaves [NQ]: queue-length recording


class Resources(NamedTuple):
    holder: jnp.ndarray  # [NR] i32, -1 = free
    acc: ts.StepAccum    # leaves [NR]: utilization recording


class Pools(NamedTuple):
    level: jnp.ndarray   # [NP] f64 available units
    held: jnp.ndarray    # [NP, P] f64 per-process held amounts
    held_seq: jnp.ndarray  # [NP, P] i32 grab order (LIFO victim selection)
    next_seq: jnp.ndarray  # [NP] i32
    acc: ts.StepAccum    # leaves [NP]: in-use recording


class Buffers(NamedTuple):
    level: jnp.ndarray   # [NB] f64 stored amount
    acc: ts.StepAccum    # leaves [NB]: level recording


class PQueues(NamedTuple):
    items: jnp.ndarray   # [NPQ, CAP] f64 payloads
    prio: jnp.ndarray    # [NPQ, CAP] f64 item priorities (higher first)
    seq: jnp.ndarray     # [NPQ, CAP] i32 insertion order (FIFO tiebreak)
    live: jnp.ndarray    # [NPQ, CAP] bool slot occupancy
    next_seq: jnp.ndarray  # [NPQ] i32
    acc: ts.StepAccum    # leaves [NPQ]: length recording


class Sim(NamedTuple):
    """One replication's full state."""

    clock: jnp.ndarray
    rep: jnp.ndarray       # i32 replication index (logger trial context)
    rng: rb.RandomState
    events: ev.EventSet
    wakes: ev.Wakes        # dense per-process resumes (see eventset.Wakes)
    procs: pr.Procs
    guards: gd.Guards
    queues: Queues
    resources: Resources
    pools: Pools
    buffers: Buffers
    pqueues: PQueues
    user: Any
    done: jnp.ndarray      # bool, set by model code (api.stop)
    err: jnp.ndarray       # i32, ERR_* (0 = healthy)
    n_events: jnp.ndarray  # i64, dispatched events (bench metric)
    #: kernel path only: this lane's next dispatch targets a boundary
    #: block — the chunk freezes it for the host driver (pallas_run)
    boundary_pending: jnp.ndarray
    #: flight recorder ring (obs.trace.TraceRing) or None — None prunes
    #: the leaves from the pytree, so a disabled recorder costs zero ops
    #: (the logger's NLOGINFO story, as state instead of lines)
    trace: Any = None
    #: metrics registry (obs.metrics.Metrics) or None, same contract
    metrics: Any = None
    #: per-lane horizon (TIME scalar) or None — None prunes the leaf so
    #: the historical pytree (and static-``t_end`` programs) are
    #: untouched.  When carried, :func:`make_cond` reads it INSTEAD of
    #: its static ``t_end``: the lane stops dispatching once its next
    #: event would pass ``t_stop``, exactly as a program compiled with
    #: that static horizon would.  This is what lets heterogeneous
    #: horizons share ONE compiled chunk program (a short lane goes
    #: dead early; ``-inf`` makes a lane dead-on-arrival — the wave
    #: padding mask, docs/14_wave_packing.md)
    t_stop: Any = None


def _tree_select(pred, a, b):
    # leaves untouched by either branch are the *same object* (branches are
    # built with _replace from a shared base) — pass them through instead of
    # emitting a select, so an event that modifies three arrays doesn't
    # rewrite every leaf of the Sim (full-state HBM traffic per event was
    # the dominant dispatch cost before this)
    return jax.tree.map(
        lambda x, y: x if x is y else dyn.bwhere(pred, x, y), a, b
    )


def _batched(tree, n):
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n,) + jnp.shape(x)), tree
    )


def init_sim(spec: ModelSpec, seed, replication, params=None, t0=0.0,
             t_stop=None) -> Sim:
    """Build one replication's initial state and schedule process starts
    (parity: the trial-init sequence `benchmark/MM1_multi.c:91-124`).

    ``seed`` may be a python int OR a traced u64 scalar: the stream key
    is ``fmix64(seed + c*replication)`` — pure integer arithmetic, so a
    per-lane seed column produces bit-identical streams to the
    historical static-seed trace (the Tier-A packing contract,
    docs/14_wave_packing.md).  ``t_stop`` (optional, TIME scalar) gives
    the lane a per-lane horizon — see :class:`Sim`."""
    nq = max(len(spec.queues), 1)
    nr = max(len(spec.resources), 1)
    np_ = max(len(spec.pools), 1)
    nb = max(len(spec.buffers), 1)
    npq = max(len(spec.pqueues), 1)
    events = ev.create(spec.event_cap)
    procs = pr.create(
        spec.proc_entry, spec.proc_prio, spec.n_flocals, spec.n_ilocals
    )
    # process starts are dense wakes at t0, consuming seqs 0..n_started-1
    # in pid order exactly as the former per-start ev.schedule calls did
    # (golden-stable: all-started models get seq=arange(P)).  Spawn-pool
    # rows (proc_start False) stay CREATED with no wake until api.spawn.
    import numpy as _np

    started_np = _np.asarray(spec.proc_start, bool)
    started = jnp.asarray(started_np)
    seq0 = _np.cumsum(started_np) - started_np  # rank among started
    wakes = ev.wakes_create(spec.n_procs)._replace(
        time=jnp.where(started, jnp.asarray(t0, config.TIME), ev.NEVER),
        sig=jnp.full((spec.n_procs,), pr.SUCCESS, _I),
        seq=jnp.asarray(seq0, _I),
    )
    events = events._replace(
        next_seq=jnp.asarray(int(started_np.sum()), _I)
    )
    procs = procs._replace(
        status=jnp.where(
            started, jnp.asarray(pr.RUNNING, _I), jnp.asarray(pr.CREATED, _I)
        ),
    )
    user = spec.user_init(params) if spec.user_init else jnp.zeros(())
    t0 = jnp.asarray(t0, _T)
    pool_caps = jnp.asarray([p.capacity for p in spec.pools] or [0.0], _R)
    buf_init = jnp.asarray([b.initial for b in spec.buffers] or [0.0], _R)
    return Sim(
        clock=t0,
        rep=jnp.asarray(replication, _I),
        rng=rb.initialize(seed, replication),
        events=events,
        wakes=wakes,
        procs=procs,
        guards=gd.create(spec.n_guards),
        # absent components carry no state at all (None prunes the
        # pytree — the while_loop body then never touches those leaves),
        # and recording accumulators exist only if some member records
        queues=Queues(
            items=jnp.zeros((nq, spec.queue_cap_max), _R),
            head=jnp.zeros((nq,), _I),
            size=jnp.zeros((nq,), _I),
            acc=_batched(ts.step_create(t0, 0.0), nq)
            if any(q.record for q in spec.queues)
            else None,
        )
        if spec.queues
        else None,
        resources=Resources(
            holder=jnp.full((nr,), -1, _I),
            acc=_batched(ts.step_create(t0, 0.0), nr)
            if any(r.record for r in spec.resources)
            else None,
        )
        if spec.resources
        else None,
        pools=Pools(
            level=pool_caps,
            held=jnp.zeros((np_, spec.n_procs), _R),
            held_seq=jnp.zeros((np_, spec.n_procs), _I),
            next_seq=jnp.zeros((np_,), _I),
            acc=_batched(ts.step_create(t0, 0.0), np_)
            if any(pl.record for pl in spec.pools)
            else None,
        )
        if spec.pools
        else None,
        buffers=Buffers(
            level=buf_init,
            # the recorded signal starts at each buffer's *initial* level,
            # not 0 — otherwise time-average levels are biased low
            acc=_batched(ts.step_create(t0, 0.0), nb)._replace(
                last_v=buf_init
            )
            if any(b.record for b in spec.buffers)
            else None,
        )
        if spec.buffers
        else None,
        pqueues=PQueues(
            items=jnp.zeros((npq, spec.pqueue_cap_max), _R),
            prio=jnp.zeros((npq, spec.pqueue_cap_max), _R),
            seq=jnp.zeros((npq, spec.pqueue_cap_max), _I),
            live=jnp.zeros((npq, spec.pqueue_cap_max), jnp.bool_),
            next_seq=jnp.zeros((npq,), _I),
            acc=_batched(ts.step_create(t0, 0.0), npq)
            if any(q.record for q in spec.pqueues)
            else None,
        )
        if spec.pqueues
        else None,
        user=user,
        done=jnp.asarray(False),
        err=jnp.where(
            events.overflow,
            jnp.asarray(ERR_EVENT_OVERFLOW, _I),
            jnp.zeros((), _I),
        ),
        n_events=jnp.zeros((), config.COUNT),
        boundary_pending=jnp.asarray(False),
        # observability state is trace-time gated like the logger mask:
        # disabled (the default) carries no arrays at all
        trace=obs_trace.create() if obs_trace.enabled() else None,
        metrics=obs_metrics.create(
            N_KINDS + len(spec.user_handlers), len(spec.queues)
        )
        if obs_metrics.enabled()
        else None,
        t_stop=None if t_stop is None else jnp.asarray(t_stop, _T),
    )


# --- micro-ops on Sim --------------------------------------------------------


class _ConstTable:
    """Tiny static per-component table indexed by a traced id.

    Emits a select chain over scalar literals instead of materializing an
    array constant: Pallas kernels cannot capture array constants, and for
    the 1–8 entries these tables have, a literal select chain is also
    cheaper than a dynamic-slice gather on the VPU.  Behaves like the
    1-D array it replaces for ``tab[idx]`` with an int or traced index.
    """

    def __init__(self, values, dtype):
        self._values = list(values)
        self._dtype = dtype

    def __len__(self):
        return len(self._values)

    def __getitem__(self, idx):
        vals = self._values
        if isinstance(idx, int):
            return jnp.asarray(vals[idx], self._dtype)
        out = jnp.asarray(vals[0], self._dtype)
        for j in range(1, len(vals)):
            out = jnp.where(
                idx == j, jnp.asarray(vals[j], self._dtype), out
            )
        return out


_kfori = dyn.kfori  # scan-free counted loop (see dyn.kfori docstring)


def _bounded_while(cond, body, init, bound: int):
    """``lax.while_loop`` with a trip-count backstop in kernel mode.

    In kernel mode the emitted while keeps the REAL data-dependent
    condition (per-lane at trace time); lanelast's batched-cond rule
    lowers it as a scalar any-lane-live condition with per-lane freeze
    masking — so the loop exits after max-over-lanes iterations instead
    of always running ``bound`` masked steps (the chain loop's bound is
    ``spec.max_chain``=16 by default while real chains are 2-3 blocks:
    measured ~5x of pure hot-loop waste before this).  ``bound`` remains
    as the runaway backstop the callers' error codes check."""
    if not config.KERNEL_MODE:
        return lax.while_loop(cond, body, init)

    def wcond(kc):
        k, c = kc
        return cond(c) & (k < bound)

    def wbody(kc):
        k, c = kc
        return k + jnp.int32(1), body(c)

    return lax.while_loop(wcond, wbody, (jnp.int32(0), init))[1]


def _and(pred, gate):
    """AND a handler's write predicate with its branch gate; ``True``
    short-circuits so ungated paths trace zero extra ops."""
    if gate is True:
        return pred
    if pred is True:
        return gate
    return pred & gate


def _gated(fn):
    """Mark a branch as SELF-GATED for :func:`_vswitch`: it accepts a
    ``gate`` keyword and guarantees its output Sim is identical to its
    input Sim wherever ``gate`` is false (every write pred-gated).
    _vswitch then composes gated branches sequentially under their
    exclusive selection predicates instead of select-merging their
    outputs — zero merge ops for the Sim."""
    fn.self_gated = True
    return fn


def _vswitch(idx, branches, *args):
    """``lax.switch`` for the vmapped interpreter.  Under vmap a
    lax.switch executes every traced branch anyway, but lowers to an
    N-ary ``select_n`` which Mosaic rejects (only 2-way selects).  Two
    strategies replace the merge:

    * branches marked with :func:`_gated` (the internal command
      handlers) are composed SEQUENTIALLY, each fully pred-gated by its
      exclusive selection predicate — the inactive handlers' writes are
      runtime no-ops, so the chain needs no Sim merge at all.  A later
      branch's reads can see an earlier branch's traced writes, but
      whenever the later branch is the selected one those writes were
      gated off — the composition is exact.
    * unmarked branches (user blocks) are evaluated against the base
      args and select-merged per leaf, folding only over the branches
      that actually changed each leaf (identity test).  A table is
      either all-gated or all-ungated; mixing raises.

    The branch predicates are exclusive and exhaustive (every caller
    clips or LUT-maps ``idx`` into range).  Outside kernel mode the real
    lax.switch is kept: an *unbatched* run then executes only the
    selected branch (side effects like debug callbacks fire once, and
    scalar oracle runs stay cheap; gated handlers see gate=True there).
    """
    if not config.KERNEL_MODE:
        return lax.switch(idx, branches, *args)
    # dedupe identical branch callables: the dispatch table aliases the
    # same handler at several indices (K_PROC and K_TIMER both run
    # on_proc), and tracing it per alias would duplicate the entire chain
    # loop in the hot kernel (measured: 2x the step body for any model
    # with no user handlers)
    uniq: list = []
    index_sets: list = []
    for j, b in enumerate(branches):
        for u, (ub, idxs) in enumerate(zip(uniq, index_sets)):
            if ub is b:
                idxs.append(j)
                break
        else:
            uniq.append(b)
            index_sets.append([j])
    idx = jnp.asarray(idx, _I)
    if len(uniq) == 1:
        # exhaustive single branch: always selected (gate stays True)
        return uniq[0](*args)
    sels = []
    for idxs in index_sets:
        s = idx == idxs[0]
        for j in idxs[1:]:
            s = s | (idx == j)
        sels.append(s)

    is_gated = [getattr(b, "self_gated", False) for b in uniq]
    base_sim = args[0]
    cur = base_sim
    outs = []
    for u, b in enumerate(uniq):
        if is_gated[u]:
            o = b(cur, *args[1:], gate=sels[u])
            cur = o[0] if isinstance(o, tuple) else o
            outs.append(o)
        else:
            outs.append(b(*args))

    flat0, treedef = jax.tree.flatten(outs[0])
    flats = [flat0]
    for u, o in enumerate(outs[1:], 1):
        fl, td = jax.tree.flatten(o)
        if td != treedef:
            raise TypeError(
                f"_vswitch branch {u} returned a different pytree "
                f"structure than branch 0:\n{td}\nvs\n{treedef}"
            )
        flats.append(fl)

    if any(is_gated):
        # gated tables must be all-gated: the Sim result is the chain's
        # output and needs NO merge; non-Sim positions (yielded flags)
        # still select over every branch below.  (A mixed gated/ungated
        # table has no call site — fail loudly rather than run an
        # unexercised merge semantics.)
        if not all(is_gated):
            raise TypeError(
                "_vswitch: mixed gated/ungated branch table is not "
                "supported — gate all branches or none"
            )
        flat_cur = jax.tree.flatten(cur)[0]
        n_sim = len(flat_cur)
    else:
        flat_cur = []
        n_sim = 0

    merged = []
    for pos, leaf_vals in enumerate(zip(*flats)):
        if pos < n_sim:
            merged.append(flat_cur[pos])
            continue
        groups: list = []  # (value, [branch indices]) by identity
        for u, v in enumerate(leaf_vals):
            for gv, gus in groups:
                if gv is v:
                    gus.append(u)
                    break
            else:
                groups.append((v, [u]))
        if len(groups) == 1:
            merged.append(leaf_vals[0])
            continue
        # the value shared by the most branches is the select default
        # (fewest selects); ties break on first occurrence for a stable
        # trace
        groups.sort(key=lambda g: -len(g[1]))
        res = groups[0][0]
        for gv, gus in groups[1:]:
            s = sels[gus[0]]
            for u in gus[1:]:
                s = s | sels[u]
            res = dyn.bwhere(s, gv, res)
        merged.append(res)
    return jax.tree.unflatten(treedef, merged)


def _check_gated_noop(name: str, h, sim: Sim, tag: int) -> None:
    """Eagerly run one self-gated handler with ``gate=False`` on a
    CONCRETE Sim and assert the output is bitwise identical — the
    invariant :func:`_vswitch`'s zero-merge sequential composition rests
    on.  A handler with one ungated write corrupts *other lanes'* state
    only under vmap, far from the cause; this fails loudly at the
    handler, by name and leaf path."""
    import numpy as np

    cmd = pr.Command(
        jnp.asarray(tag, _I),
        jnp.asarray(0.5, _R),
        jnp.asarray(0.25, _R),
        jnp.asarray(0.125, _R),
        jnp.zeros((), _I),
        jnp.zeros((), _I),
    )
    out = h(
        sim, jnp.zeros((), _I), cmd, jnp.asarray(False),
        gate=jnp.zeros((), jnp.bool_),
    )
    sim2 = out[0] if isinstance(out, tuple) else out
    flat, _ = jax.tree_util.tree_flatten_with_path(sim)
    flat2 = jax.tree.leaves(sim2)
    for (path, a), b in zip(flat, flat2):
        a, b = np.asarray(a), np.asarray(b)
        same = (
            np.array_equal(a, b, equal_nan=True)
            if np.issubdtype(a.dtype, np.inexact)
            else np.array_equal(a, b)
        )
        if not same:
            raise AssertionError(
                f"gated handler {name!r} (tag {tag}) is not a no-op "
                f"under gate=False: Sim leaf "
                f"{jax.tree_util.keystr(path)} changed — every write in "
                "a _gated handler must be pred-gated by its gate"
            )


def validate_gated_handlers(spec: ModelSpec, sim: Sim) -> None:
    """Debug-tier structural check over the full handler table: every
    self-gated command handler must leave a concrete Sim bitwise
    untouched under ``gate=False``.  Traced nowhere — runs eagerly on
    one per-lane Sim, once per kernel build (pallas_run wires it behind
    the dbc debug tier), so the invariant the fuzz battery only samples
    is enforced structurally.

    Checked once per DISPATCH SLOT, not per unique handler: an aliased
    handler (h_queue at put/get/put_hold/get_hold, h_buffer at both
    verbs) branches internally on cmd.tag, and an ungated write on the
    get side would be invisible under the put tag.  Eager and
    once-per-build, so the aliased repeats cost nothing that matters."""
    apply = _make_apply(spec, None)
    for tag, h in apply.handler_items:
        if not getattr(h, "self_gated", False):
            continue
        _check_gated_noop(
            getattr(h, "__name__", repr(h)), h, sim, tag
        )


def _set_err(sim: Sim, pred, code) -> Sim:
    return sim._replace(
        err=jnp.where((sim.err == 0) & pred, jnp.asarray(code, _I), sim.err)
    )


def _schedule_if(sim: Sim, pred, t, prio, kind, subj, arg) -> Sim:
    es2, _ = ev.schedule(sim.events, t, prio, kind, subj, arg)
    es2 = _tree_select(pred, es2, sim.events)
    sim = sim._replace(events=es2)
    return _set_err(sim, es2.overflow, ERR_EVENT_OVERFLOW)


def _schedule_wake(sim: Sim, pred, p, sig, t=None) -> Sim:
    """Arm a resume for process p at ``t`` (default: now).  Dense wake
    slot — at most one resume per process exists, every caller follows
    the cancel-before-rearm discipline, so the overwrite is safe.  A
    non-finite target time fails the replication (the general table's
    overflow-as-failure parity)."""
    t = sim.clock if t is None else t
    wk2, ok = ev.wake_set(sim.wakes, p, t, sig, sim.events.next_seq, pred)
    sim = sim._replace(
        wakes=wk2,
        events=sim.events._replace(
            next_seq=sim.events.next_seq + ok.astype(_I)
        ),
    )
    armed = pred if pred is not True else jnp.asarray(True)
    return _set_err(sim, armed & ~ok, ERR_EVENT_OVERFLOW)


def _guard_signal(sim: Sim, gid, pred=True, spec=None) -> Sim:
    """Wake the best waiter (if any): schedule its retry at the current
    time with its process priority (parity: cmb_resourceguard_signal
    scheduling wakeup events rather than switching directly).  ``pred``
    gates the whole signal (lets handlers run straight-line with masked
    writes instead of a whole-Sim branch select).

    Observer forwarding (parity: cmb_resourceguard_register,
    `src/cmb_resourceguard.c:313-330`): when ``spec`` is supplied and a
    condition declares ``observes`` covering this guard, the signal also
    re-evaluates that condition's waiters — so a release/put/rollback
    satisfying a predicate wakes its cond_wait-ers without the model
    signalling manually.  Observer-free models trace zero extra ops."""
    pid, found = gd.best_waiter(
        sim.procs.pend_guard, sim.procs.pend_seq, sim.procs.prio, gid
    )
    woke = found if pred is True else (found & pred)
    p = jnp.maximum(pid, 0)
    # dequeue = clearing membership; the pend_* command fields stay for
    # the woken process's retry (retry-keeps-seq reads pend_seq)
    sim = sim._replace(
        procs=sim.procs._replace(
            pend_guard=dyn.dset(sim.procs.pend_guard, p, -1, woke)
        )
    )
    sim = _schedule_wake(sim, woke, p, pr.SUCCESS)
    if spec is not None:
        for c in spec.conditions:
            if not c.observes:
                continue
            # membership of THIS (possibly traced) gid in the observed
            # set, as a const table lookup; forwarding is gated by the
            # same pred as the signal itself
            obs = _ConstTable(
                [1 if g in c.observes else 0 for g in range(spec.n_guards)],
                jnp.int32,
            )
            fire = obs[jnp.asarray(gid, _I)] != 0
            if pred is not True:
                fire = fire & pred
            sim = cond_signal(spec, sim, c.id, pred=fire)
    return sim


def _guard_wait(sim: Sim, p, gid, cmd: pr.Command, is_retry=False,
                pred=True) -> Sim:
    """Pend the blocked command, enqueue on the guard, and advance pc to
    the continuation (signals deliver there if the wait is aborted).
    ``pred`` gates every write (see _guard_signal).

    A retry re-enqueues with the process's original FIFO sequence so a
    woken-but-unsatisfied waiter keeps its place (no starvation; parity
    with the reference's evaluate-the-front-without-dequeuing signals)."""
    seq_override = jnp.where(
        jnp.asarray(is_retry), dyn.dget(sim.procs.pend_seq, p), jnp.asarray(-1, _I)
    )
    g2, seq = gd.alloc_seq(sim.guards, gid, seq_override, pred)
    # membership IS pend_guard (dense guards): the pend bookkeeping below
    # is the whole enqueue; nothing can overflow (capacity = P)
    # one grouped write: every pend field lands at the same pid under the
    # same gate, so the scan-over-rows arm serves all nine from a single
    # block loop (dense mode is the per-field dset sequence, unchanged)
    (pend_tag, pend_f, pend_f2, pend_f3, pend_i, pend_pc, pend_guard,
     pend_seq, pc) = dyn.dset_tree(
        (sim.procs.pend_tag, sim.procs.pend_f, sim.procs.pend_f2,
         sim.procs.pend_f3, sim.procs.pend_i, sim.procs.pend_pc,
         sim.procs.pend_guard, sim.procs.pend_seq, sim.procs.pc),
        p,
        (cmd.tag, cmd.f, cmd.f2, cmd.f3, cmd.i, cmd.next_pc,
         jnp.asarray(gid, _I), seq, cmd.next_pc),
        pred,
    )
    procs = sim.procs._replace(
        pend_tag=pend_tag, pend_f=pend_f, pend_f2=pend_f2, pend_f3=pend_f3,
        pend_i=pend_i, pend_pc=pend_pc, pend_guard=pend_guard,
        pend_seq=pend_seq, pc=pc,
    )
    return sim._replace(procs=procs, guards=g2)


def _clear_pend(sim: Sim, p, pred=True) -> Sim:
    pend_tag, pend_guard = dyn.dset_tree(
        (sim.procs.pend_tag, sim.procs.pend_guard), p,
        (pr.NO_PEND, -1), pred,
    )
    return sim._replace(
        procs=sim.procs._replace(pend_tag=pend_tag, pend_guard=pend_guard)
    )


def _record_row(acc: ts.StepAccum, row, t, v, pred=True) -> ts.StepAccum:
    """step_record on one row of a batched StepAccum, gated by ``pred``."""
    one = dyn.dget_tree(acc, row)
    upd = ts.step_record(one, t, v)
    return dyn.dset_tree(acc, row, upd, pred)


def _record_row_if(flags, acc, row, t, v, pred=True):
    """Recording gated by per-component static flags: traces to nothing
    when no component records (parity: the reference's optional recording
    — a documented hot-loop cost), and to a masked update when only some
    do."""
    if acc is None or not any(flags):
        return acc
    rec = _record_row(acc, row, t, v, pred)
    if all(flags):
        return rec
    # int table compared != 0: a bool _ConstTable would emit i1 select
    # chains, which Mosaic cannot lower in kernel mode
    mask = _ConstTable([int(bool(f)) for f in flags], jnp.int32)[row] != 0
    return _tree_select(mask, rec, acc)


def _cancel_wake(sim: Sim, p, pred=True) -> Sim:
    """Cancel p's outstanding resume (a no-op if none is armed).  The
    analog of cancelling a stale hold timer (`src/cmb_process.c:344-349`)."""
    return sim._replace(wakes=ev.wake_clear(sim.wakes, p, pred))


def _unwait(spec: ModelSpec, sim: Sim, p, pred=True) -> Sim:
    """Detach p from whatever it waits on: guard membership, pending
    command, wake event (parity: cmi_process_cancel_awaiteds,
    `src/cmb_process.c:694-748`).  Dense guards: clearing ``pend_guard``
    (done by _clear_pend) IS the guard removal.  Statics: bookkeeping a
    model's command set cannot populate stays out of the trace."""
    if _may_pend(spec, sim):
        sim = _clear_pend(sim, p, pred)
    sim = _cancel_wake(sim, p, pred)
    procs = sim.procs
    if _may_wait_procs(spec, sim):
        procs = procs._replace(
            await_pid=dyn.dset(procs.await_pid, p, -1, pred)
        )
    if _may_wait_events(spec, sim):
        procs = procs._replace(
            await_evt=dyn.dset(procs.await_evt, p, -1, pred)
        )
    return sim._replace(procs=procs)


def _scan_evt_waiters(sim: Sim, decide) -> Sim:
    """Shared waiter scan, fully vectorized: ``decide(sim, h_vec[P]) ->
    (wake_vec, sig_vec)`` elementwise over every process's awaited
    handle; woken waiters get their dense wake slot armed (FIFO seqs in
    pid order, like the mass-wake in _wake_waiters) and their await
    cleared.  (The per-pid counted loop this replaces ran P masked
    [P]-wide iterations per step for wait_event models — O(P^2).)"""
    h = sim.procs.await_evt
    awaiting = (h >= 0) & (sim.procs.status == pr.RUNNING)
    wake, sig = decide(sim, h)
    wake = wake & awaiting
    sim = _mass_wake(sim, wake, sig)
    return sim._replace(
        procs=sim.procs._replace(
            await_evt=jnp.where(wake, jnp.asarray(-1, _I), h)
        ),
    )


def _dispatch_evt_wakes(sim: Sim, handle, found, pred=True) -> Sim:
    """Wake processes waiting on the just-popped event with SUCCESS —
    before its action runs, like the reference (`src/cmb_event.c:312-314`)
    — and, as the lazy arm of the cancel protocol, any waiter whose awaited
    handle has died (pattern-cancelled timers etc.) with CANCELLED.

    ``pred`` suppresses the WHOLE scan (both arms) for a step that defers
    a boundary dispatch: even the stale arm must wait, because its wake
    would be armed at the un-advanced clock and dispatch AHEAD of the
    deferred event — the host-side XLA step re-runs the scan in order."""

    def decide(sim, h):
        fired = found & (h == handle)
        stale = ~fired & ~ev._valid_vec(sim.events, h)
        wake = fired | stale
        if pred is not True:
            wake = wake & pred
        return wake, jnp.where(fired, pr.SUCCESS, pr.CANCELLED).astype(_I)

    return _scan_evt_waiters(sim, decide)


def _cancel_evt_wakes(sim: Sim, handle, pred) -> Sim:
    """Wake waiters of a just-cancelled event with CANCELLED immediately
    (the eager arm; parity: the reference wakes waiter lists at cancel)."""

    def decide(sim, h):
        return (
            jnp.asarray(pred) & (h == handle),
            jnp.asarray(pr.CANCELLED, _I),
        )

    return _scan_evt_waiters(sim, decide)


def _exclusive_rank(mask):
    """[P] bool -> [P] i32: for each true element, how many true elements
    precede it (pid-ascending).  Log-doubling prefix sum built from
    concatenate+slice (both have lanelast/Mosaic rules; lax.cumsum's
    lowering does not)."""
    x = mask.astype(_I)
    n = x.shape[0]
    inc = x
    shift = 1
    while shift < n:
        inc = inc + lax.concatenate(
            [jnp.zeros((shift,), _I), lax.slice(inc, (0,), (n - shift,))],
            dimension=0,
        )
        shift *= 2
    return inc - x


def _mass_wake(sim: Sim, mask, sig) -> Sim:
    """Arm the dense wake slot of every process in ``mask`` at the
    current clock, assigning FIFO seqs in pid order — the contract both
    waiter-wake paths (WAIT_PROC and wait_event) must share.  The count
    dtype is pinned: under x64, jnp.sum would promote i32 -> i64."""
    base = sim.events.next_seq
    n_woken = jnp.sum(mask.astype(_I), dtype=_I)
    wk = sim.wakes
    wk2 = ev.Wakes(
        time=jnp.where(mask, sim.clock, wk.time),
        sig=jnp.where(mask, jnp.asarray(sig, _I), wk.sig),
        seq=jnp.where(mask, base + _exclusive_rank(mask), wk.seq),
    )
    return sim._replace(
        wakes=wk2,
        events=sim.events._replace(next_seq=base + n_woken),
    )


def _wake_waiters(spec: ModelSpec, sim: Sim, target, sig, pred=True) -> Sim:
    """Wake every process waiting on `target` finishing (WAIT_PROC) — one
    vectorized mass-arm of the dense wake table.  (The per-pid loop this
    replaces cost O(P^2) per event at AWACS scale: its [P]-wide body ran
    P masked iterations inside every chain step.)  Seqs are assigned in
    pid order among the woken, exactly as the loop did.

    Statically absent from models that never issue C_WAIT_PROC:
    ``await_pid`` is then always -1, so the scan plus its prefix-rank
    seq assignment (~45 [P]-wide ops per exit) can wake no one."""
    if not _may_wait_procs(spec, sim):
        return sim
    waiting = (sim.procs.await_pid == jnp.asarray(target, _I)) & (
        sim.procs.status == pr.RUNNING
    )
    if pred is not True:
        waiting = waiting & pred
    sim = _mass_wake(sim, waiting, sig)
    return sim._replace(
        procs=sim.procs._replace(
            await_pid=jnp.where(
                waiting, jnp.asarray(-1, _I), sim.procs.await_pid
            )
        ),
    )


def _abort_cleanup(spec: ModelSpec, sim: Sim, p, pend: pr.Command, sig,
                   pred=True) -> Sim:
    """Command-specific cleanup when a pended wait is aborted:

    * pool acquire: roll the holding back to its pre-call amount and
      return the difference (parity: the INTERRUPTED unwind in
      cmi_pool_acquire_inner) — except on PREEMPTED, where a mugger
      already took everything;
    * buffer get/put: keep the partial amount and report the obtained/
      deposited quantity in the result register (partial-fulfillment
      contract, `src/cmb_buffer.c:194-346`)."""
    sig = jnp.asarray(sig, _I)
    if spec.pools:
        p_guard_c = _ConstTable([pl.guard for pl in spec.pools], _I)
        p_rec_c = [pl.record for pl in spec.pools]
        p_cap_c = _ConstTable([pl.capacity for pl in spec.pools], _R)
        k = jnp.clip(pend.i, 0, len(spec.pools) - 1)
        is_pool = (pend.tag == pr.C_POOL_ACQ) | (pend.tag == pr.C_POOL_PRE)
        do_rb = is_pool & (sig != pr.PREEMPTED)
        if pred is not True:
            do_rb = do_rb & pred
        excess = jnp.maximum(dyn.dget2(sim.pools.held, k, p) - pend.f2, 0.0)
        rb = sim._replace(
            pools=sim.pools._replace(
                level=dyn.dadd(sim.pools.level, k, excess),
                held=dyn.dadd2(sim.pools.held, k, p, -excess),
                acc=_record_row_if(
                    p_rec_c, sim.pools.acc, k, sim.clock,
                    p_cap_c[k] - (dyn.dget(sim.pools.level, k) + excess),
                ),
            )
        )
        rb = _guard_signal(rb, p_guard_c[k], spec=spec)
        sim = _tree_select(do_rb, rb, sim)
    if spec.buffers:
        is_buf = (pend.tag == pr.C_BUF_GET) | (pend.tag == pr.C_BUF_PUT)
        if pred is not True:
            is_buf = is_buf & pred
        obtained = pend.f2 - pend.f
        sim = sim._replace(
            procs=sim.procs._replace(
                got=dyn.dset(sim.procs.got, p, obtained, is_buf)
            )
        )
    return sim


def _abort_wait(spec: ModelSpec, sim: Sim, p, sig, pred=True) -> Sim:
    """Abort whatever p is waiting on AND run the command-specific abort
    cleanup (pool rollback, buffer partial-fulfillment report).  Every
    wait-aborting path — timer/interrupt delivery, preemption, mugging,
    stop — must come through here; clearing the pend without the cleanup
    silently breaks the rollback/partial-fulfillment contracts."""
    if not _may_pend(spec, sim):
        # nothing can ever pend: no snapshot, no command-specific
        # cleanup — unwait is the whole abort
        return _unwait(spec, sim, p, pred)
    pend = pr.Command(*dyn.dget_tree(
        (sim.procs.pend_tag, sim.procs.pend_f, sim.procs.pend_f2,
         sim.procs.pend_f3, sim.procs.pend_i, sim.procs.pend_pc), p,
    ))
    # _abort_cleanup self-gates on pend.tag, so NO_PEND is a clean no-op
    return _abort_cleanup(
        spec, _unwait(spec, sim, p, pred), p, pend, sig, pred=pred
    )


def finish_process(spec: ModelSpec, sim: Sim, p, exit_sig, pred=True) -> Sim:
    """Terminate process p: status, waiter wakeup, resource cleanup
    (parity: kill semantics — drop resources, cancel awaits, wake waiters,
    `src/cmb_process.c:776-828`).  Every write is gated by ``pred`` so
    h_exit can run straight-line under its branch gate."""
    r_guard = _ConstTable([r.guard for r in spec.resources] or [0], _I)
    p_guard = _ConstTable([pl.guard for pl in spec.pools] or [0], _I)
    p_cap = _ConstTable([pl.capacity for pl in spec.pools] or [0.0], _R)

    r_rec = [r.record for r in spec.resources]
    p_rec = [pl.record for pl in spec.pools]

    sim = _abort_wait(spec, sim, p, exit_sig, pred=pred)
    # cancel any outstanding timers aimed at p
    es2, _ = ev.pattern_cancel(sim.events, kind=K_TIMER, subj=p, pred=pred)
    sim = sim._replace(events=es2)
    sim = sim._replace(
        procs=sim.procs._replace(
            status=dyn.dset(sim.procs.status, p, pr.FINISHED, pred),
            exit_sig=dyn.dset(
                sim.procs.exit_sig, p, jnp.asarray(exit_sig, _I), pred
            ),
        )
    )
    sim = _wake_waiters(spec, sim, p, exit_sig, pred=pred)

    # drop binary resources held by p (holdable drop protocol)
    def drop_res(rid, sim):
        held = dyn.dget(sim.resources.holder, rid) == p
        if pred is not True:
            held = held & pred
        r2 = Resources(
            holder=dyn.dset(sim.resources.holder, rid, -1, held),
            acc=_record_row_if(
                r_rec, sim.resources.acc, rid, sim.clock, 0.0, held
            ),
        )
        sim = sim._replace(resources=r2)
        return _guard_signal(sim, r_guard[rid], pred=held, spec=spec)

    # pool units held by p return to the pool
    def drop_pool(k, sim):
        amt = dyn.dget2(sim.pools.held, k, p)
        has = amt > 0.0
        if pred is not True:
            has = has & pred
        p2 = sim.pools._replace(
            level=dyn.dadd(sim.pools.level, k, amt, has),
            held=dyn.dset2(sim.pools.held, k, p, 0.0, has),
            acc=_record_row_if(
                p_rec, sim.pools.acc, k, sim.clock,
                p_cap[k] - (dyn.dget(sim.pools.level, k) + amt), has,
            ),
        )
        sim = sim._replace(pools=p2)
        return _guard_signal(sim, p_guard[k], pred=has, spec=spec)

    if spec.resources:
        sim = _kfori(0, sim.resources.holder.shape[0], drop_res, sim)
    if spec.pools:
        sim = _kfori(0, sim.pools.level.shape[0], drop_pool, sim)
    return sim


# --- inter-process verbs (callable from blocks via core.api) -----------------


def interrupt(spec: ModelSpec, sim: Sim, target, sig) -> Sim:
    """Deliver ``sig`` to a waiting process NOW, aborting whatever it waits
    on (parity: cmb_process_interrupt, `include/cmb_process.h:406`)."""
    target = jnp.asarray(target, _I)
    alive = dyn.dget(sim.procs.status, target) == pr.RUNNING
    sim = _abort_wait(spec, sim, target, sig, pred=alive)
    return _schedule_wake(sim, alive, target, jnp.asarray(sig, _I))


def stop_process(spec: ModelSpec, sim: Sim, target) -> Sim:
    """Kill a process (parity: cmb_process_stop, `src/cmb_process.c:803-828`):
    drops its resources, cancels its waits/timers, wakes its waiters with
    STOPPED."""
    target = jnp.asarray(target, _I)
    alive = dyn.dget(sim.procs.status, target) == pr.RUNNING
    return finish_process(spec, sim, target, pr.STOPPED, pred=alive)


def release_resource(spec: ModelSpec, sim: Sim, p, rid, pred=True) -> Sim:
    """Release binary resource ``rid`` held by ``p`` inline — the body of
    the C_RELEASE handler, callable from a block (via api.release) so a
    release costs ZERO chain iterations: it never blocks and never
    yields, so making it a command spent a full masked kernel body pass
    per call just to run these few writes (the reference's plain
    function call, `src/cmb_resource.c:249-273`, had the same
    insight — only waits go through the scheduler)."""
    rid = jnp.asarray(rid, _I)
    r_guard = _ConstTable([r.guard for r in spec.resources] or [0], _I)
    r_rec = [r.record for r in spec.resources]
    owner_ok = dyn.dget(sim.resources.holder, rid) == p
    r2 = Resources(
        holder=dyn.dset(sim.resources.holder, rid, -1, pred),
        acc=_record_row_if(
            r_rec, sim.resources.acc, rid, sim.clock, 0.0, pred
        ),
    )
    sim = sim._replace(resources=r2)
    sim = _guard_signal(sim, r_guard[rid], pred=pred, spec=spec)
    return _set_err(sim, _and(~owner_ok, pred), ERR_BAD_RELEASE)


def release_pool(spec: ModelSpec, sim: Sim, p, k, amount, pred=True) -> Sim:
    """Release ``amount`` units of pool ``k`` inline (parity:
    cmb_resourcepool_release; partial release allowed) — the body of the
    C_POOL_REL handler, callable from a block via api.pool_release (see
    :func:`release_resource` for why inline releases are free)."""
    k = jnp.asarray(k, _I)
    p_guard = _ConstTable([pl.guard for pl in spec.pools] or [0], _I)
    p_cap = _ConstTable([pl.capacity for pl in spec.pools] or [0.0], _R)
    p_rec = [pl.record for pl in spec.pools]
    amount = jnp.asarray(amount, _R)
    amt = jnp.minimum(amount, dyn.dget2(sim.pools.held, k, p))  # partial ok
    # profile-scaled ownership tolerance: held amounts accumulate in
    # REAL, so the release check must forgive rounding at REAL's
    # resolution (a fixed 1e-12 is below f32 eps and would degenerate
    # to exact compare under the kernel profile); floored at the
    # historical 1e-12 — held carries absolute error from its past
    # magnitude, not amount's, so the relative term alone would be
    # tighter than the old constant on f64
    tol = jnp.maximum(
        64.0 * float(jnp.finfo(config.REAL_DTYPE).eps) * jnp.maximum(
            jnp.asarray(1.0, config.REAL_DTYPE), jnp.abs(amount)
        ),
        jnp.asarray(1e-12, config.REAL_DTYPE),
    )
    owner_ok = dyn.dget2(sim.pools.held, k, p) >= amount - tol
    in_use = p_cap[k] - (dyn.dget(sim.pools.level, k) + amt)
    p2 = sim.pools._replace(
        level=dyn.dadd(sim.pools.level, k, amt, pred),
        held=dyn.dadd2(sim.pools.held, k, p, -amt, pred),
        acc=_record_row_if(
            p_rec, sim.pools.acc, k, sim.clock, in_use, pred
        ),
    )
    sim = sim._replace(pools=p2)
    sim = _guard_signal(sim, p_guard[k], pred=pred, spec=spec)
    return _set_err(sim, _and(~owner_ok, pred), ERR_BAD_RELEASE)


def spawn_process(sim: Sim, pt, at=None, prio=None):
    """Activate one row of a spawn pool (a process type declared with
    ``start=False``); returns ``(sim, pid)`` with pid == -1 when every
    row of the pool is currently RUNNING.

    The jit answer to runtime process creation
    (`cmb_process_create`/`cmb_process_start`,
    `include/cmb_process.h:119-180`): the pool's rows are declared
    statically, activation picks the lowest-pid CREATED-or-FINISHED row,
    resets its per-process state, and arms its entry wake at ``at``
    (default: now).  FINISHED rows are recycled — their timers were
    pattern-cancelled and waiters woken at exit, so reuse is clean."""
    lo, n = pt.first_pid, pt.count
    if lo < 0:
        raise ValueError("spawn_process needs a built model's ProcessType")
    pididx = jnp.arange(sim.procs.pc.shape[0], dtype=_I)
    in_pool = (pididx >= lo) & (pididx < lo + n)
    free = in_pool & (
        (sim.procs.status == pr.CREATED)
        | (sim.procs.status == pr.FINISHED)
    )
    found = jnp.any(free)
    # lowest free pid — iota-min, NOT argmax: several rows tie at True
    # and Mosaic's argmax tie-break differs from XLA's lowest-index rule
    # (the first on-device fuzz divergence — dyn.first_true32)
    slot = dyn.first_true32(free).astype(_I)
    p = jnp.where(found, slot, 0)
    new_prio = jnp.asarray(pt.prio if prio is None else prio, _I)
    (status, pc, prio, got, exit_sig, await_pid, await_evt, pend_tag,
     pend_guard, locals_f, locals_i) = dyn.dset_tree(
        (sim.procs.status, sim.procs.pc, sim.procs.prio, sim.procs.got,
         sim.procs.exit_sig, sim.procs.await_pid, sim.procs.await_evt,
         sim.procs.pend_tag, sim.procs.pend_guard, sim.procs.locals_f,
         sim.procs.locals_i),
        p,
        (pr.RUNNING, pt.entry_pc, new_prio, 0.0, 0, -1, -1, pr.NO_PEND,
         -1, 0.0, 0),
        found,
    )
    procs = sim.procs._replace(
        status=status, pc=pc, prio=prio, got=got, exit_sig=exit_sig,
        await_pid=await_pid, await_evt=await_evt, pend_tag=pend_tag,
        pend_guard=pend_guard, locals_f=locals_f, locals_i=locals_i,
    )
    sim = sim._replace(procs=procs)
    t = sim.clock if at is None else jnp.asarray(at, _T)
    sim = _schedule_wake(sim, found, p, pr.SUCCESS, t=t)
    return sim, jnp.where(found, slot, jnp.asarray(-1, _I))


def timer_add(sim: Sim, p, dur, sig):
    """Schedule a timer delivering ``sig`` to p after ``dur`` (parity:
    cmb_process_timer_add); returns (sim, handle)."""
    es2, handle = ev.schedule(
        sim.events, sim.clock + jnp.maximum(jnp.asarray(dur, _T), 0.0),
        dyn.dget(sim.procs.prio, p), K_TIMER, p, sig,
    )
    sim = sim._replace(events=es2)
    return _set_err(sim, es2.overflow, ERR_EVENT_OVERFLOW), handle


def timer_cancel(sim: Sim, handle, spec: Optional[ModelSpec] = None):
    """Cancel a timer (or any event) by handle (parity:
    cmb_process_timer_cancel / cmb_event_cancel); returns (sim, existed).

    When ``spec`` is passed and the model can wait on events, processes
    waiting on this handle wake with CANCELLED immediately (without it
    they still wake, lazily, at the next dispatch — see
    _dispatch_evt_wakes)."""
    es2, ok = ev.cancel(sim.events, handle)
    sim = sim._replace(events=es2)
    if spec is not None and _may_wait_events(spec, sim):
        sim = _cancel_evt_wakes(sim, handle, ok)
    return sim, ok


def timers_clear(sim: Sim, p) -> Sim:
    """Cancel all timers aimed at p (parity: cmb_process_timers_clear)."""
    es2, _ = ev.pattern_cancel(sim.events, kind=K_TIMER, subj=p)
    return sim._replace(events=es2)


def priority_set(sim: Sim, p, new_prio) -> Sim:
    """Change a process's priority, reshuffling its wake event and guard
    entry (parity: cmb_process_priority_set, `src/cmb_process.c:170-220`)."""
    new_prio = jnp.asarray(new_prio, _I)
    # no wake or guard touch-up: both pop_merged and gd.best_waiter read
    # procs.prio LIVE, which IS the reshuffle the reference performs here
    return sim._replace(
        procs=sim.procs._replace(prio=dyn.dset(sim.procs.prio, p, new_prio)),
    )


def _cond_satisfied(spec: ModelSpec, sim: Sim, cid, pid):
    """Evaluate condition ``cid``'s registered predicate for ``pid``.
    A static (python int) ``cid`` — the observer-forwarding path —
    traces only that condition's predicate."""
    if not spec.conditions:
        return jnp.asarray(False)
    if isinstance(cid, int):
        return jnp.asarray(spec.conditions[cid].predicate(sim, pid))
    pred_fns = [
        (lambda c: (lambda s, q: jnp.asarray(c.predicate(s, q))))(c)
        for c in spec.conditions
    ]
    return _vswitch(
        jnp.clip(jnp.asarray(cid, _I), 0, len(pred_fns) - 1), pred_fns, sim,
        pid,
    )


def cond_signal(spec: ModelSpec, sim: Sim, cid, pred=True) -> Sim:
    """Signal a condition: evaluate the predicate for every waiter and wake
    all satisfied ones (parity: cmb_condition_signal's two-pass wake-all,
    `src/cmb_condition.c:106-167`; the woken retry re-checks, so spurious
    wakeups re-wait inside the framework).  ``pred`` gates the whole
    signal (the observer-forwarding path runs straight-line, masked)."""
    if not spec.conditions:
        return sim
    if isinstance(cid, int):
        gid = spec.conditions[cid].guard
    else:
        c_guard = _ConstTable([c.guard for c in spec.conditions], _I)
        cid = jnp.asarray(cid, _I)
        gid = c_guard[cid]

    def visit(q, sim):
        # dense guards: candidate waiters are the processes themselves
        live = dyn.dget(sim.procs.pend_guard, q) == gid
        satisfied = _cond_satisfied(spec, sim, cid, q)
        wake = live & satisfied
        if pred is not True:
            wake = wake & pred
        sim = sim._replace(
            procs=sim.procs._replace(
                pend_guard=dyn.dset(sim.procs.pend_guard, q, -1, wake)
            )
        )
        return _schedule_wake(sim, wake, q, pr.SUCCESS)

    # O(P) iterations of a per-pid traced predicate (the user predicate
    # takes one pid, so it cannot be vectorized here); bodies touch [P]
    # rows, so this is the O(P^2) shape class for cond-heavy big-P
    # models — acceptable because conditions wake rarely, flagged in
    # tools/kernel_cost's audit notes
    return _kfori(0, sim.procs.pc.shape[0], visit, sim)


# --- command handlers ---------------------------------------------------------


def _infer_used_tags(spec: ModelSpec, sim: Sim):
    """The set of command tags this model's blocks can emit, collected by
    abstractly tracing every block once (constructors register their tag —
    see process._tag_collector).  Pended retries re-apply a tag a block
    emitted, so the set is closed under the dispatch protocol.  Returns
    None (= trace the full table) if any block resists abstract evaluation.
    """
    if pr._tag_collector is not None:
        return None  # nested inference (a block queried it): be conservative
    tags: set = set()
    pr._tag_collector = tags
    try:
        p0 = jnp.zeros((), _I)
        for blk in spec.blocks:
            # fresh wrapper per trace: jax.eval_shape memoizes on
            # (function, avals), and a cache hit would skip the block
            # body — the collector's side effect — entirely.  Two specs
            # sharing block functions at identical Sim avals (e.g. a
            # dataclasses.replace twin of a spec whose tags were already
            # inferred) would then "infer" an EMPTY tag set and route
            # every command to h_invalid/ERR_USER (found by the stream
            # regrow battery, pinned in tests/test_stream.py).
            jax.eval_shape(lambda *a: blk(*a), sim, p0, p0)
    except Exception:
        return None
    finally:
        pr._tag_collector = None
    return frozenset(tags)


def _used_tags_for(spec: ModelSpec, sim: Sim):
    """Memoized on the spec object itself (an id()-keyed dict would hand a
    recycled id a stale tag set after the old spec is collected)."""
    if not hasattr(spec, "_used_tags_memo"):
        spec._used_tags_memo = _infer_used_tags(spec, sim)
    return spec._used_tags_memo


def _may_wait_events(spec: ModelSpec, sim: Sim) -> bool:
    """Static: can this model issue C_WAIT_EVT?  Gates the per-dispatch
    waiter scan (an O(P) fori) out of models that never wait on events."""
    used = _used_tags_for(spec, sim)
    return used is None or pr.C_WAIT_EVT in used


def _may_wait_procs(spec: ModelSpec, sim: Sim) -> bool:
    """Static: can this model issue C_WAIT_PROC?  Gates the exit-time
    waiter mass-wake out of models that never wait on processes."""
    used = _used_tags_for(spec, sim)
    return used is None or pr.C_WAIT_PROC in used


#: command tags whose handlers can pend (block through _guard_wait) —
#: the only writers of procs.pend_tag
_PENDING_TAGS = frozenset({
    pr.C_PUT, pr.C_GET, pr.C_ACQUIRE, pr.C_PREEMPT, pr.C_POOL_ACQ,
    pr.C_POOL_PRE, pr.C_BUF_GET, pr.C_BUF_PUT, pr.C_PQ_PUT, pr.C_PQ_GET,
    pr.C_COND_WAIT, pr.C_PUT_HOLD, pr.C_GET_HOLD, pr.C_ACQ_HOLD,
    pr.C_PRE_HOLD, pr.C_POOL_ACQ_HOLD, pr.C_POOL_PRE_HOLD,
    pr.C_BUF_GET_HOLD, pr.C_BUF_PUT_HOLD, pr.C_PQ_PUT_HOLD,
    pr.C_PQ_GET_HOLD,
})


def _may_pend(spec: ModelSpec, sim: Sim) -> bool:
    """Static: can ANY command this model emits block through a guard?
    If not, ``pend_tag`` stays NO_PEND forever and resume's whole
    retry/abort arm — the pend reads, the per-chain-iteration use_pend
    merge, the clears — gates out of the trace (hold/exit-only models
    like AWACS keep only the wake bookkeeping)."""
    used = _used_tags_for(spec, sim)
    return used is None or bool(_PENDING_TAGS & set(used))


def _make_apply(spec: ModelSpec, used_tags=None):
    q_cap = _ConstTable([q.capacity for q in spec.queues] or [1], _I)
    q_front = _ConstTable([q.front_guard for q in spec.queues] or [0], _I)
    q_rear = _ConstTable([q.rear_guard for q in spec.queues] or [0], _I)
    r_guard = _ConstTable([r.guard for r in spec.resources] or [0], _I)
    p_guard = _ConstTable([p.guard for p in spec.pools] or [0], _I)
    p_cap = _ConstTable([p.capacity for p in spec.pools] or [0.0], _R)
    b_cap = _ConstTable([b.capacity for b in spec.buffers] or [0.0], _R)
    b_front = _ConstTable([b.front_guard for b in spec.buffers] or [0], _I)
    b_rear = _ConstTable([b.rear_guard for b in spec.buffers] or [0], _I)
    pq_cap = _ConstTable([q.capacity for q in spec.pqueues] or [1], _I)
    pq_front = _ConstTable([q.front_guard for q in spec.pqueues] or [0], _I)
    pq_rear = _ConstTable([q.rear_guard for q in spec.pqueues] or [0], _I)
    c_guard = _ConstTable([c.guard for c in spec.conditions] or [0], _I)
    q_rec = [q.record for q in spec.queues]
    r_rec = [r.record for r in spec.resources]
    p_rec = [pl.record for pl in spec.pools]
    b_rec = [b.record for b in spec.buffers]
    pq_rec = [q.record for q in spec.pqueues]

    def set_pc(sim, p, pc, pred=True):
        return sim._replace(
            procs=sim.procs._replace(pc=dyn.dset(sim.procs.pc, p, pc, pred))
        )

    @_gated
    def h_hold(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        dur = jnp.maximum(cmd.f, 0.0)
        sim = _schedule_wake(
            sim, gate, p, pr.SUCCESS, t=sim.clock + dur
        )
        return set_pc(sim, p, cmd.next_pc, gate), jnp.asarray(True)

    @_gated
    def h_exit(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        return (
            finish_process(spec, sim, p, pr.SUCCESS, pred=gate),
            jnp.asarray(True),
        )

    @_gated
    def h_jump(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        return set_pc(sim, p, cmd.next_pc, gate), jnp.asarray(False)

    @_gated
    def h_queue(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        """PUT and GET as ONE traced handler, aliased at both dispatch
        slots (so _vswitch traces it once).  The verbs differ only in a
        few scalar selects; sharing lets the ring's full-width ops —
        the largest line in the kernel's per-event budget — serve both:
        one one-hot, one read pass, one write pass (dyn.dexchange2: a
        get "writes back" the value it read, a bitwise no-op).  All
        writes pred-gated straight-line, as before (no whole-Sim branch
        select — each saved select is a full pass over the ring).
        """
        qid = cmd.i
        is_put = (cmd.tag == pr.C_PUT) | (cmd.tag == pr.C_PUT_HOLD)
        # fused verbs: on success the process holds cmd.f3 instead of
        # continuing inline — the whole queue cycle in ONE chain
        # iteration (process.put_hold/get_hold)
        fused = (cmd.tag == pr.C_PUT_HOLD) | (cmd.tag == pr.C_GET_HOLD)
        size = dyn.dget(sim.queues.size, qid)
        head = dyn.dget(sim.queues.head, qid)
        cap = q_cap[qid]
        # no-jump-ahead fairness (parity: src/cmb_resource.c:202-233): a
        # fresh caller must queue behind existing waiters (putters watch
        # the rear guard, getters the front); a woken caller IS the
        # dequeued front and may proceed despite others behind it
        own_gid = jnp.where(is_put, q_rear[qid], q_front[qid])
        may = is_retry | gd.is_empty(sim.procs.pend_guard, own_gid)
        blocked = jnp.where(is_put, size >= cap, size <= 0) | ~may
        ok = _and(~blocked, gate)
        ok_get = ok & ~is_put

        idx = jnp.where(is_put, (head + size) % cap, head)
        item, items2 = dyn.dexchange2(
            sim.queues.items, qid, idx, cmd.f, is_put, ok
        )
        dsz = jnp.where(is_put, 1, -1).astype(size.dtype)
        sim = sim._replace(
            queues=Queues(
                items=items2,
                head=dyn.dset(sim.queues.head, qid, (head + 1) % cap,
                              ok_get),
                size=dyn.dadd(sim.queues.size, qid, dsz, ok),
                acc=_record_row_if(
                    q_rec, sim.queues.acc, qid, sim.clock,
                    (size + dsz).astype(_R), ok,
                ),
            ),
            procs=sim.procs._replace(
                got=dyn.dset(sim.procs.got, p, item, ok_get)
            ),
        )
        if sim.metrics is not None:
            # queue-length high-water ratchet, gated by the same ok as
            # the size write (gate=False lanes write nothing — the
            # _gated no-op contract holds bitwise)
            sim = obs_metrics.on_queue_len(sim, qid, size + dsz, ok)
        # signal order preserved from the split handlers (wake seqs are
        # order-assigned): a get signals rear (space) then front
        # (leftover items); a put frees no space, so only the getter
        # side can newly be satisfiable
        sim = _guard_signal(sim, q_rear[qid], pred=ok_get, spec=spec)
        sim = _guard_signal(sim, q_front[qid], pred=ok, spec=spec)
        # fused success: hold cmd.f3 (h_hold semantics), waking at
        # next_pc — the signal seqs above come first, as they would if
        # the hold were issued by a continuation block
        sim = _schedule_wake(
            sim, _and(fused, ok), p, pr.SUCCESS,
            t=sim.clock + jnp.maximum(cmd.f3, 0.0),
        )
        # both outcomes continue at next_pc (the blocked path's signals
        # deliver there), so the pc write is gated only by the branch
        sim = set_pc(sim, p, cmd.next_pc, gate)
        sim = _guard_wait(
            sim, p, own_gid, cmd, is_retry, pred=_and(blocked, gate)
        )
        return sim, blocked | fused

    def _grab_resource(sim, p, rid, pred=True):
        r2 = Resources(
            holder=dyn.dset(sim.resources.holder, rid, p, pred),
            acc=_record_row_if(
                r_rec, sim.resources.acc, rid, sim.clock, 1.0, pred
            ),
        )
        return sim._replace(resources=r2)

    @_gated
    def h_acquire(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        rid = cmd.i
        fused = cmd.tag == pr.C_ACQ_HOLD
        free = dyn.dget(sim.resources.holder, rid) < 0
        may_grab = is_retry | gd.is_empty(sim.procs.pend_guard, r_guard[rid])
        ok = free & may_grab

        sim = _grab_resource(sim, p, rid, _and(ok, gate))
        sim = _schedule_wake(
            sim, _and(fused & ok, gate), p, pr.SUCCESS,
            t=sim.clock + jnp.maximum(cmd.f3, 0.0),
        )
        sim = set_pc(sim, p, cmd.next_pc, gate)
        sim = _guard_wait(
            sim, p, r_guard[rid], cmd, is_retry, pred=_and(~ok, gate)
        )
        return sim, ~ok | fused

    @_gated
    def h_preempt(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        """Parity: cmb_resource_preempt (`src/cmb_resource.c:275-325`) —
        grab if free; kick a holder of <= priority (it resumes with
        PREEMPTED, its pending waits cancelled); else wait like acquire.
        Straight-line: the three outcomes write disjoint state under
        exclusive predicates."""
        rid = cmd.i
        fused = cmd.tag == pr.C_PRE_HOLD
        holder = dyn.dget(sim.resources.holder, rid)
        free = holder < 0
        victim = jnp.maximum(holder, 0)
        can_kick = ~free & (dyn.dget(sim.procs.prio, p) >= dyn.dget(sim.procs.prio, victim))
        g_free = _and(free, gate)
        g_kick = _and(can_kick, gate)
        blocked = ~free & ~can_kick

        # kick path: cancel victim's awaits (incl. pool rollback /
        # buffer partial report if it was waiting on one), deliver
        # PREEMPTED
        sim = _abort_wait(spec, sim, victim, pr.PREEMPTED, pred=g_kick)
        sim = _schedule_wake(sim, g_kick, victim, pr.PREEMPTED)
        # holder switch on kick: no utilization record (still in use);
        # fresh grab on free records
        sim = sim._replace(
            resources=sim.resources._replace(
                holder=dyn.dset(sim.resources.holder, rid, p, g_kick)
            )
        )
        sim = _grab_resource(sim, p, rid, g_free)
        sim = _schedule_wake(
            sim, _and(fused & ~blocked, gate), p, pr.SUCCESS,
            t=sim.clock + jnp.maximum(cmd.f3, 0.0),
        )
        sim = set_pc(sim, p, cmd.next_pc, _and(free | can_kick, gate))
        sim = _guard_wait(
            sim, p, r_guard[rid], cmd, is_retry, pred=_and(blocked, gate)
        )
        return sim, blocked | fused

    @_gated
    def h_release(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        sim2 = release_resource(spec, sim, p, cmd.i, pred=gate)
        sim2 = set_pc(sim2, p, cmd.next_pc, gate)
        return sim2, jnp.asarray(False)

    def _pool_stamp(sim, k, q, pred=True):
        """Stamp q's grab order on its first units (LIFO victim order)."""
        fresh = dyn.dget2(sim.pools.held, k, q) <= 0.0
        if pred is not True:
            fresh = fresh & pred
        pools = sim.pools._replace(
            held_seq=dyn.dset2(sim.pools.held_seq, k, q,
                dyn.dget(sim.pools.next_seq, k), fresh
            ),
            next_seq=dyn.dadd(sim.pools.next_seq, k, 1, fresh),
        )
        return sim._replace(pools=pools)

    def _pool_acquire_impl(sim: Sim, p, cmd: pr.Command, is_retry, mug,
                           gate=True):
        """Greedy acquire (parity: cmi_pool_acquire_inner,
        `src/cmb_resourcepool.c:362-533`): take available units NOW, then
        (preempt variant) mug strictly-lower-priority holders lowest-prio-
        first / LIFO, then pend for the remainder.  pend_f carries the
        remaining claim; pend_f2 the pre-call holding for abort rollback."""
        k = cmd.i
        rem = cmd.f
        init_held = jnp.where(
            is_retry, dyn.dget(sim.procs.pend_f2, p), dyn.dget2(sim.pools.held, k, p)
        )

        # greedy grab (the reference pool has no no-jump-ahead gate: new
        # callers race for available units; FIFO applies to the wait line)
        take = jnp.clip(rem, 0.0, dyn.dget(sim.pools.level, k))
        sim = _pool_stamp(sim, k, p, pred=gate)
        sim = sim._replace(
            pools=sim.pools._replace(
                level=dyn.dadd(sim.pools.level, k, -take, gate),
                held=dyn.dadd2(sim.pools.held, k, p, take, gate),
            )
        )
        rem = rem - take

        if mug:
            n_procs = sim.procs.prio.shape[0]
            pididx = jnp.arange(n_procs)

            def can_mug(carry):
                sim, rem = carry
                vmask = (
                    (dyn.dget(sim.pools.held, k) > 0.0)
                    & (sim.procs.prio < dyn.dget(sim.procs.prio, p))
                    & (pididx != p)
                )
                return _and((rem > 0.0) & jnp.any(vmask), gate)

            def mug_one(carry):
                sim, rem = carry
                vmask = (
                    (dyn.dget(sim.pools.held, k) > 0.0)
                    & (sim.procs.prio < dyn.dget(sim.procs.prio, p))
                    & (pididx != p)
                )
                # lowest priority first, then LIFO (latest grab first)
                vprio = jnp.min(
                    jnp.where(vmask, sim.procs.prio, jnp.iinfo(jnp.int32).max)
                )
                m2 = vmask & (sim.procs.prio == vprio)
                vseq = jnp.max(jnp.where(m2, dyn.dget(sim.pools.held_seq, k), -1))
                v = _argmax32(m2 & (dyn.dget(sim.pools.held_seq, k) == vseq)).astype(_I)
                loot = dyn.dget2(sim.pools.held, k, v)
                used = jnp.minimum(loot, rem)
                surplus = loot - used
                sim = sim._replace(
                    pools=sim.pools._replace(
                        held=dyn.dadd2(
                            dyn.dset2(sim.pools.held, k, v, 0.0), k, p, used
                        ),
                        level=dyn.dadd(sim.pools.level, k, surplus),
                    )
                )
                # victim loses everything and resumes with PREEMPTED
                sim = _abort_wait(spec, sim, v, pr.PREEMPTED)
                sim = _schedule_wake(sim, True, v, pr.PREEMPTED)
                return sim, rem - used

            sim, rem = _bounded_while(
                can_mug, mug_one, (sim, rem), spec.n_procs
            )

        done = rem <= 0.0
        fused = (cmd.tag == pr.C_POOL_ACQ_HOLD) | (
            cmd.tag == pr.C_POOL_PRE_HOLD
        )
        in_use = p_cap[k] - dyn.dget(sim.pools.level, k)
        sim = sim._replace(
            pools=sim.pools._replace(
                acc=_record_row_if(
                    p_rec, sim.pools.acc, k, sim.clock, in_use, gate
                )
            )
        )
        # leftovers may satisfy the next waiter — signaled ONLY on success
        # (parity: cmi_pool_acquire_inner signals after completing a grab;
        # signaling from a still-blocked partial grab would ping-pong
        # wakes between starved waiters forever)
        sim = _guard_signal(sim, p_guard[k], pred=_and(done, gate), spec=spec)
        # fused success: the pre-drawn hold (f3 rides the pend through a
        # blocked wait), armed after the signal like h_queue
        sim = _schedule_wake(
            sim, _and(fused & done, gate), p, pr.SUCCESS,
            t=sim.clock + jnp.maximum(cmd.f3, 0.0),
        )
        sim = set_pc(sim, p, cmd.next_pc, _and(done, gate))
        sim = _guard_wait(
            sim,
            p,
            p_guard[k],
            cmd._replace(f=rem, f2=init_held),
            is_retry,
            pred=_and(~done, gate),
        )
        return sim, ~done | fused

    @_gated
    def h_pool_acquire(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        return _pool_acquire_impl(sim, p, cmd, is_retry, mug=False, gate=gate)

    @_gated
    def h_pool_preempt(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        return _pool_acquire_impl(sim, p, cmd, is_retry, mug=True, gate=gate)

    @_gated
    def h_pool_release(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        sim2 = release_pool(spec, sim, p, cmd.i, cmd.f, pred=gate)
        sim2 = set_pc(sim2, p, cmd.next_pc, gate)
        return sim2, jnp.asarray(False)

    def _buffer_xfer_impl(sim: Sim, p, cmd: pr.Command, is_retry, getting,
                          gate=True):
        """Greedy partial-fulfillment transfer shared by get/put (parity:
        cmb_buffer_get/_put, `src/cmb_buffer.c:194-346`): move what fits
        now, wait for the remainder; an aborted wait keeps the partial
        amount and the continuation reads it via api.got.

        ``getting`` is a TRACED scalar (cmd.tag == C_BUF_GET): both
        dispatch slots alias one handler, so the impl traces once and
        the verbs differ in a few scalar selects (signal order is
        other-then-my for both, so no wake-seq hazard).

        Signals: opposite guard on any progress (the transfer freed space /
        added content for the other side); SAME-side guard only on
        completion — a partial grab leaves this side drained/full, so a
        same-side wake could only spin (and a zero-progress re-signal
        would ping-pong wakes between starved waiters forever)."""
        b = cmd.i
        rem = cmd.f
        total = jnp.where(is_retry, dyn.dget(sim.procs.pend_f2, p), cmd.f)
        level = dyn.dget(sim.buffers.level, b)
        room = jnp.where(getting, level, b_cap[b] - level)
        moved = jnp.clip(rem, 0.0, room)
        level2 = level + jnp.where(getting, -moved, moved)
        rem2 = rem - moved
        done = rem2 <= 0.0
        my_guard = jnp.where(getting, b_front[b], b_rear[b])
        other_guard = jnp.where(getting, b_rear[b], b_front[b])
        sim = sim._replace(
            buffers=Buffers(
                level=dyn.dset(sim.buffers.level, b, level2, gate),
                acc=_record_row_if(
                    b_rec, sim.buffers.acc, b, sim.clock, level2, gate
                ),
            )
        )
        sim = _guard_signal(sim, other_guard, pred=_and(moved > 0.0, gate), spec=spec)
        # pass leftover wake along on completion only
        sim = _guard_signal(sim, my_guard, pred=_and(done, gate), spec=spec)
        sim = sim._replace(
            procs=sim.procs._replace(
                got=dyn.dset(sim.procs.got, p, total, _and(done, gate))
            )
        )
        fused = (cmd.tag == pr.C_BUF_GET_HOLD) | (
            cmd.tag == pr.C_BUF_PUT_HOLD
        )
        sim = _schedule_wake(
            sim, _and(fused & done, gate), p, pr.SUCCESS,
            t=sim.clock + jnp.maximum(cmd.f3, 0.0),
        )
        sim = set_pc(sim, p, cmd.next_pc, gate)
        sim = _guard_wait(
            sim, p, my_guard, cmd._replace(f=rem2, f2=total), is_retry,
            pred=_and(~done, gate),
        )
        return sim, ~done | fused

    @_gated
    def h_buffer(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        getting = (cmd.tag == pr.C_BUF_GET) | (
            cmd.tag == pr.C_BUF_GET_HOLD
        )
        return _buffer_xfer_impl(
            sim, p, cmd, is_retry, getting=getting, gate=gate,
        )

    @_gated
    def h_pq_put(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        qid = cmd.i
        n_live = jnp.sum(dyn.dget(sim.pqueues.live, qid).astype(_I))
        may = is_retry | gd.is_empty(sim.procs.pend_guard, pq_rear[qid])
        full = (n_live >= pq_cap[qid]) | ~may
        ok = _and(~full, gate)
        # lowest free column — several columns tie at True; argmax
        # tie-breaks are backend-dependent under Mosaic (first_true32)
        free_col = dyn.first_true32(
            ~dyn.dget(sim.pqueues.live, qid)
        ).astype(_I)
        pq2 = PQueues(
            items=dyn.dset2(sim.pqueues.items, qid, free_col, cmd.f, ok),
            prio=dyn.dset2(sim.pqueues.prio, qid, free_col, cmd.f2, ok),
            seq=dyn.dset2(
                sim.pqueues.seq, qid, free_col,
                dyn.dget(sim.pqueues.next_seq, qid), ok,
            ),
            live=dyn.dset2(sim.pqueues.live, qid, free_col, True, ok),
            next_seq=dyn.dadd(sim.pqueues.next_seq, qid, 1, ok),
            acc=_record_row_if(
                pq_rec, sim.pqueues.acc, qid, sim.clock,
                (n_live + 1).astype(_R), ok,
            ),
        )
        sim = sim._replace(pqueues=pq2)
        # put frees no slots: only the getter side can newly proceed
        sim = _guard_signal(sim, pq_front[qid], pred=ok, spec=spec)
        fused = cmd.tag == pr.C_PQ_PUT_HOLD
        sim = _schedule_wake(
            sim, fused & ok, p, pr.SUCCESS,
            t=sim.clock + jnp.maximum(cmd.f3, 0.0),
        )
        sim = set_pc(sim, p, cmd.next_pc, gate)
        sim = _guard_wait(
            sim, p, pq_rear[qid], cmd, is_retry, pred=_and(full, gate)
        )
        return sim, full | fused

    @_gated
    def h_pq_get(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        qid = cmd.i
        live = dyn.dget(sim.pqueues.live, qid)
        may = is_retry | gd.is_empty(sim.procs.pend_guard, pq_front[qid])
        empty = ~jnp.any(live) | ~may
        n_live = jnp.sum(live.astype(_I))
        # highest priority, then FIFO
        neg_inf = jnp.asarray(-jnp.inf, _R)
        p_best = jnp.max(jnp.where(live, dyn.dget(sim.pqueues.prio, qid), neg_inf))
        m = live & (dyn.dget(sim.pqueues.prio, qid) == p_best)
        s_min = jnp.min(
            jnp.where(m, dyn.dget(sim.pqueues.seq, qid), jnp.iinfo(jnp.int32).max)
        )
        col = _argmax32(m & (dyn.dget(sim.pqueues.seq, qid) == s_min)).astype(_I)
        item = dyn.dget2(sim.pqueues.items, qid, col)
        ok = _and(~empty, gate)
        pq2 = sim.pqueues._replace(
            live=dyn.dset2(sim.pqueues.live, qid, col, False, ok),
            acc=_record_row_if(
                pq_rec, sim.pqueues.acc, qid, sim.clock,
                (n_live - 1).astype(_R), ok,
            ),
        )
        sim = sim._replace(
            pqueues=pq2,
            procs=sim.procs._replace(
                got=dyn.dset(sim.procs.got, p, item, ok)
            ),
        )
        sim = _guard_signal(sim, pq_rear[qid], pred=ok, spec=spec)
        sim = _guard_signal(sim, pq_front[qid], pred=ok, spec=spec)
        fused = cmd.tag == pr.C_PQ_GET_HOLD
        sim = _schedule_wake(
            sim, fused & ok, p, pr.SUCCESS,
            t=sim.clock + jnp.maximum(cmd.f3, 0.0),
        )
        sim = set_pc(sim, p, cmd.next_pc, gate)
        sim = _guard_wait(
            sim, p, pq_front[qid], cmd, is_retry, pred=_and(empty, gate)
        )
        return sim, empty | fused

    @_gated
    def h_cond_wait(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        """First issue always blocks until a signal (parity: the reference's
        guard wait enqueues + yields unconditionally); a signal-driven retry
        re-checks the predicate and re-waits if it no longer holds (the
        documented spurious-wakeup contract, handled inside the framework)."""
        cid = cmd.i
        satisfied = _cond_satisfied(spec, sim, cid, p)
        proceed = is_retry & satisfied
        sim = set_pc(sim, p, cmd.next_pc, gate)
        sim = _guard_wait(
            sim, p, c_guard[cid], cmd, is_retry, pred=_and(~proceed, gate)
        )
        return sim, ~proceed

    @_gated
    def h_wait_proc(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        tgt = cmd.i
        finished = dyn.dget(sim.procs.status, tgt) == pr.FINISHED
        # already finished: yield anyway and deliver the target's exit
        # signal (SUCCESS or STOPPED) through an immediate wakeup, so the
        # continuation sees the same signal either way
        sim = _schedule_wake(
            sim, _and(finished, gate), p, dyn.dget(sim.procs.exit_sig, tgt)
        )
        sim = sim._replace(
            procs=sim.procs._replace(
                await_pid=dyn.dset(
                    sim.procs.await_pid, p, tgt, _and(~finished, gate)
                )
            )
        )
        return set_pc(sim, p, cmd.next_pc, gate), jnp.asarray(True)

    @_gated
    def h_wait_evt(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        """Wait for event handle cmd.i to be dispatched (parity:
        cmb_process_wait_event, `include/cmb_process.h:374`).  A dead
        handle (already fired or cancelled) delivers CANCELLED through an
        immediate wakeup, mirroring wait_process's already-finished path."""
        h = cmd.i
        valid = ev._valid(sim.events, h)
        sim = _schedule_wake(
            sim, _and(~valid, gate), p, jnp.asarray(pr.CANCELLED, _I)
        )
        sim = sim._replace(
            procs=sim.procs._replace(
                await_evt=dyn.dset(
                    sim.procs.await_evt, p, h, _and(valid, gate)
                )
            )
        )
        return set_pc(sim, p, cmd.next_pc, gate), jnp.asarray(True)

    @_gated
    def h_invalid(sim: Sim, p, cmd: pr.Command, is_retry, gate=True):
        """Stub for commands whose component type the model never declared
        — keeps the traced handler table small (compile time scales with
        it) while turning stray commands into a contained failure."""
        return _set_err(sim, gate, ERR_USER), jnp.asarray(True)

    def component_gate(pred, h):
        return h if pred else h_invalid

    has_q = bool(spec.queues)
    has_r = bool(spec.resources)
    handlers = [
        h_hold,                                  # C_HOLD
        h_exit,                                  # C_EXIT
        h_jump,                                  # C_JUMP
        component_gate(has_q, h_queue),                    # C_PUT
        component_gate(has_q, h_queue),                    # C_GET
        component_gate(has_r, h_acquire),                  # C_ACQUIRE
        component_gate(has_r, h_release),                  # C_RELEASE
        component_gate(has_r, h_preempt),                  # C_PREEMPT
        component_gate(bool(spec.pools), h_pool_acquire),  # C_POOL_ACQ
        component_gate(bool(spec.pools), h_pool_release),  # C_POOL_REL
        component_gate(bool(spec.buffers), h_buffer),      # C_BUF_GET
        component_gate(bool(spec.buffers), h_buffer),      # C_BUF_PUT
        component_gate(bool(spec.pqueues), h_pq_put),      # C_PQ_PUT
        component_gate(bool(spec.pqueues), h_pq_get),      # C_PQ_GET
        component_gate(bool(spec.conditions), h_cond_wait),  # C_COND_WAIT
        h_wait_proc,                             # C_WAIT_PROC
        component_gate(bool(spec.pools), h_pool_preempt),  # C_POOL_PRE
        h_wait_evt,                              # C_WAIT_EVT
        component_gate(has_q, h_queue),                    # C_PUT_HOLD
        component_gate(has_q, h_queue),                    # C_GET_HOLD
        component_gate(has_r, h_acquire),                  # C_ACQ_HOLD
        component_gate(has_r, h_preempt),                  # C_PRE_HOLD
        component_gate(bool(spec.pools), h_pool_acquire),  # C_POOL_ACQ_HOLD
        component_gate(bool(spec.pools), h_pool_preempt),  # C_POOL_PRE_HOLD
        component_gate(bool(spec.buffers), h_buffer),      # C_BUF_GET_HOLD
        component_gate(bool(spec.buffers), h_buffer),      # C_BUF_PUT_HOLD
        component_gate(bool(spec.pqueues), h_pq_put),      # C_PQ_PUT_HOLD
        component_gate(bool(spec.pqueues), h_pq_get),      # C_PQ_GET_HOLD
    ]

    if used_tags is None:
        def apply_command(sim: Sim, p, cmd: pr.Command, is_retry=False):
            return _vswitch(
                jnp.clip(cmd.tag, 0, pr.N_COMMANDS - 1), handlers, sim, p,
                cmd, jnp.asarray(is_retry),
            )
        apply_command.handler_items = list(enumerate(handlers))
        return apply_command

    # Specialized table: trace only the handlers this model's blocks can
    # emit (every traced lax.switch branch *executes* for every lane under
    # vmap — dead handlers are pure hot-loop cost).  Unknown tags land on
    # h_invalid -> ERR_USER, a contained failure, never corruption.
    used = sorted(t for t in used_tags if 0 <= t < pr.N_COMMANDS)
    table = [handlers[t] for t in used] + [h_invalid]
    lut_vals = [len(used)] * pr.N_COMMANDS
    for j, t in enumerate(used):
        lut_vals[t] = j
    lut = _ConstTable(lut_vals, jnp.int32)

    def apply_command(sim: Sim, p, cmd: pr.Command, is_retry=False):
        idx = lut[jnp.clip(cmd.tag, 0, pr.N_COMMANDS - 1)]
        return _vswitch(
            idx, table, sim, p, cmd, jnp.asarray(is_retry),
        )

    apply_command.handler_items = list(enumerate(handlers))
    return apply_command


# --- the dispatcher -----------------------------------------------------------


def make_step(spec: ModelSpec):
    """Build ``step(sim) -> sim`` dispatching exactly one event."""
    blocks = list(spec.blocks)

    # The handler table is specialized to the tags the model's blocks can
    # emit, which requires a Sim to trace them against — so it is built
    # lazily at the first (tracing) call and cached.  Static per spec:
    # retraces at other batch shapes reuse it.
    _cache: dict = {}

    def apply_command(sim: Sim, p, cmd: pr.Command, is_retry=False):
        if "apply" not in _cache:
            _cache["apply"] = _make_apply(spec, _used_tags_for(spec, sim))
        return _cache["apply"](sim, p, cmd, is_retry)

    def _boundary_stub(sim, p, sig):
        # placeholder for a boundary block in the KERNEL trace: the block
        # is unreachable there (dispatch defers it to the chunk driver;
        # mid-chain entry is flagged ERR_BOUNDARY before the switch), so
        # its body — the whole point of the marker — stays out of the
        # kernel jaxpr
        return sim, pr.exit_()

    def run_block(sim: Sim, p, sig):
        table = blocks
        if config.KERNEL_MODE and spec.boundary_pcs:
            table = [
                _boundary_stub if pc in spec.boundary_pcs else b
                for pc, b in enumerate(blocks)
            ]
        return _vswitch(
            jnp.clip(dyn.dget(sim.procs.pc, p), 0, len(blocks) - 1),
            table,
            sim,
            p,
            sig,
        )

    def resume(sim: Sim, p, sig, gate=True):
        """Resume process p with a signal: retry or abort a pending
        command, then chain blocks until something yields.

        ``gate`` (scalar bool) disables the resume entirely: every
        preamble write is pred-gated by it and the chain loop starts
        pre-yielded, so a gated-off lane's output IS the input — no
        caller-side merge needed.  (The while loop's own freeze
        semantics — lanelast's per-lane freeze in the kernel, jax's
        batched-while carry selects under vmap, a plain false condition
        unbatched — already guarantee the loop body writes nothing when
        the condition is false from iteration 0.)"""
        # statics: machinery a model cannot exercise stays out of the
        # trace entirely (the flags derive from the inferred command-tag
        # set, memoized per spec)
        may_pend = _may_pend(spec, sim)

        # any remaining wake event is stale once we are resumed
        sim = _cancel_wake(sim, p, pred=gate)
        # ANY delivery ends a wait-on-process / wait-on-event: a direct
        # user-timer wake bypasses the abort arm, and a surviving
        # await_pid/await_evt would spuriously re-resume this process when
        # the target later finishes/fires (parity:
        # cmi_process_cancel_awaiteds runs on every signal delivery,
        # `src/cmb_process.c:694-748`); statically absent when the model
        # cannot wait on processes/events
        procs2 = sim.procs
        if _may_wait_procs(spec, sim):
            procs2 = procs2._replace(
                await_pid=dyn.dset(procs2.await_pid, p, -1, gate)
            )
        if _may_wait_events(spec, sim):
            procs2 = procs2._replace(
                await_evt=dyn.dset(procs2.await_evt, p, -1, gate)
            )
        sim = sim._replace(procs=procs2)

        if may_pend:
            pend = pr.Command(
                dyn.dget(sim.procs.pend_tag, p),
                dyn.dget(sim.procs.pend_f, p),
                dyn.dget(sim.procs.pend_f2, p),
                dyn.dget(sim.procs.pend_f3, p),
                dyn.dget(sim.procs.pend_i, p),
                dyn.dget(sim.procs.pend_pc, p),
            )
            has_pend = pend.tag != pr.NO_PEND
            ok_wake = jnp.asarray(sig, _I) == pr.SUCCESS
            gated = has_pend if gate is True else (has_pend & gate)

            # Unwait-BEFORE-cleanup, as _abort_wait orders it: _clear_pend
            # must clear p's guard membership before _abort_cleanup's pool
            # rollback signals the pool guard, or p steals its own rollback
            # wake (best_waiter would still see p enrolled) and the waiter
            # the signal was meant for starves.  _abort_cleanup reads the
            # pend from the snapshot above, so clearing first is safe.
            # (_clear_pend also covers the SUCCESS-wake path: a user timer
            # with sig=SUCCESS can wake a pended process directly, and the
            # cleared pend_guard IS the dense-guard removal — no zombie
            # membership can survive.)
            sim = _clear_pend(sim, p, pred=gate)
            # non-SUCCESS wake of a pended process: abort the wait — the
            # signal flows to the continuation block below.  Sequential
            # predication instead of branch-and-merge: the preamble above
            # already did the unwait bookkeeping (wake cancel, await
            # clears) for EVERY path, so the abort arm is just the
            # command-specific cleanup, pred-gated; for pool/buffer-free
            # models it traces to nothing.  A SUCCESS wake re-attempts the
            # pended command as the chain's first iteration (use_pend) —
            # handlers are traced only there.
            sim = _abort_cleanup(
                spec, sim, p, pend, sig, pred=gated & ~ok_wake
            )
            use_pend0 = has_pend & ok_wake
        else:
            # nothing can ever pend: no retry arm, no use_pend merge in
            # the chain body, no pend bookkeeping
            pend = None
            use_pend0 = jnp.asarray(False)
        yielded0 = (
            jnp.asarray(False) if gate is True else ~jnp.asarray(gate)
        )

        def cond(carry):
            sim, sig, yielded, n, use_pend = carry
            alive = (dyn.dget(sim.procs.status, p) == pr.RUNNING) & (sim.err == 0)
            return ~yielded & alive & (n < MAX_CHAIN)

        def body(carry):
            sim, sig, _, n, use_pend = carry
            # Draw-word hoist (bits.stash_arm): every block branch's first
            # counter tick shares one traced Threefry keyed on the
            # pre-dispatch rng tracers; branches are exclusive per lane,
            # so one block of ~120 scalar ops serves every draw site in
            # the switch (values bit-identical, lazily traced — see
            # random/bits.py).  The XLA cond arm below traces blocks in a
            # sub-trace where the key cannot match; it simply misses.
            rb.stash_arm(sim.rng)
            try:
                if not may_pend:
                    # no retry arm exists: the block always runs and its
                    # command applies directly (no use_pend merge at all)
                    if config.KERNEL_MODE and spec.boundary_pcs:
                        in_b = boundary_table[dyn.dget(sim.procs.pc, p)] != 0
                        sim = _set_err(sim, in_b, ERR_BOUNDARY)
                    sim2, cmd = run_block(sim, p, sig)
                elif config.KERNEL_MODE:
                    if spec.boundary_pcs:
                        # boundary blocks may only be entered by dispatch
                        # (which the kernel defers to the chunk driver) —
                        # reaching one mid-chain would run its stub, so it
                        # fails the lane loudly instead
                        in_b = boundary_table[dyn.dget(sim.procs.pc, p)] != 0
                        sim = _set_err(sim, in_b & ~use_pend, ERR_BOUNDARY)
                    # both arms run under vmap regardless; the explicit
                    # bwhere-fold keeps bool leaves off Mosaic's unsupported
                    # i1 select_n path
                    s_blk, c_blk = run_block(sim, p, sig)
                    sim2 = _tree_select(use_pend, sim, s_blk)
                    cmd = jax.tree.map(
                        lambda a, b: dyn.bwhere(use_pend, a, b), pend, c_blk
                    )
                else:
                    # scalar/XLA path keeps lax.cond: an unbatched pend-retry
                    # must not execute the block (user side effects fire once)
                    sim2, cmd = lax.cond(
                        use_pend,
                        lambda s: (s, pend),
                        lambda s: run_block(s, p, sig),
                        sim,
                    )
            finally:
                rb.stash_clear()
            sim2, yielded = apply_command(
                sim2, p, cmd,
                is_retry=use_pend if may_pend else False,
            )
            return (
                sim2,
                jnp.asarray(pr.SUCCESS, _I),
                yielded,
                n + 1,
                jnp.asarray(False),
            )

        chain_bound = spec.max_chain if config.KERNEL_MODE else MAX_CHAIN
        sim, _, yielded, n = _bounded_while(
            cond,
            body,
            (
                sim,
                jnp.asarray(sig, _I),
                yielded0,
                jnp.zeros((), _I),
                use_pend0,
            ),
            chain_bound,
        )[:4]
        # runaway containment: in kernel mode a process still live and
        # unyielded after spec.max_chain chained commands is flagged the
        # same way a MAX_CHAIN overrun is on the XLA path
        alive_end = (dyn.dget(sim.procs.status, p) == pr.RUNNING) & (
            sim.err == 0
        )
        runaway = (
            (~yielded & alive_end)
            if config.KERNEL_MODE
            else (n >= MAX_CHAIN)
        )
        sim = _set_err(sim, runaway, ERR_CHAIN_RUNAWAY)
        if sim.metrics is not None:
            # n == 0 exactly when the resume was gated off, so the hook's
            # own ran-gate preserves the "gated-off resume output IS the
            # input" contract on_proc rests on (per-lane values; the
            # masked adds contribute zero there)
            sim = obs_metrics.on_resume(sim, n, use_pend0)
        return sim

    def on_proc(sim: Sim, subj, arg, gate):
        # NO merge at all: resume pred-gates every preamble write by
        # (event-found & target-alive) and starts the chain pre-yielded
        # when gated off, so a gated-off lane's resume output IS the
        # input.  (Each merge layer here used to cost a select per Sim
        # leaf, because the chain while returns every carried leaf as a
        # fresh value.)
        alive = dyn.dget(sim.procs.status, subj) == pr.RUNNING
        return resume(sim, subj, arg, gate=alive & gate)

    user_handlers = [
        (lambda fn: (
            lambda sim, subj, arg, gate:
            _tree_select(gate, fn(sim, subj, arg), sim)
        ))(fn)
        for fn in spec.user_handlers
    ]
    dispatch_fns = [on_proc, on_proc] + user_handlers  # K_PROC, K_TIMER

    boundary_table = (
        _ConstTable(
            [
                1 if pc in spec.boundary_pcs else 0
                for pc in range(len(spec.blocks))
            ],
            _I,
        )
        if spec.boundary_pcs
        else None
    )

    def step(sim: Sim) -> Sim:
        event, take_e, take_w = ev.peek_merged(
            sim.events, sim.wakes, sim.procs.prio, K_PROC
        )
        if config.KERNEL_MODE and spec.boundary_pcs:
            # a resume whose target block is a boundary block is NOT
            # dispatched here: the event stays in its table, the lane
            # raises boundary_pending, and the chunk driver applies one
            # plain-XLA engine step to it between chunks (MXU physics —
            # parity with the reference's in-coroutine CUDA launches)
            pc_t = dyn.dget(
                sim.procs.pc, jnp.maximum(event.subj, 0)
            )
            is_b = boundary_table[pc_t] != 0
            boundary = event.found & (event.kind <= K_TIMER) & is_b
            proceed = event.found & ~boundary
            not_deferred = ~boundary
            sim = sim._replace(boundary_pending=boundary)
        else:
            proceed = event.found
            not_deferred = True
        out_of_events = ~event.found  # BEFORE the boundary defer masks it
        event = event._replace(found=proceed)
        # event-set occupancy BEFORE the consume (the popped event still
        # pends): the high-water gauge of how close this replication came
        # to ERR_EVENT_OVERFLOW.  Computed only when a registry is carried
        # — the [CAP]+[P] reductions stay out of the metrics-off trace.
        if sim.metrics is not None:
            occupancy = ev.length(sim.events) + jnp.sum(
                jnp.isfinite(sim.wakes.time).astype(_I), dtype=_I
            )
        es2, wk2 = ev.consume_merged(
            sim.events, sim.wakes, take_e, take_w, proceed
        )
        sim = sim._replace(
            events=es2,
            wakes=wk2,
            clock=jnp.where(proceed, event.time, sim.clock),
            n_events=sim.n_events
            + jnp.where(proceed, 1, 0).astype(config.COUNT),
        )
        # the flight-recorder/metrics hooks return sim UNCHANGED (the
        # same object — zero traced ops) when the Sim carries no
        # ring/registry; with one, this is THE dispatch-site write
        sim = obs_trace.emit(
            sim, event.time, event.subj, event.kind, event.arg, proceed
        )
        if sim.metrics is not None:
            sim = obs_metrics.on_dispatch(sim, event.kind, occupancy, proceed)
        if _may_wait_events(spec, sim):
            # wake event-waiters before the action runs (reference order,
            # `src/cmb_event.c:312-314`); statically absent from models
            # that never issue wait_event.  The stale-handle arm can
            # schedule wakes even on an empty pop, so "out of events" is
            # judged AFTER the scan (else a cancel that drains the set
            # would strand its waiter forever).
            sim = _dispatch_evt_wakes(
                sim, event.handle, event.found, not_deferred
            )
            sim = sim._replace(
                done=sim.done
                | (
                    out_of_events
                    & ev.is_empty(sim.events)
                    & ev.wakes_empty(sim.wakes)
                )
            )
        else:
            sim = sim._replace(done=sim.done | out_of_events)
        return _vswitch(
            jnp.clip(event.kind, 0, len(dispatch_fns) - 1),
            dispatch_fns,
            sim,
            event.subj,
            event.arg,
            event.found,
        )

    return step


def make_cond(spec: ModelSpec, t_end: Optional[float] = None):
    """Build the per-replication liveness predicate ``cond(sim) -> bool``
    used by :func:`make_run` (and by the Pallas kernel runner, which hoists
    the while-loop out of vmap and needs the same predicate)."""

    def cond(sim: Sim):
        empty = ev.is_empty(sim.events) & ev.wakes_empty(sim.wakes)
        if _may_wait_events(spec, sim):
            # an event-waiter whose handle died with the set (a cancel was
            # the run's last activity) still needs one more step: the
            # stale-handle scan there schedules its CANCELLED wake
            stranded = jnp.any(
                (sim.procs.await_evt >= 0)
                & (sim.procs.status == pr.RUNNING)
            )
            out_of_work = empty & ~stranded
        else:
            out_of_work = empty
        live = ~sim.done & (sim.err == 0) & ~out_of_work
        if config.KERNEL_MODE and spec.boundary_pcs:
            # a lane whose next dispatch is a boundary block freezes in
            # the chunk; the chunk driver steps it host-side (the XLA
            # path traces with KERNEL_MODE off and never sees this)
            live = live & ~sim.boundary_pending
        # horizon: a Sim carrying a per-lane ``t_stop`` leaf (the
        # heterogeneous-wave path) reads it INSTEAD of the static
        # ``t_end`` — ``t_stop = t_end`` reproduces the static check's
        # decisions bit-for-bit (same compare on the same values), and
        # ``t_stop = +inf`` reproduces ``t_end=None`` (the conjunct is
        # identically true); ``-inf`` is the dead-on-arrival pad lane
        lim = sim.t_stop if sim.t_stop is not None else t_end
        if lim is not None:
            nxt = jnp.minimum(
                ev.min_time(sim.events), jnp.min(sim.wakes.time)
            )
            live = live & ((nxt <= lim) | (empty & ~out_of_work))
        return live

    return cond


def make_run(
    spec: ModelSpec,
    t_end: Optional[float] = None,
    pack: Optional[bool] = None,
    max_steps: Optional[int] = None,
):
    """Build ``run(sim) -> sim``: dispatch events until the model stops
    (api.stop), fails, runs out of events, or passes ``t_end``
    (parity: cmb_event_queue_execute; t_end plays the role of the
    user-scheduled end event).

    ``pack`` selects the while-loop carry layout (None defers to
    ``config.xla_pack_enabled()`` — ``CIMBA_XLA_PACK``, auto-on for
    accelerator backends): packed runs the SAME step/cond on a carry of
    a few wide per-dtype buffers instead of the Sim's ~50 narrow leaves
    (core/carry.py, the same packing the Pallas chunk loop uses under
    ``CIMBA_KERNEL_PACK``).  Pack/unpack are bitwise-lossless structural
    ops, so trajectories are identical; ``pack=False`` reproduces
    today's per-leaf jaxpr exactly.  See docs/11_dispatch_cost.md.

    ``max_steps`` bounds one invocation to at most that many dispatches
    (the bounded-chunk variant, docs/12_streaming.md): the loop carries
    a per-replication step counter and exits when either the liveness
    cond fails or the counter hits the bound, so a host loop can
    re-dispatch the returned Sim until :func:`make_cond` reports it
    done.  Truncation is exact: each lane executes the identical step
    sequence the unbounded loop would, merely split across invocations
    — chunked trajectories are bitwise the monolithic ones (pinned by
    tests/test_stream.py).  ``None`` (the default) keeps today's
    unbounded loop, jaxpr-identical to before this knob existed."""
    step = make_step(spec)
    cond = make_cond(spec, t_end)
    if pack is None:
        pack = config.xla_pack_enabled()
    if max_steps is not None and max_steps <= 0:
        raise ValueError(f"max_steps must be positive, got {max_steps}")
    if not pack:
        if max_steps is None:
            def run(sim: Sim) -> Sim:
                return lax.while_loop(cond, step, sim)

            return run

        def run(sim: Sim) -> Sim:
            def kcond(kc):
                return cond(kc[1]) & (kc[0] < max_steps)

            def kbody(kc):
                return kc[0] + jnp.asarray(1, _I), step(kc[1])

            return lax.while_loop(
                kcond, kbody, (jnp.zeros((), _I), sim)
            )[1]

        return run

    from cimba_tpu.core import carry as _carry

    def run(sim: Sim) -> Sim:
        leaves, treedef = jax.tree.flatten(sim)
        plan = _carry.pack_plan(
            [
                jax.ShapeDtypeStruct(jnp.shape(l), jnp.result_type(l))
                for l in leaves
            ],
            lane_last=False,
        )

        def unflatten(bufs):
            return jax.tree.unflatten(
                treedef, _carry.unpack(list(bufs), plan)
            )

        def pstep(bufs):
            return tuple(
                _carry.pack(jax.tree.leaves(step(unflatten(bufs))), plan)
            )

        if max_steps is None:
            def pcond(bufs):
                return cond(unflatten(bufs))

            out = lax.while_loop(
                pcond, pstep, tuple(_carry.pack(leaves, plan))
            )
            return unflatten(out)

        def kcond(kb):
            return cond(unflatten(kb[1])) & (kb[0] < max_steps)

        def kbody(kb):
            return kb[0] + jnp.asarray(1, _I), pstep(kb[1])

        out = lax.while_loop(
            kcond, kbody,
            (jnp.zeros((), _I), tuple(_carry.pack(leaves, plan))),
        )
        return unflatten(out[1])

    return run


# --- chunked dispatch: watchdog-proof runs of any length ---------------------


def make_chunk(
    spec: ModelSpec,
    t_end: Optional[float] = None,
    pack: Optional[bool] = None,
    max_steps: int = 1024,
    audit: bool = False,
):
    """Build ``chunk(sims) -> (sims, any_live)`` over a BATCHED Sim
    (leading lane axis): one bounded dispatch chunk (each lane advances
    at most ``max_steps`` events) plus the cheap liveness scalar the
    host loop polls.  Not jitted — callers jit it with donation
    (:func:`make_chunked_run`) or wrap it in ``shard_map`` first
    (``runner.experiment`` composes it with the replication mesh).

    ``audit=True`` (the determinism-audit plane, docs/18_audit.md)
    appends a THIRD output: the per-wave carry-class digest vector
    (:func:`cimba_tpu.obs.audit.sim_digest` over the post-chunk Sim),
    which :func:`drive_chunks` hands to its ``on_digest`` hook at every
    chunk boundary.  Trace-time gated like the flight recorder:
    ``audit=False`` (the default) takes the historical code path —
    the chunk jaxpr is character-identical to one built before the
    knob existed (pinned in tests/test_audit.py)."""
    bounded = make_run(spec, t_end=t_end, pack=pack, max_steps=max_steps)
    cond = make_cond(spec, t_end)

    if not audit:
        def chunk(sims: Sim):
            sims = jax.vmap(bounded)(sims)
            return sims, jnp.any(jax.vmap(cond)(sims))

        return chunk

    from cimba_tpu.obs import audit as obs_audit

    def chunk(sims: Sim):
        sims = jax.vmap(bounded)(sims)
        return (
            sims,
            jnp.any(jax.vmap(cond)(sims)),
            obs_audit.sim_digest(sims),
        )

    return chunk


def make_refill(spec: ModelSpec):
    """Build ``refill(sims, mask, reps, seeds, t_stops, params) ->
    sims``: re-initialize EXACTLY the masked lanes of a batched Sim
    through the same per-lane init path the wave was born from
    (:func:`init_sim` with per-lane seed/horizon columns,
    docs/14_wave_packing.md) and splice the fresh rows into the live
    carry — the lane-recycling primitive behind continuous wave refill
    (docs/22_refill.md).

    Unmasked lanes pass through BIT-IDENTICALLY (a per-leaf masked
    select; leaves are never re-laid-out), so a mid-wave splice cannot
    perturb its wave-mates — and a refilled lane starts from exactly
    the state its solo run would start from, which is what makes a
    refilled request's result bitwise its solo run's (trajectories are
    lane-local under vmap; chunk phase is trajectory-invariant).  Works
    on either carry layout: the batched Sim BETWEEN chunks is always
    the plain per-leaf pytree (packing lives inside the while-loop
    carry), so one refill program serves ``pack=True`` and
    ``pack=False`` chunk programs alike, under both dtype profiles.

    The wave must carry the per-lane ``t_stop`` leaf (refill waves
    always do — lane death and reclamation are horizon-driven); a
    ``t_stop=-inf`` row retires a lane into reclaimable dead capacity
    (the pad-lane encoding), which is also how cancellation and
    deadline expiry free lanes mid-wave.  Not jitted here — callers
    jit with the Sim DONATED (``runner.experiment._refill_program``),
    so a boundary splice allocates nothing beyond the fresh rows."""

    def refill(sims: Sim, mask, reps, seeds, t_stops, params):
        if sims.t_stop is None:
            raise ValueError(
                "make_refill: the wave carries no per-lane t_stop "
                "leaf — refill needs horizon-carrying waves (the "
                "serving layer always materializes the column on the "
                "refill path; see docs/22_refill.md)"
            )
        fresh = jax.vmap(
            lambda r, s, t, p: init_sim(spec, s, r, p, t_stop=t)
        )(reps, seeds, t_stops, params)

        def sel(a, b):
            m = mask.reshape(mask.shape + (1,) * (a.ndim - 1))
            return jnp.where(m, a, b)

        return jax.tree.map(sel, fresh, sims)

    return refill


def make_lanes_live(spec: ModelSpec, t_end: Optional[float] = None):
    """Build ``live(sims) -> bool[L]`` over a batched Sim: each lane's
    :func:`make_cond` liveness — the per-lane readback the refill
    driver (and the live lane-occupancy gauge, docs/22_refill.md)
    polls at chunk boundaries to learn which lanes died this chunk.
    Read-only and tiny; callers jit WITHOUT donation so the readback
    never races the next chunk's buffer donation."""
    cond = make_cond(spec, t_end)

    def live(sims: Sim):
        return jax.vmap(cond)(sims)

    return live


def drive_chunks(
    chunk,
    sims: Sim,
    *,
    poll_every: int = 4,
    on_chunk=None,
    on_state=None,
    on_state_every: int = 0,
    max_chunks: Optional[int] = None,
    n0: int = 0,
    on_digest=None,
    on_boundary=None,
) -> Sim:
    """Host loop over a jitted, donated ``chunk(sims) -> (sims,
    any_live)``: re-dispatch until every lane is done.

    The ``any_live`` scalar is polled ASYNCHRONOUSLY: up to
    ``poll_every`` chunks are queued before the oldest flag is read, so
    jax's async dispatch keeps the device pipeline full instead of
    round-tripping a host sync per chunk.  Over-dispatched chunks after
    global completion are exact no-ops (every lane's cond is false, the
    while loop exits at iteration 0, and donation aliases the buffers
    straight through), so late polling never perturbs the result.

    ``on_chunk(n)`` fires after each dispatch — bench.py refreshes its
    watchdog heartbeat here.  ``on_state(sims, n)`` fires every
    ``on_state_every`` chunks with the CURRENT batched Sim, *before* it
    is donated into the next chunk — the checkpoint hook (chunk
    boundaries are the natural checkpoints; ``runner.checkpoint``
    serializes from here).  ``n0`` offsets the chunk counter (a resumed
    run keeps counting where the checkpoint left off).  ``max_chunks``
    is an optional hard stop (the returned Sim may then be unfinished;
    :func:`make_cond` tells).

    ``on_digest(n, vec)`` fires per chunk when the chunk program was
    built with ``audit=True`` (a third output — the carry-class digest
    vector, docs/18_audit.md); the vector is handed over as a device
    array so the drive loop stays asynchronous.  Over-dispatched no-op
    chunks after completion still append (their digests repeat the
    settled state — deterministic, so trails stay comparable).

    ``on_boundary(n, sims)`` fires after each chunk with the CURRENT
    batched Sim, before it is donated into the next dispatch — the
    refill hook (docs/22_refill.md): the hook may inspect per-lane
    liveness (:func:`make_lanes_live`) and return a REPLACEMENT Sim
    (typically the jitted, donated refill program's output with dead
    lanes re-seeded); returning ``None`` leaves the wave untouched.
    When the hook splices (returns non-None), the queued liveness
    flags are discarded: they describe the pre-splice wave, and a
    stale ``any_live=False`` from before a refill revived lanes must
    not retire the wave under the fresh work.
    """
    from collections import deque

    poll_every = max(int(poll_every), 1)
    pending = deque()
    n = n0
    while max_chunks is None or n - n0 < max_chunks:
        out = chunk(sims)
        sims, any_live = out[0], out[1]
        n += 1
        if on_digest is not None and len(out) > 2:
            on_digest(n, out[2])
        if on_chunk is not None:
            on_chunk(n)
        if on_boundary is not None:
            respliced = on_boundary(n, sims)
            if respliced is not None:
                sims = respliced
                pending.clear()
                continue
        if (
            on_state is not None
            and on_state_every > 0
            and n % on_state_every == 0
        ):
            on_state(sims, n)
        pending.append(any_live)
        if len(pending) >= poll_every and not bool(pending.popleft()):
            break
    return sims


def make_chunked_run(
    spec: ModelSpec,
    t_end: Optional[float] = None,
    pack: Optional[bool] = None,
    chunk_steps: int = 1024,
    poll_every: int = 4,
    donate: bool = True,
    on_chunk=None,
    max_chunks: Optional[int] = None,
):
    """Build ``run(sims) -> sims`` over a batched Sim: the chunked,
    device-resident twin of ``jit(vmap(make_run(spec)))``.

    One jitted chunk program advances every lane at most ``chunk_steps``
    dispatches; the host re-dispatches it with ``donate_argnums`` so the
    batched Sim stays on device with ZERO inter-chunk copies (XLA
    aliases each chunk's input buffers to its outputs), polling the
    ``any_live`` scalar every ``poll_every`` chunks (see
    :func:`drive_chunks`).  Trajectories are bitwise the monolithic
    run's — chunking only splits the while loop across dispatches — but
    no single device program runs longer than one chunk, so runs of any
    length clear the TPU runtime's ~3-minute program watchdog
    (docs/12_streaming.md).

    The jitted chunk is exposed as ``run.chunk`` (tests verify its
    donation) and compiles ONCE per batch shape — warm re-runs reuse it.
    """
    chunk = jax.jit(
        make_chunk(spec, t_end=t_end, pack=pack, max_steps=chunk_steps),
        donate_argnums=(0,) if donate else (),
    )

    def run(sims: Sim) -> Sim:
        return drive_chunks(
            chunk, sims, poll_every=poll_every, on_chunk=on_chunk,
            max_chunks=max_chunks,
        )

    run.chunk = chunk
    return run
