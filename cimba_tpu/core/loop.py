"""The event loop: one replication's dispatcher, jit-compiled and vmapped.

Reference parity: ``cmb_event_queue_execute`` (`src/cmb_event.c:296-335`)
— pop next event, advance the clock, run the action, repeat — where the
action context-switches into a coroutine until it yields
(`src/cmb_process.c:329-375`).

TPU rendition (the "fiber scheduler lowered to an XLA while-loop" of the
north star): ``make_run`` builds ``lax.while_loop(cond, step, sim)`` where
``step`` pops from the flat event set, advances the batched clock, and
dispatches through ``lax.switch``:

* kind 0 = process wakeup: resume the subject process — an inner bounded
  while_loop runs its current block (``lax.switch`` over the model's block
  table) and applies the returned command, chaining while commands complete
  without yielding.  This is exactly a coroutine running until it waits,
  with (pc, locals) rows instead of a C stack.
* kinds >= 1 = user handlers (parity: arbitrary (action, subject, object)
  events).

Everything is scalar-style over a single replication's :class:`Sim`;
``jax.vmap`` supplies the replication axis and ``shard_map`` the mesh
(runner/).  Blocked commands pend on guards and are *re-attempted* on
wakeup, which reproduces the reference's loop-around-guard-wait fairness
protocol (`src/cmb_resource.c:202-233`).

Failure containment (parity: §3.5 error recovery, `src/cimba.c:185-209`):
any structural failure — event/guard overflow, non-finite time, a block
chain that never yields — sets ``sim.err`` and freezes the replication;
the experiment runner counts and masks it, and the other replications in
the batch are unaffected.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from cimba_tpu.config import INDEX_DTYPE, REAL_DTYPE, TIME_DTYPE
from cimba_tpu.core import eventset as ev
from cimba_tpu.core import guard as gd
from cimba_tpu.core import process as pr
from cimba_tpu.core.model import ModelSpec
from cimba_tpu.random import bits as rb
from cimba_tpu.stats import timeseries as ts

_I = INDEX_DTYPE
_R = REAL_DTYPE
_T = TIME_DTYPE

K_PROC = 0  # event kind: resume process `subj` with signal `arg`

# chain-safety bound: a process may not execute more than this many blocks
# without yielding (a JUMP cycle would otherwise hang the whole batch)
MAX_CHAIN = 1024

# error codes (sim.err)
ERR_NONE = 0
ERR_EVENT_OVERFLOW = 1
ERR_GUARD_OVERFLOW = 2
ERR_CHAIN_RUNAWAY = 3
ERR_USER = 4
ERR_BAD_RELEASE = 5


class Queues(NamedTuple):
    items: jnp.ndarray  # [NQ, QCAP] f64 ring buffers
    head: jnp.ndarray   # [NQ] i32
    size: jnp.ndarray   # [NQ] i32
    acc: ts.StepAccum   # leaves [NQ]: queue-length recording


class Resources(NamedTuple):
    holder: jnp.ndarray  # [NR] i32, -1 = free
    acc: ts.StepAccum    # leaves [NR]: utilization recording


class Sim(NamedTuple):
    """One replication's full state."""

    clock: jnp.ndarray
    rng: rb.RandomState
    events: ev.EventSet
    procs: pr.Procs
    guards: gd.Guards
    queues: Queues
    resources: Resources
    user: Any
    done: jnp.ndarray      # bool, set by model code (api.stop)
    err: jnp.ndarray       # i32, ERR_* (0 = healthy)
    n_events: jnp.ndarray  # i64, dispatched events (bench metric)


def _tree_select(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def _batched(tree, n):
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x, (n,) + jnp.shape(x)), tree
    )


def init_sim(spec: ModelSpec, seed, replication, params=None, t0=0.0) -> Sim:
    """Build one replication's initial state and schedule process starts
    (parity: the trial-init sequence `benchmark/MM1_multi.c:91-124`)."""
    nq = max(len(spec.queues), 1)
    nr = max(len(spec.resources), 1)
    events = ev.create(spec.event_cap)
    procs = pr.create(
        spec.proc_entry, spec.proc_prio, spec.n_flocals, spec.n_ilocals
    )
    # start events, in pid order (FIFO among simultaneous starts)
    for pid in range(spec.n_procs):
        events, _ = ev.schedule(
            events, t0, int(spec.proc_prio[pid]), K_PROC, pid, pr.SUCCESS
        )
    procs = procs._replace(
        status=jnp.full((spec.n_procs,), pr.RUNNING, _I)
    )
    user = spec.user_init(params) if spec.user_init else jnp.zeros(())
    t0 = jnp.asarray(t0, _T)
    return Sim(
        clock=t0,
        rng=rb.initialize(seed, replication),
        events=events,
        procs=procs,
        guards=gd.create(spec.n_guards, spec.guard_cap),
        queues=Queues(
            items=jnp.zeros((nq, spec.queue_cap_max), _R),
            head=jnp.zeros((nq,), _I),
            size=jnp.zeros((nq,), _I),
            acc=_batched(ts.step_create(t0, 0.0), nq),
        ),
        resources=Resources(
            holder=jnp.full((nr,), -1, _I),
            acc=_batched(ts.step_create(t0, 0.0), nr),
        ),
        user=user,
        done=jnp.asarray(False),
        # an event_cap too small for even the start events is a failed
        # replication from step zero
        err=jnp.where(
            events.overflow, jnp.asarray(ERR_EVENT_OVERFLOW, _I), jnp.zeros((), _I)
        ),
        n_events=jnp.zeros((), jnp.int64),
    )


# --- micro-ops on Sim --------------------------------------------------------


def _set_err(sim: Sim, pred, code) -> Sim:
    return sim._replace(
        err=jnp.where((sim.err == 0) & pred, jnp.asarray(code, _I), sim.err)
    )


def _schedule_if(sim: Sim, pred, t, prio, kind, subj, arg) -> Sim:
    es2, _ = ev.schedule(sim.events, t, prio, kind, subj, arg)
    es2 = _tree_select(pred, es2, sim.events)
    sim = sim._replace(events=es2)
    return _set_err(sim, es2.overflow, ERR_EVENT_OVERFLOW)


def _guard_signal(sim: Sim, gid) -> Sim:
    """Wake the best waiter (if any): schedule its retry at the current
    time with its process priority (parity: cmb_resourceguard_signal
    scheduling wakeup events rather than switching directly)."""
    g2, pid = gd.pop_best(sim.guards, gid)
    woke = pid != gd.NO_PID
    p = jnp.maximum(pid, 0)
    sim = sim._replace(guards=g2)
    return _schedule_if(
        sim, woke, sim.clock, sim.procs.prio[p], K_PROC, p, pr.SUCCESS
    )


def _guard_wait(sim: Sim, p, gid, cmd: pr.Command) -> Sim:
    """Pend the blocked command and enqueue the process on the guard."""
    procs = sim.procs._replace(
        pend_tag=sim.procs.pend_tag.at[p].set(cmd.tag),
        pend_f=sim.procs.pend_f.at[p].set(cmd.f),
        pend_i=sim.procs.pend_i.at[p].set(cmd.i),
        pend_pc=sim.procs.pend_pc.at[p].set(cmd.next_pc),
    )
    g2, ok = gd.enqueue(sim.guards, gid, p, sim.procs.prio[p])
    sim = sim._replace(procs=procs, guards=g2)
    return _set_err(sim, ~ok, ERR_GUARD_OVERFLOW)


def _record_row(acc: ts.StepAccum, row, t, v) -> ts.StepAccum:
    """step_record on one row of a batched StepAccum."""
    one = jax.tree.map(lambda x: x[row], acc)
    upd = ts.step_record(one, t, v)
    return jax.tree.map(lambda a, u: a.at[row].set(u), acc, upd)


# --- command handlers ---------------------------------------------------------


def _make_apply(spec: ModelSpec):
    q_cap = jnp.asarray(
        [q.capacity for q in spec.queues] or [1], _I
    )
    q_front = jnp.asarray([q.front_guard for q in spec.queues] or [0], _I)
    q_rear = jnp.asarray([q.rear_guard for q in spec.queues] or [0], _I)
    r_guard = jnp.asarray([r.guard for r in spec.resources] or [0], _I)

    def set_pc(sim, p, pc):
        return sim._replace(
            procs=sim.procs._replace(pc=sim.procs.pc.at[p].set(pc))
        )

    def h_hold(sim: Sim, p, cmd: pr.Command):
        dur = jnp.maximum(cmd.f, 0.0)
        es2, handle = ev.schedule(
            sim.events, sim.clock + dur, sim.procs.prio[p], K_PROC, p,
            pr.SUCCESS,
        )
        sim = sim._replace(
            events=es2,
            procs=sim.procs._replace(
                wake_handle=sim.procs.wake_handle.at[p].set(handle),
                pc=sim.procs.pc.at[p].set(cmd.next_pc),
            ),
        )
        sim = _set_err(sim, es2.overflow, ERR_EVENT_OVERFLOW)
        return sim, jnp.asarray(True)

    def h_exit(sim: Sim, p, cmd: pr.Command):
        sim = sim._replace(
            procs=sim.procs._replace(
                status=sim.procs.status.at[p].set(pr.FINISHED)
            )
        )
        return sim, jnp.asarray(True)

    def h_jump(sim: Sim, p, cmd: pr.Command):
        return set_pc(sim, p, cmd.next_pc), jnp.asarray(False)

    def h_put(sim: Sim, p, cmd: pr.Command):
        qid = cmd.i
        size = sim.queues.size[qid]
        cap = q_cap[qid]
        full = size >= cap

        # proceed path: ring insert at (head + size) mod cap (cap <= phys)
        col = (sim.queues.head[qid] + size) % cap
        q2 = Queues(
            items=sim.queues.items.at[qid, col].set(cmd.f),
            head=sim.queues.head,
            size=sim.queues.size.at[qid].add(1),
            acc=_record_row(
                sim.queues.acc, qid, sim.clock, (size + 1).astype(_R)
            ),
        )
        ok_sim = sim._replace(queues=q2)
        ok_sim = _guard_signal(ok_sim, q_front[qid])
        ok_sim = set_pc(ok_sim, p, cmd.next_pc)

        blocked_sim = _guard_wait(sim, p, q_rear[qid], cmd)
        return _tree_select(full, blocked_sim, ok_sim), full

    def h_get(sim: Sim, p, cmd: pr.Command):
        qid = cmd.i
        size = sim.queues.size[qid]
        empty = size <= 0
        cap = q_cap[qid]

        head = sim.queues.head[qid]
        item = sim.queues.items[qid, head]
        q2 = Queues(
            items=sim.queues.items,
            head=sim.queues.head.at[qid].set((head + 1) % cap),
            size=sim.queues.size.at[qid].add(-1),
            acc=_record_row(
                sim.queues.acc, qid, sim.clock, (size - 1).astype(_R)
            ),
        )
        ok_sim = sim._replace(
            queues=q2,
            procs=sim.procs._replace(got=sim.procs.got.at[p].set(item)),
        )
        ok_sim = _guard_signal(ok_sim, q_rear[qid])
        ok_sim = set_pc(ok_sim, p, cmd.next_pc)

        blocked_sim = _guard_wait(sim, p, q_front[qid], cmd)
        return _tree_select(empty, blocked_sim, ok_sim), empty

    def h_acquire(sim: Sim, p, cmd: pr.Command):
        rid = cmd.i
        free = sim.resources.holder[rid] < 0
        may_grab = gd.is_empty(sim.guards, r_guard[rid])
        ok = free & may_grab

        r2 = Resources(
            holder=sim.resources.holder.at[rid].set(p),
            acc=_record_row(sim.resources.acc, rid, sim.clock, 1.0),
        )
        ok_sim = sim._replace(resources=r2)
        ok_sim = set_pc(ok_sim, p, cmd.next_pc)

        blocked_sim = _guard_wait(sim, p, r_guard[rid], cmd)
        return _tree_select(~ok, blocked_sim, ok_sim), ~ok

    def h_release(sim: Sim, p, cmd: pr.Command):
        rid = cmd.i
        owner_ok = sim.resources.holder[rid] == p
        r2 = Resources(
            holder=sim.resources.holder.at[rid].set(-1),
            acc=_record_row(sim.resources.acc, rid, sim.clock, 0.0),
        )
        sim2 = sim._replace(resources=r2)
        sim2 = _guard_signal(sim2, r_guard[rid])
        sim2 = set_pc(sim2, p, cmd.next_pc)
        sim2 = _set_err(sim2, ~owner_ok, ERR_BAD_RELEASE)
        return sim2, jnp.asarray(False)

    handlers = [h_hold, h_exit, h_jump, h_put, h_get, h_acquire, h_release]

    def apply_command(sim: Sim, p, cmd: pr.Command):
        return lax.switch(
            jnp.clip(cmd.tag, 0, pr.N_COMMANDS - 1), handlers, sim, p, cmd
        )

    return apply_command


# --- the dispatcher -----------------------------------------------------------


def make_step(spec: ModelSpec):
    """Build ``step(sim) -> sim`` dispatching exactly one event."""
    apply_command = _make_apply(spec)
    blocks = list(spec.blocks)

    def run_block(sim: Sim, p, sig):
        return lax.switch(
            jnp.clip(sim.procs.pc[p], 0, len(blocks) - 1),
            blocks,
            sim,
            p,
            sig,
        )

    def resume(sim: Sim, p, sig):
        """Resume process p: retry a pending command if one exists, then
        chain blocks until something yields."""
        pend = pr.Command(
            sim.procs.pend_tag[p],
            sim.procs.pend_f[p],
            sim.procs.pend_i[p],
            sim.procs.pend_pc[p],
        )
        has_pend = pend.tag != pr.NO_PEND
        sim = sim._replace(
            procs=sim.procs._replace(
                pend_tag=sim.procs.pend_tag.at[p].set(pr.NO_PEND)
            )
        )
        # retry pending op (or no-op)
        retried, ry = apply_command(sim, p, pend)
        sim = _tree_select(has_pend, retried, sim)
        yielded = has_pend & ry

        def cond(carry):
            sim, sig, yielded, n = carry
            alive = (sim.procs.status[p] == pr.RUNNING) & (sim.err == 0)
            return ~yielded & alive & (n < MAX_CHAIN)

        def body(carry):
            sim, sig, _, n = carry
            sim, cmd = run_block(sim, p, sig)
            sim, yielded = apply_command(sim, p, cmd)
            return sim, jnp.asarray(pr.SUCCESS, _I), yielded, n + 1

        sim, _, yielded, n = lax.while_loop(
            cond, body, (sim, jnp.asarray(sig, _I), yielded, jnp.zeros((), _I))
        )
        return _set_err(sim, n >= MAX_CHAIN, ERR_CHAIN_RUNAWAY)

    def on_proc(sim: Sim, subj, arg):
        alive = sim.procs.status[subj] == pr.RUNNING
        resumed = resume(sim, subj, arg)
        return _tree_select(alive, resumed, sim)

    user_handlers = [
        (lambda fn: (lambda sim, subj, arg: fn(sim, subj, arg)))(fn)
        for fn in spec.user_handlers
    ]
    dispatch_fns = [on_proc] + user_handlers

    def step(sim: Sim) -> Sim:
        es2, event = ev.pop(sim.events)
        sim = sim._replace(
            events=es2,
            clock=jnp.where(event.found, event.time, sim.clock),
            n_events=sim.n_events + jnp.where(event.found, 1, 0).astype(jnp.int64),
            done=sim.done | ~event.found,
        )
        dispatched = lax.switch(
            jnp.clip(event.kind, 0, len(dispatch_fns) - 1),
            dispatch_fns,
            sim,
            event.subj,
            event.arg,
        )
        return _tree_select(event.found, dispatched, sim)

    return step


def make_run(spec: ModelSpec, t_end: Optional[float] = None):
    """Build ``run(sim) -> sim``: dispatch events until the model stops
    (api.stop), fails, runs out of events, or passes ``t_end``
    (parity: cmb_event_queue_execute; t_end plays the role of the
    user-scheduled end event)."""
    step = make_step(spec)

    def cond(sim: Sim):
        live = ~sim.done & (sim.err == 0) & ~ev.is_empty(sim.events)
        if t_end is not None:
            nxt = jnp.min(sim.events.time)
            live = live & (nxt <= t_end)
        return live

    def run(sim: Sim) -> Sim:
        return lax.while_loop(cond, step, sim)

    return run