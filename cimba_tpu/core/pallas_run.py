"""The Pallas mega-kernel event loop: whole-run stepping in VMEM.

Reference parity: this is the TPU answer to the reference's hot loop —
``cmb_event_queue_execute`` (`src/cmb_event.c:296-335`) popping from the
hashheap (`src/cmi_hashheap.c:454-522`) at ~6M events/s/core.

Why it exists: running the interpreter as a plain XLA ``lax.while_loop``
costs ~3.5 ms of sequential fused-kernel latency *per event* plus one HBM
round-trip of the whole batched Sim per step (measured, BENCH_NOTES.md) —
five orders of magnitude off the reference.  Here the *entire run* executes
inside one ``pallas_call``: every Sim leaf lives in VMEM for the duration,
steps happen back-to-back on the VPU with no kernel-dispatch or HBM cost
per event.

Design:

* **Same interpreter.**  The kernel body calls ``loop.make_step(spec)`` —
  the exact dispatcher the XLA path runs — under ``jax.vmap``; there is no
  second implementation of the engine semantics (the f64 XLA path stays the
  bit-exact oracle; tests compare the two).
* **f32 profile.**  Mosaic has no 64-bit types, so the kernel traces under
  ``config.profile("f32")`` (f32 clock/statistics, i32 counters).  The
  caller owns profile selection: build spec + init under f32, run here.
* **Lane-last layout.**  A batched leaf is ``[component_dims..., L]`` with
  the replication lane axis *last*, so lanes map onto the 128-wide VPU lane
  dimension and small component axes (event slots, processes) land on
  sublanes.  ``vmap(step, in_axes=-1)`` batches the interpreter; vmap's
  while-loop batching rule turns per-lane loops into any-lane loops with
  select masking, which Mosaic lowers fine.
* **Chunked calls.**  One kernel invocation advances every lane by up to
  ``chunk_steps`` events (VMEM residency bounds per-call wall time under
  the device watchdog); an outer XLA while-loop re-invokes until every
  lane is done.  Each re-invocation costs one HBM round-trip of the Sim —
  amortized over ``chunk_steps`` events it is noise.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cimba_tpu import config
from cimba_tpu.core import loop as cl
from cimba_tpu.core.model import ModelSpec


def _to_lane_last(tree):
    return jax.tree.map(lambda x: jnp.moveaxis(x, 0, -1), tree)


def _to_lane_first(tree):
    return jax.tree.map(lambda x: jnp.moveaxis(x, -1, 0), tree)


def make_kernel_run(
    spec: ModelSpec,
    t_end: Optional[float] = None,
    chunk_steps: int = 512,
    max_chunks: int = 10_000,
    interpret: bool = False,
    single_step: bool = False,
):
    """Build ``run(sims) -> sims`` where ``sims`` is a lane-FIRST batched
    Sim (the shape ``jax.vmap(init_sim)`` produces) and every lane is
    advanced to completion inside Pallas kernels.

    Must be built and called under the f32 profile
    (``config.use_profile("f32")``); raises otherwise — Mosaic cannot
    represent 64-bit leaves.
    """
    if config.active_profile() != "f32":
        raise ValueError(
            "make_kernel_run requires config.profile('f32') — Mosaic has "
            "no 64-bit types; build the spec and init_sim under f32 too"
        )
    step = cl.make_step(spec)
    cond = cl.make_cond(spec, t_end)

    vstep = jax.vmap(step, in_axes=-1, out_axes=-1)
    vcond_lane = jax.vmap(cond, in_axes=-1)

    def batched_chunk(sim):
        """Advance every lane by up to chunk_steps events.  The while-loop
        is written batched by hand (scalar any-lane condition + explicit
        per-lane masking) because a vmapped while's vector condition does
        not lower in Mosaic; leaves are lane-last, so the [L] mask
        broadcasts against [..., L] leaves."""

        def wcond(carry):
            sim, k = carry
            return (k < chunk_steps) & jnp.any(vcond_lane(sim))

        def lane_sel(live, x, y):
            """Mosaic-safe ``where(live, x, y)`` for lane-LAST leaves: the
            [L] mask broadcasts across *major* dims, and the rank expansion
            plus any bool-payload select are routed through i32 (Mosaic
            supports neither i1 broadcasts into select_n nor i1 payloads —
            dyn.bwhere covers the lane-first case, this the lane-last)."""
            if x is y:
                return x
            m = jnp.broadcast_to(live.astype(jnp.int32), x.shape) != 0
            if x.dtype == jnp.bool_:
                return (m & x) | (~m & y)
            return jnp.where(m, x, y)

        def wbody(carry):
            sim, k = carry
            live = vcond_lane(sim)
            sim2 = vstep(sim)
            sim = jax.tree.map(
                lambda x, y: lane_sel(live, x, y), sim2, sim
            )
            return sim, k + 1

        if single_step:
            # bisect aid (tools/mosaic_bisect.py): one masked step, no
            # while loop — separates step-lowering bugs from loop-lowering
            sim, _ = wbody((sim, jnp.zeros((), jnp.int32)))
            return sim
        sim, _ = lax.while_loop(
            wcond, wbody, (sim, jnp.zeros((), jnp.int32))
        )
        return sim

    def kernel(jaxpr, const_info, n, *refs):
        nc = sum(1 for kind, _ in const_info if kind == "in")
        in_refs = refs[:n]
        const_refs = list(refs[n : n + nc])
        out_refs = refs[n + nc :]
        consts = []
        for kind, payload in const_info:
            if kind == "in":
                shape, size = payload
                ref = const_refs.pop(0)
                vals = [ref[i] for i in range(size)]  # SMEM: scalar loads
                c = vals[0] if shape == () else jnp.stack(vals).reshape(shape)
                consts.append(c)
            else:
                consts.append(payload)
        args = [r[...] for r in in_refs]
        outs = jax.core.eval_jaxpr(jaxpr, consts, *args)
        for r, leaf in zip(out_refs, outs):
            r[...] = leaf

    def build_chunk_call(leaves, treedef):
        """Trace the batched chunk to a jaxpr, hoist its array constants
        (Pallas kernels cannot capture them and jax.closure_convert hoists
        only float consts), and wrap it in a pallas_call.  Returns
        ``(chunk_fn, consts_in)`` where ``chunk_fn(*leaves)`` advances
        every lane by one chunk.  Exposed for tools/mosaic_bisect.py."""
        n = len(leaves)
        config.KERNEL_MODE = True
        try:
            flat_chunk = jax.make_jaxpr(
                lambda *ls: jax.tree.leaves(
                    batched_chunk(jax.tree.unflatten(treedef, ls))
                )
            )(*leaves)
        finally:
            config.KERNEL_MODE = False
        _maybe_dump_64bit(flat_chunk)
        const_info = []  # ("in", shape) for shipped arrays, ("lit", value)
        consts_in = []
        import numpy as _np

        for c in flat_chunk.consts:
            if isinstance(c, (jax.Array, _np.ndarray)):
                const_info.append(("in", (jnp.shape(c), jnp.size(c))))
                # integer tables ride in SMEM; rank>=1 at the boundary
                consts_in.append(jnp.reshape(c, (-1,)))
            else:
                const_info.append(("lit", c))
        chunk_call = pl.pallas_call(
            partial(kernel, flat_chunk.jaxpr, const_info, n),
            out_shape=[jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves],
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * n
            + [pl.BlockSpec(memory_space=pltpu.SMEM)] * len(consts_in),
            out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * n,
            input_output_aliases={i: i for i in range(n)},
            interpret=interpret,
        )
        return (lambda *ls: chunk_call(*ls, *consts_in)), consts_in

    def run(sims):
        # Host-level driver, NOT for use under an outer jit.  The whole
        # kernel path — tracing, Mosaic lowering AND compilation — must
        # happen with x64 off: under x64, fori_loop counters, weak
        # Python-int literals and iinfo bounds materialize as int64
        # (Mosaic's 64->32 convert rule recurses forever), and Mosaic's
        # own lower_fun helpers re-trace reduction identities as f64.
        # Lowering runs at first call of the inner jit, so the first chunk
        # invocation sits inside this scope too.  Init (u64 seed mixing)
        # stays outside, under the session's x64 setting.
        with jax.enable_x64(False):
            return _run(sims)

    def _run(sims):
        sims = _to_lane_last(sims)
        leaves, treedef = jax.tree.flatten(sims)

        chunk_fn, _ = build_chunk_call(leaves, treedef)

        # Chunks are dispatched from the host: each call is bounded device
        # time (well under the runtime watchdog), the any-lane-live check
        # costs one tiny jitted reduction between chunks, and — decisive —
        # compilation of the chunk happens on its first call, still inside
        # the x64-off scope above.
        chunk_jit = jax.jit(chunk_fn)
        alive_jit = jax.jit(
            lambda *ls: jnp.any(vcond_lane(jax.tree.unflatten(treedef, ls)))
        )
        it = 0
        while bool(alive_jit(*leaves)) and it < max_chunks:
            leaves = chunk_jit(*leaves)
            it += 1
        if it >= max_chunks and bool(alive_jit(*leaves)):
            raise RuntimeError(
                f"make_kernel_run: lanes still live after max_chunks="
                f"{max_chunks} x chunk_steps={chunk_steps} events — raise "
                "one of them (a silent partial run would corrupt statistics)"
            )
        sims = jax.tree.unflatten(treedef, leaves)
        return _to_lane_first(sims)

    run.build_chunk_call = build_chunk_call
    return run


def _maybe_dump_64bit(closed_jaxpr):
    """CIMBA_KERNEL_DEBUG=1: print every 64-bit-typed value in the chunk
    jaxpr with its source line (Mosaic has no 64-bit types; anything listed
    here will fail to lower)."""
    import os as _os

    if not _os.environ.get("CIMBA_KERNEL_DEBUG"):
        return
    seen = set()

    def _walk(jaxpr):
        for eqn in jaxpr.eqns:
            for v in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(v, "aval", None)
                if (
                    aval is not None
                    and hasattr(aval, "dtype")
                    and aval.dtype.itemsize == 8
                ):
                    src = jax._src.source_info_util.summarize(eqn.source_info)
                    key = (str(eqn.primitive), str(aval.dtype), src)
                    if key not in seen:
                        seen.add(key)
                        print("KERNEL64:", key)
            for val in eqn.params.values():
                vals = val if isinstance(val, (list, tuple)) else [val]
                for v2 in vals:
                    j2 = getattr(v2, "jaxpr", None)
                    if j2 is not None:
                        _walk(j2 if hasattr(j2, "eqns") else j2.jaxpr)

    _walk(closed_jaxpr.jaxpr)
