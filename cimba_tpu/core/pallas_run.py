"""The Pallas mega-kernel event loop: whole-run stepping in VMEM.

Reference parity: this is the TPU answer to the reference's hot loop —
``cmb_event_queue_execute`` (`src/cmb_event.c:296-335`) popping from the
hashheap (`src/cmi_hashheap.c:454-522`) at ~6M events/s/core.

Why it exists: running the interpreter as a plain XLA ``lax.while_loop``
costs ~3.5 ms of sequential fused-kernel latency *per event* plus one HBM
round-trip of the whole batched Sim per step (measured, BENCH_NOTES.md) —
five orders of magnitude off the reference.  Here the *entire run* executes
inside one ``pallas_call``: every Sim leaf lives in VMEM for the duration,
steps happen back-to-back on the VPU with no kernel-dispatch or HBM cost
per event.

Design:

* **Same interpreter.**  The kernel body evaluates the jaxpr of
  ``loop.make_step(spec)`` — the exact dispatcher the XLA path runs; there
  is no second implementation of the engine semantics (the f64 XLA path
  stays the bit-exact oracle; tests compare the two).
* **f32 profile.**  Mosaic has no 64-bit types, so the kernel traces under
  ``config.profile("f32")`` (f32 clock/statistics, i32 counters).  The
  caller owns profile selection: build spec + init under f32, run here.
* **Lane-LAST layout, hand-batched.**  In the kernel a batched leaf is
  ``[component_dims..., L]`` with the replication lane axis last, so lanes
  sit on the 128-wide minor dim of every Mosaic tile and per-lane scalars
  (clock, pc — the hot values) are full native rows.  Crucially the
  batching is NOT ``jax.vmap``: vmap's reshape/broadcast batching rules
  normalize batch dims to axis 0 and emit minor-axis transposes that the
  Mosaic layout pass rejects (bisected in round 2).  ``core/lanelast.py``
  re-batches the per-lane step jaxpr with lanes pinned last;
  ``core/bool32.py`` then rewrites every i1 vector to an i32 carrier
  (i1 logic chains and i1<->i32 converts also crash the layout pass).
* **Chunked calls.**  One kernel invocation advances every lane by up to
  ``chunk_steps`` events (VMEM residency bounds per-call wall time under
  the device watchdog); an outer host loop re-invokes until every lane is
  done.  Each re-invocation costs one HBM round-trip of the Sim —
  amortized over ``chunk_steps`` events it is noise.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cimba_tpu import config
from cimba_tpu.core import bool32, carry, dyn, lanelast
from cimba_tpu.core import loop as cl
from cimba_tpu.core.model import ModelSpec


def _vmem_limit_bytes(lane_block=None) -> int:
    """Mosaic scoped-vmem budget for the chunk kernel, in bytes.

    Default 96 MiB (v5e has 128 MiB; the 16 MiB Mosaic default rejects
    the whole-Sim-resident kernel above L≈1024 — measured offline,
    BENCH_NOTES round 4); 110 MiB under lane blocking (the grid's DMA
    double-buffering adds a few MiB — an Lb=8192 block measured 97.3
    MiB offline, 1.3 over the plain budget).  Override with
    ``CIMBA_KERNEL_VMEM_LIMIT``."""
    raw = config.env_raw("CIMBA_KERNEL_VMEM_LIMIT").strip()
    if not raw:
        return (110 if lane_block else 96) * 1024 * 1024
    try:
        return int(raw)
    except ValueError as e:
        raise ValueError(
            f"CIMBA_KERNEL_VMEM_LIMIT must be an integer byte count, "
            f"got {raw!r}"
        ) from e


# Carry packing (see core/carry.py — ONE implementation serves this
# kernel chunk loop and loop.make_run's packed XLA while-loop, so the
# two hot paths can never diverge on buffer layout).  The lane-last
# aliases below keep this module's historical API: the chunk's batched
# leaves are [comp..., L] and pack into [rows, L] buffers.  Why packing
# at all: Mosaic's per-iteration cost of the chunk while-loop scales
# super-linearly with the number of narrow carried leaves — measured on
# v5e (BENCH_NOTES round-5 floor probes): mm1's real 54-leaf carry
# costs ~135 us/step with a TRIVIAL body, the same bytes in a few wide
# f32 buffers <1 us.


def _pack_plan(avals):
    return carry.pack_plan(avals, lane_last=True)


def _pack(leaves, plan):
    return carry.pack(leaves, plan)


def _unpack(packed, plan, L):
    return carry.unpack(packed, plan, L)


def make_kernel_run(
    spec: ModelSpec,
    t_end: Optional[float] = None,
    chunk_steps: int = 512,
    max_chunks: int = 10_000,
    interpret: bool = False,
    single_step: bool = False,
    mesh=None,
    packed: Optional[bool] = None,
    lane_block: Optional[int] = None,
):
    """Build ``run(sims) -> sims`` where ``sims`` is a lane-FIRST batched
    Sim (the shape ``jax.vmap(init_sim)`` produces) and every lane is
    advanced to completion inside Pallas kernels.

    Must be built and called under the f32 profile
    (``config.use_profile("f32")``); raises otherwise — Mosaic cannot
    represent 64-bit leaves.

    ``mesh``: a 1-D ``jax.sharding.Mesh`` to shard lanes over.  Each
    device runs the SAME chunk kernel on its local lane block
    (``shard_map`` over the minor lane axis — reference parity: one event
    loop per worker thread, `src/cimba.c:156-221`); the host loop drives
    all devices in lockstep on a global any-lane-live check, so devices
    whose lanes finished early idle-mask until the slowest is done.  This
    composes with the all_gather statistics merge in
    ``runner.experiment`` — together they are the v5e-8 path.
    """
    if config.active_profile() != "f32":
        raise ValueError(
            "make_kernel_run requires config.profile('f32') — Mosaic has "
            "no 64-bit types; build the spec and init_sim under f32 too"
        )
    if packed is None:
        # carry packing (see _pack_plan): opt-in via env until measured
        # faster on hardware, then flip the default
        packed = config.env_raw("CIMBA_KERNEL_PACK") != "0"
    if lane_block is None:
        # lane blocking: run the chunk as a pallas GRID over lane
        # blocks — VMEM holds ONE block's Sim (so total lanes are no
        # longer VMEM-capped), Mosaic compiles a block-sized program
        # (so compile time stops growing with total lanes), and one
        # launch advances every block (amortizing the ~75 ms/launch
        # host overhead over L/lane_block more events).  Lanes are
        # independent, so per-block while-loops are trajectory-
        # identical to the monolithic form: each block just exits its
        # loop when its own lanes are done.
        raw = config.env_raw("CIMBA_KERNEL_LANE_BLOCK").strip()
        try:
            lane_block = int(raw) if raw else None
        except ValueError as e:
            raise ValueError(
                f"CIMBA_KERNEL_LANE_BLOCK must be an integer lane count, "
                f"got {raw!r}"
            ) from e
    step = cl.make_step(spec)
    cond = cl.make_cond(spec, t_end)

    def trace_chunk(leaves, treedef):
        """``leaves`` are LANE-LAST ([comp..., L]).  Trace the per-lane
        step/cond, batch them lane-last (core/lanelast.py), assemble the
        chunk loop, and bool32-rewrite it.  Returns ``(flat_chunk,
        bool_idx, carrier_avals)`` — the exact program the kernel runs
        (tools/mosaic_eqn_bisect.py bisects THIS, so tool and kernel can
        never diverge)."""
        L = leaves[0].shape[-1]
        per_avals = [
            jax.ShapeDtypeStruct(l.shape[:-1], l.dtype) for l in leaves
        ]
        config.KERNEL_MODE = True
        try:
            # one-hot memo scoped per trace: repeated accesses at the
            # same pid/slot index share a single iota==i mask (cleared
            # between traces so no tracer crosses jaxprs)
            with dyn.oh_cache():
                step_j = jax.make_jaxpr(
                    lambda *ls: jax.tree.leaves(
                        step(jax.tree.unflatten(treedef, ls))
                    )
                )(*per_avals)
            with dyn.oh_cache():
                cond_j = jax.make_jaxpr(
                    lambda *ls: cond(jax.tree.unflatten(treedef, ls))
                )(*per_avals)
        finally:
            config.KERNEL_MODE = False
        _maybe_dump_64bit(step_j)

        def vstep(ls):
            outs = lanelast.eval_lanelast(
                step_j.jaxpr,
                step_j.consts,
                L,
                [lanelast._Val(x, True) for x in ls],
            )
            return [
                lanelast._promote(o, v.aval, L)
                for o, v in zip(outs, step_j.jaxpr.outvars)
            ]

        def vcond(ls):
            (o,) = lanelast.eval_lanelast(
                cond_j.jaxpr,
                cond_j.consts,
                L,
                [lanelast._Val(x, True) for x in ls],
            )
            return lanelast._promote(o, cond_j.jaxpr.outvars[0].aval, L)

        def batched_chunk(*ls):
            """Advance every lane by up to chunk_steps events: a scalar
            any-lane-live condition with per-lane select masking.  The
            [L] mask broadcasts against [comp..., L] leaves over leading
            dims — the one broadcast direction Mosaic always supports."""

            def wcond(carry):
                ls, k = carry
                return (k < chunk_steps) & jnp.any(vcond(list(ls)))

            def wbody(carry):
                ls, k = carry
                live = vcond(list(ls))
                new = vstep(list(ls))
                out = tuple(
                    x if x is y else jnp.where(live, x, y)
                    for x, y in zip(new, ls)
                )
                return out, k + 1

            if single_step:
                # bisect aid (tools/mosaic_bisect.py): one masked step,
                # no loop — separates step bugs from loop bugs
                out, _ = wbody((tuple(ls), jnp.zeros((), jnp.int32)))
                return list(out)
            if packed:
                # packed carry: the while loop carries 2-5 wide buffers
                # instead of ~54 narrow leaves (see _pack_plan); the
                # body unpacks, steps, repacks, and applies the live
                # mask per BUFFER (one wide select each) instead of
                # per leaf
                plan = _pack_plan(
                    [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in ls]
                )

                def pcond(carry):
                    bufs, k = carry
                    return (k < chunk_steps) & jnp.any(
                        vcond(_unpack(list(bufs), plan, L))
                    )

                def pbody(carry):
                    bufs, k = carry
                    ls2 = _unpack(list(bufs), plan, L)
                    live = vcond(ls2)
                    new = vstep(ls2)
                    nbufs = _pack(new, plan)
                    merged = tuple(
                        b if b is nb else jnp.where(live, nb, b)
                        for b, nb in zip(bufs, nbufs)
                    )
                    return merged, k + 1

                out, _ = lax.while_loop(
                    pcond,
                    pbody,
                    (tuple(_pack(list(ls), plan)), jnp.zeros((), jnp.int32)),
                )
                return _unpack(list(out), plan, L)
            out, _ = lax.while_loop(
                wcond, wbody, (tuple(ls), jnp.zeros((), jnp.int32))
            )
            return list(out)

        flat_chunk = jax.make_jaxpr(batched_chunk)(*leaves)

        # eliminate i1 vectors: bool leaves become i32 carriers at the
        # kernel boundary and every logic op inside runs bitwise on i32
        # (core/bool32.py — the Mosaic layout pass check-fails on i1
        # logic chains and i1<->i32 converts, bisected)
        bool_idx = frozenset(
            i for i, l in enumerate(leaves) if l.dtype == jnp.bool_
        )
        carrier_avals = [
            jax.ShapeDtypeStruct(
                l.shape, jnp.int32 if i in bool_idx else l.dtype
            )
            for i, l in enumerate(leaves)
        ]
        flat_chunk = bool32.transform(flat_chunk, carrier_avals)
        return flat_chunk, bool_idx, carrier_avals

    def build_chunk_call(leaves, treedef):
        """trace_chunk + constant hoisting to SMEM + the pallas_call.
        Returns ``(chunk_fn, consts_in)`` where ``chunk_fn(*leaves)``
        advances every lane by one chunk.  With ``lane_block`` the call
        becomes a 1-D grid over lane blocks (see make_kernel_run)."""
        n = len(leaves)
        L = leaves[0].shape[-1]
        Lb = lane_block or L
        if lane_block and not interpret and Lb % 1024:
            # per-lane scalars batch to 1-D [L] leaves, which XLA lays
            # out in 1024-wide tiles — a lane block that splits a tile
            # fails Mosaic's operand-layout check (measured offline:
            # "XLA layout T(1024) does not match Mosaic layout T(128)")
            raise ValueError(
                f"lane_block={Lb} must be a multiple of 1024 (the XLA "
                "tile width of 1-D per-lane leaves)"
            )
        if L % Lb:
            raise ValueError(
                f"lanes={L} must divide evenly by lane_block={Lb}"
                + (
                    " (under mesh= the chunk is built at the PER-DEVICE "
                    "local lane width, so lane_block applies per shard)"
                    if mesh is not None
                    else ""
                )
            )
        block_avals = [
            jax.ShapeDtypeStruct(l.shape[:-1] + (Lb,), l.dtype)
            for l in leaves
        ]
        flat_chunk, bool_idx, block_carriers = trace_chunk(
            block_avals, treedef
        )
        # out_shape is FULL width; the kernel sees block-shaped refs.
        # Derive it from trace_chunk's carriers (widen the lane axis)
        # so the carrier dtype rule has one source of truth.
        carrier_avals = [
            jax.ShapeDtypeStruct(a.shape[:-1] + (L,), a.dtype)
            for a in block_carriers
        ]

        const_info, smem_in, vmem_in = route_consts(flat_chunk.consts)
        consts_in = smem_in + vmem_in
        if Lb == L:
            grid_kwargs = {}
            state_specs = [pl.BlockSpec(memory_space=pltpu.VMEM)] * n
        else:
            def _spec_of(a):
                nd = len(a.shape)
                return pl.BlockSpec(
                    a.shape[:-1] + (Lb,),
                    lambda i, _nd=nd: (0,) * (_nd - 1) + (i,),
                )

            grid_kwargs = {"grid": (L // Lb,)}
            state_specs = [_spec_of(a) for a in carrier_avals]
        chunk_call = pl.pallas_call(
            partial(_kernel_body, flat_chunk.jaxpr, const_info, n),
            out_shape=[
                jax.ShapeDtypeStruct(a.shape, a.dtype)
                for a in carrier_avals
            ],
            in_specs=state_specs + const_specs(const_info),
            out_specs=state_specs,
            input_output_aliases={i: i for i in range(n)},
            interpret=interpret,
            # Mosaic's scoped-vmem budget defaults to 16 MiB; the
            # whole-Sim-resident kernel's temporaries pass that around
            # L≈2048 lanes (measured offline: 20.4M @ 2048, 24.0M @
            # 4096) while v5e has 128 MiB of VMEM.  Budget for the
            # bench's L=4096 with headroom; harmless when interpret or
            # on CPU (ignored).
            compiler_params=(
                None
                if interpret
                else getattr(
                    pltpu, "CompilerParams",
                    getattr(pltpu, "TPUCompilerParams", None),
                )(vmem_limit_bytes=_vmem_limit_bytes(lane_block))
            ),
            **grid_kwargs,
        )

        def chunk_fn(*ls):
            boxed = [
                l.astype(jnp.int32) if i in bool_idx else l
                for i, l in enumerate(ls)
            ]
            outs = chunk_call(*boxed, *consts_in)
            return [
                (o != 0) if i in bool_idx else o for i, o in enumerate(outs)
            ]

        return chunk_fn, consts_in

    _validated = []

    def run(sims):
        # Host-level driver, NOT for use under an outer jit.  The whole
        # kernel path — tracing, Mosaic lowering AND compilation — must
        # happen with x64 off: under x64, loop counters, weak Python-int
        # literals and iinfo bounds materialize as int64 (Mosaic's 64->32
        # convert rule recurses forever), and Mosaic's own lower_fun
        # helpers re-trace reduction identities as f64.  Lowering runs at
        # first call of the inner jit, so the first chunk invocation sits
        # inside this scope too.  Init (u64 seed mixing) stays outside,
        # under the session's x64 setting.
        if not _validated:
            # debug tier: enforce the _vswitch zero-merge invariant
            # structurally — every self-gated handler is a bitwise no-op
            # under gate=False on a concrete lane-0 Sim (eager, once per
            # kernel build; a violation corrupts OTHER lanes only under
            # vmap, far from its cause)
            from cimba_tpu.utils import dbc

            if dbc.debug_enabled() and not any(
                isinstance(l, jax.core.Tracer)
                for l in jax.tree.leaves(sims)
            ):
                cl.validate_gated_handlers(
                    spec, jax.tree.map(lambda x: x[0], sims)
                )
            _validated.append(True)
        with config.x64_scope(False):
            return _run(sims)

    _built = {}  # (treedef, leaf avals) -> (chunk_jit, alive_jit)

    def _lane_specs(leaves):
        from jax.sharding import PartitionSpec as P

        (axis,) = mesh.axis_names
        return tuple(
            P(*([None] * (l.ndim - 1) + [axis])) for l in leaves
        )

    def _get_built(leaves, treedef):
        key = (treedef, tuple((l.shape, str(l.dtype)) for l in leaves))
        if key not in _built:
            if mesh is None:
                chunk_fn, _ = build_chunk_call(leaves, treedef)
                chunk_jit = jax.jit(chunk_fn)
            else:
                # per-device kernel: build the chunk at LOCAL lane width
                # (L is a static kernel shape), then shard_map it over
                # the minor lane axis
                # version-compat import (see runner.experiment.shard_map)
                from cimba_tpu.runner.experiment import shard_map

                n_dev = mesh.devices.size
                L = leaves[0].shape[-1]
                if L % n_dev:
                    raise ValueError(
                        f"lanes={L} must divide evenly over {n_dev} "
                        "devices"
                    )
                local = [
                    jax.ShapeDtypeStruct(
                        l.shape[:-1] + (L // n_dev,), l.dtype
                    )
                    for l in leaves
                ]
                chunk_fn, _ = build_chunk_call(local, treedef)
                specs = _lane_specs(leaves)
                sharded = shard_map(
                    lambda *ls: tuple(chunk_fn(*ls)),
                    mesh=mesh,
                    in_specs=specs,
                    out_specs=specs,
                    check_vma=False,
                )
                chunk_jit = jax.jit(lambda *ls: list(sharded(*ls)))
            vcond1 = jax.vmap(cond)  # lane-first, for host-side liveness
            alive_jit = jax.jit(
                lambda *ls: jnp.any(
                    vcond1(
                        jax.tree.unflatten(
                            treedef,
                            [jnp.moveaxis(l, -1, 0) for l in ls],
                        )
                    )
                )
            )
            if spec.boundary_pcs:
                # host-side application of boundary-block dispatches:
                # ONE ordinary XLA engine step (KERNEL_MODE off — MXU
                # matmuls, gathers, everything) on exactly the frozen
                # lanes, between chunks.  A fresh make_step instance:
                # the kernel one's handler cache is bound to kernel-mode
                # tracing.
                xstep = jax.vmap(cl.make_step(spec))

                def _boundary_apply(*ls):
                    sims = jax.tree.unflatten(
                        treedef, [jnp.moveaxis(l, -1, 0) for l in ls]
                    )
                    pending = sims.boundary_pending  # [L]
                    cleared = sims._replace(
                        boundary_pending=jnp.zeros_like(pending)
                    )
                    stepped = xstep(cleared)
                    out = jax.tree.map(
                        lambda a, b: jnp.where(
                            pending.reshape(
                                pending.shape + (1,) * (a.ndim - 1)
                            ),
                            a,
                            b,
                        ),
                        stepped,
                        cleared,
                    )
                    return [
                        jnp.moveaxis(l, 0, -1)
                        for l in jax.tree.leaves(out)
                    ]

                pending_any = jax.jit(
                    lambda *ls: jnp.any(
                        jax.tree.unflatten(
                            treedef, list(ls)
                        ).boundary_pending
                    )
                )
                boundary_jit = jax.jit(_boundary_apply)
            else:
                pending_any = boundary_jit = None
            _built[key] = (chunk_jit, alive_jit, pending_any, boundary_jit)
        return _built[key]

    def _run(sims):
        first, treedef = jax.tree.flatten(sims)
        # kernel boundary: lane axis moves last (XLA-side moveaxis, cheap)
        leaves = [jnp.moveaxis(l, 0, -1) for l in first]
        if mesh is not None:
            from jax.sharding import NamedSharding

            leaves = [
                jax.device_put(l, NamedSharding(mesh, s))
                for l, s in zip(leaves, _lane_specs(leaves))
            ]

        # Chunks are dispatched from the host: each call is bounded device
        # time (well under the runtime watchdog), the any-lane-live check
        # costs one tiny jitted reduction between chunks, and — decisive —
        # compilation of the chunk happens on its first call, still inside
        # the x64-off scope above.  The build (trace + lanelast + bool32 +
        # jit wrappers) is cached per leaf-shape so repeat runs — and a
        # warmup before a timed run — reuse the compiled chunk.
        chunk_jit, alive_jit, pending_any, boundary_jit = _get_built(
            leaves, treedef
        )
        # budget accounting: a boundary freeze can cut a chunk short (the
        # frozen lane stops stepping mid-chunk), so boundary rounds get
        # their own budget — each dispatches >= 1 event per pending lane,
        # bounding them by the same total-event budget instead of eating
        # the full-chunk counter 1:1
        it = rounds = 0
        max_rounds = max_chunks * chunk_steps
        while bool(alive_jit(*leaves)) and it < max_chunks:
            leaves = chunk_jit(*leaves)
            if boundary_jit is not None and bool(pending_any(*leaves)):
                leaves = boundary_jit(*leaves)
                rounds += 1
                if rounds >= max_rounds:
                    break
            else:
                it += 1
        if bool(alive_jit(*leaves)) and (
            it >= max_chunks or rounds >= max_rounds
        ):
            raise RuntimeError(
                f"make_kernel_run: lanes still live after {it} full chunks"
                f" (max {max_chunks} x {chunk_steps} events) and {rounds} "
                "boundary rounds — raise chunk_steps/max_chunks (a silent "
                "partial run would corrupt statistics)"
            )
        leaves = [jnp.moveaxis(l, -1, 0) for l in leaves]
        return jax.tree.unflatten(treedef, leaves)

    run.build_chunk_call = build_chunk_call
    run.trace_chunk = trace_chunk
    return run


def route_consts(consts):
    """Const routing, shared by the kernel and tools/mosaic_eqn_bisect.py
    so tool and kernel can never diverge on const placement.  Three kinds
    (python literals stay captured; arrays must become kernel inputs or
    pallas rejects the trace):

    * ``smem``: small integer tables / scalars — flattened, rebuilt by
      per-element scalar loads (dynamic indexing friendly);
    * ``vmem``: float or large arrays (e.g. the AWACS NN weights,
      lane-ready [K,n,1]) — whole-ref VMEM reads in natural shape, no
      reshape at the boundary (Mosaic shape casts from flattened form are
      exactly the crash class core/lanelast.py exists to avoid).

    Returns ``(const_info, smem_in, vmem_in)``; kernel arg order is
    ``*smem_in, *vmem_in`` after the state leaves.
    """
    const_info = []  # ("lit", value) | ("smem", (shape, size)) | ("vmem",)
    smem_in, vmem_in = [], []
    for c in consts:
        if not (hasattr(c, "dtype") and hasattr(c, "shape")):
            const_info.append(("lit", c))
            continue
        arr = jnp.asarray(c)  # normalizes TypedNdArray / np scalars
        if arr.ndim == 0 or (
            jnp.issubdtype(arr.dtype, jnp.integer) and arr.size <= 256
        ):
            const_info.append(("smem", (arr.shape, arr.size)))
            smem_in.append(jnp.reshape(arr, (-1,)))
        else:
            const_info.append(("vmem",))
            vmem_in.append(arr)
    return const_info, smem_in, vmem_in


def const_specs(const_info):
    """BlockSpecs for the const inputs, in ``*smem_in, *vmem_in`` order."""
    n_smem = sum(1 for info in const_info if info[0] == "smem")
    n_vmem = sum(1 for info in const_info if info[0] == "vmem")
    return [pl.BlockSpec(memory_space=pltpu.SMEM)] * n_smem + [
        pl.BlockSpec(memory_space=pltpu.VMEM)
    ] * n_vmem


def materialize_consts(const_info, const_refs):
    """Rebuild const VALUES from their kernel refs inside a kernel body.
    ``const_refs``: the refs for ``*smem_in, *vmem_in``, in order."""
    n_smem = sum(1 for info in const_info if info[0] == "smem")
    smem_refs = list(const_refs[:n_smem])
    vmem_refs = list(const_refs[n_smem:])
    consts = []
    for info in const_info:
        if info[0] == "smem":
            shape, size = info[1]
            ref = smem_refs.pop(0)
            vals = [ref[i] for i in range(size)]  # SMEM: scalar loads
            c = vals[0] if shape == () else jnp.stack(vals).reshape(shape)
            consts.append(c)
        elif info[0] == "vmem":
            consts.append(vmem_refs.pop(0)[...])
        else:
            consts.append(info[1])
    return consts


def _kernel_body(jaxpr, const_info, n, *refs):
    nc = sum(1 for info in const_info if info[0] != "lit")
    in_refs = refs[:n]
    out_refs = refs[n + nc :]
    consts = materialize_consts(const_info, refs[n : n + nc])
    # the jaxpr is bool32-transformed: ex-bool leaves are i32 at this
    # boundary already, and no i1 vector survives inside
    args = [r[...] for r in in_refs]
    outs = jax.core.eval_jaxpr(jaxpr, consts, *args)
    for r, leaf in zip(out_refs, outs):
        r[...] = leaf


def _maybe_dump_64bit(closed_jaxpr):
    """CIMBA_KERNEL_DEBUG=1: print every 64-bit-typed value in the chunk
    jaxpr with its source line (Mosaic has no 64-bit types; anything listed
    here will fail to lower)."""
    if not config.env_raw("CIMBA_KERNEL_DEBUG"):
        return
    seen = set()

    def _walk(jaxpr):
        for eqn in jaxpr.eqns:
            for v in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(v, "aval", None)
                if (
                    aval is not None
                    and hasattr(aval, "dtype")
                    and aval.dtype.itemsize == 8
                ):
                    src = jax._src.source_info_util.summarize(eqn.source_info)
                    key = (str(eqn.primitive), str(aval.dtype), src)
                    if key not in seen:
                        seen.add(key)
                        print("KERNEL64:", key)
            for val in eqn.params.values():
                vals = val if isinstance(val, (list, tuple)) else [val]
                for v2 in vals:
                    j2 = getattr(v2, "jaxpr", None)
                    if j2 is not None:
                        _walk(j2 if hasattr(j2, "eqns") else j2.jaxpr)

    _walk(closed_jaxpr.jaxpr)
