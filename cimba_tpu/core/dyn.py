"""Dynamic indexing as one-hot select/reduce — the Mosaic-safe idiom.

Written scalar-style (one replication) like the rest of the engine and
batched by vmap.  ``arr[i]`` / ``arr.at[i].set(v)`` with a traced index
lower to ``gather`` / ``scatter`` HLOs once vmapped, and Mosaic supports
almost none of that (only full same-shape ``take_along_axis``).  A one-hot
compare + select + reduce over the small component axes (event slots,
processes, guard slots — all <= a few hundred) expresses the same thing
with ops every backend vectorizes; under vmap the lane dimension rides
along untouched.  On the VPU this is also *faster* than a gather for these
sizes: a handful of full-width vector ops, no serialized address math.

All helpers accept an optional ``pred``: ``dset(a, i, v, pred)`` is
``a.at[i].set(jnp.where(pred, v, a[i]))`` fused into the mask — the
dominant call pattern in the engine's handlers.

Out-of-range semantics differ from jnp deliberately: a negative or too-big
index matches no slot, so reads return the dtype's zero and writes are
no-ops.  Every engine call site either pre-clips or guards with ``pred``;
"no match -> no effect" is the *safer* default for the -1 sentinel handles
threaded through the loop.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from cimba_tpu import config

_I32 = jnp.int32


def kfori(lo: int, hi, body, init):
    """``lax.fori_loop`` outside kernel mode; a while-loop on an unbatched
    scalar counter inside it.  jax lowers a static-trip-count fori as
    ``lax.scan``, and scan's vmap batching rule normalizes every carry's
    batch axis to 0 — under the lane-LAST mega-kernel layout that wraps
    the loop in transposes of every carried leaf, which the Mosaic layout
    pass check-fails on (measured round 2, tools/mosaic_eqn_bisect).  The
    while form keeps carries in their batched layout: its condition reads
    only the counter, which vmap leaves unbatched, so the lowered
    condition is the scalar Mosaic requires."""
    if not config.KERNEL_MODE:
        return lax.fori_loop(lo, hi, body, init)

    def wbody(carry):
        k, c = carry
        return k + jnp.int32(1), body(k, c)

    return lax.while_loop(
        lambda kc: kc[0] < hi, wbody, (jnp.int32(lo), init)
    )[1]


def bwhere(pred, x, y):
    """``jnp.where`` with a lower-rank bool ``pred``, Mosaic-safe.

    Broadcasting a bool against a higher-rank operand inserts a minor
    dim on an i1 vector, which Mosaic only supports for 32-bit types;
    routing the rank expansion through int32 sidesteps it.  Semantically
    identical to ``jnp.where(pred, x, y)``.

    The expanded predicate is memoized per (pred, shape) under
    :func:`oh_cache` — a tree-select merge applies ONE predicate to many
    leaves, and without the memo every leaf of a given shape re-emitted
    the reshape/broadcast/compare chain (measured round 4: the mask
    plumbing, not the selects, was the majority of merge ops).
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    p = jnp.asarray(pred)
    rank = max(x.ndim, y.ndim)
    extra = rank - p.ndim
    if x.dtype == jnp.bool_ and y.dtype == jnp.bool_:
        # bool select entirely in i32: Mosaic's select_n on i1 payloads
        # needs an i32->i1 truncation it does not support, and elementwise
        # i1 and/or chains mix mask layouts the layout pass check-fails on
        # (measured: `layout.h Check failed: arr.size() >= layout_rank`
        # on the rank-1 `or` this used to emit) — so combine as 0/1 ints
        # and produce i1 once, from the trailing comparison: the xor form
        # ((x^y)&p)^y needs one op fewer than (p&x)|(~p&y)
        shape = jnp.broadcast_shapes(
            x.shape, y.shape, p.shape + (1,) * max(extra, 0)
        )
        pi = _pexp_i32(p, shape, max(extra, 0))
        xi = jnp.broadcast_to(x, shape).astype(_I32)
        yi = jnp.broadcast_to(y, shape).astype(_I32)
        return (((xi ^ yi) & pi) ^ yi) != 0
    if extra <= 0 or p.dtype != jnp.bool_:
        return jnp.where(p, x, y)
    shape = jnp.broadcast_shapes(x.shape, y.shape)
    return jnp.where(_pexp_mask(p, shape, extra), x, y)


def _pexp_i32(p, shape, extra: int):
    """``broadcast_to(p.astype(i32).reshape(p.shape + (1,)*extra), shape)``,
    memoized per (pred, shape) while :func:`oh_cache` is active."""
    return _cached(
        ("pexp_i32", shape, extra), (p,),
        lambda: jnp.broadcast_to(
            p.astype(_I32).reshape(p.shape + (1,) * extra), shape
        ),
    )


def _pexp_mask(p, shape, extra: int):
    """Rank-expanded i1 mask of ``shape`` from bool ``p``, via i32
    (Mosaic-safe), memoized per (pred, shape)."""
    return _cached(
        ("pexp_mask", shape, extra), (p,),
        lambda: _pexp_i32(p, shape, extra) != 0,
    )


def _expand_mask(mask, shape, extra: int):
    """bool mask -> bool of ``shape`` without i1 rank-expansion.

    Memoized per (mask, shape): reads/writes at one cached one-hot reuse
    the expansion across every same-shaped component array they touch."""
    if extra == 0:
        if mask.shape == tuple(shape):
            return mask
        return _cached(
            ("mexp", shape, 0), (mask,),
            lambda: jnp.broadcast_to(mask, shape),
        )
    return _pexp_mask(mask, shape, extra)


# One-hot memo: the dispatcher reads/writes many component arrays at the
# SAME traced index inside one step (procs.pc, .status, .prio ... all at
# pid p), and every dget/dset re-derived the iota==i mask — at AWACS
# scale ([P]=1001) the dominant per-access cost.  Keyed by (dims, id) of
# the live index tracer; entries hold a strong ref to the index so ids
# cannot be reused while cached.  Enabled around kernel-mode step
# tracing (pallas_run), where the trace is built once per spec; the
# bounded leak of one trace's masks is reclaimed by oh_cache_clear().
_oh_cache = None


def oh_cache_enable() -> None:
    global _oh_cache
    _oh_cache = {}


def oh_cache_clear() -> None:
    global _oh_cache
    _oh_cache = None


import contextlib as _contextlib


@_contextlib.contextmanager
def oh_cache():
    """Scope the one-hot memo around exactly one jaxpr trace."""
    oh_cache_enable()
    try:
        yield
    finally:
        oh_cache_clear()


def _key_of(i):
    # concrete ints key by value (a fresh const tracer per asarray call
    # would never hit); live tracers key by identity, pinned in the entry
    try:
        return ("v", int(i))
    except Exception:
        return ("t", id(i))


def _cached(key_dims, idx_objs, make):
    if _oh_cache is None:
        return make()
    # the CURRENT trace scopes the entry: a mask built inside a
    # while/cond body sub-trace must never be served to the enclosing
    # trace (leaked tracer) or vice versa
    from jax._src import core as _jcore

    trace = _jcore.trace_ctx.trace
    key = (id(trace), key_dims, tuple(_key_of(i) for i in idx_objs))
    hit = _oh_cache.get(key)
    if hit is None:
        hit = (make(), idx_objs, trace)  # refs pin the ids
        _oh_cache[key] = hit
    return hit[0]


def _oh1(n: int, i):
    """One-hot bool mask [n] for scalar index i (batched by vmap).

    A size-1 dim needs no compare: component ids come from build-time
    Refs, so a valid index over [1] is always 0 and the mask is
    constant-true (single-queue/resource models skip the iota+eq pass
    entirely)."""
    if n == 1:
        return jnp.ones((1,), jnp.bool_)
    i = jnp.asarray(i, _I32)
    return _cached(
        (n,), (i,),
        lambda: _iota((n,), 0) == i,
    )


def _iota(shape, axis: int):
    """``lax.broadcasted_iota`` memoized per (shape, axis): one-hots at
    DIFFERENT indices over the same table share the ramp."""
    return _cached(
        ("iota", shape, axis), (),
        lambda: lax.broadcasted_iota(_I32, shape, axis),
    )


def first_true32(mask):
    """Lowest True index (i32; ``mask.shape[0]`` when none) WITHOUT an
    arg-reduction.  ``lax.argmax`` over a mask with several True entries
    is a tie among equal maxima: XLA resolves ties lowest-index, but
    Mosaic's hardware arg-reduction lowering does not honor that rule —
    first on-device contact caught the spawn free-row pick choosing a
    different row than the XLA path, swapping two symmetric processes'
    trajectories (kernel-vs-XLA fuzz, seed 1).  Free-slot/row/column
    picks therefore use this explicit iota-min, whose tie semantics are
    backend-independent by construction.  Out-of-range on an all-False
    mask is safe at every call site: the derived one-hot is then
    all-False and the write/read it gates is masked off."""
    n = mask.shape[0]
    return jnp.min(jnp.where(mask, _iota((n,), 0), jnp.asarray(n, _I32)))


def _oh2(n0: int, n1: int, i0, i1):
    """One-hot bool mask [n0, n1] for a 2-D index (size-1 dims skip
    their compare — see :func:`_oh1`)."""
    i0 = jnp.asarray(i0, _I32)
    i1 = jnp.asarray(i1, _I32)

    def make():
        if n0 == 1 and n1 == 1:
            return jnp.ones((1, 1), jnp.bool_)
        if n0 == 1:
            return _iota((1, n1), 1) == i1
        if n1 == 1:
            return _iota((n0, 1), 0) == i0
        m0 = _iota((n0, n1), 0) == i0
        m1 = _iota((n0, n1), 1) == i1
        return m0 & m1

    keys = (() if n0 == 1 else (i0,)) + (() if n1 == 1 else (i1,))
    if not keys:
        return make()
    return _cached((n0, n1), keys, make)


def _reduce_pick(mask, arr):
    """Sum-reduce ``arr`` where ``mask``, over the mask's dims.

    With a one-hot (or empty) mask this *is* the indexed read; zero when
    nothing matches.  Bool arrays reduce with any() to stay bool.
    """
    k = mask.ndim
    m = _expand_mask(mask, arr.shape, arr.ndim - k)
    if arr.dtype == jnp.bool_:
        return jnp.any(m & arr, axis=tuple(range(k)))
    # dtype pinned: under x64, jnp.sum would promote i32 -> i64
    return jnp.sum(jnp.where(m, arr, jnp.zeros((), arr.dtype)),
                   axis=tuple(range(k)), dtype=arr.dtype)


# --- scan-over-rows table dispatch (docs/25_compile_wall.md) ----------------
#
# Dense one-hot dispatch materializes every access as full-table-width
# ops.  That is the right trade for event slots and guard tables, but it
# puts the process-table height P into every access's program text, and
# on the kernel path Mosaic tile-unrolls those ``[P, Lb]`` vector ops —
# AWACS (P=1001) is compile-prohibitive at the lane-block grid
# (BENCH_NOTES round 5).  With ``CIMBA_TABLE_SCAN`` on, accesses to axes
# strictly taller than ``CIMBA_TABLE_SCAN_BLOCK`` run a counted loop over
# fixed-size row blocks instead: dynamic-slice one block (the loop
# counter is unbatched, so vmap emits a slice, never a gather), apply the
# SAME one-hot pick/write within the owning block, write the block back.
# Emitted program text then references one ``[B, ...]`` block regardless
# of table height, and results stay bitwise identical: reads accumulate
# the same zeros the dense sum adds, writes put back non-matching rows
# unchanged, and the block-ownership predicate keeps the clamped tail
# block's overlap write-once while preserving the out-of-range no-op.
# (One documented exception: blocked ``dadd``/``dadd2`` can wash a
# ``-0.0`` result to ``+0.0`` in the tail block's overlap rows — only
# when the added value is itself a signed zero.)
#
# The loop rides :func:`kfori`, so the XLA path lowers it as a scan and
# the mega-kernel keeps the scalar-counter while form Mosaic needs.


def _blk(n: int):
    """``(block, n_blocks)`` when the scan engages on an axis of height
    ``n`` (knob on AND the axis strictly taller than the block), else
    ``None`` — small tables stay dense, which is both the perf answer
    and the small-P structural-inertness contract."""
    if n <= 1 or not config.table_scan_enabled():
        return None
    B = config.table_scan_block()
    if n <= B:
        return None
    return B, -(-n // B)


def _blk2(n0: int, n1: int):
    """``(axis, block, n_blocks)`` for a 2-D table, blocking the taller
    engaging axis (the engine's 2-D tables are ``[components, slots]``,
    so axis 1 is the one that scales), else ``None``."""
    for ax, n in ((1, n1), (0, n0)):
        b = _blk(n)
        if b is not None:
            return (ax,) + b
    return None


def _blk_start(k, B: int, n: int):
    """Unbatched i32 start row of block ``k``, clamped so the tail block
    stays in range when ``B`` does not divide ``n`` (the resulting
    overlap is kept write-once by :func:`_blk_own`)."""
    return jnp.minimum(k * jnp.asarray(B, _I32), jnp.asarray(n - B, _I32))


def _blk_own(i, k, B: int, start):
    """Within-block index of row ``i`` under block ``k``'s ownership;
    ``-1`` (no one-hot match) when block ``k`` does not own row ``i``.
    Ownership is ``i div B == k`` with truncating division: every
    out-of-range or gated-off index (the ``-1`` sentinel included) owns
    no block, reproducing the dense helpers' no-op semantics."""
    i = jnp.asarray(i, _I32)
    own = lax.div(i, jnp.asarray(B, _I32)) == k
    return jnp.where(own, i - start, jnp.asarray(-1, _I32))


def _acc_pick(acc, mask, blk):
    """Accumulate one block's one-hot pick into ``acc`` (OR for bool —
    any() keeps bool — and the dense sum's add for everything else)."""
    r = _reduce_pick(mask, blk)
    return acc | r if blk.dtype == jnp.bool_ else acc + r


def _scan_get1(arrs, i):
    """Blocked ``dget`` over several same-height tables at ONE shared
    index: a single block loop slices each table once per block and
    applies one shared within-block one-hot."""
    n = arrs[0].shape[0]
    B, nb = _blk(n)
    accs = tuple(jnp.zeros(a.shape[1:], a.dtype) for a in arrs)

    def body(k, accs):
        start = _blk_start(k, B, n)
        m = _oh1(B, _blk_own(i, k, B, start))
        return tuple(
            _acc_pick(acc, m, lax.dynamic_slice_in_dim(a, start, B, 0))
            for a, acc in zip(arrs, accs)
        )

    return list(kfori(0, nb, body, accs))


def _scan_set1(arrs, i, vals, pred=True, add=False):
    """Blocked ``dset``/``dadd`` over several same-height tables at ONE
    shared (gated) index.  The gate always folds into the index here —
    a blocked axis is wide by construction, and ``-1`` owns no block."""
    n = arrs[0].shape[0]
    B, nb = _blk(n)
    if pred is not True:
        i = _gate_idx(i, pred)

    def body(k, arrs_k):
        start = _blk_start(k, B, n)
        m = _oh1(B, _blk_own(i, k, B, start))
        outs = []
        for a, v in zip(arrs_k, vals):
            blk = lax.dynamic_slice_in_dim(a, start, B, 0)
            if add:
                me = _expand_mask(m, blk.shape, blk.ndim - 1)
                v = jnp.asarray(v, a.dtype)
                blk = blk + jnp.where(me, v, jnp.zeros((), a.dtype))
            else:
                blk = _masked_write(blk, m, v, True)
            outs.append(lax.dynamic_update_slice_in_dim(a, blk, start, 0))
        return tuple(outs)

    return list(kfori(0, nb, body, tuple(arrs)))


def _blk_oh2(n0: int, n1: int, i0, i1, ax: int, B: int, k, start):
    """Within-block 2-D one-hot for block ``k`` of the blocked axis."""
    if ax == 0:
        return _oh2(B, n1, _blk_own(i0, k, B, start), i1)
    return _oh2(n0, B, i0, _blk_own(i1, k, B, start))


def _scan_get2(arr, i0, i1, ax: int, B: int, nb: int):
    n = arr.shape[ax]
    acc0 = jnp.zeros(arr.shape[2:], arr.dtype)

    def body(k, acc):
        start = _blk_start(k, B, n)
        m = _blk_oh2(arr.shape[0], arr.shape[1], i0, i1, ax, B, k, start)
        return _acc_pick(acc, m, lax.dynamic_slice_in_dim(arr, start, B, ax))

    return kfori(0, nb, body, acc0)


def _scan_set2(arr, i0, i1, v, pred, ax: int, B: int, nb: int, add=False):
    n = arr.shape[ax]
    if pred is not True:
        if ax == 0:
            i0 = _gate_idx(i0, pred)
        else:
            i1 = _gate_idx(i1, pred)

    def body(k, a):
        start = _blk_start(k, B, n)
        m = _blk_oh2(arr.shape[0], arr.shape[1], i0, i1, ax, B, k, start)
        blk = lax.dynamic_slice_in_dim(a, start, B, ax)
        if add:
            me = _expand_mask(m, blk.shape, blk.ndim - 2)
            vv = jnp.asarray(v, a.dtype)
            blk = blk + jnp.where(me, vv, jnp.zeros((), a.dtype))
        else:
            blk = _masked_write(blk, m, v, True)
        return lax.dynamic_update_slice_in_dim(a, blk, start, ax)

    return kfori(0, nb, body, arr)


def _scan_exchange2(arr, i0, i1, v, do_write, pred, ax: int, B: int, nb: int):
    n = arr.shape[ax]
    if pred is not True:
        if ax == 0:
            i0 = _gate_idx(i0, pred)
        else:
            i1 = _gate_idx(i1, pred)
    v = jnp.asarray(v, arr.dtype)

    def body(k, carry):
        item, a = carry
        start = _blk_start(k, B, n)
        m = _blk_oh2(arr.shape[0], arr.shape[1], i0, i1, ax, B, k, start)
        blk = lax.dynamic_slice_in_dim(a, start, B, ax)
        it = _reduce_pick(m, blk)
        # the target row lives in exactly one block, so the owning
        # block's pick IS the full read and non-owning blocks write
        # back their rows bitwise-unchanged
        wv = jnp.where(do_write, v, it)
        blk = _masked_write(blk, m, wv, True)
        a = lax.dynamic_update_slice_in_dim(a, blk, start, ax)
        item = item | it if arr.dtype == jnp.bool_ else item + it
        return item, a

    item0 = jnp.zeros(arr.shape[2:], arr.dtype)
    return kfori(0, nb, body, (item0, arr))


def dget(arr, i):
    """``arr[i]`` (scalar if arr is 1-D, row if 2-D+) for a traced index."""
    if arr.shape[0] == 1:
        # single-member component table: the read is the row itself
        return lax.reshape(arr, arr.shape[1:])
    if _blk(arr.shape[0]) is not None:
        return _scan_get1([arr], i)[0]
    return _reduce_pick(_oh1(arr.shape[0], i), arr)


def dget_tree(tree, i):
    """:func:`dget` over every leaf of ``tree`` at ONE shared index.

    Dense mode is exactly ``jax.tree.map(lambda a: dget(a, i), tree)``
    (jaxpr character-identical to the historical per-leaf calls); scan
    mode serves every leaf from a single block loop — the grouped form
    is what keeps the blocked program's eqn count near the dense one's
    at the many-fields-one-pid dispatcher sites."""
    import jax

    leaves = jax.tree.leaves(tree)
    if (leaves and all(a.shape[0] == leaves[0].shape[0] for a in leaves)
            and leaves[0].shape[0] > 1 and _blk(leaves[0].shape[0]) is not None):
        outs = iter(_scan_get1(leaves, i))
        return jax.tree.map(lambda _: next(outs), tree)
    return jax.tree.map(lambda a: dget(a, i), tree)


def dset_tree(tree, i, vals, pred=True):
    """:func:`dset` over every leaf of ``tree`` at ONE shared gated
    index (``vals`` is a matching tree of written values).  Dense mode
    is exactly the per-leaf ``dset`` tree-map; scan mode shares one
    block loop across the leaves (see :func:`dget_tree`)."""
    import jax

    leaves = jax.tree.leaves(tree)
    if (leaves and all(a.shape[0] == leaves[0].shape[0] for a in leaves)
            and leaves[0].shape[0] > 1 and _blk(leaves[0].shape[0]) is not None):
        vleaves = jax.tree.leaves(vals)
        outs = iter(_scan_set1(leaves, i, vleaves, pred))
        return jax.tree.map(lambda _: next(outs), tree)
    return jax.tree.map(lambda a, v: dset(a, i, v, pred), tree, vals)


def dget2(arr, i0, i1):
    """``arr[i0, i1]`` for traced indices."""
    b2 = _blk2(arr.shape[0], arr.shape[1])
    if b2 is not None:
        return _scan_get2(arr, i0, i1, *b2)
    return _reduce_pick(_oh2(arr.shape[0], arr.shape[1], i0, i1), arr)


def _masked_write(arr, mask, v, pred):
    if pred is not True:
        mask = mask & pred
    m = _expand_mask(mask, arr.shape, arr.ndim - mask.ndim)
    v = jnp.asarray(v, arr.dtype)
    if arr.dtype == jnp.bool_:
        # i1 select_n needs a truncation Mosaic lacks; use logic
        return (m & jnp.broadcast_to(v, arr.shape)) | (~m & arr)
    return jnp.where(m, v, arr)


#: axes at least this wide gate writes through the index instead of a
#: mask AND — below it, losing the shared base one-hot costs more ops
#: than the table-wide AND saves elements
_GATE_IDX_MIN = 32


def _gate_idx(i, pred):
    """Fold a scalar write predicate into the index: out-of-range matches
    no slot, so ``pred=False -> i=-1`` makes the write a no-op with ONE
    scalar select instead of a table-wide ``mask & pred`` AND (on the
    256-slot queue ring that AND was a full-width op per put).  Only
    applied to wide axes (``_GATE_IDX_MIN``): narrow tables keep the
    shared cached one-hot plus a cheap AND.  Memoized per (i, pred) so
    several same-slot writes under one gate share one one-hot."""
    return _cached(
        ("gidx",), (i, pred),
        lambda: jnp.where(pred, jnp.asarray(i, _I32), jnp.asarray(-1, _I32)),
    )


def dset(arr, i, v, pred=True):
    """``arr.at[i].set(v)``, gated by ``pred`` (no-op where false)."""
    if _blk(arr.shape[0]) is not None:
        return _scan_set1([arr], i, [v], pred)[0]
    if pred is not True and arr.shape[0] >= _GATE_IDX_MIN:
        return _masked_write(arr, _oh1(arr.shape[0], _gate_idx(i, pred)), v, True)
    return _masked_write(arr, _oh1(arr.shape[0], i), v, pred)


def dset2(arr, i0, i1, v, pred=True):
    """``arr.at[i0, i1].set(v)``, gated by ``pred``."""
    n0, n1 = arr.shape[0], arr.shape[1]
    b2 = _blk2(n0, n1)
    if b2 is not None:
        return _scan_set2(arr, i0, i1, v, pred, *b2)
    if pred is not True:
        # fold the gate into whichever axis actually compares (size-1
        # axes skip their compare in _oh2 and cannot carry the gate)
        if n1 >= _GATE_IDX_MIN:
            i1, pred = _gate_idx(i1, pred), True
        elif n0 >= _GATE_IDX_MIN:
            i0, pred = _gate_idx(i0, pred), True
    return _masked_write(arr, _oh2(n0, n1, i0, i1), v, pred)


def dadd(arr, i, v, pred=True):
    """``arr.at[i].add(v)``, gated by ``pred``."""
    if _blk(arr.shape[0]) is not None:
        return _scan_set1([arr], i, [v], pred, add=True)[0]
    if pred is not True and arr.shape[0] >= _GATE_IDX_MIN:
        i, pred = _gate_idx(i, pred), True
    mask = _oh1(arr.shape[0], i)
    if pred is not True:
        mask = mask & pred
    m = _expand_mask(mask, arr.shape, arr.ndim - mask.ndim)
    v = jnp.asarray(v, arr.dtype)
    return arr + jnp.where(m, v, jnp.zeros((), arr.dtype))


def dexchange2(arr, i0, i1, v, do_write, pred=True):
    """Read-or-write at ONE shared one-hot: returns
    ``(arr[i0, i1], arr.at[i0, i1].set(where(do_write, v, arr[i0, i1])))``
    gated by ``pred``.

    Where ``do_write`` is false the written value is the read itself — a
    bitwise no-op — so a single mask (and a single full-width select)
    serves both verbs.  This is how the combined queue handler halves the
    ring's full-width ops: put and get share the compare and the write
    pass, differing only in a scalar select of the value.
    """
    n0, n1 = arr.shape[0], arr.shape[1]
    b2 = _blk2(n0, n1)
    if b2 is not None:
        return _scan_exchange2(arr, i0, i1, v, do_write, pred, *b2)
    if pred is not True:
        if n1 >= _GATE_IDX_MIN:
            i1, pred = _gate_idx(i1, pred), True
        elif n0 >= _GATE_IDX_MIN:
            i0, pred = _gate_idx(i0, pred), True
    mask = _oh2(n0, n1, i0, i1)
    if pred is not True:
        mask = mask & pred
    item = _reduce_pick(mask, arr)
    wv = jnp.where(do_write, jnp.asarray(v, arr.dtype), item)
    return item, _masked_write(arr, mask, wv, True)


def set_col(arr, k: int, col):
    """``arr.at[:, k].set(col)`` for a *static* column index — expressed as
    a select over a constant column mask (``.at[:, k]`` lowers to a scatter,
    which Mosaic has no rule for)."""
    m = lax.broadcasted_iota(_I32, (1, arr.shape[1]), 1) == k
    return jnp.where(m, col[:, None].astype(arr.dtype), arr)


def dadd2(arr, i0, i1, v, pred=True):
    """``arr.at[i0, i1].add(v)``, gated by ``pred``."""
    n0, n1 = arr.shape[0], arr.shape[1]
    b2 = _blk2(n0, n1)
    if b2 is not None:
        return _scan_set2(arr, i0, i1, v, pred, *b2, add=True)
    if pred is not True:
        if n1 >= _GATE_IDX_MIN:
            i1, pred = _gate_idx(i1, pred), True
        elif n0 >= _GATE_IDX_MIN:
            i0, pred = _gate_idx(i0, pred), True
    mask = _oh2(n0, n1, i0, i1)
    if pred is not True:
        mask = mask & pred
    m = _expand_mask(mask, arr.shape, arr.ndim - mask.ndim)
    v = jnp.asarray(v, arr.dtype)
    return arr + jnp.where(m, v, jnp.zeros((), arr.dtype))
