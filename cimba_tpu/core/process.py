"""Processes as state machines: the coroutine layer, lowered to data.

Reference parity: ``cmb_process`` (`src/cmb_process.c`, 870 lines) gives
each simulated process a stack, an assembly context switch, and
hold/interrupt/stop/wait semantics with a signal-code protocol
(`include/cmb_process.h:59-99`).  All control transfers are routed through
scheduled events — the dispatcher never jumps directly between coroutines.

TPU redesign (SURVEY.md §7 "coroutines become state machines"): a process
is a row in a struct-of-arrays — program counter, status, priority, pending
command, result register, typed locals.  A process *body* is a list of
**blocks**: pure functions ``block(sim, pid, sig) -> (sim, Command)``
covering the straight-line code between two yield points of the equivalent
coroutine.  The dispatcher (core/loop.py) runs blocks through
``lax.switch`` and chains non-yielding commands in an inner while_loop —
exactly a coroutine resuming until it next waits, with the C stack replaced
by the explicit (pc, locals) row.  No stacks, no guard pages, no context
switch: the entire fiber kernel (reference components #2-#4, 1800 LoC of
C+asm) becomes array indexing.

Signal codes keep the reference's protocol and values.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE

_I = INDEX_DTYPE
_R = config.REAL

# --- signal protocol (parity: include/cmb_process.h:59-99) -------------------
SUCCESS = 0
PREEMPTED = -1
INTERRUPTED = -2
STOPPED = -3
CANCELLED = -4
TIMEOUT = -5

# --- process status (parity: enum cmb_process_state + queued refinement) -----
CREATED = 0
RUNNING = 1   # live: executing, holding, or waiting on a guard
FINISHED = 2

# --- command tags -------------------------------------------------------------
C_HOLD = 0       # yield for a duration                      (f=dur)
C_EXIT = 1       # terminate the process
C_JUMP = 2       # continue immediately at next_pc
C_PUT = 3        # blocking put into object queue i          (f=item)
C_GET = 4        # blocking get from object queue i
C_ACQUIRE = 5    # blocking acquire of binary resource i
C_RELEASE = 6    # release binary resource i (never blocks)
C_PREEMPT = 7    # priority acquire of resource i (may kick the holder)
C_POOL_ACQ = 8   # blocking acquire of f units from pool i
C_POOL_REL = 9   # release f units back to pool i (never blocks)
C_BUF_GET = 10   # blocking take of f units from buffer i
C_BUF_PUT = 11   # blocking add of f units into buffer i
C_PQ_PUT = 12    # blocking put into priority queue i        (f=item, f2=prio)
C_PQ_GET = 13    # blocking get from priority queue i
C_COND_WAIT = 14 # wait on condition i until signaled & predicate true
C_WAIT_PROC = 15 # wait for process i to finish
C_POOL_PRE = 16  # greedy pool acquire that may mug lower-priority holders
C_WAIT_EVT = 17  # wait for event handle i to be dispatched
# Fused verbs (TPU-first redesign, no reference counterpart needed —
# the reference's straight-line C makes a between-yield continuation
# free, while the masked kernel pays a FULL body pass per chain
# iteration; fusing the ubiquitous "<blocking verb>; hold(t)" pair into
# one command makes the hot cycle ONE iteration per event).  Every
# blocking verb has a ``*_hold`` twin; the pre-drawn hold duration
# rides the dedicated f3 payload so it survives a pend (f/f2 keep
# their verb-specific meanings through the retry/abort protocol —
# pool rollback holding, buffer totals, pq item priority):
C_PUT_HOLD = 18       # put f into queue i, then hold f3   (f=item, f3=dur)
C_GET_HOLD = 19       # get from queue i, then hold f3     (f3=dur)
C_ACQ_HOLD = 20       # acquire resource i, then hold f3
C_PRE_HOLD = 21       # preempt resource i, then hold f3
C_POOL_ACQ_HOLD = 22  # acquire f units of pool i, then hold f3
C_POOL_PRE_HOLD = 23  # preempt-acquire f units of pool i, then hold f3
C_BUF_GET_HOLD = 24   # take f units from buffer i, then hold f3
C_BUF_PUT_HOLD = 25   # add f units into buffer i, then hold f3
C_PQ_PUT_HOLD = 26    # pq put, then hold f3           (f=item, f2=prio)
C_PQ_GET_HOLD = 27    # pq get, then hold f3
N_COMMANDS = 28


class Command(NamedTuple):
    """Uniform command pytree (every block returns one)."""

    tag: jnp.ndarray      # i32
    f: jnp.ndarray        # f64 payload (duration, item, amount)
    f2: jnp.ndarray       # f64 second payload (item priority, ...)
    f3: jnp.ndarray       # f64 fused hold duration (``*_hold`` verbs)
    i: jnp.ndarray        # i32 payload (queue/resource/pool id)
    next_pc: jnp.ndarray  # i32 block to continue at


# When set (by core.loop's used-tag inference pass), every constructed
# command registers its tag here.  Tags reach _cmd as Python int constants,
# so collection works under abstract (eval_shape) tracing — the dispatcher
# uses the collected set to trace only the handlers a model can invoke
# (vmapped lax.switch executes *every* traced branch for every lane, so an
# unused handler is pure hot-loop cost).
_tag_collector = None


# Per-dtype cache of the scalar zero constant: every command built with a
# defaulted payload shares ONE array object per trace-visible dtype, so
# ``select``'s identity check (below) skips the where on fields neither
# branch sets — e.g. f3 in a model with no fused verbs costs zero ops.
_zero_cache: dict = {}


def _zero(dt):
    import jax

    key = jnp.dtype(dt)
    z = _zero_cache.get(key)
    if z is None or z.dtype != key:
        z = jnp.zeros((), key)
        # cache only a CONCRETE array of the requested dtype: under an
        # abstract trace (tag inference's eval_shape) creation ops yield
        # tracers of that trace, and under x64-off an f64 request
        # silently downcasts — caching either poisons later traces
        if z.dtype == key and not isinstance(z, jax.core.Tracer):
            _zero_cache[key] = z
    return z


def _pay(v, dt):
    return _zero(dt) if isinstance(v, (int, float)) and v == 0 else (
        jnp.asarray(v, dt)
    )


def _cmd(tag, f=0.0, f2=0.0, f3=0.0, i=0, next_pc=0) -> Command:
    if _tag_collector is not None:
        _tag_collector.add(int(tag))
    return Command(
        jnp.asarray(tag, _I),
        _pay(f, _R),
        _pay(f2, _R),
        _pay(f3, _R),
        _pay(i, _I),
        _pay(next_pc, _I),
    )


def hold(duration, next_pc) -> Command:
    """Yield for `duration` sim time (parity: cmb_process_hold)."""
    return _cmd(C_HOLD, f=duration, next_pc=next_pc)


def exit_() -> Command:
    """Terminate (parity: cmb_process_exit / returning from the body)."""
    return _cmd(C_EXIT)


def jump(next_pc) -> Command:
    """Continue at another block without yielding."""
    return _cmd(C_JUMP, next_pc=next_pc)


def put(queue, item, next_pc) -> Command:
    """Blocking put (parity: cmb_objectqueue_put)."""
    return _cmd(C_PUT, f=item, i=queue, next_pc=next_pc)


def get(queue, next_pc) -> Command:
    """Blocking get (parity: cmb_objectqueue_get); the item lands in the
    process's result register (api.got)."""
    return _cmd(C_GET, i=queue, next_pc=next_pc)


def put_hold(queue, item, duration, next_pc) -> Command:
    """Fused ``put; hold(duration)``: attempt the put now; once it
    succeeds (immediately or after pending on the rear guard), hold for
    ``duration`` and wake at ``next_pc``.  Semantically identical to
    ``cmd.put`` followed by a block returning ``cmd.hold`` — but ONE
    chain iteration instead of two, which is the whole per-event cost
    on the kernel path (docs/07).  Draw ``duration`` before yielding."""
    return _cmd(C_PUT_HOLD, f=item, f3=duration, i=queue, next_pc=next_pc)


def get_hold(queue, duration, next_pc) -> Command:
    """Fused ``get; hold(duration)``: once the get succeeds the item is
    in api.got and the process holds ``duration`` before waking at
    ``next_pc`` — the M/M/1 service cycle in one chain iteration (see
    :func:`put_hold`)."""
    return _cmd(C_GET_HOLD, f3=duration, i=queue, next_pc=next_pc)


def acquire_hold(resource, duration, next_pc) -> Command:
    """Fused ``acquire; hold(duration)``: once the resource is granted
    (immediately or after waiting), hold ``duration`` and wake at
    ``next_pc`` — the canonical seize-then-serve pair in one chain
    iteration (see :func:`put_hold` for the cost rationale)."""
    return _cmd(C_ACQ_HOLD, f3=duration, i=resource, next_pc=next_pc)


def preempt_hold(resource, duration, next_pc) -> Command:
    """Fused ``preempt; hold(duration)`` (see :func:`preempt`)."""
    return _cmd(C_PRE_HOLD, f3=duration, i=resource, next_pc=next_pc)


def pool_acquire_hold(pool, amount, duration, next_pc) -> Command:
    """Fused ``pool_acquire; hold(duration)``: hold fires when the full
    claim is granted (the greedy-partial wait protocol is unchanged —
    pend rollback state rides f/f2, the duration rides f3)."""
    return _cmd(
        C_POOL_ACQ_HOLD, f=amount, f3=duration, i=pool, next_pc=next_pc
    )


def pool_preempt_hold(pool, amount, duration, next_pc) -> Command:
    """Fused ``pool_preempt; hold(duration)`` (see :func:`pool_preempt`)."""
    return _cmd(
        C_POOL_PRE_HOLD, f=amount, f3=duration, i=pool, next_pc=next_pc
    )


def buffer_get_hold(buffer, amount, duration, next_pc) -> Command:
    """Fused ``buffer_get; hold(duration)``: hold fires on completed
    transfer (partial-fulfillment waits keep their contract)."""
    return _cmd(
        C_BUF_GET_HOLD, f=amount, f3=duration, i=buffer, next_pc=next_pc
    )


def buffer_put_hold(buffer, amount, duration, next_pc) -> Command:
    """Fused ``buffer_put; hold(duration)`` (see :func:`buffer_get_hold`)."""
    return _cmd(
        C_BUF_PUT_HOLD, f=amount, f3=duration, i=buffer, next_pc=next_pc
    )


def pq_put_hold(pqueue, item, prio, duration, next_pc) -> Command:
    """Fused ``pq_put; hold(duration)`` (item priority stays on f2)."""
    return _cmd(
        C_PQ_PUT_HOLD, f=item, f2=prio, f3=duration, i=pqueue,
        next_pc=next_pc,
    )


def pq_get_hold(pqueue, duration, next_pc) -> Command:
    """Fused ``pq_get; hold(duration)``: the item lands in api.got."""
    return _cmd(C_PQ_GET_HOLD, f3=duration, i=pqueue, next_pc=next_pc)


def acquire(resource, next_pc) -> Command:
    """Blocking acquire of a binary resource (parity: cmb_resource_acquire)."""
    return _cmd(C_ACQUIRE, i=resource, next_pc=next_pc)


def release(resource, next_pc) -> Command:
    """Release a binary resource; continues without yielding."""
    return _cmd(C_RELEASE, i=resource, next_pc=next_pc)


def preempt(resource, next_pc) -> Command:
    """Priority acquire (parity: cmb_resource_preempt): takes the resource
    from a holder of equal or lower priority (myprio >= holder prio, as in
    `src/cmb_resource.c:294`), delivering PREEMPTED to it."""
    return _cmd(C_PREEMPT, i=resource, next_pc=next_pc)


def pool_acquire(pool, amount, next_pc) -> Command:
    """Blocking acquire of ``amount`` units (parity: cmb_resourcepool_acquire,
    `src/cmb_resourcepool.c:362-533`): greedily grabs whatever is available
    now and waits for the remainder; aborted waits (INTERRUPTED/TIMEOUT)
    roll the holding back to what it was before the call."""
    return _cmd(C_POOL_ACQ, f=amount, i=pool, next_pc=next_pc)


def pool_preempt(pool, amount, next_pc) -> Command:
    """Greedy pool acquire that may also mug strictly-lower-priority
    holders (parity: cmb_resourcepool_preempt): victims are taken lowest
    priority first, LIFO within a priority, lose their ENTIRE holding, and
    resume with PREEMPTED; the surplus beyond the claim returns to the
    pool."""
    return _cmd(C_POOL_PRE, f=amount, i=pool, next_pc=next_pc)


def pool_release(pool, amount, next_pc) -> Command:
    """Release units back (parity: cmb_resourcepool_release; partial release
    allowed)."""
    return _cmd(C_POOL_REL, f=amount, i=pool, next_pc=next_pc)


def buffer_get(buffer, amount, next_pc) -> Command:
    """Take ``amount`` from a fungible store (parity: cmb_buffer_get)."""
    return _cmd(C_BUF_GET, f=amount, i=buffer, next_pc=next_pc)


def buffer_put(buffer, amount, next_pc) -> Command:
    """Add ``amount`` into a fungible store (parity: cmb_buffer_put)."""
    return _cmd(C_BUF_PUT, f=amount, i=buffer, next_pc=next_pc)


def pq_put(pqueue, item, prio, next_pc) -> Command:
    """Blocking put with per-item priority (parity: cmb_priorityqueue_put)."""
    return _cmd(C_PQ_PUT, f=item, f2=prio, i=pqueue, next_pc=next_pc)


def pq_get(pqueue, next_pc) -> Command:
    """Blocking get of the highest-priority item (parity:
    cmb_priorityqueue_get)."""
    return _cmd(C_PQ_GET, i=pqueue, next_pc=next_pc)


def cond_wait(condition, next_pc) -> Command:
    """Wait until the condition is signaled and its predicate holds
    (parity: cmb_condition_wait; spurious wakeups re-wait internally)."""
    return _cmd(C_COND_WAIT, i=condition, next_pc=next_pc)


def wait_process(pid, next_pc) -> Command:
    """Wait for another process to finish (parity: cmb_process_wait_process);
    delivers SUCCESS if it exited, STOPPED if it was killed."""
    return _cmd(C_WAIT_PROC, i=pid, next_pc=next_pc)


def wait_event(handle, next_pc) -> Command:
    """Wait for an arbitrary scheduled event to occur (parity:
    cmb_process_wait_event, `include/cmb_process.h:374`): the continuation
    receives SUCCESS when the event is dispatched (waiters wake before the
    event's action runs, `src/cmb_event.c:312-314`), CANCELLED if the event
    was cancelled (or the handle was already dead), or the interrupting
    signal if this process is interrupted while waiting."""
    return _cmd(C_WAIT_EVT, i=handle, next_pc=next_pc)


def select(pred, a: Command, b: Command) -> Command:
    """Branch-free choice between two commands (pred ? a : b).  Fields
    carried as the SAME object on both sides (shared zero constants from
    ``_cmd``, or a common payload tracer) skip their select entirely."""
    return Command(
        *[x if x is y else jnp.where(pred, x, y) for x, y in zip(a, b)]
    )


# no pending command sentinel
NO_PEND = jnp.int32(-1)


class Procs(NamedTuple):
    """All processes of one replication, struct-of-arrays [P]."""

    pc: jnp.ndarray        # i32 current block (global index)
    status: jnp.ndarray    # i32 CREATED/RUNNING/FINISHED
    prio: jnp.ndarray      # i32 current priority
    pend_tag: jnp.ndarray  # i32 blocked command tag, NO_PEND if none
    pend_f: jnp.ndarray    # f64
    pend_f2: jnp.ndarray   # f64
    pend_f3: jnp.ndarray   # f64 fused hold duration riding the pend
    pend_i: jnp.ndarray    # i32
    pend_pc: jnp.ndarray   # i32
    pend_guard: jnp.ndarray  # i32 guard the process waits on, -1 if none
    pend_seq: jnp.ndarray  # i32 guard FIFO position (kept across retries)
    await_pid: jnp.ndarray  # i32 process this one waits for (-1 none)
    await_evt: jnp.ndarray  # i32 event handle this one waits for (-1 none)
    exit_sig: jnp.ndarray  # i32 signal delivered to waiters (SUCCESS/STOPPED)
    got: jnp.ndarray       # f64 result register (last GET item, ...)
    locals_f: jnp.ndarray  # [P, NF] f64 user locals
    locals_i: jnp.ndarray  # [P, NI] i32 user locals


def create(entry_pcs, prios, n_flocals: int, n_ilocals: int) -> Procs:
    entry = jnp.asarray(entry_pcs, _I)
    p = entry.shape[0]
    return Procs(
        pc=entry,
        status=jnp.full((p,), CREATED, _I),
        prio=jnp.asarray(prios, _I),
        pend_tag=jnp.full((p,), NO_PEND, _I),
        pend_f=jnp.zeros((p,), _R),
        pend_f2=jnp.zeros((p,), _R),
        pend_f3=jnp.zeros((p,), _R),
        pend_i=jnp.zeros((p,), _I),
        pend_pc=jnp.zeros((p,), _I),
        pend_guard=jnp.full((p,), -1, _I),
        pend_seq=jnp.full((p,), -1, _I),
        await_pid=jnp.full((p,), -1, _I),
        await_evt=jnp.full((p,), -1, _I),
        exit_sig=jnp.full((p,), SUCCESS, _I),
        got=jnp.zeros((p,), _R),
        locals_f=jnp.zeros((p, max(n_flocals, 1)), _R),
        locals_i=jnp.zeros((p, max(n_ilocals, 1)), _I),
    )
