"""The future-event set: fixed-capacity, branch-free, batched by vmap.

Reference parity: the event queue is the reference's performance heart — a
binary heap fused with a hash map (`src/cmi_hashheap.c`, 937 lines of
open-addressing, tombstones and Fibonacci hashing) giving O(log n) pops and
O(1) handle-based cancel/reschedule (`src/cmb_event.c:190-335`).

TPU redesign: none of that survives contact with the VPU.  A heap's
sift-up/down is a chain of data-dependent scalar gathers — poison under
vmap.  Instead the event set is a **flat slot table**: CAP parallel arrays,
`time == +inf` marks a free slot, and "pop min" is a lexicographic argmin
over (time, -priority, seq) computed with three masked reductions — O(CAP)
work but a handful of fully-vectorized VPU ops, which for the CAP <= a few
hundred of process-interaction models beats the heap's serial pointer
chasing by a wide margin.  Handles are (slot | generation<<16), making
cancel/reschedule O(1) scatters and ABA-safe, replacing the hash map
entirely.  The hashheap's amortized-doubling growth
(`src/cmi_hashheap.c:384-426`) becomes a static capacity with an overflow
flag — the replication is failure-masked, the experiment continues
(SURVEY.md §7 hard part (b)).

Event ordering contract (parity with `src/cmb_event.c:75-100`): earlier
time first, then HIGHER priority, then FIFO by sequence number.

All functions are scalar-style (one replication); the framework vmaps.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp
from jax import lax

from cimba_tpu import config
from cimba_tpu.core import dyn
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.config import argmax32 as _argmax32, argmin32 as _argmin32

_T = config.TIME
_I = INDEX_DTYPE

#: slot value meaning "no event here"
NEVER = jnp.inf
#: handle returned when scheduling fails (capacity exhausted)
NULL_HANDLE = jnp.int32(-1)

_GEN_SHIFT = 16
_SLOT_MASK = (1 << _GEN_SHIFT) - 1


class BlockMin(NamedTuple):
    """Per-block packed minima: the two-level tournament's upper level.

    Each of the CAP/B blocks summarizes its B slots' lexicographic
    (time asc, prio DESC, seq asc) winner — the full popped payload, so
    ``peek_merged`` reduces over NB = CAP/B rows instead of CAP slots
    (the "schedule the reduction, don't re-scan" move; see
    docs/11_dispatch_cost.md).  Maintained incrementally: every
    single-slot mutation refreshes exactly the covering block
    (recompute-from-table, so a masked-off write is automatically a
    summary no-op); mass cancels rebuild all rows in one reshape pass.
    An empty block carries the same fold identities ``_lexmin`` uses
    (+inf / int32 min / int32 max), so the upper-level lexmin needs no
    special casing."""

    time: jnp.ndarray  # [NB] _T, block winner's time (+inf = empty)
    prio: jnp.ndarray  # [NB] i32, winner's priority (int32 min = empty)
    seq: jnp.ndarray   # [NB] i32, winner's seq (int32 max = empty)
    slot: jnp.ndarray  # [NB] i32, winner's ABSOLUTE slot index
    kind: jnp.ndarray  # [NB] i32, winner's dispatch kind
    subj: jnp.ndarray  # [NB] i32, winner's subject
    arg: jnp.ndarray   # [NB] i32, winner's payload
    gen: jnp.ndarray   # [NB] i32, winner's slot generation


class EventSet(NamedTuple):
    """One replication's future events (CAP slots, struct-of-arrays)."""

    time: jnp.ndarray   # [CAP] f64, +inf = free
    prio: jnp.ndarray   # [CAP] i32, higher fires first at equal time
    seq: jnp.ndarray    # [CAP] i32, schedule order, FIFO tiebreak
    kind: jnp.ndarray   # [CAP] i32, dispatch index (framework/user handler)
    subj: jnp.ndarray   # [CAP] i32, subject (process id, resource id, ...)
    arg: jnp.ndarray    # [CAP] i32, payload (signal code, ...)
    gen: jnp.ndarray    # [CAP] i32, slot generation (ABA-safe handles)
    next_seq: jnp.ndarray  # i32, next sequence number
    overflow: jnp.ndarray  # bool, a schedule was dropped
    #: hierarchical block minima (BlockMin) or None — None prunes the
    #: leaves from the pytree, so the flat-scan oracle's EventSet is
    #: structurally identical to the historical one
    blk: Any = None


class Event(NamedTuple):
    """A popped event."""

    time: jnp.ndarray
    prio: jnp.ndarray
    kind: jnp.ndarray
    subj: jnp.ndarray
    arg: jnp.ndarray
    found: jnp.ndarray   # bool: False if the set was empty
    handle: jnp.ndarray  # the event's (pre-pop) handle; NULL_HANDLE if none


def hier_block(capacity: int):
    """Block size for the hierarchical minima at this capacity, or None
    for the flat layout.  Hierarchy pays only when there are at least two
    blocks to tournament over; capacities that don't tile evenly (or the
    flat-oracle flag) keep the flat scan."""
    if not config.eventset_hier_enabled():
        return None
    b = config.eventset_block()
    if b < 2 or capacity % b or capacity // b < 2:
        return None
    return b


def create(capacity: int) -> EventSet:
    if capacity > _SLOT_MASK + 1:
        raise ValueError(f"event capacity {capacity} exceeds {_SLOT_MASK + 1}")
    b = hier_block(capacity)
    blk = None
    if b is not None:
        nb = capacity // b
        # empty-table summary == what _refresh_* computes on an empty
        # block: the _lexmin fold identities, winner slot defaulting to
        # the block base (argmax over an all-false mask picks index 0)
        blk = BlockMin(
            time=jnp.full((nb,), NEVER, _T),
            prio=jnp.full((nb,), jnp.iinfo(jnp.int32).min, _I),
            seq=jnp.full((nb,), jnp.iinfo(jnp.int32).max, _I),
            slot=jnp.arange(nb, dtype=_I) * b,
            kind=jnp.zeros((nb,), _I),
            subj=jnp.zeros((nb,), _I),
            arg=jnp.zeros((nb,), _I),
            gen=jnp.zeros((nb,), _I),
        )
    return EventSet(
        time=jnp.full((capacity,), NEVER, _T),
        prio=jnp.zeros((capacity,), _I),
        seq=jnp.zeros((capacity,), _I),
        kind=jnp.zeros((capacity,), _I),
        subj=jnp.zeros((capacity,), _I),
        arg=jnp.zeros((capacity,), _I),
        gen=jnp.zeros((capacity,), _I),
        next_seq=jnp.zeros((), _I),
        overflow=jnp.asarray(False),
        blk=blk,
    )


def _handle(slot, gen):
    return (gen << _GEN_SHIFT) | slot


def schedule(es: EventSet, t, prio, kind, subj, arg):
    """Insert an event; returns (es, handle).

    A non-finite time or a full table sets the overflow/error flag and
    returns NULL_HANDLE — the caller (event loop) masks the replication
    as failed rather than corrupting state.
    """
    t = jnp.asarray(t, _T)
    free = jnp.isinf(es.time)
    # first free slot — iota-min, NOT argmax: several slots are free, and
    # Mosaic's argmax tie-break differs from XLA's lowest-index rule
    # (dyn.first_true32); out-of-range when full is gated by ok
    slot = dyn.first_true32(free).astype(_I)
    ok = jnp.any(free) & jnp.isfinite(t)
    # ONE shared write mask for all six field scatters (a per-field
    # dyn.dset would re-derive the iota==slot one-hot six times over —
    # measured as the dominant per-schedule cost at large CAP, back when
    # holds still lived here; timer-heavy models still hit this path)
    m = dyn._oh1(es.time.shape[0], slot) & ok

    def put(a, v):
        return jnp.where(m, jnp.asarray(v, a.dtype), a)

    es2 = es._replace(
        time=put(es.time, t),
        prio=put(es.prio, jnp.asarray(prio, _I)),
        seq=put(es.seq, es.next_seq),
        kind=put(es.kind, jnp.asarray(kind, _I)),
        subj=put(es.subj, jnp.asarray(subj, _I)),
        arg=put(es.arg, jnp.asarray(arg, _I)),
        next_seq=es.next_seq + jnp.where(ok, 1, 0).astype(_I),
        overflow=es.overflow | ~ok,
    )
    es2 = _touch(es2, slot)
    handle = jnp.where(
        ok, _handle(slot, dyn._reduce_pick(m, es.gen)), NULL_HANDLE
    )
    return es2, handle.astype(_I)


def _slot_of(handle):
    return handle & _SLOT_MASK


def _gen_of(handle):
    return handle >> _GEN_SHIFT


def _valid(es: EventSet, handle):
    slot = _slot_of(handle)
    return (
        (handle >= 0)
        & jnp.isfinite(dyn.dget(es.time, slot))
        & (dyn.dget(es.gen, slot) == _gen_of(handle))
    )


def _valid_vec(es: EventSet, handles):
    """Vectorized :func:`_valid` for a [k] vector of handles (the
    wait_event waiter scan checks every process's awaited handle per
    step — a per-handle dget would make that scan O(k*CAP) serial).
    One [k, CAP] one-hot serves both the liveness and generation reads;
    out-of-range slots behave exactly as the scalar dget (all-false
    mask -> zero picks)."""
    slot = (jnp.maximum(handles, 0) & _SLOT_MASK)[:, None]
    oh = slot == lax.broadcasted_iota(
        jnp.int32, (1, es.time.shape[0]), 1
    )
    t_at = jnp.sum(
        jnp.where(oh, es.time[None, :], jnp.zeros((), _T)),
        axis=1, dtype=_T,
    )
    g_at = jnp.sum(
        jnp.where(oh, es.gen[None, :], jnp.zeros((), _I)),
        axis=1, dtype=_I,
    )
    return (
        (handles >= 0)
        & jnp.isfinite(t_at)
        & (g_at == _gen_of(handles))
    )


def _handle_mask(es: EventSet, handle):
    """Shared (one-hot mask, ok) for handle-addressed ops: the slot
    one-hot is derived once and reused for the liveness/generation reads
    AND the writes, instead of one one-hot per dget/dset."""
    slot = _slot_of(jnp.maximum(handle, 0))
    ohs = dyn._oh1(es.time.shape[0], slot)
    t_at = dyn._reduce_pick(ohs, es.time)
    g_at = dyn._reduce_pick(ohs, es.gen)
    ok = (handle >= 0) & jnp.isfinite(t_at) & (g_at == _gen_of(handle))
    return ohs & ok, ok


def cancel(es: EventSet, handle):
    """Remove by handle; returns (es, existed).  O(1) scatter — the
    capability the reference needed the whole hash map for."""
    m, ok = _handle_mask(es, handle)
    es2 = es._replace(
        time=jnp.where(m, _T(NEVER), es.time),
        gen=es.gen + m.astype(_I),
    )
    return _touch(es2, _slot_of(jnp.maximum(handle, 0))), ok


def reschedule(es: EventSet, handle, new_t):
    """Move an event in time, keeping FIFO seq (parity:
    ``cmb_event_reschedule``).  Returns (es, existed)."""
    new_t = jnp.asarray(new_t, _T)
    m, ok = _handle_mask(es, handle)
    fin = jnp.isfinite(new_t)
    es2 = es._replace(time=jnp.where(m & fin, new_t, es.time))
    return _touch(es2, _slot_of(jnp.maximum(handle, 0))), ok & fin


def reprioritize(es: EventSet, handle, new_prio):
    """Parity: ``cmb_event_reprioritize``.  Returns (es, existed)."""
    m, ok = _handle_mask(es, handle)
    es2 = es._replace(
        prio=jnp.where(m, jnp.asarray(new_prio, _I), es.prio)
    )
    return _touch(es2, _slot_of(jnp.maximum(handle, 0))), ok


def _lexmin(time, prio, seq):
    """Shared (time asc, prio desc, seq asc) argnext over parallel arrays:
    returns (mask, found, t_min, p_max, s_min).  ``found`` is folded into
    the first mask, which makes the result EXACTLY one-hot with no
    uniquification pass: live slots carry distinct seq values (strictly
    increasing at schedule, preserved by reschedule), and when the set is
    empty the mask is all-false rather than matching every +inf free
    slot."""
    t_min = jnp.min(time)
    found = jnp.isfinite(t_min)
    m1 = (time == t_min) & found
    p_max = jnp.max(jnp.where(m1, prio, jnp.iinfo(jnp.int32).min))
    m2 = m1 & (prio == p_max)
    s_min = jnp.min(jnp.where(m2, seq, jnp.iinfo(jnp.int32).max))
    m3 = m2 & (seq == s_min)  # one-hot (or empty): seq unique when live
    return m3, found, t_min, p_max, s_min


# --- hierarchical block minima (the two-level tournament) -----------------
#
# Upper level: BlockMin, one lexmin winner per B-slot block.  The global
# winner is the lexmin over block winners (the tournament property of a
# total order), and live slots carry globally unique seq values, so the
# two-level pick is BITWISE the flat scan's pick — pinned by
# tests/test_eventset_hier.py across both dtype profiles and under vmap.
# XLA-path only: the per-block refresh lowers to gathers under vmap,
# which Mosaic has no rule for, so kernel-mode tracing over a
# hierarchical EventSet raises loudly at build time (the obs/trace
# precedent) instead of miscompiling.


def _no_kernel():
    if config.KERNEL_MODE:
        raise ValueError(
            "hierarchical event-set minima are XLA-path only (the block "
            "refresh lowers to gathers Mosaic has no rule for) — build "
            "kernel-path Sims under config.EVENTSET_HIER=False / "
            "CIMBA_EVENTSET_HIER=0"
        )


def _blk_geometry(es: EventSet):
    nb = es.blk.time.shape[0]
    return nb, es.time.shape[0] // nb


def _lexmin_rows(time, prio, seq):
    """Row-wise :func:`_lexmin` over ``[NB, B]`` block views: returns
    per-row (mask, found, t_min, p_max, s_min), same fold identities."""
    t_min = jnp.min(time, axis=1)
    found = jnp.isfinite(t_min)
    m1 = (time == t_min[:, None]) & found[:, None]
    p_max = jnp.max(
        jnp.where(m1, prio, jnp.iinfo(jnp.int32).min), axis=1
    )
    m2 = m1 & (prio == p_max[:, None])
    s_min = jnp.min(
        jnp.where(m2, seq, jnp.iinfo(jnp.int32).max), axis=1
    )
    m3 = m2 & (seq == s_min[:, None])
    return m3, found, t_min, p_max, s_min


def _refresh_all(es: EventSet) -> BlockMin:
    """Rebuild every block summary from the table in one reshape pass —
    the mass-mutation (pattern_cancel) and regrow-rebuild path."""
    _no_kernel()
    nb, b = _blk_geometry(es)

    def rs(a):
        return lax.reshape(a, (nb, b))

    m3, found, t_min, p_max, s_min = _lexmin_rows(
        rs(es.time), rs(es.prio), rs(es.seq)
    )
    j = _argmax32(m3, axis=1).astype(_I)

    def pick(a):
        return jnp.sum(
            jnp.where(m3, rs(a), jnp.zeros((), a.dtype)),
            axis=1, dtype=a.dtype,
        )

    return BlockMin(
        time=t_min,
        prio=p_max,
        seq=s_min,
        slot=jnp.arange(nb, dtype=_I) * b + j,
        kind=pick(es.kind),
        subj=pick(es.subj),
        arg=pick(es.arg),
        gen=pick(es.gen),
    )


def _refresh_slot(es: EventSet, slot) -> BlockMin:
    """Recompute the one block summary covering ``slot`` (O(B) slice +
    O(NB) row write).  Out-of-range slots (a full-table schedule, a
    garbage handle) write no row: the dynamic_slice clamps and the dset
    matches nothing — and since the table write was masked off in those
    same cases, no-write is exactly right."""
    _no_kernel()
    nb, b = _blk_geometry(es)
    blkid = jnp.asarray(slot, _I) // b
    start = blkid * b

    def seg(a):
        return lax.dynamic_slice(a, (start,), (b,))

    m3, found, t_min, p_max, s_min = _lexmin(
        seg(es.time), seg(es.prio), seg(es.seq)
    )
    new = BlockMin(
        time=t_min,
        prio=p_max,
        seq=s_min,
        slot=start + _argmax32(m3).astype(_I),
        kind=dyn._reduce_pick(m3, seg(es.kind)),
        subj=dyn._reduce_pick(m3, seg(es.subj)),
        arg=dyn._reduce_pick(m3, seg(es.arg)),
        gen=dyn._reduce_pick(m3, seg(es.gen)),
    )
    return BlockMin(
        *(dyn.dset(a, blkid, v) for a, v in zip(es.blk, new))
    )


def _touch(es: EventSet, slot) -> EventSet:
    """Refresh the block summary covering ``slot`` after a single-slot
    table write.  Recompute-from-table: safe even when the write was
    pred-gated off (the recomputed row equals the old one)."""
    if es.blk is None:
        return es
    return es._replace(blk=_refresh_slot(es, slot))


def _touch_all(es: EventSet) -> EventSet:
    if es.blk is None:
        return es
    return es._replace(blk=_refresh_all(es))


def _hier_next(es: EventSet):
    """Two-level pick: (found, slot, time, prio, kind, subj, arg, gen,
    take_mask[CAP]) from the NB block winners — bitwise the flat scan's
    answer (tournament over a total order; unique seqs kill ties)."""
    _no_kernel()
    m_b, found, t_min, p_max, _ = _lexmin(
        es.blk.time, es.blk.prio, es.blk.seq
    )
    slot = dyn._reduce_pick(m_b, es.blk.slot)
    take = (
        lax.broadcasted_iota(jnp.int32, es.time.shape, 0) == slot
    ) & found
    return (
        found,
        slot,
        dyn._reduce_pick(m_b, es.blk.time),
        dyn._reduce_pick(m_b, es.blk.prio),
        dyn._reduce_pick(m_b, es.blk.kind),
        dyn._reduce_pick(m_b, es.blk.subj),
        dyn._reduce_pick(m_b, es.blk.arg),
        dyn._reduce_pick(m_b, es.blk.gen),
        take,
    )


def _argnext(es: EventSet):
    """Index of the next event: min time, then max prio, then min seq —
    three masked reductions, no data-dependent control flow."""
    m3, found, _, _, _ = _lexmin(es.time, es.prio, es.seq)
    slot = _argmax32(m3).astype(_I)
    return slot, m3, found


def _next_parts(es: EventSet):
    """(found, slot, time, prio, kind, subj, arg, gen, take[CAP]) of the
    next event — the flat scan or the two-level tournament, bitwise
    interchangeable.  Not-found fields are the all-false-mask picks
    (zeros), matching the flat reductions exactly."""
    if es.blk is not None:
        return _hier_next(es)
    slot, m, found = _argnext(es)
    return (
        found,
        slot,
        dyn._reduce_pick(m, es.time),
        dyn._reduce_pick(m, es.prio),
        dyn._reduce_pick(m, es.kind),
        dyn._reduce_pick(m, es.subj),
        dyn._reduce_pick(m, es.arg),
        dyn._reduce_pick(m, es.gen),
        m,
    )


def peek(es: EventSet) -> Event:
    found, slot, t, prio, kind, subj, arg, gen, _ = _next_parts(es)
    return Event(
        time=t,
        prio=prio,
        kind=kind,
        subj=subj,
        arg=arg,
        found=found,
        handle=jnp.where(
            found, _handle(slot, gen), NULL_HANDLE
        ).astype(_I),
    )


def pop(es: EventSet):
    """Remove and return the next event; (es, Event)."""
    found, slot, t, prio, kind, subj, arg, gen, m = _next_parts(es)
    ev = Event(
        time=t,
        prio=prio,
        kind=kind,
        subj=subj,
        arg=arg,
        found=found,
        handle=jnp.where(
            found, _handle(slot, gen), NULL_HANDLE
        ).astype(_I),
    )
    # m already folds `found` (all-false on an empty set), so the consume
    # writes need no extra gating
    es2 = es._replace(
        time=jnp.where(m, _T(NEVER), es.time),
        gen=es.gen + m.astype(_I),
    )
    return _touch(es2, slot), ev


def is_empty(es: EventSet):
    if es.blk is not None:
        return ~jnp.any(jnp.isfinite(es.blk.time))
    return ~jnp.any(jnp.isfinite(es.time))


def min_time(es: EventSet):
    """Soonest live time (+inf when empty) — O(NB) under the hierarchy
    (the t_end horizon check in loop.make_cond runs this every step)."""
    if es.blk is not None:
        return jnp.min(es.blk.time)
    return jnp.min(es.time)


def length(es: EventSet):
    return jnp.sum(jnp.isfinite(es.time).astype(_I))


# --- pattern operations (parity: cmb_event_pattern_* wildcards,
#     `src/cmb_event.c:459-493`) — vectorized full scans -------------------

WILDCARD = jnp.int32(-1)


def _match(es: EventSet, kind, subj):
    live = jnp.isfinite(es.time)
    k = jnp.asarray(kind, _I)
    s = jnp.asarray(subj, _I)
    mk = (k == WILDCARD) | (es.kind == k)
    ms = (s == WILDCARD) | (es.subj == s)
    return live & mk & ms


def pattern_count(es: EventSet, kind=WILDCARD, subj=WILDCARD):
    return jnp.sum(_match(es, kind, subj).astype(_I))


def pattern_cancel(es: EventSet, kind=WILDCARD, subj=WILDCARD, pred=True):
    """Cancel all matching events; returns (es, n_cancelled).  ``pred``
    gates the cancellation (n_cancelled still reports the match count)."""
    m = _match(es, kind, subj)
    mw = m if pred is True else (m & pred)
    es2 = es._replace(
        time=jnp.where(mw, NEVER, es.time),
        gen=es.gen + mw.astype(_I),
    )
    # mass mutation can touch any block: rebuild all rows in one pass
    return _touch_all(es2), jnp.sum(m.astype(_I))


def pattern_find(es: EventSet, kind=WILDCARD, subj=WILDCARD):
    """Handle of the soonest matching event, else NULL_HANDLE."""
    m = _match(es, kind, subj)
    t = jnp.where(m, es.time, NEVER)
    t_min = jnp.min(t)
    found = jnp.isfinite(t_min)
    # lowest slot among equal-time matches — argmin time ties are
    # backend-dependent under Mosaic (dyn.first_true32)
    slot = dyn.first_true32(m & (t == t_min)).astype(_I)
    return jnp.where(
        found, _handle(slot, dyn.dget(es.gen, slot)), NULL_HANDLE
    ).astype(_I)

# --- dense per-process resume events ------------------------------------
#
# The overwhelming majority of events in any model are process resumes —
# holds, guard wakes, interrupt/timer deliveries (kind K_PROC) — and the
# dispatcher maintains at most ONE pending resume per process (every
# K_PROC schedule either follows a cancel of the previous wake or targets
# a process that provably has none; loop.py's _schedule_wake/_cancel_wake
# discipline).  Storing them densely with slot = pid removes the general
# table's free-slot search, generation tags and scatter masks for the hot
# case, and shrinks the general table to timers + user events only.
# Priority is read LIVE from procs.prio at pop time — exactly the
# semantics priority_set's reshuffle used to restore — and seq draws from
# the same next_seq counter as the general table, so the (time, prio
# DESC, seq) dispatch contract is preserved verbatim across both tables.
# (Reference parity note: this splits `cmi_hashheap` by event class; the
# reference's heap does not need the split because its per-op cost is
# O(log n) serial, ours is O(table width) vectorized.)


class Wakes(NamedTuple):
    """Pending per-process resumes ([P] slots, +inf time = none)."""

    time: jnp.ndarray  # [P] _T
    sig: jnp.ndarray   # [P] i32 signal delivered on resume
    seq: jnp.ndarray   # [P] i32 FIFO tiebreak (shared next_seq counter)


def wakes_create(n: int) -> Wakes:
    return Wakes(
        time=jnp.full((n,), NEVER, _T),
        sig=jnp.zeros((n,), _I),
        seq=jnp.zeros((n,), _I),
    )


def wake_set(wk: Wakes, p, t, sig, seq, pred=True):
    """Arm (or overwrite) process p's resume; returns (wk, ok).  ``ok``
    is false — and nothing is written — for a non-finite time (the
    general table's overflow-as-failure parity; a dense slot can never
    be 'full')."""
    t = jnp.asarray(t, _T)
    ok = jnp.isfinite(t)
    if pred is not True:
        ok = ok & pred
    m = dyn._oh1(wk.time.shape[0], p) & ok
    return (
        Wakes(
            time=jnp.where(m, t, wk.time),
            sig=jnp.where(m, jnp.asarray(sig, _I), wk.sig),
            seq=jnp.where(m, jnp.asarray(seq, _I), wk.seq),
        ),
        ok,
    )


def wake_clear(wk: Wakes, p, pred=True) -> Wakes:
    m = dyn._oh1(wk.time.shape[0], p)
    if pred is not True:
        m = m & pred
    return wk._replace(time=jnp.where(m, _T(NEVER), wk.time))


def wakes_empty(wk: Wakes):
    return ~jnp.any(jnp.isfinite(wk.time))


def peek_merged(es: EventSet, wk: Wakes, prio, wake_kind):
    """Next event across the general table and the dense wakes WITHOUT
    consuming it (lexicographic (time, prio DESC, seq) over the union;
    ``prio`` is the live procs.prio array, ``wake_kind`` the dispatch
    kind a wake pop reports — the caller's K_PROC).  Returns
    (Event, take_e, take_w): the one-hot consume masks for the two
    tables, for :func:`consume_merged`.  A wake pop carries
    ``handle=NULL_HANDLE`` — wake events are unaddressable, so the
    wait_event machinery (which only ever holds general-table handles)
    never matches them."""
    if es.blk is not None:
        # two-level tournament: the general arm reduces over the NB
        # block winners (docs/11_dispatch_cost.md) — same values, fewer
        # elements.  t_e/p_e/s_e keep the _lexmin fold identities for
        # the empty case (the wake_first compare below relies on them) —
        # which is why this branch is NOT _next_parts: that helper's
        # empty-case fields are the all-false-mask picks (zeros), the
        # contract peek/pop share with the historical flat reductions.
        # The ordering itself has one home either way: _lexmin.
        m_b, found_e, t_e, p_e, s_e = _lexmin(
            es.blk.time, es.blk.prio, es.blk.seq
        )
        slot_e = dyn._reduce_pick(m_b, es.blk.slot)
        kind_e = dyn._reduce_pick(m_b, es.blk.kind)
        subj_e = dyn._reduce_pick(m_b, es.blk.subj)
        arg_e = dyn._reduce_pick(m_b, es.blk.arg)
        gen_e = dyn._reduce_pick(m_b, es.blk.gen)
        take_e = (
            lax.broadcasted_iota(jnp.int32, es.time.shape, 0) == slot_e
        ) & found_e
    else:
        m_e, found_e, t_e, p_e, s_e = _lexmin(es.time, es.prio, es.seq)
        slot_e = _argmax32(m_e).astype(_I)
        kind_e = dyn._reduce_pick(m_e, es.kind)
        subj_e = dyn._reduce_pick(m_e, es.subj)
        arg_e = dyn._reduce_pick(m_e, es.arg)
        gen_e = dyn._reduce_pick(m_e, es.gen)
        take_e = m_e
    m_w, found_w, t_w, p_w, s_w = _lexmin(wk.time, prio, wk.seq)

    wake_first = found_w & (
        ~found_e
        | (t_w < t_e)
        | ((t_w == t_e) & ((p_w > p_e) | ((p_w == p_e) & (s_w < s_e))))
    )
    found = found_e | found_w

    pid_w = _argmax32(m_w).astype(_I)
    event = Event(
        time=jnp.where(wake_first, t_w, t_e),
        prio=jnp.where(wake_first, p_w, p_e),
        kind=jnp.where(
            wake_first, jnp.asarray(wake_kind, _I), kind_e
        ),
        subj=jnp.where(wake_first, pid_w, subj_e),
        arg=jnp.where(
            wake_first, dyn._reduce_pick(m_w, wk.sig), arg_e
        ),
        found=found,
        handle=jnp.where(
            found & ~wake_first,
            _handle(slot_e, gen_e),
            NULL_HANDLE,
        ).astype(_I),
    )
    return event, take_e & ~wake_first, m_w & wake_first


def consume_merged(es: EventSet, wk: Wakes, take_e, take_w, pred=True):
    """Remove the peeked event (``pred`` gates the removal — the kernel
    driver defers boundary-block dispatches by peeking without
    consuming)."""
    if pred is not True:
        take_e = take_e & pred
        take_w = take_w & pred
    es2 = es._replace(
        time=jnp.where(take_e, _T(NEVER), es.time),
        gen=es.gen + take_e.astype(_I),
    )
    if es.blk is not None:
        # single-slot consume: refresh only the covering block (an
        # all-false take yields an out-of-range slot -> refresh no-op)
        es2 = _touch(es2, dyn.first_true32(take_e))
    wk2 = wk._replace(time=jnp.where(take_w, _T(NEVER), wk.time))
    return es2, wk2


def pop_merged(es: EventSet, wk: Wakes, prio, wake_kind):
    """peek_merged + consume_merged in one step; returns (es, wk, Event)."""
    event, take_e, take_w = peek_merged(es, wk, prio, wake_kind)
    es2, wk2 = consume_merged(es, wk, take_e, take_w)
    return es2, wk2, event
