"""The future-event set: fixed-capacity, branch-free, batched by vmap.

Reference parity: the event queue is the reference's performance heart — a
binary heap fused with a hash map (`src/cmi_hashheap.c`, 937 lines of
open-addressing, tombstones and Fibonacci hashing) giving O(log n) pops and
O(1) handle-based cancel/reschedule (`src/cmb_event.c:190-335`).

TPU redesign: none of that survives contact with the VPU.  A heap's
sift-up/down is a chain of data-dependent scalar gathers — poison under
vmap.  Instead the event set is a **flat slot table**: CAP parallel arrays,
`time == +inf` marks a free slot, and "pop min" is a lexicographic argmin
over (time, -priority, seq) computed with three masked reductions — O(CAP)
work but a handful of fully-vectorized VPU ops, which for the CAP <= a few
hundred of process-interaction models beats the heap's serial pointer
chasing by a wide margin.  Handles are (slot | generation<<16), making
cancel/reschedule O(1) scatters and ABA-safe, replacing the hash map
entirely.  The hashheap's amortized-doubling growth
(`src/cmi_hashheap.c:384-426`) becomes a static capacity with an overflow
flag — the replication is failure-masked, the experiment continues
(SURVEY.md §7 hard part (b)).

Event ordering contract (parity with `src/cmb_event.c:75-100`): earlier
time first, then HIGHER priority, then FIFO by sequence number.

All functions are scalar-style (one replication); the framework vmaps.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from cimba_tpu import config
from cimba_tpu.core import dyn
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.config import argmax32 as _argmax32, argmin32 as _argmin32

_T = config.TIME
_I = INDEX_DTYPE

#: slot value meaning "no event here"
NEVER = jnp.inf
#: handle returned when scheduling fails (capacity exhausted)
NULL_HANDLE = jnp.int32(-1)

_GEN_SHIFT = 16
_SLOT_MASK = (1 << _GEN_SHIFT) - 1


class EventSet(NamedTuple):
    """One replication's future events (CAP slots, struct-of-arrays)."""

    time: jnp.ndarray   # [CAP] f64, +inf = free
    prio: jnp.ndarray   # [CAP] i32, higher fires first at equal time
    seq: jnp.ndarray    # [CAP] i32, schedule order, FIFO tiebreak
    kind: jnp.ndarray   # [CAP] i32, dispatch index (framework/user handler)
    subj: jnp.ndarray   # [CAP] i32, subject (process id, resource id, ...)
    arg: jnp.ndarray    # [CAP] i32, payload (signal code, ...)
    gen: jnp.ndarray    # [CAP] i32, slot generation (ABA-safe handles)
    next_seq: jnp.ndarray  # i32, next sequence number
    overflow: jnp.ndarray  # bool, a schedule was dropped


class Event(NamedTuple):
    """A popped event."""

    time: jnp.ndarray
    prio: jnp.ndarray
    kind: jnp.ndarray
    subj: jnp.ndarray
    arg: jnp.ndarray
    found: jnp.ndarray   # bool: False if the set was empty
    handle: jnp.ndarray  # the event's (pre-pop) handle; NULL_HANDLE if none


def create(capacity: int) -> EventSet:
    if capacity > _SLOT_MASK + 1:
        raise ValueError(f"event capacity {capacity} exceeds {_SLOT_MASK + 1}")
    return EventSet(
        time=jnp.full((capacity,), NEVER, _T),
        prio=jnp.zeros((capacity,), _I),
        seq=jnp.zeros((capacity,), _I),
        kind=jnp.zeros((capacity,), _I),
        subj=jnp.zeros((capacity,), _I),
        arg=jnp.zeros((capacity,), _I),
        gen=jnp.zeros((capacity,), _I),
        next_seq=jnp.zeros((), _I),
        overflow=jnp.asarray(False),
    )


def _handle(slot, gen):
    return (gen << _GEN_SHIFT) | slot


def schedule(es: EventSet, t, prio, kind, subj, arg):
    """Insert an event; returns (es, handle).

    A non-finite time or a full table sets the overflow/error flag and
    returns NULL_HANDLE — the caller (event loop) masks the replication
    as failed rather than corrupting state.
    """
    t = jnp.asarray(t, _T)
    free = jnp.isinf(es.time)
    # first free slot — iota-min, NOT argmax: several slots are free, and
    # Mosaic's argmax tie-break differs from XLA's lowest-index rule
    # (dyn.first_true32); out-of-range when full is gated by ok
    slot = dyn.first_true32(free).astype(_I)
    ok = jnp.any(free) & jnp.isfinite(t)
    # ONE shared write mask for all six field scatters (a per-field
    # dyn.dset would re-derive the iota==slot one-hot six times over —
    # measured as the dominant per-schedule cost at large CAP, back when
    # holds still lived here; timer-heavy models still hit this path)
    m = dyn._oh1(es.time.shape[0], slot) & ok

    def put(a, v):
        return jnp.where(m, jnp.asarray(v, a.dtype), a)

    es2 = EventSet(
        time=put(es.time, t),
        prio=put(es.prio, jnp.asarray(prio, _I)),
        seq=put(es.seq, es.next_seq),
        kind=put(es.kind, jnp.asarray(kind, _I)),
        subj=put(es.subj, jnp.asarray(subj, _I)),
        arg=put(es.arg, jnp.asarray(arg, _I)),
        gen=es.gen,
        next_seq=es.next_seq + jnp.where(ok, 1, 0).astype(_I),
        overflow=es.overflow | ~ok,
    )
    handle = jnp.where(
        ok, _handle(slot, dyn._reduce_pick(m, es.gen)), NULL_HANDLE
    )
    return es2, handle.astype(_I)


def _slot_of(handle):
    return handle & _SLOT_MASK


def _gen_of(handle):
    return handle >> _GEN_SHIFT


def _valid(es: EventSet, handle):
    slot = _slot_of(handle)
    return (
        (handle >= 0)
        & jnp.isfinite(dyn.dget(es.time, slot))
        & (dyn.dget(es.gen, slot) == _gen_of(handle))
    )


def _valid_vec(es: EventSet, handles):
    """Vectorized :func:`_valid` for a [k] vector of handles (the
    wait_event waiter scan checks every process's awaited handle per
    step — a per-handle dget would make that scan O(k*CAP) serial).
    One [k, CAP] one-hot serves both the liveness and generation reads;
    out-of-range slots behave exactly as the scalar dget (all-false
    mask -> zero picks)."""
    slot = (jnp.maximum(handles, 0) & _SLOT_MASK)[:, None]
    oh = slot == lax.broadcasted_iota(
        jnp.int32, (1, es.time.shape[0]), 1
    )
    t_at = jnp.sum(
        jnp.where(oh, es.time[None, :], jnp.zeros((), _T)),
        axis=1, dtype=_T,
    )
    g_at = jnp.sum(
        jnp.where(oh, es.gen[None, :], jnp.zeros((), _I)),
        axis=1, dtype=_I,
    )
    return (
        (handles >= 0)
        & jnp.isfinite(t_at)
        & (g_at == _gen_of(handles))
    )


def _handle_mask(es: EventSet, handle):
    """Shared (one-hot mask, ok) for handle-addressed ops: the slot
    one-hot is derived once and reused for the liveness/generation reads
    AND the writes, instead of one one-hot per dget/dset."""
    slot = _slot_of(jnp.maximum(handle, 0))
    ohs = dyn._oh1(es.time.shape[0], slot)
    t_at = dyn._reduce_pick(ohs, es.time)
    g_at = dyn._reduce_pick(ohs, es.gen)
    ok = (handle >= 0) & jnp.isfinite(t_at) & (g_at == _gen_of(handle))
    return ohs & ok, ok


def cancel(es: EventSet, handle):
    """Remove by handle; returns (es, existed).  O(1) scatter — the
    capability the reference needed the whole hash map for."""
    m, ok = _handle_mask(es, handle)
    return (
        es._replace(
            time=jnp.where(m, _T(NEVER), es.time),
            gen=es.gen + m.astype(_I),
        ),
        ok,
    )


def reschedule(es: EventSet, handle, new_t):
    """Move an event in time, keeping FIFO seq (parity:
    ``cmb_event_reschedule``).  Returns (es, existed)."""
    new_t = jnp.asarray(new_t, _T)
    m, ok = _handle_mask(es, handle)
    fin = jnp.isfinite(new_t)
    return (
        es._replace(
            time=jnp.where(m & fin, new_t, es.time)
        ),
        ok & fin,
    )


def reprioritize(es: EventSet, handle, new_prio):
    """Parity: ``cmb_event_reprioritize``.  Returns (es, existed)."""
    m, ok = _handle_mask(es, handle)
    return (
        es._replace(
            prio=jnp.where(m, jnp.asarray(new_prio, _I), es.prio)
        ),
        ok,
    )


def _lexmin(time, prio, seq):
    """Shared (time asc, prio desc, seq asc) argnext over parallel arrays:
    returns (mask, found, t_min, p_max, s_min).  ``found`` is folded into
    the first mask, which makes the result EXACTLY one-hot with no
    uniquification pass: live slots carry distinct seq values (strictly
    increasing at schedule, preserved by reschedule), and when the set is
    empty the mask is all-false rather than matching every +inf free
    slot."""
    t_min = jnp.min(time)
    found = jnp.isfinite(t_min)
    m1 = (time == t_min) & found
    p_max = jnp.max(jnp.where(m1, prio, jnp.iinfo(jnp.int32).min))
    m2 = m1 & (prio == p_max)
    s_min = jnp.min(jnp.where(m2, seq, jnp.iinfo(jnp.int32).max))
    m3 = m2 & (seq == s_min)  # one-hot (or empty): seq unique when live
    return m3, found, t_min, p_max, s_min


def _argnext(es: EventSet):
    """Index of the next event: min time, then max prio, then min seq —
    three masked reductions, no data-dependent control flow."""
    m3, found, _, _, _ = _lexmin(es.time, es.prio, es.seq)
    slot = _argmax32(m3).astype(_I)
    return slot, m3, found


def peek(es: EventSet) -> Event:
    slot, m, found = _argnext(es)
    return Event(
        time=dyn._reduce_pick(m, es.time),
        prio=dyn._reduce_pick(m, es.prio),
        kind=dyn._reduce_pick(m, es.kind),
        subj=dyn._reduce_pick(m, es.subj),
        arg=dyn._reduce_pick(m, es.arg),
        found=found,
        handle=jnp.where(
            found, _handle(slot, dyn._reduce_pick(m, es.gen)), NULL_HANDLE
        ).astype(_I),
    )


def pop(es: EventSet):
    """Remove and return the next event; (es, Event)."""
    slot, m, found = _argnext(es)
    ev = Event(
        time=dyn._reduce_pick(m, es.time),
        prio=dyn._reduce_pick(m, es.prio),
        kind=dyn._reduce_pick(m, es.kind),
        subj=dyn._reduce_pick(m, es.subj),
        arg=dyn._reduce_pick(m, es.arg),
        found=found,
        handle=jnp.where(
            found, _handle(slot, dyn._reduce_pick(m, es.gen)), NULL_HANDLE
        ).astype(_I),
    )
    # m already folds `found` (all-false on an empty set), so the consume
    # writes need no extra gating
    es2 = es._replace(
        time=jnp.where(m, _T(NEVER), es.time),
        gen=es.gen + m.astype(_I),
    )
    return es2, ev


def is_empty(es: EventSet):
    return ~jnp.any(jnp.isfinite(es.time))


def length(es: EventSet):
    return jnp.sum(jnp.isfinite(es.time).astype(_I))


# --- pattern operations (parity: cmb_event_pattern_* wildcards,
#     `src/cmb_event.c:459-493`) — vectorized full scans -------------------

WILDCARD = jnp.int32(-1)


def _match(es: EventSet, kind, subj):
    live = jnp.isfinite(es.time)
    k = jnp.asarray(kind, _I)
    s = jnp.asarray(subj, _I)
    mk = (k == WILDCARD) | (es.kind == k)
    ms = (s == WILDCARD) | (es.subj == s)
    return live & mk & ms


def pattern_count(es: EventSet, kind=WILDCARD, subj=WILDCARD):
    return jnp.sum(_match(es, kind, subj).astype(_I))


def pattern_cancel(es: EventSet, kind=WILDCARD, subj=WILDCARD, pred=True):
    """Cancel all matching events; returns (es, n_cancelled).  ``pred``
    gates the cancellation (n_cancelled still reports the match count)."""
    m = _match(es, kind, subj)
    mw = m if pred is True else (m & pred)
    return (
        es._replace(
            time=jnp.where(mw, NEVER, es.time),
            gen=es.gen + mw.astype(_I),
        ),
        jnp.sum(m.astype(_I)),
    )


def pattern_find(es: EventSet, kind=WILDCARD, subj=WILDCARD):
    """Handle of the soonest matching event, else NULL_HANDLE."""
    m = _match(es, kind, subj)
    t = jnp.where(m, es.time, NEVER)
    t_min = jnp.min(t)
    found = jnp.isfinite(t_min)
    # lowest slot among equal-time matches — argmin time ties are
    # backend-dependent under Mosaic (dyn.first_true32)
    slot = dyn.first_true32(m & (t == t_min)).astype(_I)
    return jnp.where(
        found, _handle(slot, dyn.dget(es.gen, slot)), NULL_HANDLE
    ).astype(_I)

# --- dense per-process resume events ------------------------------------
#
# The overwhelming majority of events in any model are process resumes —
# holds, guard wakes, interrupt/timer deliveries (kind K_PROC) — and the
# dispatcher maintains at most ONE pending resume per process (every
# K_PROC schedule either follows a cancel of the previous wake or targets
# a process that provably has none; loop.py's _schedule_wake/_cancel_wake
# discipline).  Storing them densely with slot = pid removes the general
# table's free-slot search, generation tags and scatter masks for the hot
# case, and shrinks the general table to timers + user events only.
# Priority is read LIVE from procs.prio at pop time — exactly the
# semantics priority_set's reshuffle used to restore — and seq draws from
# the same next_seq counter as the general table, so the (time, prio
# DESC, seq) dispatch contract is preserved verbatim across both tables.
# (Reference parity note: this splits `cmi_hashheap` by event class; the
# reference's heap does not need the split because its per-op cost is
# O(log n) serial, ours is O(table width) vectorized.)


class Wakes(NamedTuple):
    """Pending per-process resumes ([P] slots, +inf time = none)."""

    time: jnp.ndarray  # [P] _T
    sig: jnp.ndarray   # [P] i32 signal delivered on resume
    seq: jnp.ndarray   # [P] i32 FIFO tiebreak (shared next_seq counter)


def wakes_create(n: int) -> Wakes:
    return Wakes(
        time=jnp.full((n,), NEVER, _T),
        sig=jnp.zeros((n,), _I),
        seq=jnp.zeros((n,), _I),
    )


def wake_set(wk: Wakes, p, t, sig, seq, pred=True):
    """Arm (or overwrite) process p's resume; returns (wk, ok).  ``ok``
    is false — and nothing is written — for a non-finite time (the
    general table's overflow-as-failure parity; a dense slot can never
    be 'full')."""
    t = jnp.asarray(t, _T)
    ok = jnp.isfinite(t)
    if pred is not True:
        ok = ok & pred
    m = dyn._oh1(wk.time.shape[0], p) & ok
    return (
        Wakes(
            time=jnp.where(m, t, wk.time),
            sig=jnp.where(m, jnp.asarray(sig, _I), wk.sig),
            seq=jnp.where(m, jnp.asarray(seq, _I), wk.seq),
        ),
        ok,
    )


def wake_clear(wk: Wakes, p, pred=True) -> Wakes:
    m = dyn._oh1(wk.time.shape[0], p)
    if pred is not True:
        m = m & pred
    return wk._replace(time=jnp.where(m, _T(NEVER), wk.time))


def wakes_empty(wk: Wakes):
    return ~jnp.any(jnp.isfinite(wk.time))


def peek_merged(es: EventSet, wk: Wakes, prio, wake_kind):
    """Next event across the general table and the dense wakes WITHOUT
    consuming it (lexicographic (time, prio DESC, seq) over the union;
    ``prio`` is the live procs.prio array, ``wake_kind`` the dispatch
    kind a wake pop reports — the caller's K_PROC).  Returns
    (Event, take_e, take_w): the one-hot consume masks for the two
    tables, for :func:`consume_merged`.  A wake pop carries
    ``handle=NULL_HANDLE`` — wake events are unaddressable, so the
    wait_event machinery (which only ever holds general-table handles)
    never matches them."""
    m_e, found_e, t_e, p_e, s_e = _lexmin(es.time, es.prio, es.seq)
    m_w, found_w, t_w, p_w, s_w = _lexmin(wk.time, prio, wk.seq)

    wake_first = found_w & (
        ~found_e
        | (t_w < t_e)
        | ((t_w == t_e) & ((p_w > p_e) | ((p_w == p_e) & (s_w < s_e))))
    )
    found = found_e | found_w

    slot_e = _argmax32(m_e).astype(_I)
    pid_w = _argmax32(m_w).astype(_I)
    event = Event(
        time=jnp.where(wake_first, t_w, t_e),
        prio=jnp.where(wake_first, p_w, p_e),
        kind=jnp.where(
            wake_first, jnp.asarray(wake_kind, _I),
            dyn._reduce_pick(m_e, es.kind),
        ),
        subj=jnp.where(wake_first, pid_w, dyn._reduce_pick(m_e, es.subj)),
        arg=jnp.where(
            wake_first, dyn._reduce_pick(m_w, wk.sig),
            dyn._reduce_pick(m_e, es.arg),
        ),
        found=found,
        handle=jnp.where(
            found & ~wake_first,
            _handle(slot_e, dyn._reduce_pick(m_e, es.gen)),
            NULL_HANDLE,
        ).astype(_I),
    )
    return event, m_e & ~wake_first, m_w & wake_first


def consume_merged(es: EventSet, wk: Wakes, take_e, take_w, pred=True):
    """Remove the peeked event (``pred`` gates the removal — the kernel
    driver defers boundary-block dispatches by peeking without
    consuming)."""
    if pred is not True:
        take_e = take_e & pred
        take_w = take_w & pred
    es2 = es._replace(
        time=jnp.where(take_e, _T(NEVER), es.time),
        gen=es.gen + take_e.astype(_I),
    )
    wk2 = wk._replace(time=jnp.where(take_w, _T(NEVER), wk.time))
    return es2, wk2


def pop_merged(es: EventSet, wk: Wakes, prio, wake_kind):
    """peek_merged + consume_merged in one step; returns (es, wk, Event)."""
    event, take_e, take_w = peek_merged(es, wk, prio, wake_kind)
    es2, wk2 = consume_merged(es, wk, take_e, take_w)
    return es2, wk2, event
