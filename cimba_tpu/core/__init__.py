"""cimba-tpu core: event set, guards, processes-as-state-machines, dispatcher.

The reference's L1-L4 (coroutine kernel, event queue, hashheap, process
layer — SURVEY.md §1) re-imagined as batched array state stepped by a
jit-compiled while-loop.
"""

from cimba_tpu.core import api, eventset, guard, loop, model, process
from cimba_tpu.core.loop import (
    Sim,
    drive_chunks,
    init_sim,
    make_chunk,
    make_chunked_run,
    make_run,
    make_step,
)
from cimba_tpu.core.model import Model, ModelSpec
from cimba_tpu.core import process as cmd  # command constructors namespace

__all__ = [
    "api",
    "cmd",
    "eventset",
    "guard",
    "loop",
    "model",
    "process",
    "Sim",
    "drive_chunks",
    "init_sim",
    "make_chunk",
    "make_chunked_run",
    "make_run",
    "make_step",
    "Model",
    "ModelSpec",
]