"""Global configuration for cimba-tpu.

The reference (cimba) does platform detection and TLS-model selection in
``src/cmi_config.h``.  The TPU-native analog is dtype discipline and JAX
global configuration:

* Simulated **time is float64**.  A clock near 1e6 with unit-scale increments
  needs ~1e-10 relative resolution for stable event ordering; float32's
  epsilon at 1e6 is 0.0625 which would corrupt waiting-time statistics.
  float64 is software-emulated on TPU but only the clock/event-time arrays
  pay that cost.
* **Sample values, amounts and statistics accumulate in float64** as well so
  that per-replication summaries are reproducible against the scalar oracle.
* **Indices, handles, program counters are int32** (TPU-native width).
* **RNG internals are uint32** (threefry2x32 counters/keys), which is the
  natively fast integer width on TPU.

Importing :mod:`cimba_tpu` enables ``jax_enable_x64``.  All framework arrays
carry explicit dtypes, so user code that wants pure-32-bit models can still
build them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: Simulated-time dtype (see module docstring).
TIME_DTYPE = jnp.float64
#: Continuous sample / statistics dtype.
REAL_DTYPE = jnp.float64
#: Index / handle / counter dtype.
INDEX_DTYPE = jnp.int32
#: Signal codes are int32 (the reference uses int64 signals; int32 covers the
#: protocol and all practical user signals; see core/signals.py).
SIGNAL_DTYPE = jnp.int32
#: RNG word dtype.
BITS_DTYPE = jnp.uint32

#: Sentinel "time" for empty event slots: +inf sorts after every real event.
TIME_NEVER = float("inf")


def setup() -> None:
    """Enable the JAX global flags cimba-tpu requires (idempotent)."""
    jax.config.update("jax_enable_x64", True)


setup()
