"""Global configuration for cimba-tpu.

The reference (cimba) does platform detection and TLS-model selection in
``src/cmi_config.h``.  The TPU-native analog is dtype discipline and JAX
global configuration:

* Simulated **time is float64**.  A clock near 1e6 with unit-scale increments
  needs ~1e-10 relative resolution for stable event ordering; float32's
  epsilon at 1e6 is 0.0625 which would corrupt waiting-time statistics.
  float64 is software-emulated on TPU but only the clock/event-time arrays
  pay that cost.
* **Sample values, amounts and statistics accumulate in float64** as well so
  that per-replication summaries are reproducible against the scalar oracle.
* **Indices, handles, program counters are int32** (TPU-native width).
* **RNG internals are uint32** (threefry2x32 counters/keys), which is the
  natively fast integer width on TPU.

Importing :mod:`cimba_tpu` enables ``jax_enable_x64``.  All framework arrays
carry explicit dtypes, so user code that wants pure-32-bit models can still
build them.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

#: Simulated-time dtype (see module docstring).  Mutable — see
#: :func:`use_profile`; read it through :data:`TIME` at trace time.
TIME_DTYPE = jnp.float64
#: Continuous sample / statistics dtype.  Mutable — see :func:`use_profile`.
REAL_DTYPE = jnp.float64
#: Wide event-counter dtype (``sim.n_events``).  Mutable with the profile:
#: int64 in the exact profile, int32 in the f32 profile (Mosaic has no i64).
COUNT_DTYPE = jnp.int64
#: Index / handle dtype.
INDEX_DTYPE = jnp.int32
#: Signal codes are int32 (the reference uses int64 signals; int32 covers the
#: protocol and all practical user signals; see core/signals.py).
SIGNAL_DTYPE = jnp.int32
#: RNG word dtype.
BITS_DTYPE = jnp.uint32

#: Sentinel "time" for empty event slots: +inf sorts after every real event.
TIME_NEVER = float("inf")


def argmax32(x, axis: int = 0):
    """``jnp.argmax`` with an int32 result.  Under x64, jnp's arg-reductions
    return int64 — and Mosaic's int64→int32 convert rule recurses forever,
    so everything in a potential kernel path uses these.  Mosaic's
    arg-reduction lowering supports only f32 operands, so bool/int masks
    (every call site's operand is a mask, a small int, or a time) are cast;
    ties keep lowest-index semantics either way."""
    from jax import lax

    if x.dtype != jnp.float32 and x.dtype != jnp.float64:
        x = x.astype(jnp.float32)
    return lax.argmax(x, axis, jnp.int32)


def argmin32(x, axis: int = 0):
    """``jnp.argmin`` with an int32 result (see :func:`argmax32`)."""
    from jax import lax

    if x.dtype != jnp.float32 and x.dtype != jnp.float64:
        x = x.astype(jnp.float32)
    return lax.argmin(x, axis, jnp.int32)


class _DtypeHandle:
    """A live view of a mutable config dtype.

    numpy's dtype protocol resolves any object with a ``.dtype`` attribute,
    so a handle can stand wherever a dtype literal can: ``jnp.asarray(x, _R)``,
    ``x.astype(_R)``, ``jnp.zeros((), _R)``.  Calling it casts a scalar,
    mirroring ``jnp.float64(x)``.  Modules alias these once
    (``_R = config.REAL``) and automatically follow :func:`use_profile`
    switches at trace time — which is how the same interpreter traces in
    float64 for the exact XLA path and in float32 inside the Pallas
    mega-kernel (Mosaic/TPU has no 64-bit types).
    """

    def __init__(self, name: str):
        self._name = name

    @property
    def dtype(self):
        return jnp.dtype(globals()[self._name])

    def __call__(self, x):
        return jnp.asarray(x, globals()[self._name])

    def __repr__(self):
        return f"config.{self._name}(={self.dtype.name})"

    # Hash/eq follow the CURRENT resolution, not object identity: jax's
    # ``canonicalize_dtype`` memoizes on the dtype argument, and with
    # id-based hashing the first profile to resolve a handle poisoned
    # every later trace under the other profile (f64 clocks inside an
    # f32 trace — the cross-profile branch-dtype mismatches this fixes).
    def __hash__(self):
        return hash(self.dtype)

    def __eq__(self, other):
        if isinstance(other, _DtypeHandle):
            return self.dtype == other.dtype
        try:
            return self.dtype == jnp.dtype(other)
        except TypeError:
            return NotImplemented


TIME = _DtypeHandle("TIME_DTYPE")
REAL = _DtypeHandle("REAL_DTYPE")
COUNT = _DtypeHandle("COUNT_DTYPE")

_PROFILES = {
    # exact profile: matches the scalar oracle bit-for-bit; default.
    "f64": dict(TIME_DTYPE=jnp.float64, REAL_DTYPE=jnp.float64,
                COUNT_DTYPE=jnp.int64),
    # TPU-kernel profile: every array Mosaic-representable (no 64-bit
    # types).  Clock resolution is f32 (documented envelope: fine for runs
    # with t_end * eps32 well below the smallest meaningful interval);
    # statistics accumulate in f32.
    "f32": dict(TIME_DTYPE=jnp.float32, REAL_DTYPE=jnp.float32,
                COUNT_DTYPE=jnp.int32),
}

_ACTIVE_PROFILE = "f64"

# --- CIMBA_* environment knob registry (docs/19_static_analysis.md) ---------
#
# Every environment variable the PACKAGE reads is declared here and read
# through :func:`env_raw` — the round-trip rule CHK005 in tools/check.py
# enforces statically.  ``trace_gate=True`` marks knobs that change what
# a traced program looks like; each of those must be claimed by a gate
# in :mod:`cimba_tpu.check.gates`, whose registry sweep proves the
# off-state is jaxpr-identical to the baseline (tests/test_check.py has
# the completeness test).  Operator-tool knobs (CIMBA_BENCH_*, sweep
# probes, examples) stay outside: they configure host scripts, never
# library trace state.

ENV_KNOBS = {
    # trace-time program gates (registry-swept in check/gates.py)
    "CIMBA_EVENTSET_HIER": dict(
        default="1", trace_gate=True,
        doc="hierarchical event-set minima (core/eventset.py)",
    ),
    "CIMBA_EVENTSET_BLOCK": dict(
        default="128", trace_gate=True,
        doc="event-set block size for the hierarchical minima",
    ),
    "CIMBA_XLA_PACK": dict(
        default="", trace_gate=True,
        doc="packed XLA while-loop carry (core/carry.py)",
    ),
    "CIMBA_AUDIT": dict(
        default="", trace_gate=True,
        doc="determinism audit collection (obs/audit.py; the chunk "
            "program's audit arm is an explicit argument — the env var "
            "only selects host-side collection, pinned ambient-inert)",
    ),
    "CIMBA_TUNE": dict(
        default="1", trace_gate=True,
        doc="tuned-schedule resolution (tune/registry.py): =0 opts "
            "every entry point out of resolving searched dispatch "
            "schedules from the program store — programs are then "
            "jaxpr-identical to the hand-frozen defaults (the 'tune' "
            "gate in check/gates.py pins this); explicit kwargs "
            "always win either way (docs/21_autotune.md)",
    ),
    "CIMBA_REFILL": dict(
        default="", trace_gate=True,
        doc="continuous wave refill (docs/22_refill.md): =1 makes "
            "Service(refill=None) recycle dead lanes at chunk "
            "boundaries — retire a finished request's lanes early and "
            "splice queued compatible requests into them.  Purely a "
            "HOST-side dispatch policy: the chunk program is untouched "
            "(the 'refill' gate in check/gates.py pins ambient "
            "inertness), and the refill/liveness programs are separate "
            "compiles keyed by the same compatibility class",
    ),
    "CIMBA_TABLE_SCAN": dict(
        default="", trace_gate=True,
        doc="scan-over-rows process-table dispatch (core/dyn.py, "
            "docs/25_compile_wall.md): =1 replaces the dense one-hot "
            "expand/select over full [P, ...] component tables with a "
            "counted loop over fixed-size row blocks, so emitted "
            "program text references one block regardless of P.  Off "
            "(the default) is jaxpr character-identical to the dense "
            "dispatch; on is bitwise result-identical (same one-hot "
            "pick within the owning block).  Only engages on axes "
            "strictly taller than the block size — structurally inert "
            "for small-P models",
    ),
    "CIMBA_TABLE_SCAN_BLOCK": dict(
        default="128", trace_gate=True,
        doc="row-block height for the scan-over-rows table dispatch "
            "(sublane-friendly multiple; axes <= the block stay dense)",
    ),
    "CIMBA_WAVE_FUSE": dict(
        default="", trace_gate=True,
        doc="cross-spec wave fusion (docs/26_wave_fusion.md): =1 makes "
            "Service(fuse=None) pack compatible-shape DIFFERENT specs "
            "into one fused wave whose init/refill lax.switch each "
            "lane through its own member's model on a per-lane "
            "spec-id column.  Off (the default) every wave stays "
            "single-class and traces the character-identical "
            "historical programs (the 'wave_fuse' gate in "
            "check/gates.py pins ambient inertness); on, a member "
            "lane's trajectory is bitwise its solo per-spec wave's "
            "(core/fuse.py has the argument)",
    ),
    "CIMBA_QOS": dict(
        default="", trace_gate=True,
        doc="multi-tenant QoS plane (docs/27_qos.md): =1 makes "
            "Service(qos=None) apportion freed refill lanes across "
            "tenants by deficit-weighted round robin, order equal-"
            "priority requests within a class by earliest deadline "
            "(EDF), and enforce per-tenant quotas/rate limits at "
            "submit with structured RetryAfter backpressure.  Purely "
            "a HOST-side admission policy: the tenant id never joins "
            "the program/compatibility class key and the chunk "
            "program is untouched (the 'qos' gate in check/gates.py "
            "pins ambient inertness); delivered results stay bitwise "
            "their direct solo calls regardless of admission order",
    ),
    "CIMBA_DEVICE_SCHED": dict(
        default="", trace_gate=True,
        doc="preemptive device scheduler "
            "(docs/24_device_scheduler.md): =1 makes "
            "Service(device_sched=None) run concurrent refill waves "
            "per device with memory-aware admission and checkpoint-"
            "evict-restore preemption of lower-priority waves.  Purely "
            "a HOST-side dispatch policy: the chunk program is "
            "untouched (the 'device_sched' gate in check/gates.py pins "
            "ambient inertness); checkpoints ride the PR 3 resumable "
            "path, so a preempted wave restores bit-identically",
    ),
    # kernel-path knobs: Mosaic programs, covered by the dedicated
    # kernel parity batteries (test_mosaic_aot / test_pallas_run), not
    # the XLA-path gate sweep (interpret-mode tracing is over tier-1
    # budget)
    "CIMBA_KERNEL_PACK": dict(
        default="0", trace_gate=False,
        doc="packed carry inside the Pallas mega-kernel",
    ),
    "CIMBA_KERNEL_LANE_BLOCK": dict(
        default="", trace_gate=False,
        doc="Pallas lane-block grid size (core/pallas_run.py)",
    ),
    "CIMBA_KERNEL_VMEM_LIMIT": dict(
        default="", trace_gate=False,
        doc="Mosaic scoped-vmem budget override, bytes",
    ),
    "CIMBA_KERNEL_DEBUG": dict(
        default="", trace_gate=False,
        doc="dump 64-bit-typed jaxpr values before Mosaic lowering",
    ),
    # host-side state (no traced-program effect)
    "CIMBA_PROGRAM_CACHE_CAP": dict(
        default="64", trace_gate=False,
        doc="bounded program-cache capacity (serve/cache.py)",
    ),
    "CIMBA_PROGRAM_STORE": dict(
        default="", trace_gate=False,
        doc="persistent AOT program store root (serve/store.py)",
    ),
    "CIMBA_PROGRAM_STORE_XLA_MIN_S": dict(
        default="0", trace_gate=False,
        doc="min compile seconds for jax's persistent cache entries",
    ),
    # fleet plane (docs/20_fleet.md): host-side process topology and
    # fault injection — no traced-program effect
    "CIMBA_FLEET_CHAOS": dict(
        default="", trace_gate=False,
        doc="fleet fault injection (fleet/chaos.py): comma-separated "
            "k=v knobs — seed=<u64>, drop=<k> (drop first-attempt wire "
            "responses deterministically by fmix64(seed, slice, "
            "request id)), kill=<n> (SIGKILL the slice after n served "
            "requests), scrape_delay_ms=<ms> (stall /healthz + "
            "/metrics responses)",
    ),
    "CIMBA_FLEET_DIST": dict(
        default="", trace_gate=False,
        doc="opt-in jax.distributed multi-controller init at slice "
            "startup (fleet/dist.py): coordinator_address,"
            "num_processes,process_id — off (the default) never "
            "touches jax.distributed",
    ),
    "CIMBA_FLEET_TELEMETRY": dict(
        default="", trace_gate=False,
        doc="fleet trace plane (docs/23_fleet_observability.md): a "
            "DIRECTORY path makes every slice process attach a "
            "Telemetry plane and write its span JSONL to "
            "<dir>/<slice>.spans.jsonl, adopting the trace context "
            "carried by run headers so slice span trees graft under "
            "the router's wire spans; empty (the default) = no slice "
            "telemetry, zero cost — a host-side observability knob "
            "with no traced-program effect",
    ),
    "CIMBA_FLEET_CAPACITY": dict(
        default="1", trace_gate=False,
        doc="capacity-aware fleet placement "
            "(docs/23_fleet_observability.md): on (the default), the "
            "router ranks candidate slices by scraped free-lane "
            "headroom whenever EVERY candidate reports the refill "
            "capacity signal, falling back to least-loaded otherwise; "
            "=0 pins least-loaded placement.  Host-side policy only — "
            "results are bitwise identical either way",
    ),
    # assertion tiers: compile-out is the FEATURE (utils/dbc.py); the
    # gated-handler invariant battery (test_gated_invariant.py) owns
    # their correctness, so they are not registry gates
    "CIMBA_NDEBUG": dict(
        default="0", trace_gate=False,
        doc="disable the heavyweight debug assertion tier",
    ),
    "CIMBA_NASSERT": dict(
        default="0", trace_gate=False,
        doc="disable the release assertion tier too",
    ),
}


def env_raw(name: str, default=None) -> str:
    """Read one registered ``CIMBA_*`` environment knob (the CHK005
    round-trip point: package code reads env through here, never
    ``os.environ`` directly, so :data:`ENV_KNOBS` can never drift from
    what the package actually consults).  ``default=None`` uses the
    registered default; an unregistered name raises — register the knob
    (and, for a trace gate, its identity gate in check/gates.py)
    first."""
    import os

    knob = ENV_KNOBS.get(name)
    if knob is None:
        raise KeyError(
            f"{name} is not a registered CIMBA_* environment knob — add "
            "it to cimba_tpu.config.ENV_KNOBS (and check/gates.py if it "
            "gates trace-time program structure); see "
            "docs/19_static_analysis.md"
        )
    if default is None:
        default = knob["default"]
    return os.environ.get(name, default)

#: True while tracing inside the Pallas mega-kernel (set by
#: core.pallas_run).  Data-dependent while-loops in the interpreter become
#: masked bounded fori-loops under this flag: Mosaic cannot lower a
#: batched (vector) loop condition.
KERNEL_MODE = False

# --- dispatch-cost levers (docs/11_dispatch_cost.md) -------------------------
#
# Both tri-state: ``None`` defers to the environment variable (and its
# default), ``True``/``False`` override it programmatically — bench.py
# flips these to measure the packed+hierarchical and flat arms in one
# process.  Both bind at TRACE time (like the dtype profile): arrays and
# jaxprs already built keep their layout.

#: Hierarchical (two-level tournament) event-set minima.  ``None`` ->
#: ``CIMBA_EVENTSET_HIER`` (default on — structurally inert unless the
#: event capacity is a >= 2x multiple of the block size, which no
#: shipped model's is); ``False`` is the flat-scan oracle.
EVENTSET_HIER = None

#: Event-set block size for the hierarchical minima.  ``None`` ->
#: ``CIMBA_EVENTSET_BLOCK`` (default 128, a lane-friendly multiple).
EVENTSET_BLOCK = None

#: Packed XLA while-loop carry (core/carry.py).  ``None`` ->
#: ``CIMBA_XLA_PACK``; unset environment auto-selects: packed on
#: accelerator backends (where the per-leaf carry cost is measured),
#: per-leaf on CPU (today's jaxpr).  ``CIMBA_XLA_PACK=0`` / ``False``
#: always reproduces the current per-leaf jaxpr bitwise.
XLA_PACK = None


#: Scan-over-rows table dispatch (core/dyn.py).  ``None`` ->
#: ``CIMBA_TABLE_SCAN`` (default off — dense one-hot dispatch, today's
#: jaxpr character-identical); ``True`` blocks every table access whose
#: row axis is taller than :func:`table_scan_block`.
TABLE_SCAN = None

#: Row-block height for the scan-over-rows dispatch.  ``None`` ->
#: ``CIMBA_TABLE_SCAN_BLOCK`` (default 128).
TABLE_SCAN_BLOCK = None


def table_scan_enabled() -> bool:
    if TABLE_SCAN is not None:
        return bool(TABLE_SCAN)
    raw = env_raw("CIMBA_TABLE_SCAN").strip()
    return bool(raw) and raw != "0"


def table_scan_block() -> int:
    if TABLE_SCAN_BLOCK is not None:
        return int(TABLE_SCAN_BLOCK)
    return int(env_raw("CIMBA_TABLE_SCAN_BLOCK"))


def eventset_hier_enabled() -> bool:
    if EVENTSET_HIER is not None:
        return bool(EVENTSET_HIER)
    return env_raw("CIMBA_EVENTSET_HIER") != "0"


def eventset_block() -> int:
    if EVENTSET_BLOCK is not None:
        return int(EVENTSET_BLOCK)
    return int(env_raw("CIMBA_EVENTSET_BLOCK"))


def xla_pack_enabled() -> bool:
    if XLA_PACK is not None:
        return bool(XLA_PACK)
    raw = env_raw("CIMBA_XLA_PACK").strip()
    if raw:
        return raw != "0"
    # auto: the wide-carry cost this packs away is the accelerator
    # while-loop's (BENCH_NOTES round 5 floor probes); CPU keeps the
    # per-leaf carry it has always run
    return jax.default_backend() != "cpu"


def active_profile() -> str:
    return _ACTIVE_PROFILE


def use_profile(name: str) -> None:
    """Switch the trace-time dtype profile ("f64" exact / "f32" kernel).

    Affects subsequent *tracing* only; arrays already built keep their
    dtypes.  Model builds and runs under different profiles coexist in one
    process (specs carry no dtypes; all arrays are created at trace time).
    """
    global _ACTIVE_PROFILE
    if name not in _PROFILES:
        raise ValueError(f"unknown profile {name!r}; one of {sorted(_PROFILES)}")
    globals().update(_PROFILES[name])
    _ACTIVE_PROFILE = name


@contextlib.contextmanager
def profile(name: str):
    """Scoped :func:`use_profile` (restores the previous profile on exit)."""
    prev = _ACTIVE_PROFILE
    use_profile(name)
    try:
        yield
    finally:
        use_profile(prev)


def x64_scope(enable: bool):
    """``jax.enable_x64(enable)`` across jax versions (older releases only
    ship the context manager under ``jax.experimental``)."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(enable)
    from jax.experimental import enable_x64 as _enable_x64

    return _enable_x64(enable)


def setup() -> None:
    """Enable the JAX global flags cimba-tpu requires (idempotent)."""
    jax.config.update("jax_enable_x64", True)


setup()
