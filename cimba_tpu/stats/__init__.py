"""cimba-tpu statistics subsystem.

Parity with the reference's L0 statistics components (SURVEY.md §2 #21-24):
``cmb_datasummary`` / ``cmb_wtdsummary`` -> :mod:`cimba_tpu.stats.summary`
(one weighted-merge implementation serves both), ``cmb_dataset`` ->
:mod:`cimba_tpu.stats.dataset`, ``cmb_timeseries`` ->
:mod:`cimba_tpu.stats.timeseries` (plus the streaming StepAccum used by the
jitted event loop).
"""

from cimba_tpu.stats import dataset, summary, timeseries
from cimba_tpu.stats.summary import (
    Summary,
    add,
    empty,
    halfwidth,
    kurtosis,
    mean,
    merge,
    merge_tree,
    skewness,
    stddev,
    t_quantile,
    variance,
)
from cimba_tpu.stats.timeseries import (
    StepAccum,
    step_create,
    step_finalize,
    step_record,
)

__all__ = [name for name in dir() if not name.startswith("_")]
