"""Streaming moment summaries with associative merge.

Reference parity: ``cmb_datasummary`` (`src/cmb_datasummary.c:77-166`) and
``cmb_wtdsummary`` (`src/cmb_wtdsummary.c:83-195`) — one-pass streaming
count/min/max/M1..M4 with Pébay's pairwise merge, which the reference uses
to combine per-pthread results and this framework uses to combine
per-replication results across lanes and chips.

Design notes (TPU-first):

* One implementation serves both: the unweighted summary is the weighted
  one with unit weights.  A single sample is a degenerate summary
  ``(w, x, 0, 0, 0)``, so ``add`` is ``merge`` with a singleton — the Pébay
  weighted-merge formulas (2008 for counts, 2016 for weights) are the only
  moment math in the framework.
* Central-moment accumulation (not raw power sums) so within-replication
  streams stay numerically stable even when mean >> stddev.
* ``merge`` is associative and commutative up to float rounding.  Across
  lanes use :func:`merge_tree` (binary reduction, log2 steps under jit);
  across devices ``all_gather`` the tiny summaries and fold — ``psum``
  only sums, and moment merging is not a plain sum.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from cimba_tpu import config

_R = config.REAL


class Summary(NamedTuple):
    """Moment summary — weighted (``w`` = total weight) or unweighted
    (``w`` = count); ``n`` tracks the number of samples in either case."""

    n: jnp.ndarray      # sample count (f64 for pytree homogeneity)
    w: jnp.ndarray      # total weight (== n for unweighted use)
    mn: jnp.ndarray     # min sample value
    mx: jnp.ndarray     # max sample value
    m1: jnp.ndarray     # weighted mean
    m2: jnp.ndarray     # sum of w * (x - m1)^2
    m3: jnp.ndarray     # sum of w * (x - m1)^3
    m4: jnp.ndarray     # sum of w * (x - m1)^4


def empty() -> Summary:
    z = jnp.zeros((), _R)
    return Summary(z, z, jnp.asarray(jnp.inf, _R), jnp.asarray(-jnp.inf, _R), z, z, z, z)


def merge(a: Summary, b: Summary) -> Summary:
    """Pébay pairwise merge; exact for empty operands."""
    w = a.w + b.w
    # Guard the empty-side divisions; jnp.where keeps it branch-free.
    safe_w = jnp.where(w > 0.0, w, _R(1.0))
    d = b.m1 - a.m1
    frac_b = b.w / safe_w
    m1 = a.m1 + d * frac_b
    wa_wb = a.w * b.w
    m2 = a.m2 + b.m2 + d * d * wa_wb / safe_w
    m3 = (
        a.m3
        + b.m3
        + d**3 * wa_wb * (a.w - b.w) / safe_w**2
        + 3.0 * d * (a.w * b.m2 - b.w * a.m2) / safe_w
    )
    m4 = (
        a.m4
        + b.m4
        + d**4 * wa_wb * (a.w * a.w - wa_wb + b.w * b.w) / safe_w**3
        + 6.0 * d * d * (a.w * a.w * b.m2 + b.w * b.w * a.m2) / safe_w**2
        + 4.0 * d * (a.w * b.m3 - b.w * a.m3) / safe_w
    )
    # An empty side must not perturb the other (d may involve junk m1=0).
    take_a = b.w == 0.0
    take_b = a.w == 0.0
    pick = lambda ma, mb, mm: jnp.where(take_a, ma, jnp.where(take_b, mb, mm))
    return Summary(
        n=a.n + b.n,
        w=w,
        mn=jnp.minimum(a.mn, b.mn),
        mx=jnp.maximum(a.mx, b.mx),
        m1=pick(a.m1, b.m1, m1),
        m2=pick(a.m2, b.m2, m2),
        m3=pick(a.m3, b.m3, m3),
        m4=pick(a.m4, b.m4, m4),
    )


def add(s: Summary, x, weight=1.0) -> Summary:
    """Add one (weighted) sample: merge with a singleton summary."""
    x = jnp.asarray(x, _R)
    w = jnp.asarray(weight, _R)
    z = jnp.zeros((), _R)
    single = Summary(jnp.asarray(1.0, _R), w, x, x, x, z, z, z)
    return merge(s, single)


def merge_tree(summaries: Summary) -> Summary:
    """Reduce a batched Summary (leading axis R) to one via binary tree.

    R need not be a power of two; odd tails fold into element 0.  Runs in
    log2(R) vectorized merge steps under jit — the TPU analog of the
    reference merging per-thread summaries on the main thread.
    """
    import jax

    r = jax.tree.leaves(summaries)[0].shape[0]
    while r > 1:
        half = r // 2
        lo = jax.tree.map(lambda x: x[:half], summaries)
        hi = jax.tree.map(lambda x: x[half : 2 * half], summaries)
        merged = jax.vmap(merge)(lo, hi)
        if r % 2:
            odd = jax.tree.map(lambda x: x[r - 1], summaries)
            first = jax.tree.map(lambda x: x[0], merged)
            folded = merge(first, odd)
            merged = jax.tree.map(
                lambda m, f: m.at[0].set(f), merged, folded
            )
        summaries = merged
        r = half
    return jax.tree.map(lambda x: x[0], summaries)


# --- derived statistics (parity: cmb_datasummary_* accessors) ---------------


def mean(s: Summary):
    return s.m1


def variance(s: Summary):
    """Sample variance with frequency weights: m2 / (w - 1)."""
    return s.m2 / jnp.maximum(s.w - 1.0, 1e-300)


def pop_variance(s: Summary):
    return s.m2 / jnp.maximum(s.w, 1e-300)


def stddev(s: Summary):
    return jnp.sqrt(variance(s))


def skewness(s: Summary):
    """Population skewness g1 = (m3/w) / (m2/w)^1.5."""
    w = jnp.maximum(s.w, 1e-300)
    return (s.m3 / w) / jnp.maximum((s.m2 / w) ** 1.5, 1e-300)


def kurtosis(s: Summary):
    """Population kurtosis g2 = (m4/w) / (m2/w)^2 (3.0 for a normal)."""
    w = jnp.maximum(s.w, 1e-300)
    return (s.m4 / w) / jnp.maximum((s.m2 / w) ** 2, 1e-300)


def t_quantile(p, dof):
    """Student-t quantile t_{p, dof} via the Cornish–Fisher expansion
    around the normal quantile (Abramowitz & Stegun 26.7.5, four
    correction terms).  Branch-free and jit/vmap-friendly — the sweep
    engine evaluates it over a whole grid of cells per stopping round.

    Accuracy: converges to the normal quantile as ``dof`` grows (the
    corrections decay as 1/dof), and is within ~1e-4 of the true
    quantile for ``dof >= 4`` at the usual confidences; at ``dof`` of
    2-3 the error is a few tenths of a percent, and ``dof < 2`` (only
    reachable from a 2-sample summary) is conservative-to-loose by
    design — a stopping rule should not be trusting 2 samples anyway
    (see :class:`cimba_tpu.sweep.HalfwidthTarget`'s ``min_reps``).
    """
    from jax.scipy.special import ndtri

    z = ndtri(jnp.asarray(p, _R))
    v = jnp.maximum(jnp.asarray(dof, _R), 1.0)
    z2 = z * z
    g1 = (z2 + 1.0) * z / 4.0
    g2 = ((5.0 * z2 + 16.0) * z2 + 3.0) * z / 96.0
    g3 = (((3.0 * z2 + 19.0) * z2 + 17.0) * z2 - 15.0) * z / 384.0
    g4 = (
        ((((79.0 * z2 + 776.0) * z2 + 1482.0) * z2 - 1920.0) * z2 - 945.0)
        * z / 92160.0
    )
    return z + (g1 + (g2 + (g3 + g4 / v) / v) / v) / v


def halfwidth(s: Summary, confidence: float = 0.95):
    """Confidence-interval halfwidth of the mean:
    ``t_{q, w-1} * sqrt(variance(s) / w)`` with ``q = 1 - (1-c)/2``.

    The ONE definition the sweep engine's stopping rule
    (:class:`cimba_tpu.sweep.HalfwidthTarget`) and result reports
    share, so "the cell converged" means the same thing in both.  Uses
    the t-quantile at ``w - 1`` degrees of freedom for small summaries
    and flows into the normal quantile as ``w`` grows (the
    :func:`t_quantile` corrections decay as ``1/dof``).  A summary
    with fewer than two samples has no variance estimate: returns
    ``+inf`` (never "converged"), not a misleading 0.
    """
    if not 0.0 < confidence < 1.0:
        raise ValueError(
            f"confidence must be in (0, 1), got {confidence}"
        )
    q = 1.0 - (1.0 - confidence) / 2.0
    hw = t_quantile(q, s.w - 1.0) * jnp.sqrt(
        variance(s) / jnp.maximum(s.w, 1e-300)
    )
    return jnp.where(s.w >= 2.0, hw, jnp.asarray(jnp.inf, _R))
