"""cimba-check: the static verification plane (docs/19_static_analysis.md).

Every hard bug in PRs 1-9 was an instance of a mechanically checkable
class: tracing-cache leakage across dtype profiles, ``id()`` semantics
leaking into persisted keys, torn reads in the threaded serving layer,
and gated features whose off state must stay jaxpr-identical.  This
package shifts those checks left of pytest — they run before anything
executes:

* :mod:`cimba_tpu.check.astlint` — stdlib-``ast`` lints over the repo's
  own source (no jax import): CHK001 persisted ``id()``, CHK002 lock
  discipline against declared must-hold maps, CHK003 blind exception
  swallows, CHK004 wall-clock/RNG in digest content paths, CHK005
  un-proxied ``CIMBA_*`` environment reads.
* :mod:`cimba_tpu.check.jaxprlint` — program-level lints over traced
  jaxprs (static with respect to execution): JXL001 donation coverage
  of chunk-program carries, JXL002 hot-path purity (no callbacks, no
  gathers), JXL003 weak-type hygiene of the packed carry.
* :mod:`cimba_tpu.check.gates` — the trace-time feature-gate registry:
  every gate (trace, metrics, audit, pack, hier eventset) registers
  once and the sweep auto-generates its off == baseline jaxpr-identity
  check under both dtype profiles, replacing N hand-written pins.

``tools/check.py`` is the CLI (exit 0 clean / 1 findings / 2 error,
``--json``, per-rule suppression via ``# cimba: noqa(RULE)``).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

__all__ = ["Finding", "JSON_VERSION", "findings_to_json"]

#: --json schema version (bump on incompatible layout changes)
JSON_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    """One checker finding: a rule firing at a source coordinate."""

    rule: str              # "CHK001".."CHK005", "JXL001".."JXL003", "GATE"
    path: str              # repo-relative where possible
    line: int              # 1-based; 0 = whole-file / program-level
    message: str
    suppressed: bool = False   # a `# cimba: noqa(RULE)` hit this line

    def format(self) -> str:
        sup = "  [suppressed]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{sup}"


def findings_to_json(
    findings: List[Finding],
    suppressed: List[Finding],
    *,
    checked_files: int,
    program_checks: Optional[dict] = None,
) -> dict:
    """The ``--json`` report body (schema :data:`JSON_VERSION`)."""

    def rec(f: Finding) -> dict:
        return {
            "rule": f.rule, "path": f.path, "line": f.line,
            "message": f.message,
        }

    counts: dict = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    out = {
        "version": JSON_VERSION,
        "status": "clean" if not findings else "findings",
        "checked_files": checked_files,
        "counts": counts,
        "findings": [rec(f) for f in findings],
        "suppressed": [rec(f) for f in suppressed],
    }
    if program_checks is not None:
        out["program_checks"] = program_checks
    return out
