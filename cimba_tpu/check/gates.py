"""The trace-time feature-gate registry and its identity sweep.

Every trace-time feature gate this repo ships — the flight recorder
(:mod:`obs.trace`), the metrics registry (:mod:`obs.metrics`), the
determinism-audit chunk arm (:mod:`obs.audit` via ``make_chunk``), the
packed XLA carry (``CIMBA_XLA_PACK``), and the hierarchical event set
(``CIMBA_EVENTSET_HIER``/``_BLOCK``) — carries the same contract: its
OFF state must trace a program jaxpr-identical to the baseline, under
both dtype profiles, and ambient environment state must never leak into
a traced program except through the gate's documented resolution point.

Historically each gate pinned that contract with its own hand-written
test (test_trace / test_xla_pack / test_audit), which a NEW gate could
simply forget.  This registry inverts the burden: a gate registers once
as a :class:`Gate` and :func:`sweep` auto-generates its identity checks;
the completeness test in tests/test_check.py fails if a trace-gate env
knob exists in ``config.ENV_KNOBS`` but no gate here claims it — so
forgetting is now a test failure, not a latent soundness hole.

Checks per gate, per dtype profile (``f64`` and ``f32``):

1. **off == baseline** — the program with the gate explicitly OFF is
   character-identical to the default-state program (or, for gates
   whose default resolves ON on this backend, to the explicit-ON one).
2. **ambient inertness** — for gates whose env knob must NOT bind at
   trace time (``ambient_env``): the default program with the env var
   set is still the OFF program.
3. **env off-state** — for gates whose env knob IS the resolution point
   (``off_env``): the env-disabled default reproduces the OFF program.
4. **the knob is live** — the explicit-ON program differs (skipped for
   structurally-inert gates like the hierarchical event set at shipped
   model capacities).

The sweep traces jaxprs only (``jax.make_jaxpr`` — nothing compiles or
executes), restores every global it touches, and memoizes identical
arms so the whole registry costs a handful of small mm1 traces.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Callable, Dict, Optional, Tuple

from cimba_tpu.check import Finding

__all__ = ["Gate", "GATES", "sweep", "claimed_env_knobs", "PROFILES"]

PROFILES = ("f64", "f32")


# -- gate state context managers ---------------------------------------------


@contextlib.contextmanager
def _trace_state(enabled: bool, capacity: int = 16):
    from cimba_tpu.obs import trace as ot

    prev_enabled, prev_cap = ot.enabled(), ot.capacity()
    try:
        if enabled:
            ot.enable(capacity)
        else:
            # a full enable/disable CYCLE, not a no-op: the off arm
            # proves no sticky state (capacity, partial enables)
            # survives into later traces
            ot.enable(capacity)
            ot.disable()
        yield
    finally:
        if prev_enabled:
            ot.enable(prev_cap)
        else:
            ot.disable()


@contextlib.contextmanager
def _metrics_state(enabled: bool):
    from cimba_tpu.obs import metrics as om

    prev = om.enabled()
    try:
        if enabled:
            om.enable()
        else:
            om.enable()
            om.disable()
        yield
    finally:
        om.enable() if prev else om.disable()


@contextlib.contextmanager
def _hier_state(hier: Optional[bool], block: Optional[int] = None):
    from cimba_tpu import config

    prev_h, prev_b = config.EVENTSET_HIER, config.EVENTSET_BLOCK
    try:
        config.EVENTSET_HIER = hier
        if block is not None:
            config.EVENTSET_BLOCK = block
        yield
    finally:
        config.EVENTSET_HIER = prev_h
        config.EVENTSET_BLOCK = prev_b


@contextlib.contextmanager
def _noop_state():
    yield


@contextlib.contextmanager
def _table_scan_state(scan: Optional[bool], block: Optional[int] = None):
    from cimba_tpu import config

    prev_s, prev_b = config.TABLE_SCAN, config.TABLE_SCAN_BLOCK
    try:
        config.TABLE_SCAN = scan
        if block is not None:
            config.TABLE_SCAN_BLOCK = block
        yield
    finally:
        config.TABLE_SCAN = prev_s
        config.TABLE_SCAN_BLOCK = prev_b


@contextlib.contextmanager
def _tune_state(on: bool):
    """The tune gate's arms: a resolved :class:`~cimba_tpu.tune.space.
    Schedule` binds through its ``scope()`` (the config tri-states) —
    the ON arm applies a schedule whose knob provably changes the
    traced program (the pack arm OPPOSITE to this backend's default),
    the OFF arm applies the empty default schedule (which must be the
    baseline)."""
    from cimba_tpu.tune.space import Schedule

    sched = Schedule(pack=_pack_default_is_off()) if on else Schedule()
    with sched.scope():
        yield


# -- the registry -------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Gate:
    """One registered trace-time feature gate.

    ``program`` picks the traced program ("run" = ``make_run`` on an
    mm1 Sim, "chunk" = ``make_chunk`` on a vmapped wave).  The OFF/ON
    arms are forced either through builder kwargs (explicit program
    arguments like ``pack=``/``audit=``) or a state context manager
    (module globals like the recorder's enable flag); ``extra_arms``
    are additional named states that must ALSO trace the off program
    (e.g. a different hierarchical block size below the inertness
    threshold)."""

    name: str
    env: Tuple[str, ...]          # ENV_KNOBS names this gate claims
    program: str                  # "run" | "chunk"
    off_kwargs: dict = dataclasses.field(default_factory=dict)
    on_kwargs: Optional[dict] = None
    off_ctx: Callable = _noop_state
    on_ctx: Optional[Callable] = None
    ambient_env: dict = dataclasses.field(default_factory=dict)
    off_env: dict = dataclasses.field(default_factory=dict)
    on_differs: bool = True
    #: None = default always resolves OFF; else a predicate (the packed
    #: carry defaults ON on accelerator backends)
    default_is_off: Optional[Callable[[], bool]] = None


def _pack_default_is_off() -> bool:
    import jax

    return jax.default_backend() == "cpu"


GATES: Tuple[Gate, ...] = (
    Gate(
        name="trace",
        env=(),
        program="run",
        off_ctx=lambda: _trace_state(False),
        on_ctx=lambda: _trace_state(True),
    ),
    Gate(
        name="metrics",
        env=(),
        program="run",
        off_ctx=lambda: _metrics_state(False),
        on_ctx=lambda: _metrics_state(True),
    ),
    Gate(
        name="pack",
        env=("CIMBA_XLA_PACK",),
        program="run",
        off_kwargs={"pack": False},
        on_kwargs={"pack": True},
        off_env={"CIMBA_XLA_PACK": "0"},
        default_is_off=_pack_default_is_off,
    ),
    Gate(
        name="eventset_hier",
        env=("CIMBA_EVENTSET_HIER", "CIMBA_EVENTSET_BLOCK"),
        program="run",
        off_ctx=lambda: _hier_state(False),
        on_ctx=lambda: _hier_state(True),
        off_env={"CIMBA_EVENTSET_HIER": "0"},
        # structurally inert below the 2x-block capacity threshold —
        # which every shipped model is; the ON arm must therefore trace
        # the SAME program (that inertness is itself the pinned claim)
        on_differs=False,
    ),
    Gate(
        name="table_scan",
        env=("CIMBA_TABLE_SCAN", "CIMBA_TABLE_SCAN_BLOCK"),
        program="run",
        off_ctx=lambda: _table_scan_state(False),
        on_ctx=lambda: _table_scan_state(True),
        off_env={"CIMBA_TABLE_SCAN": "0"},
        # the scan-over-rows dispatch only engages on table axes
        # STRICTLY taller than the block (docs/25_compile_wall.md) —
        # every sweep-model axis is <= the default block, so the ON
        # program must equal the OFF one (that small-P structural
        # inertness is itself the pinned claim; knob liveness at tall-P
        # is pinned in tests/test_table_scan.py where the model height
        # is controlled).  The ambient arm rides the same inertness:
        # the env knob DOES bind at trace time, but at sweep-model
        # scale it must still trace the baseline program.
        ambient_env={"CIMBA_TABLE_SCAN": "1"},
        on_differs=False,
    ),
    Gate(
        name="audit",
        env=("CIMBA_AUDIT",),
        program="chunk",
        off_kwargs={"audit": False},
        on_kwargs={"audit": True},
        # the audit knob is an explicit program ARGUMENT; the env var
        # only selects host-side collection and must never bind into a
        # traced program (the test_audit pin, generalized)
        ambient_env={"CIMBA_AUDIT": "1"},
    ),
    Gate(
        name="tune",
        env=("CIMBA_TUNE",),
        program="run",
        off_ctx=lambda: _tune_state(False),
        on_ctx=lambda: _tune_state(True),
        # with no tuned entry in reach (the sweep clears CIMBA_* env,
        # so no store resolves), the env knob must be ambient-inert in
        # BOTH states: resolution is a host-side decision that binds
        # programs only through the Schedule scope / explicit kwargs
        # (docs/21_autotune.md); CIMBA_TUNE=0 (tuned-resolution off)
        # must therefore be jaxpr-identical to the default
        ambient_env={"CIMBA_TUNE": "1"},
        off_env={"CIMBA_TUNE": "0"},
    ),
    Gate(
        name="refill",
        env=("CIMBA_REFILL",),
        program="chunk",
        # continuous wave refill (docs/22_refill.md) is a HOST-side
        # dispatch policy: the knob selects lane recycling in the
        # serve dispatcher and must never bind into a traced chunk
        # program — the refilled wave runs the SAME chunk program as
        # the refill-off one (the splice is a separate program).  No
        # ON arm: there is no chunk-program state to flip.
        ambient_env={"CIMBA_REFILL": "1"},
        off_env={"CIMBA_REFILL": "0"},
    ),
    Gate(
        name="device_sched",
        env=("CIMBA_DEVICE_SCHED",),
        program="chunk",
        # the preemptive device scheduler
        # (docs/24_device_scheduler.md) is, like refill, a HOST-side
        # dispatch policy: the knob selects concurrent-wave admission
        # and checkpoint-evict-restore preemption in the serve
        # dispatcher and must never bind into a traced chunk program —
        # a scheduled wave runs the SAME chunk program as the plain
        # one (checkpointing reuses the PR 3 resumable path, outside
        # any trace).  No ON arm: no chunk-program state to flip.
        ambient_env={"CIMBA_DEVICE_SCHED": "1"},
        off_env={"CIMBA_DEVICE_SCHED": "0"},
    ),
    Gate(
        name="qos",
        env=("CIMBA_QOS",),
        program="chunk",
        # the multi-tenant QoS plane (docs/27_qos.md) is, like refill,
        # a HOST-side admission policy: the knob selects weighted-fair
        # lane apportionment / EDF ordering / quota throttling in the
        # serve dispatcher, and the tenant id must never bind into a
        # traced chunk program — a request admitted under QoS runs the
        # SAME chunk program as one admitted in raw priority order
        # (tenant is carried beside trace_context, outside the
        # compatibility class key).  No ON arm: no chunk-program
        # state to flip.
        ambient_env={"CIMBA_QOS": "1"},
        off_env={"CIMBA_QOS": "0"},
    ),
    Gate(
        name="wave_fuse",
        env=("CIMBA_WAVE_FUSE",),
        program="chunk",
        # cross-spec wave fusion (docs/26_wave_fusion.md) is, like
        # refill, a HOST-side packing policy: the knob selects whether
        # the serve dispatcher groups compatible-shape specs into
        # fused waves, and must never bind into a traced chunk
        # program — a single-spec wave runs the SAME chunk program
        # whether fusion is on or off (the fused superprogram is a
        # separate compile on the merged spec, and only forms when a
        # wave actually spans >1 exact class).  No ON arm: no
        # chunk-program state to flip.
        ambient_env={"CIMBA_WAVE_FUSE": "1"},
        off_env={"CIMBA_WAVE_FUSE": "0"},
    ),
)


def claimed_env_knobs() -> set:
    """Every ENV_KNOBS name some registered gate claims — what the
    completeness test checks ``config.ENV_KNOBS``'s trace gates
    against."""
    out: set = set()
    for g in GATES:
        out.update(g.env)
    return out


# -- program builders ---------------------------------------------------------


def _tiny_spec():
    """A minimal 1-process hold/exit model: every gate's code path
    (dispatch site, carry layout, event-set minima, chunk digest) with
    a ~7x cheaper trace than mm1 — the tier-1 sweep model.  Its
    ``event_cap=1`` sits below every hierarchy threshold, which is
    exactly what the eventset gate's inertness arms require."""
    from cimba_tpu.core import api, cmd
    from cimba_tpu.core.model import Model

    m = Model("gatecheck", event_cap=1, guard_cap=2)

    @m.block
    def work(sim, p, sig):
        done = api.clock(sim) > 4.0
        return sim, cmd.select(
            done, cmd.exit_(), cmd.hold(1.0, next_pc=work.pc)
        )

    m.process("w", entry=work)
    return m.build(), ()


def _model_point(model: str):
    if model == "tiny":
        return _tiny_spec()
    if model == "mm1":
        from cimba_tpu.models import mm1

        spec, _ = mm1.build(record=False)
        return spec, mm1.params(10)
    raise ValueError(f"unknown gate-sweep model {model!r}")


def _trace_program(
    profile: str, program: str, kwargs: dict, model: str,
) -> str:
    """One traced jaxpr as text — spec/Sim built INSIDE the profile and
    gate state, since gated leaves (trace ring, metrics registry) ride
    the Sim pytree."""
    import jax
    import jax.numpy as jnp

    from cimba_tpu import config
    from cimba_tpu.core import loop as cl

    with config.profile(profile):
        spec, params = _model_point(model)
        if program == "run":
            sim = cl.init_sim(spec, 1, 0, params)
            return str(jax.make_jaxpr(cl.make_run(spec, **kwargs))(sim))
        if program == "chunk":
            sims = jax.vmap(
                lambda r: cl.init_sim(spec, 3, r, params)
            )(jnp.arange(4))
            return str(
                jax.make_jaxpr(
                    cl.make_chunk(spec, max_steps=8, **kwargs)
                )(sims)
            )
        raise ValueError(f"unknown gate program {program!r}")


@contextlib.contextmanager
def _env(overrides: dict):
    saved = {k: os.environ.get(k) for k in overrides}
    try:
        for k, v in overrides.items():
            os.environ[k] = v
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@contextlib.contextmanager
def _clean_env(names) -> None:
    saved = {k: os.environ.pop(k, None) for k in names}
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v


# -- the sweep ----------------------------------------------------------------


def sweep(profiles=PROFILES, gates=None, model="mm1") -> Tuple[list, dict]:
    """Run every registered gate's identity checks.  ``model`` picks the
    traced spec: ``"mm1"`` (the shipped model every historical pin
    used — the CLI/ci.sh default) or ``"tiny"`` (a minimal model with
    ~7x cheaper traces — what tier-1 sweeps on budget).  Returns
    ``(findings, report)`` — findings carry rule ``"GATE"`` (empty =
    every gate holds); the report maps ``gate/profile`` to the list of
    checks that ran (what the ``--json`` output embeds)."""
    gates = GATES if gates is None else tuple(gates)
    findings: list = []
    report: Dict[str, list] = {}
    memo: Dict[tuple, str] = {}
    all_env = [k for g in gates for k in g.env]

    def build(profile, gate, arm_key, kwargs, ctx_factory, env):
        key = (
            profile, gate.program, tuple(sorted(kwargs.items())),
            arm_key, tuple(sorted(env.items())),
        )
        if key not in memo:
            with _env(env), ctx_factory():
                memo[key] = _trace_program(
                    profile, gate.program, kwargs, model,
                )
        return memo[key]

    with _clean_env(all_env):
        for gate in gates:
            for profile in profiles:
                ran = []
                gid = f"{gate.name}/{profile}"

                def fail(msg):
                    findings.append(Finding(
                        rule="GATE", path=f"gate:{gid}", line=0,
                        message=msg,
                    ))

                baseline = build(
                    profile, gate, "default", {}, _noop_state, {},
                )
                off = build(
                    profile, gate, f"{gate.name}:off", gate.off_kwargs,
                    gate.off_ctx, {},
                )
                on = None
                if gate.on_kwargs is not None or gate.on_ctx is not None:
                    on = build(
                        profile, gate, f"{gate.name}:on",
                        gate.on_kwargs or {},
                        gate.on_ctx or _noop_state, {},
                    )
                if gate.default_is_off is None or gate.default_is_off():
                    ran.append("off==baseline")
                    if off != baseline:
                        fail(
                            "explicit-off program differs from the "
                            "default-state program — the gate's off "
                            "state is not the baseline"
                        )
                elif on is not None:
                    ran.append("on==baseline(default-on backend)")
                    if on != baseline:
                        fail(
                            "default resolves ON on this backend but "
                            "the explicit-on program differs from the "
                            "default program"
                        )
                if gate.ambient_env:
                    ran.append("ambient-inert")
                    ambient = build(
                        profile, gate, "default", {}, _noop_state,
                        gate.ambient_env,
                    )
                    if ambient != off:
                        fail(
                            f"ambient env {gate.ambient_env} leaked "
                            "into the traced default program — the "
                            "knob must stay an explicit argument"
                        )
                if gate.off_env:
                    # CIMBA_<GATE>=0 must reproduce the explicit-off
                    # program on EVERY backend (pack's auto-on default
                    # included: "=0 always reproduces per-leaf")
                    ran.append("env-off==off")
                    env_off = build(
                        profile, gate, "default", {}, _noop_state,
                        gate.off_env,
                    )
                    if env_off != off:
                        fail(
                            f"env off-state {gate.off_env} does not "
                            "reproduce the explicit-off program"
                        )
                if gate.name == "table_scan":
                    # second block size, still above every sweep-model
                    # axis: "axes <= the block stay dense" must hold at
                    # any block, not just the default
                    ran.append("block-inert")
                    blocked = build(
                        profile, gate, "table_scan:block1024", {},
                        lambda: _table_scan_state(True, 1024), {},
                    )
                    if blocked != off:
                        fail(
                            "CIMBA_TABLE_SCAN_BLOCK=1024 changed the "
                            "traced program for a model whose every "
                            "table axis fits one block (small-table "
                            "structural inertness broken)"
                        )
                if gate.name == "eventset_hier":
                    # block-size inertness below the capacity threshold
                    ran.append("block-inert")
                    blocked = build(
                        profile, gate, "hier:block64", {},
                        lambda: _hier_state(True, 64), {},
                    )
                    if blocked != off:
                        fail(
                            "EVENTSET_BLOCK=64 changed the traced "
                            "program for a model below the hierarchy "
                            "capacity threshold (structural inertness "
                            "broken)"
                        )
                if on is not None:
                    if gate.on_differs:
                        ran.append("on-differs")
                        if on == off:
                            fail(
                                "explicit-on program equals the off "
                                "program — the gate knob is dead"
                            )
                    else:
                        ran.append("on-inert")
                        if on != off:
                            fail(
                                "gate declared structurally inert but "
                                "its ON program differs"
                            )
                report[gid] = ran
    return findings, report
