"""AST lints over the repo's own source — stdlib ``ast``, no jax.

Five rules, each fossilizing a bug class this repo has actually hit
(docs/19_static_analysis.md has the rule table with the history):

* **CHK001** — no ``id(...)`` in persistence modules.  ``id()`` keys are
  meaningless across a process boundary; ``UnstableStoreKey`` only fires
  at runtime, this fires at check time.  Scope: files declaring
  ``# cimba-check: persist-path``.
* **CHK002** — lock discipline.  A class declares its must-hold map
  (``# cimba-check: must-hold(_lock) attr, attr...``) and every access
  of a listed attribute outside a lexical ``with self._lock`` block is
  flagged (the torn-read audit of docs/17, made structural).  Methods
  whose name ends ``_locked`` or that carry
  ``# cimba-check: assume-held`` are documented caller-holds-lock.
  Closures defined inside a method are analyzed as NOT holding the lock
  (they run whenever they run).
* **CHK003** — no blind exception swallows: a bare ``except:`` anywhere,
  or an ``except Exception/BaseException:`` whose body is only
  ``pass`` — in a dispatcher or sampler thread that silently eats the
  evidence of the bug that killed it.
* **CHK004** — no wall-clock or RNG in digest/fingerprint content paths
  (functions declaring ``# cimba-check: content-path``): a timestamp or
  random draw inside digested content silently breaks "bitwise
  reproducible is one string equality" (the PR 9 timestamp-exclusion
  rule, generalized).
* **CHK005** — every ``CIMBA_*`` environment read inside the package
  round-trips through ``config.env_raw`` and its ``ENV_KNOBS`` registry
  (so trace gates can't dodge the gate registry).  Scope: files under
  ``cimba_tpu/`` except ``config.py`` itself, plus files declaring
  ``# cimba-check: env-proxied``.

Suppression: a trailing ``# cimba: noqa(RULE)`` (comma-list accepted) on
the flagged line suppresses that rule there; suppressed findings are
still reported in the ``--json`` ``suppressed`` list, never silently
dropped.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

# relative import: tools/check.py --ast-only file-loads this module
# under a private package name so the AST front never imports the
# cimba_tpu package (and therefore never imports jax)
from . import Finding

__all__ = ["RULES", "check_file", "check_paths", "iter_py_files"]

RULES = {
    "CHK001": "id() in a persistence path (persist-path files)",
    "CHK002": "must-hold attribute touched outside its declared lock",
    "CHK003": "bare except, or Exception/BaseException swallowed by pass",
    "CHK004": "wall-clock/RNG call inside a digest content path",
    "CHK005": "CIMBA_* env read bypassing config.env_raw/ENV_KNOBS",
}

_DIRECTIVE = re.compile(r"#\s*cimba-check:\s*(.+?)\s*$")
_NOQA = re.compile(r"#\s*cimba:\s*noqa\(([A-Za-z0-9_,\s]+)\)")
_MUST_HOLD = re.compile(r"must-hold\(([^)]+)\)\s*(.*)$")

#: CHK004 ban list: call segments that mean "this content is no longer
#: a pure function of the run" (time.monotonic included: monotonic
#: origins differ per process, which is exactly the non-reproducibility
#: CHK004 exists to keep out of digests)
_WALLCLOCK_FIRST = {"time"}
_BANNED_SEGMENTS = {"random", "secrets", "uuid", "urandom"}
_DATETIME_TAILS = {"now", "utcnow", "today"}

_SWALLOW_TYPES = {"Exception", "BaseException"}


def _noqa_rules(comment: str) -> Set[str]:
    m = _NOQA.search(comment)
    if not m:
        return set()
    return {r.strip().upper() for r in m.group(1).split(",") if r.strip()}


def _comments_by_line(source: str) -> Dict[int, str]:
    """Real ``#`` comments per line (via tokenize — a directive quoted
    inside a docstring is prose, not a directive)."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(
            io.StringIO(source).readline
        ):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass  # astlint already reports unparseable files
    return out


class _FileCtx:
    """Parsed source + directives of one checked file."""

    def __init__(self, path: str, display: str):
        self.path = path
        self.display = display
        with open(path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=path)
        self.comments = _comments_by_line(self.source)
        self.persist_path = False
        self.env_proxied = False
        self.content_path_lines: Set[int] = set()
        self.assume_held_lines: Set[int] = set()
        self.must_hold: List[Tuple[int, Set[str], Set[str]]] = []
        for i, line in self.comments.items():
            m = _DIRECTIVE.search(line)
            if not m:
                continue
            body = m.group(1)
            if body.startswith("persist-path"):
                self.persist_path = True
            elif body.startswith("env-proxied"):
                self.env_proxied = True
            elif body.startswith("content-path"):
                self.content_path_lines.add(i)
            elif body.startswith("assume-held"):
                self.assume_held_lines.add(i)
            else:
                mh = _MUST_HOLD.match(body)
                if mh:
                    locks = {
                        s.strip() for s in mh.group(1).split(",")
                        if s.strip()
                    }
                    attrs = {
                        s.strip() for s in mh.group(2).split(",")
                        if s.strip()
                    }
                    self.must_hold.append((i, locks, attrs))

    def comment_of(self, lineno: int) -> str:
        return self.comments.get(lineno, "")


class _Findings:
    """Collects findings, routing noqa'd ones to the suppressed list."""

    def __init__(self, ctx: _FileCtx):
        self.ctx = ctx
        self.active: List[Finding] = []
        self.suppressed: List[Finding] = []

    def add(self, rule: str, lineno: int, message: str) -> None:
        sup = rule in _noqa_rules(self.ctx.comment_of(lineno))
        f = Finding(
            rule=rule, path=self.ctx.display, line=lineno,
            message=message, suppressed=sup,
        )
        (self.suppressed if sup else self.active).append(f)


def _dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` as a string, or None for anything fancier."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# CHK001 — id() in persistence paths
# ---------------------------------------------------------------------------


def _chk001(ctx: _FileCtx, out: _Findings) -> None:
    if not ctx.persist_path:
        return
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "id"
        ):
            out.add(
                "CHK001", node.lineno,
                "id() in a persist-path file: object identities are "
                "meaningless across a process boundary — digest by "
                "value, or suppress with a justification if only an "
                "in-process ordinal derived from it is persisted",
            )


# ---------------------------------------------------------------------------
# CHK002 — lock discipline
# ---------------------------------------------------------------------------


def _enclosing_class(
    tree: ast.Module, lineno: int,
) -> Optional[ast.ClassDef]:
    """The innermost ClassDef whose span contains ``lineno``."""
    best: Optional[ast.ClassDef] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                if best is None or node.lineno > best.lineno:
                    best = node
    return best


def _is_assume_held(ctx: _FileCtx, fn: ast.FunctionDef) -> bool:
    if fn.name.endswith("_locked"):
        return True
    first = min(
        [fn.lineno] + [d.lineno for d in fn.decorator_list]
    )
    return bool(
        {first, first - 1, fn.lineno} & ctx.assume_held_lines
    )


class _LockWalker(ast.NodeVisitor):
    """Walk one method body tracking lexical ``with self.<lock>`` depth;
    flag protected ``self.<attr>`` accesses while it is zero."""

    def __init__(self, locks: Set[str], attrs: Set[str],
                 out: _Findings, cls: str, method: str):
        self.locks = locks
        self.attrs = attrs
        self.out = out
        self.cls = cls
        self.method = method
        self.held = 0

    def _is_lock(self, expr: ast.AST) -> bool:
        return (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in self.locks
        )

    def visit_With(self, node: ast.With) -> None:
        locked = any(self._is_lock(i.context_expr) for i in node.items)
        for i in node.items:
            self.visit(i.context_expr)
            if i.optional_vars is not None:
                self.visit(i.optional_vars)
        if locked:
            self.held += 1
        for stmt in node.body:
            self.visit(stmt)
        if locked:
            self.held -= 1

    def _visit_closure(self, node) -> None:
        # a nested def/lambda runs whenever it is later called — the
        # lock held at its definition site proves nothing
        prev, self.held = self.held, 0
        self.generic_visit(node)
        self.held = prev

    def visit_FunctionDef(self, node) -> None:
        self._visit_closure(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_closure(node)

    def visit_Lambda(self, node) -> None:
        self._visit_closure(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            self.held == 0
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.attrs
        ):
            self.out.add(
                "CHK002", node.lineno,
                f"{self.cls}.{node.attr} touched in {self.method}() "
                f"outside `with self.{sorted(self.locks)[0]}` (declared "
                "must-hold)",
            )
        self.generic_visit(node)


def _chk002(ctx: _FileCtx, out: _Findings) -> None:
    for lineno, locks, attrs in ctx.must_hold:
        cls = _enclosing_class(ctx.tree, lineno)
        if cls is None:
            out.add(
                "CHK002", lineno,
                "must-hold directive outside any class body",
            )
            continue
        for item in cls.body:
            if not isinstance(
                item, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            if item.name == "__init__" or _is_assume_held(ctx, item):
                continue
            walker = _LockWalker(locks, attrs, out, cls.name, item.name)
            for stmt in item.body:
                walker.visit(stmt)


# ---------------------------------------------------------------------------
# CHK003 — blind exception swallows
# ---------------------------------------------------------------------------


def _only_pass(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant
        ):
            continue  # docstring / `...`
        return False
    return True


def _handler_names(node: Optional[ast.AST]) -> Set[str]:
    if node is None:
        return set()
    if isinstance(node, ast.Tuple):
        return {
            n for e in node.elts for n in _handler_names(e)
        }
    name = _dotted_name(node)
    return {name.rsplit(".", 1)[-1]} if name else set()


def _chk003(ctx: _FileCtx, out: _Findings) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.add(
                "CHK003", node.lineno,
                "bare `except:` — catches SystemExit/KeyboardInterrupt "
                "and hides the evidence; name the exception",
            )
            continue
        if _handler_names(node.type) & _SWALLOW_TYPES and _only_pass(
            node.body
        ):
            out.add(
                "CHK003", node.lineno,
                "except Exception/BaseException swallowed by `pass` — "
                "in a dispatcher/sampler thread this eats the bug that "
                "killed it; narrow the type, count it, or re-raise",
            )


# ---------------------------------------------------------------------------
# CHK004 — wall-clock / RNG in content paths
# ---------------------------------------------------------------------------


def _banned_call(dotted: str) -> Optional[str]:
    segs = dotted.split(".")
    if segs[0] in _WALLCLOCK_FIRST and len(segs) > 1:
        return "wall-clock"
    if _BANNED_SEGMENTS & set(segs):
        return "RNG/identifier"
    if "datetime" in segs and segs[-1] in _DATETIME_TAILS:
        return "wall-clock"
    return None


def _chk004(ctx: _FileCtx, out: _Findings) -> None:
    if not ctx.content_path_lines:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        first = min(
            [node.lineno] + [d.lineno for d in node.decorator_list]
        )
        if not (
            {first, first - 1, node.lineno} & ctx.content_path_lines
        ):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            dotted = _dotted_name(sub.func)
            if dotted is None:
                continue
            why = _banned_call(dotted)
            if why is not None:
                out.add(
                    "CHK004", sub.lineno,
                    f"{dotted}() is {why} inside content path "
                    f"{node.name}() — digested content must be a pure "
                    "function of the run (timestamps live OUTSIDE the "
                    "digest, like run cards' created_unix)",
                )


# ---------------------------------------------------------------------------
# CHK005 — un-proxied CIMBA_* env reads
# ---------------------------------------------------------------------------


def _module_consts(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "CIMBA_..."`` constants."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
            and node.value.value.startswith("CIMBA_")
        ):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.value
    return out


def _os_aliases(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "os":
                    out.add(a.asname or "os")
    return out


def _chk005_applies(ctx: _FileCtx) -> bool:
    if ctx.env_proxied:
        return True
    norm = ctx.path.replace(os.sep, "/")
    if "/cimba_tpu/" not in norm and not norm.startswith("cimba_tpu/"):
        return False
    return not norm.endswith("cimba_tpu/config.py")


def _chk005(ctx: _FileCtx, out: _Findings) -> None:
    if not _chk005_applies(ctx):
        return
    consts = _module_consts(ctx.tree)
    aliases = _os_aliases(ctx.tree) or {"os"}

    def env_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value if node.value.startswith("CIMBA_") else None
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        return None

    def is_environ(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id in aliases
        )

    for node in ast.walk(ctx.tree):
        name = None
        if isinstance(node, ast.Call):
            dotted_ok = (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("get", "setdefault")
                and is_environ(node.func.value)
            ) or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "getenv"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in aliases
            )
            if dotted_ok and node.args:
                name = env_name(node.args[0])
        elif isinstance(node, ast.Subscript) and is_environ(node.value):
            name = env_name(node.slice)
        if name is not None:
            out.add(
                "CHK005", node.lineno,
                f"{name} read via os.environ — package code reads "
                "CIMBA_* knobs through config.env_raw() so the "
                "ENV_KNOBS registry (and the gate registry behind it) "
                "can never drift from reality",
            )


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

_CHECKS = (_chk001, _chk002, _chk003, _chk004, _chk005)


def check_file(
    path: str, repo_root: Optional[str] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Run every AST rule over one file; returns ``(findings,
    suppressed)``.  Unparseable files yield one CHKERR finding (the CLI
    maps any finding to exit 1; a syntax error in checked source is a
    finding, not a checker crash)."""
    display = path
    if repo_root:
        try:
            display = os.path.relpath(path, repo_root)
        except ValueError:
            pass
    try:
        ctx = _FileCtx(path, display)
    except (SyntaxError, UnicodeDecodeError) as e:
        return (
            [Finding("CHKERR", display, getattr(e, "lineno", 0) or 0,
                     f"unparseable: {e.msg if hasattr(e, 'msg') else e}")],
            [],
        )
    out = _Findings(ctx)
    for chk in _CHECKS:
        chk(ctx, out)
    return out.active, out.suppressed


def iter_py_files(paths) -> List[str]:
    """Expand files/directories into a sorted list of ``.py`` files
    (``__pycache__`` skipped)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames if d != "__pycache__"
                ]
                for fn in filenames:
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
        elif p.endswith(".py"):
            files.append(p)
    return sorted(set(files))


def check_paths(
    paths, repo_root: Optional[str] = None,
) -> Tuple[List[Finding], List[Finding], int]:
    """AST-lint every ``.py`` file under ``paths``; returns
    ``(findings, suppressed, n_files)``."""
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    files = iter_py_files(paths)
    for f in files:
        a, s = check_file(f, repo_root)
        findings.extend(a)
        suppressed.extend(s)
    return findings, suppressed, len(files)
