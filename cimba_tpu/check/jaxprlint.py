"""Program-level lints over traced jaxprs — static w.r.t. execution.

These checks trace and lower real programs (``jax.make_jaxpr`` /
``jit.lower``) but never compile or execute anything.  Three rules plus
the gate-registry sweep (:mod:`cimba_tpu.check.gates`):

* **JXL001 — donation coverage.**  Every carry input of a
  ``make_chunk`` program must be donated/aliased (the PR 3 invariant:
  chunk n+1 aliases chunk n's buffers — zero inter-chunk copies, flat
  steady-state memory).  Verified against the lowered StableHLO's
  ``tf.aliasing_output`` markers: one per carry leaf, exactly.
* **JXL002 — hot-path purity.**  The chunk program's jaxpr must contain
  no host round-trips (``pure_callback``/``io_callback``/
  ``debug_callback``/print, infeed/outfeed) — a callback would
  serialize the very dispatch loop it observes — and no ``gather``
  primitives beyond the model's registered budget (shipped models
  compile to zero gathers; an unexpected gather is usually an advanced
  indexing slip that Mosaic will refuse and XLA will scatter-gather
  slowly).
* **JXL003 — weak-type hygiene.**  No weakly-typed leaf may enter the
  packed carry: a weak Python scalar re-specializes jit caches and is
  exactly the dtype-profile memo-leak hazard behind the PR 1
  ``_DtypeHandle`` bug.  Verified over the init program's abstract
  output under both dtype profiles.

Run by ``tools/check.py`` (skipped under ``--ast-only``) and tier-1's
tests/test_check.py.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from cimba_tpu.check import Finding

__all__ = [
    "BANNED_PRIMITIVES", "GATHER_BUDGET",
    "donation_findings", "purity_findings", "weak_type_findings",
    "check_programs", "collect_primitives",
]

#: primitives that must never appear in a chunk program (host
#: round-trips serialize the dispatch loop; debug prints don't survive
#: serialization into the program store)
BANNED_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_local_array_to_global_array",
})

#: per-model gather budget for JXL002 (primitive name "gather"); every
#: shipped model compiles to zero — raise a model's budget here ONLY
#: with a comment justifying the access pattern
GATHER_BUDGET: Dict[str, int] = {}

_ALIAS_MARKER = re.compile(r"tf\.aliasing_output")


def collect_primitives(jaxpr) -> Dict[str, int]:
    """Primitive-name histogram of a (Closed)Jaxpr, recursing into
    every sub-jaxpr (while bodies, pjit calls, cond branches)."""
    import jax

    counts: Dict[str, int] = {}

    def walk(jx):
        for eq in jx.eqns:
            counts[eq.primitive.name] = (
                counts.get(eq.primitive.name, 0) + 1
            )
            for v in eq.params.values():
                for sub in _sub_jaxprs(v):
                    walk(sub)

    def _sub_jaxprs(v):
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v
        elif isinstance(v, (list, tuple)):
            for x in v:
                yield from _sub_jaxprs(x)

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)
    return counts


def _mm1_wave(profile: str):
    import jax
    import jax.numpy as jnp

    from cimba_tpu import config
    from cimba_tpu.core import loop as cl
    from cimba_tpu.models import mm1

    with config.profile(profile):
        spec, _ = mm1.build(record=False)
        sims = jax.vmap(
            lambda r: cl.init_sim(spec, 3, r, mm1.params(10))
        )(jnp.arange(4))
    return spec, sims


def donation_findings(
    chunk_j, sims, label: str,
) -> List[Finding]:
    """JXL001 for one jitted chunk program: every carry leaf aliased in
    the lowered text."""
    import jax

    n_leaves = len(jax.tree_util.tree_leaves(sims))
    text = chunk_j.lower(sims).as_text()
    n_aliased = len(_ALIAS_MARKER.findall(text))
    if n_aliased != n_leaves:
        return [Finding(
            rule="JXL001", path=f"program:{label}", line=0,
            message=(
                f"chunk program donates {n_aliased} of {n_leaves} "
                "carry leaves — every carry input must alias its "
                "output (the PR 3 zero-copy invariant; an undonated "
                "leaf doubles its steady-state memory and copies per "
                "chunk)"
            ),
        )]
    return []


def purity_findings(
    jaxpr, label: str, gather_budget: int = 0,
) -> List[Finding]:
    """JXL002 for one traced program."""
    counts = collect_primitives(jaxpr)
    out: List[Finding] = []
    hit = sorted(set(counts) & BANNED_PRIMITIVES)
    if hit:
        out.append(Finding(
            rule="JXL002", path=f"program:{label}", line=0,
            message=(
                f"host round-trip primitive(s) {hit} in a chunk "
                "program — callbacks/prints serialize the dispatch "
                "loop and cannot ride the program store"
            ),
        ))
    n_gather = counts.get("gather", 0)
    if n_gather > gather_budget:
        out.append(Finding(
            rule="JXL002", path=f"program:{label}", line=0,
            message=(
                f"{n_gather} gather primitive(s) in the chunk program "
                f"(budget {gather_budget}) — an unexpected gather is "
                "usually an advanced-indexing slip; register a budget "
                "in check.jaxprlint.GATHER_BUDGET only with a "
                "justified access pattern"
            ),
        ))
    return out


def weak_type_findings(tree, label: str) -> List[Finding]:
    """JXL003 over a pytree of (abstract or concrete) carry values."""
    import jax
    from jax.api_util import shaped_abstractify

    out: List[Finding] = []
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    weak = [
        jax.tree_util.keystr(p)
        for p, leaf in leaves_with_path
        if shaped_abstractify(leaf).weak_type
    ]
    if weak:
        out.append(Finding(
            rule="JXL003", path=f"program:{label}", line=0,
            message=(
                f"weakly-typed leaves in the packed carry: {weak} — a "
                "weak Python scalar re-specializes jit caches per "
                "profile (the PR 1 dtype-memo leak); cast through "
                "config.TIME/REAL/COUNT at creation"
            ),
        ))
    return out


def check_programs(
    profiles: Tuple[str, ...] = ("f64", "f32"),
    with_gates: bool = True,
    gate_model: str = "mm1",
) -> Tuple[List[Finding], dict]:
    """The full program-lint battery over the shipped reference model
    (mm1, the model every historical pin used): donation + purity +
    weak types per dtype profile, plus the gate-registry sweep.
    Returns ``(findings, report)``."""
    import jax

    from cimba_tpu import config
    from cimba_tpu.runner import experiment as ex

    findings: List[Finding] = []
    report: dict = {"programs": {}}
    for profile in profiles:
        # trace under the SAME profile the Sim was built in — mixing
        # is exactly the cross-profile hazard JXL003 polices
        with config.profile(profile):
            spec, sims = _mm1_wave(profile)
            label = f"mm1/{profile}"
            chunk_j = ex._chunk_program(spec, None, False, 8, None)
            findings.extend(donation_findings(chunk_j, sims, label))
            jaxpr = jax.make_jaxpr(lambda s: chunk_j(s))(sims)
        findings.extend(purity_findings(
            jaxpr, label, GATHER_BUDGET.get("mm1", 0)
        ))
        findings.extend(weak_type_findings(sims, label))
        report["programs"][label] = {
            "carry_leaves": len(jax.tree_util.tree_leaves(sims)),
            "checks": ["JXL001", "JXL002", "JXL003"],
        }
    if with_gates:
        from cimba_tpu.check import gates as _gates

        gate_findings, gate_report = _gates.sweep(
            profiles, model=gate_model,
        )
        findings.extend(gate_findings)
        report["gates"] = gate_report
    return findings, report
