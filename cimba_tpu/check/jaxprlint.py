"""Program-level lints over traced jaxprs — static w.r.t. execution.

These checks trace and lower real programs (``jax.make_jaxpr`` /
``jit.lower``) but never compile or execute anything.  Four rules plus
the gate-registry sweep (:mod:`cimba_tpu.check.gates`):

* **JXL001 — donation coverage.**  Every carry input of a
  ``make_chunk`` program must be donated/aliased (the PR 3 invariant:
  chunk n+1 aliases chunk n's buffers — zero inter-chunk copies, flat
  steady-state memory).  Verified against the lowered StableHLO's
  ``tf.aliasing_output`` markers: one per carry leaf, exactly.
* **JXL002 — hot-path purity.**  The chunk program's jaxpr must contain
  no host round-trips (``pure_callback``/``io_callback``/
  ``debug_callback``/print, infeed/outfeed) — a callback would
  serialize the very dispatch loop it observes — and no ``gather``
  primitives beyond the model's registered budget (shipped models
  compile to zero gathers; an unexpected gather is usually an advanced
  indexing slip that Mosaic will refuse and XLA will scatter-gather
  slowly).
* **JXL003 — weak-type hygiene.**  No weakly-typed leaf may enter the
  packed carry: a weak Python scalar re-specializes jit caches and is
  exactly the dtype-profile memo-leak hazard behind the PR 1
  ``_DtypeHandle`` bug.  Verified over the init program's abstract
  output under both dtype profiles.
* **JXL004 — program-size budget.**  The chunk program's total jaxpr
  equation count must stay under the model's registered ceiling
  (:data:`EQN_BUDGET`).  Program TEXT growth is the compile wall
  (docs/25_compile_wall.md): a ceiling breach means something started
  emitting per-row or per-step equations (a Python loop over processes,
  an unrolled scan) — the class of regression that compiles fine at dev
  scale and takes >25 minutes at AWACS scale.  Counted with the same
  walker as ``cimba_tpu.obs.program_size``.

Run by ``tools/check.py`` (skipped under ``--ast-only``) and tier-1's
tests/test_check.py.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from cimba_tpu.check import Finding

__all__ = [
    "BANNED_PRIMITIVES", "GATHER_BUDGET", "EQN_BUDGET",
    "FUSED_EQN_FACTOR",
    "donation_findings", "purity_findings", "weak_type_findings",
    "size_findings", "fused_size_findings", "check_programs",
    "collect_primitives",
]

#: primitives that must never appear in a chunk program (host
#: round-trips serialize the dispatch loop; debug prints don't survive
#: serialization into the program store)
BANNED_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_local_array_to_global_array",
})

#: per-model gather budget for JXL002 (primitive name "gather"); every
#: shipped model compiles to zero — raise a model's budget here ONLY
#: with a comment justifying the access pattern
GATHER_BUDGET: Dict[str, int] = {}

#: per-model chunk-program equation ceiling for JXL004.  Calibrated
#: ~1.3x over the measured default-knob counts (mm1 8675, awacs 4191
#: dense / 4475 scan-on, both profiles within a few eqns) so dtype
#: profiles and the table-scan arm fit, but a per-row unroll (which
#: multiplies the count by table height) cannot.  Raise only with a
#: program_size measurement justifying the new floor.
EQN_BUDGET: Dict[str, int] = {"mm1": 11000, "awacs": 6000}

#: JXL004 sublinearity factor for fused superprograms
#: (docs/26_wave_fusion.md): a K-member fused chunk program's equation
#: count must stay under this fraction of the SUM of the K members'
#: solo counts — the members share ONE copy of the machinery (event
#: heap, guards, queues; the bulk of every chunk program) and only
#: their block tables concatenate, so the merged program must be far
#: sublinear in K.  Linear growth here means the machinery duplicated
#: per member — the compile wall fusion exists to avoid.
FUSED_EQN_FACTOR = 0.6

_ALIAS_MARKER = re.compile(r"tf\.aliasing_output")


def collect_primitives(jaxpr) -> Dict[str, int]:
    """Primitive-name histogram of a (Closed)Jaxpr, recursing into
    every sub-jaxpr (while bodies, pjit calls, cond branches)."""
    import jax

    counts: Dict[str, int] = {}

    def walk(jx):
        for eq in jx.eqns:
            counts[eq.primitive.name] = (
                counts.get(eq.primitive.name, 0) + 1
            )
            for v in eq.params.values():
                for sub in _sub_jaxprs(v):
                    walk(sub)

    def _sub_jaxprs(v):
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v
        elif isinstance(v, (list, tuple)):
            for x in v:
                yield from _sub_jaxprs(x)

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)
    return counts


def _mm1_wave(profile: str):
    import jax
    import jax.numpy as jnp

    from cimba_tpu import config
    from cimba_tpu.core import loop as cl
    from cimba_tpu.models import mm1

    with config.profile(profile):
        spec, _ = mm1.build(record=False)
        sims = jax.vmap(
            lambda r: cl.init_sim(spec, 3, r, mm1.params(10))
        )(jnp.arange(4))
    return spec, sims


def donation_findings(
    chunk_j, sims, label: str,
) -> List[Finding]:
    """JXL001 for one jitted chunk program: every carry leaf aliased in
    the lowered text."""
    import jax

    n_leaves = len(jax.tree_util.tree_leaves(sims))
    text = chunk_j.lower(sims).as_text()
    n_aliased = len(_ALIAS_MARKER.findall(text))
    if n_aliased != n_leaves:
        return [Finding(
            rule="JXL001", path=f"program:{label}", line=0,
            message=(
                f"chunk program donates {n_aliased} of {n_leaves} "
                "carry leaves — every carry input must alias its "
                "output (the PR 3 zero-copy invariant; an undonated "
                "leaf doubles its steady-state memory and copies per "
                "chunk)"
            ),
        )]
    return []


def purity_findings(
    jaxpr, label: str, gather_budget: int = 0,
) -> List[Finding]:
    """JXL002 for one traced program."""
    counts = collect_primitives(jaxpr)
    out: List[Finding] = []
    hit = sorted(set(counts) & BANNED_PRIMITIVES)
    if hit:
        out.append(Finding(
            rule="JXL002", path=f"program:{label}", line=0,
            message=(
                f"host round-trip primitive(s) {hit} in a chunk "
                "program — callbacks/prints serialize the dispatch "
                "loop and cannot ride the program store"
            ),
        ))
    n_gather = counts.get("gather", 0)
    if n_gather > gather_budget:
        out.append(Finding(
            rule="JXL002", path=f"program:{label}", line=0,
            message=(
                f"{n_gather} gather primitive(s) in the chunk program "
                f"(budget {gather_budget}) — an unexpected gather is "
                "usually an advanced-indexing slip; register a budget "
                "in check.jaxprlint.GATHER_BUDGET only with a "
                "justified access pattern"
            ),
        ))
    return out


def size_findings(
    eqns: int, label: str, budget: Optional[int],
) -> List[Finding]:
    """JXL004 for one traced program: total equation count (recursive —
    count with the :func:`collect_primitives` walk or
    ``obs.program_size``) under the model's ceiling."""
    if budget is None:
        return []
    n = int(eqns)
    if n > budget:
        return [Finding(
            rule="JXL004", path=f"program:{label}", line=0,
            message=(
                f"chunk program has {n} jaxpr equations (budget "
                f"{budget}) — program text growth is the compile wall "
                "(docs/25_compile_wall.md); look for a Python loop "
                "over table rows or an unrolled scan, or raise "
                "check.jaxprlint.EQN_BUDGET with a program_size "
                "measurement justifying the new floor"
            ),
        )]
    return []


def fused_size_findings(
    fused_eqns: int, solo_eqns, label: str,
) -> List[Finding]:
    """JXL004 for one fused superprogram: the merged chunk program's
    equation count against ``FUSED_EQN_FACTOR`` x the sum of its
    members' solo counts (``solo_eqns`` — one entry per member).  The
    budget is derived, not tabled: it scales with whatever the members
    actually cost, so the pinned claim is pure SUBLINEARITY."""
    budget = int(sum(int(n) for n in solo_eqns) * FUSED_EQN_FACTOR)
    n = int(fused_eqns)
    if n > budget:
        return [Finding(
            rule="JXL004", path=f"program:{label}", line=0,
            message=(
                f"fused superprogram has {n} jaxpr equations — over "
                f"{FUSED_EQN_FACTOR}x the {sum(int(x) for x in solo_eqns)}"
                "-eqn sum of its members' solo programs (budget "
                f"{budget}).  Fusion must share one machinery copy "
                "and concatenate only block tables "
                "(docs/26_wave_fusion.md); near-linear growth means "
                "per-member duplication — the compile wall fusion "
                "exists to avoid"
            ),
        )]
    return []


def weak_type_findings(tree, label: str) -> List[Finding]:
    """JXL003 over a pytree of (abstract or concrete) carry values."""
    import jax
    from jax.api_util import shaped_abstractify

    out: List[Finding] = []
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    weak = [
        jax.tree_util.keystr(p)
        for p, leaf in leaves_with_path
        if shaped_abstractify(leaf).weak_type
    ]
    if weak:
        out.append(Finding(
            rule="JXL003", path=f"program:{label}", line=0,
            message=(
                f"weakly-typed leaves in the packed carry: {weak} — a "
                "weak Python scalar re-specializes jit caches per "
                "profile (the PR 1 dtype-memo leak); cast through "
                "config.TIME/REAL/COUNT at creation"
            ),
        ))
    return out


def check_programs(
    profiles: Tuple[str, ...] = ("f64", "f32"),
    with_gates: bool = True,
    gate_model: str = "mm1",
) -> Tuple[List[Finding], dict]:
    """The full program-lint battery over the shipped reference model
    (mm1, the model every historical pin used): donation + purity +
    weak types per dtype profile, plus the gate-registry sweep.
    Returns ``(findings, report)``."""
    import jax

    from cimba_tpu import config
    from cimba_tpu.runner import experiment as ex

    findings: List[Finding] = []
    report: dict = {"programs": {}}
    for profile in profiles:
        # trace under the SAME profile the Sim was built in — mixing
        # is exactly the cross-profile hazard JXL003 polices
        with config.profile(profile):
            spec, sims = _mm1_wave(profile)
            label = f"mm1/{profile}"
            chunk_j = ex._chunk_program(spec, None, False, 8, None)
            findings.extend(donation_findings(chunk_j, sims, label))
            jaxpr = jax.make_jaxpr(lambda s: chunk_j(s))(sims)
        findings.extend(purity_findings(
            jaxpr, label, GATHER_BUDGET.get("mm1", 0)
        ))
        findings.extend(size_findings(
            sum(collect_primitives(jaxpr).values()), label,
            EQN_BUDGET.get("mm1"),
        ))
        findings.extend(weak_type_findings(sims, label))
        report["programs"][label] = {
            "carry_leaves": len(jax.tree_util.tree_leaves(sims)),
            "checks": ["JXL001", "JXL002", "JXL003", "JXL004"],
        }
        # JXL004 additionally covers the model whose table height IS
        # the compile wall (awacs: [P, ...] tables); trace-only, small
        # P — the eqn count is P-independent unless something unrolls
        with config.profile(profile):
            from cimba_tpu.models import awacs as _awacs

            a_spec, _ = _awacs.build(16)
            a_label = f"awacs/{profile}"
            from cimba_tpu.obs import program_size as _ps

            a_size = _ps.chunk_program_size(
                a_spec, _awacs.params(2.0), profile=None, lower=False,
            )
        findings.extend(size_findings(
            a_size.eqns, a_label, EQN_BUDGET.get("awacs"),
        ))
        report["programs"][a_label] = {
            "eqns": a_size.eqns, "checks": ["JXL004"],
        }
    if with_gates:
        from cimba_tpu.check import gates as _gates

        gate_findings, gate_report = _gates.sweep(
            profiles, model=gate_model,
        )
        findings.extend(gate_findings)
        report["gates"] = gate_report
    return findings, report
