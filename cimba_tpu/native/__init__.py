"""ctypes bindings for the native runtime pieces (native/cimba_native.cpp).

Builds on demand with the in-tree Makefile (g++; no pybind11 — plain
extern "C" + ctypes per the environment's binding constraints).  Absent a
C++ toolchain the import still succeeds and ``available()`` returns False;
everything native has a Python fallback (utils/seed.py, the Python oracle
in tests).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.normpath(os.path.join(_HERE, "..", "..", "native"))
_SO = os.path.join(_NATIVE_DIR, "build", "libcimba_native.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_NATIVE_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO):
        src = os.path.join(_NATIVE_DIR, "cimba_native.cpp")
        if not os.path.exists(src) or not _build():
            return None
    try:
        lib = ctypes.CDLL(_SO)
        _bind(lib)
    except OSError:
        return None
    except AttributeError:
        # stale .so predating a symbol (local build artifact): rebuild
        # from source and reload; if the rebuild or the reload still
        # misses symbols, degrade to unavailable rather than crash.
        # (make clean first: gcc rewrites in place, and dlopen caches
        # by (dev, inode) — a fresh inode guarantees a fresh mapping)
        try:
            subprocess.run(
                ["make", "-s", "clean"],
                cwd=_NATIVE_DIR,
                check=True,
                capture_output=True,
                timeout=60,
            )
        except Exception:
            return None
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
            _bind(lib)
        except (OSError, AttributeError):
            return None
    _lib = lib
    return lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.cimba_hwseed.restype = ctypes.c_uint64
    lib.cimba_threefry2x32.argtypes = [ctypes.c_uint32] * 4 + [
        ctypes.POINTER(ctypes.c_uint32)
    ] * 2
    lib.cimba_oracle_mm1.argtypes = [
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_double,
        ctypes.c_double,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.cimba_mm1_single.argtypes = [
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_double,
        ctypes.c_double,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.cimba_oracle_mmc.argtypes = [
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_double,
        ctypes.c_double,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_double),
    ]


def available() -> bool:
    return load() is not None


def hwseed() -> int:
    """RDSEED/RDRAND-backed 64-bit seed (parity: cmb_random_hwseed);
    falls back to utils.seed.hwseed without the native library."""
    lib = load()
    if lib is None:
        from cimba_tpu.utils.seed import hwseed as py_hwseed

        return py_hwseed()
    return int(lib.cimba_hwseed())


def threefry2x32(k0: int, k1: int, c0: int, c1: int) -> tuple[int, int]:
    lib = load()
    assert lib is not None
    o0 = ctypes.c_uint32()
    o1 = ctypes.c_uint32()
    lib.cimba_threefry2x32(k0, k1, c0, c1, ctypes.byref(o0), ctypes.byref(o1))
    return o0.value, o1.value


def _summary(out) -> dict:
    """The shared [clock, n, mean, m2, min, max, events] out7 layout."""
    keys = ("clock", "n", "mean", "m2", "min", "max")
    d = {k: out[i] for i, k in enumerate(keys)}
    d["events"] = int(out[6])
    return d


def oracle_mm1(
    seed: int, rep: int, n_objects: int, arr_mean: float, srv_mean: float
) -> dict:
    """Run the scalar C++ M/M/1 oracle; returns the summary dict."""
    lib = load()
    assert lib is not None
    out = (ctypes.c_double * 7)()
    lib.cimba_oracle_mm1(seed, rep, n_objects, arr_mean, srv_mean, out)
    return _summary(out)


def mm1_single(
    seed: int, rep: int, n_objects: int, arr_mean: float, srv_mean: float
) -> dict:
    """Single-stream M/M/1 on the host core at engine semantics — the
    native latency path (run_mm1_fast in cimba_native.cpp); results are
    bitwise-equal to :func:`oracle_mm1` (pinned by test_native.py).

    ``fast_path_overflow`` reports a slot-table invariant violation in
    the fast path: the result then came from the general run_mm1 engine
    (structured fallback — the fast path must never abort the process)."""
    lib = load()
    assert lib is not None
    out = (ctypes.c_double * 8)()
    lib.cimba_mm1_single(seed, rep, n_objects, arr_mean, srv_mean, out)
    d = _summary(out)
    d["fast_path_overflow"] = bool(out[7])
    return d


def oracle_mmc(
    seed: int,
    rep: int,
    n_objects: int,
    arr_mean: float,
    srv_mean: float,
    c: int,
) -> dict:
    """Run the scalar C++ M/M/c oracle; returns the summary dict."""
    lib = load()
    assert lib is not None
    out = (ctypes.c_double * 7)()
    lib.cimba_oracle_mmc(seed, rep, n_objects, arr_mean, srv_mean, c, out)
    return _summary(out)