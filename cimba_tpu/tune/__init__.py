"""cimba_tpu.tune — the schedule autotuner (docs/21_autotune.md).

Every dispatch knob on the hot path — the hierarchical event-set
minima (``CIMBA_EVENTSET_HIER`` / ``CIMBA_EVENTSET_BLOCK``), the
packed XLA while-loop carry (``CIMBA_XLA_PACK``), the chunk budget
(``chunk_steps``), the wave quantum (``wave_size``), and the Pallas
lane-block grid where the kernel path is live — is a *schedule*: it
changes how fast a program runs, never what it computes (the
per-knob bitwise pins of docs/11/12/14).  BENCH_NOTES round 6 proved
the right setting flips by workload (the hierarchical min wins on
pop-dominated event sets and loses on mutation-bursty ones; the
packed carry wins on mm1/mg1 CPU arms), so hand-frozen defaults are
wrong for someone.  This package searches the schedule space per
(program, backend, workload bucket), pins every candidate bitwise
against the default schedule, persists the winner in the PR 6
program-store manifest, and makes every entry point —
``run_experiment_stream``, ``serve.Service``, ``sweep.run_sweep``,
fleet slices — resolve the tuned schedule at program-build time
(``CIMBA_TUNE=0`` opts out; explicit kwargs always win).

Submodules: :mod:`~cimba_tpu.tune.space` (the declarative
``ScheduleSpace`` and the ``Schedule`` record),
:mod:`~cimba_tpu.tune.measure` (the interleaved best-of-k measurement
harness — the ONE timing implementation bench.py's arm batteries now
ride), :mod:`~cimba_tpu.tune.search` (budgeted search emitting a
crash-atomic ``TuneReport`` JSON), :mod:`~cimba_tpu.tune.registry`
(store persistence + resolution), :mod:`~cimba_tpu.tune.probe` (the
step-probe workload whose default schedule round 6 proved wrong).
"""

from cimba_tpu.tune.space import Schedule, ScheduleSpace, default_space
from cimba_tpu.tune.measure import Arm, ArmResult, MeasureReport, measure_arms
from cimba_tpu.tune.search import TuneReport, search_schedule, write_report
from cimba_tpu.tune.registry import (
    TUNE_ENV,
    resolve_schedule,
    save_tuned,
    tune_enabled,
    tune_key,
    workload_bucket,
)

__all__ = [
    "Schedule", "ScheduleSpace", "default_space",
    "Arm", "ArmResult", "MeasureReport", "measure_arms",
    "TuneReport", "search_schedule", "write_report",
    "TUNE_ENV", "tune_enabled", "tune_key", "workload_bucket",
    "resolve_schedule", "save_tuned",
]
