"""Tuned-schedule persistence and resolution.

The winner of a :func:`~cimba_tpu.tune.search.search_schedule` run
persists in the PR 6 program-store manifest (a ``"tuned"`` section
beside ``"entries"`` — same file, same crash-atomic + cross-process
lock discipline, same strict environment invalidation ladder: a
jax/jaxlib/backend/device drift invalidates a tuned entry exactly like
a serialized executable, docs/15_program_store.md) keyed by

    ``tune_key = sha256(stable_spec_fingerprint, backend, device kind,
    workload bucket)``

— value-based, so a fresh process resolves the same entry a tuner
process saved.  The workload bucket is the pow2 ceiling of R: a tuned
schedule is a per-workload-SCALE decision (the round-6 lesson — the
winner flips between the 256-lane CPU window and the 131072-lane TPU
point), and bucketing at pow2 granularity keeps nearby R sharing one
entry without letting a 64-lane probe's winner govern a million-lane
fleet.

Resolution (:func:`resolve_schedule`) is what every entry point calls
at program-build time — ``run_experiment_stream``,
``serve.Service.submit``, ``sweep.run_sweep``, fleet slices via the
service.  The ladder, loudest first:

1. explicit kwargs / an explicit ``schedule=`` always win
   (``source="override"``);
2. ``CIMBA_TUNE=0`` opts out entirely (``source="off"``);
3. a valid tuned entry in the store (env-checked) resolves
   (``source="tuned"``);
4. otherwise the hand-frozen defaults run, as they always have
   (``source="default"``).

The source surfaces in ``Service.stats()["schedule"]`` / ``/varz`` and
in every run card's ``schedule`` block, so "which schedule did this
number run under?" is always answerable (docs/21_autotune.md).
Lookups are memoized per (store root, key) against the manifest's
mtime, so the serve submit path never re-parses the manifest per
request.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import os
import threading
import time
from typing import Optional, Tuple

from cimba_tpu.tune.space import Schedule

__all__ = [
    "TUNE_ENV", "tune_enabled", "workload_bucket", "tune_key",
    "save_tuned", "lookup_tuned", "resolve_schedule",
    "ResolvedEntry", "resolve_entry",
]

#: environment knob: "0" opts every entry point out of tuned-schedule
#: resolution (registered in ``config.ENV_KNOBS``; the ``tune`` gate in
#: check/gates.py pins that the off state is jaxpr-identical to the
#: default)
TUNE_ENV = "CIMBA_TUNE"

_lock = threading.Lock()
#: (store root, tune key) -> (manifest mtime, entry-or-None, verdict
#: counter name); every access holds ``_lock``
_memo: dict = {}


def tune_enabled() -> bool:
    from cimba_tpu import config

    return config.env_raw(TUNE_ENV) != "0"


def workload_bucket(n_replications: int) -> int:
    """The pow2 ceiling of R — the workload-scale bucket a tuned entry
    is keyed by (64 and 100 lanes share a schedule; 256 and 131072 do
    not)."""
    R = int(n_replications)
    if R <= 1:
        return 1
    return 1 << (R - 1).bit_length()


# cimba-check: content-path
def tune_key(spec, *, n_replications: int, backend: Optional[str] = None,
             device_kind: Optional[str] = None) -> str:
    """The persistent tuned-entry key: sha256 over the VALUE-based spec
    fingerprint, backend, device kind, and the workload bucket.
    Raises :class:`~cimba_tpu.serve.store.UnstableStoreKey` when the
    spec has no value identity (same contract as the artifact store)."""
    from cimba_tpu.serve import store as _pstore

    if backend is None or device_kind is None:
        import jax

        dev = jax.devices()[0]
        if backend is None:
            backend = jax.default_backend()
        if device_kind is None:
            device_kind = getattr(dev, "device_kind", "?")
    key = (
        "tune", 1,
        _pstore.stable_spec_fingerprint(spec),
        str(backend), str(device_kind),
        workload_bucket(n_replications),
    )
    return hashlib.sha256(repr(key).encode("utf-8")).hexdigest()


def _invalidate_memo(root: str) -> None:
    with _lock:
        for k in [k for k in _memo if k[0] == root]:
            del _memo[k]


def save_tuned(store, spec, n_replications: int, report) -> Optional[dict]:
    """Persist a search's winner into ``store``'s manifest (merged
    under the cross-process manifest lock).  ``report`` is a
    :class:`~cimba_tpu.tune.search.TuneReport`; a HOLD decision saves
    nothing and returns None — the default needs no entry.  Returns
    the written record."""
    from cimba_tpu.serve import store as _pstore

    if getattr(report, "decision", None) != "tuned":
        return None
    try:
        key = tune_key(
            spec, n_replications=n_replications,
            backend=report.backend, device_kind=report.device_kind,
        )
    except _pstore.UnstableStoreKey as e:
        # no value identity -> no persistent slot to save under: record
        # a downgrade like the artifact path (the in-process winner can
        # still be applied via an explicit schedule= kwarg)
        import warnings

        warnings.warn(
            f"tuned winner for {report.spec_name!r} cannot persist "
            f"({e}); pass schedule= explicitly instead",
            _pstore.StoreInvalidationWarning,
        )
        store._count("downgrades")
        return None
    # the winner arm's compile/program-size numbers ride into the
    # manifest (docs/25_compile_wall.md): a tuned entry that traded
    # run-time for compile-time shows its price next to the speedup
    win_row = next(
        (r for r in report.arms if r.get("name") == report.winner_name),
        None,
    ) or {}
    rec = {
        "schedule": report.winner.to_json(),
        "schedule_digest": report.winner.digest(),
        "env": _pstore._environment(),
        "created": time.time(),
        "report_digest": report.digest(),
        "meta": {
            "model": report.spec_name,
            "bucket": report.bucket,
            "workload": report.workload,
            "speedup_frac": report.speedup_frac,
            "noise_floor_frac": report.noise_floor_frac,
            "compile_s": win_row.get("compile_s"),
            "program_size": win_row.get("program_size"),
        },
    }

    def put(manifest):
        manifest.setdefault("tuned", {})[key] = rec

    store._update_manifest(put)
    store._count("tuned_saves")
    _invalidate_memo(store.root)
    return rec


def lookup_tuned(store, key: str) -> Optional[dict]:
    """One tuned entry by key, under the artifact store's invalidation
    ladder: absent -> counted miss; environment drift (jax/jaxlib/
    backend/device/x64) -> counted ``tuned_invalidated`` with a loud
    :class:`~cimba_tpu.serve.store.StoreInvalidationWarning` — a tuned
    schedule measured on different software/hardware is a guess, and
    this registry exists to end guessing.  Memoized against the
    manifest mtime (the serve submit path resolves per request)."""
    import warnings

    from cimba_tpu.serve import store as _pstore

    mpath = store._manifest_path()
    try:
        mtime = os.stat(mpath).st_mtime_ns
    except OSError:
        mtime = None
    memo_key = (store.root, key)
    with _lock:
        hit = _memo.get(memo_key)
        if hit is not None and hit[0] == mtime:
            # re-count the memoized VERDICT, not a guess from the
            # payload: an env-invalidated entry must keep reading as
            # invalidated in the counters (the re-run-the-search
            # signal), never degrade into "misses" after the first
            # lookup; the warning stays once-per-manifest-generation
            store._count(hit[2])
            return hit[1]
    with store._lock:
        manifest = store._read_manifest()
    entry = (manifest.get("tuned") or {}).get(key)
    out = None
    if entry is None:
        verdict = "tuned_misses"
    elif entry.get("env") != _pstore._environment():
        env = _pstore._environment()
        drift = {
            k: (entry.get("env", {}).get(k), env[k])
            for k in env if entry.get("env", {}).get(k) != env[k]
        }
        warnings.warn(
            f"tuned schedule entry {key[:16]} was measured in a "
            f"different environment ({drift}); falling back to the "
            "default schedule — re-run the search",
            _pstore.StoreInvalidationWarning,
        )
        verdict = "tuned_invalidated"
    else:
        verdict = "tuned_hits"
        out = entry
    store._count(verdict)
    with _lock:
        _memo[memo_key] = (mtime, out, verdict)
    return out


def resolve_schedule(
    spec, n_replications: int, *, store=None,
) -> Tuple[Optional[Schedule], str, Optional[str]]:
    """The resolution ladder every entry point rides at program-build
    time: ``(schedule | None, source, tune_entry_digest | None)`` with
    ``source`` one of ``"off"`` (``CIMBA_TUNE=0``), ``"default"`` (no
    store / no entry / invalidated / unstable spec), or ``"tuned"``.
    ``store=None`` resolves ``CIMBA_PROGRAM_STORE`` (the fleet-slice
    path — a slice with the env knob set resolves tuned schedules with
    zero configuration); ``store=False`` opts out like a missing
    store.  Never raises: an unstable spec or a corrupt record is a
    counted degrade to the default schedule, exactly like the artifact
    ladder."""
    import warnings

    from cimba_tpu.serve import store as _pstore

    if not tune_enabled():
        return None, "off", None
    if store is False:
        return None, "default", None
    st = store if store is not None else _pstore.default_store()
    if st is None:
        return None, "default", None
    try:
        key = tune_key(spec, n_replications=n_replications)
    except _pstore.UnstableStoreKey:
        return None, "default", None
    entry = lookup_tuned(st, key)
    if entry is None:
        return None, "default", None
    try:
        sched = Schedule.from_json(entry["schedule"])
    except (KeyError, TypeError, ValueError) as e:
        warnings.warn(
            f"tuned schedule entry {key[:16]} is malformed "
            f"({type(e).__name__}: {e}); using the default schedule",
            _pstore.StoreInvalidationWarning,
        )
        st._count("tuned_invalidated")
        return None, "default", None
    return sched, "tuned", entry.get("schedule_digest")


@dataclasses.dataclass
class ResolvedEntry:
    """One entry point's resolved schedule: the effective argument
    knobs (explicit kwargs already folded in — they always win), the
    trace-time knob subset to bind via :meth:`scope`, the resolution
    ``source`` (``override``/``tuned``/``default``/``off``), and the
    ``schedule`` block run cards and ``Service.stats()`` surface."""

    schedule: Optional[Schedule]
    source: str
    tune_digest: Optional[str]
    pack: Optional[bool]
    chunk_steps: int
    wave_size: Optional[int]
    applied: dict

    def scope(self):
        """Context manager binding the resolved TRACE-time knobs
        (event-set layout, kernel lane block) for a dispatch region.
        The argument knobs (pack/chunk/wave) ride kwargs instead, and
        an ambient programmatic override (``config.EVENTSET_HIER``
        et al. already set — the bench ``_dispatch_arm`` idiom) is
        never clobbered: explicit wins over tuned, tuned over
        default."""
        if self.schedule is None:
            return contextlib.nullcontext()
        from cimba_tpu import config

        sub = Schedule(
            eventset_hier=(
                self.schedule.eventset_hier
                if config.EVENTSET_HIER is None else None
            ),
            eventset_block=(
                self.schedule.eventset_block
                if config.EVENTSET_BLOCK is None else None
            ),
            lane_block=self.schedule.lane_block,
        )
        if sub.is_default():
            return contextlib.nullcontext()
        return sub.scope()

    def block(self) -> dict:
        """The ``schedule`` block (docs/18_audit.md): resolved knobs +
        resolution source + tuned-entry digest — what run cards carry
        so every bitwise claim names the schedule it ran under."""
        knobs = {
            "pack": self.pack,
            "chunk_steps": self.chunk_steps,
            "wave_size": self.wave_size,
        }
        if self.schedule is not None:
            for f in ("eventset_hier", "eventset_block", "lane_block",
                      "waves_per_device", "preempt_quantum",
                      "mem_fraction", "fuse", "fuse_max_specs"):
                v = getattr(self.schedule, f)
                if v is not None:
                    knobs[f] = v
        return {
            "source": self.source,
            "tune_entry": self.tune_digest,
            "knobs": knobs,
        }


def resolve_entry(
    spec,
    n_replications: int,
    *,
    schedule: Optional[Schedule] = None,
    pack: Optional[bool] = None,
    chunk_steps: Optional[int] = None,
    wave_size: Optional[int] = None,
    store=None,
    default_chunk_steps: int = 1024,
) -> ResolvedEntry:
    """Fold one entry point's explicit kwargs over the resolution
    ladder and return the effective knob set.  ``schedule=`` (an
    explicit :class:`Schedule`) pre-empts the registry entirely
    (``source="override"`` — the search harness and power users);
    otherwise a registry-resolved schedule fills ONLY the knobs the
    caller left unset, and ``source`` reports ``"tuned"`` only when at
    least one tuned knob actually took effect."""
    if schedule is not None:
        sched, source, dig = schedule, "override", None
    else:
        sched, source, dig = resolve_schedule(
            spec, n_replications, store=store,
        )
    applied: dict = {}
    eff_pack = pack
    if eff_pack is None and sched is not None and sched.pack is not None:
        eff_pack = bool(sched.pack)
        applied["pack"] = eff_pack
    eff_chunk = chunk_steps
    if eff_chunk is None:
        if sched is not None and sched.chunk_steps is not None:
            eff_chunk = int(sched.chunk_steps)
            applied["chunk_steps"] = eff_chunk
        else:
            eff_chunk = int(default_chunk_steps)
    eff_wave = wave_size
    if eff_wave is None and sched is not None \
            and sched.wave_size is not None:
        eff_wave = int(sched.wave_size)
        applied["wave_size"] = eff_wave
    if sched is not None:
        from cimba_tpu import config

        if sched.eventset_hier is not None \
                and config.EVENTSET_HIER is None:
            applied["eventset_hier"] = bool(sched.eventset_hier)
        if sched.eventset_block is not None \
                and config.EVENTSET_BLOCK is None:
            applied["eventset_block"] = int(sched.eventset_block)
        if sched.lane_block is not None:
            applied["lane_block"] = int(sched.lane_block)
        # device-scheduler policy knobs (docs/24_device_scheduler.md):
        # service-level, not per-request — serve.Service adopts them
        # at submit time when its own constructor knobs were left
        # None (Service._adopt_sched_knobs); they count as applied so
        # the resolution source stays truthful
        if sched.waves_per_device is not None:
            applied["waves_per_device"] = int(sched.waves_per_device)
        if sched.preempt_quantum is not None:
            applied["preempt_quantum"] = int(sched.preempt_quantum)
        if sched.mem_fraction is not None:
            applied["mem_fraction"] = float(sched.mem_fraction)
        # wave-fusion policy knobs (docs/26_wave_fusion.md): same
        # service-level adoption path (Service._adopt_fuse_knobs)
        if sched.fuse is not None:
            applied["fuse"] = bool(sched.fuse)
        if sched.fuse_max_specs is not None:
            applied["fuse_max_specs"] = int(sched.fuse_max_specs)
    if source == "tuned" and not applied:
        # a tuned entry existed but every one of its knobs lost to an
        # explicit kwarg/ambient override — the run is the caller's
        source = "override"
    return ResolvedEntry(
        schedule=sched,
        source=source,
        tune_digest=dig,
        pack=eff_pack,
        chunk_steps=int(eff_chunk),
        wave_size=eff_wave,
        applied=applied,
    )
