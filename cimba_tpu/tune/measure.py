"""The measurement harness: interleaved best-of-k twin-arm timing.

``bench.py`` grew this idiom three times (the chunked-vs-monolithic
stream arm, the telemetry-overhead arm, the dispatch-arm batteries):
measure every arm at the SAME operating point, interleave the rounds so
machine drift on a noisy shared host lands on every arm equally, take
the best-of-k wall per arm, and never compare numbers measured at
different moments of the battery.  This module is that idiom factored
into ONE implementation — :func:`measure_arms` — which bench.py now
rides (the deduplication satellite of docs/21_autotune.md) and the
schedule search builds on.

Contract:

* **Interleaved rounds**: round ``r`` runs every live arm once, in
  order; an arm's headline wall is its best (min) across rounds.  A
  load spike hits whichever arm was running, not systematically the
  same one.
* **Compile/run split**: each arm's optional ``prepare()`` (trace +
  warm-compile — the ``with_report`` split's compile leg) is timed
  separately and never inside a timed round; an arm whose prepare
  exceeds ``compile_budget_s`` is recorded ``SKIPPED`` with the
  measured time — never silently dropped.
* **Noise floor from self-vs-self**: the baseline arm runs TWICE per
  round (a blind twin).  The relative rate gap between its two
  best-of-k measurements is the floor below which a "win" is
  indistinguishable from machine noise — the search HOLDs the default
  unless a challenger clears it.
* **Wall budget**: rounds stop early once ``budget_s`` is spent
  (every arm still has equal rounds — the budget cuts whole rounds);
  arms that never got a round are ``SKIPPED`` with the reason
  recorded.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional

__all__ = ["Arm", "ArmResult", "MeasureReport", "measure_arms"]

OK = "ok"
SKIPPED = "skipped"

#: the baseline twin's arm name suffix (never reported as its own arm —
#: it exists only to estimate the noise floor)
_TWIN = "__self_twin__"


@dataclasses.dataclass
class Arm:
    """One measurable arm.  ``run()`` is a single timed invocation and
    returns an opaque payload (the last round's payload is kept on the
    result — callers stash event counts / digests there);
    ``prepare()`` is the untimed-region compile/warm leg (timed
    separately as the arm's ``compile_s``)."""

    name: str
    run: Callable[[], Any]
    prepare: Optional[Callable[[], Any]] = None
    meta: Any = None
    #: optional program-size record for this arm (the
    #: ``obs.program_size`` dict — eqns / jaxpr_bytes / ...), carried
    #: verbatim onto the result and report so compile cost rides next
    #: to wall time (docs/25_compile_wall.md)
    program_size: Optional[dict] = None


@dataclasses.dataclass
class ArmResult:
    name: str
    status: str                    # "ok" | "skipped"
    walls: List[float]
    best_wall: Optional[float]
    compile_s: Optional[float]
    payload: Any = None
    skip_reason: Optional[str] = None
    meta: Any = None
    program_size: Optional[dict] = None

    def rate(self, units: Optional[float]) -> Optional[float]:
        """``units / best_wall`` (events, replications, ... — the
        caller's unit), or None when unmeasured."""
        if units is None or not self.best_wall:
            return None
        return units / self.best_wall


@dataclasses.dataclass
class MeasureReport:
    """What :func:`measure_arms` returns: per-arm results in input
    order, the rounds actually completed, and the self-vs-self noise
    floor (relative rate fraction; None when ``noise_twin=False`` or
    the twin never completed a round)."""

    arms: List[ArmResult]
    baseline: str
    rounds_done: int
    noise_floor_frac: Optional[float]
    wall_s: float

    def arm(self, name: str) -> ArmResult:
        for a in self.arms:
            if a.name == name:
                return a
        raise KeyError(name)

    def beats_floor(self, challenger: str, units_of=None) -> bool:
        """True when ``challenger``'s best wall beats the baseline's by
        MORE than the noise floor (the search's win criterion —
        docs/21_autotune.md).  With no floor measured, any win counts
        (the caller opted out of the twin)."""
        base = self.arm(self.baseline)
        ch = self.arm(challenger)
        if base.best_wall is None or ch.best_wall is None:
            return False
        # rates compare inversely to walls; units cancel
        gain = base.best_wall / ch.best_wall - 1.0
        floor = self.noise_floor_frac or 0.0
        return gain > floor

    def to_json(self, units_of=None) -> dict:
        """A JSON-safe summary (payloads are reduced through
        ``units_of(payload) -> float|None`` when given)."""
        arms = []
        for a in self.arms:
            units = units_of(a.payload) if (
                units_of is not None and a.payload is not None
            ) else None
            arms.append({
                "name": a.name,
                "status": a.status,
                "walls_s": [round(w, 6) for w in a.walls],
                "best_wall_s": a.best_wall,
                "compile_s": a.compile_s,
                "units": units,
                "rate": a.rate(units),
                "skip_reason": a.skip_reason,
                "program_size": a.program_size,
            })
        return {
            "arms": arms,
            "baseline": self.baseline,
            "rounds_done": self.rounds_done,
            "noise_floor_frac": self.noise_floor_frac,
            "wall_s": self.wall_s,
        }


def measure_arms(
    arms,
    *,
    repeats: int = 3,
    baseline: int = 0,
    budget_s: Optional[float] = None,
    compile_budget_s: Optional[float] = None,
    noise_twin: bool = True,
    on_round: Optional[Callable[[int], None]] = None,
) -> MeasureReport:
    """Measure ``arms`` (a list of :class:`Arm`) interleaved
    best-of-``repeats`` at one operating point.  ``baseline`` indexes
    the incumbent arm (run twice per round when ``noise_twin`` — its
    twin's gap is the noise floor).  ``on_round(r)`` is the per-round
    progress hook (bench.py's watchdog heartbeat).  Budgets are wall
    seconds over the whole call; blowing one records SKIPPED arms /
    truncated rounds, never a silent drop."""
    arms = list(arms)
    if not arms:
        raise ValueError("measure_arms: no arms")
    if not 0 <= baseline < len(arms):
        raise ValueError(
            f"baseline index {baseline} out of range for {len(arms)} arms"
        )
    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    t_start = time.perf_counter()

    def spent() -> float:
        return time.perf_counter() - t_start

    results = [
        ArmResult(
            name=a.name, status=OK, walls=[], best_wall=None,
            compile_s=None, meta=a.meta, program_size=a.program_size,
        )
        for a in arms
    ]

    # -- prepare legs (the compile/run split): untimed-region, budgeted
    live: List[int] = []
    for i, arm in enumerate(arms):
        if budget_s is not None and spent() > budget_s and i != baseline:
            results[i].status = SKIPPED
            results[i].skip_reason = (
                f"wall budget ({budget_s:.1f}s) exhausted before prepare"
            )
            continue
        if arm.prepare is not None:
            # time inside try/finally: a prepare that RAISES (a caller
            # aborting a hung compile via its own timeout) still gets
            # its measured partial seconds attached to the skip record
            # — "slow compile" and "hung compile" must stay
            # distinguishable in the report (docs/25_compile_wall.md)
            t0 = time.perf_counter()
            try:
                arm.prepare()
            except Exception as e:
                results[i].compile_s = time.perf_counter() - t0
                if i == baseline:
                    raise
                results[i].status = SKIPPED
                results[i].skip_reason = (
                    f"prepare raised after {results[i].compile_s:.1f}s: "
                    f"{type(e).__name__}: {e}"
                )
                continue
            results[i].compile_s = time.perf_counter() - t0
            if (
                compile_budget_s is not None
                and results[i].compile_s > compile_budget_s
                and i != baseline
            ):
                results[i].status = SKIPPED
                results[i].skip_reason = (
                    f"compile {results[i].compile_s:.1f}s over the "
                    f"{compile_budget_s:.1f}s compile budget"
                )
                continue
        live.append(i)
    if baseline not in live:
        raise RuntimeError(
            "measure_arms: the baseline arm was skipped — there is no "
            "incumbent to race (raise the budgets)"
        )

    # -- interleaved rounds: [baseline twin?] + every live arm, in order
    twin_walls: List[float] = []
    rounds_done = 0
    for r in range(repeats):
        if budget_s is not None and rounds_done >= 1 and spent() > budget_s:
            break  # whole-round cut: every arm keeps equal rounds
        for i in live:
            t0 = time.perf_counter()
            payload = arms[i].run()
            wall = time.perf_counter() - t0
            results[i].walls.append(wall)
            results[i].payload = payload
            if i == baseline and noise_twin:
                t0 = time.perf_counter()
                arms[i].run()
                twin_walls.append(time.perf_counter() - t0)
        rounds_done += 1
        if on_round is not None:
            on_round(rounds_done)

    for i in live:
        res = results[i]
        if res.walls:
            res.best_wall = min(res.walls)
        elif res.status == OK:
            res.status = SKIPPED
            res.skip_reason = (
                f"wall budget ({budget_s:.1f}s) exhausted before any "
                "round"
            )

    floor = None
    base = results[baseline]
    if noise_twin and twin_walls and base.best_wall:
        tw = min(twin_walls)
        hi, lo = max(base.best_wall, tw), min(base.best_wall, tw)
        # relative RATE gap between two measurements of the same arm:
        # rate ~ 1/wall, so the gap is hi/lo - 1
        floor = hi / lo - 1.0

    return MeasureReport(
        arms=results,
        baseline=arms[baseline].name,
        rounds_done=rounds_done,
        noise_floor_frac=floor,
        wall_s=spent(),
    )
