"""The step-probe workload: the model whose default schedule is wrong.

BENCH_NOTES round 6 (tools/dispatch_cost_probe.py) measured the
hierarchical event-set losing on mutation-bursty timer workloads — the
per-mutation block refresh costs more than the saved scan when every
resume re-arms a burst of timers — while the shipped default leaves the
hierarchy ON.  This module packages that adversarial shape as a
searchable model (a big-table ticker: each resume re-arms
``per_resume`` timers spread over a large event table), so
``bench.py --config tune`` and the tune tests can demonstrate the
autotuner finding a real, noise-floor-clearing win over the default on
at least one shipped workload (the acceptance bar of
docs/21_autotune.md).

Unlike the raw ``make_step`` microprobe in tools/, this spec runs
through the ordinary chunked stream path (``t_end`` bounds the run),
so search arms are measured and bitwise-pinned by exactly the
machinery that serves production traffic.
"""

from __future__ import annotations

# module-level imports (the models/ convention): the block below must
# reference these as GLOBALS, not closure cells — a module object in a
# closure cell has no stable value digest, and the probe's whole point
# is exercising the persistent tuned-entry path (UnstableStoreKey
# would demote every search on it to unsaveable)
import jax.numpy as jnp

from cimba_tpu.core import api, cmd
from cimba_tpu.core.model import Model
from cimba_tpu.stats import summary as sm

__all__ = ["build", "params", "DEFAULT_T_END"]

#: default horizon: ~`t_end / hold` resumes per lane — enough steps for
#: the per-step cost difference to dominate dispatch overhead on the
#: CPU window while the schedule burst stays inside the event table
DEFAULT_T_END = 0.2


def build(event_cap: int = 2048, per_resume: int = 16,
          rearm_spread: int = 1793, hold: float = 0.002):
    """The mutation-bursty ticker spec: one process holding ``hold``
    per resume and re-arming ``per_resume`` far-future timers (spread
    over ``rearm_spread`` distinct times so pattern-cancel never
    collapses them) into an ``event_cap``-slot table — the ``sched``
    shape of tools/dispatch_cost_probe.py as a whole-Sim model.
    ``event_cap`` must hold the burst: with ``t_end`` T, a lane
    schedules ``~T/hold * per_resume`` timers (all far-future), so size
    the horizon accordingly.  Returns ``(spec, ())`` in the model
    builders' convention.  The probe records per-resume waits so the
    default ``summary_path`` works unchanged."""
    m = Model("tune_step_probe", n_ilocals=1, event_cap=event_cap)

    @m.user_state
    def user_init(params):
        return {"wait": sm.empty()}

    @m.block
    def tick(sim, p, sig):
        k = api.local_i(sim, p, 0)
        sim = api.add_local_i(sim, p, 0, 1)
        for i in range(per_resume):
            sim, _ = api.timer_add(
                sim, p,
                5.0 + ((k + i) % rearm_spread).astype(jnp.float32)
                * 0.003,
                0,
            )
        wait = sm.add(sim.user["wait"], api.clock(sim))
        sim = api.set_user(sim, {**sim.user, "wait": wait})
        return sim, cmd.hold(hold, next_pc=tick.pc)

    m.process("ticker", entry=tick)
    return m.build(), ()


def params(_n=None):
    """The probe takes no per-lane parameters (the model-builder
    convention's params hook; the workload knob is ``t_end``)."""
    return None
