"""The schedule space: typed axes over the existing dispatch knobs.

A :class:`Schedule` is a record of the knobs one run binds; a
:class:`ScheduleSpace` is the declarative grid of candidate schedules
the search measures.  Nothing here is new mechanism — every field maps
onto a knob the runner/serve/sweep entry points already accept
(``pack=``, ``chunk_steps=``, ``wave_size=``) or a trace-time tri-state
``cimba_tpu.config`` already exposes (``EVENTSET_HIER`` /
``EVENTSET_BLOCK`` / ``XLA_PACK`` — the ``bench.py _dispatch_arm``
idiom, made a first-class object).  Schedules never change results,
only speed:

* ``eventset_hier`` / ``eventset_block`` — bitwise the flat scan's
  pick (tests/test_eventset_hier.py);
* ``pack`` — trajectory-identical carry layout (tests/test_xla_pack.py);
* ``chunk_steps`` — chunked trajectories ARE the monolithic ones
  bitwise, and folds happen per wave, not per chunk (docs/12);
* ``wave_size`` — per-lane trajectories and the exact counters are
  identical; the pooled Pébay summary may differ in merge-ORDER
  rounding (docs/12), which is why the search pins each candidate
  against a default-knob twin at the candidate's OWN geometry
  (:mod:`cimba_tpu.tune.search`);
* ``lane_block`` — the Pallas kernel grid (``CIMBA_KERNEL_LANE_BLOCK``),
  only meaningful where the kernel path is live;
* ``table_scan`` / ``table_block`` — scan-over-rows process-table
  dispatch (docs/25_compile_wall.md): bitwise the dense access
  (tests/test_table_scan.py), trades per-access work for O(1)-in-P
  program text — a compile-time/run-time dial, not a results knob.

Validity predicates prune instead of measuring: the hierarchical
event-set is structurally inert whenever the model's event capacity is
not a >= 2x multiple of the block size (the PR 2 inertness contract) —
for such a model every ``eventset_hier``/``eventset_block`` setting
traces the SAME program, so :meth:`ScheduleSpace.candidates`
canonicalizes those axes away rather than timing identical arms.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
from typing import Optional, Tuple

__all__ = ["Schedule", "ScheduleSpace", "default_space"]

#: schema version of the persisted schedule record — bump on field
#: changes so stale tuned entries invalidate loudly instead of
#: resolving garbage knobs.  2: PR 17 added the device-scheduler knobs
#: (``waves_per_device`` / ``preempt_quantum`` / ``mem_fraction``).
#: 3: the table-scan dispatch knobs (``table_scan`` / ``table_block``
#: — docs/25_compile_wall.md).  4: the wave-fusion knobs (``fuse`` /
#: ``fuse_max_specs`` — docs/26_wave_fusion.md).
SCHEDULE_FORMAT = 4

#: the knob fields, in canonical order (the JSON/digest field set)
_FIELDS = (
    "eventset_hier", "eventset_block", "pack",
    "chunk_steps", "wave_size", "lane_block",
    "table_scan", "table_block",
    "waves_per_device", "preempt_quantum", "mem_fraction",
    "fuse", "fuse_max_specs",
)

#: device-scheduler knob defaults (docs/24_device_scheduler.md) — ONE
#: definition: ``serve.Service`` resolves its ``None`` constructor
#: values against these, and :meth:`Schedule.canonical` collapses
#: explicit settings equal to them (an arm binding the default is the
#: default arm — prune, don't measure)
DEFAULT_WAVES_PER_DEVICE = 2
DEFAULT_PREEMPT_QUANTUM = 8
DEFAULT_MEM_FRACTION = 0.8

#: wave-fusion roster cap default (docs/26_wave_fusion.md) — the same
#: ONE-definition rule: ``serve.Service`` resolves ``fuse_max_specs=
#: None`` against this, and :meth:`Schedule.canonical` collapses an
#: explicit equal setting.  4 keeps the fused superprogram's size
#: growth comfortably under the JXL004 sublinearity budget.
DEFAULT_FUSE_MAX_SPECS = 4

#: schedule fields that change the *geometry* of a run (wave partition
#: / chunk boundaries) rather than the traced step program — the
#: search pins these against a default-knob twin at the same geometry,
#: and ``tools/audit_diff.py`` treats drift in the bitwise-invariant
#: ones as env drift, not divergence
GEOMETRY_FIELDS = ("chunk_steps", "wave_size")


@dataclasses.dataclass(frozen=True)
class Schedule:
    """One point of the schedule space.  ``None`` per field means
    "leave that knob at its ambient default" — so ``Schedule()`` IS
    the default schedule, and a resolved/tuned schedule only ever
    overrides the knobs it was actually searched over.

    Delivery is two-channel, matching how the knobs already bind:

    * :meth:`stream_kwargs` — the argument knobs (``pack``,
      ``chunk_steps``, ``wave_size``) as kwargs for
      ``run_experiment_stream`` / ``Request`` / ``run_sweep``;
    * :meth:`scope` — the trace-time knobs (event-set layout, the
      ambient pack default, the kernel lane block) as a context
      manager over the ``cimba_tpu.config`` tri-states, restoring the
      previous state on exit (the ``_dispatch_arm`` idiom).
    """

    eventset_hier: Optional[bool] = None
    eventset_block: Optional[int] = None
    pack: Optional[bool] = None
    chunk_steps: Optional[int] = None
    wave_size: Optional[int] = None
    lane_block: Optional[int] = None
    # table-scan dispatch knobs (docs/25_compile_wall.md): scan-over-
    # rows process-table access on/off plus the row-block size.  Trace-
    # time, results bitwise either way (tests/test_table_scan.py) —
    # pure program-size/compile-time trade
    table_scan: Optional[bool] = None
    table_block: Optional[int] = None
    # device-scheduler policy knobs (docs/24_device_scheduler.md):
    # concurrent waves per device, the preemption quantum (chunks
    # between preemption points), and the device-memory admission
    # fraction.  Host-side dispatch policy only — results are bitwise
    # whatever these bind — consumed by serve.Service when its own
    # constructor knobs are left None.
    waves_per_device: Optional[int] = None
    preempt_quantum: Optional[int] = None
    mem_fraction: Optional[float] = None
    # wave-fusion policy knobs (docs/26_wave_fusion.md): cross-spec
    # fused-wave packing on/off plus the per-class member roster cap.
    # Host-side packing policy consumed by serve.Service when its own
    # constructor knobs are left None — member lanes are bitwise their
    # solo runs either way; the searched trade is occupancy versus
    # fused-program size (obs/program_size.py prices it)
    fuse: Optional[bool] = None
    fuse_max_specs: Optional[int] = None

    def knobs(self) -> dict:
        """The non-default fields only (what this schedule binds)."""
        out = {}
        for f in _FIELDS:
            v = getattr(self, f)
            if v is not None:
                out[f] = v
        return out

    def is_default(self) -> bool:
        return not self.knobs()

    def stream_kwargs(self) -> dict:
        """The argument-knob subset: kwargs every stream-shaped entry
        point accepts (only knobs this schedule binds appear)."""
        out = {}
        if self.pack is not None:
            out["pack"] = bool(self.pack)
        if self.chunk_steps is not None:
            out["chunk_steps"] = int(self.chunk_steps)
        if self.wave_size is not None:
            out["wave_size"] = int(self.wave_size)
        return out

    @contextlib.contextmanager
    def scope(self):
        """Bind the trace-time knobs for the duration: the
        ``config.EVENTSET_HIER`` / ``EVENTSET_BLOCK`` / ``XLA_PACK`` /
        ``TABLE_SCAN`` / ``TABLE_SCAN_BLOCK`` tri-states (set only for
        the fields this schedule carries)
        plus ``CIMBA_KERNEL_LANE_BLOCK`` for the kernel grid.  Restores
        the previous state on exit.  Like the dtype profile, these bind
        at TRACE time: programs already compiled keep their layout, and
        the serve/stream program keys resolve the state at key-build
        time so a scope switch misses the cache rather than replaying a
        stale arm (docs/11_dispatch_cost.md)."""
        import os

        from cimba_tpu import config

        prev = (config.EVENTSET_HIER, config.EVENTSET_BLOCK,
                config.XLA_PACK, config.TABLE_SCAN,
                config.TABLE_SCAN_BLOCK)
        # the lane-block knob has no config tri-state — its documented
        # binding point IS the env var (core/pallas_run.py reads it via
        # env_raw), so this scope writes/restores the var itself; the
        # suppressions below mark the one sanctioned non-env_raw site
        prev_lane = os.environ.get("CIMBA_KERNEL_LANE_BLOCK")  # cimba: noqa(CHK005) — save/restore, not a knob read
        try:
            if self.eventset_hier is not None:
                config.EVENTSET_HIER = bool(self.eventset_hier)
            if self.eventset_block is not None:
                config.EVENTSET_BLOCK = int(self.eventset_block)
            if self.pack is not None:
                config.XLA_PACK = bool(self.pack)
            if self.table_scan is not None:
                config.TABLE_SCAN = bool(self.table_scan)
            if self.table_block is not None:
                config.TABLE_SCAN_BLOCK = int(self.table_block)
            if self.lane_block is not None:
                os.environ["CIMBA_KERNEL_LANE_BLOCK"] = str(  # cimba: noqa(CHK005) — the binding site
                    int(self.lane_block)
                )
            yield self
        finally:
            (config.EVENTSET_HIER, config.EVENTSET_BLOCK,
             config.XLA_PACK, config.TABLE_SCAN,
             config.TABLE_SCAN_BLOCK) = prev
            if self.lane_block is not None:
                if prev_lane is None:
                    os.environ.pop("CIMBA_KERNEL_LANE_BLOCK", None)
                else:
                    os.environ["CIMBA_KERNEL_LANE_BLOCK"] = prev_lane  # cimba: noqa(CHK005) — restore

    def canonical(self, spec=None) -> "Schedule":
        """The structurally-effective form of this schedule for
        ``spec``: knobs that cannot change the traced program collapse
        to their default, so two candidates that would trace identical
        programs compare equal and the search never times both
        (prune, don't measure — docs/21_autotune.md).  Rules:

        * a knob explicitly set to what the ambient default already
          resolves to (hier=True under the default-on env, pack
          matching this backend's auto, the default block size, the
          entry points' ``chunk_steps=1024``) is the default arm;
        * ``eventset_block`` is dead when the hierarchy resolves off;
        * the PR 2 inertness contract: the hierarchy is structurally
          inert unless ``event_cap`` is a >= 2x multiple of the block
          size — below that, both event-set knobs are dead for this
          ``spec``;
        * ``table_block`` is dead when the table scan resolves off,
          and both table-scan knobs are dead when no dyn-accessed
          table axis of ``spec`` exceeds the effective block (the
          core/dyn.py small-P inertness contract: a block covering the
          whole axis traces the dense program character-identically).
        """
        from cimba_tpu import config

        hier, block = self.eventset_hier, self.eventset_block
        pack, chunk = self.pack, self.chunk_steps
        if pack is not None and bool(pack) == config.xla_pack_enabled():
            pack = None
        if chunk is not None and int(chunk) == 1024:
            chunk = None
        if hier is not None and (
            bool(hier) == config.eventset_hier_enabled()
        ):
            hier = None
        if block is not None and (
            int(block) == config.eventset_block()
        ):
            block = None
        eff_hier = (
            bool(hier) if hier is not None
            else config.eventset_hier_enabled()
        )
        if not eff_hier:
            block = None
        if spec is not None:
            cap = int(getattr(spec, "event_cap", 0) or 0)
            eff_block = (
                int(block) if block is not None
                else config.eventset_block()
            )
            # the hierarchy only materializes summary rows when the
            # cap holds at least two full blocks (core/eventset.py) —
            # below that every hier/block setting traces the flat
            # program
            if cap < 2 * eff_block:
                hier, block = None, None
        tscan, tblock = self.table_scan, self.table_block
        if tscan is not None and (
            bool(tscan) == config.table_scan_enabled()
        ):
            tscan = None
        if tblock is not None and (
            int(tblock) == config.table_scan_block()
        ):
            tblock = None
        eff_tscan = (
            bool(tscan) if tscan is not None
            else config.table_scan_enabled()
        )
        if not eff_tscan:
            tblock = None
        if spec is not None and eff_tscan:
            eff_tblock = (
                int(tblock) if tblock is not None
                else config.table_scan_block()
            )
            # the tallest axis core/dyn.py can row-block for this
            # spec: process tables [P], queue/pqueue rings, guard
            # slots — the scan only engages when an axis exceeds the
            # block, so below that every setting traces dense
            tallest = max(
                len(spec.proc_entry),
                int(getattr(spec, "queue_cap_max", 0) or 0),
                int(getattr(spec, "pqueue_cap_max", 0) or 0),
                int(getattr(spec, "guard_cap", 0) or 0),
            )
            if tallest <= eff_tblock:
                tscan, tblock = None, None
        # device-scheduler knobs: an arm binding the stock default IS
        # the default arm (host-side policy; never traced)
        wpd, quantum, memf = (
            self.waves_per_device, self.preempt_quantum,
            self.mem_fraction,
        )
        if wpd is not None and int(wpd) == DEFAULT_WAVES_PER_DEVICE:
            wpd = None
        if quantum is not None and (
            int(quantum) == DEFAULT_PREEMPT_QUANTUM
        ):
            quantum = None
        if memf is not None and float(memf) == DEFAULT_MEM_FRACTION:
            memf = None
        # wave-fusion knobs: fusion defaults OFF (the CIMBA_WAVE_FUSE
        # ambient default), so an explicit fuse=False is the default
        # arm; the roster cap is dead when fusion resolves off, and
        # the stock cap is the default arm when it resolves on
        fuse, fmax = self.fuse, self.fuse_max_specs
        if fuse is not None and not bool(fuse):
            fuse = None
        if fuse is None:
            fmax = None
        elif fmax is not None and int(fmax) == DEFAULT_FUSE_MAX_SPECS:
            fmax = None
        return dataclasses.replace(
            self, eventset_hier=hier, eventset_block=block,
            pack=pack, chunk_steps=chunk, table_scan=tscan,
            table_block=tblock, waves_per_device=wpd,
            preempt_quantum=quantum, mem_fraction=memf,
            fuse=fuse, fuse_max_specs=fmax,
        )

    # -- persistence ---------------------------------------------------------

    def to_json(self) -> dict:
        out = {"format": SCHEDULE_FORMAT}
        out.update({f: getattr(self, f) for f in _FIELDS})
        return out

    @classmethod
    def from_json(cls, doc: dict) -> "Schedule":
        if doc.get("format") != SCHEDULE_FORMAT:
            raise ValueError(
                f"schedule record format {doc.get('format')!r} != "
                f"{SCHEDULE_FORMAT} — stale tuned entry (re-run the "
                "search)"
            )
        kw = {}
        for f in _FIELDS:
            v = doc.get(f)
            if v is not None:
                if f in ("eventset_hier", "pack", "table_scan",
                         "fuse"):
                    v = bool(v)
                elif f == "mem_fraction":
                    v = float(v)
                else:
                    v = int(v)
            kw[f] = v
        return cls(**kw)

    # cimba-check: content-path
    def digest(self) -> str:
        """sha256 hex of the canonical JSON — how run cards and the
        store manifest cite one schedule by value."""
        return hashlib.sha256(
            json.dumps(self.to_json(), sort_keys=True).encode("utf-8")
        ).hexdigest()

    def label(self) -> str:
        """A short human arm name: ``default`` or the bound knobs."""
        k = self.knobs()
        if not k:
            return "default"
        return ",".join(f"{n}={v}" for n, v in sorted(k.items()))


@dataclasses.dataclass(frozen=True)
class ScheduleSpace:
    """The declarative candidate grid: per-knob value tuples (empty =
    the knob is not searched and stays default everywhere).  Axis
    values of ``None`` inside a tuple mean "the default arm of that
    knob" — every space implicitly contains the all-default schedule
    even when no axis lists ``None``."""

    eventset_hier: Tuple = ()
    eventset_block: Tuple = ()
    pack: Tuple = ()
    chunk_steps: Tuple = ()
    wave_size: Tuple = ()
    lane_block: Tuple = ()
    table_scan: Tuple = ()
    table_block: Tuple = ()
    waves_per_device: Tuple = ()
    preempt_quantum: Tuple = ()
    mem_fraction: Tuple = ()
    fuse: Tuple = ()
    fuse_max_specs: Tuple = ()

    def axes(self) -> dict:
        """The non-empty axes, name -> value tuple."""
        out = {}
        for f in _FIELDS:
            vals = tuple(getattr(self, f))
            if vals:
                out[f] = vals
        return out

    def candidates(self, spec=None) -> list:
        """Every valid, structurally-distinct :class:`Schedule` of the
        grid, default first.  Each axis is augmented with the default
        arm (``None``), the cartesian product is canonicalized against
        ``spec`` (inert knob settings collapse — prune, don't
        measure), and duplicates are dropped keeping first-seen
        order."""
        import itertools

        axes = self.axes()
        names = list(axes)
        pools = [
            (None,) + tuple(v for v in axes[n] if v is not None)
            for n in names
        ]
        seen = set()
        out = []
        # the default schedule always leads: it is the incumbent every
        # candidate is pinned and raced against
        for values in itertools.product(*pools) if names else [()]:
            sched = Schedule(**dict(zip(names, values)))
            canon = sched.canonical(spec)
            key = tuple(
                getattr(canon, f) for f in _FIELDS
            )
            if key in seen:
                continue
            seen.add(key)
            out.append(canon)
        if not out or not out[0].is_default():
            out.insert(0, Schedule())
        return out


def default_space(
    spec=None, *, kernel: bool = False, device_sched: bool = False,
    fuse: bool = False,
) -> ScheduleSpace:
    """The stock search space over the dispatch knobs of
    docs/11_dispatch_cost.md: hierarchical event-set on/off with a
    pow2 block grid, packed carry on/off, and a small ``chunk_steps``
    grid around the entry points' default.  ``wave_size`` is not
    searched by default (its pooled summary is merge-order-sensitive —
    opt in explicitly when counts-exact statistics are what you
    serve); ``lane_block`` joins only with ``kernel=True`` (the Pallas
    path); the device-scheduler policy knobs (``waves_per_device``,
    ``preempt_quantum`` — docs/24_device_scheduler.md) join only with
    ``device_sched=True``, since they are inert outside a
    ``CIMBA_DEVICE_SCHED`` serve loop (``mem_fraction`` joins them —
    the admission fraction is only live under the scheduler); the
    wave-fusion pair (``fuse`` / ``fuse_max_specs`` —
    docs/26_wave_fusion.md) joins only with ``fuse=True``, since
    fusion is inert on single-spec workloads.  The table-scan pair
    (docs/25_compile_wall.md) is always in the grid — for small-table
    models every setting collapses to the default arm, so it only
    costs candidates where a table actually exceeds a block.  Axes
    that are structurally inert for ``spec`` cost nothing:
    :meth:`ScheduleSpace.candidates` collapses them."""
    space = ScheduleSpace(
        eventset_hier=(True, False),
        eventset_block=(64, 128, 256),
        pack=(True, False),
        chunk_steps=(256, 1024, 4096),
        table_scan=(True, False),
        table_block=(64, 128, 256),
        lane_block=(8, 16, 32) if kernel else (),
        waves_per_device=(1, 2, 4) if device_sched else (),
        preempt_quantum=(4, 8, 16) if device_sched else (),
        mem_fraction=(0.6, 0.8) if device_sched else (),
        fuse=(True, False) if fuse else (),
        fuse_max_specs=(2, 4, 8) if fuse else (),
    )
    return space
