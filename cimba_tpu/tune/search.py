"""Budgeted search over a schedule space, emitting a ``TuneReport``.

The search races every structurally-distinct candidate of a
:class:`~cimba_tpu.tune.space.ScheduleSpace` against the default
schedule at ONE operating point (the caller's (spec, params, R) —
schedules are per-workload, which is the whole reason they are
searched, docs/21_autotune.md), through the real
``run_experiment_stream`` entry point:

* **exhaustive** when the grid fits the wall budget (the common case —
  canonicalization already collapsed inert knobs);
* **successive halving** otherwise: one interleaved pilot round over
  the live set, drop the slowest half (the incumbent default is never
  dropped), repeat until the survivors x ``repeats`` fit, then a full
  :func:`~cimba_tpu.tune.measure.measure_arms` pass with the
  self-vs-self noise twin.  Every eliminated/skipped arm stays in the
  report with its measured walls — nothing is silently dropped.

**Bitwise pinning**: a candidate is eligible to win only if its
result digest equals the default schedule's at the candidate's own
wave geometry — dispatch knobs (event-set layout, packed carry) and
``chunk_steps`` are bitwise-invariant, so same-``wave_size`` arms must
reproduce the baseline digest exactly; a candidate that changes
``wave_size`` is pinned against an untimed default-knob twin at that
``wave_size`` (the pooled summary's merge order legitimately follows
the wave partition, docs/12_streaming.md).  A pin failure is a
determinism bug somewhere and raises by default (``strict_pin``).

**Decision**: the best pinned challenger must beat the default by more
than the measured noise floor (plus ``min_gain``) or the report HOLDs
the default — a tuned entry is only ever written for a win the machine
could actually distinguish from its own jitter.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
import warnings
from typing import Callable, List, Optional

from cimba_tpu.tune import measure as _measure
from cimba_tpu.tune.space import Schedule, ScheduleSpace, default_space

__all__ = ["TuneReport", "search_schedule", "write_report", "load_report"]

#: TuneReport schema version
REPORT_FORMAT = 1


class SchedulePinError(RuntimeError):
    """A candidate schedule's result diverged bitwise from the default
    schedule's — schedules must never change results; this is a
    determinism bug, not a slow arm."""


@dataclasses.dataclass
class TuneReport:
    """One search's full record: every arm (times, status, digest,
    pinned), the noise floor, the winner, and provenance — the JSON
    artifact ``tools/bench_history.py --tune`` collates."""

    spec_name: str
    spec_fingerprint: Optional[str]   # sha256 of the stable fingerprint
    backend: str
    device_kind: str
    bucket: int                       # workload bucket (pow2 of R)
    workload: dict
    space: dict
    arms: List[dict]
    baseline: str
    noise_floor_frac: Optional[float]
    winner: Schedule
    winner_name: str
    decision: str                     # "tuned" | "hold"
    speedup_frac: float               # winner rate gain over default
    env: dict
    created_unix: float
    wall_s: float

    def to_json(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["format"] = REPORT_FORMAT
        doc["winner"] = self.winner.to_json()
        doc["report_digest"] = self.digest()
        return doc

    # cimba-check: content-path
    def digest(self) -> str:
        """Content digest (sha256) excluding the creation timestamp —
        two identical searches on one machine digest identically (the
        run-card discipline, docs/18_audit.md)."""
        doc = dataclasses.asdict(self)
        doc["winner"] = self.winner.to_json()
        doc.pop("created_unix", None)
        doc.pop("wall_s", None)
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True, default=str).encode("utf-8")
        ).hexdigest()


def write_report(report: TuneReport, out_dir) -> str:
    """Write a report content-addressed (``tunereport_<digest16>.json``),
    crash-atomic (tmp + rename — the run-card discipline)."""
    out_dir = str(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    doc = report.to_json()
    path = os.path.join(
        out_dir, f"tunereport_{doc['report_digest'][:16]}.json"
    )
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True, default=str)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_report(path) -> dict:
    """Load one TuneReport JSON with a loud error naming the file."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("format") != REPORT_FORMAT:
        raise ValueError(
            f"{path}: not a TuneReport (format "
            f"{doc.get('format') if isinstance(doc, dict) else '?'} != "
            f"{REPORT_FORMAT})"
        )
    return doc


def _block_result(st):
    """Block on every result leaf — the timing anchor (async dispatch
    must not leak out of a timed region)."""
    import jax

    jax.block_until_ready(
        jax.tree.leaves((st.summary, st.n_failed, st.total_events))
    )
    return st


def search_schedule(
    spec,
    params,
    n_replications: int,
    *,
    space: Optional[ScheduleSpace] = None,
    candidates: Optional[list] = None,
    wave_size: Optional[int] = None,
    seed: int = 2026,
    t_end: Optional[float] = None,
    mesh=None,
    summary_path=None,
    warm_params=None,
    repeats: int = 2,
    budget_s: Optional[float] = None,
    compile_budget_s: Optional[float] = None,
    min_gain: float = 0.0,
    strict_pin: bool = True,
    probe_program_size: bool = True,
    program_cache=None,
    out_dir=None,
    on_round: Optional[Callable[[int], None]] = None,
    workload_label: Optional[str] = None,
    runner: Optional[Callable] = None,
) -> TuneReport:
    """Search the schedule space for ``(spec, params, R)`` and return a
    :class:`TuneReport` (written to ``out_dir`` when given).  The
    default arm is always measured (it is the incumbent and the noise
    twin); ``warm_params`` (e.g. the model's tiny-workload params)
    warms each arm's compiled shapes outside the timed rounds.  The
    report's winner is only persisted by the caller
    (:func:`cimba_tpu.tune.registry.save_tuned`) — searching and
    adopting are separate decisions.

    ``runner(schedule, warm=...)`` replaces the direct stream call as
    the measured workload — the hook serve-backed searches use for
    knobs the direct path never exercises (``waves_per_device`` /
    ``preempt_quantum`` / ``mem_fraction`` / ``fuse``, which live in
    the Service dispatcher, not the chunk program).  It must return a
    StreamResult-shaped payload (``summary``/``n_failed``/
    ``total_events``/``metrics`` — tuples of per-request results are
    fine; the digest walks leaves), deterministic for a given
    schedule so the bitwise pin holds across arms."""
    import jax

    from cimba_tpu.obs import audit as _audit
    from cimba_tpu.runner import experiment as ex
    from cimba_tpu.serve import cache as _pcache
    from cimba_tpu.serve import store as _pstore
    from cimba_tpu.tune import registry as _registry

    R = int(n_replications)
    if candidates is None:
        if space is None:
            space = default_space(spec)
        candidates = space.candidates(spec)
    else:
        # canonicalize + dedup explicit candidates too: structurally
        # inert knob settings collapse (prune, don't measure)
        from cimba_tpu.tune.space import _FIELDS as _SCHED_FIELDS

        canon, seen = [], set()
        for c in candidates:
            cc = c.canonical(spec)
            key = tuple(getattr(cc, f) for f in _SCHED_FIELDS)
            if key not in seen:
                seen.add(key)
                canon.append(cc)
        candidates = [Schedule()] + [
            c for c in canon if not c.is_default()
        ]
    space_doc = space.axes() if space is not None else {
        "explicit": [c.label() for c in candidates]
    }
    cache = (
        program_cache if program_cache is not None
        else _pcache.ProgramCache(capacity=max(64, 4 * len(candidates)))
    )
    base_wave = R if wave_size is None else int(wave_size)

    def eff_wave(sched: Schedule) -> int:
        return int(sched.wave_size) if sched.wave_size is not None \
            else base_wave

    def run_point(sched: Schedule, warm: bool):
        if runner is not None:
            return _block_result(runner(sched, warm=warm))
        p = warm_params if (warm and warm_params is not None) else params
        st = ex.run_experiment_stream(
            spec, p, R,
            wave_size=eff_wave(sched),
            seed=seed, t_end=t_end, mesh=mesh,
            summary_path=(
                summary_path if summary_path is not None
                else ex.default_summary_path
            ),
            program_cache=cache,
            schedule=sched,   # explicit: the registry is never consulted
        )
        return _block_result(st)

    def probe_size(sched: Schedule) -> Optional[dict]:
        """Trace-only program-size probe under the arm's trace-time
        scope (docs/25_compile_wall.md) — eqn count / jaxpr bytes next
        to each arm's wall numbers, so a compile-budget skip is
        measured data, not a silent cut.  Never compiles; a model the
        probe can't trace (exotic spec) degrades to None, not a
        failed search."""
        if not probe_program_size:
            return None
        from cimba_tpu.obs import program_size as _ps

        try:
            with sched.scope():
                return _ps.chunk_program_size(
                    spec, params, lanes=4, lower=False,
                ).to_dict()
        except Exception:
            return None

    def make_arm(sched: Schedule) -> _measure.Arm:
        name = sched.label()

        def prepare(sched=sched):
            run_point(sched, warm=True)
            if warm_params is None:
                return
            # warm at the REAL workload too when a cheap warm ran
            # first: jit specializes per shape, and params shapes are
            # identical either way, so this is usually a cache hit
            run_point(sched, warm=False)

        def run(sched=sched):
            return run_point(sched, warm=False)

        return _measure.Arm(name=name, run=run, prepare=prepare,
                            meta=sched, program_size=probe_size(sched))

    arms = [make_arm(c) for c in candidates]
    psizes = {a.name: a.program_size for a in arms}
    by_name = {c.label(): c for c in candidates}
    t0 = time.perf_counter()

    # -- successive halving when the grid x budget doesn't fit ---------------
    stage_rows: dict = {}   # name -> {"stage_walls": [...], "status": ...}
    for a in arms:
        stage_rows[a.name] = {"stage_walls": [], "stages": 0}
    live = arms
    stage = 0
    final_rep = None
    while True:
        remaining = (
            None if budget_s is None
            else budget_s - (time.perf_counter() - t0)
        )
        last_round = None
        if stage:
            walls = [
                stage_rows[a.name]["stage_walls"][-1]
                for a in live if stage_rows[a.name]["stage_walls"]
            ]
            last_round = sum(walls) if walls else None
        fits = (
            budget_s is None
            or len(live) <= 2
            or (
                stage > 0 and last_round is not None
                and last_round * (repeats + 1) <= (remaining or 0.0)
            )
        )
        if fits:
            final_rep = _measure.measure_arms(
                live, repeats=repeats, baseline=0,
                budget_s=remaining, noise_twin=True,
                compile_budget_s=compile_budget_s if stage == 0 else None,
                on_round=on_round,
            )
            break
        pilot = _measure.measure_arms(
            live, repeats=1, baseline=0, budget_s=remaining,
            noise_twin=False,
            compile_budget_s=compile_budget_s if stage == 0 else None,
            on_round=on_round,
        )
        ranked = []
        for res in pilot.arms:
            row = stage_rows[res.name]
            row["stage_walls"].extend(res.walls)
            row["stages"] += 1
            if res.status == _measure.SKIPPED:
                row["status"] = "skipped"
                row["skip_reason"] = res.skip_reason
            elif res.best_wall is not None:
                ranked.append((res.best_wall, res.name))
        ranked.sort()
        remaining = (
            None if budget_s is None
            else budget_s - (time.perf_counter() - t0)
        )
        full_round = sum(w for w, _ in ranked)
        if (
            remaining is None
            or full_round * (repeats + 1) <= remaining
            or len(ranked) <= 2
        ):
            # the pilot proved the whole grid fits the budget after
            # all (compiles dominated the estimate): keep every arm
            survivors = {name for _, name in ranked}
        else:
            keep = max(2, (len(ranked) + 1) // 2)
            survivors = {name for _, name in ranked[:keep]}
        survivors.add(arms[0].name)   # the incumbent is never dropped
        for res in pilot.arms:
            if res.name not in survivors and res.status == _measure.OK:
                stage_rows[res.name]["status"] = "eliminated"
        # prepares already ran in stage 0 — don't re-pay them per stage
        live = [
            dataclasses.replace(a, prepare=None)
            for a in live if a.name in survivors
        ]
        live.sort(key=lambda a: 0 if a.name == arms[0].name else 1)
        stage += 1

    # -- bitwise pinning -----------------------------------------------------
    base_res = final_rep.arm(arms[0].name)
    base_payload = base_res.payload
    if base_payload is None:
        raise RuntimeError(
            "tune.search: the default schedule never completed a "
            "measured round — raise the budget"
        )
    pin_digests = {
        base_wave: _audit.stream_result_digest(base_payload)
    }

    def pin_digest_for(w: int) -> str:
        if w not in pin_digests:
            # untimed default-knob twin at this wave geometry: the
            # merge order follows the wave partition, so the bitwise
            # reference must share it
            if runner is not None:
                pin_digests[w] = _audit.stream_result_digest(
                    _block_result(
                        runner(Schedule(wave_size=w), warm=False)
                    )
                )
                return pin_digests[w]
            st = ex.run_experiment_stream(
                spec, params, R, wave_size=w, seed=seed, t_end=t_end,
                mesh=mesh,
                summary_path=(
                    summary_path if summary_path is not None
                    else ex.default_summary_path
                ),
                program_cache=cache,
                schedule=Schedule(wave_size=w),
            )
            pin_digests[w] = _audit.stream_result_digest(
                _block_result(st)
            )
        return pin_digests[w]

    rows: List[dict] = []
    rates: dict = {}
    for cand in candidates:
        name = cand.label()
        srow = stage_rows[name]
        row = {
            "name": name,
            "schedule": cand.to_json(),
            "stage_walls_s": [round(w, 6) for w in srow["stage_walls"]],
            "status": srow.get("status", "ok"),
            "skip_reason": srow.get("skip_reason"),
            "walls_s": [],
            "best_wall_s": None,
            "compile_s": None,
            "program_size": psizes.get(name),
            "events": None,
            "rate": None,
            "digest": None,
            "pinned": None,
        }
        try:
            res = final_rep.arm(name)
        except KeyError:
            res = None
        if res is not None:
            row["walls_s"] = [round(w, 6) for w in res.walls]
            row["best_wall_s"] = res.best_wall
            row["compile_s"] = res.compile_s
            if res.status == _measure.SKIPPED:
                row["status"] = "skipped"
                row["skip_reason"] = res.skip_reason
            elif res.payload is not None:
                events = int(res.payload.total_events)
                dig = _audit.stream_result_digest(res.payload)
                row["events"] = events
                row["digest"] = dig
                pinned = dig == pin_digest_for(eff_wave(cand))
                row["pinned"] = pinned
                if not pinned:
                    row["status"] = "mismatch"
                    msg = (
                        f"tune.search: arm {name!r} diverged bitwise "
                        f"from the default schedule at wave_size="
                        f"{eff_wave(cand)} — schedules must never "
                        "change results"
                    )
                    if strict_pin:
                        raise SchedulePinError(msg)
                    warnings.warn(msg, RuntimeWarning)
                elif res.best_wall:
                    row["rate"] = events / res.best_wall
                    rates[name] = row["rate"]
        rows.append(row)

    # -- decision ------------------------------------------------------------
    base_name = arms[0].name
    base_rate = rates.get(base_name)
    floor = final_rep.noise_floor_frac
    winner_name, decision, speedup = base_name, "hold", 0.0
    if base_rate:
        best_name = max(rates, key=lambda n: rates[n])
        gain = rates[best_name] / base_rate - 1.0
        if (
            best_name != base_name
            and gain > (floor or 0.0) + float(min_gain)
        ):
            winner_name, decision, speedup = best_name, "tuned", gain
        else:
            speedup = max(gain, 0.0) if best_name != base_name else 0.0
    winner = by_name[winner_name]

    try:
        fp = hashlib.sha256(
            repr(_pstore.stable_spec_fingerprint(spec)).encode("utf-8")
        ).hexdigest()
    except _pstore.UnstableStoreKey:
        fp = None
    dev = jax.devices()[0]
    workload = {
        "R": R,
        "wave_size": base_wave,
        "t_end": t_end,
        "seed": int(seed),
        "label": workload_label,
    }
    report = TuneReport(
        spec_name=getattr(spec, "name", "?"),
        spec_fingerprint=fp,
        backend=jax.default_backend(),
        device_kind=getattr(dev, "device_kind", "?"),
        bucket=_registry.workload_bucket(R),
        workload=workload,
        space={k: list(v) for k, v in space_doc.items()},
        arms=rows,
        baseline=base_name,
        noise_floor_frac=floor,
        winner=winner,
        winner_name=winner_name,
        decision=decision,
        speedup_frac=speedup,
        env=_audit.environment(),
        created_unix=time.time(),
        wall_s=time.perf_counter() - t0,
    )
    if out_dir:
        write_report(report, out_dir)
    return report
