"""Checkpoint/resume of batched experiment state.

The reference has **no checkpointing** (SURVEY.md §5: trials are short and
restartable); long pod-scale experiments need it, so this is new
capability.  It falls out of the architecture: a replication's complete
state is one pytree (including the counter-based RNG position), so
``save``/``restore`` round-trips the whole batch and ``make_run`` simply
continues — resumed runs are bit-identical to uninterrupted ones (tested).

Format: a flat numpy ``.npz`` of the pytree leaves with an atomic rename —
deliberately dependency-free (the state is a modest pytree of dense arrays;
an async/sharded checkpoint stack like orbax buys nothing at this size and
would be the only non-jax dependency in the hot path).

Restore validates **every leaf's shape and dtype** against ``like`` and
the saved **spec fingerprint** (leaf shapes/dtypes at save time, plus an
optional caller tag such as capacities/profile): a capacity regrow, dtype
profile switch, or model edit between save and restore fails loudly with
the first mismatching leaf named — never as downstream shape garbage.
"""

from __future__ import annotations

# cimba-check: persist-path  (CHK001: checkpoints are disk artifacts —
# the saved fingerprint must be value-based, never id()-derived)

import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

#: bumped when the on-disk layout changes
_FORMAT = 1


def _flatten(tree: Any):
    leaves, treedef = jax.tree.flatten(tree)
    return leaves, treedef


def _fingerprint(leaves, tag: Optional[str]) -> str:
    return json.dumps({
        "format": _FORMAT,
        "tag": tag,
        "leaves": [
            [list(np.shape(x)), str(np.asarray(x).dtype)] for x in leaves
        ],
    })


def save(path: str, sims: Any, *, tag: Optional[str] = None) -> None:
    """Write a batched Sim (or any pytree) to ``path`` (.npz).

    ``tag`` is an opaque caller string stored in the fingerprint and
    checked verbatim at restore — the runner passes the spec's identity
    (name, capacities, dtype profile) so a same-shape-different-model
    restore still fails loudly.

    Atomicity: the bytes land in a UNIQUELY-named temp file in the same
    directory (``mkstemp`` — two concurrent savers, e.g. a service
    checkpointing two runs to siblings of one dir, cannot clobber each
    other's half-written temp), are fsync'd to disk, and only then
    ``os.replace``d over ``path`` — a preemption or crash at ANY point
    leaves either the previous complete checkpoint or none, never a
    torn file, and ``restore`` only ever reads ``path``, so leftover
    ``*.tmp`` orphans from a killed process are ignored (tested in
    tests/test_checkpoint_atomic.py)."""
    import tempfile

    leaves, _ = _flatten(sims)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays["__spec__"] = np.frombuffer(
        _fingerprint(leaves, tag).encode(), dtype=np.uint8
    )
    path = os.path.abspath(path)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path),
        prefix=os.path.basename(path) + ".",
        suffix=".tmp",
    )
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(fh, **arrays)
            fh.flush()
            os.fsync(fh.fileno())  # durable BEFORE the rename publishes it
        os.replace(tmp, path)  # atomic: never a torn checkpoint at `path`
    except BaseException:
        try:  # a failed save must not litter (or leave a decoy temp)
            os.unlink(tmp)
        except OSError:
            pass
        raise


def spec_tag(spec: Any) -> str:
    """Canonical fingerprint tag for a built ModelSpec: name, process
    count, capacities, and the active dtype profile — everything that
    changes the Sim's meaning without necessarily changing a leaf
    count."""
    from cimba_tpu import config

    return json.dumps({
        "model": getattr(spec, "name", "?"),
        "n_procs": getattr(spec, "n_procs", -1),
        "event_cap": getattr(spec, "event_cap", -1),
        "queue_cap_max": getattr(spec, "queue_cap_max", -1),
        "pqueue_cap_max": getattr(spec, "pqueue_cap_max", -1),
        "real": str(config.REAL),
        "time": str(config.TIME),
    })


def run_tag(
    spec: Any, *, seed: int, params: Any = None, t_end: Any = None,
) -> str:
    """:func:`spec_tag` extended with the run's ``seed``, horizon, and a
    digest of its (broadcast) params: a chunked checkpoint restored
    under a different seed, ``t_end``, or swept parameters would
    silently continue/hybridize the OLD run — the shapes all match — so
    the runner fingerprints every trajectory-changing knob and a
    mismatched resume fails loudly instead (chunk_steps/pack are
    trajectory-neutral and stay out of the tag)."""
    import hashlib

    base = json.loads(spec_tag(spec))
    base["seed"] = int(seed)
    base["t_end"] = None if t_end is None else float(t_end)
    if params is not None:
        h = hashlib.sha256()
        for x in _flatten(params)[0]:
            a = np.asarray(x)
            h.update(f"{a.shape}:{a.dtype}:".encode())
            h.update(a.tobytes())
        base["params_sha256"] = h.hexdigest()
    return json.dumps(base)


def save_resumable(
    path: str, sims: Any, *, spec: Any = None, progress: int = 0,
    tag: Optional[str] = None,
) -> None:
    """Checkpoint a chunked run at a chunk boundary: the batched Sim
    plus its chunk counter, spec-fingerprinted (chunk boundaries are
    the natural checkpoints — between chunks the COMPLETE state of
    every replication, RNG position included, is the Sim pytree the
    host loop holds; ``run_experiment_chunked`` calls this from its
    ``on_state`` hook).  ``spec`` supplies the fingerprint tag via
    :func:`spec_tag` unless an explicit ``tag`` is given.

    ``spec_tag`` alone does NOT guard against resuming under a
    different seed, horizon, or swept params — those all produce
    identical shapes and spec identity.  Callers checkpointing a
    specific run should pass ``tag=run_tag(spec, seed=..., params=...,
    t_end=...)`` as ``run_experiment_chunked`` does; the bare ``spec=``
    form only proves the model matches."""
    if tag is None and spec is not None:
        tag = spec_tag(spec)
    save(
        path,
        (sims, jnp.asarray(int(progress), jnp.int32)),
        tag=tag,
    )


def restore_resumable(
    path: str, like: Any, *, spec: Any = None, tag: Optional[str] = None,
):
    """Inverse of :func:`save_resumable`: returns ``(sims, progress)``.
    ``like`` is a same-shaped batched Sim — a fresh init of the same
    experiment or its ``jax.eval_shape`` aval tree (no materialization);
    validation is :func:`restore`'s — the first mismatching leaf or a
    spec-fingerprint change fails loudly.  As with
    :func:`save_resumable`, pass ``tag=run_tag(...)`` to also pin the
    run's seed/params/horizon; ``spec=`` alone only proves the model
    matches."""
    if tag is None and spec is not None:
        tag = spec_tag(spec)
    sims, progress = restore(
        path, (like, jnp.zeros((), jnp.int32)), tag=tag
    )
    return sims, int(progress)


def restore(path: str, like: Any, *, tag: Optional[str] = None) -> Any:
    """Read a checkpoint written by :func:`save`; ``like`` supplies the
    pytree structure and every leaf's expected shape and dtype — a
    freshly-initialized batch, or its ``jax.eval_shape`` aval tree
    (``ShapeDtypeStruct`` leaves carry exactly what validation reads,
    without materializing a batch).  Raises ``ValueError`` naming the
    first mismatch if the file disagrees with ``like`` or with ``tag``."""
    leaves, treedef = _flatten(like)
    with np.load(path) as data:
        names = [f for f in data.files if f != "__spec__"]
        if "__spec__" not in data.files:
            if tag is not None:
                raise ValueError(
                    "checkpoint has no spec fingerprint (written by a "
                    "pre-fingerprint save?) but tag verification was "
                    "requested — cannot prove it matches this spec"
                )
        else:
            saved = json.loads(bytes(data["__spec__"]).decode())
            if saved.get("format") != _FORMAT:
                raise ValueError(
                    f"checkpoint format {saved.get('format')} != "
                    f"supported {_FORMAT}"
                )
            if tag is not None and saved.get("tag") != tag:
                raise ValueError(
                    "checkpoint spec fingerprint mismatch —\n"
                    f"  saved:    {saved.get('tag')}\n"
                    f"  restoring:{tag}\n"
                    "model/capacities/profile changed between save and "
                    "restore (e.g. a capacity regrow); re-init instead"
                )
        if len(names) != len(leaves):
            raise ValueError(
                f"checkpoint has {len(names)} leaves, expected "
                f"{len(leaves)} — model structure changed?"
            )
        new = []
        for i, x in enumerate(leaves):
            arr = data[f"leaf_{i}"]
            want_shape = tuple(np.shape(x))
            # ShapeDtypeStruct / jax array leaves carry .dtype; plain
            # python scalars fall back through asarray
            dt = getattr(x, "dtype", None)
            want_dtype = np.dtype(dt) if dt is not None else np.asarray(x).dtype
            if tuple(arr.shape) != want_shape:
                raise ValueError(
                    f"checkpoint leaf {i}: shape {tuple(arr.shape)} != "
                    f"expected {want_shape} — capacity or batch size "
                    "changed between save and restore?"
                )
            if arr.dtype != want_dtype:
                raise ValueError(
                    f"checkpoint leaf {i}: dtype {arr.dtype} != expected "
                    f"{want_dtype} — dtype profile changed between save "
                    "and restore?"
                )
            new.append(jnp.asarray(arr, want_dtype))
    return jax.tree.unflatten(treedef, new)
