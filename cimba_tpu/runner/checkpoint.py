"""Checkpoint/resume of batched experiment state.

The reference has **no checkpointing** (SURVEY.md §5: trials are short and
restartable); long pod-scale experiments need it, so this is new
capability.  It falls out of the architecture: a replication's complete
state is one pytree (including the counter-based RNG position), so
``save``/``restore`` round-trips the whole batch and ``make_run`` simply
continues — resumed runs are bit-identical to uninterrupted ones (tested).

Format: a flat numpy ``.npz`` of the pytree leaves with an atomic rename —
deliberately dependency-free (the state is a modest pytree of dense arrays;
an async/sharded checkpoint stack like orbax buys nothing at this size and
would be the only non-jax dependency in the hot path).  Structure changes
are rejected at restore by leaf-count mismatch.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def _flatten(tree: Any):
    leaves, treedef = jax.tree.flatten(tree)
    return leaves, treedef


def save(path: str, sims: Any) -> None:
    """Write a batched Sim (or any pytree) to ``path`` (.npz)."""
    leaves, _ = _flatten(sims)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp, path)  # atomic: a crash never leaves a torn checkpoint


def restore(path: str, like: Any) -> Any:
    """Read a checkpoint written by :func:`save`; ``like`` supplies the
    pytree structure and dtypes (e.g. a freshly-initialized batch)."""
    leaves, treedef = _flatten(like)
    with np.load(path) as data:
        if len(data.files) != len(leaves):
            raise ValueError(
                f"checkpoint has {len(data.files)} leaves, expected "
                f"{len(leaves)} — model structure changed?"
            )
        new = [
            jnp.asarray(data[f"leaf_{i}"], x.dtype)
            for i, x in enumerate(leaves)
        ]
    return jax.tree.unflatten(treedef, new)