"""The experiment runner: replications fanned over lanes and chips.

Reference parity: ``cimba_run`` (`src/cimba.c:232-276`) — a pthread worker
pool pulling trials off an atomic counter, with per-thread init/exit hooks
and longjmp failure recovery, returning the failed-trial count.

TPU redesign: replications are the leading axis of every state array.

* The atomic work-stealing dispenser disappears: partitioning is static —
  replication r is lane r of the batch (`vmap`), shard r // per_device of
  the mesh (`shard_map`).  Balanced because every replication runs the
  same model; divergence in *length* is absorbed by the batched
  while-loop's masking.
* Thread hooks (the reference's per-thread CUDA stream setup,
  `tutorial/tut_5_3.c:854-880`) have no analog: SPMD code is identical on
  every chip, and device-local setup is XLA's job.
* Failure recovery: a failed replication freezes with ``sim.err`` set and
  is counted (`result.n_failed`) — the §3.5 longjmp story without a
  longjmp, and unlike the reference the failed replication's partial state
  remains inspectable.
* Cross-replication statistics: ``pooled_summary`` tree-merges the
  per-replication Pébay summaries; under a mesh the per-shard partials go
  through ``all_gather`` over ICI and merge identically on every device.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map as _shard_map_impl
except ImportError:  # older jax keeps shard_map under experimental
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def shard_map(f, **kw):
    """``jax.shard_map`` across jax versions: older releases live under
    ``jax.experimental`` and spell ``check_vma`` as ``check_rep``."""
    import inspect

    if "check_vma" in kw and (
        "check_vma" not in inspect.signature(_shard_map_impl).parameters
    ):
        kw["check_rep"] = kw.pop("check_vma")
    return _shard_map_impl(f, **kw)

from cimba_tpu.core.loop import (
    Sim,
    drive_chunks,
    init_sim,
    make_chunk,
    make_run,
)
from cimba_tpu.core.model import ModelSpec
from cimba_tpu.stats import summary as sm

REP_AXIS = "rep"


def default_summary_path(sims):
    """The default pooled statistic: the per-replication ``wait``
    summary every shipped queueing model records.  A NAMED module-level
    function (not a fresh lambda) so every caller that leaves
    ``summary_path`` unset shares one identity — the fold-program cache
    and the serving layer's request-compatibility key both key on it."""
    return sims.user["wait"]


class ExperimentResult(NamedTuple):
    sims: Sim                 # batched: every leaf has leading axis [R]
    n_failed: jnp.ndarray     # replications with err != 0
    total_events: jnp.ndarray # dispatched events across all replications


class StreamResult(NamedTuple):
    """What :func:`run_experiment_stream` returns: pooled statistics for
    all R replications WITHOUT the batched sims (they were streamed
    through the device in waves and folded into these accumulators)."""

    summary: sm.Summary        # pooled over every replication
    n_failed: jnp.ndarray      # replications with err != 0, all waves
    total_events: jnp.ndarray  # i64 dispatched events, all waves
    n_waves: int
    n_regrows: int             # wave-granular capacity regrows performed
    metrics: Any = None        # pooled obs.metrics registry when enabled
    audit: Any = None          # run card (docs/18_audit.md) when audited


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D replication mesh over the available devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(devs, (REP_AXIS,))


def _broadcast_params(params: Any, n: int):
    """Scalar params broadcast to [n]; leaves already [n, ...] pass through."""
    def bc(x):
        x = jnp.asarray(x)
        if x.ndim > 0 and x.shape[0] == n:
            return x
        return jnp.broadcast_to(x, (n,) + x.shape)

    return jax.tree.map(bc, params)


def _slice_params(params: Any, n_total: int, lo: int, n: int):
    """The wave view of an experiment array: swept leaves (leading axis
    ``n_total``) are sliced to rows ``[lo, lo+n)``; every other leaf is
    broadcast to the wave exactly as ``_broadcast_params`` would have
    broadcast it to the full batch.

    ``_slice_params(p, R, lo, n)`` is bitwise
    ``_broadcast_params(p, R)[lo:lo+n]`` on every leaf — the wave's
    lanes see exactly the parameter rows the monolithic run's lanes
    ``lo..lo+n-1`` see, WITHOUT materializing any [R]-sized array (the
    M/G/1 sweep regression, pinned in tests/test_stream.py).  Shared
    leaves are broadcast here (not left to a later ``_broadcast_params``
    pass) so a shared leaf whose leading axis happens to equal the wave
    size cannot be misread as per-lane data.

    This is also the delivery contract ``sweep.SweepGrid`` rows ride:
    a grid cell's scalar row broadcast to its wave slot here equals
    the monolithic ``grid.rows()`` broadcast row-for-row, which is
    what makes the sweep engine's cells bitwise the monolithic sweep
    (docs/16_sweeps.md)."""
    def sl(x):
        x = jnp.asarray(x)
        if x.ndim > 0 and x.shape[0] == n_total:
            return x[lo : lo + n]
        return jnp.broadcast_to(x, (n,) + x.shape)

    return jax.tree.map(sl, params)


def run_experiment(
    spec: ModelSpec,
    params: Any,
    n_replications: int,
    *,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    t_end: Optional[float] = None,
    with_report: bool = False,
    profile_dir: Optional[str] = None,
    pack: Optional[bool] = None,
):
    """Run ``n_replications`` independent replications of ``spec``.

    ``params`` is the experiment array (reference: the user's trial struct
    array): a pytree whose leaves are either scalars (shared by all
    replications) or arrays with leading axis ``n_replications`` (a
    parameter sweep — the M/G/1 4x5x10 sweep pattern).

    ``with_report=True`` returns ``(ExperimentResult, obs.prof.RunReport)``
    instead: the run goes through the AOT path so the report carries the
    trace/compile/execute wall-time split, plus device memory stats and —
    when the metrics registry is enabled — the pooled metrics snapshot.
    ``profile_dir`` additionally wraps the execute leg in a
    ``jax.profiler.trace`` context writing there.

    ``pack`` selects the while-loop carry layout (see
    :func:`cimba_tpu.core.loop.make_run`; None = the
    ``CIMBA_XLA_PACK``/backend auto default) — trajectory-identical
    either way, bench.py measures both arms through this knob.
    """
    run = make_run(spec, t_end=t_end, pack=pack)
    pb = _broadcast_params(params, n_replications)
    reps = jnp.arange(n_replications)

    def one(rep, p):
        return run(init_sim(spec, seed, rep, p))

    vm = jax.vmap(one)

    timings = None
    if mesh is None:
        fn = vm
    else:
        n_dev = mesh.devices.size
        if n_replications % n_dev:
            raise ValueError(
                f"n_replications={n_replications} must divide evenly over "
                f"{n_dev} devices"
            )

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(REP_AXIS), P(REP_AXIS)),
            out_specs=P(REP_AXIS),
            check_vma=False,  # cond/switch branches mix replicated constants
            # with varying data; semantics are plain SPMD over 'rep'
        )
        def sharded(reps_local, p_local):
            return vm(reps_local, p_local)

        fn = sharded

    if with_report:
        from cimba_tpu.obs import prof as _prof

        sims, timings = _prof.profiled_call(
            jax.jit(fn), reps, pb, profile_dir=profile_dir
        )
    else:
        sims = jax.jit(fn)(reps, pb)

    result = ExperimentResult(
        sims=sims,
        n_failed=jnp.sum((sims.err != 0).astype(jnp.int32)),
        total_events=jnp.sum(sims.n_events),
    )
    if not with_report:
        return result
    from cimba_tpu.obs import metrics as _metrics

    snap = None
    if sims.metrics is not None:
        snap = _metrics.snapshot(jax.jit(_metrics.pool)(sims.metrics), spec)
    report = _prof.build_report(
        timings,
        n_replications=n_replications,
        n_failed=int(result.n_failed),
        total_events=int(result.total_events),
        metrics=snap,
        profile_dir=profile_dir,
    )
    return result, report


def run_experiment_regrow(
    spec: ModelSpec,
    params: Any,
    n_replications: int,
    *,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    t_end: Optional[float] = None,
    max_regrows: int = 4,
    pack: Optional[bool] = None,
):
    """``run_experiment`` with the capacity escape hatch: if any
    replication died with ``ERR_EVENT_OVERFLOW``/``ERR_GUARD_OVERFLOW``,
    double the event cap and re-run the WHOLE batch under the grown
    spec (a re-jit at the larger shapes).

    Reference parity: the reference's hashheap grows amortized-doubling
    under the hood (`src/cmi_hashheap.c:384-426`); under jit capacities
    are static shapes, so growth happens between jit calls instead.
    Re-running every lane (not only the overflowed ones) keeps the
    batched Sim shape-consistent, and costs nothing in correctness:
    replication streams are counter-derived from (seed, rep), so healthy
    lanes reproduce bit-identically under any capacity.

    Returns ``(result, final_spec, n_regrows)`` — ``final_spec`` is what
    actually ran last (callers reuse it to skip re-discovery).
    """
    import dataclasses

    import numpy as np

    from cimba_tpu.core import loop as _cl

    # dense guards cannot overflow; the event table is the one growable cap
    grow_errs = (_cl.ERR_EVENT_OVERFLOW,)
    for n_regrows in range(max_regrows + 1):
        result = run_experiment(
            spec, params, n_replications, seed=seed, mesh=mesh,
            t_end=t_end, pack=pack,
        )
        err = np.asarray(result.sims.err)
        if not np.isin(err, grow_errs).any():
            return result, spec, n_regrows
        if n_regrows < max_regrows:
            spec = dataclasses.replace(
                spec, event_cap=2 * spec.event_cap,
            )
    raise RuntimeError(
        f"run_experiment_regrow: capacity overflow persists after "
        f"{max_regrows} doublings (last run at event_cap={spec.event_cap}) "
        "— the model schedules unboundedly or the cap estimate is "
        "pathologically low"
    )


def _chunk_program(
    spec: ModelSpec,
    t_end,
    pack,
    chunk_steps: int,
    mesh: Optional[Mesh],
    donate: bool = True,
    audit: bool = False,
):
    """One compiled chunk program: ``chunk(sims) -> (sims, any_live)``,
    jitted with the batched Sim DONATED so chunk n+1 aliases chunk n's
    output buffers — zero inter-chunk copies, flat steady-state device
    memory (the donation contract, docs/12_streaming.md).  Under a mesh
    the chunk runs per-shard with the liveness flag psum-reduced over
    ICI, so the host polls one replicated scalar.

    ``audit=True`` (docs/18_audit.md) appends the per-wave carry-class
    digest vector as a third output.  Under a mesh the digest is
    computed per shard with GLOBAL lane offsets (``axis_index x local
    lanes``) and psum-combined — integer sums mod 2^64 are exact and
    commutative, so the combined digest equals the single-device digest
    of the same wave.  ``audit=False`` is the historical program,
    jaxpr-identical (pinned in tests/test_audit.py)."""
    if mesh is None:
        chunk = make_chunk(
            spec, t_end=t_end, pack=pack, max_steps=chunk_steps,
            audit=audit,
        )
    else:
        chunk_local = make_chunk(
            spec, t_end=t_end, pack=pack, max_steps=chunk_steps
        )
        out_specs = (P(REP_AXIS), P()) + ((P(),) if audit else ())

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(REP_AXIS),),
            out_specs=out_specs,
            check_vma=False,
        )
        def chunk(sims):
            sims, live_local = chunk_local(sims)
            n_live = jax.lax.psum(
                live_local.astype(jnp.int32), REP_AXIS
            )
            out = (sims, n_live > 0)
            if audit:
                from cimba_tpu.obs import audit as _obs_audit

                n_local = jax.tree.leaves(sims)[0].shape[0]
                off = jax.lax.axis_index(REP_AXIS).astype(
                    jnp.uint64
                ) * jnp.uint64(n_local)
                dig = _obs_audit.sim_digest(sims, lane_offset=off)
                out = out + (jax.lax.psum(dig, REP_AXIS),)
            return out

    return jax.jit(chunk, donate_argnums=(0,) if donate else ())


def _seed_column(seed, n: int):
    """A per-lane ``[n]`` u64 seed column (one request seed broadcast).

    Seed is per-lane DATA on the chunked/streamed/served paths, not a
    constant baked into the compiled init program: ``init_sim`` derives
    each lane's stream as ``fmix64(seed + c*rep)`` — pure integer
    arithmetic, bit-identical whether ``seed`` arrives traced or
    static — so requests differing only in seed share one compiled
    program (docs/14_wave_packing.md)."""
    return jnp.full((n,), jnp.asarray(seed, jnp.uint64))


def _horizon_column(t_end, n: int):
    """A per-lane ``[n]`` horizon column: ``t_end`` broadcast, with
    ``None`` (run to completion) encoded as ``+inf`` — the lane-data
    image of the static ``t_end`` knob (see ``Sim.t_stop``)."""
    from cimba_tpu import config as _config

    return jnp.full(
        (n,), jnp.inf if t_end is None else t_end, _config.TIME
    )


def _init_program(spec: ModelSpec, mesh: Optional[Mesh]):
    """``init(reps, seeds, t_stops, params) -> batched Sim`` (sharded
    over the mesh when one is given, so the chunk program never
    reshards).

    ``seeds`` is the per-lane u64 seed column (:func:`_seed_column`)
    and ``t_stops`` the per-lane horizon column
    (:func:`_horizon_column`) — both lane DATA, so one compiled init
    program serves every (seed, horizon) mix; ``t_stops=None`` omits
    the horizon leaf entirely (the Sim then matches the historical
    pytree — static-``t_end`` programs and old checkpoints)."""
    def init(reps, seeds, t_stops, p):
        return jax.vmap(
            lambda r, s, t, q: init_sim(spec, s, r, q, t_stop=t)
        )(reps, seeds, t_stops, p)

    if mesh is not None:
        init = partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P(REP_AXIS), P(REP_AXIS), P(REP_AXIS), P(REP_AXIS),
            ),
            out_specs=P(REP_AXIS),
            check_vma=False,
        )(init)
    return jax.jit(init)


def _refill_program(spec: ModelSpec, mesh: Optional[Mesh]):
    """One compiled refill program: ``refill(sims, mask, reps, seeds,
    t_stops, params) -> sims`` (:func:`cimba_tpu.core.loop.
    make_refill`), jitted with the batched Sim DONATED so a boundary
    splice aliases the wave's buffers instead of copying them — the
    same zero-copy contract the chunk program rides
    (docs/12_streaming.md).  Under a mesh every operand is lane-data
    sharded over ``REP_AXIS``, so the splice never reshards the wave
    the chunk program runs on.  Compiled once per wave shape alongside
    ``(init, chunk)`` — after warmup a refill is a cached dispatch,
    never a compile (docs/22_refill.md)."""
    from cimba_tpu.core.loop import make_refill

    refill = make_refill(spec)
    if mesh is not None:
        refill = partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P(REP_AXIS), P(REP_AXIS), P(REP_AXIS), P(REP_AXIS),
                P(REP_AXIS), P(REP_AXIS),
            ),
            out_specs=P(REP_AXIS),
            check_vma=False,
        )(refill)
    return jax.jit(refill, donate_argnums=(0,))


def _fused_init_program(fused, mesh: Optional[Mesh]):
    """The fused twin of :func:`_init_program`: ``init(reps, seeds,
    t_stops, sids, params) -> batched Sim`` with a per-lane ``sids``
    (spec-id) column switching each lane's ``init_sim`` through its own
    member spec (:func:`cimba_tpu.core.fuse.make_fused_init`,
    docs/26_wave_fusion.md).  Fused waves ALWAYS materialize the
    horizon column — the refill splice and lane reclamation need it —
    which is bitwise-safe (``t_stop=t_end`` reproduces the static
    cond's decisions and no result reads the leaf)."""
    from cimba_tpu.core.fuse import make_fused_init

    init = make_fused_init(fused)
    if mesh is not None:
        init = partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P(REP_AXIS), P(REP_AXIS), P(REP_AXIS), P(REP_AXIS),
                P(REP_AXIS),
            ),
            out_specs=P(REP_AXIS),
            check_vma=False,
        )(init)
    return jax.jit(init)


def _fused_refill_program(fused, mesh: Optional[Mesh]):
    """The fused twin of :func:`_refill_program`: ``refill(sims, mask,
    reps, seeds, t_stops, sids, params) -> sims``
    (:func:`cimba_tpu.core.fuse.make_fused_refill`), jitted with the
    batched Sim DONATED like its solo twin.  One program serves every
    member of the fusion class, so a boundary splice admitting ANY
    member is a cached dispatch, never a compile
    (docs/26_wave_fusion.md)."""
    from cimba_tpu.core.fuse import make_fused_refill

    refill = make_fused_refill(fused)
    if mesh is not None:
        refill = partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P(REP_AXIS), P(REP_AXIS), P(REP_AXIS), P(REP_AXIS),
                P(REP_AXIS), P(REP_AXIS), P(REP_AXIS),
            ),
            out_specs=P(REP_AXIS),
            check_vma=False,
        )(refill)
    return jax.jit(refill, donate_argnums=(0,))


def _live_program(spec: ModelSpec, mesh: Optional[Mesh]):
    """One compiled per-lane liveness readback: ``live(sims) ->
    bool[L]`` (:func:`cimba_tpu.core.loop.make_lanes_live`) — NOT
    donated (it reads the wave the next chunk will consume).  The
    refill driver polls it at chunk boundaries to learn which lanes
    died this chunk; the serving layer's live lane-occupancy gauge
    rides the same program (docs/22_refill.md)."""
    from cimba_tpu.core.loop import make_lanes_live

    live = make_lanes_live(spec)
    if mesh is not None:
        live = partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(REP_AXIS),),
            out_specs=P(REP_AXIS),
            check_vma=False,
        )(live)
    return jax.jit(live)


def _tel_hooks(telemetry, kind: str, on_wave, on_chunk):
    """Generalize the ``on_wave``/``on_chunk`` progress hooks into
    telemetry ticks (docs/17_telemetry.md): with a
    :class:`cimba_tpu.obs.telemetry.Telemetry` plane attached, each
    wave/chunk boundary ticks its counter and refreshes the liveness
    heartbeat (the watchdog primitive ``bench.py`` reads), THEN calls
    the user hook.  ``telemetry=None`` returns the hooks untouched —
    the zero-overhead default (no wrapper closures, no allocations on
    the drive loop)."""
    if telemetry is None:
        return on_wave, on_chunk

    def wave_hook(n_waves, lanes_done, _u=on_wave):
        telemetry.tick(f"{kind}.wave")
        if _u is not None:
            _u(n_waves, lanes_done)

    def chunk_hook(n, _u=on_chunk):
        telemetry.tick(f"{kind}.chunk")
        if _u is not None:
            _u(n)

    return wave_hook, chunk_hook


def run_experiment_chunked(
    spec: ModelSpec,
    params: Any,
    n_replications: int,
    *,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    t_end: Optional[float] = None,
    pack: Optional[bool] = None,
    chunk_steps: int = 1024,
    poll_every: int = 4,
    donate: bool = True,
    on_chunk=None,
    telemetry=None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 0,
    resume: bool = False,
):
    """:func:`run_experiment` with chunked, donated dispatch: the batched
    Sim stays device-resident while the host re-dispatches one compiled
    chunk program (every lane advances at most ``chunk_steps`` events
    per dispatch) until all lanes finish.

    Trajectories are bitwise the monolithic run's — chunking only splits
    the while loop across dispatches — but no single device program
    outlives one chunk, so arbitrarily long runs clear the TPU runtime's
    ~3-minute program watchdog, and the ``any_live`` poll (every
    ``poll_every`` chunks, asynchronous) keeps the dispatch pipeline
    full.  See docs/12_streaming.md.

    ``checkpoint_path`` + ``checkpoint_every`` save the batched Sim at
    chunk boundaries (``runner.checkpoint.save_resumable``, tagged with
    spec identity, ``seed``, and a params digest); ``resume=True``
    restores from an existing checkpoint first — the resumed run is
    bit-identical to an uninterrupted one (the Sim is the complete
    state, RNG position included), and a resume under a different spec,
    seed, or params fails loudly on the fingerprint instead of silently
    continuing the old run.
    """
    import os as _os

    from cimba_tpu.serve import store as _pstore

    # CIMBA_PROGRAM_STORE: recompiles on this path become disk hits
    # (docs/15_program_store.md mechanism (a); no-op when unset)
    _pstore.maybe_enable_persistent_cache()
    pb = _broadcast_params(params, n_replications)
    reps = jnp.arange(n_replications)
    if mesh is not None and n_replications % mesh.devices.size:
        raise ValueError(
            f"n_replications={n_replications} must divide evenly over "
            f"{mesh.devices.size} devices"
        )
    init_j = _init_program(spec, mesh)
    # static horizon, no per-lane t_stop leaf (t_stops=None): the
    # checkpointed pytree stays the historical one, and the chunk
    # program below keeps its static t_end cond
    seeds = _seed_column(seed, n_replications)

    n0 = 0
    sims = None
    ckpt_tag = None
    if checkpoint_path:
        from cimba_tpu.runner import checkpoint as _ckpt

        # the tag carries seed + horizon + params digest beyond spec
        # identity: a resume under different seed/t_end/params has
        # matching shapes and would otherwise silently continue the OLD
        # run's trajectories
        ckpt_tag = _ckpt.run_tag(spec, seed=seed, params=pb, t_end=t_end)
    if checkpoint_path and resume:
        if _os.path.exists(checkpoint_path):
            # restore validates against an ABSTRACT init (eval_shape):
            # materializing a full fresh batch just to serve as the
            # shape/dtype template would waste the init compute and
            # transiently hold TWO full batched Sims on exactly the
            # memory-bound runs checkpointing targets
            sims, n0 = _ckpt.restore_resumable(
                checkpoint_path,
                jax.eval_shape(init_j, reps, seeds, None, pb),
                tag=ckpt_tag,
            )
    if sims is None:
        sims = init_j(reps, seeds, None, pb)

    on_state = None
    if checkpoint_path and checkpoint_every:
        def on_state(s, n):
            _ckpt.save_resumable(
                checkpoint_path, s, tag=ckpt_tag, progress=n
            )

    _, on_chunk = _tel_hooks(telemetry, "chunked", None, on_chunk)
    chunk = _chunk_program(spec, t_end, pack, chunk_steps, mesh, donate)
    sims = drive_chunks(
        chunk, sims, poll_every=poll_every, on_chunk=on_chunk,
        on_state=on_state, on_state_every=checkpoint_every, n0=n0,
    )
    return ExperimentResult(
        sims=sims,
        n_failed=jnp.sum((sims.err != 0).astype(jnp.int32)),
        total_events=jnp.sum(sims.n_events),
    )


def run_experiment_stream(
    spec: ModelSpec,
    params: Any,
    n_replications: int,
    *,
    wave_size: Optional[int] = None,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    t_end: Optional[float] = None,
    pack: Optional[bool] = None,
    chunk_steps: Optional[int] = None,
    poll_every: int = 4,
    summary_path=default_summary_path,
    max_regrows: int = 0,
    on_wave=None,
    on_chunk=None,
    telemetry=None,
    program_cache: Optional[dict] = None,
    audit=None,
    schedule=None,
) -> StreamResult:
    """Pooled statistics for R replications with R beyond the
    per-dispatch lane budget: stream waves of ``wave_size`` lanes
    through ONE compiled chunk program (chunked, donated dispatch — see
    :func:`run_experiment_chunked`), folding each finished wave's pooled
    Pébay summary, metrics registry (when ``obs.metrics`` is enabled),
    failure count, and event total into on-device accumulators.  The
    batched sims of a wave are freed before the next wave initializes,
    so peak device memory is one wave regardless of R — pooled
    statistics for R in the millions without ever materializing all
    sims.

    Lane r of wave w is replication ``w*wave_size + r``: identical
    (seed, rep)-derived streams and bitwise-identical per-wave parameter
    rows (:func:`_slice_params`) make every replication's trajectory
    bitwise the monolithic run's; the summary fold is the associative
    Pébay merge, so the pooled moments match the monolithic pool up to
    float merge-order rounding (counts and event totals exactly).

    Composition: ``mesh`` shards each wave over devices (wave = local
    lanes x devices, the ``make_sharded_experiment`` topology);
    ``max_regrows > 0`` retries a wave under a doubled event cap when it
    hit ``ERR_EVENT_OVERFLOW`` (regrow at wave granularity — later waves
    keep the grown spec; healthy lanes reproduce bit-identically under
    any capacity).  A final partial wave re-specializes the same
    programs at the remainder shape (one extra compile).

    ``on_wave(n_waves, lanes_done)`` and ``on_chunk(n)`` are progress
    hooks (bench.py refreshes its watchdog heartbeat there).
    ``telemetry`` generalizes them: a
    :class:`cimba_tpu.obs.telemetry.Telemetry` plane gets a tick
    (counter + liveness heartbeat) per wave and per chunk, and — with
    spans enabled — one "stream" span covering the call with a
    per-wave event trail (docs/17_telemetry.md).  All host-side: the
    compiled programs and the streamed results are bitwise identical
    with or without it.

    ``program_cache``: pass the SAME mapping to repeated calls to reuse
    the compiled init/chunk/fold programs across calls (bench.py's
    warm-then-time protocol; a service shares one cache across every
    request).  Every setting a program bakes in — the spec's structural
    fingerprint, the dtype profile, the ``obs.metrics`` and
    ``obs.trace`` states, the event-set layout flags, the resolved
    ``pack`` arm, ``chunk_steps``, ``mesh``, and ``summary_path``
    identity — is part of the cache key, so a mismatched call
    recompiles rather than replaying stale programs.  ``seed`` and
    ``t_end`` are NOT program constants on this path: they ride as
    per-lane data columns (bit-identical trajectories), so calls
    differing only in them — and structurally identical spec twins from
    ``dataclasses.replace`` — share compiled programs
    (docs/14_wave_packing.md); jitted programs additionally
    re-specialize per wave shape internally, so full waves always
    share one compile.  The default is
    a fresh :class:`cimba_tpu.serve.cache.ProgramCache` — a bounded LRU
    with hit/miss/eviction counters (``CIMBA_PROGRAM_CACHE_CAP``);
    plain dicts keep working for legacy callers but never evict.

    Cold starts: with ``CIMBA_PROGRAM_STORE`` set (or a cache whose
    ``store=`` names a :class:`~cimba_tpu.serve.store.ProgramStore`),
    a cache miss hydrates serialized executables from disk before
    compiling, and every jit on this path additionally rides jax's
    persistent compilation cache — a fresh process reaches its first
    result without re-paying XLA compile (docs/15_program_store.md).

    Sweeping many scenarios?  :func:`cimba_tpu.sweep.run_sweep` drives
    this same chunked machinery per grid cell — per-cell pooled
    summaries (bitwise these calls'), adaptive replication counts, and
    shared waves across cells (docs/16_sweeps.md).

    ``audit`` (docs/18_audit.md): ``None`` defers to the
    ``CIMBA_AUDIT`` env knob (unset = off — the chunk program is then
    jaxpr-identical to the unaudited one, pinned); ``True`` / a
    directory path / an :class:`cimba_tpu.obs.audit.Audit` enable the
    determinism audit — the chunk program additionally folds each
    packed carry class into a per-wave digest vector at every chunk
    boundary (the digest trail), and the returned ``StreamResult``
    carries a content-addressed **run card** in ``.audit`` (spec
    fingerprint, seed schedule, program key, env, geometry, trail,
    result digest), written to the Audit's ``out_dir`` when set.  Two
    clean same-seed runs produce identical trails and the same card
    digest; ``tools/audit_diff.py`` localizes any divergence to its
    first (wave, chunk, carry-class).

    ``schedule`` / tuned resolution (docs/21_autotune.md): the
    dispatch knobs left unset here — ``pack``, ``chunk_steps``
    (default 1024), ``wave_size``, and the trace-time event-set
    layout — resolve through :func:`cimba_tpu.tune.registry.
    resolve_entry` at program-build time: an explicit
    ``schedule=``:class:`~cimba_tpu.tune.space.Schedule` binds exactly
    that schedule (the search harness's arm dispatch); otherwise, with
    ``CIMBA_TUNE`` on (the default) and a program store in reach
    (``program_cache.store`` / ``CIMBA_PROGRAM_STORE``), a searched
    winner for this (spec, backend, workload bucket) fills the unset
    knobs.  Explicit kwargs ALWAYS win, ``CIMBA_TUNE=0`` restores the
    hand-frozen defaults bitwise, and the resolution source
    (tuned/default/override) is recorded in the run card's
    ``schedule`` block when auditing.
    """
    from cimba_tpu.serve import cache as _pcache_r
    from cimba_tpu.tune import registry as _tune_reg

    store = None
    if isinstance(program_cache, _pcache_r.ProgramCache):
        # respect an explicitly opted-out cache (store=False)
        store = program_cache._store
    rs = _tune_reg.resolve_entry(
        spec, n_replications, schedule=schedule, pack=pack,
        chunk_steps=chunk_steps, wave_size=wave_size, store=store,
    )
    with rs.scope():
        return _stream_impl(
            spec, params, n_replications,
            wave_size=rs.wave_size, seed=seed, mesh=mesh, t_end=t_end,
            pack=rs.pack, chunk_steps=rs.chunk_steps,
            poll_every=poll_every, summary_path=summary_path,
            max_regrows=max_regrows, on_wave=on_wave,
            on_chunk=on_chunk, telemetry=telemetry,
            program_cache=program_cache, audit=audit,
            sched_block=rs.block(),
        )


def _stream_impl(
    spec: ModelSpec,
    params: Any,
    n_replications: int,
    *,
    wave_size: Optional[int] = None,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    t_end: Optional[float] = None,
    pack: Optional[bool] = None,
    chunk_steps: int = 1024,
    poll_every: int = 4,
    summary_path=default_summary_path,
    max_regrows: int = 0,
    on_wave=None,
    on_chunk=None,
    telemetry=None,
    program_cache: Optional[dict] = None,
    audit=None,
    sched_block: Optional[dict] = None,
) -> StreamResult:
    """The stream runner's body (see :func:`run_experiment_stream`,
    which resolves the schedule and enters its trace-time scope before
    delegating here — program keys and traces below must see the
    bound knobs)."""
    import dataclasses

    import numpy as np

    from cimba_tpu.core import loop as _cl
    from cimba_tpu.obs import audit as _obs_audit
    from cimba_tpu.obs import metrics as _metrics
    from cimba_tpu.serve import cache as _pcache

    R = int(n_replications)
    if R <= 0:
        raise ValueError(f"n_replications must be positive, got {R}")
    if wave_size is None or wave_size >= R:
        wave_size = R
    if wave_size <= 0:
        raise ValueError(f"wave_size must be positive, got {wave_size}")
    if mesh is not None:
        n_dev = mesh.devices.size
        if wave_size % n_dev or R % n_dev:
            raise ValueError(
                f"wave_size={wave_size} and n_replications={R} must "
                f"divide evenly over {n_dev} devices"
            )

    aud = _obs_audit.resolve(audit)
    use_audit = aud is not None
    spec0 = spec  # regrow replaces spec; the card cites the original

    with_metrics = _metrics.enabled()
    acc = _pcache.stream_acc(spec, with_metrics)

    # the program cache, its keys, the fold program, and the preflight
    # all live in serve/cache.py now — the serving layer's request
    # compatibility key IS the program key, so both stay one definition
    programs = (
        program_cache if program_cache is not None else _pcache.ProgramCache()
    )
    fold_j = _pcache.get_fold(programs, with_metrics, summary_path)

    def get_programs(spec):
        # one (init, chunk) program pair per (spec STRUCTURE, settings)
        # point — seed and t_end are per-lane columns now, NOT program
        # constants, so calls differing only in them share compiled
        # programs (the Tier-A packing contract, docs/14_wave_packing);
        # jit re-specializes per wave shape internally (full waves
        # share one compile).  Regrow's dataclasses.replace doubles
        # event_cap, which changes the structural fingerprint, so
        # grown capacities get their own programs as before.
        return _pcache.get_programs(
            programs, spec, mesh=mesh, pack=pack,
            chunk_steps=chunk_steps, with_metrics=with_metrics,
            audit=use_audit,
        )

    init_probe, _ = get_programs(spec)
    _pcache.preflight_summary_path(
        programs, spec, init_probe, summary_path, params,
        R, min(wave_size, R), with_metrics,
    )

    on_wave, on_chunk = _tel_hooks(telemetry, "stream", on_wave, on_chunk)
    rec = telemetry.spans if telemetry is not None else None
    trace = None
    if rec is not None:
        trace = rec.new_trace()
        rec.start(
            trace, "stream", spec=spec.name, R=R, wave_size=wave_size,
        )

    grow_errs = (_cl.ERR_EVENT_OVERFLOW,)
    n_waves = 0
    n_regrows = 0
    lo = 0
    try:
        while lo < R:
            n = min(wave_size, R - lo)
            reps = jnp.arange(lo, lo + n)
            pw = _slice_params(params, R, lo, n)
            seeds = _seed_column(seed, n)
            # no horizon -> NO t_stop leaf: the chunk cond then skips
            # the per-event next-event-min + compare entirely (the
            # historical t_end=None jaxpr — per-event cost matters on
            # the headline path).  jit re-specializes per pytree
            # structure under the same program key, so both variants
            # share the cache entry.
            t_stops = None if t_end is None else _horizon_column(t_end, n)
            on_digest = None
            if use_audit:
                def on_digest(c, d, _w=n_waves, _aud=aud):
                    _aud.on_chunk(_w, c, d)
            while True:
                init_j, chunk_j = get_programs(spec)
                sims = init_j(reps, seeds, t_stops, pw)
                sims = drive_chunks(
                    chunk_j, sims, poll_every=poll_every,
                    on_chunk=on_chunk, on_digest=on_digest,
                )
                if n_regrows >= max_regrows:
                    break
                err = np.asarray(sims.err)
                if not np.isin(err, grow_errs).any():
                    break
                # wave-granular regrow: double the event cap and re-run
                # THIS wave (healthy lanes reproduce bit-identically —
                # streams are counter-derived); later waves keep the
                # grown spec.  Drop the failed wave's sims before the
                # re-init allocates — holding the name across init_j
                # would peak at TWO waves of HBM
                spec = dataclasses.replace(
                    spec, event_cap=2 * spec.event_cap
                )
                n_regrows += 1
                sims = None
            acc = fold_j(acc, sims)
            # release the wave's batched sims before the next wave's
            # init allocates: the one-wave peak-memory contract (fold_j
            # has the buffers; the host must not keep a second live
            # reference)
            sims = None
            n_waves += 1
            lo += n
            if rec is not None:
                rec.event(trace, "wave", n=n_waves, lanes_done=lo)
            if on_wave is not None:
                on_wave(n_waves, lo)
    except BaseException:
        if rec is not None:
            rec.end_trace(trace, "error")
        raise
    if rec is not None:
        rec.end_trace(trace, "completed", n_waves=n_waves)

    result = StreamResult(
        summary=acc[0],
        n_failed=acc[1],
        total_events=acc[2],
        n_waves=n_waves,
        n_regrows=n_regrows,
        metrics=acc[3] if with_metrics else None,
    )
    if use_audit:
        from cimba_tpu import config as _config
        from cimba_tpu.serve import store as _pstore

        try:
            pkey = _pstore.store_key(
                spec0, with_metrics, mesh=mesh, pack=pack,
                chunk_steps=chunk_steps,
            )
        except Exception:
            pkey = None  # unstable spec: the card's spec block says why
        card = aud.finalize(
            "stream",
            spec=spec0,
            seed_schedule={"seed": int(seed)},
            geometry={
                "R": R,
                "wave_size": wave_size,
                "chunk_steps": chunk_steps,
                "poll_every": poll_every,
                "t_end": t_end,
                "pack": bool(
                    pack if pack is not None
                    else _config.xla_pack_enabled()
                ),
                "profile": _config.active_profile(),
                "with_metrics": with_metrics,
                "mesh": _pstore._mesh_descriptor(mesh),
                "n_waves": n_waves,
                "n_regrows": n_regrows,
            },
            program_key=pkey,
            result_digest=_obs_audit.stream_result_digest(result),
            schedule=sched_block,
            telemetry=(
                telemetry.snapshot() if telemetry is not None else None
            ),
        )
        result = result._replace(audit=card)
    return result


def pooled_summary(batched: sm.Summary) -> sm.Summary:
    """Merge per-replication summaries into one (host-side / jit-able)."""
    return jax.jit(sm.merge_tree)(batched)


def make_sharded_experiment(
    spec: ModelSpec, n_replications: int, mesh: Mesh, *,
    summary_path=default_summary_path,
    t_end: Optional[float] = None,
    pack: Optional[bool] = None,
):
    """Build the fully-fused multi-chip experiment step: run all local
    replications AND reduce statistics over the mesh inside one jitted
    program (per-shard Pébay partials ride an all_gather over ICI, the
    scalar counters a psum).  Returns ``fn(params, seed=0) ->
    (pooled Summary, n_failed, total_events)`` — everything replicated.

    When the metrics registry is enabled (``obs.metrics.enable()``) at
    build time, the return gains a fourth element: the registry pooled
    over lanes AND the mesh (psum for counters/histograms, pmax for
    high-water gauges — the same ICI layer the summaries ride).  The
    flag binds here, like logger flags bind at trace time: don't flip it
    between build and run.
    """
    from cimba_tpu.obs import metrics as _metrics

    run = make_run(spec, t_end=t_end, pack=pack)
    reps = jnp.arange(n_replications)
    with_metrics = _metrics.enabled()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(REP_AXIS), P(REP_AXIS), P()),
        out_specs=(P(), P(), P()) + ((P(),) if with_metrics else ()),
        check_vma=False,
    )
    def sharded(reps_local, p_local, seed):
        def one_seeded(rep, p):
            return run(init_sim(spec, seed, rep, p))

        sims = jax.vmap(one_seeded)(reps_local, p_local)
        if (sims.metrics is None) == with_metrics:
            # the flag bound at build time; init_sim re-reads it at trace
            # time — fail with the subsystem's loud, named error instead
            # of an opaque NoneType crash deep in the shard_map trace
            raise RuntimeError(
                "make_sharded_experiment: obs.metrics was "
                f"{'enabled' if with_metrics else 'disabled'} when this "
                "experiment was built but flipped before the first call "
                "— the flag binds at build time (like logger flags at "
                "trace time); rebuild the experiment after changing it"
            )
        local = sm.merge_tree(summary_path(sims))
        # gather per-shard partial summaries over ICI, merge identically
        # everywhere (merge is not a plain sum, so psum cannot do it)
        gathered = jax.tree.map(
            lambda x: jax.lax.all_gather(x, REP_AXIS), local
        )
        pooled = sm.merge_tree(gathered)
        n_failed = jax.lax.psum(
            jnp.sum((sims.err != 0).astype(jnp.int32)), REP_AXIS
        )
        events = jax.lax.psum(jnp.sum(sims.n_events), REP_AXIS)
        if with_metrics:
            pooled_metrics = _metrics.pool_across(
                _metrics.pool(sims.metrics), REP_AXIS
            )
            return pooled, n_failed, events, pooled_metrics
        return pooled, n_failed, events

    def experiment(params, seed=0):
        pb = _broadcast_params(params, n_replications)
        return sharded(reps, pb, jnp.asarray(seed, jnp.uint64))

    return jax.jit(experiment)