"""The experiment runner: replications fanned over lanes and chips.

Reference parity: ``cimba_run`` (`src/cimba.c:232-276`) — a pthread worker
pool pulling trials off an atomic counter, with per-thread init/exit hooks
and longjmp failure recovery, returning the failed-trial count.

TPU redesign: replications are the leading axis of every state array.

* The atomic work-stealing dispenser disappears: partitioning is static —
  replication r is lane r of the batch (`vmap`), shard r // per_device of
  the mesh (`shard_map`).  Balanced because every replication runs the
  same model; divergence in *length* is absorbed by the batched
  while-loop's masking.
* Thread hooks (the reference's per-thread CUDA stream setup,
  `tutorial/tut_5_3.c:854-880`) have no analog: SPMD code is identical on
  every chip, and device-local setup is XLA's job.
* Failure recovery: a failed replication freezes with ``sim.err`` set and
  is counted (`result.n_failed`) — the §3.5 longjmp story without a
  longjmp, and unlike the reference the failed replication's partial state
  remains inspectable.
* Cross-replication statistics: ``pooled_summary`` tree-merges the
  per-replication Pébay summaries; under a mesh the per-shard partials go
  through ``all_gather`` over ICI and merge identically on every device.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map as _shard_map_impl
except ImportError:  # older jax keeps shard_map under experimental
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def shard_map(f, **kw):
    """``jax.shard_map`` across jax versions: older releases live under
    ``jax.experimental`` and spell ``check_vma`` as ``check_rep``."""
    import inspect

    if "check_vma" in kw and (
        "check_vma" not in inspect.signature(_shard_map_impl).parameters
    ):
        kw["check_rep"] = kw.pop("check_vma")
    return _shard_map_impl(f, **kw)

from cimba_tpu.core.loop import Sim, init_sim, make_run
from cimba_tpu.core.model import ModelSpec
from cimba_tpu.stats import summary as sm

REP_AXIS = "rep"


class ExperimentResult(NamedTuple):
    sims: Sim                 # batched: every leaf has leading axis [R]
    n_failed: jnp.ndarray     # replications with err != 0
    total_events: jnp.ndarray # dispatched events across all replications


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D replication mesh over the available devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(devs, (REP_AXIS,))


def _broadcast_params(params: Any, n: int):
    """Scalar params broadcast to [n]; leaves already [n, ...] pass through."""
    def bc(x):
        x = jnp.asarray(x)
        if x.ndim > 0 and x.shape[0] == n:
            return x
        return jnp.broadcast_to(x, (n,) + x.shape)

    return jax.tree.map(bc, params)


def run_experiment(
    spec: ModelSpec,
    params: Any,
    n_replications: int,
    *,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    t_end: Optional[float] = None,
    with_report: bool = False,
    profile_dir: Optional[str] = None,
    pack: Optional[bool] = None,
):
    """Run ``n_replications`` independent replications of ``spec``.

    ``params`` is the experiment array (reference: the user's trial struct
    array): a pytree whose leaves are either scalars (shared by all
    replications) or arrays with leading axis ``n_replications`` (a
    parameter sweep — the M/G/1 4x5x10 sweep pattern).

    ``with_report=True`` returns ``(ExperimentResult, obs.prof.RunReport)``
    instead: the run goes through the AOT path so the report carries the
    trace/compile/execute wall-time split, plus device memory stats and —
    when the metrics registry is enabled — the pooled metrics snapshot.
    ``profile_dir`` additionally wraps the execute leg in a
    ``jax.profiler.trace`` context writing there.

    ``pack`` selects the while-loop carry layout (see
    :func:`cimba_tpu.core.loop.make_run`; None = the
    ``CIMBA_XLA_PACK``/backend auto default) — trajectory-identical
    either way, bench.py measures both arms through this knob.
    """
    run = make_run(spec, t_end=t_end, pack=pack)
    pb = _broadcast_params(params, n_replications)
    reps = jnp.arange(n_replications)

    def one(rep, p):
        return run(init_sim(spec, seed, rep, p))

    vm = jax.vmap(one)

    timings = None
    if mesh is None:
        fn = vm
    else:
        n_dev = mesh.devices.size
        if n_replications % n_dev:
            raise ValueError(
                f"n_replications={n_replications} must divide evenly over "
                f"{n_dev} devices"
            )

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(REP_AXIS), P(REP_AXIS)),
            out_specs=P(REP_AXIS),
            check_vma=False,  # cond/switch branches mix replicated constants
            # with varying data; semantics are plain SPMD over 'rep'
        )
        def sharded(reps_local, p_local):
            return vm(reps_local, p_local)

        fn = sharded

    if with_report:
        from cimba_tpu.obs import prof as _prof

        sims, timings = _prof.profiled_call(
            jax.jit(fn), reps, pb, profile_dir=profile_dir
        )
    else:
        sims = jax.jit(fn)(reps, pb)

    result = ExperimentResult(
        sims=sims,
        n_failed=jnp.sum((sims.err != 0).astype(jnp.int32)),
        total_events=jnp.sum(sims.n_events),
    )
    if not with_report:
        return result
    from cimba_tpu.obs import metrics as _metrics

    snap = None
    if sims.metrics is not None:
        snap = _metrics.snapshot(jax.jit(_metrics.pool)(sims.metrics), spec)
    report = _prof.build_report(
        timings,
        n_replications=n_replications,
        n_failed=int(result.n_failed),
        total_events=int(result.total_events),
        metrics=snap,
        profile_dir=profile_dir,
    )
    return result, report


def run_experiment_regrow(
    spec: ModelSpec,
    params: Any,
    n_replications: int,
    *,
    seed: int = 0,
    mesh: Optional[Mesh] = None,
    t_end: Optional[float] = None,
    max_regrows: int = 4,
    pack: Optional[bool] = None,
):
    """``run_experiment`` with the capacity escape hatch: if any
    replication died with ``ERR_EVENT_OVERFLOW``/``ERR_GUARD_OVERFLOW``,
    double the event cap and re-run the WHOLE batch under the grown
    spec (a re-jit at the larger shapes).

    Reference parity: the reference's hashheap grows amortized-doubling
    under the hood (`src/cmi_hashheap.c:384-426`); under jit capacities
    are static shapes, so growth happens between jit calls instead.
    Re-running every lane (not only the overflowed ones) keeps the
    batched Sim shape-consistent, and costs nothing in correctness:
    replication streams are counter-derived from (seed, rep), so healthy
    lanes reproduce bit-identically under any capacity.

    Returns ``(result, final_spec, n_regrows)`` — ``final_spec`` is what
    actually ran last (callers reuse it to skip re-discovery).
    """
    import dataclasses

    import numpy as np

    from cimba_tpu.core import loop as _cl

    # dense guards cannot overflow; the event table is the one growable cap
    grow_errs = (_cl.ERR_EVENT_OVERFLOW,)
    for n_regrows in range(max_regrows + 1):
        result = run_experiment(
            spec, params, n_replications, seed=seed, mesh=mesh,
            t_end=t_end, pack=pack,
        )
        err = np.asarray(result.sims.err)
        if not np.isin(err, grow_errs).any():
            return result, spec, n_regrows
        if n_regrows < max_regrows:
            spec = dataclasses.replace(
                spec, event_cap=2 * spec.event_cap,
            )
    raise RuntimeError(
        f"run_experiment_regrow: capacity overflow persists after "
        f"{max_regrows} doublings (last run at event_cap={spec.event_cap}) "
        "— the model schedules unboundedly or the cap estimate is "
        "pathologically low"
    )


def pooled_summary(batched: sm.Summary) -> sm.Summary:
    """Merge per-replication summaries into one (host-side / jit-able)."""
    return jax.jit(sm.merge_tree)(batched)


def make_sharded_experiment(
    spec: ModelSpec, n_replications: int, mesh: Mesh, *,
    summary_path=lambda sims: sims.user["wait"],
    t_end: Optional[float] = None,
    pack: Optional[bool] = None,
):
    """Build the fully-fused multi-chip experiment step: run all local
    replications AND reduce statistics over the mesh inside one jitted
    program (per-shard Pébay partials ride an all_gather over ICI, the
    scalar counters a psum).  Returns ``fn(params, seed=0) ->
    (pooled Summary, n_failed, total_events)`` — everything replicated.

    When the metrics registry is enabled (``obs.metrics.enable()``) at
    build time, the return gains a fourth element: the registry pooled
    over lanes AND the mesh (psum for counters/histograms, pmax for
    high-water gauges — the same ICI layer the summaries ride).  The
    flag binds here, like logger flags bind at trace time: don't flip it
    between build and run.
    """
    from cimba_tpu.obs import metrics as _metrics

    run = make_run(spec, t_end=t_end, pack=pack)
    reps = jnp.arange(n_replications)
    with_metrics = _metrics.enabled()

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(REP_AXIS), P(REP_AXIS), P()),
        out_specs=(P(), P(), P()) + ((P(),) if with_metrics else ()),
        check_vma=False,
    )
    def sharded(reps_local, p_local, seed):
        def one_seeded(rep, p):
            return run(init_sim(spec, seed, rep, p))

        sims = jax.vmap(one_seeded)(reps_local, p_local)
        if (sims.metrics is None) == with_metrics:
            # the flag bound at build time; init_sim re-reads it at trace
            # time — fail with the subsystem's loud, named error instead
            # of an opaque NoneType crash deep in the shard_map trace
            raise RuntimeError(
                "make_sharded_experiment: obs.metrics was "
                f"{'enabled' if with_metrics else 'disabled'} when this "
                "experiment was built but flipped before the first call "
                "— the flag binds at build time (like logger flags at "
                "trace time); rebuild the experiment after changing it"
            )
        local = sm.merge_tree(summary_path(sims))
        # gather per-shard partial summaries over ICI, merge identically
        # everywhere (merge is not a plain sum, so psum cannot do it)
        gathered = jax.tree.map(
            lambda x: jax.lax.all_gather(x, REP_AXIS), local
        )
        pooled = sm.merge_tree(gathered)
        n_failed = jax.lax.psum(
            jnp.sum((sims.err != 0).astype(jnp.int32)), REP_AXIS
        )
        events = jax.lax.psum(jnp.sum(sims.n_events), REP_AXIS)
        if with_metrics:
            pooled_metrics = _metrics.pool_across(
                _metrics.pool(sims.metrics), REP_AXIS
            )
            return pooled, n_failed, events, pooled_metrics
        return pooled, n_failed, events

    def experiment(params, seed=0):
        pb = _broadcast_params(params, n_replications)
        return sharded(reps, pb, jnp.asarray(seed, jnp.uint64))

    return jax.jit(experiment)