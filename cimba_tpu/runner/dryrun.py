"""In-process body of the multi-chip dry run (see ``__graft_entry__``).

This module is imported by a *subprocess* whose environment already forces
the CPU backend with ``--xla_force_host_platform_device_count=N`` — the
dry run is a correctness check of the sharded program on a virtual mesh,
and must stay green regardless of real-accelerator/tunnel state.  Keep jax
imports inside the function so importing this module never touches a
backend.
"""

from __future__ import annotations


def run_dryrun(n_devices: int) -> None:
    """Full experiment step over an ``n_devices`` mesh: replications shard
    over the 'rep' axis (the DES analog of data parallelism — a discrete-
    event simulator has no tensor/pipeline dims; its scale axes are
    replications across chips and, later, intra-trial agents across lanes),
    with per-shard Pébay statistics merged via all_gather and scalar
    counters via psum over the mesh.  One step on tiny shapes.
    """
    import jax

    from cimba_tpu.models import mm1
    from cimba_tpu.runner import experiment as ex
    from cimba_tpu.stats import summary as sm

    mesh = ex.make_mesh(n_devices)
    spec, _ = mm1.build()
    fn = ex.make_sharded_experiment(spec, 2 * n_devices, mesh)
    pooled, n_failed, events = jax.block_until_ready(
        fn(mm1.params(20), seed=1)
    )
    assert int(n_failed) == 0, f"dryrun had failed replications: {n_failed}"
    assert int(pooled.n) == 2 * n_devices * 20, int(pooled.n)
    assert float(sm.mean(pooled)) > 0.0
    print(
        f"dryrun_multichip OK: {n_devices} devices, "
        f"{int(events)} events, mean wait {float(sm.mean(pooled)):.3f}",
        flush=True,
    )


if __name__ == "__main__":
    import sys

    run_dryrun(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
