"""In-process body of the multi-chip dry run (see ``__graft_entry__``).

This module is imported by a *subprocess* whose environment already forces
the CPU backend with ``--xla_force_host_platform_device_count=N`` — the
dry run is a correctness check of the sharded program on a virtual mesh,
and must stay green regardless of real-accelerator/tunnel state.  Keep jax
imports inside the function so importing this module never touches a
backend.
"""

from __future__ import annotations


def run_dryrun(n_devices: int) -> None:
    """Full experiment step over an ``n_devices`` mesh: replications shard
    over the 'rep' axis (the DES analog of data parallelism — a discrete-
    event simulator has no tensor/pipeline dims; its scale axes are
    replications across chips and, later, intra-trial agents across lanes),
    with per-shard Pébay statistics merged via all_gather and scalar
    counters via psum over the mesh.  One step on tiny shapes.
    """
    import jax

    from cimba_tpu.models import mm1
    from cimba_tpu.runner import experiment as ex
    from cimba_tpu.stats import summary as sm

    mesh = ex.make_mesh(n_devices)
    spec, _ = mm1.build()
    # volume matters: 32 reps/device x 50 objects is enough to catch a
    # cross-shard statistics bug (wrong merge weights, shard overlap,
    # dropped shard) that a smoke-sized run would slip past
    reps = 32 * n_devices
    fn = ex.make_sharded_experiment(spec, reps, mesh)
    pooled, n_failed, events = jax.block_until_ready(
        fn(mm1.params(50), seed=1)
    )
    assert int(n_failed) == 0, f"dryrun had failed replications: {n_failed}"
    assert int(pooled.n) == reps * 50, int(pooled.n)
    mean = float(sm.mean(pooled))
    assert mean > 0.0
    if n_devices == 8:
        # golden pooled mean for the canonical driver configuration
        # (f64 path, seed=1, 256 reps x 50 objects): device placement
        # must not leak into pooled statistics.  Regenerated round 5
        # with the fused-verb mm1 cycle (stream order shifted — see
        # tests/test_golden.py).
        golden = 4.112945867223963
        assert abs(mean - golden) <= 1e-9 * golden, (mean, golden)

    # the chunked/streamed arm over the same mesh: waves of (local lanes
    # x devices) through one donated chunk program under shard_map must
    # reproduce the monolithic sharded experiment's event count and
    # pooled statistics (stream fold = associative Pébay merge)
    stream_events = _dryrun_stream_mesh(
        mesh, n_devices, spec, reps, int(events), pooled
    )
    # the serving layer over the same mesh: concurrent requests packed
    # into shared sharded waves must return per-request results
    # IDENTICAL to direct single-caller streamed runs
    serve_events = _dryrun_serve_mesh(mesh, n_devices, spec)
    # the Pallas kernel path over the same mesh (interpret mode on the
    # virtual devices; Mosaic-compiled on real chips): per-device chunk
    # kernels under shard_map must agree with the XLA path's event counts
    kernel_events = _dryrun_kernel_mesh(mesh, n_devices)
    # the flagship (AWACS) through kernel + boundary blocks over the
    # mesh: DES chunks shard per device, the MXU dwell scorer applies
    # between chunks on the sharded batch — the full v5e-8 shape
    awacs_events = _dryrun_awacs_mesh(mesh, n_devices)
    print(
        f"dryrun_multichip OK: {n_devices} devices, "
        f"{int(events)} events, mean wait {float(sm.mean(pooled)):.3f}, "
        f"stream-mesh events {stream_events}, "
        f"serve-mesh events {serve_events}, "
        f"kernel-mesh events {kernel_events}, "
        f"awacs-boundary-mesh events {awacs_events}",
        flush=True,
    )


def _dryrun_stream_mesh(mesh, n_devices, spec, n_reps, mono_events,
                        mono_pooled) -> int:
    """Streamed waves over the mesh (runner.run_experiment_stream): the
    wave chunk program shards lanes per device (shard_map + donated
    re-dispatch, liveness psum-polled over ICI); pooled statistics must
    match the monolithic sharded experiment."""
    import jax

    from cimba_tpu.models import mm1
    from cimba_tpu.runner import experiment as ex
    from cimba_tpu.stats import summary as sm

    st = ex.run_experiment_stream(
        spec, mm1.params(50), n_reps,
        wave_size=8 * n_devices, chunk_steps=32, seed=1, mesh=mesh,
    )
    st = jax.block_until_ready(st)
    assert int(st.n_failed) == 0, f"stream dryrun failures: {st.n_failed}"
    assert int(st.total_events) == mono_events, (
        int(st.total_events), mono_events,
    )
    assert float(st.summary.n) == float(mono_pooled.n)
    m_mono, m_st = float(sm.mean(mono_pooled)), float(sm.mean(st.summary))
    assert abs(m_st - m_mono) <= 1e-9 * abs(m_mono), (m_st, m_mono)
    assert st.n_waves == n_reps // (8 * n_devices), st.n_waves
    return int(st.total_events)


def _dryrun_serve_mesh(mesh, n_devices, spec) -> int:
    """The serving layer on the virtual mesh (docs/13_serving.md):
    three threaded clients — two compatible (packed into one sharded
    wave), one a stranger (different seed) — each bitwise-identical to
    the direct mesh-sharded run_experiment_stream call through the
    same shared program cache."""
    import threading

    import jax
    import numpy as np

    from cimba_tpu import serve
    from cimba_tpu.models import mm1
    from cimba_tpu.runner import experiment as ex

    cache = serve.ProgramCache()
    per_req = 8 * n_devices
    cases = [("a", 40, 1), ("b", 60, 1), ("c", 40, 4)]
    out = {}
    with serve.Service(
        max_wave=4 * per_req, mesh=mesh, cache=cache
    ) as svc:
        def client(label, n, seed):
            out[label] = svc.submit(serve.Request(
                spec, mm1.params(n), per_req, seed=seed,
                wave_size=per_req, chunk_steps=32, label=label,
            )).result(600)

        ts = [threading.Thread(target=client, args=c) for c in cases]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    total = 0
    for label, n, seed in cases:
        direct = ex.run_experiment_stream(
            spec, mm1.params(n), per_req, wave_size=per_req,
            chunk_steps=32, seed=seed, mesh=mesh, program_cache=cache,
        )
        res = out[label]
        assert int(res.n_failed) == 0, f"serve-mesh {label} failures"
        for x, y in zip(
            jax.tree.leaves((res.summary, res.total_events)),
            jax.tree.leaves((direct.summary, direct.total_events)),
        ):
            np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y), err_msg=f"serve-mesh {label}"
            )
        total += int(res.total_events)
    return total


def _dryrun_model_mesh(mesh, n_devices: int, build, params, label) -> int:
    """Sharded mega-kernel dry run for one model: f32 profile, lanes
    split over the mesh, bitwise-compared against the single-device
    kernel run."""
    import jax
    import jax.numpy as jnp

    from cimba_tpu import config
    from cimba_tpu.core import loop as cl
    from cimba_tpu.core import pallas_run as pr

    with config.profile("f32"):
        spec, _ = build()

        def one(rep):
            return cl.init_sim(spec, 2026, rep, params)

        sims = jax.jit(jax.vmap(one))(jnp.arange(2 * n_devices))
        interp = jax.default_backend() != "tpu"
        single = pr.make_kernel_run(
            spec, chunk_steps=32, interpret=interp
        )(sims)
        sharded = pr.make_kernel_run(
            spec, chunk_steps=32, interpret=interp, mesh=mesh
        )(sims)
        assert bool((single.n_events == sharded.n_events).all()), label
        assert bool((single.clock == sharded.clock).all()), label
        assert int(sharded.err.sum()) == 0, f"{label} dryrun errors"
        # packed carry over the same mesh: the carry-layout change must
        # be invisible to the sharded trajectory too
        packed = pr.make_kernel_run(
            spec, chunk_steps=32, interpret=interp, mesh=mesh, packed=True
        )(sims)
        assert bool((single.n_events == packed.n_events).all()), (
            f"{label} packed"
        )
        assert bool((single.clock == packed.clock).all()), f"{label} packed"
        return int(sharded.n_events.sum())


def _dryrun_kernel_mesh(mesh, n_devices: int) -> int:
    from cimba_tpu.models import mm1

    return _dryrun_model_mesh(
        mesh, n_devices,
        build=lambda: mm1.build(record=False),
        params=(1.0 / 0.9, 1.0, 20),
        label="kernel-mesh",
    )


def _dryrun_awacs_mesh(mesh, n_devices: int) -> int:
    """Flagship: AWACS (boundary-block NN physics) sharded over the mesh."""
    from cimba_tpu.models import awacs

    return _dryrun_model_mesh(
        mesh, n_devices,
        build=lambda: awacs.build(8),
        params=awacs.params(1.0),
        label="awacs-mesh",
    )


if __name__ == "__main__":
    import sys

    run_dryrun(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
