"""Exporters: flight-recorder rings as Chrome-trace / Perfetto JSON.

The exported dict follows the Trace Event Format (the ``chrome://tracing``
/ Perfetto JSON schema): a ``traceEvents`` list of instant events, one per
recorded dispatch, with ``pid`` = replication index and ``tid`` = the
event subject (process id), so Perfetto's process/thread tracks render
replications as processes and simulated processes as threads.  Name
tables come from the model spec — the same tables
:mod:`cimba_tpu.utils.debug` renders golden dumps with.

Timestamps: Chrome trace ``ts`` is microseconds; simulated time is
unitless, so one simulated time unit is exported as one second
(``ts = t * 1e6``) to keep sub-unit event spacing visible.
"""

from __future__ import annotations

import json

import numpy as np

from cimba_tpu.obs import metrics as _metrics
from cimba_tpu.obs import trace as _trace
from cimba_tpu.utils.debug import kind_name as _kind_name
from cimba_tpu.utils.debug import subj_name as _subj_name

#: top-level keys every export carries (the CI smoke validates these)
REQUIRED_KEYS = ("traceEvents", "displayTimeUnit", "otherData")

#: microseconds per simulated time unit in the exported ``ts``
TS_SCALE = 1e6


def _lane(sims, r):
    import jax

    return jax.tree.map(lambda x: x[r], sims)


def chrome_trace(sims, spec=None) -> dict:
    """Build the Chrome-trace dict from a Sim (single replication or a
    batched one — every lane's ring becomes one trace-viewer process).

    Raises if the Sim carries no ring (recorder was disabled at init)."""
    batched = np.ndim(np.asarray(sims.clock)) > 0
    lanes = range(np.asarray(sims.clock).shape[0]) if batched else (None,)

    events = []
    total = 0
    for r in lanes:
        sim = _lane(sims, r) if r is not None else sims
        # the JSON pid is the LANE index (unique by construction), not
        # sim.rep: lanes may legitimately share a replication id (e.g. a
        # seed sweep at replication=0), and colliding pids would merge
        # their tracks; rep is kept in the process_name metadata
        rep = int(sim.rep)
        pid_track = r if r is not None else rep
        if sim.trace is None:
            raise ValueError(
                "chrome_trace: Sim carries no flight-recorder ring — "
                "call obs.trace.enable() before init_sim/run"
            )
        ring = _trace.unwrap(sim.trace)
        total += len(ring["seq"])
        seen_tids = {}
        for t, pid, kind, arg, seq in zip(
            ring["t"], ring["pid"], ring["kind"], ring["arg"], ring["seq"]
        ):
            pid, kind = int(pid), int(kind)
            events.append(
                {
                    "name": f"{_kind_name(kind, spec)} "
                    f"{_subj_name(pid, kind, spec)}",
                    "ph": "i",
                    "s": "t",
                    "ts": float(t) * TS_SCALE,
                    "pid": pid_track,
                    "tid": pid,
                    "args": {
                        "kind": kind,
                        "arg": int(arg),
                        "seq": int(seq),
                    },
                }
            )
            seen_tids.setdefault(pid, _subj_name(pid, kind, spec))
        # metadata rows name the tracks (Trace Event Format "M" events)
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid_track,
                "args": {"name": f"replication {rep}"},
            }
        )
        for tid, name in sorted(seen_tids.items()):
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid_track,
                    "tid": tid,
                    "args": {"name": name},
                }
            )

    other = {
        "model": spec.name if spec is not None else "?",
        "recorded_events": total,
        "ts_unit": "1 simulated time unit = 1 s",
    }
    if getattr(sims, "metrics", None) is not None:
        m = sims.metrics
        if batched:
            import jax

            m = jax.jit(_metrics.pool)(m)
        other["metrics"] = _metrics.snapshot(m, spec)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def dump_chrome_trace(path: str, sims, spec=None) -> dict:
    """Export to ``path`` (JSON); returns the dict that was written."""
    doc = chrome_trace(sims, spec)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def dump_service_trace(path: str, service) -> dict:
    """Export a :class:`cimba_tpu.serve.Service`'s request-lifecycle
    trace (one complete span per request + the queue-depth counter
    track — the same Trace Event Format schema as
    :func:`chrome_trace`, service stats in ``otherData.service``) to
    ``path`` after validation; returns the dict that was written."""
    doc = service.chrome_trace()
    validate_chrome_trace(doc)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def validate_chrome_trace(doc: dict) -> None:
    """Structural check used by the CI smoke: required top-level keys,
    non-empty events, per-event required fields, and per-replication
    monotone timestamps (dispatch order is time order)."""
    for k in REQUIRED_KEYS:
        if k not in doc:
            raise ValueError(f"chrome trace missing top-level key {k!r}")
    evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    if not evs:
        raise ValueError("chrome trace has no events")
    last_ts: dict = {}
    for e in evs:
        for k in ("name", "ph", "ts", "pid", "tid"):
            if k not in e:
                raise ValueError(f"trace event missing {k!r}: {e}")
        if e["ts"] < last_ts.get(e["pid"], float("-inf")):
            raise ValueError(
                f"timestamps not monotone within replication {e['pid']}"
            )
        last_ts[e["pid"]] = e["ts"]
