"""Run profiling: the compile-vs-execute split, device memory, RunReport.

On TPU the wall time of an experiment is dominated by two very different
costs — tracing+XLA compilation (host, once per (spec, shape)) and device
execution (the thing bench.py measures) — and conflating them is the
single most common profiling mistake with jit code.  :func:`profiled_call`
splits them with the AOT API (``lower``/``compile``), and
:class:`RunReport` packages the split with device memory stats and a
metrics snapshot: the run's whole observability story in one JSON-able
object, surfaced by ``run_experiment(..., with_report=True)`` and the
bench battery's metrics section.
"""

from __future__ import annotations

import dataclasses
import time
from contextlib import contextmanager
from typing import Any, Optional

import jax


@dataclasses.dataclass
class RunReport:
    """What one experiment run cost and did (all host-side scalars)."""

    trace_lower_s: float          # python tracing + StableHLO lowering
    compile_s: float              # XLA/backend compilation
    execute_s: float              # device execution (block_until_ready)
    n_replications: int
    n_failed: int
    total_events: int
    events_per_sec: float
    backend: str
    device_memory: Optional[dict] = None   # jax Device.memory_stats()
    metrics: Optional[dict] = None         # obs.metrics.snapshot (pooled)
    profile_dir: Optional[str] = None      # jax.profiler trace output, if any

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def device_memory_stats() -> Optional[dict]:
    """``memory_stats()`` of the first local device, None where the
    backend doesn't report (CPU)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    # ints only: the dict goes straight into BENCH_*.json
    return {k: int(v) for k, v in stats.items() if isinstance(v, (int, float))}


@contextmanager
def trace_ctx(profile_dir: Optional[str]):
    """``jax.profiler.trace`` scoped around the execute leg when a
    directory is given; a no-op otherwise.  View the output with
    Perfetto/TensorBoard."""
    if not profile_dir:
        yield
        return
    with jax.profiler.trace(profile_dir):
        yield


def profiled_call(fn, *args, profile_dir: Optional[str] = None):
    """Run jitted ``fn(*args)`` with the compile/execute split measured.

    Returns ``(out, timings)`` where timings is a dict with
    ``trace_lower_s``, ``compile_s``, ``execute_s``.  Uses the AOT path
    (``fn.lower().compile()``) so the three legs are cleanly separated;
    ``fn`` must be a ``jax.jit`` callable.
    """
    t0 = time.perf_counter()
    lowered = fn.lower(*args)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    with trace_ctx(profile_dir):
        out = jax.block_until_ready(compiled(*args))
    t3 = time.perf_counter()
    return out, {
        "trace_lower_s": t1 - t0,
        "compile_s": t2 - t1,
        "execute_s": t3 - t2,
    }


def build_report(
    timings: dict,
    *,
    n_replications: int,
    n_failed: int,
    total_events: int,
    metrics: Optional[dict] = None,
    profile_dir: Optional[str] = None,
) -> RunReport:
    ex = max(timings["execute_s"], 1e-12)
    return RunReport(
        trace_lower_s=timings["trace_lower_s"],
        compile_s=timings["compile_s"],
        execute_s=timings["execute_s"],
        n_replications=int(n_replications),
        n_failed=int(n_failed),
        total_events=int(total_events),
        events_per_sec=float(total_events) / ex,
        backend=jax.default_backend(),
        device_memory=device_memory_stats(),
        metrics=metrics,
        profile_dir=profile_dir,
    )
