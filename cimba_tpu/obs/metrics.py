"""The metrics registry: named counters/gauges/histograms as Sim arrays.

Reference parity: the reference has no first-class metrics — you grep its
logger output.  Here the dispatcher's own health signals are carried as
arrays *inside* the jitted program and pooled exactly like the model's
statistics: summed/maxed across vmap lanes, and over ICI via the same
``all_gather``/``psum`` path ``make_sharded_experiment`` uses for Pébay
summaries (counters and histogram bins are plain sums, so ``psum`` does
pool them; high-water gauges pool with ``pmax``).

Registry (fixed per spec, sized at ``init_sim``):

* ``dispatch_by_kind`` [NK] — events dispatched per kind (K_PROC,
  K_TIMER, user handlers); their sum is ``events_dispatched`` and equals
  ``sim.n_events``.
* ``guard_retries`` — pended commands re-attempted on a SUCCESS wake
  (the guard fairness protocol's retry arm firing).
* ``queue_hwm`` [NQ] — per-objectqueue length high-water mark.
* ``event_hwm`` — future-event-set occupancy high-water mark (general
  table + armed dense wakes): how close the run came to
  ``ERR_EVENT_OVERFLOW``.
* ``chain_hist`` [CHAIN_BINS] — histogram of blocks chained per dispatch
  (bin i = chain length i+1; last bin is overflow): the
  kernel-path cost model's central quantity, measured instead of guessed.

Trace-time gating mirrors :mod:`cimba_tpu.obs.trace`: disabled means
``Sim.metrics is None`` and every hook returns its input Sim object —
zero ops.  An enabled registry traced under ``config.KERNEL_MODE``
raises at build time (see the kernel-path contract in docs/07).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import dyn

_I = INDEX_DTYPE
_C = config.COUNT

#: chain-length histogram bins: lengths 1..CHAIN_BINS-1, last bin = longer
CHAIN_BINS = 8

_enabled = False


class Metrics(NamedTuple):
    """One replication's registry (pooled shapes are identical)."""

    dispatch_by_kind: jnp.ndarray  # [NK] COUNT
    guard_retries: jnp.ndarray     # COUNT
    queue_hwm: jnp.ndarray         # [NQ] i32
    event_hwm: jnp.ndarray         # i32
    chain_hist: jnp.ndarray        # [CHAIN_BINS] COUNT


def enable() -> None:
    """Enable the registry for subsequently *traced* runs (re-jit to take
    effect, like ``logger.flags_on``)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def create(n_kinds: int, n_queues: int) -> Metrics:
    """A zeroed registry; called by ``init_sim`` when enabled."""
    return Metrics(
        dispatch_by_kind=jnp.zeros((max(n_kinds, 1),), _C),
        guard_retries=jnp.zeros((), _C),
        queue_hwm=jnp.zeros((max(n_queues, 1),), _I),
        event_hwm=jnp.zeros((), _I),
        chain_hist=jnp.zeros((CHAIN_BINS,), _C),
    )


def _kernel_check() -> None:
    if config.KERNEL_MODE:
        raise RuntimeError(
            "obs.metrics: metrics registry updates inside the Pallas "
            "kernel path — carrying the registry through the chunked "
            "kernel must be a deliberate choice, not a leftover global "
            "flag.  Disable metrics for kernel runs "
            "(obs.metrics.disable()) or run on the XLA while-loop path "
            "(cl.make_run).  See docs/07_kernel_path.md."
        )


# --- update hooks (called from core/loop.py; no-ops when disabled) ---------


def on_dispatch(sim, kind, occupancy, pred):
    """Per dispatched event: count its kind and track event-set occupancy
    high-water (``occupancy`` = general-table live + armed wakes)."""
    m = sim.metrics
    if m is None:
        return sim
    _kernel_check()
    nk = m.dispatch_by_kind.shape[0]
    k = jnp.clip(jnp.asarray(kind, _I), 0, nk - 1)
    occ = jnp.where(pred, jnp.asarray(occupancy, _I), m.event_hwm)
    return sim._replace(
        metrics=m._replace(
            dispatch_by_kind=dyn.dadd(
                m.dispatch_by_kind, k, jnp.ones((), _C), pred
            ),
            event_hwm=jnp.maximum(m.event_hwm, occ),
        )
    )


def on_resume(sim, n_chain, retried):
    """Per resume: chain-length histogram and the guard-retry counter.
    ``n_chain`` is the chain loop's iteration count (0 when the resume
    was gated off — those are not counted)."""
    m = sim.metrics
    if m is None:
        return sim
    _kernel_check()
    ran = jnp.asarray(n_chain, _I) > 0
    bin_ = jnp.clip(jnp.asarray(n_chain, _I) - 1, 0, CHAIN_BINS - 1)
    return sim._replace(
        metrics=m._replace(
            chain_hist=dyn.dadd(m.chain_hist, bin_, jnp.ones((), _C), ran),
            guard_retries=m.guard_retries
            + (jnp.asarray(retried) & ran).astype(_C),
        )
    )


def on_queue_len(sim, qid, length, pred):
    """Per successful queue verb: ratchet the queue's high-water mark.
    Every write is gated by ``pred`` (the handler's ok-and-gate), so the
    hook is legal inside a ``_gated`` handler."""
    m = sim.metrics
    if m is None:
        return sim
    _kernel_check()
    length = jnp.asarray(length, _I)
    cur = dyn.dget(m.queue_hwm, qid)
    return sim._replace(
        metrics=m._replace(
            queue_hwm=dyn.dset(
                m.queue_hwm, qid, jnp.maximum(cur, length), pred
            )
        )
    )


# --- pooling ----------------------------------------------------------------


def events_dispatched(m: Metrics):
    """Total events across kinds (equals ``sim.n_events`` per lane, or
    their sum after pooling)."""
    return jnp.sum(m.dispatch_by_kind)


def pool(m: Metrics) -> Metrics:
    """Pool a batched registry (leading axis R) into one: counters and
    histogram bins sum — associative and commutative, so the merge is
    order-independent — and high-water gauges take the max."""
    return Metrics(
        dispatch_by_kind=jnp.sum(m.dispatch_by_kind, axis=0),
        guard_retries=jnp.sum(m.guard_retries, axis=0),
        queue_hwm=jnp.max(m.queue_hwm, axis=0),
        event_hwm=jnp.max(m.event_hwm, axis=0),
        chain_hist=jnp.sum(m.chain_hist, axis=0),
    )


def merge(a: Metrics, b: Metrics) -> Metrics:
    """Merge two (already lane-pooled) registries into one — the stream
    runner's wave fold (``run_experiment_stream``): counters and
    histogram bins add, high-water gauges max.  The same associative,
    commutative algebra :func:`pool` applies along the lane axis, so
    folding waves one at a time equals pooling all lanes at once."""
    return Metrics(
        dispatch_by_kind=a.dispatch_by_kind + b.dispatch_by_kind,
        guard_retries=a.guard_retries + b.guard_retries,
        queue_hwm=jnp.maximum(a.queue_hwm, b.queue_hwm),
        event_hwm=jnp.maximum(a.event_hwm, b.event_hwm),
        chain_hist=a.chain_hist + b.chain_hist,
    )


def pool_across(m: Metrics, axis_name: str) -> Metrics:
    """Pool an (already lane-pooled) registry across a mesh axis inside
    ``shard_map`` — the ICI leg: ``psum`` for the summable fields,
    ``pmax`` for the high-water gauges (the same collective layer
    ``make_sharded_experiment`` rides for summaries)."""
    return Metrics(
        dispatch_by_kind=jax.lax.psum(m.dispatch_by_kind, axis_name),
        guard_retries=jax.lax.psum(m.guard_retries, axis_name),
        queue_hwm=jax.lax.pmax(m.queue_hwm, axis_name),
        event_hwm=jax.lax.pmax(m.event_hwm, axis_name),
        chain_hist=jax.lax.psum(m.chain_hist, axis_name),
    )


def snapshot(m: Metrics, spec=None, regrows: Optional[int] = None) -> dict:
    """Host-side: the registry as a JSON-able dict, with names resolved
    from the model spec where one is given (kind/queue name tables, the
    same ones ``utils.debug`` renders with).  ``regrows`` attaches the
    runner's host-side capacity-regrow count when the caller has one."""
    import numpy as np

    from cimba_tpu.utils.debug import kind_name

    by_kind = np.asarray(m.dispatch_by_kind)
    dispatch = {}
    for k in range(by_kind.shape[0]):
        name = kind_name(k, spec)
        if name in dispatch:  # duplicate handler names must not collide
            name = f"{name}#{k}"
        dispatch[name] = int(by_kind[k])
    q_names = (
        [q.name for q in spec.queues] if spec and spec.queues else None
    )
    hwm = np.asarray(m.queue_hwm)
    queue_hwm = {
        (q_names[i] if q_names and i < len(q_names) else f"q{i}"): int(hwm[i])
        for i in range(hwm.shape[0])
    }
    out = {
        "events_dispatched": int(by_kind.sum()),
        "dispatch_by_kind": dispatch,
        "guard_retries": int(m.guard_retries),
        "queue_hwm": queue_hwm,
        "event_hwm": int(m.event_hwm),
        "chain_hist": [int(c) for c in np.asarray(m.chain_hist)],
    }
    if regrows is not None:
        out["regrows"] = int(regrows)
    return out
