"""The flight recorder: an on-device dispatch-event ring buffer.

Reference parity: ``cmb_event_queue_print`` shows the *pending* events at
one instant; the flight recorder keeps the last ``capacity`` events the
dispatcher *actually executed* — what a scheduler log would show, but as
arrays inside the jitted program ("observability must live inside the
compiled program" — the per-event host callback a naive log would need
serializes the very loop it observes).

Design, mirroring :mod:`cimba_tpu.utils.logger`:

* **Trace-time gating.**  :func:`enable`/:func:`disable` flip a Python
  global read while *tracing*; with the recorder disabled, ``Sim.trace``
  is ``None`` (the pytree prunes the leaves) and :func:`emit` returns the
  Sim object it was given — the dispatch site traces to literally zero
  ops.  Re-jit after flipping, exactly like logger flags.
* **Struct-of-arrays ring.**  ``(t, pid, kind, arg, seq)`` slots plus a
  monotone ``count``; slot ``count % capacity`` is overwritten, so the
  ring always holds the *last* ``min(count, capacity)`` dispatches.
  ``seq`` is the global dispatch index, so a wrapped ring still tells you
  exactly which events it kept.
* **Batched by vmap.**  The ring rides the Sim pytree: one independent
  ring per replication, sharded with the Sim over a mesh.
* **Kernel-path contract** (docs/07): an enabled recorder reached while
  tracing under ``config.KERNEL_MODE`` raises HERE, loudly, at build time
  — mirroring ``logger._emit``.  The ring's writes are Mosaic-legal ops,
  but its contents only mean something host-side, and hauling the ring
  through the chunked kernel carry is a cost the kernel path must opt
  into deliberately, not inherit from a leftover global flag.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import dyn

_I = INDEX_DTYPE
_T = config.TIME

#: default ring capacity (events kept per replication)
DEFAULT_CAPACITY = 256

_enabled = False
_capacity = DEFAULT_CAPACITY


class TraceRing(NamedTuple):
    """One replication's last ``capacity`` dispatched events."""

    t: jnp.ndarray      # [CAP] TIME — dispatch clock
    pid: jnp.ndarray    # [CAP] i32 — event subject (process id / user subj)
    kind: jnp.ndarray   # [CAP] i32 — dispatch kind (K_PROC/K_TIMER/user)
    arg: jnp.ndarray    # [CAP] i32 — event payload (signal code / user arg)
    seq: jnp.ndarray    # [CAP] i32 — global dispatch index; -1 = never written
    count: jnp.ndarray  # i32 — total dispatches recorded (wrap detector)


def enable(capacity: int = DEFAULT_CAPACITY) -> None:
    """Enable the recorder for subsequently *traced* runs (re-jit to take
    effect, like ``logger.flags_on``).  ``capacity`` bounds device memory:
    5 arrays x capacity per replication."""
    global _enabled, _capacity
    if capacity <= 0:
        raise ValueError(f"trace capacity must be positive, got {capacity}")
    _enabled = True
    _capacity = int(capacity)


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def capacity() -> int:
    return _capacity


def create(cap: int | None = None) -> TraceRing:
    """A fresh (empty) ring; called by ``init_sim`` when enabled."""
    cap = _capacity if cap is None else int(cap)
    return TraceRing(
        t=jnp.zeros((cap,), _T),
        pid=jnp.zeros((cap,), _I),
        kind=jnp.zeros((cap,), _I),
        arg=jnp.zeros((cap,), _I),
        seq=jnp.full((cap,), -1, _I),
        count=jnp.zeros((), _I),
    )


def _kernel_check() -> None:
    if config.KERNEL_MODE:
        raise RuntimeError(
            "obs.trace: flight-recorder emission inside the Pallas kernel "
            "path — the ring's contents are host-export state and hauling "
            "them through the chunked kernel carry must be a deliberate "
            "choice, not a leftover global flag.  Disable the recorder for "
            "kernel runs (obs.trace.disable(), the logger.flags_off "
            "analog) or run this model on the XLA while-loop path "
            "(cl.make_run).  See docs/07_kernel_path.md."
        )


def emit(sim, t, pid, kind, arg, pred):
    """Record one dispatched event, gated by ``pred`` (the dispatcher's
    event-found predicate).  Returns ``sim`` unchanged — the *same
    object*, zero traced ops — when the Sim carries no ring."""
    ring = sim.trace
    if ring is None:
        return sim
    _kernel_check()
    cap = ring.t.shape[0]
    slot = jnp.mod(ring.count, cap)
    armed = jnp.asarray(pred)
    ring2 = TraceRing(
        t=dyn.dset(ring.t, slot, jnp.asarray(t, _T), pred),
        pid=dyn.dset(ring.pid, slot, jnp.asarray(pid, _I), pred),
        kind=dyn.dset(ring.kind, slot, jnp.asarray(kind, _I), pred),
        arg=dyn.dset(ring.arg, slot, jnp.asarray(arg, _I), pred),
        seq=dyn.dset(ring.seq, slot, ring.count, pred),
        count=ring.count + armed.astype(_I),
    )
    return sim._replace(trace=ring2)


def unwrap(ring: TraceRing):
    """Host-side: the ring's valid entries in dispatch order.

    Returns a dict of numpy arrays ``{t, pid, kind, arg, seq}`` sorted by
    ``seq`` (the global dispatch index), holding the last
    ``min(count, capacity)`` recorded events.  Fetch one lane of a
    batched Sim first (``jax.tree.map(lambda x: x[r], sims)``), as with
    :mod:`cimba_tpu.utils.debug`.
    """
    import numpy as np

    seq = np.asarray(ring.seq)
    valid = seq >= 0
    order = np.argsort(seq[valid], kind="stable")
    out = {}
    for name in ("t", "pid", "kind", "arg", "seq"):
        out[name] = np.asarray(getattr(ring, name))[valid][order]
    out["count"] = int(ring.count)
    out["capacity"] = int(seq.shape[0])
    return out
