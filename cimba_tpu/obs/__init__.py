"""Observability: flight recorder, metrics registry, exporters, profiling.

The reference makes its event loop inspectable with ``cmb_logger``
flag-mask lines and ``cmb_event_queue_print`` golden dumps; this package
is the TPU-native half of that story — observability that lives *inside*
the compiled program as arrays, because a host callback cannot cross an
XLA while-loop iteration (let alone a Mosaic kernel) without serializing
the run it is meant to observe.

Three parts, all trace-time gated like :mod:`cimba_tpu.utils.logger`
(disabled = literally zero ops in the jaxpr):

* :mod:`~cimba_tpu.obs.trace` — the **flight recorder**: a
  capacity-bounded on-device ring buffer ``(t, pid, kind, arg, seq)``
  written at the dispatch site in ``core/loop.py``.  One ring per
  replication under ``vmap``.
* :mod:`~cimba_tpu.obs.metrics` — the **metrics registry**: named
  counters/gauges/histograms carried as Sim arrays (dispatches by kind,
  queue high-water marks, guard retries, chain-length histogram),
  pooled across replications and over ICI.
* :mod:`~cimba_tpu.obs.export` / :mod:`~cimba_tpu.obs.prof` —
  **exporters and profiling**: Chrome-trace/Perfetto JSON of a
  replication's ring, and a :class:`~cimba_tpu.obs.prof.RunReport`
  capturing the compile-vs-execute wall-time split, device memory and a
  metrics snapshot.

Kernel-path contract (docs/07): both the recorder and the metrics
registry raise a loud build-time error when an enabled instance is
traced under ``config.KERNEL_MODE`` — mirroring ``logger._emit``.

Host-side: :mod:`~cimba_tpu.obs.telemetry` (the serving control-plane's
time-series registry, request spans, health sampler — stdlib-only) and
:mod:`~cimba_tpu.obs.expose` (``/metrics`` Prometheus text, ``/healthz``,
``/varz`` over HTTP).  Opt-in with the same discipline: everything takes
``telemetry=None`` and a None means no threads, no span allocations, and
compiled programs bitwise-unchanged (docs/17_telemetry.md).

Provenance: :mod:`~cimba_tpu.obs.audit` — the determinism-audit plane
(docs/18_audit.md): chunk-boundary carry digests (trace-time gated,
``audit=False`` == jaxpr-identical), content-addressed run cards, and
divergence localization (``tools/audit_diff.py``).
"""

from cimba_tpu.obs import metrics, trace  # noqa: F401

# export, prof, telemetry, and expose are imported lazily by callers
# (they pull in numpy/json/http and the runner surface; the hot loop
# only ever needs trace/metrics)
