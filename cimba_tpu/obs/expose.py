"""Telemetry exposition: ``/metrics``, ``/healthz``, ``/varz`` over HTTP.

The scrape surface of the host-side telemetry plane
(:mod:`cimba_tpu.obs.telemetry`): a stdlib-only
``http.server.ThreadingHTTPServer`` — opt-in, never started implicitly —
serving

* ``/metrics`` — the registry in Prometheus text exposition format
  (version 0.0.4): counters, gauges, and the log2-bucket histograms
  rendered as cumulative ``_bucket{le=...}`` series;
* ``/healthz`` — the structured liveness verdict
  (:meth:`~cimba_tpu.obs.telemetry.Telemetry.healthz`): HTTP 200 for
  ``ok``/``degraded``, 503 for ``unhealthy`` (a dead or stalled
  dispatcher), JSON body either way;
* ``/varz`` — the full JSON snapshot (registry with history rings, raw
  service stats, span counters).

Also here: :func:`render_prometheus` (the formatter), and
:func:`parse_prometheus_text` — the minimal parser the round-trip tests
and ``tools/metrics_dump.py`` share, so "the text we emit parses" is
checked against one in-repo definition, not by eyeball.

See docs/17_telemetry.md for the scrape-config snippet.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from cimba_tpu.obs.telemetry import Telemetry

__all__ = [
    "render_prometheus", "parse_prometheus_text",
    "ExpositionServer", "start",
]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return (
        str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")
    )


def _unescape_label(v: str) -> str:
    """Invert :func:`_escape_label` one character at a time — a chain
    of str.replace calls cannot (``\\n`` produced by escaping a real
    backslash-then-n must not come back as a newline)."""
    out = []
    i = 0
    while i < len(v):
        ch = v[i]
        if ch == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append(
                {"n": "\n", '"': '"', "\\": "\\"}.get(nxt, ch + nxt)
            )
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() else repr(f)


def render_prometheus(registry) -> str:
    """The registry as Prometheus text exposition format.  Histograms
    render their sparse log2 buckets cumulatively with ``le`` at the
    bucket's upper power-of-two boundary plus the mandatory
    ``le="+Inf"``, ``_sum``, and ``_count`` series."""
    lines = []
    for fam in registry.collect():
        name, kind = fam["name"], fam["kind"]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {kind}")
        for s in fam["series"]:
            labels = s["labels"]
            if kind == "histogram":
                acc = 0
                for e in sorted(s["buckets"]):
                    acc += s["buckets"][e]
                    bl = dict(labels)
                    bl["le"] = _fmt_value(2.0 ** e)
                    lines.append(
                        f"{name}_bucket{_fmt_labels(bl)} {acc}"
                    )
                bl = dict(labels)
                bl["le"] = "+Inf"
                lines.append(f"{name}_bucket{_fmt_labels(bl)} {s['count']}")
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} "
                    f"{_fmt_value(s['sum'])}"
                )
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {s['count']}"
                )
            else:
                lines.append(
                    f"{name}{_fmt_labels(labels)} {_fmt_value(s['value'])}"
                )
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> dict:
    """Minimal Prometheus text parser (the subset
    :func:`render_prometheus` emits): returns ``{"types": {name: kind},
    "samples": {name: {(("label","value"), ...): float}}}`` with label
    tuples sorted by key.  Raises ``ValueError`` on a malformed line —
    the round-trip tests lean on that."""
    types: Dict[str, str] = {}
    samples: Dict[str, Dict[tuple, float]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            lab_str, _, val_str = rest.rpartition("}")
            val_str = val_str.strip()
            labels = []
            buf = []
            # split on commas outside quotes, tracking escapes — a
            # quote right after an escaped backslash ("a\\") CLOSES the
            # value, and a naive last-char check would miss that
            in_q = False
            esc = False
            cur = ""
            for ch in lab_str:
                if in_q:
                    cur += ch
                    if esc:
                        esc = False
                    elif ch == "\\":
                        esc = True
                    elif ch == '"':
                        in_q = False
                elif ch == '"':
                    in_q = True
                    cur += ch
                elif ch == ",":
                    buf.append(cur)
                    cur = ""
                else:
                    cur += ch
            if in_q:
                raise ValueError(f"unterminated label value: {raw!r}")
            if cur:
                buf.append(cur)
            for item in buf:
                if "=" not in item:
                    raise ValueError(f"malformed label in line: {raw!r}")
                k, v = item.split("=", 1)
                v = v.strip()
                if not (v.startswith('"') and v.endswith('"')):
                    raise ValueError(f"unquoted label value: {raw!r}")
                labels.append((k.strip(), _unescape_label(v[1:-1])))
            key = tuple(sorted(labels))
        else:
            parts = line.split()
            if len(parts) != 2:
                raise ValueError(f"malformed sample line: {raw!r}")
            name, val_str = parts
            key = ()
        name = name.strip()
        if not name:
            raise ValueError(f"empty metric name: {raw!r}")
        try:
            val = float(val_str.replace("+Inf", "inf"))
        except ValueError as e:
            raise ValueError(f"malformed value in line: {raw!r}") from e
        samples.setdefault(name, {})[key] = val
    return {"types": types, "samples": samples}


class ExpositionServer:
    """The opt-in HTTP exposition server over one
    :class:`~cimba_tpu.obs.telemetry.Telemetry` plane.

    ``port=0`` binds an ephemeral port (read it back from ``.port`` /
    ``.url``).  The server thread and every handler thread are daemons;
    :meth:`close` shuts the listener down.  Binding is loopback by
    default — exposing a fleet means fronting this with real infra, not
    flipping the default.  ``delay_s`` stalls every response by that
    long — the fleet chaos plane's ``scrape_delay_ms`` knob
    (docs/20_fleet.md), which is how the health poller's timeout path
    gets exercised deterministically; 0 (the default) adds nothing."""

    def __init__(self, telemetry: Telemetry, host: str = "127.0.0.1",
                 port: int = 0, delay_s: float = 0.0):
        self.telemetry = telemetry
        self.delay_s = float(delay_s)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):     # quiet: no stderr per scrape
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if outer.delay_s > 0:
                    time.sleep(outer.delay_s)
                try:
                    if path == "/metrics":
                        body = render_prometheus(
                            outer.telemetry.registry
                        ).encode()
                        self._send(200, body, CONTENT_TYPE)
                    elif path == "/healthz":
                        h = outer.telemetry.healthz()
                        code = 200 if h["ok"] else 503
                        self._send(
                            code, json.dumps(h, indent=2).encode(),
                            "application/json",
                        )
                    elif path == "/varz":
                        self._send(
                            200,
                            json.dumps(outer.telemetry.varz()).encode(),
                            "application/json",
                        )
                    else:
                        self._send(
                            404,
                            b'{"error": "try /metrics, /healthz, /varz"}',
                            "application/json",
                        )
                except BrokenPipeError:
                    pass           # scraper hung up mid-response
                except Exception as e:
                    # a scrape bug must return 500, not kill the thread
                    try:
                        self._send(
                            500,
                            json.dumps({"error": repr(e)}).encode(),
                            "application/json",
                        )
                    except OSError:
                        # CHK003 fix: the 500 can only fail because the
                        # socket is already gone (scraper hung up) —
                        # anything else must surface, not vanish in a
                        # handler thread
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="cimba-exposition", daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def start(telemetry: Telemetry, *, host: str = "127.0.0.1",
          port: int = 0, delay_s: float = 0.0) -> ExpositionServer:
    """Start the exposition server over ``telemetry`` (opt-in: nothing
    anywhere starts one implicitly).  Returns the running server; its
    ``.url`` is what you point a scrape config (or
    ``tools/metrics_dump.py``) at.  ``delay_s`` is the chaos-plane
    scrape stall (see :class:`ExpositionServer`)."""
    return ExpositionServer(telemetry, host=host, port=port,
                            delay_s=delay_s)
