"""Determinism audit & provenance plane (docs/18_audit.md).

Every bitwise claim this repo makes — pad-and-mask inertness
(docs/14), store hydration (docs/15), sweep round seeds (docs/16),
chunked == monolithic trajectories (docs/12) — was checkable only
inside a pytest process.  This module turns those claims into
**citable artifacts**:

* **Chunk-boundary carry digests** — with auditing enabled, the chunk
  program folds each packed carry class (the f32 / i32+u32 / f64 / i64
  classes of :mod:`cimba_tpu.core.carry`) into a per-wave u64 digest
  vector: every carried leaf is bitcast to its class's unsigned
  payload, each element mixed (fmix64) with its global (lane, offset,
  leaf) position, and the mixes summed mod 2^64 — an order-independent
  exact integer reduction, so the digest is deterministic under any
  XLA reduction order and combines across mesh shards with a plain
  ``psum``.  The host appends one digest row per chunk: the **digest
  trail**.  Trace-time gated in the :mod:`obs.trace` idiom: a chunk
  program built with ``audit=False`` (the default) is jaxpr
  character-identical to one built before this module existed (pinned
  in tests/test_audit.py).
* **Run cards** — a content-addressed JSON artifact per run: spec
  fingerprint (the store's value-based identity), seed schedule,
  resolved program key, environment block (jax/jaxlib/backend/x64/
  package — the same :func:`~cimba_tpu.obs.telemetry.build_info` dict
  ``/varz`` exposes), wave/chunk geometry, the digest trail, the
  result digest, and an optional telemetry snapshot.  The card digest
  excludes the creation timestamp, so two clean same-seed runs in two
  processes produce byte-for-byte the SAME card digest — "bitwise
  reproducible" becomes an equality between two hex strings.
* **Divergence localization** — :func:`diff_cards` /
  :func:`diff_trails` compare two trails and report the FIRST
  divergent (wave, chunk, carry-class); ``tools/audit_diff.py`` wraps
  them with CI-friendly exit codes.

Module-level imports are stdlib-only by design: the diff/report half
must stay loadable without jax (``tools/audit_diff.py`` file-loads this
module directly), so every device-facing function imports jax locally.
"""

from __future__ import annotations

# cimba-check: persist-path  (CHK001: run cards are disk artifacts —
# nothing id()-derived may feed them)

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "AUDIT_ENV", "CARD_FORMAT", "CLASS_NAMES",
    "Audit", "resolve", "sim_digest", "format_digests",
    "result_digest", "stream_result_digest",
    "run_card", "card_digest", "write_run_card", "load_run_card",
    "diff_trails", "diff_cards", "environment",
]

#: environment knob: unset/"0" = off, "1" = collect in memory, any
#: other value = a directory run cards are written into
AUDIT_ENV = "CIMBA_AUDIT"

#: run-card schema version (bump on incompatible layout changes)
CARD_FORMAT = 1

#: the packed carry classes digested, in `core.carry._CLASSES` order
CLASS_NAMES = ("f32", "i32", "f64", "i64")

_CLASS_BITS = {"f32": 32, "i32": 32, "f64": 64, "i64": 64}

_U64 = (1 << 64) - 1

#: splitmix64 golden gamma — the per-leaf salt stride
_GAMMA = 0x9E3779B97F4A7C15


def _fmix64_host(x: int) -> int:
    """murmur3 fmix64 on a python int (the host twin of the traced
    mixer — used for per-leaf salts, which are trace-time constants)."""
    x &= _U64
    x ^= x >> 33
    x = (x * 0xFF51AFD7ED558CCD) & _U64
    x ^= x >> 33
    x = (x * 0xC4CEB9FE1A85EC53) & _U64
    x ^= x >> 33
    return x


# ---------------------------------------------------------------------------
# device-side digest
# ---------------------------------------------------------------------------


def _fmix64(x):
    """murmur3 fmix64 elementwise on a u64 array (wrapping mults —
    XLA integer arithmetic is modular, so this is exact and
    deterministic on every backend)."""
    import jax.numpy as jnp

    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(0xFF51AFD7ED558CCD)
    x = x ^ (x >> jnp.uint64(33))
    x = x * jnp.uint64(0xC4CEB9FE1A85EC53)
    x = x ^ (x >> jnp.uint64(33))
    return x


def sim_digest(sims, lane_offset=0):
    """Per-carry-class digest vector ``[4] u64`` of a BATCHED Sim
    (leading lane axis) — the on-device digest the audited chunk
    program appends at every chunk boundary.

    Per leaf in flatten order: bitcast to the class's unsigned payload
    (f32→u32, f64/i64→u64; i32/u32 ride as themselves — exactly the
    :mod:`core.carry` class membership), mix each element with its
    position key ``(lane + lane_offset) * inner + offset`` and a
    per-leaf salt through fmix64, and sum mod 2^64 into the class
    accumulator.  Summation is an exact commutative integer reduction:
    the digest is independent of XLA's reduction order, and a mesh
    shard's digest ``psum``s into the global one (``lane_offset`` =
    ``axis_index * local_lanes`` makes shard-local positions global, so
    a 1-device mesh digest equals the unsheltered one).  Bool leaves
    (and anything outside the four classes) pass through undigested —
    they are derived state, and any divergence in them is preceded by a
    divergence in the numeric carries that produced them.

    32-bit classes accumulate in full u64 (masking to u32 happens only
    at host formatting time — :func:`format_digests` — so shard sums
    still combine exactly)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from cimba_tpu.core import carry as _carry

    leaves = jax.tree.leaves(sims)
    sums: Dict[str, Any] = {
        name: jnp.zeros((), jnp.uint64) for name in CLASS_NAMES
    }
    off = jnp.asarray(lane_offset, jnp.uint64)
    for ordinal, leaf in enumerate(leaves):
        dt = jnp.result_type(leaf)
        cname = None
        for name, _, members in _carry._CLASSES:
            if any(dt == m for m in members):
                cname = name
                break
        if cname is None:
            continue
        wide = _CLASS_BITS[cname] == 64
        bits = lax.bitcast_convert_type(
            leaf, jnp.uint64 if wide else jnp.uint32
        ).astype(jnp.uint64)
        W = int(leaf.shape[0])
        inner = 1
        for d in leaf.shape[1:]:
            inner *= int(d)
        bits = bits.reshape((W, inner))
        lane = lax.broadcasted_iota(jnp.uint64, (W, inner), 0) + off
        within = lax.broadcasted_iota(jnp.uint64, (W, inner), 1)
        pos = lane * jnp.uint64(inner) + within
        salt = _fmix64_host((ordinal + 1) * _GAMMA)
        h = _fmix64(bits ^ _fmix64(pos ^ jnp.uint64(salt)))
        sums[cname] = sums[cname] + jnp.sum(h, dtype=jnp.uint64)
    return jnp.stack([sums[n] for n in CLASS_NAMES])


# cimba-check: content-path
def format_digests(vec) -> Dict[str, str]:
    """One digest vector as the JSON trail-row payload: hex strings,
    32-bit classes masked to their u32 payload width."""
    import numpy as np

    v = np.asarray(vec)
    out = {}
    for i, name in enumerate(CLASS_NAMES):
        x = int(v[i]) & _U64
        if _CLASS_BITS[name] == 32:
            out[name] = f"0x{x & 0xFFFFFFFF:08x}"
        else:
            out[name] = f"0x{x:016x}"
    return out


# ---------------------------------------------------------------------------
# result digests (host-side, exact)
# ---------------------------------------------------------------------------


# cimba-check: content-path
def result_digest(tree) -> str:
    """sha256 hex over a pytree of arrays: structure + per-leaf
    dtype/shape/bytes in flatten order.  Bitwise — two results digest
    equal iff every leaf is bit-for-bit equal."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    h = hashlib.sha256()
    h.update(repr(treedef).encode("utf-8"))
    for leaf in leaves:
        a = np.asarray(leaf)
        h.update(str(a.dtype).encode("utf-8"))
        h.update(repr(a.shape).encode("utf-8"))
        h.update(a.tobytes())
    return h.hexdigest()


# cimba-check: content-path
def stream_result_digest(res) -> str:
    """The canonical digest of a ``StreamResult``: summary + failure
    count + event total (+ pooled metrics when carried).  ``n_waves``/
    ``n_regrows`` are geometry bookkeeping, not results, and the audit
    card records geometry separately — so a served request's digest can
    equal its direct call's (the serve contract, docs/13_serving.md)."""
    parts: tuple = (res.summary, res.n_failed, res.total_events)
    if res.metrics is not None:
        parts = parts + (res.metrics,)
    return result_digest(parts)


# ---------------------------------------------------------------------------
# the host-side collector
# ---------------------------------------------------------------------------


class Audit:
    """Host-side audit collector for one run: accumulates the digest
    trail (device vectors appended per chunk, converted lazily) and
    finalizes into a run card.  ``out_dir`` (optional) is where
    :meth:`finalize` writes the content-addressed card."""

    def __init__(self, out_dir=None, label: Optional[str] = None):
        self.out_dir = None if out_dir is None else str(out_dir)
        self.label = label
        self._trail: List[Tuple[int, int, Any]] = []
        self.card: Optional[dict] = None
        self.card_path: Optional[str] = None

    def on_chunk(self, wave: int, chunk: int, vec) -> None:
        """Append one chunk boundary's digest vector (held as a device
        array — conversion is deferred so the drive loop stays
        asynchronous)."""
        self._trail.append((int(wave), int(chunk), vec))

    def __len__(self) -> int:
        return len(self._trail)

    def trail_rows(self) -> List[dict]:
        """The trail as JSON rows: ``{"wave", "chunk", "f32", "i32",
        "f64", "i64"}`` in append order."""
        rows = []
        for w, c, vec in self._trail:
            row: dict = {"wave": w, "chunk": c}
            row.update(format_digests(vec))
            rows.append(row)
        return rows

    def finalize(self, kind: str, **blocks) -> dict:
        """Build (and, with ``out_dir`` set, write) this run's card.
        Keyword blocks are passed through to :func:`run_card`."""
        card = run_card(
            kind, digest_trail=self.trail_rows(), label=self.label,
            **blocks,
        )
        self.card = card
        if self.out_dir:
            self.card_path = write_run_card(card, self.out_dir)
        return card


def resolve(audit) -> Optional[Audit]:
    """Normalize an ``audit=`` argument: ``None`` defers to the
    ``CIMBA_AUDIT`` env knob (unset/"0" = off, "1" = in-memory, a path
    = write cards there), ``False`` forces off, ``True`` collects in
    memory, a path string collects + writes, an :class:`Audit` is used
    as-is."""
    if audit is None:
        # local import: the diff half of this module stays loadable
        # without the package (tools/audit_diff.py file-loads it)
        from cimba_tpu import config as _config

        v = _config.env_raw(AUDIT_ENV)
        if v in ("", "0"):
            return None
        return Audit() if v == "1" else Audit(out_dir=v)
    if audit is False:
        return None
    if audit is True:
        return Audit()
    if isinstance(audit, Audit):
        return audit
    if isinstance(audit, (str, os.PathLike)):
        return Audit(out_dir=audit)
    raise TypeError(
        f"audit= expects None, bool, a directory path, or an "
        f"obs.audit.Audit — got {type(audit).__name__}"
    )


# ---------------------------------------------------------------------------
# run cards
# ---------------------------------------------------------------------------


def environment() -> dict:
    """The card's env block — the SAME dict ``/varz`` serves as its
    ``build`` section (:func:`cimba_tpu.obs.telemetry.build_info`), so
    a fleet audit can cross-check a scraped process against a stored
    artifact field-for-field."""
    from cimba_tpu.obs.telemetry import build_info

    return build_info()


# cimba-check: content-path
def spec_block(spec) -> dict:
    """The card's spec identity: name + sha256 of the store's
    VALUE-based structural fingerprint (stable across processes —
    ``cache.spec_fingerprint``'s ``id()``s are not).  A spec that
    resists value fingerprinting records why instead of crashing the
    run it documents."""
    out: dict = {"name": getattr(spec, "name", None)}
    try:
        from cimba_tpu.serve import store as _pstore

        fp = _pstore.stable_spec_fingerprint(spec)
        out["spec_fingerprint"] = hashlib.sha256(
            repr(fp).encode("utf-8")
        ).hexdigest()
    except Exception as e:
        out["spec_fingerprint"] = None
        out["unstable"] = f"{type(e).__name__}: {e}"
    return out


def run_card(
    kind: str,
    *,
    spec=None,
    geometry: Optional[dict] = None,
    seed_schedule: Optional[dict] = None,
    digest_trail: Optional[List[dict]] = None,
    result_digest: Optional[str] = None,
    cells: Optional[List[dict]] = None,
    telemetry: Optional[dict] = None,
    program_key: Optional[str] = None,
    label: Optional[str] = None,
    schedule: Optional[dict] = None,
    extra: Optional[dict] = None,
) -> dict:
    """Assemble one run card (omitted blocks are left out, not nulled)
    and stamp its content digest.  ``spec`` may be a ModelSpec (hashed
    via :func:`spec_block`) or a pre-built dict.  ``schedule`` is the
    resolved dispatch-schedule block (docs/21_autotune.md — knobs +
    resolution source + tuned-entry digest), so every bitwise claim
    names the schedule it ran under; :func:`diff_cards` treats drift
    there as environment drift, never divergence (schedules change
    speed, not results)."""
    card: dict = {
        "format": CARD_FORMAT,
        "kind": str(kind),
        "created_unix": time.time(),
        "env": environment(),
    }
    if label:
        card["label"] = str(label)
    if spec is not None:
        card["spec"] = spec if isinstance(spec, dict) else spec_block(spec)
    if seed_schedule is not None:
        card["seed_schedule"] = seed_schedule
    if geometry is not None:
        card["geometry"] = geometry
    if program_key is not None:
        card["program_key"] = program_key
    if schedule is not None:
        card["schedule"] = schedule
    if digest_trail is not None:
        card["digest_trail"] = digest_trail
    if result_digest is not None:
        card["result_digest"] = result_digest
    if cells is not None:
        card["cells"] = cells
    if telemetry is not None:
        card["telemetry"] = telemetry
    if extra is not None:
        card["extra"] = extra
    card["card_digest"] = card_digest(card)
    return card


# cimba-check: content-path
def card_digest(card: dict) -> str:
    """Content digest of a card: sha256 over the canonical JSON of
    everything EXCEPT ``card_digest`` itself and the creation
    timestamp — two clean same-seed runs (same machine/env) therefore
    produce the SAME digest, which is the whole point: "bitwise
    reproducible" becomes one string equality."""
    body = {
        k: v for k, v in card.items()
        if k not in ("card_digest", "created_unix")
    }
    return hashlib.sha256(
        json.dumps(body, sort_keys=True, default=str).encode("utf-8")
    ).hexdigest()


def write_run_card(card: dict, out_dir) -> str:
    """Write a card content-addressed (``runcard_<digest16>.json``),
    crash-atomic (tmp + rename).  Identical runs collide on the same
    path with identical content (minus timestamp) — benign."""
    out_dir = str(out_dir)
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(
        out_dir, f"runcard_{card['card_digest'][:16]}.json"
    )
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(card, f, indent=2, sort_keys=True, default=str)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_run_card(path) -> dict:
    """Load a run card (or a bare digest-trail JSON list, wrapped) with
    a loud error naming the file on anything malformed."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        doc = {"format": CARD_FORMAT, "kind": "trail",
               "digest_trail": doc}
    if not isinstance(doc, dict) or "kind" not in doc:
        raise ValueError(
            f"{path}: not a run card (expected a JSON object with a "
            "'kind' field, or a bare digest-trail list)"
        )
    return doc


# ---------------------------------------------------------------------------
# divergence localization (stdlib-only — tools/audit_diff.py rides this)
# ---------------------------------------------------------------------------


# cimba-check: content-path
def diff_trails(a_rows: List[dict], b_rows: List[dict]) -> Optional[dict]:
    """First divergent trail row between two digest trails, or ``None``
    when identical.  The report names the (wave, chunk) coordinate and
    the carry classes that differ — ``classes`` is ``["geometry"]``
    when the coordinates themselves disagree and ``["length"]`` when
    one trail is a prefix of the other."""
    for i, (ra, rb) in enumerate(zip(a_rows, b_rows)):
        if (ra.get("wave"), ra.get("chunk")) != (
            rb.get("wave"), rb.get("chunk")
        ):
            return {
                "index": i, "wave": ra.get("wave"),
                "chunk": ra.get("chunk"), "classes": ["geometry"],
                "a": ra, "b": rb,
            }
        classes = [n for n in CLASS_NAMES if ra.get(n) != rb.get(n)]
        if classes:
            return {
                "index": i, "wave": ra.get("wave"),
                "chunk": ra.get("chunk"), "classes": classes,
                "a": {n: ra.get(n) for n in classes},
                "b": {n: rb.get(n) for n in classes},
            }
    if len(a_rows) != len(b_rows):
        i = min(len(a_rows), len(b_rows))
        longer = a_rows if len(a_rows) > len(b_rows) else b_rows
        row = longer[i] if i < len(longer) else {}
        return {
            "index": i, "wave": row.get("wave"),
            "chunk": row.get("chunk"), "classes": ["length"],
            "a_len": len(a_rows), "b_len": len(b_rows),
        }
    return None


#: geometry fields that must match for two trails to be comparable at
#: all (digests are geometry-specific: different wave partitions fold
#: different chunk boundaries, and ``poll_every`` changes how many
#: deterministic no-op trailing chunks each wave appends — a mismatch
#: there is geometry drift, not a determinism regression)
_GEOMETRY_KEYS = (
    "R", "wave_size", "chunk_steps", "poll_every", "t_end", "profile",
    "pack", "mesh", "with_metrics",
)

#: geometry keys that are SCHEDULE knobs with bitwise-invariant results
#: (docs/21_autotune.md): when both cards carry a ``schedule`` block,
#: drift here is env drift (a different tuned schedule ran), not
#: incomparability — chunk boundaries move, so the TRAIL comparison is
#: skipped, but the result digests must still be equal.  ``wave_size``
#: stays a hard geometry key: the pooled summary's merge order follows
#: the wave partition, so cross-wave-size results legitimately differ.
_SCHEDULE_GEOMETRY_KEYS = ("chunk_steps", "pack")


# cimba-check: content-path
def diff_cards(a: dict, b: dict) -> dict:
    """Compare two run cards.  Returns a report dict:

    * ``comparable`` — False (with ``reasons``) when the cards describe
      different experiments (spec fingerprint, kind, or geometry
      drift) and a digest comparison would be meaningless;
    * ``env_drift`` — environment keys that differ (jax/jaxlib/
      backend/x64/...): reported, but not blocking — cross-environment
      divergence is exactly what an audit is for.  Dispatch-SCHEDULE
      drift (docs/21_autotune.md — a different tuned/override schedule
      ran) reports here too, as ``schedule.<knob>`` entries, never as
      divergence: schedules change speed, not results;
    * ``schedule_drift`` — the drifted schedule-block keys by
      themselves (``[]`` when both cards ran the same schedule or
      either card predates schedule blocks);
    * ``first_divergence`` — :func:`diff_trails` on the digest trails
      (skipped, with ``trail_skipped`` set, when the schedule drift
      moved the chunk boundaries — the RESULT digests still compare);
    * ``result_equal`` — result-digest equality (None when either card
      carries none);
    * ``identical`` — comparable, no trail divergence, and results not
      known unequal.
    """
    reasons: List[str] = []
    fa = (a.get("spec") or {}).get("spec_fingerprint")
    fb = (b.get("spec") or {}).get("spec_fingerprint")
    if fa and fb and fa != fb:
        reasons.append("spec fingerprint differs")
    if a.get("kind") != b.get("kind"):
        reasons.append(
            f"kind differs ({a.get('kind')!r} vs {b.get('kind')!r})"
        )
    sa, sb = a.get("schedule"), b.get("schedule")
    schedule_drift: List[str] = []
    if isinstance(sa, dict) and isinstance(sb, dict):
        ka = dict(sa.get("knobs") or {}, source=sa.get("source"))
        kb = dict(sb.get("knobs") or {}, source=sb.get("source"))
        schedule_drift = sorted(
            k for k in set(ka) | set(kb) if ka.get(k) != kb.get(k)
        )
    ga, gb = a.get("geometry") or {}, b.get("geometry") or {}
    geo_drift_all = [
        k for k in _GEOMETRY_KEYS
        if k in ga and k in gb and ga[k] != gb[k]
    ]
    # schedule-owned, bitwise-invariant geometry keys: with schedule
    # blocks on both cards they are env-class drift (the schedule
    # changed), not incomparability — results must still match
    sched_geo = [
        k for k in geo_drift_all
        if k in _SCHEDULE_GEOMETRY_KEYS
        and isinstance(sa, dict) and isinstance(sb, dict)
    ]
    geo_drift = [k for k in geo_drift_all if k not in sched_geo]
    if geo_drift:
        reasons.append("geometry differs: " + ", ".join(geo_drift))
    ea, eb = a.get("env") or {}, b.get("env") or {}
    env_drift = sorted(
        k for k in set(ea) | set(eb) if ea.get(k) != eb.get(k)
    )
    env_drift += [f"schedule.{k}" for k in schedule_drift]
    seeds_differ = (
        a.get("seed_schedule") is not None
        and b.get("seed_schedule") is not None
        and a["seed_schedule"] != b["seed_schedule"]
    )
    trail_skipped = bool(sched_geo)
    divergence = None
    if not trail_skipped:
        divergence = diff_trails(
            a.get("digest_trail") or [], b.get("digest_trail") or []
        )
    ra, rb = a.get("result_digest"), b.get("result_digest")
    result_equal = None if (ra is None or rb is None) else (ra == rb)
    comparable = not reasons
    return {
        "comparable": comparable,
        "reasons": reasons,
        "env_drift": env_drift,
        "schedule_drift": schedule_drift,
        "seeds_differ": seeds_differ,
        "first_divergence": divergence,
        "trail_skipped": trail_skipped,
        "result_equal": result_equal,
        "trail_len": (
            len(a.get("digest_trail") or []),
            len(b.get("digest_trail") or []),
        ),
        "identical": bool(
            comparable and divergence is None and result_equal is not False
        ),
    }
