"""Host-side telemetry plane: time-series registry, spans, health.

The in-program observability of PR 1 (:mod:`obs.trace`, :mod:`obs.metrics`)
lives *inside* the compiled program as arrays; this module is its host-side
counterpart — the control-plane signals a production serving stack needs
scrapeable at runtime: how deep are the queues, how full are the waves,
how often do deadlines miss, is the dispatcher thread alive.  Three parts:

* :class:`Registry` — a thread-safe registry of **labeled time series**:
  monotone counters, gauges, and log2-bucket histograms, each series
  keeping a ring-buffered history of recent samples.  Rendered to
  Prometheus text / scraped over HTTP by :mod:`cimba_tpu.obs.expose`.
* :class:`SpanRecorder` — **request-scoped spans**: a ``trace_id`` minted
  at :meth:`cimba_tpu.serve.Service.submit` and threaded through
  admit → queue → pack → wave → chunk → fold → deliver (and through
  :func:`cimba_tpu.sweep.run_sweep`'s rounds), streamed as JSONL (one
  complete span per line, written at span END so a line is never torn)
  and exported into the validator-clean ``chrome_trace()`` docs.
* :class:`Telemetry` — the plane itself: a background **sampler** thread
  that scrapes ``Service.stats()`` / ``ProgramCache.stats()`` (store
  counters included) into the registry on an interval, heartbeats for
  liveness (the watchdog primitive ``bench.py`` rides), and the
  ``healthz()``/``varz()`` snapshots the exposition server serves.

The disabled == zero-overhead contract (the host-side image of
``obs.trace``'s disabled == jaxpr-identical rule): every integration
point takes ``telemetry=None`` as its default, and None means NO
background threads, NO span objects allocated on the hot submit path,
and — because everything here is host-side bookkeeping that never joins
a trace — compiled programs bitwise-unchanged either way (pinned in
tests/test_telemetry.py).  This module is stdlib-only by design: it
imports no jax, so the operator tooling (tools/metrics_dump.py) stays
light and nothing here can perturb trace-time state.

See docs/17_telemetry.md.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "Registry", "Family", "SpanRecorder", "Telemetry",
    "METRIC_PREFIX", "build_info",
]

#: every metric family this package creates is namespaced under this
METRIC_PREFIX = "cimba_"

_BUILD_INFO: Optional[dict] = None


def build_info() -> dict:
    """The process's build/provenance block — python, package version,
    and (when jax is importable) jax/jaxlib versions, backend, device
    kind/count, and the x64 flag.  ONE definition serves both the
    ``/varz`` ``build`` section and the run cards' ``env`` block
    (:func:`cimba_tpu.obs.audit.environment`), so a fleet audit can
    cross-check a scraped process against a stored artifact
    field-for-field (docs/18_audit.md).  jax is imported lazily and
    guarded: this module stays stdlib-only at import time.  Cached —
    none of it changes within a process."""
    global _BUILD_INFO
    if _BUILD_INFO is not None:
        return dict(_BUILD_INFO)
    import platform

    out: dict = {"python": platform.python_version()}
    try:
        from importlib import metadata as _md

        out["package"] = _md.version("cimba_tpu")
    except Exception:
        out["package"] = None
    try:
        import jax
        import jaxlib

        dev = jax.devices()[0]
        out.update(
            jax=jax.__version__,
            jaxlib=jaxlib.__version__,
            backend=jax.default_backend(),
            device_kind=getattr(dev, "device_kind", "?"),
            n_devices=jax.device_count(),
            x64=bool(jax.config.jax_enable_x64),
        )
    except Exception:  # cimba: noqa(CHK003) — jax-less/deviceless scrape
        # tooling still gets the python/package half; jax can fail here
        # with backend-specific errors, not just ImportError, and a
        # build-info probe must never take down a scrape
        pass
    _BUILD_INFO = out
    return dict(out)

#: log2 histogram exponent clamp — buckets span 2^-30 .. 2^30 (seconds:
#: ~1 ns to ~34 years), anything outside lands in the edge buckets, so
#: label cardinality is bounded no matter what gets observed
_EXP_MIN, _EXP_MAX = -30, 30

_KINDS = ("counter", "gauge", "histogram")


def _label_key(label_names: Tuple[str, ...], kv: dict) -> tuple:
    if set(kv) != set(label_names):
        raise ValueError(
            f"labels {sorted(kv)} do not match the family's declared "
            f"label names {sorted(label_names)}"
        )
    return tuple(str(kv[k]) for k in label_names)


class _Series:
    """One labeled time series: the current value plus a bounded ring of
    ``(t, value)`` history samples (appended by the sampler's
    :meth:`Registry.tick_history`, not per update — history is a
    sampled view, the live value is exact)."""

    __slots__ = ("label_values", "value", "sum", "count", "buckets",
                 "ring")

    def __init__(self, label_values: tuple, kind: str, history: int):
        self.label_values = label_values
        self.value = 0.0          # counter/gauge current value
        self.sum = 0.0            # histogram
        self.count = 0            # histogram
        self.buckets: Optional[Dict[int, int]] = (
            {} if kind == "histogram" else None
        )
        self.ring: deque = deque(maxlen=max(int(history), 1))


class _Handle:
    """A series bound to its family and registry lock — what
    ``family.labels(...)`` returns and what update calls go through."""

    __slots__ = ("_family", "_series")

    def __init__(self, family: "Family", series: _Series):
        self._family = family
        self._series = series

    # -- counter -------------------------------------------------------------

    def inc(self, n: float = 1.0) -> None:
        if self._family.kind not in ("counter", "gauge"):
            raise TypeError(f"inc() on a {self._family.kind}")
        if self._family.kind == "counter" and n < 0:
            raise ValueError(f"counters only go up, got inc({n})")
        with self._family._lock:
            self._series.value += n

    def set_total(self, v: float) -> None:
        """Mirror an externally-maintained cumulative total (e.g. a
        ``Service.stats()`` counter) into this counter.  Monotone: a
        smaller value than the current one is ignored rather than
        making the counter appear to go backwards mid-scrape."""
        if self._family.kind != "counter":
            raise TypeError(f"set_total() on a {self._family.kind}")
        with self._family._lock:
            if v > self._series.value:
                self._series.value = float(v)

    # -- gauge ---------------------------------------------------------------

    def set(self, v: float) -> None:
        if self._family.kind != "gauge":
            raise TypeError(f"set() on a {self._family.kind}")
        with self._family._lock:
            self._series.value = float(v)

    # -- histogram -----------------------------------------------------------

    def observe(self, v: float) -> None:
        if self._family.kind != "histogram":
            raise TypeError(f"observe() on a {self._family.kind}")
        e = _log2_exponent(v)
        with self._family._lock:
            s = self._series
            s.buckets[e] = s.buckets.get(e, 0) + 1
            s.sum += float(v)
            s.count += 1

    # -- reads ---------------------------------------------------------------

    def get(self) -> float:
        with self._family._lock:
            s = self._series
            return float(s.count if self._family.kind == "histogram"
                         else s.value)


def _log2_exponent(v: float) -> int:
    """The log2 bucket ``v`` falls in: the smallest integer ``e`` with
    ``v <= 2**e`` (clamped to the bounded exponent range; non-positive
    and non-finite values clamp to the edge buckets)."""
    if not (v > 0.0) or math.isinf(v):
        return _EXP_MIN if not v > 0.0 else _EXP_MAX
    m, e = math.frexp(v)        # v = m * 2**e, m in [0.5, 1)
    if m == 0.5:                # exact power of two sits ON its boundary
        e -= 1
    return min(max(e, _EXP_MIN), _EXP_MAX)


class Family:
    """One metric family: a name, a kind (counter | gauge | histogram),
    help text, declared label names, and the labeled series under it."""

    # cimba-check: must-hold(_lock) _series

    def __init__(self, registry: "Registry", name: str, kind: str,
                 help: str, label_names: Tuple[str, ...]):
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = registry._lock
        self._history = registry.history
        self._series: "OrderedDict[tuple, _Series]" = OrderedDict()

    def labels(self, **kv) -> _Handle:
        key = _label_key(self.label_names, kv)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = _Series(key, self.kind, self._history)
                self._series[key] = s
        return _Handle(self, s)

    def remove(self, **kv) -> None:
        """Drop one labeled series (no-op when absent) — how the fleet
        federation prunes a dead slice's series so rollups stay
        sum-of-live (docs/23_fleet_observability.md)."""
        key = _label_key(self.label_names, kv)
        with self._lock:
            self._series.pop(key, None)

    # label-less convenience: family-level update ops act on the () series
    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def set_total(self, v: float) -> None:
        self.labels().set_total(v)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def get(self, **kv) -> float:
        return self.labels(**kv).get()


class Registry:
    """A thread-safe registry of metric families.

    ``counter``/``gauge``/``histogram`` are get-or-create: asking for an
    existing name returns the existing family (kind must match — a name
    silently changing kind would corrupt every scrape).  ``history``
    bounds each series' sample ring; :meth:`tick_history` (called by the
    Telemetry sampler) appends one ``(t, value)`` sample per series."""

    # cimba-check: must-hold(_lock) _families

    def __init__(self, history: int = 256):
        self.history = int(history)
        self._lock = threading.RLock()
        self._families: "OrderedDict[str, Family]" = OrderedDict()

    def _family(self, name: str, kind: str, help: str,
                labels: Tuple[str, ...]) -> Family:
        if kind not in _KINDS:
            raise ValueError(f"unknown metric kind {kind!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}, not {kind}"
                    )
                return fam
            fam = Family(self, name, kind, help, tuple(labels))
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Tuple[str, ...] = ()) -> Family:
        return self._family(name, "counter", help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Tuple[str, ...] = ()) -> Family:
        return self._family(name, "gauge", help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Tuple[str, ...] = ()) -> Family:
        return self._family(name, "histogram", help, labels)

    # -- snapshots -----------------------------------------------------------

    def collect(self) -> List[dict]:
        """An atomic snapshot of every family and series — ONE lock
        acquisition for the whole registry, so a scrape can never see
        half of one update (the torn-read contract the exposition
        endpoints rely on).  Returns plain data (JSON-able)."""
        out = []
        with self._lock:
            for fam in self._families.values():
                series = []
                for s in fam._series.values():
                    rec: Dict[str, Any] = {
                        "labels": dict(zip(fam.label_names,
                                           s.label_values)),
                    }
                    if fam.kind == "histogram":
                        rec["buckets"] = dict(s.buckets)
                        rec["sum"] = s.sum
                        rec["count"] = s.count
                    else:
                        rec["value"] = s.value
                    rec["history"] = list(s.ring)
                    series.append(rec)
                out.append({
                    "name": fam.name, "kind": fam.kind, "help": fam.help,
                    "label_names": list(fam.label_names),
                    "series": series,
                })
        return out

    def tick_history(self, t: Optional[float] = None) -> None:
        """Append one ``(t, value)`` sample to every series' ring (the
        sampler's job; histogram series sample their count)."""
        t = time.monotonic() if t is None else t
        with self._lock:
            for fam in self._families.values():
                for s in fam._series.values():
                    v = s.count if fam.kind == "histogram" else s.value
                    s.ring.append((t, v))

    def get_sample(self, name: str, **labels) -> Optional[float]:
        """The current value of one series (None when absent) —
        convenience for tests and the bench snapshot."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            key = tuple(str(labels[k]) for k in fam.label_names
                        if k in labels)
            if len(key) != len(fam.label_names):
                return None
            s = fam._series.get(key)
            if s is None:
                return None
            return float(s.count if fam.kind == "histogram" else s.value)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


class SpanRecorder:
    """Request-scoped span trees, streamed as JSONL.

    A **trace** is one request's (or one sweep's) lifetime; **spans**
    are its phases (queue, wave, …) and **events** are instants on them
    (chunk ticks, fold boundaries, deliver).  A span line is written at
    span END — complete, never torn — as::

        {"trace": "t0001", "span": "s0003", "parent": "s0001",
         "name": "wave", "t0": 0.0123, "dur": 0.4, "outcome": "ok", ...}

    events carry ``"ph": "i"`` and a single ``"t"``.  Completeness is a
    structural guarantee, not a convention: :meth:`end_trace` closes
    every still-open span of the trace in reverse start order before
    closing the root, so a request that is cancelled, deadline-expired,
    or retried-to-exhaustion still yields exactly one complete span
    tree (tests/test_telemetry.py pins all four outcomes).  A bounded
    ring keeps recent completed spans in memory for the
    ``chrome_trace()`` export.

    ``max_bytes`` (opt-in) caps the JSONL file's growth: once the
    current file exceeds it, the log rotates (``path`` →
    ``path + ".1"``, replacing the previous generation) — but ONLY at
    a trace boundary with NO other trace open, so a span tree is never
    torn across files (a long soak keeps at most two generations on
    disk; ``counters["rotations"]`` says how often it happened).

    **Cross-process grafting** (docs/23_fleet_observability.md): a
    recorder can :meth:`adopt_trace` a trace id minted by ANOTHER
    process's recorder (the fleet router), recording its local span
    tree under the remote trace with the local root parented on a
    remote span id.  ``node`` namespaces every locally-minted id with a
    ``.node`` suffix, so the two processes' per-process counters cannot
    collide when their JSONL files are merged into one tree."""

    # cimba-check: must-hold(_lock) _open, _by_trace, _n, _bytes, _fh, counters, completed, _remote_parent

    def __init__(self, path=None, cap: int = 4096,
                 max_bytes: Optional[int] = None,
                 node: Optional[str] = None):
        self._lock = threading.Lock()
        self._m0 = time.monotonic()
        self._n = 0
        self._node = None if node is None else str(node)
        self._suffix = "" if node is None else f".{node}"
        self._open: Dict[str, dict] = {}
        self._by_trace: "OrderedDict[str, List[str]]" = OrderedDict()
        # trace id -> the REMOTE parent span id its local root hangs
        # under (adopt_trace); end_trace needs it to recognize the
        # local root, whose parent is NOT None for an adopted trace
        self._remote_parent: Dict[str, str] = {}
        self.completed: deque = deque(maxlen=int(cap))
        self.counters = {
            "traces_started": 0, "traces_ended": 0, "traces_adopted": 0,
            "spans_started": 0, "spans_ended": 0, "events": 0,
            "rotations": 0,
        }
        self._path = None if path is None else str(path)
        self._max_bytes = None if max_bytes is None else int(max_bytes)
        self._bytes = 0
        self._fh = None
        if self._path is not None:
            self._fh = open(self._path, "a", buffering=1)
            try:
                self._bytes = os.path.getsize(self._path)
            except OSError:
                self._bytes = 0

    # -- lifecycle -----------------------------------------------------------

    def new_trace(self) -> str:
        with self._lock:
            self._n += 1
            tid = f"t{self._n:08x}{self._suffix}"
            self._by_trace[tid] = []
            self.counters["traces_started"] += 1
            return tid

    def adopt_trace(self, trace: str,
                    parent: Optional[str] = None) -> str:
        """Adopt a trace id minted by a REMOTE recorder (the wire's
        ``trace`` header): spans recorded locally under ``trace`` write
        lines carrying the remote id, so the two processes' JSONL files
        merge into one tree.  ``parent`` is the remote span id the
        local root will hang under — :meth:`end_trace` treats the span
        parented on it as the root (its parent is not ``None``, which
        is how a purely local root is recognized).  Idempotent per
        trace id; returns ``trace``."""
        with self._lock:
            if trace not in self._by_trace:
                self._by_trace[trace] = []
                self.counters["traces_adopted"] += 1
            if parent is not None:
                self._remote_parent[trace] = str(parent)
            return trace

    def start(self, trace: str, name: str,
              parent: Optional[str] = None, **attrs) -> str:
        now = time.monotonic()
        with self._lock:
            self._n += 1
            sid = f"s{self._n:08x}{self._suffix}"
            rec = {
                "trace": trace, "span": sid, "parent": parent,
                "name": name, "m0": now,
            }
            if attrs:
                rec["attrs"] = attrs
            self._open[sid] = rec
            self._by_trace.setdefault(trace, []).append(sid)
            self.counters["spans_started"] += 1
            return sid

    def end(self, span: str, outcome: Optional[str] = None,
            **attrs) -> None:
        now = time.monotonic()
        with self._lock:
            rec = self._open.pop(span, None)
            if rec is None:
                return           # already closed (end_trace raced) — fine
            sids = self._by_trace.get(rec["trace"])
            if sids is not None and span in sids:
                sids.remove(span)
            self._finish_locked(rec, now, outcome, attrs)

    def _finish_locked(self, rec, now, outcome, attrs) -> None:
        rec["m1"] = now
        if outcome is not None:
            rec["outcome"] = outcome
        if attrs:
            rec.setdefault("attrs", {}).update(attrs)
        self.counters["spans_ended"] += 1
        self.completed.append(rec)
        if self._fh is not None:
            line = {
                "trace": rec["trace"], "span": rec["span"],
                "parent": rec["parent"], "name": rec["name"],
                "t0": rec["m0"] - self._m0,
                "dur": rec["m1"] - rec["m0"],
            }
            if "outcome" in rec:
                line["outcome"] = rec["outcome"]
            if "attrs" in rec:
                line.update(rec["attrs"])
            data = json.dumps(line) + "\n"
            self._fh.write(data)
            self._bytes += len(data)

    def event(self, trace: str, name: str,
              parent: Optional[str] = None, **attrs) -> None:
        """An instant event on a trace (one JSONL line, ``ph: "i"``)."""
        now = time.monotonic()
        with self._lock:
            rec = {
                "trace": trace, "span": None, "parent": parent,
                "name": name, "m0": now, "m1": now, "ph": "i",
            }
            if attrs:
                rec["attrs"] = attrs
            self.counters["events"] += 1
            self.completed.append(rec)
            if self._fh is not None:
                line = {
                    "trace": trace, "parent": parent, "name": name,
                    "t": now - self._m0, "ph": "i",
                }
                line.update(attrs)
                data = json.dumps(line) + "\n"
                self._fh.write(data)
                self._bytes += len(data)

    def end_trace(self, trace: str, outcome: str, **attrs) -> None:
        """Close the trace: every still-open span ends in reverse start
        order (children before parents), the LAST one — the root —
        carrying ``outcome``.  The no-orphans guarantee lives here."""
        now = time.monotonic()
        with self._lock:
            sids = self._by_trace.pop(trace, None)
            remote = self._remote_parent.pop(trace, None)
            if sids is None:
                return
            for sid in reversed(sids):
                rec = self._open.pop(sid, None)
                if rec is None:
                    continue
                # an adopted trace's local root is parented on the
                # REMOTE span id, not None (adopt_trace recorded it)
                is_root = (
                    rec["parent"] is None or rec["parent"] == remote
                )
                self._finish_locked(
                    rec, now, outcome if is_root else "aborted",
                    attrs if is_root else {},
                )
            self.counters["traces_ended"] += 1
            self._maybe_rotate_locked()

    def _maybe_rotate_locked(self) -> None:
        """Rotate the JSONL log once it exceeds ``max_bytes`` — called
        only from :meth:`end_trace` (a trace boundary) and only when NO
        trace remains open, so every trace's lines live in exactly one
        generation (the never-tear-a-tree contract)."""
        if (
            self._fh is None
            or self._max_bytes is None
            or self._bytes <= self._max_bytes
            or self._by_trace
        ):
            return
        self._fh.close()
        try:
            os.replace(self._path, self._path + ".1")
            rotated = True
        except OSError:
            rotated = False  # best-effort; keep appending either way
        self._fh = open(self._path, "a", buffering=1)
        if rotated:
            self._bytes = 0
            self.counters["rotations"] += 1
        else:
            # the full file is still live: keep the byte count honest
            # (a reset here would silently defeat the cap and report
            # phantom rotations forever)
            try:
                self._bytes = os.path.getsize(self._path)
            except OSError:
                pass

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            fh.close()

    # -- export --------------------------------------------------------------

    def chrome_events(self, t0: float, pid_of: Callable[[str], Any],
                      tid_of: Callable[[str], int]) -> List[dict]:
        """Completed spans/events as Chrome-trace events: ``'X'`` spans
        and ``'i'`` instants, ``ts`` offset against the caller's ``t0``
        (a monotonic origin), pid/tid resolved per record by the caller
        (``pid_of(trace)`` may return None to skip a record).  The
        caller is responsible for per-pid timestamp ordering (sort by
        ``ts``)."""
        with self._lock:
            recs = list(self.completed)
        out = []
        for r in recs:
            pid = pid_of(r["trace"])
            if pid is None:
                continue
            ev = {
                "name": r["name"],
                "ts": (r["m0"] - t0) * 1e6,
                "pid": pid,
                "tid": tid_of(r["name"]),
                "args": dict(r.get("attrs", {})),
            }
            if r.get("ph") == "i":
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = max((r["m1"] - r["m0"]) * 1e6, 0.0)
                if "outcome" in r:
                    ev["args"]["outcome"] = r["outcome"]
            out.append(ev)
        return out


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------


class Telemetry:
    """The host-side telemetry plane: registry + spans + sampler +
    health.

    Opt-in by construction — code paths take ``telemetry=None`` and do
    nothing (no threads, no allocations) without one.  With one:

    * :meth:`attach_service` registers a collector that scrapes
      ``Service.stats()`` (counters, queue depths by class, lane
      occupancy/waste, program cache + store counters) into the
      registry, and starts the background sampler (interval > 0).
    * :meth:`tick`/:meth:`heartbeat` are the cheap hot-path hooks the
      runner/sweep/serve layers call per wave/chunk/round.
    * :meth:`healthz` / :meth:`varz` are what
      :mod:`cimba_tpu.obs.expose` serves.

    ``spans=True`` (or a ``span_path``) turns on the
    :class:`SpanRecorder`; ``interval=0`` disables the sampler thread
    (ticks and collectors still work, scrapes just happen on demand).
    """

    # cimba-check: must-hold(_lock) _hb, _collectors, _services, _service_collectors, _errors, _thread

    def __init__(
        self,
        *,
        interval: float = 0.25,
        history: int = 256,
        spans: bool = False,
        span_path=None,
        span_max_bytes: Optional[int] = None,
        span_node: Optional[str] = None,
        registry: Optional[Registry] = None,
        stall_s: float = 30.0,
        autostart: bool = True,
    ):
        self.registry = registry if registry is not None else Registry(
            history=history
        )
        self.spans: Optional[SpanRecorder] = (
            SpanRecorder(path=span_path, max_bytes=span_max_bytes,
                         node=span_node)
            if (spans or span_path is not None) else None
        )
        self.interval = float(interval)
        self.stall_s = float(stall_s)
        self._autostart = bool(autostart)
        self._lock = threading.RLock()
        self._hb: Dict[str, float] = {}
        self._collectors: List[Callable[[], None]] = []
        self._healthz_hooks: "OrderedDict[str, Callable[[], dict]]" = (
            OrderedDict()
        )
        self._services: List[tuple] = []       # (name, service)
        self._service_collectors: Dict[int, Callable] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._errors = 0
        self._ticks = self.registry.counter(
            METRIC_PREFIX + "ticks_total",
            "progress ticks by source (waves, chunks, rounds)",
            labels=("source",),
        )
        self._hb_gauge = self.registry.gauge(
            METRIC_PREFIX + "heartbeat_age_seconds",
            "seconds since the source last reported progress",
            labels=("source",),
        )

    # -- hot-path hooks ------------------------------------------------------

    def heartbeat(self, source: str = "main") -> None:
        with self._lock:
            self._hb[source] = time.monotonic()

    def heartbeat_age(self, source: Optional[str] = None) -> float:
        """Seconds since ``source`` last beat — or, with no source, the
        FRESHEST beat across all sources (the watchdog reading: any
        progress anywhere counts).  ``inf`` when nothing ever beat."""
        now = time.monotonic()
        with self._lock:
            if source is not None:
                t = self._hb.get(source)
                return float("inf") if t is None else now - t
            if not self._hb:
                return float("inf")
            return now - max(self._hb.values())

    def tick(self, source: str, n: int = 1) -> None:
        """One progress tick: counter + heartbeat.  The generalized
        ``on_wave``/``on_chunk`` hook body (docs/17_telemetry.md)."""
        self._ticks.labels(source=source).inc(n)
        self.heartbeat(source)

    # -- wiring --------------------------------------------------------------

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors.append(fn)
        if self._autostart:
            self.start()

    def remove_collector(self, fn: Callable[[], None]) -> None:
        """Drop a collector registered with :meth:`add_collector`
        (idempotent) — what a shutting-down fleet router calls so a
        long-lived plane stops scraping it."""
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def add_healthz(self, name: str, fn: Callable[[], dict]) -> None:
        """Register an extra health contributor: ``fn()`` returns a
        check dict whose ``"status"`` ("ok" | "degraded" | "unhealthy")
        folds into the overall :meth:`healthz` verdict and whose body
        lands under ``checks[name]``.  How a non-``Service`` component
        (the fleet router's slice-verdict rollup,
        docs/23_fleet_observability.md) joins the verdict."""
        with self._lock:
            self._healthz_hooks[str(name)] = fn

    def remove_healthz(self, name: str) -> None:
        with self._lock:
            self._healthz_hooks.pop(str(name), None)

    def attach_service(self, service, name: Optional[str] = None) -> str:
        """Register ``service`` with the plane: a stats collector, the
        health checks, and (autostart) the sampler thread.  Returns the
        label the service's series carry.  ``Service.shutdown()`` calls
        :meth:`detach_service`, so a long-lived plane observing a
        churn of short-lived services neither pins them in memory nor
        keeps scraping corpses."""
        with self._lock:
            # the default-name read of _services happens under the same
            # lock as the append: two services attaching concurrently
            # must not mint one label (CHK002)
            name = name or getattr(service, "name", None) or (
                f"service{len(self._services)}"
            )
            collector = _service_collector(self.registry, name, service)
            self._services.append((name, service))
            self._service_collectors[id(service)] = collector
        self.add_collector(collector)
        return name

    def detach_service(self, service) -> None:
        """Stop observing ``service``: take one final stats sample
        (counters freeze at their true final values), then drop its
        collector, health entry, and the plane's reference to it —
        the service can be garbage-collected.  Idempotent."""
        with self._lock:
            collector = self._service_collectors.pop(id(service), None)
        if collector is not None:
            try:
                collector()        # final sample, best-effort
            except Exception:
                with self._lock:
                    self._errors += 1
        with self._lock:
            self._services = [
                (n, s) for n, s in self._services if s is not service
            ]
            if collector is not None:
                try:
                    self._collectors.remove(collector)
                except ValueError:
                    pass

    def observe_request(self, service: str, outcome: str,
                        latency_s: float,
                        ttfw_s: Optional[float] = None) -> None:
        """Push-side request telemetry (called by ``Service._finish``):
        the latency histogram by outcome, plus time-to-first-wave."""
        self.registry.histogram(
            METRIC_PREFIX + "serve_request_latency_seconds",
            "submit-to-result latency by outcome (log2 buckets)",
            labels=("service", "outcome"),
        ).labels(service=service, outcome=outcome).observe(latency_s)
        if ttfw_s is not None:
            self.registry.histogram(
                METRIC_PREFIX + "serve_time_to_first_wave_seconds",
                "submit-to-first-dispatch latency (log2 buckets)",
                labels=("service",),
            ).labels(service=service).observe(ttfw_s)

    # -- sampler -------------------------------------------------------------

    def start(self) -> None:
        """Start the background sampler (idempotent; no-op when
        ``interval <= 0`` — on-demand sampling only)."""
        if self.interval <= 0:
            return
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="cimba-telemetry", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.sample()

    def sample(self) -> None:
        """One sampler pass: run every collector, refresh the
        heartbeat-age gauges, append one history sample per series.
        Collector exceptions are counted, never propagated — a flaky
        stats source must not kill the sampler."""
        with self._lock:
            collectors = list(self._collectors)
            hb = dict(self._hb)
        for fn in collectors:
            try:
                fn()
            except Exception:
                with self._lock:
                    self._errors += 1
        now = time.monotonic()
        for source, t in hb.items():
            self._hb_gauge.labels(source=source).set(now - t)
        self.heartbeat("sampler")
        self.registry.tick_history(now)

    def close(self) -> None:
        """Stop the sampler thread and close the span log (idempotent).
        Attached services are NOT shut down — the plane observes them,
        it does not own them."""
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if self.spans is not None:
            self.spans.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- health / snapshots --------------------------------------------------

    def healthz(self) -> dict:
        """The liveness/saturation verdict behind ``/healthz``:

        * ``unhealthy`` — a service's dispatcher thread is dead, or its
          heartbeat is staler than ``stall_s`` while work is
          outstanding (a live dispatcher beats at least every queue
          poll; chunk ticks keep it fresh through long waves);
        * ``degraded`` — the admission queue is saturated, or the
          program store reports corruption/downgrades (serving still
          works, somebody should look);
        * ``ok`` otherwise.
        """
        checks: Dict[str, Any] = {}
        status = "ok"

        def worse(s):
            nonlocal status
            order = ("ok", "degraded", "unhealthy")
            if order.index(s) > order.index(status):
                status = s

        with self._lock:
            services = list(self._services)
        for name, svc in services:
            c: Dict[str, Any] = {}
            thread = getattr(svc, "_thread", None)
            alive = bool(thread is not None and thread.is_alive())
            c["dispatcher_alive"] = alive
            age = self.heartbeat_age(f"serve.{name}.dispatch")
            chunk_age = self.heartbeat_age(f"serve.{name}.chunk")
            age = min(age, chunk_age)
            c["heartbeat_age_s"] = None if math.isinf(age) else round(
                age, 3
            )
            try:
                st = svc.stats()
            except Exception as e:
                c["stats_error"] = repr(e)
                worse("unhealthy")
                checks[name] = c
                continue
            outstanding = st.get("outstanding", 0)
            stalled = (
                outstanding > 0 and age > self.stall_s
            )
            c["stalled"] = stalled
            if not alive or stalled:
                worse("unhealthy")
            depth = st.get("queue_depth", 0)
            cap = st.get("queue_capacity")
            c["queue_depth"] = depth
            c["queue_capacity"] = cap
            saturated = cap is not None and depth >= cap
            c["queue_saturated"] = saturated
            if saturated:
                worse("degraded")
            store = st.get("program_store")
            if store is not None:
                flags = store.get("flags") or {}
                c["store_flags"] = flags
                if any(flags.values()):
                    worse("degraded")
            # determinism audit (docs/18_audit.md): a result digest
            # that failed its expectation means the fleet is no longer
            # bitwise-reproducible — serving still works, but somebody
            # must look before citing any run card
            mism = st.get("digest_mismatches", 0)
            c["digest_mismatches"] = mism
            if mism:
                worse("degraded")
            checks[name] = c
        # extra contributors (add_healthz): each returns a check dict
        # with a "status" that folds into the verdict — the fleet
        # router's slice rollup reports through here
        with self._lock:
            hooks = list(self._healthz_hooks.items())
        extra: Dict[str, Any] = {}
        for hname, fn in hooks:
            try:
                c = dict(fn())
            except Exception as e:
                c = {"status": "unhealthy", "error": repr(e)}
            s = c.get("status", "ok")
            worse(s if s in ("ok", "degraded", "unhealthy")
                  else "unhealthy")
            extra[hname] = c
        with self._lock:
            thread = self._thread
            errors = self._errors
        out = {
            "status": status,
            "ok": status != "unhealthy",
            "services": checks,
            "sampler_alive": thread is not None and thread.is_alive(),
            "collector_errors": errors,
        }
        if extra:
            out["checks"] = extra
        return out

    def varz(self) -> dict:
        """The full JSON snapshot behind ``/varz``: every registry
        family with history rings, raw ``stats()`` of every attached
        service, span counters, heartbeats."""
        with self._lock:
            services = list(self._services)
            hb = dict(self._hb)
        now = time.monotonic()
        out: Dict[str, Any] = {
            "metrics": self.registry.collect(),
            "heartbeat_age_s": {
                k: round(now - t, 3) for k, t in hb.items()
            },
            "health": self.healthz(),
            # the build/provenance block — the SAME dict run cards
            # record as their env block (docs/18_audit.md), so a
            # scraped process cross-checks against a stored artifact
            "build": build_info(),
        }
        svc_stats = {}
        for name, svc in services:
            try:
                svc_stats[name] = svc.stats()
            except Exception as e:
                svc_stats[name] = {"error": repr(e)}
        out["services"] = svc_stats
        if self.spans is not None:
            out["spans"] = dict(self.spans.counters)
            out["spans"]["open"] = self.spans.open_count()
        return out

    def snapshot(self) -> dict:
        """A compact dict for embedding in reports (the bench JSON's
        per-battery telemetry section): tick counters, heartbeat ages,
        span counters — no history rings."""
        now = time.monotonic()
        with self._lock:
            hb = {k: round(now - t, 3) for k, t in self._hb.items()}
        ticks = {}
        with self.registry._lock:
            fam = self.registry._families.get(
                METRIC_PREFIX + "ticks_total"
            )
            if fam is not None:
                for s in fam._series.values():
                    ticks[s.label_values[0]] = int(s.value)
        out: Dict[str, Any] = {
            "heartbeat_age_s": hb, "ticks": ticks,
        }
        if self.spans is not None:
            out["spans"] = dict(self.spans.counters)
            out["spans"]["open"] = self.spans.open_count()
        return out


def _service_collector(registry: Registry, name: str, service):
    """The collector :meth:`Telemetry.attach_service` registers: map one
    atomic ``Service.stats()`` snapshot into registry families.  Keeps a
    previous sample to derive per-second outcome rates (deadline-miss /
    retry / cancel) as gauges alongside the raw cumulative counters."""
    P = METRIC_PREFIX
    lab = {"service": name}
    req_counters = (
        "submitted", "admitted", "rejected", "throttled", "completed",
        "failed", "cancelled", "deadline_exceeded",
    )
    raw_counters = (
        "retries", "batches", "waves", "lanes_dispatched", "lanes_padded",
        "digest_mismatches",
    )
    rate_keys = ("completed", "cancelled", "deadline_exceeded",
                 "retries", "throttled")
    prev = {"t": None, "vals": {}, "qos": {}}

    def collect():
        st = service.stats()
        now = time.monotonic()
        for k in req_counters:
            registry.counter(
                P + f"serve_requests_{k}_total",
                f"requests {k.replace('_', ' ')}", labels=("service",),
            ).labels(**lab).set_total(st[k])
        for k in raw_counters:
            registry.counter(
                P + f"serve_{k}_total", k.replace("_", " "),
                labels=("service",),
            ).labels(**lab).set_total(st[k])
        registry.gauge(
            P + "serve_queue_depth", "admitted requests waiting",
            labels=("service",),
        ).labels(**lab).set(st["queue_depth"])
        registry.gauge(
            P + "serve_queue_depth_hwm", "queue depth high-water mark",
            labels=("service",),
        ).labels(**lab).set(st["queue_depth_hwm"])
        cap = st.get("queue_capacity")
        if cap is not None:
            registry.gauge(
                P + "serve_queue_capacity", "admission queue capacity",
                labels=("service",),
            ).labels(**lab).set(cap)
        registry.gauge(
            P + "serve_outstanding", "admitted, not yet delivered",
            labels=("service",),
        ).labels(**lab).set(st["outstanding"])
        by_class = registry.gauge(
            P + "serve_queue_depth_class",
            "queued requests per compatibility class",
            labels=("service", "klass"),
        )
        for klass, depth in st.get("queue_depth_by_class", {}).items():
            by_class.labels(service=name, klass=klass).set(depth)
        occ = st.get("lane_occupancy", {})
        registry.gauge(
            P + "serve_padding_waste_ratio",
            "padded lanes / all dispatched lanes",
            labels=("service",),
        ).labels(**lab).set(occ.get("padding_waste_frac", 0.0))
        # the LIVE per-chunk occupancy view (docs/22_refill.md): how
        # full the in-flight wave is right now / on average over the
        # recent boundary window — decay (and refill) in real time,
        # not the pack-time snapshot
        registry.gauge(
            P + "serve_lane_occupancy_now",
            "live lanes / wave lanes at the latest chunk boundary",
            labels=("service",),
        ).labels(**lab).set(occ.get("occupancy_now", 0.0))
        registry.gauge(
            P + "serve_lane_occupancy_mean",
            "mean live-lane occupancy over recent chunk boundaries",
            labels=("service",),
        ).labels(**lab).set(occ.get("occupancy_mean", 0.0))
        ref = st.get("refill")
        if ref:
            registry.gauge(
                P + "serve_refill_enabled",
                "continuous wave refill active (docs/22_refill.md)",
                labels=("service",),
            ).labels(**lab).set(1.0 if ref.get("enabled") else 0.0)
            # the refill wave's free-lane pool RIGHT NOW — the fleet
            # router's capacity-placement signal (docs/23): admission
            # headroom, where queue depth is only backlog
            registry.gauge(
                P + "serve_free_lanes",
                "free lanes in the in-flight refill wave",
                labels=("service",),
            ).labels(**lab).set(ref.get("free_lanes", 0))
            for k in ("refill_boundaries", "refill_admissions",
                      "refill_retirements", "lanes_refilled",
                      "lanes_reclaimed", "mid_wave_deliveries"):
                if k in ref:
                    registry.counter(
                        P + f"serve_{k}_total",
                        k.replace("_", " "), labels=("service",),
                    ).labels(**lab).set_total(ref[k])
        ds = st.get("device_sched")
        if ds:
            registry.gauge(
                P + "serve_device_sched_enabled",
                "preemptive device scheduler active "
                "(docs/24_device_scheduler.md)",
                labels=("service",),
            ).labels(**lab).set(1.0 if ds.get("enabled") else 0.0)
            registry.gauge(
                P + "serve_waves_live",
                "concurrent RUNNING waves on the device right now",
                labels=("service",),
            ).labels(**lab).set(ds.get("waves_live", 0))
            # the admission headroom in BYTES — the memory-side twin
            # of serve_free_lanes for capacity-aware placement
            free = ds.get("est_free_mem_bytes")
            if free is not None:
                registry.gauge(
                    P + "serve_est_free_device_mem_bytes",
                    "estimated free device memory under the "
                    "admission budget",
                    labels=("service",),
                ).labels(**lab).set(free)
            for k in ("preemptions", "evictions", "restores",
                      "sched_waves_started", "mem_rejects"):
                if k in ds:
                    registry.counter(
                        P + f"serve_{k}_total",
                        k.replace("_", " "), labels=("service",),
                    ).labels(**lab).set_total(ds[k])
        qs = st.get("qos")
        if qs:
            registry.gauge(
                P + "serve_qos_enabled",
                "multi-tenant QoS plane active (docs/27_qos.md)",
                labels=("service",),
            ).labels(**lab).set(1.0 if qs.get("enabled") else 0.0)
            tenants = qs.get("tenants", {})
            held = qs.get("lanes_held", {})
            held_g = registry.gauge(
                P + "serve_qos_lanes_held",
                "lanes a tenant holds in flight against its quota",
                labels=("service", "tenant"),
            )
            goodput_g = registry.gauge(
                P + "serve_qos_goodput_ratio",
                "completed / submitted per tenant",
                labels=("service", "tenant"),
            )
            p99_g = registry.gauge(
                P + "serve_qos_latency_p99_seconds",
                "p99 completed-request latency per tenant over the "
                "recent window",
                labels=("service", "tenant"),
            )
            for tname, tc in tenants.items():
                tlab = {"service": name, "tenant": tname}
                for k in ("submitted", "admitted", "throttled",
                          "throttled_rate", "throttled_quota",
                          "completed", "deadline_exceeded",
                          "claims", "lanes_claimed"):
                    if k in tc:
                        registry.counter(
                            P + f"serve_qos_{k}_total",
                            f"per-tenant requests {k.replace('_', ' ')}"
                            " (docs/27_qos.md)",
                            labels=("service", "tenant"),
                        ).labels(**tlab).set_total(tc[k])
                # every tenant ever seen reports, zeros included — the
                # held gauge must drop to 0 when a tenant drains, and
                # goodput is completed/submitted (the fairness signal
                # a flooded victim's dashboard watches)
                held_g.labels(**tlab).set(held.get(tname, 0))
                sub = tc.get("submitted", 0)
                goodput_g.labels(**tlab).set(
                    tc.get("completed", 0) / sub if sub else 0.0
                )
                p99_g.labels(**tlab).set(tc.get("latency_p99_s", 0.0))
        registry.gauge(
            P + "serve_classes_seen", "distinct compatibility classes",
            labels=("service",),
        ).labels(**lab).set(st.get("classes_seen", 0))
        cache = st.get("program_cache")
        if cache:
            for k in ("hits", "misses", "evictions"):
                registry.counter(
                    P + f"program_cache_{k}_total", f"program cache {k}",
                    labels=("service",),
                ).labels(**lab).set_total(cache[k])
            for k in ("size", "capacity"):
                registry.gauge(
                    P + f"program_cache_{k}", f"program cache {k}",
                    labels=("service",),
                ).labels(**lab).set(cache[k])
        store = st.get("program_store")
        if store:
            for k in ("saves", "hits", "misses", "invalidated",
                      "corrupt", "downgrades", "fallback_shapes",
                      "artifact_dispatches"):
                if k in store:
                    registry.counter(
                        P + f"program_store_{k}_total",
                        f"program store {k}", labels=("service",),
                    ).labels(**lab).set_total(store[k])
        # per-second outcome rates from the sampler's own cadence
        t_prev, vals_prev = prev["t"], prev["vals"]
        vals_now = {k: st[k] for k in rate_keys}
        # per-tenant outcome rates (docs/27_qos.md): throttle and
        # completion velocity per tenant — the live view of a flood
        # being absorbed (cumulative counters only show it in slope)
        qos_now = {}
        if qs:
            for tname, tc in qs.get("tenants", {}).items():
                for k in ("completed", "throttled"):
                    qos_now[(tname, k)] = tc.get(k, 0)
        if t_prev is not None and now > t_prev:
            dt = now - t_prev
            for k in rate_keys:
                registry.gauge(
                    P + f"serve_{k}_per_second",
                    f"{k.replace('_', ' ')} rate over the last sample "
                    "interval",
                    labels=("service",),
                ).labels(**lab).set(
                    max(vals_now[k] - vals_prev.get(k, 0), 0) / dt
                )
            for (tname, k), v in qos_now.items():
                registry.gauge(
                    P + f"serve_qos_{k}_per_second",
                    f"per-tenant {k} rate over the last sample "
                    "interval (docs/27_qos.md)",
                    labels=("service", "tenant"),
                ).labels(service=name, tenant=tname).set(
                    max(v - prev["qos"].get((tname, k), 0), 0) / dt
                )
        prev["t"], prev["vals"], prev["qos"] = now, vals_now, qos_now

    return collect
