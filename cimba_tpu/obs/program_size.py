"""Program size as a first-class cost (docs/25_compile_wall.md).

The compile wall is invisible in wall-clock benchmarks until it is hit:
a program whose TEXT grows with a model dimension (the dense ``[P, ...]``
table dispatch before the scan-over-rows arm) compiles fine at dev scale
and then takes >25 minutes at AWACS scale on the kernel path
(BENCH_NOTES round 5).  This module makes the growth measurable *before*
any compile: a probe that traces and lowers a program — never compiles,
never executes — and reports

* ``eqns`` — jaxpr equation count, recursing into sub-jaxprs (the
  check/jaxprlint walker, so JXL004's budget and this probe can never
  disagree on what an equation is);
* ``jaxpr_bytes`` — the jaxpr pretty-printed text size;
* ``hlo_bytes`` — the lowered module text size (StableHLO);
* ``hlo_proto_bytes`` — the serialized HLO proto size when the backend
  exposes it (0 otherwise);
* ``trace_s`` / ``lower_s`` — wall seconds for the two stages.

Surfaces: ``tools/program_size.py`` (CLI), ``tune/measure.py`` arm
reports, the serve/store manifest (next to ``footprint_bytes``), and
``bench.py --config compile_wall``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ProgramSize:
    eqns: int
    jaxpr_bytes: int
    hlo_bytes: int
    hlo_proto_bytes: int
    trace_s: float
    lower_s: float

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ProgramSize":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls)})


def count_eqns(jaxpr) -> int:
    """Total equation count including sub-jaxprs (scan/while/pjit bodies
    and friends) — the same walk JXL004 budgets against."""
    from cimba_tpu.check.jaxprlint import collect_primitives

    return sum(collect_primitives(jaxpr).values())


def measure(fn, *avals, lower: bool = True) -> ProgramSize:
    """Probe ``fn`` at abstract arguments (arrays or ShapeDtypeStructs):
    trace, optionally lower, report sizes.  Nothing compiles or runs —
    at AWACS scale the *compile* is the wall this probe exists to
    predict, so the probe itself must stay cheap."""
    import jax

    t0 = time.perf_counter()
    closed = jax.make_jaxpr(fn)(*avals)
    trace_s = time.perf_counter() - t0
    eqns = count_eqns(closed.jaxpr)
    jaxpr_bytes = len(str(closed).encode())
    hlo_bytes = 0
    hlo_proto_bytes = 0
    lower_s = 0.0
    if lower:
        t0 = time.perf_counter()
        lowered = jax.jit(fn).lower(*avals)
        lower_s = time.perf_counter() - t0
        hlo_bytes = len(lowered.as_text().encode())
        try:
            proto = lowered.compiler_ir("hlo").as_serialized_hlo_module_proto()
            hlo_proto_bytes = len(proto)
        except Exception:
            hlo_proto_bytes = 0  # dialect not exposed on this backend
    return ProgramSize(
        eqns=eqns, jaxpr_bytes=jaxpr_bytes, hlo_bytes=hlo_bytes,
        hlo_proto_bytes=hlo_proto_bytes,
        trace_s=round(trace_s, 4), lower_s=round(lower_s, 4),
    )


def fused_program_size(
    specs, params=(), *, lanes: int = 4, max_steps: int = 64,
    profile: Optional[str] = None, seed: int = 2026, lower: bool = True,
) -> ProgramSize:
    """Probe the fused superprogram of ``specs``
    (docs/26_wave_fusion.md): merge them through
    :func:`cimba_tpu.core.fuse.fuse_specs` and measure the merged
    spec's chunk program.  This is THE number the fusion trade buys
    its occupancy with — the JXL004 sublinearity budget
    (:func:`cimba_tpu.check.jaxprlint.fused_size_findings`) holds it
    under a fraction of the members' solo-program sum (the machinery
    is shared; only the block tables concatenate)."""
    from cimba_tpu.core import fuse as _fuse

    fused = _fuse.fuse_specs(specs)
    return chunk_program_size(
        fused.spec, params, lanes=lanes, max_steps=max_steps,
        profile=profile, seed=seed, lower=lower,
    )


def chunk_program_size(
    spec, params=(), *, lanes: int = 4, max_steps: int = 64,
    profile: Optional[str] = None, seed: int = 2026, lower: bool = True,
) -> ProgramSize:
    """Probe a model's chunk program (the serve/kernel unit of work) at
    ``lanes`` replications.  Builds only abstract values — no arrays are
    materialized."""
    import contextlib

    import jax
    import jax.numpy as jnp

    from cimba_tpu import config
    from cimba_tpu.core import loop as cl

    ctx = config.profile(profile) if profile else contextlib.nullcontext()
    with ctx:
        sims = jax.eval_shape(
            jax.vmap(lambda r: cl.init_sim(spec, seed, r, params)),
            jnp.arange(lanes),
        )
        fn = cl.make_chunk(spec, max_steps=max_steps)
        return measure(fn, sims, lower=lower)
