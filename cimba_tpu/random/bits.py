"""Counter-based random bit generation: Threefry-2x32 streams.

Reference parity: ``src/cmb_random.c`` keeps a thread-local 256-bit sfc64
state seeded through splitmix64, with per-trial seed derivation via
MurmurHash3 fmix64 (`src/cmb_random.c:54-103`, `include/cimba.h:133-147`).

The TPU-native redesign replaces the *stateful* generator with a
*counter-based* one (Salmon et al., "Parallel Random Numbers: As Easy as
1, 2, 3", SC'11): each replication owns an independent Threefry-2x32 stream
identified by a 64-bit key, and every draw consumes one 64-bit counter
value.  Properties this buys on TPU:

* stateless block function — the stream state carried through
  ``lax.while_loop`` is just ``(key0, key1, counter)``: 3 words per
  replication instead of sfc64's 4x64-bit mutable state;
* any draw is addressable by ``(key, n)`` — replaying / checkpointing a
  replication mid-stream is trivial (store the counter);
* identical semantics under vmap/shard_map: replication r's n-th draw is a
  pure function of (seed, r, n), independent of batching layout.  This is
  the "seed-identical per-replication summaries" contract.

All arithmetic is uint32, the natively fast integer width on TPU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from cimba_tpu.config import BITS_DTYPE

_U32 = BITS_DTYPE

# Threefry-2x32 rotation schedule (Salmon et al. 2011, table 2).
_ROT_A = (13, 15, 26, 6)
_ROT_B = (17, 29, 16, 24)
# Key-schedule parity constant for Threefry (SkeinKsParity for 32-bit words).
_PARITY = jnp.uint32(0x1BD11BDA)


def _rotl(x, r: int):
    return (x << _U32(r)) | (x >> _U32(32 - r))


def _mix4(x0, x1, rots):
    for r in rots:
        x0 = x0 + x1
        x1 = _rotl(x1, r)
        x1 = x1 ^ x0
    return x0, x1


def threefry2x32(k0, k1, c0, c1):
    """20-round Threefry-2x32 block: (key, counter) -> two uint32 words.

    Implemented from the published algorithm (Random123 / SC'11 paper).
    """
    k0 = jnp.asarray(k0, _U32)
    k1 = jnp.asarray(k1, _U32)
    ks2 = k0 ^ k1 ^ _PARITY
    x0 = jnp.asarray(c0, _U32) + k0
    x1 = jnp.asarray(c1, _U32) + k1

    x0, x1 = _mix4(x0, x1, _ROT_A)
    x0, x1 = x0 + k1, x1 + ks2 + _U32(1)
    x0, x1 = _mix4(x0, x1, _ROT_B)
    x0, x1 = x0 + ks2, x1 + k0 + _U32(2)
    x0, x1 = _mix4(x0, x1, _ROT_A)
    x0, x1 = x0 + k0, x1 + k1 + _U32(3)
    x0, x1 = _mix4(x0, x1, _ROT_B)
    x0, x1 = x0 + k1, x1 + ks2 + _U32(4)
    x0, x1 = _mix4(x0, x1, _ROT_A)
    x0, x1 = x0 + ks2, x1 + k0 + _U32(5)
    return x0, x1


def fmix64(h):
    """MurmurHash3 64-bit finalizer — seed/nonce mixing.

    Parity with ``cmb_random_fmix64`` (`src/cmb_random.c:70-80`), used for
    deriving per-replication keys from (experiment seed, replication index).
    Public-domain algorithm (Austin Appleby).
    """
    h = jnp.asarray(h, jnp.uint64)
    h = h ^ (h >> jnp.uint64(33))
    h = h * jnp.uint64(0xFF51AFD7ED558CCD)
    h = h ^ (h >> jnp.uint64(33))
    h = h * jnp.uint64(0xC4CEB9FE1A85EC53)
    h = h ^ (h >> jnp.uint64(33))
    return h


class RandomState(NamedTuple):
    """Per-replication RNG stream state (a pytree of scalars when unbatched).

    ``key0/key1`` identify the stream; ``ctr`` is the number of 64-bit draws
    consumed so far, split into two uint32 words (lo, hi) so all arithmetic
    stays in uint32.
    """

    key0: jnp.ndarray
    key1: jnp.ndarray
    ctr_lo: jnp.ndarray
    ctr_hi: jnp.ndarray

    @property
    def n_draws(self):
        """Total 64-bit words drawn (uint64, for logging/checkpoint)."""
        return (
            jnp.asarray(self.ctr_hi, jnp.uint64) << jnp.uint64(32)
        ) | jnp.asarray(self.ctr_lo, jnp.uint64)


def initialize(seed, replication) -> RandomState:
    """Derive the stream for one replication from an experiment seed.

    Analog of per-trial seed derivation in the reference
    (`include/cimba.h:133-147`: seed = fmix64(experiment_seed, trial_index)).
    """
    mixed = fmix64(jnp.asarray(seed, jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15) * jnp.asarray(replication, jnp.uint64))
    k0 = jnp.asarray(mixed & jnp.uint64(0xFFFFFFFF), _U32)
    k1 = jnp.asarray(mixed >> jnp.uint64(32), _U32)
    zero = jnp.zeros((), _U32)
    return RandomState(k0, k1, zero, zero)


def to_u64(b0, b1):
    """Assemble two u32 words (lo, hi) into one u64."""
    return (jnp.asarray(b1, jnp.uint64) << jnp.uint64(32)) | jnp.asarray(
        b0, jnp.uint64
    )


# Draw-word hoist: the chain loop arms a stash per iteration (loop.py)
# so that the FIRST counter tick of every block branch shares ONE traced
# Threefry block.  Blocks are mutually exclusive per lane, so at runtime
# at most one branch consumes the words — but without the stash every
# draw *site* traced its own ~120-op Threefry, all of which execute every
# masked kernel step (mm1: 2 sites -> 260 scalar ops/event, the largest
# single line in the per-event budget).  Keyed by tracer IDENTITY of the
# incoming (key, counter): every branch receives the same pre-dispatch
# ``sim.rng`` tracers, so first ticks hit; a second tick in the same
# block has an advanced counter (new tracer) and misses to the normal
# path.  Values are bit-identical either way — the stash IS
# threefry(key, ctr) — so draw streams, goldens and checkpoints are
# unchanged.  Lazy: the block is computed at the first consuming site,
# so draw-free models trace nothing extra.
_stash = None


def stash_arm(state: RandomState) -> None:
    """Arm the hoist for the current trace with the pre-dispatch stream
    state.  Caller must :func:`stash_clear` when its trace scope ends."""
    global _stash
    from jax._src import core as _jcore

    _stash = [id(_jcore.trace_ctx.trace), state, None]


def stash_clear() -> None:
    global _stash
    _stash = None


def _stash_take(state: RandomState):
    s = _stash
    if s is None:
        return None
    from jax._src import core as _jcore

    tid, src, words = s
    if (
        tid != id(_jcore.trace_ctx.trace)
        or src.ctr_lo is not state.ctr_lo
        or src.ctr_hi is not state.ctr_hi
        or src.key0 is not state.key0
        or src.key1 is not state.key1
    ):
        return None
    if words is None:
        s[2] = words = threefry2x32(
            state.key0, state.key1, state.ctr_lo, state.ctr_hi
        )
    return words


def next_bits64(state: RandomState):
    """Draw one 64-bit word (as two uint32) and advance the counter."""
    hit = _stash_take(state)
    if hit is not None:
        b0, b1 = hit
    else:
        b0, b1 = threefry2x32(
            state.key0, state.key1, state.ctr_lo, state.ctr_hi
        )
    lo = state.ctr_lo + _U32(1)
    hi = state.ctr_hi + jnp.where(lo == _U32(0), _U32(1), _U32(0)).astype(_U32)
    return RandomState(state.key0, state.key1, lo, hi), b0, b1
