"""Two-station tandem Jackson network with probabilistic feedback.

The first queueing NETWORK in the model library (ROADMAP item 5):

    arrivals --> [q1 -> server 1] --> [q2 -> server 2] --> depart
                       ^                                |
                       +----------- p_back -------------+

External Poisson arrivals (rate lambda) join station 1; service at
station ``i`` is exponential (rate mu_i); a customer finishing station
2 routes back to station 1 with probability ``p_back``, else departs.

Theory (Jackson): the traffic equations give every station the same
effective arrival rate ``lambda_i = lambda / (1 - p_back)``, and the
product-form stationary distribution makes each station an M/M/1
marginal at ``rho_i = lambda_i / mu_i``.  By Little's law per station
the mean sojourn PER VISIT is ``W_i = 1 / (mu_i - lambda_i)`` — the
analytic pin (tests/test_tandem.py) — and the mean total time in the
network is ``(W_1 + W_2) / (1 - p_back)`` (a geometric number of
passes).

Statistics recorded per replication:

* ``w1`` / ``w2``: per-visit sojourn (queue entry -> service
  completion) at each station — pinned against ``W_i``;
* ``wait``: BOTH stations' per-visit sojourns in one summary (the
  default ``summary_path``), mean ``(W_1 + W_2) / 2`` since the visit
  rates are equal — so the model drops into every ``wait``-pooling
  flow (stream, serve, sweep) unchanged.

Implementation idiom: the mm1/mg1 fused-verb cycles (one chain
iteration per event where possible); the feedback put is a chained
``cmd.put`` -> ``get_hold`` pair (routing is not on a fused verb, and
this model is a correctness/coverage workload, not the headline).
Queue items carry their QUEUE-ENTRY timestamp, which is what makes the
per-visit sojourn measurable at the matching ``get``'s completion.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import cimba_tpu.random as cr
from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import api, cmd
from cimba_tpu.core.model import Model
from cimba_tpu.stats import summary as sm

_R = config.REAL
_I = INDEX_DTYPE

#: ilocal 0 of the arrival process: external customers produced;
#: ilocal 0 of server 2: customers departed the network
L_COUNT = 0


def build(queue_cap: int = 256):
    """Construct the tandem network; returns (spec, refs dict).

    ``queue_cap`` serves both stations.  256 (vs mm1's 128) because
    feedback compounds the tail: at the default operating point
    (rho_i ~ 0.67) the stationary P(len >= 256) is negligible, and the
    sweep grid's heavier cells (rho ~ 0.85) still clear it comfortably.
    """
    m = Model("tandem", n_ilocals=1, event_cap=1, guard_cap=4)
    q1 = m.objectqueue("station1", capacity=queue_cap)
    q2 = m.objectqueue("station2", capacity=queue_cap)

    @m.user_state
    def user_init(params):
        arr_mean, s1_mean, s2_mean, p_back, n_objects = params
        return {
            "arr_mean": jnp.asarray(arr_mean, _R),
            "s1_mean": jnp.asarray(s1_mean, _R),
            "s2_mean": jnp.asarray(s2_mean, _R),
            "p_back": jnp.asarray(p_back, _R),
            "n_objects": jnp.asarray(n_objects, _I),
            "wait": sm.empty(),   # combined per-visit sojourn (default path)
            "w1": sm.empty(),     # station-1 per-visit sojourn
            "w2": sm.empty(),     # station-2 per-visit sojourn
        }

    # --- external arrivals (the mm1 fused put_hold cycle) ------------------
    @m.block
    def a_start(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        return sim, cmd.hold(t, next_pc=a_cycle.pc)

    @m.block
    def a_cycle(sim, p, sig):
        sim = api.add_local_i(sim, p, L_COUNT, 1)
        produced = api.local_i(sim, p, L_COUNT)
        finished = produced >= sim.user["n_objects"]
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        now = api.clock(sim)
        return sim, cmd.select(
            finished,
            cmd.put(q1.id, now, next_pc=a_exit.pc),
            cmd.put_hold(q1.id, now, t, next_pc=a_cycle.pc),
        )

    @m.block
    def a_exit(sim, p, sig):
        return sim, cmd.exit_()

    # --- station 1: record w1, forward to station 2 ------------------------
    @m.block
    def s1_start(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["s1_mean"])
        return sim, cmd.get_hold(q1.id, t, next_pc=s1_cycle.pc)

    @m.block
    def s1_cycle(sim, p, sig):
        # got = the item's q1-entry timestamp: per-visit station sojourn
        w = api.clock(sim) - api.got(sim, p)
        sim = api.set_user(sim, {
            **sim.user,
            "wait": sm.add(sim.user["wait"], w),
            "w1": sm.add(sim.user["w1"], w),
        })
        # forward with the q2-ENTRY timestamp (now), so station 2
        # measures its own visit, then take the next q1 item
        now = api.clock(sim)
        return sim, cmd.put(q2.id, now, next_pc=s1_take.pc)

    @m.block
    def s1_take(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["s1_mean"])
        return sim, cmd.get_hold(q1.id, t, next_pc=s1_cycle.pc)

    # --- station 2: record w2, route (feedback or depart) ------------------
    @m.block
    def s2_start(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["s2_mean"])
        return sim, cmd.get_hold(q2.id, t, next_pc=s2_cycle.pc)

    @m.block
    def s2_cycle(sim, p, sig):
        w = api.clock(sim) - api.got(sim, p)
        sim = api.set_user(sim, {
            **sim.user,
            "wait": sm.add(sim.user["wait"], w),
            "w2": sm.add(sim.user["w2"], w),
        })
        sim, u = api.draw(sim, cr.uniform01)
        feedback = u < sim.user["p_back"]
        # count departures in server 2's ilocal; the replication stops
        # when every external customer has left the network
        sim = api.add_local_i(
            sim, p, L_COUNT, jnp.where(feedback, _I(0), _I(1))
        )
        departed = api.local_i(sim, p, L_COUNT)
        sim = api.stop(sim, departed >= sim.user["n_objects"])
        now = api.clock(sim)
        return sim, cmd.select(
            feedback,
            cmd.put(q1.id, now, next_pc=s2_take.pc),
            cmd.jump(next_pc=s2_take.pc),
        )

    @m.block
    def s2_take(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["s2_mean"])
        return sim, cmd.get_hold(q2.id, t, next_pc=s2_cycle.pc)

    m.process("arrival", entry=a_start)
    m.process("server1", entry=s1_start)
    m.process("server2", entry=s2_start)
    return m.build(), {"q1": q1, "q2": q2}


def params(
    n_objects: int,
    arr_rate: float = 0.5,
    s1_rate: float = 1.0,
    s2_rate: float = 1.25,
    p_back: float = 0.25,
):
    """Per-replication parameter tuple.  Defaults sit both stations
    near rho ~ 0.65/0.53 — loaded enough to queue, stable enough that
    modest horizons converge."""
    return (
        1.0 / arr_rate, 1.0 / s1_rate, 1.0 / s2_rate, p_back, n_objects,
    )


def internal_rate(arr_rate: float, p_back: float) -> float:
    """Jackson traffic equation: both stations see
    ``lambda / (1 - p_back)``."""
    if not 0.0 <= p_back < 1.0:
        raise ValueError(f"p_back must be in [0, 1), got {p_back}")
    return arr_rate / (1.0 - p_back)


def visit_sojourn(arr_rate: float, srv_rate: float, p_back: float) -> float:
    """Mean per-visit sojourn at one station: ``1/(mu - lambda_i)``
    (Little's law on the M/M/1 marginal; requires stability)."""
    lam = internal_rate(arr_rate, p_back)
    if lam >= srv_rate:
        raise ValueError(
            f"unstable station: lambda_i={lam:.4f} >= mu={srv_rate}"
        )
    return 1.0 / (srv_rate - lam)


def mean_visit_sojourn(
    arr_rate: float, s1_rate: float, s2_rate: float, p_back: float
) -> float:
    """What the model's combined ``wait`` summary converges to:
    ``(W_1 + W_2) / 2`` (both stations record at the same visit rate)."""
    return 0.5 * (
        visit_sojourn(arr_rate, s1_rate, p_back)
        + visit_sojourn(arr_rate, s2_rate, p_back)
    )


def network_sojourn(
    arr_rate: float, s1_rate: float, s2_rate: float, p_back: float
) -> float:
    """Mean total time in the network per external customer:
    ``(W_1 + W_2) / (1 - p_back)`` — a geometric number of passes."""
    return (
        visit_sojourn(arr_rate, s1_rate, p_back)
        + visit_sojourn(arr_rate, s2_rate, p_back)
    ) / (1.0 - p_back)


def sweep_grid(
    n_objects: int,
    arr_rates=(0.4, 0.5, 0.6),
    p_backs=(0.1, 0.25),
    s1_rate: float = 1.0,
    s2_rate: float = 1.25,
):
    """The tandem network as a sweep-able scenario grid
    (docs/16_sweeps.md): axes over external arrival rate and feedback
    probability — every cell validated stable at construction, so an
    adaptive sweep never burns rounds on a divergent queue."""
    from cimba_tpu.sweep import SweepGrid

    for a in arr_rates:
        for p in p_backs:
            visit_sojourn(a, s1_rate, p)   # raises if unstable
            visit_sojourn(a, s2_rate, p)

    def row(arr_rate, p_back):
        return (
            np.float64(1.0 / arr_rate),
            np.float64(1.0 / s1_rate),
            np.float64(1.0 / s2_rate),
            np.float64(p_back),
            np.int32(n_objects),
        )

    return SweepGrid(
        {"arr_rate": arr_rates, "p_back": p_backs}, row, name="tandem"
    )
