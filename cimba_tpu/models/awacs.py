"""AWACS radar scenario: many target agents + a scanning sensor with an
in-step vectorized physics computation.

Reference parity: the tutorial-5 AWACS scenario (`tutorial/tut_5_1.c` CPU,
`tut_5_3.c` multi-GPU): 1000 target coroutines fly straight-line legs with
random turn points; one sensor coroutine wakes every dwell interval and
scores all targets (terrain-masked detection) — on the GPU via CUDA kernels
launched from inside the coroutine.

TPU rendition of "level-3 parallelism": the physics IS jax — the sensor's
block computes detection over the whole [N, 2] position array in one
vectorized expression (later: a Pallas kernel via the same hook — a block
is arbitrary traced compute).  Per-target processes stay as framework
processes (count=N instances of one type), exercising the engine at the
reference's process counts.

Model state: user["pos_x/y"], user["vel_x/y"] [N] columns updated lazily — each
target process re-draws its leg at leg-end events; the sensor extrapolates
positions analytically between updates (pos + vel * (t - t_mark)), so
movement costs nothing between events, exactly like the reference storing
(position, velocity, t_mark) per target.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import cimba_tpu.random as cr
from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import api, cmd, dyn
from cimba_tpu.core.model import Model
from cimba_tpu.stats import summary as sm

_R = config.REAL
_I = INDEX_DTYPE

ARENA = 100.0          # square arena half-size
SPEED = 5.0            # target speed
LEG_MEAN = 4.0         # mean straight-leg duration
DETECT_RANGE = 40.0    # sensor detection radius
DWELL = 0.04 * 25      # dwell interval (scaled tut_5 pattern)

# --- NN detection scorer (BASELINE configs[4]: "on-device NN scoring",
# the reference's CUDA physics hook `tutorial/tut_5_3.cu` re-imagined as a
# Pallas matmul stack).  Weights are fixed at import (a deterministic
# stand-in for a trained radar-SNR model): two hidden layers + a strong
# skip connection on the range-gaussian feature so near targets dominate
# detections, as in the threshold model.

_NN_F = 8    # features per target
_NN_H = 32   # hidden width


def _make_nn_weights():
    rng = np.random.default_rng(20260729)

    def glorot(shape):
        lim = np.sqrt(6.0 / (shape[0] + shape[1]))
        return rng.uniform(-lim, lim, shape).astype(np.float32)

    w1 = glorot((_NN_F, _NN_H))
    b1 = np.zeros(_NN_H, np.float32)
    w2 = glorot((_NN_H, _NN_H))
    b2 = np.zeros(_NN_H, np.float32)
    # final layer sees [h2, range_gaussian]; the fixed skip weight keeps
    # the scorer physically sensible without training
    w3 = np.concatenate(
        [0.3 * glorot((_NN_H, 1)), np.full((1, 1), 8.0, np.float32)]
    )
    b3 = np.full(1, -2.0, np.float32)
    return tuple(jnp.asarray(a) for a in (w1, b1, w2, b2, w3, b3))


_NN_WEIGHTS = _make_nn_weights()


def _nn_features(pos, vel):
    """[N,2],[N,2] -> ([N,F] f32 features, [N] f32 range gaussian)."""
    pos = pos.astype(jnp.float32)
    vel = vel.astype(jnp.float32)
    r2 = jnp.sum(pos * pos, axis=1)
    g = jnp.exp(-r2 / jnp.float32(DETECT_RANGE**2))
    radial = jnp.sum(pos * vel, axis=1) / jnp.float32(SPEED * DETECT_RANGE)
    feats = jnp.stack(
        [
            pos[:, 0] / ARENA,
            pos[:, 1] / ARENA,
            r2 / jnp.float32(ARENA**2),
            g,
            vel[:, 0] / SPEED,
            vel[:, 1] / SPEED,
            radial,
            jnp.ones_like(g),
        ],
        axis=1,
    )
    return feats, g


def _nn_forward(feats, g, w1, b1, w2, b2, w3, b3):
    """The matmul stack: [N,F] -> detection probability [N] (f32)."""
    h1 = jax.nn.relu(
        jnp.dot(feats, w1, preferred_element_type=jnp.float32) + b1
    )
    h2 = jax.nn.relu(
        jnp.dot(h1, w2, preferred_element_type=jnp.float32) + b2
    )
    h2g = jnp.concatenate([h2, g[:, None]], axis=1)
    logit = jnp.dot(h2g, w3, preferred_element_type=jnp.float32) + b3
    return jax.nn.sigmoid(logit[:, 0])


def _nn_kernel(f_ref, g_ref, w1, b1, w2, b2, w3, b3, out_ref):
    out_ref[...] = _nn_forward(
        f_ref[...], g_ref[...][0],
        w1[...], b1[...][0], w2[...], b2[...][0], w3[...], b3[...][0],
    )[None]


def nn_scores(pos, vel, *, use_pallas=None, interpret=False):
    """Detection probabilities [N] for all targets — the physics hook.

    ``use_pallas=True`` executes the stack as one Pallas kernel (all
    operands in VMEM, matmuls on the MXU); ``False`` is the identical
    plain-jnp trace (the oracle for the equivalence test).  ``None``
    auto-selects Pallas on TPU.  The kernel is always pure f32 — detection
    scores need no f64 regardless of the active profile.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and not config.KERNEL_MODE
    feats, g = _nn_features(pos, vel)
    w1, b1, w2, b2, w3, b3 = _NN_WEIGHTS
    if not use_pallas:
        return _nn_forward(feats, g, w1, b1, w2, b2, w3, b3)
    n = feats.shape[0]
    npad = max(128, -(-n // 128) * 128)  # lane-width multiple; pad rows
    feats = jnp.pad(feats, ((0, npad - n), (0, 0)))
    g = jnp.pad(g, (0, npad - n))
    # rank-2 at the kernel boundary (1D vectors ride as [1, k])
    out = pl.pallas_call(
        _nn_kernel,
        out_shape=jax.ShapeDtypeStruct((1, npad), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 8,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(feats, g[None], w1, b1[None], w2, b2[None], w3, b3[None])
    return out[0, :n]


def build(n_targets: int, scoring: str = "nn"):
    """``scoring="nn"`` (default) runs the Pallas/MLP detection scorer —
    the reference's GPU physics hook (`tut_5_3.cu`) as a TPU matmul stack;
    ``"threshold"`` keeps the closed-form linear-falloff score (the
    tut_5_1 CPU model and the legacy behavior)."""
    if scoring not in ("nn", "threshold"):
        raise ValueError(f"scoring must be 'nn' or 'threshold': {scoring}")
    m = Model(
        "awacs",
        # the general event table holds only timers/user events (process
        # holds and resumes live in the dense per-pid wake table) and
        # this model schedules neither — a token capacity suffices where
        # 2*n_targets+8 slots were needed before the wake-table split,
        # and the per-event table scan cost scales with it
        event_cap=8,
        guard_cap=2,
    )

    @m.user_state
    def user_init(params):
        (t_end,) = params
        return {
            "t_end": jnp.asarray(t_end, _R),
            # positions/velocities as split x/y [N] columns, not [N,2]:
            # per-event one-hot row access on [N,2] pays a rank-expanded
            # mask (2N elements per op); split columns share the cached
            # [N] one-hot at exactly matching shape, halving the footprint of the
            # kernel path's hottest model-side ops
            "pos_x": jnp.zeros((n_targets,), _R),
            "pos_y": jnp.zeros((n_targets,), _R),
            "vel_x": jnp.zeros((n_targets,), _R),
            "vel_y": jnp.zeros((n_targets,), _R),
            "t_mark": jnp.zeros((n_targets,), _R),
            "detections": sm.empty(),  # per-dwell detection counts
            "dwells": jnp.zeros((), _I),
        }

    def _current_positions(sim):
        dt = sim.clock - sim.user["t_mark"]
        return jnp.stack(
            [
                sim.user["pos_x"] + sim.user["vel_x"] * dt,
                sim.user["pos_y"] + sim.user["vel_y"] * dt,
            ],
            axis=1,
        )

    @m.block
    def tgt_leg(sim, p, sig):
        """Start a new straight leg: random heading, exponential duration."""
        # target index within the type (targets are pids 0..N-1)
        idx = p
        # fold the position forward to now, then draw a new velocity
        # one-hot dynamic reads (dyn.dget): a raw traced-index gather has
        # no Mosaic lowering for the kernel path
        # grouped read: all five [N] columns at one pid, so the
        # scan-over-rows arm serves them from a single block loop
        t_mark, vel_x, vel_y, pos_x, pos_y = dyn.dget_tree(
            (sim.user["t_mark"], sim.user["vel_x"], sim.user["vel_y"],
             sim.user["pos_x"], sim.user["pos_y"]), idx,
        )
        dt = sim.clock - t_mark
        px = pos_x + vel_x * dt
        py = pos_y + vel_y * dt
        # soft-bounce: if outside the arena, head back toward the center.
        # Directions are selected as unit VECTORS, not heading angles:
        # cos/sin(arctan2(-y,-x)) in closed form is just -pos/|pos|, and
        # atan2 has no Pallas TPU lowering (the kernel path compiles this
        # block through Mosaic).
        sim, heading = api.draw(sim, cr.uniform, 0.0, 2.0 * jnp.pi)
        r = jnp.sqrt(px * px + py * py)
        outside = r > ARENA
        inv_r = 1.0 / jnp.maximum(r, 1e-6)
        vx = SPEED * jnp.where(outside, -px * inv_r, jnp.cos(heading))
        vy = SPEED * jnp.where(outside, -py * inv_r, jnp.sin(heading))
        u = sim.user
        w_pos_x, w_pos_y, w_vel_x, w_vel_y, w_t_mark = dyn.dset_tree(
            (u["pos_x"], u["pos_y"], u["vel_x"], u["vel_y"], u["t_mark"]),
            idx, (px, py, vx, vy, sim.clock),
        )
        sim = api.set_user(
            sim,
            {
                **u,
                "pos_x": w_pos_x,
                "pos_y": w_pos_y,
                "vel_x": w_vel_x,
                "vel_y": w_vel_y,
                "t_mark": w_t_mark,
            },
        )
        sim, leg = api.draw(sim, cr.exponential, LEG_MEAN)
        done = sim.clock >= sim.user["t_end"]
        return sim, cmd.select(
            done, cmd.exit_(), cmd.hold(leg, next_pc=tgt_leg.pc)
        )

    @m.boundary_block
    def sensor_dwell(sim, p, sig):
        """One radar dwell: vectorized detection over ALL targets — the
        physics hook (CUDA kernel in the reference, jax/Pallas here).

        A BOUNDARY block: on the kernel path this dispatch runs host-side
        between Pallas chunks as plain XLA, so the [N,32] NN stack rides
        the MXU batched over lanes instead of executing masked on every
        kernel event (it is only needed once per dwell — ~1 in 2N
        events).  Entered only via hold resumes and process entry, as
        the boundary contract requires."""
        pos = _current_positions(sim)
        # detection scores for every target, plus one uniform draw for the
        # whole dwell (scan noise)
        sim, noise = api.draw(sim, cr.uniform01)
        if scoring == "nn":
            vel = jnp.stack(
                [sim.user["vel_x"], sim.user["vel_y"]], axis=1
            )
            p_det = nn_scores(pos, vel).astype(_R)
        else:
            r2 = jnp.sum(pos * pos, axis=1)
            p_det = jnp.clip(1.2 - jnp.sqrt(r2) / DETECT_RANGE, 0.0, 1.0)
        detected = jnp.sum((p_det > noise).astype(_R))
        u = sim.user
        sim = api.set_user(
            sim,
            {
                **u,
                "detections": sm.add(u["detections"], detected),
                "dwells": u["dwells"] + 1,
            },
        )
        done = sim.clock >= sim.user["t_end"]
        sim = api.stop(sim, done)
        return sim, cmd.select(
            done, cmd.exit_(), cmd.hold(DWELL, next_pc=sensor_dwell.pc)
        )

    m.process("target", entry=tgt_leg, count=n_targets)  # pids 0..N-1
    m.process("sensor", entry=sensor_dwell, prio=1)      # pid N
    return m.build(), {}


def params(t_end: float):
    return (t_end,)