"""AWACS radar scenario: many target agents + a scanning sensor with an
in-step vectorized physics computation.

Reference parity: the tutorial-5 AWACS scenario (`tutorial/tut_5_1.c` CPU,
`tut_5_3.c` multi-GPU): 1000 target coroutines fly straight-line legs with
random turn points; one sensor coroutine wakes every dwell interval and
scores all targets (terrain-masked detection) — on the GPU via CUDA kernels
launched from inside the coroutine.

TPU rendition of "level-3 parallelism": the physics IS jax — the sensor's
block computes detection over the whole [N, 2] position array in one
vectorized expression (later: a Pallas kernel via the same hook — a block
is arbitrary traced compute).  Per-target processes stay as framework
processes (count=N instances of one type), exercising the engine at the
reference's process counts.

Model state: user["pos"] [N,2], user["vel"] [N,2] updated lazily — each
target process re-draws its leg at leg-end events; the sensor extrapolates
positions analytically between updates (pos + vel * (t - t_mark)), so
movement costs nothing between events, exactly like the reference storing
(position, velocity, t_mark) per target.
"""

from __future__ import annotations

import jax.numpy as jnp

import cimba_tpu.random as cr
from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import api, cmd
from cimba_tpu.core.model import Model
from cimba_tpu.stats import summary as sm

_R = config.REAL
_I = INDEX_DTYPE

ARENA = 100.0          # square arena half-size
SPEED = 5.0            # target speed
LEG_MEAN = 4.0         # mean straight-leg duration
DETECT_RANGE = 40.0    # sensor detection radius
DWELL = 0.04 * 25      # dwell interval (scaled tut_5 pattern)


def build(n_targets: int):
    m = Model(
        "awacs",
        event_cap=2 * n_targets + 8,
        guard_cap=2,
    )

    @m.user_state
    def user_init(params):
        (t_end,) = params
        return {
            "t_end": jnp.asarray(t_end, _R),
            "pos": jnp.zeros((n_targets, 2), _R),
            "vel": jnp.zeros((n_targets, 2), _R),
            "t_mark": jnp.zeros((n_targets,), _R),
            "detections": sm.empty(),  # per-dwell detection counts
            "dwells": jnp.zeros((), _I),
        }

    def _current_positions(sim):
        dt = sim.clock - sim.user["t_mark"]
        return sim.user["pos"] + sim.user["vel"] * dt[:, None]

    @m.block
    def tgt_leg(sim, p, sig):
        """Start a new straight leg: random heading, exponential duration."""
        # target index within the type (targets are pids 0..N-1)
        idx = p
        # fold the position forward to now, then draw a new velocity
        pos_now = sim.user["pos"][idx] + sim.user["vel"][idx] * (
            sim.clock - sim.user["t_mark"][idx]
        )
        # soft-bounce: if outside the arena, head back toward the center
        sim, heading = api.draw(sim, cr.uniform, 0.0, 2.0 * jnp.pi)
        to_center = -pos_now
        outside = jnp.linalg.norm(pos_now) > ARENA
        center_heading = jnp.arctan2(to_center[1], to_center[0])
        heading = jnp.where(outside, center_heading, heading)
        vel = SPEED * jnp.stack([jnp.cos(heading), jnp.sin(heading)])
        u = sim.user
        sim = api.set_user(
            sim,
            {
                **u,
                "pos": u["pos"].at[idx].set(pos_now),
                "vel": u["vel"].at[idx].set(vel),
                "t_mark": u["t_mark"].at[idx].set(sim.clock),
            },
        )
        sim, leg = api.draw(sim, cr.exponential, LEG_MEAN)
        done = sim.clock >= sim.user["t_end"]
        return sim, cmd.select(
            done, cmd.exit_(), cmd.hold(leg, next_pc=tgt_leg.pc)
        )

    @m.block
    def sensor_dwell(sim, p, sig):
        """One radar dwell: vectorized detection over ALL targets — the
        physics hook (CUDA kernel in the reference, jax/Pallas here)."""
        pos = _current_positions(sim)
        r2 = jnp.sum(pos * pos, axis=1)
        # detection: inside range with a smooth SNR-ish falloff, plus one
        # uniform draw for the whole dwell (scan noise)
        sim, noise = api.draw(sim, cr.uniform01)
        p_det = jnp.clip(1.2 - jnp.sqrt(r2) / DETECT_RANGE, 0.0, 1.0)
        detected = jnp.sum((p_det > noise).astype(_R))
        u = sim.user
        sim = api.set_user(
            sim,
            {
                **u,
                "detections": sm.add(u["detections"], detected),
                "dwells": u["dwells"] + 1,
            },
        )
        done = sim.clock >= sim.user["t_end"]
        sim = api.stop(sim, done)
        return sim, cmd.select(
            done, cmd.exit_(), cmd.hold(DWELL, next_pc=sensor_dwell.pc)
        )

    m.process("target", entry=tgt_leg, count=n_targets)  # pids 0..N-1
    m.process("sensor", entry=sensor_dwell, prio=1)      # pid N
    return m.build(), {}


def params(t_end: float):
    return (t_end,)