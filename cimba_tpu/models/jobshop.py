"""Job-shop network: a two-stage flow line with buffers, a shared crew
pool, and a condition-gated maintenance process.

Reference parity: the "job-shop network: buffers + condition-vars"
benchmark config (BASELINE.json configs[3], tut_4_2 pattern).  Structure:

    source --[stage A: crew + machine time]--> WIP buffer
           --[stage B: crew + machine time]--> done counter

* ``wip``: a cmb_buffer-style fungible store between the stages.
* ``crew``: a cmb_resourcepool shared by both stages (contention).
* maintenance waits on a condition "WIP backlog >= threshold" and then
  briefly slows stage B (acquiring extra crew) — exercising cond_wait
  against moving state, with the condition OBSERVING the wip buffer so
  every put re-evaluates it automatically (the
  cmb_resourceguard_register pattern — no manual cond_signal).

Fused-cycle redesign (round 5): the reference's straight-line C runs
acquire/release/put between yields for free; the masked kernel pays a
full body pass per chained command.  The cycles therefore ride the
fused verb family — ``pool_acquire_hold`` issues seize+serve as ONE
yield (service pre-drawn), ``buffer_put_hold`` fuses store+next-arrival,
and releases are INLINE (api.pool_release: release never blocks, so it
costs zero chain iterations).  Steady-state chain multiplicity drops
from ~3 to ~1.3 per event; semantics (grant order, signal order, FIFO
fairness) are the classic protocol's, pinned by tests/test_models.py.

Statistics: per-stage counts, WIP level time-average, sojourn through the
line.
"""

from __future__ import annotations

import jax.numpy as jnp

import cimba_tpu.random as cr
from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import api, cmd
from cimba_tpu.core.model import Model
from cimba_tpu.stats import summary as sm

_R = config.REAL
_I = INDEX_DTYPE


def build(
    wip_cap: float = 20.0,
    crew_size: float = 3.0,
    backlog: float = 8.0,
    b_slow: float = 5.0,
):
    """``b_slow`` scales stage B's work relative to stage A, making B the
    bottleneck so WIP genuinely accumulates (the tut_4_2 dynamic)."""
    # event_cap=1: every wake here (holds, fused holds, guard retries,
    # cond wakes) rides the dense per-pid wake table; no timers or user
    # events means the general table serves nothing — one placeholder
    # slot gates its scan/lexmin passes out of the step (mm1's round-5
    # sizing argument, models/mm1.py)
    m = Model("jobshop", n_ilocals=1, event_cap=1, guard_cap=8)
    wip = m.buffer("wip", capacity=wip_cap, initial=0.0)
    crew = m.resourcepool("crew", capacity=crew_size)
    cv = m.condition(
        "backlog",
        lambda sim, p: sim.buffers.level[wip.id] >= backlog,
        observes=[wip],
    )

    @m.user_state
    def user_init(params):
        arr_mean, work_mean, n_jobs = params
        return {
            "arr_mean": jnp.asarray(arr_mean, _R),
            "work_mean": jnp.asarray(work_mean, _R),
            "n_jobs": jnp.asarray(n_jobs, _I),
            "done": sm.empty(),          # completion-time summary
            "maintenance_runs": jnp.zeros((), _I),
        }

    # --- stage A: make one WIP unit per job -------------------------------
    # Per job at steady state: [arrival wake] a_entry seizes crew and
    # serves in one fused yield; [work-end wake] a_store counts, releases
    # inline, and fuses the store with the next arrival hold.
    @m.block
    def a_start(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        return sim, cmd.hold(t, next_pc=a_entry.pc)

    @m.block
    def a_entry(sim, p, sig):
        sim, tw = api.draw(sim, cr.exponential, sim.user["work_mean"])
        return sim, cmd.pool_acquire_hold(
            crew.id, 1.0, tw, next_pc=a_store.pc
        )

    @m.block
    def a_store(sim, p, sig):
        sim = api.add_local_i(sim, p, 0, 1)
        sim = api.pool_release(sim, _spec(), crew, p, 1.0)
        # the put signals the wip guards, and cv observes them — the
        # backlog condition re-evaluates with the unit already in store
        # (signal-before-change would never fire; the observer fires
        # after by construction)
        finished = api.local_i(sim, p, 0) >= sim.user["n_jobs"]
        sim, ta = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        return sim, cmd.select(
            finished,
            cmd.buffer_put(wip.id, 1.0, next_pc=a_exit.pc),
            cmd.buffer_put_hold(wip.id, 1.0, ta, next_pc=a_entry.pc),
        )

    @m.block
    def a_exit(sim, p, sig):
        return sim, cmd.exit_()

    # --- stage B: consume WIP ---------------------------------------------
    @m.block
    def b_take(sim, p, sig):
        return sim, cmd.buffer_get(wip.id, 1.0, next_pc=b_svc.pc)

    @m.block
    def b_svc(sim, p, sig):
        sim, t = api.draw(
            sim, cr.exponential, sim.user["work_mean"] * b_slow
        )
        return sim, cmd.pool_acquire_hold(
            crew.id, 1.0, t, next_pc=b_fin.pc
        )

    @m.block
    def b_fin(sim, p, sig):
        done = sm.add(sim.user["done"], api.clock(sim))
        sim = api.set_user(sim, {**sim.user, "done": done})
        sim = api.stop(sim, done.n >= sim.user["n_jobs"].astype(_R))
        sim = api.pool_release(sim, _spec(), crew, p, 1.0)
        return sim, cmd.buffer_get(wip.id, 1.0, next_pc=b_svc.pc)

    # --- maintenance: condition-gated -------------------------------------
    @m.block
    def mt_wait(sim, p, sig):
        return sim, cmd.cond_wait(cv.id, next_pc=mt_act.pc)

    @m.block
    def mt_act(sim, p, sig):
        sim = api.set_user(
            sim,
            {
                **sim.user,
                "maintenance_runs": sim.user["maintenance_runs"] + 1,
            },
        )
        # grab a crew member for a while (slows the shop down)
        return sim, cmd.pool_acquire_hold(
            crew.id, 1.0, 2.0, next_pc=mt_rel.pc
        )

    @m.block
    def mt_rel(sim, p, sig):
        sim = api.pool_release(sim, _spec(), crew, p, 1.0)
        return sim, cmd.cond_wait(cv.id, next_pc=mt_act.pc)

    m.process("stageA", entry=a_start)
    m.process("stageB", entry=b_take, count=2)
    m.process("maintenance", entry=mt_wait)

    spec_box = {}

    def _spec():
        return spec_box["spec"]

    spec = m.build()
    spec_box["spec"] = spec
    return spec, {"wip": wip, "crew": crew, "cond": cv}


def params(n_jobs: int, arr_mean: float = 1.0, work_mean: float = 0.4):
    return (arr_mean, work_mean, n_jobs)


def summary_path(sims):
    """The model's canonical pooled statistic — the per-replication
    completion-time summary (jobshop records no ``wait``, so the
    runner's ``default_summary_path`` does not apply).  A NAMED
    module-level function: the stream fold program, the serving
    compatibility key, and the program store's fold artifacts all key
    on its identity/content."""
    return sims.user["done"]
