"""M/M/c queue — c parallel servers fed by one FIFO.

Reference parity: the "M/M/c resource-pool queue" benchmark config
(BASELINE.json configs[1]).  Here the c servers are ``count=c`` instances
of one service process type sharing the arrival queue — the process-
interaction rendition; the machine-repair model in tests exercises
cmb_resourcepool semantics directly.

Theory: Erlang-C.  With a = lambda/mu and rho = a/c,
P_wait = ErlangC(c, a), mean wait in queue Wq = P_wait / (c*mu - lambda),
mean sojourn W = Wq + 1/mu.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

import cimba_tpu.random as cr
from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import api, cmd
from cimba_tpu.core.model import Model
from cimba_tpu.stats import summary as sm

_R = config.REAL
_I = INDEX_DTYPE

L_PRODUCED = 0


def build(c: int, queue_cap: int = 128):
    # 128 like mm1: each ring touch is a full-width kernel op, and at
    # the bench's rho ~ 0.83 (arrivals 2.5, c=3) the stationary
    # P(len >= 128) ~ 0.833^128 ~ 7e-11 per event — masked and counted
    # if ever hit (see mm1.build's sizing note)
    """M/M/c with ``c`` server-process instances."""
    m = Model(
        "mmc",
        n_ilocals=1,
        event_cap=8 + 2 * c,
        guard_cap=max(4, c + 2),
    )
    q = m.objectqueue("buffer", capacity=queue_cap)

    @m.user_state
    def user_init(params):
        arr_mean, srv_mean, n_objects = params
        return {
            "arr_mean": jnp.asarray(arr_mean, _R),
            "srv_mean": jnp.asarray(srv_mean, _R),
            "n_objects": jnp.asarray(n_objects, _I),
            "wait": sm.empty(),
        }

    # Fused-verb cycles: one chain iteration per event on the kernel
    # path (see models/mm1.py — same redesign; c servers share the
    # queue, each pending get_hold carries its own pre-drawn service
    # time through the wait)

    @m.block
    def a_start(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        return sim, cmd.hold(t, next_pc=a_cycle.pc)

    @m.block
    def a_cycle(sim, p, sig):
        sim = api.add_local_i(sim, p, L_PRODUCED, 1)
        produced = api.local_i(sim, p, L_PRODUCED)
        finished = produced >= sim.user["n_objects"]
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        now = api.clock(sim)
        return sim, cmd.select(
            finished,
            cmd.put(q.id, now, next_pc=a_exit.pc),
            cmd.put_hold(q.id, now, t, next_pc=a_cycle.pc),
        )

    @m.block
    def a_exit(sim, p, sig):
        return sim, cmd.exit_()

    @m.block
    def s_start(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["srv_mean"])
        return sim, cmd.get_hold(q.id, t, next_pc=s_cycle.pc)

    @m.block
    def s_cycle(sim, p, sig):
        t_sys = api.clock(sim) - api.got(sim, p)
        wait = sm.add(sim.user["wait"], t_sys)
        sim = api.set_user(sim, {**sim.user, "wait": wait})
        sim = api.stop(sim, wait.n >= sim.user["n_objects"].astype(_R))
        sim, t = api.draw(sim, cr.exponential, sim.user["srv_mean"])
        return sim, cmd.get_hold(q.id, t, next_pc=s_cycle.pc)

    m.process("arrival", entry=a_start, prio=0)
    m.process("server", entry=s_start, prio=0, count=c)
    return m.build(), {"queue": q}


def params(n_objects: int, arr_rate: float, srv_rate: float):
    return (1.0 / arr_rate, 1.0 / srv_rate, n_objects)


def erlang_c_sojourn(c: int, arr_rate: float, srv_rate: float) -> float:
    """Closed-form mean sojourn time for M/M/c (Erlang-C)."""
    a = arr_rate / srv_rate
    rho = a / c
    assert rho < 1.0
    inv_b = sum(a**k / math.factorial(k) for k in range(c))
    last = a**c / (math.factorial(c) * (1.0 - rho))
    p_wait = last / (inv_b + last)
    wq = p_wait / (c * srv_rate - arr_rate)
    return wq + 1.0 / srv_rate