"""M/M/1 queue — the flagship model and north-star benchmark.

Reference parity: ``benchmark/MM1_multi.c`` — an arrival process holds
exp(1/lambda) then puts a timestamp object into an unlimited FIFO; a service
process gets, holds exp(1/mu), and records the sojourn time
(`benchmark/MM1_multi.c:52-90`).  The trial ends after ``n_objects``
served objects.  Theory: mean sojourn = 1/(mu - lambda).

State per replication: two processes, one queue, one sojourn-time Summary.
Parameters travel in the user pytree (the reference's trial struct).
"""

from __future__ import annotations

import jax.numpy as jnp

import cimba_tpu.random as cr
from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import api, cmd
from cimba_tpu.core.model import Model
from cimba_tpu.stats import summary as sm

_R = config.REAL
_I = INDEX_DTYPE

#: ilocal 0 of the arrival process: number of objects produced
L_PRODUCED = 0


def build(
    queue_cap: int = 128,
    event_cap: int = 1,
    guard_cap: int = 4,
    record: bool = True,
):
    """Construct the M/M/1 model; returns (spec, refs dict).

    ``queue_cap`` bounds the FIFO (the reference uses CMB_UNLIMITED; a
    fixed capacity with overflow-as-failure is the jit trade).  Every
    ring touch is a full-width vector op in the kernel, so the cap is
    sized to the workload, not padded: at rho=0.9 the stationary
    P(len >= 128) ~ 0.9^128 ~ 1.4e-6 per event — about 140 masked,
    *counted* replication failures across the reference's entire
    100M-event headline run (bias ~1e-6 relative), while halving the
    ring's VMEM per lane vs 256.  Pass a bigger cap (or use
    run_experiment_regrow) for heavier-tailed loads.
    ``record=False`` drops queue-length recording from the hot loop (the
    benchmark configuration, like the reference's NLOGINFO build).
    ``event_cap=1``: holds and guard wakes ride the dense per-pid wake
    table; the general event table serves only timers/user events, of
    which this model has none — one placeholder slot keeps every
    general-table pass (scan, lexmin, validation) out of the per-event
    budget (trajectory-identical to any larger cap, pinned by goldens).
    """
    m = Model(
        "mm1",
        n_ilocals=1,
        event_cap=event_cap,
        guard_cap=guard_cap,
    )
    q = m.objectqueue("buffer", capacity=queue_cap, record=record)

    @m.user_state
    def user_init(params):
        arr_mean, srv_mean, n_objects = params
        return {
            "arr_mean": jnp.asarray(arr_mean, _R),
            "srv_mean": jnp.asarray(srv_mean, _R),
            "n_objects": jnp.asarray(n_objects, _I),
            "wait": sm.empty(),
        }

    # Fused-verb cycles (cmd.put_hold / cmd.get_hold): every chain
    # iteration on the kernel path costs a FULL masked body pass, so the
    # classic two-iteration cycle ("put succeeds inline, then the
    # continuation block holds" — the reference's free straight-line C
    # between yields, `benchmark/MM1_multi.c:52-90`) pays double.  The
    # fused commands issue the queue verb and the next hold as ONE
    # yield: one iteration, one body pass per event.  Durations are
    # pre-drawn at the previous wake — distributionally identical
    # (independent exponentials), order pinned by the goldens.

    @m.block
    def a_start(sim, p, sig):
        # reference arrival pattern: hold exp(1/lambda) before the
        # first put (`benchmark/MM1_multi.c:56-60`)
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        return sim, cmd.hold(t, next_pc=a_cycle.pc)

    @m.block
    def a_cycle(sim, p, sig):
        # at each arrival instant: put the timestamp and hold the next
        # pre-drawn inter-arrival — the last put continues inline to
        # the exit instead
        sim = api.add_local_i(sim, p, L_PRODUCED, 1)
        produced = api.local_i(sim, p, L_PRODUCED)
        finished = produced >= sim.user["n_objects"]
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        now = api.clock(sim)
        return sim, cmd.select(
            finished,
            cmd.put(q.id, now, next_pc=a_exit.pc),
            cmd.put_hold(q.id, now, t, next_pc=a_cycle.pc),
        )

    @m.block
    def a_exit(sim, p, sig):
        return sim, cmd.exit_()

    @m.block
    def s_start(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["srv_mean"])
        return sim, cmd.get_hold(q.id, t, next_pc=s_cycle.pc)

    @m.block
    def s_cycle(sim, p, sig):
        # at each service completion: record the finished item's
        # sojourn (got = its arrival timestamp), then get the next item
        # with a pre-drawn service time — one command per event
        t_sys = api.clock(sim) - api.got(sim, p)
        wait = sm.add(sim.user["wait"], t_sys)
        sim = api.set_user(sim, {**sim.user, "wait": wait})
        sim = api.stop(sim, wait.n >= sim.user["n_objects"].astype(_R))
        sim, t = api.draw(sim, cr.exponential, sim.user["srv_mean"])
        return sim, cmd.get_hold(q.id, t, next_pc=s_cycle.pc)

    m.process("arrival", entry=a_start, prio=0)
    m.process("service", entry=s_start, prio=0)
    return m.build(), {"queue": q}


def params(n_objects: int, arr_rate: float = 0.9, srv_rate: float = 1.0):
    """Per-replication parameter tuple (matches reference constants,
    `benchmark/MM1_multi.c:26-29`)."""
    return (1.0 / arr_rate, 1.0 / srv_rate, n_objects)