"""M/G/1 queue with lognormal service — the parameter-sweep model.

Reference parity: the M/G/1 sweep benchmark (`README.md:283-294`,
BASELINE.json configs[2]): 4 service CVs x 5 utilizations x 10 replications
= 200 trials in one experiment, each trial's parameters coming from its
slot in the experiment array.  Here the sweep is a params pytree with
leading axis R — the TPU experiment array.

Theory (Pollaczek–Khinchine): with utilization rho = lambda*E[S] and
service SCV cs2 = Var[S]/E[S]^2,
    Wq = rho * E[S] * (1 + cs2) / (2 * (1 - rho)),   W = Wq + E[S].
"""

from __future__ import annotations


import jax.numpy as jnp
import numpy as np

import cimba_tpu.random as cr
from cimba_tpu import config
from cimba_tpu.config import INDEX_DTYPE
from cimba_tpu.core import api, cmd
from cimba_tpu.core.model import Model
from cimba_tpu.stats import summary as sm

_R = config.REAL
_I = INDEX_DTYPE

L_PRODUCED = 0


def build(queue_cap: int = 512):
    """M/G/1: exponential arrivals, lognormal service of given mean/CV.

    ``queue_cap`` stays 512 (unlike mm1's 128): the sweep this model
    exists for (`sweep_params`) reaches rho=0.9 with CV=2.0, where
    Lq = rho^2(1+CV^2)/(2(1-rho)) ~ 20 and the (subexponential
    lognormal-service) tail puts P(len >= 128) near 1e-3 per event —
    a 128 ring would routinely overflow the heavy cells.  Callers
    running only light cells can pass a smaller cap."""
    # event_cap=1: no timers/user events — the dense wake table carries
    # holds and guard wakes (see models/mm1.py)
    m = Model("mg1", n_ilocals=1, event_cap=1, guard_cap=4)
    q = m.objectqueue("buffer", capacity=queue_cap)

    @m.user_state
    def user_init(params):
        arr_mean, srv_mean, srv_cv, n_objects = params
        # lognormal parameters from mean m_s and coefficient of variation
        sigma2 = jnp.log1p(jnp.asarray(srv_cv, _R) ** 2)
        mu = jnp.log(jnp.asarray(srv_mean, _R)) - 0.5 * sigma2
        return {
            "arr_mean": jnp.asarray(arr_mean, _R),
            "ln_mu": mu,
            "ln_sigma": jnp.sqrt(sigma2),
            "n_objects": jnp.asarray(n_objects, _I),
            "wait": sm.empty(),
        }

    # Fused-verb cycles: one chain iteration per event on the kernel
    # path (see models/mm1.py — same redesign, lognormal service)

    @m.block
    def a_start(sim, p, sig):
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        return sim, cmd.hold(t, next_pc=a_cycle.pc)

    @m.block
    def a_cycle(sim, p, sig):
        sim = api.add_local_i(sim, p, L_PRODUCED, 1)
        produced = api.local_i(sim, p, L_PRODUCED)
        finished = produced >= sim.user["n_objects"]
        sim, t = api.draw(sim, cr.exponential, sim.user["arr_mean"])
        now = api.clock(sim)
        return sim, cmd.select(
            finished,
            cmd.put(q.id, now, next_pc=a_exit.pc),
            cmd.put_hold(q.id, now, t, next_pc=a_cycle.pc),
        )

    @m.block
    def a_exit(sim, p, sig):
        return sim, cmd.exit_()

    @m.block
    def s_start(sim, p, sig):
        sim, t = api.draw(
            sim, cr.lognormal, sim.user["ln_mu"], sim.user["ln_sigma"]
        )
        return sim, cmd.get_hold(q.id, t, next_pc=s_cycle.pc)

    @m.block
    def s_cycle(sim, p, sig):
        t_sys = api.clock(sim) - api.got(sim, p)
        wait = sm.add(sim.user["wait"], t_sys)
        sim = api.set_user(sim, {**sim.user, "wait": wait})
        sim = api.stop(sim, wait.n >= sim.user["n_objects"].astype(_R))
        sim, t = api.draw(
            sim, cr.lognormal, sim.user["ln_mu"], sim.user["ln_sigma"]
        )
        return sim, cmd.get_hold(q.id, t, next_pc=s_cycle.pc)

    m.process("arrival", entry=a_start)
    m.process("service", entry=s_start)
    return m.build(), {"queue": q}


def sweep_grid(
    n_objects: int,
    cvs=(0.25, 0.5, 1.0, 2.0),
    utilizations=(0.5, 0.6, 0.7, 0.8, 0.9),
    srv_mean: float = 1.0,
):
    """The reference's 4x5 cell table as a declarative
    :class:`~cimba_tpu.sweep.SweepGrid` (docs/16_sweeps.md): axes over
    service CV and utilization, each cell's row the
    ``(arr_mean, srv_mean, srv_cv, n_objects)`` tuple ``build``'s
    ``user_init`` unpacks.  ``grid.rows(reps_per_cell)`` reproduces the
    historical hand-rolled experiment array bitwise (pinned in
    tests/test_sweep.py); the sweep engine consumes the grid per cell
    instead, fixed-R or adaptive."""
    from cimba_tpu.sweep import SweepGrid

    def row(cv, rho):
        return (
            np.float64(srv_mean / rho),  # lambda = rho/E[S]
            np.float64(srv_mean),
            np.float64(cv),
            np.int32(n_objects),
        )

    return SweepGrid({"cv": cvs, "rho": utilizations}, row, name="mg1")


def sweep_params(
    n_objects: int,
    cvs=(0.25, 0.5, 1.0, 2.0),
    utilizations=(0.5, 0.6, 0.7, 0.8, 0.9),
    reps_per_cell: int = 10,
    srv_mean: float = 1.0,
):
    """The reference's 4x5x10 experiment array: one row per replication
    (now a :func:`sweep_grid` projection — layout and values bitwise
    the historical hand-rolled construction).

    Returns (params tuple of [R] arrays, cells) where cells[i] = (cv, rho)
    of replication i.
    """
    grid = sweep_grid(
        n_objects, cvs=cvs, utilizations=utilizations, srv_mean=srv_mean
    )
    params, _ = grid.rows(reps_per_cell)
    cells = [
        (c["cv"], c["rho"])
        for c in grid.cells()
        for _ in range(reps_per_cell)
    ]
    return params, cells


def pk_sojourn(rho: float, cv: float, srv_mean: float = 1.0) -> float:
    """Pollaczek–Khinchine mean sojourn time."""
    wq = rho * srv_mean * (1.0 + cv * cv) / (2.0 * (1.0 - rho))
    return wq + srv_mean