"""Flag-mask logging from inside jitted simulation code.

Reference parity: ``cmb_logger`` (`src/cmb_logger.c`) — a 32-bit flag mask
(4 reserved levels + 28 user bits), line format
``[trial] [seed] time process func: msg``, INFO compiled out by
``-DNLOGINFO``, ``error`` triggering per-trial recovery.

TPU rendition: the mask is *trace-time* state.  A disabled level costs
literally nothing (the call traces to no ops — the NLOGINFO story without
a rebuild of the library, just a re-jit); an enabled level lowers to
``jax.debug.print`` host callbacks carrying the replication clock and pid.
``error`` additionally sets the replication's failure flag — the analog of
the reference's longjmp-to-worker recovery (§3.5), minus the longjmp.

Changing flags affects subsequently *traced* code: re-jit (or clear jit
caches) after flipping levels, exactly as the reference requires a
recompile for NLOGINFO.
"""

from __future__ import annotations

import jax

# reserved level bits (parity: CMB_LOGGER_* flag values)
FATAL = 1 << 0
ERROR = 1 << 1
WARNING = 1 << 2
INFO = 1 << 3
#: first free user bit (28 available, parity with the reference's layout)
USER = 1 << 4

_mask = FATAL | ERROR | WARNING  # INFO off by default, like release builds


def flags_on(bits: int) -> None:
    """Enable levels (parity: cmb_logger_flags_on)."""
    global _mask
    _mask |= bits


def flags_off(bits: int) -> None:
    """Disable levels (parity: cmb_logger_flags_off)."""
    global _mask
    _mask &= ~bits


def flags() -> int:
    return _mask


def _emit(level_name, sim, p, fmt, *args, **kwargs):
    jax.debug.print(
        "[{level}] t={t:.6f} p={p} err={e} | " + fmt,
        level=level_name,
        t=sim.clock,
        p=p,
        e=sim.err,
        *args,
        **kwargs,
        ordered=False,
    )


def info(sim, p, fmt: str, *args, **kwargs):
    """Log at INFO if enabled at trace time; returns sim unchanged."""
    if _mask & INFO:
        _emit("info", sim, p, fmt, *args, **kwargs)
    return sim


def warning(sim, p, fmt: str, *args, **kwargs):
    if _mask & WARNING:
        _emit("warn", sim, p, fmt, *args, **kwargs)
    return sim


def user(bit: int, sim, p, fmt: str, *args, **kwargs):
    """Log on a user-defined flag bit (parity: the 28 user bits)."""
    if _mask & bit:
        _emit(f"u{bit:x}", sim, p, fmt, *args, **kwargs)
    return sim


def error(sim, p, fmt: str, *args, **kwargs):
    """Log AND mark the replication failed (parity: cmb_logger_error's
    abandon-this-trial recovery — the runner counts it, the batch
    continues)."""
    from cimba_tpu.core import api

    if _mask & ERROR:
        _emit("error", sim, p, fmt, *args, **kwargs)
    return api.fail(sim)