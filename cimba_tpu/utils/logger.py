"""Flag-mask logging from inside jitted simulation code.

Reference parity: ``cmb_logger`` (`src/cmb_logger.c`) — a 32-bit flag mask
(4 reserved levels + 28 user bits), line format
``[trial] [seed] time process func: msg``, INFO compiled out by
``-DNLOGINFO``, ``error`` triggering per-trial recovery.

TPU rendition: the mask is *trace-time* state.  A disabled level costs
literally nothing (the call traces to no ops — the NLOGINFO story without
a rebuild of the library, just a re-jit); an enabled level lowers to
``jax.debug.print`` host callbacks carrying the replication clock and pid.
``error`` additionally sets the replication's failure flag — the analog of
the reference's longjmp-to-worker recovery (§3.5), minus the longjmp.

Changing flags affects subsequently *traced* code: re-jit (or clear jit
caches) after flipping levels, exactly as the reference requires a
recompile for NLOGINFO.
"""

from __future__ import annotations

import jax

# reserved level bits (parity: CMB_LOGGER_* flag values)
FATAL = 1 << 0
ERROR = 1 << 1
WARNING = 1 << 2
INFO = 1 << 3
#: first free user bit (28 available, parity with the reference's layout)
USER = 1 << 4

_mask = FATAL | ERROR | WARNING  # INFO off by default, like release builds

# settable time formatter (parity: cmb_logger_timeformatter_set,
# `src/cmb_logger.c:94-112`): a host-side ``fn(float) -> str``; None = the
# default fixed-width rendering
_timeformatter = None

# process-name table (parity: the reference line carries the process NAME
# and func(line), `src/cmb_logger.c:149-227`).  Names are static model
# structure, so the table binds host-side: Model.build() registers the
# per-pid names and log lines render ``name(pid)`` in a host callback.
_proc_names = None


def names_set(names) -> None:
    """Register per-pid process names for log rendering (called by
    ``Model.build``; last built model wins, like the reference's one
    TLS process context per thread)."""
    global _proc_names
    _proc_names = list(names) if names else None


def _pid_str(names, p) -> str:
    if names is not None and 0 <= int(p) < len(names):
        return f"{names[int(p)]}({int(p)})"
    return str(int(p))


def _caller_src() -> str:
    """Trace-time call-site tag ``func(line)`` (parity: the reference's
    __func__/__LINE__ in every line) — resolved once per trace, free at
    run time.  Walks raw frames (no inspect.stack(): that materializes
    source context for the entire, hundreds-deep tracing stack)."""
    import sys

    f = sys._getframe(2)
    for _ in range(4):
        if f is None:
            break
        if f.f_code.co_filename != __file__:
            return f"{f.f_code.co_name}({f.f_lineno})"
        f = f.f_back
    return "?"


def flags_on(bits: int) -> None:
    """Enable levels (parity: cmb_logger_flags_on)."""
    global _mask
    _mask |= bits


def flags_off(bits: int) -> None:
    """Disable levels (parity: cmb_logger_flags_off)."""
    global _mask
    _mask &= ~bits


def flags() -> int:
    return _mask


def timeformatter_set(fn) -> None:
    """Replace the time rendering on every subsequently *traced* log call
    (parity: cmb_logger_timeformatter_set; the reference swaps a function
    pointer at runtime — here, as with flags, it binds at trace time).
    ``fn(t: float) -> str`` runs host-side; pass None to restore the
    default."""
    global _timeformatter
    _timeformatter = fn


def _stream_id(sim):
    """Reproduction context (parity: the seed printed on warning+ lines,
    `src/cmb_logger.c:149-227`): the counter-based RNG means (key, ctr)
    replays the stream exactly — stronger than the reference's curseed."""
    import jax.numpy as jnp

    key = (jnp.asarray(sim.rng.key1, jnp.uint64) << jnp.uint64(32)) | (
        jnp.asarray(sim.rng.key0, jnp.uint64)
    )
    return key, sim.rng.n_draws


def _emit(level_name, sim, p, fmt, *args, **kwargs):
    """One host-callback line: ``[level] r t process func(line) err | msg``
    (parity: the reference's `[trial] [seed] time process func(line): msg`,
    `src/cmb_logger.c:149-227`).  Process names and the call-site tag are
    trace-time constants; only the numeric payload crosses the boundary.

    Kernel-path contract (docs/07): ``jax.debug.callback`` cannot cross
    a Mosaic kernel, so an enabled log level reached while tracing under
    KERNEL_MODE fails HERE, loudly, at build time — never a silent line
    loss or an opaque Mosaic lowering error hours later.  Only models
    that actually trace an enabled log call are affected; a disabled
    level still traces to nothing on every path."""
    from cimba_tpu import config as _cfg

    if _cfg.KERNEL_MODE:
        raise RuntimeError(
            f"logger.{level_name}: log emission inside the Pallas kernel "
            "path — host callbacks cannot cross a Mosaic kernel.  Either "
            "disable the level for kernel runs (logger.flags_off, the "
            "reference's NLOGINFO analog), or run this model on the XLA "
            "while-loop path (cl.make_run), which logs fine.  See "
            "docs/07_kernel_path.md."
        )
    rep = getattr(sim, "rep", -1)
    src = _caller_src()
    tff = _timeformatter
    names = _proc_names  # snapshot at trace time, like tff/src — a later
    # Model.build() must not relabel an already-jitted model's lines

    def host(r, t, p_, e, *a, **kw):
        ts = tff(float(t)) if tff is not None else f"{float(t):.6f}"
        print(
            f"[{level_name}] r={int(r)} t={ts} p={_pid_str(names, p_)} "
            f"{src} err={int(e)} | " + fmt.format(*a, **kw),
            flush=True,
        )

    jax.debug.callback(host, rep, sim.clock, p, sim.err, *args, **kwargs)


def _emit_with_seed(level_name, sim, p, fmt, *args, **kwargs):
    """warning+ lines carry the stream id for reproduction (parity:
    `src/cmb_logger.c:214-218`): rebuild the failing replication's RNG with
    RandomState(key0, key1, ctr) and replay."""
    key, ctr = _stream_id(sim)
    _emit(
        level_name, sim, p,
        fmt + "  [replay: key=0x{_key:016x} ctr={_ctr}]",
        *args, _key=key, _ctr=ctr, **kwargs,
    )


def info(sim, p, fmt: str, *args, **kwargs):
    """Log at INFO if enabled at trace time; returns sim unchanged."""
    if _mask & INFO:
        _emit("info", sim, p, fmt, *args, **kwargs)
    return sim


def warning(sim, p, fmt: str, *args, **kwargs):
    if _mask & WARNING:
        _emit_with_seed("warn", sim, p, fmt, *args, **kwargs)
    return sim


def user(bit: int, sim, p, fmt: str, *args, **kwargs):
    """Log on a user-defined flag bit (parity: the 28 user bits)."""
    if _mask & bit:
        _emit(f"u{bit:x}", sim, p, fmt, *args, **kwargs)
    return sim


def _fail_level(level_name, bit, sim, p, fmt, args, kwargs):
    """Shared body of :func:`error` and :func:`fatal`: log with the
    replay stream id if the level is enabled, and mark the replication
    failed either way.  In-kernel the failure-flag semantics are
    preserved but the log LINE cannot cross the Mosaic boundary: it is
    dropped with a trace-time Python warning (not the hard info/warning
    raise — a model's containment path must not make it un-compilable
    on the kernel)."""
    from cimba_tpu import config as _cfg
    from cimba_tpu.core import api

    if _mask & bit:
        if _cfg.KERNEL_MODE:
            import warnings

            warnings.warn(
                f"logger.{level_name} inside the Pallas kernel path: the "
                "replication failure flag is preserved, but the log "
                "line is dropped (host callbacks cannot cross a Mosaic "
                "kernel; docs/07_kernel_path.md).  Inspect sim.err and "
                "the replay key host-side instead.",
                stacklevel=3,
            )
        else:
            _emit_with_seed(level_name, sim, p, fmt, *args, **kwargs)
    return api.fail(sim)


def fatal(sim, p, fmt: str, *args, **kwargs):
    """Log at the reserved FATAL level AND mark the replication failed.

    Parity: the reference reserves the FATAL bit (the lowest of the 4
    ``CMB_LOGGER_*`` levels) for errors the run cannot recover from.
    Under the batch model nothing is allowed to take down the *process*
    — so fatal's containment is the same as :func:`error`'s (the
    replication freezes with ``sim.err`` set and the runner counts it);
    the distinction is the level tag, and that silencing the level must
    not unfail the replication."""
    return _fail_level("fatal", FATAL, sim, p, fmt, args, kwargs)


def error(sim, p, fmt: str, *args, **kwargs):
    """Log AND mark the replication failed (parity: cmb_logger_error's
    abandon-this-trial recovery — the runner counts it, the batch
    continues)."""
    return _fail_level("error", ERROR, sim, p, fmt, args, kwargs)