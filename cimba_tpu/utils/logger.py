"""Flag-mask logging from inside jitted simulation code.

Reference parity: ``cmb_logger`` (`src/cmb_logger.c`) — a 32-bit flag mask
(4 reserved levels + 28 user bits), line format
``[trial] [seed] time process func: msg``, INFO compiled out by
``-DNLOGINFO``, ``error`` triggering per-trial recovery.

TPU rendition: the mask is *trace-time* state.  A disabled level costs
literally nothing (the call traces to no ops — the NLOGINFO story without
a rebuild of the library, just a re-jit); an enabled level lowers to
``jax.debug.print`` host callbacks carrying the replication clock and pid.
``error`` additionally sets the replication's failure flag — the analog of
the reference's longjmp-to-worker recovery (§3.5), minus the longjmp.

Changing flags affects subsequently *traced* code: re-jit (or clear jit
caches) after flipping levels, exactly as the reference requires a
recompile for NLOGINFO.
"""

from __future__ import annotations

import jax

# reserved level bits (parity: CMB_LOGGER_* flag values)
FATAL = 1 << 0
ERROR = 1 << 1
WARNING = 1 << 2
INFO = 1 << 3
#: first free user bit (28 available, parity with the reference's layout)
USER = 1 << 4

_mask = FATAL | ERROR | WARNING  # INFO off by default, like release builds

# settable time formatter (parity: cmb_logger_timeformatter_set,
# `src/cmb_logger.c:94-112`): a host-side ``fn(float) -> str``; None = the
# default fixed-width rendering
_timeformatter = None


def flags_on(bits: int) -> None:
    """Enable levels (parity: cmb_logger_flags_on)."""
    global _mask
    _mask |= bits


def flags_off(bits: int) -> None:
    """Disable levels (parity: cmb_logger_flags_off)."""
    global _mask
    _mask &= ~bits


def flags() -> int:
    return _mask


def timeformatter_set(fn) -> None:
    """Replace the time rendering on every subsequently *traced* log call
    (parity: cmb_logger_timeformatter_set; the reference swaps a function
    pointer at runtime — here, as with flags, it binds at trace time).
    ``fn(t: float) -> str`` runs host-side; pass None to restore the
    default."""
    global _timeformatter
    _timeformatter = fn


def _stream_id(sim):
    """Reproduction context (parity: the seed printed on warning+ lines,
    `src/cmb_logger.c:149-227`): the counter-based RNG means (key, ctr)
    replays the stream exactly — stronger than the reference's curseed."""
    import jax.numpy as jnp

    key = (jnp.asarray(sim.rng.key1, jnp.uint64) << jnp.uint64(32)) | (
        jnp.asarray(sim.rng.key0, jnp.uint64)
    )
    return key, sim.rng.n_draws


def _emit(level_name, sim, p, fmt, *args, **kwargs):
    rep = getattr(sim, "rep", -1)
    if _timeformatter is None:
        jax.debug.print(
            "[{level}] r={r} t={t:.6f} p={p} err={e} | " + fmt,
            level=level_name,
            r=rep,
            t=sim.clock,
            p=p,
            e=sim.err,
            *args,
            **kwargs,
            ordered=False,
        )
    else:
        tff = _timeformatter

        def host(r, t, p_, e, *a, **kw):
            print(
                f"[{level_name}] r={r} t={tff(float(t))} p={p_} err={e} | "
                + fmt.format(*a, **kw),
                flush=True,
            )

        jax.debug.callback(host, rep, sim.clock, p, sim.err, *args, **kwargs)


def _emit_with_seed(level_name, sim, p, fmt, *args, **kwargs):
    """warning+ lines carry the stream id for reproduction (parity:
    `src/cmb_logger.c:214-218`): rebuild the failing replication's RNG with
    RandomState(key0, key1, ctr) and replay."""
    key, ctr = _stream_id(sim)
    _emit(
        level_name, sim, p,
        fmt + "  [replay: key=0x{_key:016x} ctr={_ctr}]",
        *args, _key=key, _ctr=ctr, **kwargs,
    )


def info(sim, p, fmt: str, *args, **kwargs):
    """Log at INFO if enabled at trace time; returns sim unchanged."""
    if _mask & INFO:
        _emit("info", sim, p, fmt, *args, **kwargs)
    return sim


def warning(sim, p, fmt: str, *args, **kwargs):
    if _mask & WARNING:
        _emit_with_seed("warn", sim, p, fmt, *args, **kwargs)
    return sim


def user(bit: int, sim, p, fmt: str, *args, **kwargs):
    """Log on a user-defined flag bit (parity: the 28 user bits)."""
    if _mask & bit:
        _emit(f"u{bit:x}", sim, p, fmt, *args, **kwargs)
    return sim


def error(sim, p, fmt: str, *args, **kwargs):
    """Log AND mark the replication failed (parity: cmb_logger_error's
    abandon-this-trial recovery — the runner counts it, the batch
    continues)."""
    from cimba_tpu.core import api

    if _mask & ERROR:
        _emit_with_seed("error", sim, p, fmt, *args, **kwargs)
    return api.fail(sim)