"""Host-side state dumps for debugging models.

Reference parity: ``cmb_event_queue_print`` (`src/cmb_event.c:510-532`),
``cmi_hashheap_print`` (`src/cmi_hashheap.c:895-937`) and the golden-file
event dumps in `test/reference/event.txt`.  These render a (single
replication's) Sim — fetch one lane with
``jax.tree.map(lambda x: x[r], sims)`` first if batched.
"""

from __future__ import annotations

import numpy as np

from cimba_tpu.core import process as pr
from cimba_tpu.core.model import ModelSpec


_KIND_NAMES = {0: "PROC", 1: "TIMER"}
_STATUS = {0: "CREATED", 1: "RUNNING", 2: "FINISHED"}


def kind_name(kind: int, spec: ModelSpec | None = None) -> str:
    """Dispatch-kind label: framework kinds by name, user kinds by their
    handler's ``__name__`` when a spec is given (the one name table both
    the golden dumps and the Chrome-trace exporter render with)."""
    if kind in _KIND_NAMES:
        return _KIND_NAMES[kind]
    if spec is not None:
        u = kind - 2
        if 0 <= u < len(spec.user_handlers):
            return getattr(spec.user_handlers[u], "__name__", f"user{kind}")
    return f"user{kind}"


def subj_name(subj: int, kind: int, spec: ModelSpec | None = None) -> str:
    """Event-subject label: process name for process/timer kinds, the raw
    id otherwise (user kinds address arbitrary subjects)."""
    if spec is not None and kind <= 1 and 0 <= subj < len(spec.proc_names):
        return spec.proc_names[subj]
    return str(subj)


def eventset_str(sim, spec: ModelSpec | None = None) -> str:
    """Pending events in firing order (parity: cmb_event_queue_print)."""
    es = sim.events
    t = np.asarray(es.time)
    live = np.isfinite(t)
    rows = []
    order = sorted(
        np.nonzero(live)[0],
        key=lambda i: (t[i], -int(es.prio[i]), int(es.seq[i])),
    )
    for i in order:
        kind = int(es.kind[i])
        kname = _KIND_NAMES.get(kind, f"user{kind}")
        subj = int(es.subj[i])
        name = (
            spec.proc_names[subj]
            if spec and kind <= 1 and subj < len(spec.proc_names)
            else str(subj)
        )
        rows.append(
            f"  t={t[i]:<14.6f} prio={int(es.prio[i]):<4d} "
            f"seq={int(es.seq[i]):<6d} {kname:<6s} subj={name} "
            f"arg={int(es.arg[i])}"
        )
    head = f"event set: {len(rows)} pending, next_seq={int(es.next_seq)}"
    return "\n".join([head] + rows)


def procs_str(sim, spec: ModelSpec | None = None) -> str:
    """Process table (parity: the per-process state the logger prints)."""
    ps = sim.procs
    rows = ["pid name            status    pc   prio pend  guard await"]
    for p in range(ps.pc.shape[0]):
        name = spec.proc_names[p] if spec else f"p{p}"
        pend = int(ps.pend_tag[p])
        rows.append(
            f"{p:<3d} {name:<15s} {_STATUS.get(int(ps.status[p]), '?'):<9s} "
            f"{int(ps.pc[p]):<4d} {int(ps.prio[p]):<4d} "
            f"{pend if pend != int(pr.NO_PEND) else '-':<5} "
            f"{int(ps.pend_guard[p]):<5d} {int(ps.await_pid[p])}"
        )
    return "\n".join(rows)


def trace_str(sim, spec: ModelSpec | None = None) -> str:
    """Flight-recorder ring in dispatch order, in the golden-dump format
    of :func:`eventset_str` (parity: what cmb_event_queue_print would
    show for the events the dispatcher already ran).  Renders a one-line
    notice when the Sim carries no ring (recorder disabled at init)."""
    ring = getattr(sim, "trace", None)
    if ring is None:
        return "flight recorder: disabled"
    from cimba_tpu.obs import trace as _trace

    r = _trace.unwrap(ring)
    rows = []
    for t, pid, kind, arg, seq in zip(
        r["t"], r["pid"], r["kind"], r["arg"], r["seq"]
    ):
        kind = int(kind)
        rows.append(
            f"  t={float(t):<14.6f} seq={int(seq):<6d} "
            f"{kind_name(kind, spec):<6s} "
            f"subj={subj_name(int(pid), kind, spec)} arg={int(arg)}"
        )
    head = (
        f"flight recorder: {len(rows)} recorded of "
        f"{r['count']} dispatched (cap {r['capacity']})"
    )
    return "\n".join([head] + rows)


def sim_str(sim, spec: ModelSpec | None = None) -> str:
    """One-replication overview (includes the flight-recorder ring when
    the Sim carries one)."""
    out = (
        f"clock={float(sim.clock):.6f} err={int(sim.err)} "
        f"done={bool(sim.done)} events_dispatched={int(sim.n_events)}\n"
        + eventset_str(sim, spec)
        + "\n"
        + procs_str(sim, spec)
    )
    if getattr(sim, "trace", None) is not None:
        out += "\n" + trace_str(sim, spec)
    return out