"""Design-by-contract assertions, three tiers, statically gated.

Reference parity: ``cmb_assert`` (`include/cmb_assert.h:45-84`) —
``cmb_assert_debug`` (off at NDEBUG), ``cmb_assert_release`` (off at
NASSERT), ``cmb_assert_always``; ~13% of the reference's lines are asserts
and disabling the debug tier is a documented ~2x speedup.

TPU rendition: tiers are trace-time flags (env ``CIMBA_NDEBUG`` /
``CIMBA_NASSERT`` or :func:`configure`), so a disabled tier traces to
nothing — the same zero-cost compile-out, per jit instead of per build.
An enabled assertion folds its predicate into the replication's failure
flag (`sim.err`), which freezes that replication and is counted by the
runner — batch-safe "abort", no host sync in the hot loop.

For Python-time (model construction) invariants use plain ``assert`` /
``raise`` — those run eagerly anyway.
"""

from __future__ import annotations

from cimba_tpu import config
from cimba_tpu.core.loop import Sim

_ndebug = bool(int(config.env_raw("CIMBA_NDEBUG")))
_nassert = bool(int(config.env_raw("CIMBA_NASSERT")))


def configure(*, ndebug: bool | None = None, nassert: bool | None = None):
    """Flip assertion tiers (re-jit afterwards, like a rebuild)."""
    global _ndebug, _nassert
    if ndebug is not None:
        _ndebug = ndebug
    if nassert is not None:
        _nassert = nassert


def debug_enabled() -> bool:
    """True when the heavyweight debug tier is active (CIMBA_NDEBUG
    unset) — used for eager structural checks too, e.g. the gated-handler
    no-op validation in the kernel path."""
    return not _ndebug


def _check(sim: Sim, pred) -> Sim:
    from cimba_tpu.core import api

    return api.fail(sim, ~pred)


def assert_debug(sim: Sim, pred) -> Sim:
    """Heavyweight invariant checks; off under CIMBA_NDEBUG (parity:
    cmb_assert_debug)."""
    if _ndebug:
        return sim
    return _check(sim, pred)


def assert_release(sim: Sim, pred) -> Sim:
    """Precondition checks; off under CIMBA_NASSERT (parity:
    cmb_assert_release)."""
    if _nassert:
        return sim
    return _check(sim, pred)


def assert_always(sim: Sim, pred) -> Sim:
    """Never compiled out (parity: cmb_assert_always)."""
    return _check(sim, pred)