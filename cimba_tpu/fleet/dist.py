"""Optional ``jax.distributed`` multi-controller init (off by default).

ROADMAP item 1's end state is a true multi-host ``Mesh`` with
cross-host ``psum`` liveness polling; this module is the flag-gated
first rung: a slice process started with ``CIMBA_FLEET_DIST`` set
joins a jax.distributed coordination service at startup, so a future
fleet can build cross-host meshes without changing the slice
entrypoint.  The knob format is
``coordinator_address,num_processes,process_id`` (e.g.
``"10.0.0.1:1234,4,0"``).

Unset (the default — and everywhere in tier-1), this module never
touches ``jax.distributed``: importing it is free, calling
:func:`maybe_init_distributed` reads one env knob and returns.
"""

from __future__ import annotations

from typing import Optional

from cimba_tpu import config as _config

ENV = "CIMBA_FLEET_DIST"


def maybe_init_distributed() -> Optional[dict]:
    """Initialize jax.distributed iff ``CIMBA_FLEET_DIST`` is set.
    Returns the parsed settings (or None when off).  Malformed settings
    raise loudly — a half-joined fleet is worse than a dead slice."""
    raw = _config.env_raw(ENV).strip()
    if not raw:
        return None
    parts = [p.strip() for p in raw.split(",")]
    if len(parts) != 3:
        raise ValueError(
            f"{ENV}={raw!r}: expected "
            "'coordinator_address,num_processes,process_id'"
        )
    addr, num, pid = parts[0], int(parts[1]), int(parts[2])
    import jax

    jax.distributed.initialize(
        coordinator_address=addr, num_processes=num, process_id=pid,
    )
    return {
        "coordinator_address": addr,
        "num_processes": num,
        "process_id": pid,
    }
