"""cimba_tpu.fleet — the multi-process serving fleet (docs/20_fleet.md).

Cimba's level-1 concurrency — trials fanned over worker threads
pulling a shared atomic counter — maps at production scale to a fleet
of dispatcher *processes*: each slice runs one device-owner
:class:`~cimba_tpu.serve.service.Service` with its own ``/healthz`` +
``/metrics`` endpoint (PR 8) and hydrates compiled programs from the
shared ``CIMBA_PROGRAM_STORE`` manifest (PR 6), while the front-door
:class:`~cimba_tpu.fleet.router.FleetRouter` keeps the single-process
``submit()/ResultHandle`` surface and adds placement (compatibility-
class co-location + least-loaded spill), liveness (health-scrape
failover within one poll interval), and requeue-with-``excluded``
recovery (the ``serve/sched.py`` pattern lifted from "failing batch
peer" to "failing host").  Results carry their PR 9 digest end to end.

    from cimba_tpu.fleet import FleetManager
    models = {"mm1": {"fn": "cimba_tpu.models.mm1:build",
                      "kwargs": {"record": False}}}
    with FleetManager(models, n_slices=2) as fm:
        h = fm.router.submit(serve.Request(fm.spec("mm1"), params, 64))
        result = h.result()

Zero-cost when unused: importing :mod:`cimba_tpu` never imports this
package, and importing this package spawns no process or thread —
only constructing a manager/router does.  Fault injection:
``CIMBA_FLEET_CHAOS`` (:mod:`cimba_tpu.fleet.chaos`).
"""

__all__ = [
    "FleetManager", "FleetRouter", "FleetHandle", "SliceHandle",
    "HealthPoller", "SliceSpawnError",
    "FleetError", "FleetRemoteError", "FleetRequeuesExhausted",
]

_EXPORTS = {
    "HealthPoller": "cimba_tpu.fleet.health",
    "FleetManager": "cimba_tpu.fleet.manager",
    "SliceSpawnError": "cimba_tpu.fleet.manager",
    "FleetError": "cimba_tpu.fleet.router",
    "FleetHandle": "cimba_tpu.fleet.router",
    "FleetRemoteError": "cimba_tpu.fleet.router",
    "FleetRequeuesExhausted": "cimba_tpu.fleet.router",
    "FleetRouter": "cimba_tpu.fleet.router",
    "SliceHandle": "cimba_tpu.fleet.router",
}


def __getattr__(name):
    # lazy exports (PEP 562): `python -m cimba_tpu.fleet.slice` runs
    # this __init__ before executing slice as __main__, and an eager
    # `from .manager import ...` here would pre-import the slice module
    # runpy is about to execute (the sys.modules double-import warning)
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    return getattr(importlib.import_module(target), name)
