"""The fleet front door: one ``submit()`` surface over many slices.

Cimba's level-1 concurrency (trials fanned over worker threads pulling
a shared atomic counter) maps at fleet scale to dispatcher *processes*,
not threads: each slice process owns one device-owner
:class:`~cimba_tpu.serve.service.Service` and the router is the
placement/liveness/failover layer above them (docs/20_fleet.md).  It
keeps the single-process serving surface — ``submit(Request)`` returns
a future with ``result()``/``digest()`` — so ``serve.run_load`` and
every client written against :class:`~cimba_tpu.serve.Service` drives
a fleet unchanged.

Placement policy (deterministic — the decisions are a pure function of
the request stream, the completion order, and the scraped state, with
every tie broken by host-side fmix64 over request ids, the PR 7
``round_seed`` idiom):

* **co-location by compatibility class** — requests are classed by the
  SAME :func:`~cimba_tpu.serve.service.request_class_key` the
  in-process dispatcher packs by, and a class sticks to the slices
  already serving it while they have window headroom, so slices keep
  packing heterogeneous waves instead of every class being sprayed
  thinly across the fleet;
* **least-loaded spill** — when the bound slices are full (or a class
  is new), the request goes to the live slice with the lowest load
  (router-tracked outstanding + the queue depth last scraped from the
  slice's ``/metrics``), growing the class's slice set;
* **capacity-aware placement** (docs/23_fleet_observability.md) — when
  every candidate slice runs the refill plane (docs/22_refill.md), the
  strongest capacity signal isn't queue depth but the live free-lane
  pool: placement ranks candidates by free-lane headroom (scraped
  ``cimba_serve_free_lanes`` minus work already pointed there) and
  falls back to least-loaded whenever any candidate lacks the signal;
  ``decision_log()`` records the capacity snapshot behind every pick;
* **bounded in-flight windows** — at most ``window`` requests are in
  flight per slice (the slice's own admission queue backpressures
  behind that).

Failover is the ``serve/sched.py`` solo-retry pattern lifted one
level: any transport failure (connection refused/reset, response
timeout, a dropped frame) — or the health poller marking the slice
down — requeues the request with the slice id appended to its
``excluded`` set, so the retry lands elsewhere; a request that runs
out of live candidates waits for the manager's replacement slice
rather than failing early.  Results carry their PR 9 digest end to
end: the slice computes ``stream_result_digest`` before the bytes
leave the process, the router recomputes it from the bytes that
arrived, and a mismatch is treated as a transport fault (requeue),
never delivered.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from cimba_tpu.fleet import wire
from cimba_tpu.serve.sched import Cancelled, ServeError, ServiceClosed
from cimba_tpu.sweep.adaptive import _GOLDEN, _fmix64

__all__ = [
    "FleetRouter", "FleetHandle", "SliceHandle",
    "FleetError", "FleetRequeuesExhausted", "FleetRemoteError",
]

#: remote error types the router reconstructs as their local classes
#: (permanent — the slice judged the REQUEST, not the transport).
#: ``QueueFull``/``ServiceClosed`` are deliberately NOT here: they
#: judge the SLICE's state at one instant (saturated admission queue,
#: shutting down), so the request requeues toward another slice
#: instead of failing while idle slices sit by.  ``RetryAfter`` IS
#: here (docs/27_qos.md): a QoS throttle judges the TENANT's policy,
#: and requeueing a throttled flood onto another slice would hand the
#: flooder slice-count times its rate — the structured backpressure
#: surfaces to the client, which sleeps ``delay_s`` and retries.
_PERMANENT_REMOTE = (
    "DeadlineExceeded", "Cancelled", "RetriesExhausted", "RetryAfter",
    "ValueError", "TypeError",
)


class FleetError(ServeError):
    """Base class of fleet-level structured errors."""


class FleetRequeuesExhausted(FleetError):
    """A request kept landing on failing slices past the requeue
    budget; the last transport reason is in the message."""

    def __init__(self, attempts: int, label: Optional[str],
                 reason: str):
        self.attempts = attempts
        self.label = label
        self.reason = reason
        super().__init__(
            f"request {label!r} requeued {attempts} time(s) without "
            f"completing (last: {reason})"
        )


class FleetRemoteError(FleetError):
    """The slice failed the request with a structured serving error the
    router relays (type name + message preserved)."""

    def __init__(self, type_name: str, message: str,
                 label: Optional[str] = None):
        self.type_name = type_name
        self.label = label
        super().__init__(f"{type_name}: {message}")


class SliceHandle:
    """One slice process as the router sees it: wire address, health
    URL, and the router-managed placement state.  Mutable state is
    owned by the router and guarded by the ROUTER's lock (the handle is
    a record, not an actor)."""

    def __init__(self, name: str, host: str, port: int,
                 health_url: str, *, proc=None, pid: Optional[int] = None):
        self.name = name
        self.host = host
        self.port = int(port)
        self.health_url = health_url.rstrip("/")
        self.proc = proc
        self.pid = pid
        # router-managed (under the router lock)
        self.up = True
        self.down_reason: Optional[str] = None
        self.down_t: Optional[float] = None
        self.outstanding = 0          # assigned, not yet released
        self.placed_total = 0
        self.queue: List["_FleetEntry"] = []   # assigned, not yet sent
        self.inflight: set = set()             # being wire-called
        self.scraped: Dict[str, Any] = {}      # health poller's view
        self.last_scrape_t: Optional[float] = None

    def __repr__(self):
        state = "up" if self.up else f"down({self.down_reason})"
        return (
            f"SliceHandle({self.name!r}, {self.host}:{self.port}, "
            f"{state}, outstanding={self.outstanding})"
        )


class _FleetEntry:
    """Router-internal per-request state."""

    __slots__ = (
        "request", "seq", "label", "cls", "model", "excluded",
        "attempts", "assigned", "submit_t", "done", "result", "exc",
        "remote_digest", "n_waves",
        "trace", "span_root", "span_pending", "span_wire",
    )

    def __init__(self, request, seq: int, cls, model: str):
        self.request = request
        self.seq = seq
        self.label = request.label
        self.cls = cls
        self.model = model
        self.excluded: set = set()   # slice ids this request must avoid
        self.attempts = 0
        self.assigned: Optional[str] = None
        self.submit_t = time.monotonic()
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[Exception] = None
        self.remote_digest: Optional[str] = None
        self.n_waves = 0
        # telemetry span state — all None without a plane (the
        # zero-allocation submit contract, same as serve._Entry)
        self.trace = None
        self.span_root = None
        self.span_pending = None
        self.span_wire = None


class FleetHandle:
    """The future :meth:`FleetRouter.submit` returns — the
    :class:`~cimba_tpu.serve.service.ResultHandle` surface."""

    def __init__(self, router: "FleetRouter", entry: _FleetEntry):
        self._router = router
        self._entry = entry

    @property
    def label(self) -> Optional[str]:
        return self._entry.label

    def done(self) -> bool:
        return self._entry.done.is_set()

    def cancel(self) -> bool:
        return self._router._cancel(self._entry)

    def exception(self, timeout: Optional[float] = None):
        if not self._entry.done.wait(timeout):
            raise TimeoutError(
                f"request {self._entry.label or self._entry.seq} not "
                f"done within {timeout}s"
            )
        return self._entry.exc

    def result(self, timeout: Optional[float] = None):
        exc = self.exception(timeout)
        if exc is not None:
            raise exc
        return self._entry.result

    def digest(self, timeout: Optional[float] = None) -> str:
        """The result's bitwise digest — verified end to end: the slice
        computed it before serialization, the router recomputed it from
        the received bytes, and the two matched (docs/20_fleet.md)."""
        self.result(timeout)
        return self._entry.remote_digest


class FleetRouter:
    """Front-door router over a set of slice processes (usually built
    and wired by :class:`~cimba_tpu.fleet.manager.FleetManager`).

    ``models`` maps model names to the SPEC OBJECTS clients put in
    their Requests — the router resolves ``request.spec`` to a wire
    model name by structural fingerprint, so ``dataclasses.replace``
    twins of a registered spec route too.  ``window`` bounds per-slice
    in-flight requests; ``place_seed`` seeds the deterministic
    tie-break; ``max_requeues`` bounds how often one request may be
    requeued across failing slices before failing loudly.

    ``telemetry`` (None-default, zero-cost off) attaches the fleet
    plane (docs/23_fleet_observability.md): router-side spans
    (request → pending → wire, requeue/failover events) whose trace
    context rides the wire so slice trees graft under them,
    ``cimba_fleet_*`` counter/gauge/histogram families, the per-slice
    rollup federation fed by :meth:`update_scrape`, and a
    slice-verdict health hook — serve ``/metrics``+``/healthz`` over
    it with :func:`cimba_tpu.obs.expose.start` and the whole fleet is
    one scrape target.  ``capacity_placement`` (None = the
    ``CIMBA_FLEET_CAPACITY`` knob, on by default) selects free-lane
    headroom ranking when every candidate slice scrapes the refill
    capacity signal."""

    # cimba-check: must-hold(_lock) _slices, _pending, _outstanding, _counters, _decisions, _class_map, _seq, _closed, _stop

    def __init__(
        self,
        *,
        models: Dict[str, Any],
        window: int = 4,
        place_seed: int = 0,
        max_requeues: int = 8,
        request_timeout: Optional[float] = 600.0,
        connect_timeout: float = 5.0,
        horizon_bucket: Optional[float] = 16.0,
        decision_cap: int = 65536,
        name: str = "cimba-fleet",
        telemetry=None,
        capacity_placement: Optional[bool] = None,
    ):
        if window <= 0:
            raise ValueError(f"window must be positive, got {window}")
        from cimba_tpu.serve import cache as _pcache

        if capacity_placement is None:
            from cimba_tpu import config as _config

            capacity_placement = _config.env_raw(
                "CIMBA_FLEET_CAPACITY"
            ).strip().lower() not in ("0", "false", "off")
        self.capacity_placement = bool(capacity_placement)
        self.name = name
        self.window = int(window)
        self.place_seed = int(place_seed)
        self.max_requeues = int(max_requeues)
        self.request_timeout = request_timeout
        self.connect_timeout = connect_timeout
        self.horizon_bucket = horizon_bucket
        self.models = dict(models)
        self._fp_to_model = {
            _pcache.spec_fingerprint(spec): mname
            for mname, spec in self.models.items()
        }
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._slices: "Dict[str, SliceHandle]" = {}
        self._pending: List[Tuple[Tuple[int, int], _FleetEntry]] = []
        self._outstanding = 0
        self._seq = 0
        self._closed = False
        self._stop = False
        # bounded: a week-long fleet must not leak its decision history
        # (the determinism pin compares windows far smaller than this)
        self._decisions: deque = deque(maxlen=int(decision_cap))
        self._counters = {
            "submitted": 0, "placed": 0, "requeues": 0,
            "completed": 0, "failed": 0, "cancelled": 0,
            "wire_errors": 0, "wire_digest_mismatches": 0,
            "expect_digest_mismatches": 0, "stale_results": 0,
        }
        self._class_map: Dict[tuple, List[str]] = {}
        # the fleet observability plane (docs/23) — None means zero
        # cost: no spans, no collector, no extra work on any path
        self._tel = telemetry
        self._rec = telemetry.spans if telemetry is not None else None
        # slice-labeled family names mirrored into the fleet registry
        # by update_scrape (the rollup federation), and names that
        # collided with a router-local family and are never mirrored
        self._fleet_families: set = set()
        self._fleet_skipped: set = set()
        self._threads: List[threading.Thread] = []
        self._placer = threading.Thread(
            target=self._place_loop, name=f"{name}-placer", daemon=True
        )
        self._placer.start()
        if telemetry is not None:
            telemetry.add_collector(self._collect)
            telemetry.add_healthz(self.name, self.fleet_health)

    # -- topology ------------------------------------------------------------

    def add_slice(self, handle: SliceHandle) -> None:
        """Register a (live) slice and start its sender threads — one
        per window slot, so at most ``window`` wire calls are in flight
        per slice."""
        with self._lock:
            if handle.name in self._slices:
                raise ValueError(
                    f"slice {handle.name!r} already registered"
                )
            self._slices[handle.name] = handle
            self._cv.notify_all()
        # prune finished sender threads (dead slices' senders exit):
        # a long churn of kills/respawns must not grow this unbounded
        self._threads = [t for t in self._threads if t.is_alive()]
        for i in range(self.window):
            t = threading.Thread(
                target=self._send_loop, args=(handle,),
                name=f"{self.name}-{handle.name}-s{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def slices(self) -> Dict[str, SliceHandle]:
        with self._lock:
            return dict(self._slices)

    def mark_down(self, name: str, reason: str) -> int:
        """Declare a slice dead (health poller or manager): its queued
        and in-flight requests requeue onto live slices with the slice
        id appended to their ``excluded`` set.  Idempotent; returns the
        number of requests requeued."""
        with self._lock:
            h = self._slices.get(name)
            if h is None or not h.up:
                return 0
            h.up = False
            h.down_reason = reason
            h.down_t = time.monotonic()
            victims = list(h.queue) + list(h.inflight)
            h.queue.clear()
            n = 0
            for e in victims:
                if self._requeue_locked(
                    e, h, f"slice down: {reason}", kind="failover"
                ):
                    n += 1
            self._cv.notify_all()
            return n

    def remove_slice(self, name: str) -> bool:
        """Forget a DOWN slice entirely (the manager calls this after
        reaping a corpse it replaced): a week of kill/respawn churn
        must not accumulate dead handles in every placement scan.  A
        slice still up is marked down first (its work requeues).
        Returns True when something was removed."""
        self.mark_down(name, "removed")
        with self._lock:
            h = self._slices.pop(name, None)
            # prune the name from every class's bound-slice list too —
            # kill/respawn churn must not grow the sticky sets (or the
            # `in bound` scan) without bound
            for names in self._class_map.values():
                if name in names:
                    names.remove(name)
            if self._tel is not None and h is not None:
                # drop the corpse's federated series (and refresh the
                # rollups) so "rollup == sum of live slices" holds
                # through kill/respawn churn
                reg = self._tel.registry
                for fname in self._fleet_families:
                    reg.gauge(fname, labels=("slice",)).remove(
                        slice=name
                    )
                self._mirror_locked(name, {})
                for fname, kind in (
                    ("cimba_fleet_slice_up", "gauge"),
                    ("cimba_fleet_slice_outstanding", "gauge"),
                    ("cimba_fleet_slice_placed_total", "counter"),
                ):
                    getattr(reg, kind)(
                        fname, labels=("fleet", "slice")
                    ).remove(fleet=self.name, slice=name)
            self._cv.notify_all()   # its sender threads wake and exit
        return h is not None

    def update_scrape(self, name: str, scraped: Dict[str, Any]) -> None:
        """The health poller's feed: the latest scraped view of one
        slice (queue depth, verdict, capacity signals, store counters)
        — read by placement, and (with a telemetry plane) mirrored
        into the fleet registry: the scrape's parsed single-value
        families land as ``{family}{slice=<name>}`` gauges plus a
        ``slice="all"`` rollup series summing the live slices, so one
        fleet ``/metrics`` covers every slice
        (docs/23_fleet_observability.md)."""
        with self._lock:
            h = self._slices.get(name)
            if h is None:
                return
            h.scraped = dict(scraped)
            h.last_scrape_t = time.monotonic()
            if self._tel is not None and scraped.get("families"):
                self._mirror_locked(name, scraped["families"])
            if self._tel is not None and scraped.get("tenants"):
                self._mirror_tenants_locked(name, scraped["tenants"])

    # cimba-check: assume-held
    def _mirror_locked(self, name: str, fams: Dict[str, float]) -> None:
        """Federate one slice's scraped families into the fleet
        registry (gauges — a federation snapshot, kinds intentionally
        flattened) and refresh the ``slice="all"`` rollups.  The name
        ``"all"`` is reserved for the rollup series."""
        reg = self._tel.registry
        for fname, val in fams.items():
            if fname in self._fleet_skipped:
                continue
            try:
                fam = reg.gauge(fname, labels=("slice",))
            except ValueError:
                fam = None
            if fam is None or fam.label_names != ("slice",):
                # the name collides with a router-LOCAL family of a
                # different kind or label set (both processes mint
                # e.g. cimba_ticks_total / cimba_heartbeat_age_seconds):
                # the local series wins and the slice copy is skipped,
                # never corrupted
                self._fleet_skipped.add(fname)
                continue
            fam.labels(slice=name).set(float(val))
            self._fleet_families.add(fname)
        for fname in self._fleet_families:
            total = 0.0
            for h2 in self._slices.values():
                if h2.up:
                    total += float(
                        (h2.scraped.get("families") or {}).get(fname, 0.0)
                    )
            reg.gauge(fname, labels=("slice",)).labels(
                slice="all"
            ).set(total)

    # cimba-check: assume-held
    def _mirror_tenants_locked(
        self, name: str, tenants: Dict[str, Dict[str, float]],
    ) -> None:
        """Federate one slice's per-tenant QoS view (docs/27_qos.md):
        the flattened family mirror above sums the tenant label away,
        so each scraped ``cimba_serve_qos_*`` family lands again as
        ``cimba_fleet_tenant_*{slice=<name>, tenant=<t>}`` gauges —
        its own fleet namespace, so it can never collide with a
        router-local serve family of a different kind — plus the
        reserved ``slice="all"`` rollup summing live slices per
        tenant.  One fleet ``/metrics`` then answers "is tenant X
        being throttled anywhere, and how much is it completing
        fleet-wide?"."""
        reg = self._tel.registry
        prefix = "cimba_serve_qos_"
        seen = set()
        for tname, row in tenants.items():
            for fname, val in row.items():
                if not fname.startswith(prefix):
                    continue
                reg.gauge(
                    "cimba_fleet_tenant_" + fname[len(prefix):],
                    labels=("slice", "tenant"),
                ).labels(slice=name, tenant=tname).set(float(val))
                seen.add(fname)
        for fname in seen:
            totals: Dict[str, float] = {}
            for h2 in self._slices.values():
                if not h2.up:
                    continue
                for tname, row in (
                    h2.scraped.get("tenants") or {}
                ).items():
                    totals[tname] = (
                        totals.get(tname, 0.0)
                        + float(row.get(fname, 0.0))
                    )
            fam = reg.gauge(
                "cimba_fleet_tenant_" + fname[len(prefix):],
                labels=("slice", "tenant"),
            )
            for tname, total in totals.items():
                fam.labels(slice="all", tenant=tname).set(total)

    # -- client surface ------------------------------------------------------

    def submit(self, request, *, block: bool = True,
               timeout: Optional[float] = None) -> FleetHandle:
        """Admit a request and return its future.  ``block``/
        ``timeout`` are accepted for :class:`~cimba_tpu.serve.Service`
        surface compatibility; the router's pending set is unbounded
        (each SLICE's admission queue is the bounded one — placement
        stops feeding a slice past its window)."""
        from cimba_tpu.obs import metrics as _metrics
        from cimba_tpu.serve import cache as _pcache
        from cimba_tpu.serve.service import request_class_key

        R = int(request.n_replications)
        if R <= 0:
            raise ValueError(f"n_replications must be positive, got {R}")
        fp = _pcache.spec_fingerprint(request.spec)
        model = self._fp_to_model.get(fp)
        if model is None:
            raise ValueError(
                "request.spec is not in this fleet's model registry "
                f"({sorted(self.models)}) — fleets serve registered "
                "models; build Requests from FleetManager.spec(name)"
            )
        if request.summary_path is not None:
            from cimba_tpu.runner import experiment as ex

            if request.summary_path is not ex.default_summary_path:
                raise ValueError(
                    "fleet requests cannot carry a custom summary_path "
                    "— functions don't cross the process boundary; "
                    "slices fold the model's default summary "
                    "(docs/20_fleet.md)"
                )
        with_metrics = _metrics.enabled()
        if with_metrics:
            # loud, like the summary_path check: the wire result format
            # carries no pooled metrics registry, and silently returning
            # metrics=None where serve.Service returns a registry (or
            # spuriously mismatching a metrics-on expect_digest) would
            # be a silent downgrade, not a feature
            raise ValueError(
                "fleet requests cannot run with the obs.metrics "
                "registry enabled — pooled metrics do not cross the "
                "wire yet (docs/20_fleet.md); disable obs.metrics or "
                "serve in-process"
            )
        cls = request_class_key(
            request, with_metrics, mesh=None,
            horizon_bucket=self.horizon_bucket,
        )
        with self._lock:
            if self._closed:
                raise ServiceClosed(
                    "fleet router is shut down — no new requests"
                )
            self._seq += 1
            entry = _FleetEntry(request, self._seq, cls, model)
            rec = self._rec
            if rec is not None:
                # minted BEFORE the heappush (the serve.Service
                # submit-before-publish invariant, one level up): once
                # the placer can see the entry, its trace exists
                entry.trace = rec.new_trace()
                entry.span_root = rec.start(
                    entry.trace, "request", seq=entry.seq,
                    label=entry.label, model=entry.model,
                    fleet=self.name,
                )
                entry.span_pending = rec.start(
                    entry.trace, "pending", parent=entry.span_root
                )
            self._outstanding += 1
            self._counters["submitted"] += 1
            heapq.heappush(
                self._pending,
                ((-request.priority, entry.seq), entry),
            )
            self._cv.notify_all()
        return FleetHandle(self, entry)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every admitted request completed; False on
        timeout."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        with self._lock:
            while self._outstanding > 0:
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining if remaining is not None else 0.5)
            return True

    def shutdown(self, wait: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop admitting; ``wait=True`` drains, ``wait=False`` cancels
        everything not yet completed.  Idempotent."""
        with self._lock:
            self._closed = True
        if wait:
            self.drain(timeout)
        with self._lock:
            self._stop = True
            if not wait:
                victims = [e for _, e in self._pending]
                for h in self._slices.values():
                    victims += list(h.queue) + list(h.inflight)
                self._pending.clear()
                for e in victims:
                    if not e.done.is_set():
                        if e.assigned is not None:
                            self._release_locked(e, e.assigned)
                        self._finish_locked(
                            e, exc=Cancelled(e.label),
                            outcome="cancelled",
                        )
            self._cv.notify_all()
        if self._tel is not None:
            # final counter flush, then detach: a scrape after shutdown
            # sees the router's last totals, not a collector racing a
            # torn-down fleet
            self._collect()
            self._tel.remove_collector(self._collect)
            self._tel.remove_healthz(self.name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(wait=True)

    # -- observability -------------------------------------------------------

    def decision_log(self) -> List[tuple]:
        """Placement/requeue decisions in order (the most recent
        ``decision_cap``): ``("place", seq, slice, snap)`` /
        ``("requeue", seq, slice, None)`` — the determinism pin's
        subject (same request stream + same chaos seed + same scraped
        state -> identical log; tests/test_fleet.py).  ``snap`` records
        the capacity evidence behind the pick:
        ``("capacity", free_lanes, headroom)`` when free-lane ranking
        engaged, ``("load", load)`` for the least-loaded fallback
        (docs/23_fleet_observability.md)."""
        with self._lock:
            return list(self._decisions)

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            out["pending"] = len(self._pending)
            out["outstanding"] = self._outstanding
            out["slices"] = {
                h.name: {
                    "up": h.up,
                    "down_reason": h.down_reason,
                    "outstanding": h.outstanding,
                    "placed_total": h.placed_total,
                    "scraped": dict(h.scraped),
                }
                for h in self._slices.values()
            }
            out["classes_seen"] = len(self._class_map)
            out["capacity_placement"] = self.capacity_placement
        return out

    def _collect(self) -> None:
        """Telemetry collector (``Telemetry.add_collector``): mirror
        the router's counters and topology into ``cimba_fleet_*``
        families at every sample/scrape, the ``_service_collector``
        idiom one level up (docs/23_fleet_observability.md)."""
        reg = self._tel.registry
        with self._lock:
            counters = dict(self._counters)
            pending = len(self._pending)
            outstanding = self._outstanding
            slices = [
                (h.name, h.up, h.outstanding, h.placed_total)
                for h in self._slices.values()
            ]
            classes = len(self._class_map)
        ev = reg.counter(
            "cimba_fleet_requests_total",
            "router request lifecycle, by event",
            labels=("fleet", "event"),
        )
        for k in ("submitted", "placed", "requeues", "completed",
                  "failed", "cancelled"):
            ev.labels(fleet=self.name, event=k).set_total(counters[k])
        fault = reg.counter(
            "cimba_fleet_wire_faults_total",
            "transport-level faults, by kind",
            labels=("fleet", "kind"),
        )
        for k in ("wire_errors", "wire_digest_mismatches",
                  "expect_digest_mismatches", "stale_results"):
            fault.labels(fleet=self.name, kind=k).set_total(counters[k])
        fl = {"fleet": self.name}
        reg.gauge(
            "cimba_fleet_pending",
            "requests awaiting placement", labels=("fleet",),
        ).labels(**fl).set(pending)
        reg.gauge(
            "cimba_fleet_outstanding",
            "requests admitted but not completed", labels=("fleet",),
        ).labels(**fl).set(outstanding)
        reg.gauge(
            "cimba_fleet_classes_seen",
            "distinct compatibility classes routed", labels=("fleet",),
        ).labels(**fl).set(classes)
        reg.gauge(
            "cimba_fleet_slices_up",
            "live slices", labels=("fleet",),
        ).labels(**fl).set(sum(1 for _, up, _, _ in slices if up))
        reg.gauge(
            "cimba_fleet_capacity_placement",
            "1 when free-lane headroom ranking is enabled",
            labels=("fleet",),
        ).labels(**fl).set(1.0 if self.capacity_placement else 0.0)
        up_f = reg.gauge(
            "cimba_fleet_slice_up",
            "slice liveness as the router sees it (1 up / 0 down)",
            labels=("fleet", "slice"),
        )
        out_f = reg.gauge(
            "cimba_fleet_slice_outstanding",
            "router-tracked in-flight requests per slice",
            labels=("fleet", "slice"),
        )
        placed_f = reg.counter(
            "cimba_fleet_slice_placed_total",
            "placements per slice", labels=("fleet", "slice"),
        )
        for name, up, outst, placed in slices:
            up_f.labels(fleet=self.name, slice=name).set(
                1.0 if up else 0.0
            )
            out_f.labels(fleet=self.name, slice=name).set(outst)
            placed_f.labels(fleet=self.name, slice=name).set_total(
                placed
            )

    def fleet_health(self) -> dict:
        """The fleet healthz hook (``Telemetry.add_healthz``): one
        verdict over the whole fleet.  Any slice down or scraped
        unhealthy/degraded -> ``degraded`` (requests still flow around
        it); a dead placer thread or zero live slices -> ``unhealthy``
        (nothing can make progress) — the serve dispatcher-dead
        semantics lifted one level (docs/23_fleet_observability.md)."""
        with self._lock:
            slices = {}
            n_up = 0
            degraded = False
            for h in self._slices.values():
                if h.up:
                    n_up += 1
                    v = str(h.scraped.get("verdict", "unknown"))
                    if v in ("degraded", "unhealthy"):
                        degraded = True
                else:
                    v = f"down:{h.down_reason}"
                    degraded = True
                slices[h.name] = v
            status = "degraded" if degraded else "ok"
            if n_up == 0 or not self._placer.is_alive():
                status = "unhealthy"
            return {
                "status": status,
                "slices": slices,
                "up": n_up,
                "pending": len(self._pending),
                "outstanding": self._outstanding,
            }

    def slice_stats(self, name: str,
                    timeout: float = 10.0) -> dict:
        """One slice's live ``Service.stats()`` over the wire (the
        ``stats`` op) — how a test or operator reads a replacement
        slice's store hit/fallback counters without scraping."""
        with self._lock:
            h = self._slices.get(name)
            if h is None:
                raise KeyError(f"unknown slice {name!r}")
            host, port = h.host, h.port
        header, _ = wire.call(
            host, port, {"op": "stats"}, timeout=timeout,
            connect_timeout=self.connect_timeout,
        )
        if not header.get("ok"):
            raise FleetRemoteError(
                header.get("error", "Error"),
                header.get("message", "stats failed"),
            )
        return header["stats"]

    # -- internals -----------------------------------------------------------

    def _cancel(self, entry: _FleetEntry) -> bool:
        with self._lock:
            if entry.done.is_set() or entry.assigned is not None:
                return False
            # remove from pending lazily: the placer drops tombstones
            self._finish_locked(
                entry, exc=Cancelled(entry.label), outcome="cancelled"
            )
            return True

    # cimba-check: assume-held
    def _finish_locked(self, entry: _FleetEntry, *, result=None,
                       exc=None, outcome: str) -> None:
        if entry.done.is_set():
            return
        entry.result = result
        entry.exc = exc
        self._counters[outcome] += 1
        self._outstanding -= 1
        if self._rec is not None and entry.trace is not None:
            # end_trace closes whatever is still open (pending on a
            # cancel, wire on a late failure) children-first, so one
            # fleet request is exactly ONE complete span tree whatever
            # its outcome (docs/23_fleet_observability.md)
            self._rec.end_trace(entry.trace, outcome=outcome)
        if self._tel is not None:
            self._tel.registry.histogram(
                "cimba_fleet_request_latency_seconds",
                "router submit -> completion, end to end",
                labels=("fleet", "outcome"),
            ).labels(fleet=self.name, outcome=outcome).observe(
                time.monotonic() - entry.submit_t
            )
        entry.done.set()
        self._cv.notify_all()

    # cimba-check: assume-held
    def _release_locked(self, entry: _FleetEntry,
                        slice_name: str) -> bool:
        """Release ``entry``'s assignment to ``slice_name`` — exactly
        one of the racing paths (sender completion, sender error,
        mark_down's sweep) wins; the rest see a changed assignment and
        stand down, so outstanding is decremented once and a request is
        never requeued twice for one failure."""
        if entry.assigned != slice_name:
            return False
        entry.assigned = None
        h = self._slices.get(slice_name)
        if h is not None:
            h.outstanding -= 1
            h.inflight.discard(entry)
            if entry in h.queue:
                h.queue.remove(entry)
        return True

    # cimba-check: assume-held
    def _requeue_locked(self, entry: _FleetEntry, h: SliceHandle,
                        reason: str, *, kind: str = "requeue") -> bool:
        if entry.done.is_set():
            return False
        if not self._release_locked(entry, h.name):
            return False
        entry.excluded.add(h.name)
        entry.attempts += 1
        self._counters["requeues"] += 1
        self._decisions.append(("requeue", entry.seq, h.name, None))
        rec = self._rec
        if rec is not None and entry.trace is not None:
            # the wire attempt (if one was in flight) ends "requeued";
            # the instant event distinguishes a transport bounce from a
            # health-poller failover in the merged tree
            if entry.span_wire is not None:
                rec.end(
                    entry.span_wire, outcome="requeued", reason=reason
                )
                entry.span_wire = None
            rec.event(
                entry.trace, kind, parent=entry.span_root,
                slice=h.name, reason=reason, attempt=entry.attempts,
            )
        if self._tel is not None:
            self._tel.registry.counter(
                "cimba_fleet_requeues_total",
                "requests bounced off a slice, by trigger",
                labels=("fleet", "kind"),
            ).labels(fleet=self.name, kind=kind).inc()
        if entry.attempts > self.max_requeues:
            self._finish_locked(
                entry,
                exc=FleetRequeuesExhausted(
                    entry.attempts, entry.label, reason
                ),
                outcome="failed",
            )
            return True
        if rec is not None and entry.trace is not None:
            # back to pending: a fresh pending span so queue time spent
            # waiting for the NEXT placement is attributed, not folded
            # into the failed wire attempt
            entry.span_pending = rec.start(
                entry.trace, "pending", parent=entry.span_root,
                requeue=entry.attempts,
            )
        heapq.heappush(
            self._pending,
            ((-entry.request.priority, entry.seq), entry),
        )
        self._cv.notify_all()
        return True

    # cimba-check: assume-held
    def _load_locked(self, h: SliceHandle) -> float:
        """A slice's placement load: what the router itself has
        outstanding there plus the queue depth last scraped from the
        slice's ``/metrics`` (a slice busy with somebody else's
        traffic — or its own backlog — reads loaded even when this
        router hasn't placed there)."""
        return h.outstanding + float(h.scraped.get("queue_depth", 0))

    # cimba-check: assume-held
    def _capacity_locked(
        self, cands: List[SliceHandle]
    ) -> Optional[Dict[str, Tuple[float, float]]]:
        """The free-lane capacity view of ``cands`` — ``name ->
        (free_lanes, headroom)`` where headroom is the scraped free-lane
        pool minus the work already pointed at the slice (router
        outstanding + scraped queue depth).  None when ANY candidate
        lacks the refill signal (refill off, or not scraped yet): the
        ranking only engages when the whole comparison is apples to
        apples (docs/23_fleet_observability.md)."""
        if not self.capacity_placement:
            return None
        caps: Dict[str, Tuple[float, float]] = {}
        for h in cands:
            sc = h.scraped
            free = sc.get("free_lanes")
            if not sc.get("refill_enabled") or free is None:
                return None
            free = float(free)
            caps[h.name] = (
                free,
                free - h.outstanding - float(sc.get("queue_depth", 0)),
            )
        return caps or None

    # cimba-check: assume-held
    def _choose_locked(
        self, entry: _FleetEntry
    ) -> Tuple[Optional[SliceHandle], Optional[tuple]]:
        cands = [
            h for h in self._slices.values()
            if h.up and h.name not in entry.excluded
            and h.outstanding < self.window
        ]
        if not cands and not any(
            h.up and h.name not in entry.excluded
            for h in self._slices.values()
        ):
            # retry of last resort: when every LIVE slice is excluded
            # (trivially a 1-slice fleet after one transient wire
            # error), re-admit live slices rather than parking a
            # healthy request forever — exclusion exists to steer away
            # from dead/suspect slices, and max_requeues still bounds
            # a genuinely poisoned loop.  Guarded on "no non-excluded
            # live slice EXISTS" (not merely "none has headroom"): a
            # busy healthy slice is worth waiting for, and falling back
            # while a freshly-killed peer is still nominally up would
            # burn the whole requeue budget on instant
            # connection-refused bounces before the poller flips it.
            # Deterministic: a pure function of (entry, topology).
            cands = [
                h for h in self._slices.values()
                if h.up and h.outstanding < self.window
            ]
        if not cands:
            return None, None
        bound = self._class_map.get(entry.cls)
        if bound:
            stuck = [h for h in cands if h.name in bound]
            if stuck:
                cands = stuck
        caps = self._capacity_locked(cands)
        if caps is not None:
            # capacity-aware: rank by free-lane headroom — the live
            # signal of what a refill slice can ABSORB, stronger than
            # queue depth which only says what's already parked
            hi = max(caps[h.name][1] for h in cands)
            best = [h for h in cands if caps[h.name][1] == hi]
        else:
            lo = min(self._load_locked(h) for h in cands)
            best = [h for h in cands if self._load_locked(h) == lo]
        # deterministic tie-break: fmix64 over the request id (the
        # PR 7 round_seed idiom) — NOT arrival order of a dict
        idx = _fmix64(
            (self.place_seed + _GOLDEN * (entry.seq + 1))
            & ((1 << 64) - 1)
        ) % len(best)
        pick = best[idx]
        snap = (
            ("capacity",) + caps[pick.name] if caps is not None
            else ("load", lo)
        )
        names = self._class_map.setdefault(entry.cls, [])
        if pick.name not in names:
            names.append(pick.name)
        return pick, snap

    def _place_loop(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    return
                placed = False
                kept: List[Tuple[Tuple[int, int], _FleetEntry]] = []
                # scan in priority order; the first placeable entry
                # wins (later entries may be placeable when the head is
                # excluded everywhere — no head-of-line block)
                while self._pending:
                    key, entry = heapq.heappop(self._pending)
                    if entry.done.is_set():
                        continue            # cancelled tombstone
                    pick, snap = self._choose_locked(entry)
                    if pick is None:
                        kept.append((key, entry))
                        continue
                    entry.assigned = pick.name
                    pick.outstanding += 1
                    pick.placed_total += 1
                    pick.queue.append(entry)
                    self._counters["placed"] += 1
                    self._decisions.append(
                        ("place", entry.seq, pick.name, snap)
                    )
                    if (self._rec is not None
                            and entry.span_pending is not None):
                        self._rec.end(
                            entry.span_pending, outcome="placed",
                            slice=pick.name,
                        )
                        entry.span_pending = None
                    placed = True
                for item in kept:
                    heapq.heappush(self._pending, item)
                if placed:
                    self._cv.notify_all()
                    continue
                self._cv.wait(0.1)

    def _send_loop(self, h: SliceHandle) -> None:
        while True:
            with self._lock:
                while h.up and not h.queue and not self._stop:
                    self._cv.wait(0.1)
                if self._stop or not h.up:
                    return
                entry = h.queue.pop(0)
                if entry.done.is_set() or entry.assigned != h.name:
                    continue
                h.inflight.add(entry)
                attempt = entry.attempts
            try:
                self._call_slice(h, entry, attempt)
            except Exception as e:
                # belt: a sender thread must NEVER die holding a claim
                # — a stranded in-flight entry blocks its client and
                # leaks a window slot forever.  Requeue and keep going.
                with self._lock:
                    self._counters["wire_errors"] += 1
                    self._requeue_locked(
                        entry, h, f"sender error: {e!r}"
                    )

    def _call_slice(self, h: SliceHandle, entry: _FleetEntry,
                    attempt: int) -> None:
        req = entry.request
        deadline = req.deadline
        if deadline is not None:
            # Service semantics preserved: the deadline is relative to
            # the ROUTER submit, so each attempt forwards the REMAINING
            # budget — re-sending the full value would silently restart
            # the clock on every requeue
            waited = time.monotonic() - entry.submit_t
            remaining = deadline - waited
            if remaining <= 0:
                from cimba_tpu.serve.sched import DeadlineExceeded

                with self._lock:
                    if self._release_locked(entry, h.name):
                        self._finish_locked(
                            entry,
                            exc=DeadlineExceeded(
                                deadline, waited, entry.label
                            ),
                            outcome="failed",
                        )
                return
            deadline = remaining
        params_node, blobs_out = wire.encode_tree(req.params)
        header = {
            "op": "run",
            "req_id": entry.seq,
            "attempt": attempt,
            "model": entry.model,
            "params": params_node,
            "n_replications": int(req.n_replications),
            "seed": int(req.seed),
            "t_end": req.t_end,
            # None rides the wire: the SLICE's service then resolves
            # the tuned schedule against its own store at submit time
            # (docs/21_autotune.md — fleet slices run the searched
            # schedule with zero router configuration)
            "chunk_steps": (
                None if req.chunk_steps is None else int(req.chunk_steps)
            ),
            "wave_size": (
                None if req.wave_size is None else int(req.wave_size)
            ),
            "priority": int(req.priority),
            "deadline": deadline,
            "label": req.label,
            # the tenant id rides the run header (docs/27_qos.md) so a
            # QoS-enabled slice applies the same per-tenant policy to
            # routed traffic; a plain JSON key — additive, older
            # slices ignore it (the wire.trace_context pattern)
            "tenant": req.tenant,
        }
        rec = self._rec
        if rec is not None and entry.trace is not None:
            with self._lock:
                if entry.done.is_set() or entry.assigned != h.name:
                    # requeued (mark_down swept it) while we built the
                    # frame — starting a span now would orphan it
                    return
                entry.span_wire = rec.start(
                    entry.trace, "wire", parent=entry.span_root,
                    slice=h.name, attempt=attempt,
                )
                span_wire = entry.span_wire
            # the cross-process graft: the slice's service adopts this
            # trace and parents its tree under our wire span
            header["trace"] = wire.trace_context(entry.trace, span_wire)
        t0 = time.monotonic()
        try:
            resp, blobs_in = wire.call(
                h.host, h.port, header, tuple(blobs_out),
                timeout=self.request_timeout,
                connect_timeout=self.connect_timeout,
            )
        except (OSError, wire.WireError) as e:
            reason = f"{type(e).__name__}: {e}"
            if isinstance(e, ConnectionRefusedError):
                # passive failure detection: refused means NOTHING is
                # listening — the process is gone.  Marking down now
                # (instead of waiting for the next scrape) requeues
                # everything assigned here and keeps the last-resort
                # fallback from bouncing off the corpse at
                # connection-refused speed until the budget is gone.
                # The health poller notices router-marked downs and
                # still drives the respawn.
                self.mark_down(h.name, reason)
            with self._lock:
                self._counters["wire_errors"] += 1
                # no-op if mark_down already requeued this entry
                self._requeue_locked(entry, h, reason)
            return
        if self._tel is not None:
            self._tel.registry.histogram(
                "cimba_fleet_wire_roundtrip_seconds",
                "one wire call: connect + run + response",
                labels=("fleet", "slice"),
            ).labels(fleet=self.name, slice=h.name).observe(
                time.monotonic() - t0
            )
        if resp.get("ok"):
            self._deliver(h, entry, resp, blobs_in)
            return
        # structured remote failure: the slice judged the REQUEST
        type_name = resp.get("error", "Error")
        message = resp.get("message", "")
        if type_name in _PERMANENT_REMOTE:
            exc = self._remote_exc(type_name, message, resp, entry)
            with self._lock:
                if self._release_locked(entry, h.name):
                    if rec is not None and entry.span_wire is not None:
                        rec.end(
                            entry.span_wire, outcome="error",
                            error=type_name,
                        )
                        entry.span_wire = None
                    self._finish_locked(entry, exc=exc, outcome="failed")
        else:
            # an unclassified slice-side crash: treat like a slice
            # fault — requeue elsewhere, bounded by max_requeues
            with self._lock:
                self._requeue_locked(
                    entry, h, f"remote {type_name}: {message}"
                )

    def _remote_exc(self, type_name: str, message: str, resp: dict,
                    entry: _FleetEntry) -> Exception:
        if type_name == "DeadlineExceeded":
            from cimba_tpu.serve.sched import DeadlineExceeded

            args = resp.get("args") or {}
            return DeadlineExceeded(
                args.get("deadline_s", entry.request.deadline or 0.0),
                args.get("waited_s", 0.0),
                entry.label,
            )
        if type_name == "RetryAfter":
            from cimba_tpu.serve.sched import RetryAfter

            args = resp.get("args") or {}
            return RetryAfter(
                float(args.get("delay_s", 0.05)),
                str(args.get("tenant", "default")),
                reason=str(args.get("reason", "rate")),
                label=entry.label,
            )
        return FleetRemoteError(type_name, message, entry.label)

    def _deliver(self, h: SliceHandle, entry: _FleetEntry, resp: dict,
                 blobs: List[bytes]) -> None:
        from cimba_tpu.obs import audit as _audit
        from cimba_tpu.runner.experiment import StreamResult

        try:
            tree = wire.decode_tree(resp["result"], blobs)
            result = StreamResult(
                summary=tree["summary"],
                n_failed=tree["n_failed"],
                total_events=tree["total_events"],
                n_waves=int(resp.get("n_waves", 0)),
                n_regrows=int(resp.get("n_regrows", 0)),
                metrics=None,
            )
            local_digest = _audit.stream_result_digest(result)
        except Exception as e:
            with self._lock:
                self._counters["wire_errors"] += 1
                self._requeue_locked(
                    entry, h, f"undecodable result: {e!r}"
                )
            return
        claimed = resp.get("digest")
        if claimed != local_digest:
            # the end-to-end integrity check (docs/18_audit.md lifted
            # to the wire): the bytes that arrived are not the bytes
            # the slice digested — a transport fault, never delivered
            with self._lock:
                self._counters["wire_digest_mismatches"] += 1
                self._requeue_locked(
                    entry, h,
                    f"wire digest mismatch ({claimed} != "
                    f"{local_digest})",
                )
            return
        expect = entry.request.expect_digest
        with self._lock:
            if not self._release_locked(entry, h.name):
                # a twin run already delivered (the slice was marked
                # down mid-call and the requeue won): identical bytes
                # either way — count, don't double-deliver
                self._counters["stale_results"] += 1
                return
            if expect is not None and expect != local_digest:
                self._counters["expect_digest_mismatches"] += 1
            if self._rec is not None and entry.span_wire is not None:
                self._rec.end(
                    entry.span_wire, outcome="ok",
                    n_waves=int(resp.get("n_waves", 0)),
                )
                entry.span_wire = None
            entry.remote_digest = local_digest
            self._finish_locked(
                entry, result=result, outcome="completed"
            )
