"""Deterministic fault injection for the fleet (``CIMBA_FLEET_CHAOS``).

Failover code that is only exercised by real outages is failover code
that has never been tested.  This module turns the three fleet failure
modes into seeded, reproducible knobs (registered in
``config.ENV_KNOBS``; docs/20_fleet.md):

* ``drop=<k>`` — a slice drops (closes the connection without
  replying) the FIRST-attempt wire response of every request whose
  ``fmix64(seed, slice salt, request id)`` lands in the 1/k bucket:
  the router sees a transport failure and requeues onto another slice.
  Only ``attempt == 0`` is ever dropped, so a chaos run still
  completes 100% of its requests — and, because the decision is a pure
  function of (seed, slice, request id), two runs of the same request
  stream drop — and therefore requeue — identically (the determinism
  pin in tests/test_fleet.py).
* ``kill=<n>`` — the slice SIGKILLs itself after serving ``n``
  requests: the mid-load hard-death arm (process exit, in-flight
  requests lost, health scrape goes unreachable).
* ``scrape_delay_ms=<ms>`` — ``/healthz`` + ``/metrics`` responses
  stall: the "alive but unscrapeable" arm that exercises the health
  poller's timeout path.

``seed=<u64>`` seeds the drop hash.  Unset (the default) injects
nothing; parsing is strict — a typo'd knob raises at slice startup,
never silently no-ops.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional

from cimba_tpu import config as _config
from cimba_tpu.sweep.adaptive import _GOLDEN, _fmix64

ENV = "CIMBA_FLEET_CHAOS"


@dataclass(frozen=True)
class ChaosConfig:
    """Parsed ``CIMBA_FLEET_CHAOS`` knobs (all off by default)."""

    seed: int = 0
    drop: int = 0
    kill: int = 0
    scrape_delay_ms: int = 0

    @property
    def active(self) -> bool:
        return bool(self.drop or self.kill or self.scrape_delay_ms)


def parse(raw: Optional[str] = None) -> ChaosConfig:
    """Parse a chaos spec (``raw=None`` reads the env knob): a
    comma-separated ``k=v`` list, e.g. ``"seed=7,drop=3,kill=20"``."""
    if raw is None:
        raw = _config.env_raw(ENV)
    raw = raw.strip()
    if not raw:
        return ChaosConfig()
    fields = {}
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(
                f"{ENV}: malformed knob {item!r} (expected k=v; knobs: "
                "seed, drop, kill, scrape_delay_ms)"
            )
        k, v = item.split("=", 1)
        k = k.strip()
        if k not in ("seed", "drop", "kill", "scrape_delay_ms"):
            raise ValueError(
                f"{ENV}: unknown knob {k!r} (knobs: seed, drop, kill, "
                "scrape_delay_ms)"
            )
        try:
            fields[k] = int(v)
        except ValueError as e:
            raise ValueError(f"{ENV}: {k}={v!r} is not an integer") from e
    return ChaosConfig(**fields)


def slice_salt(name: str) -> int:
    """A slice's stable u64 chaos salt (sha256 of its name): two slices
    with the same drop config must not drop the same request ids, or a
    dropped request would be re-dropped wherever it requeues."""
    return int.from_bytes(
        hashlib.sha256(name.encode("utf-8")).digest()[:8], "big"
    )


def should_drop(cfg: ChaosConfig, salt: int, req_id: int,
                attempt: int) -> bool:
    """Deterministic drop decision for one (slice, request, attempt):
    first attempts only (the run still completes after the requeue),
    hashed with the PR 7 host-side fmix64 idiom."""
    if cfg.drop <= 0 or attempt != 0:
        return False
    h = _fmix64(
        (int(cfg.seed) + _GOLDEN * (int(req_id) + 1)) & ((1 << 64) - 1)
    )
    h = _fmix64((h ^ int(salt)) & ((1 << 64) - 1))
    return h % cfg.drop == 0
