"""One slice of the fleet: a ``serve.Service`` process behind the wire.

The slice worker entrypoint (``python -m cimba_tpu.fleet.slice``) runs
exactly one device-owner :class:`~cimba_tpu.serve.service.Service`
plus one :class:`~cimba_tpu.obs.telemetry.Telemetry` plane with its
``/healthz`` + ``/metrics`` exposition endpoint, and serves requests
over the stdlib loopback wire protocol (:mod:`cimba_tpu.fleet.wire`).
At startup it

1. builds its model registry from ``--models`` (a JSON map of name ->
   ``{"fn": "module:callable", "kwargs": {...}}`` — specs are built
   in-process; function objects never cross the wire),
2. hydrates the shared program cache from the ``CIMBA_PROGRAM_STORE``
   manifest when the env knob names one (``serve.warm(manifest=...)``
   per model — the PR 6 zero-cold-start path, so a REPLACEMENT slice
   serves its first request warm, sub-second after ready, with
   ``fallback_shapes == 0``; a store miss logs and degrades to
   compile-on-first-request, never blocks startup),
3. prints ONE ready line to stdout — ``{"name", "pid", "port",
   "health_port", "url"}`` — which is the manager's spawn contract,

then serves forever.  Responses carry the result's PR 9
``stream_result_digest`` computed BEFORE serialization, so the router
can verify the bytes end to end.  ``CIMBA_FLEET_CHAOS``
(:mod:`cimba_tpu.fleet.chaos`) injects deterministic faults: dropped
first-attempt responses, self-SIGKILL after N requests, stalled
scrapes.

The wire ops:

* ``run`` — submit one experiment request to the Service, wait, reply
  with the encoded ``StreamResult`` + digest (or a structured error);
* ``stats`` — the Service's live ``stats()`` snapshot (JSON-safe);
* ``ping`` — liveness + identity.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import signal
import socketserver
import sys
import threading
from typing import Any, Dict, Optional

from cimba_tpu.fleet import chaos as _chaos
from cimba_tpu.fleet import wire

__all__ = ["load_models", "main"]


def load_models(models: Any) -> Dict[str, Any]:
    """Resolve a model registry — ``{name: {"fn": "module:callable",
    "kwargs": {...}}}`` (or a JSON string of it) — into ``{name:
    spec}``.  A builder returning a tuple contributes its first element
    (the ``mm1.build() -> (spec, refs)`` convention).  Shared by the
    slice entrypoint and the manager, so the parent-side specs the
    router registers are built by exactly the code the slices run."""
    if isinstance(models, str):
        models = json.loads(models)
    out: Dict[str, Any] = {}
    for name, rec in models.items():
        if isinstance(rec, str):
            rec = {"fn": rec}
        target = rec["fn"]
        mod_name, _, attr = target.partition(":")
        if not attr:
            raise ValueError(
                f"model {name!r}: builder {target!r} must be "
                "'module:callable'"
            )
        fn = getattr(importlib.import_module(mod_name), attr)
        built = fn(**(rec.get("kwargs") or {}))
        out[name] = built[0] if isinstance(built, tuple) else built
    return out


def _error_header(e: Exception) -> dict:
    h = {
        "ok": False,
        "error": type(e).__name__,
        "message": str(e),
    }
    args = {}
    # delay_s/tenant/reason: RetryAfter's QoS backpressure fields
    # (docs/27_qos.md) — the router reconstructs the throttle so the
    # client's sleep-and-retry works across the wire unchanged
    for k in ("deadline_s", "waited_s", "attempts", "capacity",
              "delay_s", "tenant", "reason"):
        v = getattr(e, k, None)
        if v is not None:
            args[k] = v
    if args:
        h["args"] = args
    return h


class _SliceServer:
    """The slice's wire server + service wiring (instantiable in-process
    for tests; the CLI main() drives one)."""

    def __init__(
        self,
        *,
        name: str,
        models: Dict[str, Any],
        max_wave: int,
        max_pending: int,
        port: int = 0,
        health_port: int = 0,
        warm_chunk_steps: Optional[int] = None,
        horizon_bucket: Optional[float] = 16.0,
        telemetry_interval: float = 0.1,
    ):
        from cimba_tpu import config as _config
        from cimba_tpu import serve
        from cimba_tpu.obs import expose as _expose
        from cimba_tpu.obs import telemetry as _tm

        self.name = name
        self.models = models
        self.chaos = _chaos.parse()
        self._chaos_salt = _chaos.slice_salt(name)
        self._served = 0
        self._dropped = 0
        self._lock = threading.Lock()

        self.cache = serve.ProgramCache()
        self.warm_report: Dict[str, str] = {}
        store_root = _config.env_raw("CIMBA_PROGRAM_STORE").strip()
        if store_root:
            for mname, spec in models.items():
                try:
                    serve.warm(
                        self.cache, spec, None, 0, manifest=store_root,
                        **(
                            {}
                            if warm_chunk_steps is None
                            else {"chunk_steps": int(warm_chunk_steps)}
                        ),
                    )
                    self.warm_report[mname] = "hydrated"
                except LookupError as e:
                    # cold start is a degraded mode, not a startup
                    # failure: the store may simply not cover this
                    # model yet — get_programs still second-chances it
                    self.warm_report[mname] = f"miss: {e}"
                    print(
                        f"[{name}] store warm miss for {mname}: {e}",
                        file=sys.stderr, flush=True,
                    )

        # span recording is opt-in via CIMBA_FLEET_TELEMETRY (a
        # directory): each slice streams its span JSONL to
        # <dir>/<name>.spans.jsonl, ids namespaced by slice name so the
        # files merge with the router's into one tree
        # (docs/23_fleet_observability.md); unset = no recorder, the
        # zero-cost default
        span_dir = _config.env_raw("CIMBA_FLEET_TELEMETRY").strip()
        span_path = None
        if span_dir:
            os.makedirs(span_dir, exist_ok=True)
            span_path = os.path.join(
                span_dir, f"{name}.spans.jsonl"
            )
        self.span_path = span_path
        self.telemetry = _tm.Telemetry(
            interval=telemetry_interval, span_path=span_path,
            span_node=name if span_path else None,
        )
        self.exposition = _expose.start(
            self.telemetry, port=health_port,
            delay_s=self.chaos.scrape_delay_ms / 1000.0,
        )
        self.service = serve.Service(
            max_wave=max_wave, max_pending=max_pending,
            cache=self.cache, telemetry=self.telemetry, name=name,
            horizon_bucket=horizon_bucket,
        )

        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    header, blobs = wire.recv_frame(self.request)
                except (OSError, wire.WireError):
                    return      # half-open probe / peer gave up
                try:
                    outer._dispatch(self.request, header, blobs)
                except (OSError, wire.WireError):
                    pass        # peer hung up mid-reply; requeue is
                    #             the ROUTER's job, nothing to do here

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Server(("127.0.0.1", port), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(
            target=self.server.serve_forever, name=f"{name}-wire",
            daemon=True,
        )
        self._thread.start()

    # -- ops -----------------------------------------------------------------

    def _dispatch(self, sock, header: dict, blobs) -> None:
        op = header.get("op")
        if op == "ping":
            wire.send_frame(sock, {
                "ok": True, "name": self.name, "pid": os.getpid(),
            })
        elif op == "stats":
            stats = json.loads(
                json.dumps(self.service.stats(), default=str)
            )
            wire.send_frame(sock, {"ok": True, "stats": stats})
        elif op == "run":
            self._run(sock, header, blobs)
        else:
            wire.send_frame(sock, {
                "ok": False, "error": "WireError",
                "message": f"unknown op {op!r}",
            })

    def _run(self, sock, header: dict, blobs) -> None:
        from cimba_tpu import serve
        from cimba_tpu.obs import audit as _audit

        if _chaos.should_drop(
            self.chaos, self._chaos_salt,
            int(header.get("req_id", 0)),
            int(header.get("attempt", 0)),
        ):
            # fault injection: the response is "lost" — close without
            # replying; the router requeues onto another slice
            with self._lock:
                self._dropped += 1
            print(
                f"[{self.name}] chaos drop req {header.get('req_id')}",
                file=sys.stderr, flush=True,
            )
            return
        model = header.get("model")
        spec = self.models.get(model)
        if spec is None:
            wire.send_frame(sock, {
                "ok": False, "error": "ValueError",
                "message": f"unknown model {model!r} (this slice "
                           f"serves {sorted(self.models)})",
            })
            return
        try:
            params = wire.decode_tree(header["params"], blobs)
            request = serve.Request(
                spec,
                params,
                int(header["n_replications"]),
                seed=int(header.get("seed", 0)),
                t_end=header.get("t_end"),
                # None = unset: the service resolves the tuned schedule
                # for this slice's store at submit (docs/21_autotune.md)
                chunk_steps=(
                    None if header.get("chunk_steps") is None
                    else int(header["chunk_steps"])
                ),
                wave_size=header.get("wave_size"),
                priority=int(header.get("priority", 0)),
                deadline=header.get("deadline"),
                label=header.get("label"),
                tenant=header.get("tenant"),
                # the router's trace id + wire-span parent: the
                # service adopts them so this slice's span tree
                # grafts under the router's (docs/23); absent or
                # malformed = locally-rooted, same as today
                trace_context=(
                    header["trace"]
                    if isinstance(header.get("trace"), dict)
                    else None
                ),
            )
            handle = self.service.submit(request)
            result = handle.result()
            digest = handle.digest()
        except Exception as e:
            wire.send_frame(sock, _error_header(e))
            return
        node, out_blobs = wire.encode_tree({
            "summary": result.summary,
            "n_failed": result.n_failed,
            "total_events": result.total_events,
        })
        wire.send_frame(sock, {
            "ok": True,
            "req_id": header.get("req_id"),
            "digest": digest,
            "n_waves": int(result.n_waves),
            "n_regrows": int(result.n_regrows),
            "result": node,
        }, tuple(out_blobs))
        kill = False
        with self._lock:
            self._served += 1
            if self.chaos.kill and self._served >= self.chaos.kill:
                kill = True
        if kill:
            # chaos hard-death: the response above made it out, the
            # PROCESS does not survive it — in-flight peers see resets,
            # the health scrape goes unreachable, the manager respawns
            print(
                f"[{self.name}] chaos kill -9 after {self._served} "
                "requests", file=sys.stderr, flush=True,
            )
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGKILL)

    # -- lifecycle -----------------------------------------------------------

    def ready_line(self) -> dict:
        return {
            "name": self.name,
            "pid": os.getpid(),
            "port": self.port,
            "health_port": self.exposition.port,
            "url": self.exposition.url,
            "warm": self.warm_report,
            "chaos": self.chaos.active,
            "spans": self.span_path,
        }

    def close(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        self.service.shutdown(wait=False)
        self.exposition.close()
        self.telemetry.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="cimba fleet slice worker: one serve.Service + "
        "telemetry endpoint per process, requests over the loopback "
        "wire protocol (docs/20_fleet.md)",
    )
    ap.add_argument("--name", default=f"slice-{os.getpid()}")
    ap.add_argument(
        "--models", required=True,
        help='JSON: {"mm1": {"fn": "cimba_tpu.models.mm1:build", '
             '"kwargs": {"record": false}}}',
    )
    ap.add_argument("--port", type=int, default=0,
                    help="wire port (0 = ephemeral, reported on stdout)")
    ap.add_argument("--health-port", type=int, default=0,
                    help="/healthz + /metrics port (0 = ephemeral)")
    ap.add_argument("--max-wave", type=int, default=4096)
    ap.add_argument("--max-pending", type=int, default=64)
    ap.add_argument(
        "--warm-chunk-steps", type=int, default=None,
        help="chunk_steps of the CIMBA_PROGRAM_STORE entry to hydrate "
        "at startup (must match what requests will carry)",
    )
    ap.add_argument(
        "--horizon-bucket", default="16.0",
        help="the Service's horizon-bucket ratio ('none' = pack all "
        "finite horizons together) — the manager forwards the "
        "router's value so the co-location class and the slice's "
        "packing class can never drift",
    )
    args = ap.parse_args(argv)
    horizon_bucket = (
        None if args.horizon_bucket.lower() == "none"
        else float(args.horizon_bucket)
    )

    # optional multi-controller init (ROADMAP item 1's jax.distributed
    # leg) — strictly opt-in behind CIMBA_FLEET_DIST, never in tier-1
    from cimba_tpu.fleet import dist as _dist

    _dist.maybe_init_distributed()

    models = load_models(args.models)
    srv = _SliceServer(
        name=args.name,
        models=models,
        max_wave=args.max_wave,
        max_pending=args.max_pending,
        port=args.port,
        health_port=args.health_port,
        warm_chunk_steps=args.warm_chunk_steps,
        horizon_bucket=horizon_bucket,
    )
    # the spawn contract: exactly ONE json line on stdout, then quiet
    # (logs go to stderr) — the manager blocks on this line
    print(json.dumps(srv.ready_line()), flush=True)

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    parent = os.getppid()
    while not stop.wait(0.5):
        if os.getppid() != parent:
            # orphaned: the manager died (or a respawn raced its
            # shutdown) — a slice must never outlive its fleet
            print(f"[{args.name}] parent gone, exiting",
                  file=sys.stderr, flush=True)
            break
    srv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
