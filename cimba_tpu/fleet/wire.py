"""The fleet's loopback wire protocol: length-prefixed JSON + npy.

One frame carries one message: a JSON header plus zero or more binary
attachments (raw ``.npy`` bodies) the header references by index —
stdlib + numpy only, no pickle (a slice must never execute bytes a
router sent it, and vice versa), no new dependencies.  Layout::

    b"CFW1"                      magic + protocol version
    u32 big-endian               header length
    <header bytes>               UTF-8 JSON object
    u32 big-endian               blob count
    per blob: u64 big-endian     blob length
              <blob bytes>       numpy .npy serialization

Pytrees cross the wire through :func:`encode_tree` /
:func:`decode_tree`: JSON literals pass through, containers are tagged
nodes, arrays become npy blobs, and the few framework NamedTuples a
result carries (``stats.summary.Summary``) are reconstructed by class
name from an explicit registry — the decode side never builds a type
the protocol didn't declare.  Python scalars stay Python scalars, so a
parameter tuple round-trips bit-exactly (``json`` floats serialize via
``repr`` and re-parse to the identical double), which is what keeps a
routed request's trajectories bitwise the direct call's.

**Trace context** (docs/23_fleet_observability.md): a ``run`` header
may carry a ``"trace"`` object — ``{"id": <router trace id>,
"parent": <router span id>}``, built by :func:`trace_context` — that
the slice's service adopts, grafting its local span tree under the
router's.  Plain JSON keys, strictly additive: a slice that predates
the field ignores it, and a header without it means a locally-rooted
trace (or none at all).

See docs/20_fleet.md for the message catalogue (``run`` / ``stats`` /
``ping``) and the failover semantics built on top.
"""

from __future__ import annotations

import io
import json
import socket
import struct
from typing import Any, List, Optional, Tuple

MAGIC = b"CFW1"

#: per-frame ceilings — a corrupt length prefix must fail loudly, not
#: allocate gigabytes (loopback frames are small: results are pooled
#: summaries, not batched sims)
MAX_HEADER = 16 << 20
MAX_BLOB = 256 << 20
MAX_BLOBS = 4096


class WireError(ConnectionError):
    """Malformed frame or a peer that hung up mid-frame."""


def trace_context(trace_id: str, parent_span: Optional[str]) -> dict:
    """The ``"trace"`` header object a ``run`` frame carries: the
    router's trace id plus the span the slice's tree should hang under
    (its wire span).  One constructor so the two sides of the wire
    agree on the key names."""
    out: dict = {"id": str(trace_id)}
    if parent_span is not None:
        out["parent"] = str(parent_span)
    return out


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise WireError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes read)"
            )
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, header: dict,
               blobs: Tuple[bytes, ...] = ()) -> None:
    hb = json.dumps(header).encode("utf-8")
    parts = [MAGIC, struct.pack(">I", len(hb)), hb,
             struct.pack(">I", len(blobs))]
    for b in blobs:
        parts.append(struct.pack(">Q", len(b)))
        parts.append(b)
    sock.sendall(b"".join(parts))


def recv_frame(sock: socket.socket) -> Tuple[dict, List[bytes]]:
    magic = _recv_exact(sock, 4)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} (expected {MAGIC!r})")
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    if hlen > MAX_HEADER:
        raise WireError(f"header length {hlen} exceeds {MAX_HEADER}")
    try:
        header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        # corrupt bytes are a TRANSPORT fault (WireError -> requeue),
        # never an exception class the caller didn't sign up for
        raise WireError(f"undecodable frame header: {e}") from e
    if not isinstance(header, dict):
        raise WireError("frame header is not a JSON object")
    (nblobs,) = struct.unpack(">I", _recv_exact(sock, 4))
    if nblobs > MAX_BLOBS:
        raise WireError(f"blob count {nblobs} exceeds {MAX_BLOBS}")
    blobs = []
    for _ in range(nblobs):
        (blen,) = struct.unpack(">Q", _recv_exact(sock, 8))
        if blen > MAX_BLOB:
            raise WireError(f"blob length {blen} exceeds {MAX_BLOB}")
        blobs.append(_recv_exact(sock, blen))
    return header, blobs


# ---------------------------------------------------------------------------
# pytree <-> (json node, npy blobs)
# ---------------------------------------------------------------------------

def _nt_classes() -> dict:
    """NamedTuple classes the protocol may reconstruct by name — an
    explicit allowlist, resolved lazily so this module stays importable
    without jax."""
    from cimba_tpu.stats.summary import Summary

    return {"Summary": Summary}


def _to_npy(arr) -> bytes:
    import numpy as np

    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def encode_tree(x: Any) -> Tuple[Any, List[bytes]]:
    """Encode a pytree of JSON literals / containers / arrays /
    registered NamedTuples into a JSON-able node plus npy blobs."""
    import numpy as np

    blobs: List[bytes] = []

    def enc(v):
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        if isinstance(v, tuple) and hasattr(v, "_fields"):
            cname = type(v).__name__
            if cname not in _nt_classes():
                raise TypeError(
                    f"NamedTuple {cname!r} is not in the wire "
                    "protocol's reconstruction registry "
                    "(fleet.wire._nt_classes)"
                )
            return {"__t": "nt", "c": cname, "v": [enc(e) for e in v]}
        if isinstance(v, tuple):
            return {"__t": "tuple", "v": [enc(e) for e in v]}
        if isinstance(v, list):
            return {"__t": "list", "v": [enc(e) for e in v]}
        if isinstance(v, dict):
            keys = list(v.keys())
            if not all(isinstance(k, str) for k in keys):
                raise TypeError(
                    "wire dicts need string keys, got "
                    f"{[type(k).__name__ for k in keys]}"
                )
            return {
                "__t": "dict", "k": keys,
                "v": [enc(v[k]) for k in keys],
            }
        if isinstance(v, (np.ndarray, np.generic)) or (
            hasattr(v, "dtype") and hasattr(v, "shape")
        ):
            blobs.append(_to_npy(v))
            return {"__t": "nd", "i": len(blobs) - 1}
        raise TypeError(
            f"{type(v).__module__}.{type(v).__qualname__} has no wire "
            "encoding (JSON literals, tuples/lists/dicts, arrays, and "
            "registered NamedTuples only)"
        )

    return enc(x), blobs


def decode_tree(node: Any, blobs: List[bytes]) -> Any:
    """Invert :func:`encode_tree`."""
    import numpy as np

    def dec(v):
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        if isinstance(v, dict) and "__t" in v:
            t = v["__t"]
            if t == "tuple":
                return tuple(dec(e) for e in v["v"])
            if t == "list":
                return [dec(e) for e in v["v"]]
            if t == "dict":
                return {k: dec(e) for k, e in zip(v["k"], v["v"])}
            if t == "nt":
                cls = _nt_classes().get(v["c"])
                if cls is None:
                    raise WireError(
                        f"unknown NamedTuple class {v['c']!r} in frame"
                    )
                return cls(*(dec(e) for e in v["v"]))
            if t == "nd":
                raw = blobs[int(v["i"])]
                return np.load(io.BytesIO(raw), allow_pickle=False)
            raise WireError(f"unknown wire node tag {t!r}")
        raise WireError(f"undecodable wire node {type(v).__name__}")

    return dec(node)


def call(
    host: str,
    port: int,
    header: dict,
    blobs: Tuple[bytes, ...] = (),
    *,
    timeout: Optional[float] = None,
    connect_timeout: float = 5.0,
) -> Tuple[dict, List[bytes]]:
    """One request/response round-trip on a fresh loopback connection
    (the router's client leg).  ``timeout`` bounds the RESPONSE wait —
    an experiment may legitimately run for a while; ``connect_timeout``
    bounds only the dial.  Raises ``OSError``/:class:`WireError` on any
    transport failure — the router's requeue trigger."""
    with socket.create_connection(
        (host, port), timeout=connect_timeout
    ) as sock:
        sock.settimeout(timeout)
        send_frame(sock, header, blobs)
        return recv_frame(sock)
